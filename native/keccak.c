/* keccak.c — Keccak-f[1600] + Ethereum-flavoured keccak-256.
 *
 * Native hashing for the framework's host runtime: the reference keeps its
 * keccak hot loop in assembly (crypto/sha3/keccakf_amd64.s); this is the
 * portable C equivalent behind the Python ctypes seam
 * (gethsharding_tpu/native.py). Multi-rate padding with the 0x01 domain
 * byte (Ethereum keccak, NOT NIST SHA3-256).
 *
 * Exports:
 *   gs_keccak256(in, len, out32)
 *   gs_keccak256_batch(in, n, stride, len, out)  -- n messages of equal
 *       length `len`, laid out every `stride` bytes; out = n*32 bytes.
 */

#include <stdint.h>
#include <string.h>

#define ROTL64(x, n) (((x) << (n)) | ((x) >> (64 - (n))))

static const uint64_t RC[24] = {
    0x0000000000000001ULL, 0x0000000000008082ULL, 0x800000000000808aULL,
    0x8000000080008000ULL, 0x000000000000808bULL, 0x0000000080000001ULL,
    0x8000000080008081ULL, 0x8000000000008009ULL, 0x000000000000008aULL,
    0x0000000000000088ULL, 0x0000000080008009ULL, 0x000000008000000aULL,
    0x000000008000808bULL, 0x800000000000008bULL, 0x8000000000008089ULL,
    0x8000000000008003ULL, 0x8000000000008002ULL, 0x8000000000000080ULL,
    0x000000000000800aULL, 0x800000008000000aULL, 0x8000000080008081ULL,
    0x8000000000008080ULL, 0x0000000080000001ULL, 0x8000000080008008ULL};

static const int ROTC[24] = {1,  3,  6,  10, 15, 21, 28, 36, 45, 55, 2,  14,
                             27, 41, 56, 8,  25, 43, 62, 18, 39, 61, 20, 44};
static const int PILN[24] = {10, 7,  11, 17, 18, 3, 5,  16, 8,  21, 24, 4,
                             15, 23, 19, 13, 12, 2, 20, 14, 22, 9,  6,  1};

void gs_keccak_f1600(uint64_t st[25]) {
  uint64_t bc[5], t;
  for (int round = 0; round < 24; round++) {
    /* theta */
    for (int i = 0; i < 5; i++)
      bc[i] = st[i] ^ st[i + 5] ^ st[i + 10] ^ st[i + 15] ^ st[i + 20];
    for (int i = 0; i < 5; i++) {
      t = bc[(i + 4) % 5] ^ ROTL64(bc[(i + 1) % 5], 1);
      for (int j = 0; j < 25; j += 5) st[j + i] ^= t;
    }
    /* rho + pi */
    t = st[1];
    for (int i = 0; i < 24; i++) {
      int j = PILN[i];
      uint64_t tmp = st[j];
      st[j] = ROTL64(t, ROTC[i]);
      t = tmp;
    }
    /* chi */
    for (int j = 0; j < 25; j += 5) {
      for (int i = 0; i < 5; i++) bc[i] = st[j + i];
      for (int i = 0; i < 5; i++)
        st[j + i] = bc[i] ^ ((~bc[(i + 1) % 5]) & bc[(i + 2) % 5]);
    }
    /* iota */
    st[0] ^= RC[round];
  }
}

void gs_keccak256(const uint8_t *in, uint64_t len, uint8_t *out32) {
  uint64_t st[25];
  uint8_t block[136];
  memset(st, 0, sizeof(st));
  while (len >= 136) {
    for (int i = 0; i < 17; i++) {
      uint64_t lane;
      memcpy(&lane, in + 8 * i, 8); /* little-endian hosts */
      st[i] ^= lane;
    }
    gs_keccak_f1600(st);
    in += 136;
    len -= 136;
  }
  memset(block, 0, sizeof(block));
  memcpy(block, in, len);
  block[len] = 0x01;   /* Ethereum keccak domain padding */
  block[135] |= 0x80;
  for (int i = 0; i < 17; i++) {
    uint64_t lane;
    memcpy(&lane, block + 8 * i, 8);
    st[i] ^= lane;
  }
  gs_keccak_f1600(st);
  memcpy(out32, st, 32);
}

void gs_keccak256_batch(const uint8_t *in, uint64_t n, uint64_t stride,
                        uint64_t len, uint8_t *out) {
  for (uint64_t i = 0; i < n; i++)
    gs_keccak256(in + i * stride, len, out + 32 * i);
}
