/* mpt.c — bulk Merkle-Patricia-trie root construction.
 *
 * The native runtime's answer to the per-byte DeriveSha scalability trap:
 * the reference computes collation chunk roots by inserting one entry per
 * BODY BYTE into a Go trie (sharding/collation.go CalculateChunkRoot ->
 * core/types/derive_sha.go) — fine in Go, minutes in Python for a 1 MiB
 * body. This builds the same root bottom-up from a sorted entry list in
 * one pass: yellow-paper node encodings (leaf/extension 2-item lists with
 * hex-prefix paths, 17-item branches, >=32-byte nodes referenced by
 * keccak), byte-identical with gethsharding_tpu/core/trie.py (enforced by
 * the differential tests).
 *
 * Scope: insert-only tries with small keys/values (caps below) — exactly
 * the DeriveSha shape. Duplicate keys keep the last value (update
 * semantics).
 *
 * Export:
 *   int gs_mpt_root(keys, key_stride, key_lens, vals, val_stride,
 *                   val_lens, n, out32)
 *     -> 0 on success, nonzero on cap violations.
 */

#include <stdint.h>
#include <stdlib.h>
#include <string.h>

void gs_keccak256(const uint8_t *in, uint64_t len, uint8_t *out32);

#define KEY_CAP 32      /* max key bytes -> max 64 nibbles (secure-trie keccak keys) */
#define VAL_CAP 128     /* max value bytes (state-account RLP <= 110) */
#define MAX_NIB (2 * KEY_CAP)
/* worst node: branch of 16 embedded children (<32B each) + value + header */
#define NODE_BUF 1024

typedef struct {
  const uint8_t *nib;  /* n * MAX_NIB */
  const uint8_t *nlen; /* nibble path length per entry */
  const uint8_t *val;  /* n * VAL_CAP (RLP-encoded values) */
  const uint8_t *vlen;
  const uint32_t *idx; /* sorted order */
} Ctx;

/* ---- RLP helpers ---- */

static uint64_t rlp_str(const uint8_t *data, uint64_t len, uint8_t *out) {
  if (len == 1 && data[0] < 0x80) {
    out[0] = data[0];
    return 1;
  }
  if (len <= 55) {
    out[0] = 0x80 + (uint8_t)len;
    memcpy(out + 1, data, len);
    return len + 1;
  }
  /* long form (56..255 bytes — VAL_CAP bounds the inputs) */
  out[0] = 0xb8;
  out[1] = (uint8_t)len;
  memcpy(out + 2, data, len);
  return len + 2;
}

static uint64_t rlp_list_wrap(uint8_t *payload, uint64_t plen, uint8_t *out) {
  if (plen <= 55) {
    out[0] = 0xc0 + (uint8_t)plen;
    memcpy(out + 1, payload, plen);
    return plen + 1;
  }
  uint64_t l = plen;
  int lenlen = 0;
  uint8_t lenbytes[8];
  while (l) {
    lenbytes[lenlen++] = (uint8_t)(l & 0xFF);
    l >>= 8;
  }
  out[0] = 0xf7 + (uint8_t)lenlen;
  for (int i = 0; i < lenlen; i++) out[1 + i] = lenbytes[lenlen - 1 - i];
  memcpy(out + 1 + lenlen, payload, plen);
  return 1 + lenlen + plen;
}

/* hex-prefix encode path[0..len) with leaf flag; returns byte length */
static uint64_t hp_encode(const uint8_t *path, uint64_t len, int leaf,
                          uint8_t *out) {
  uint8_t flag = leaf ? 2 : 0;
  uint64_t olen = 0;
  if (len % 2 == 1) {
    out[0] = (uint8_t)(((flag + 1) << 4) | path[0]);
    path++;
    len--;
    olen = 1;
  } else {
    out[0] = (uint8_t)(flag << 4);
    olen = 1;
  }
  for (uint64_t i = 0; i < len; i += 2)
    out[olen++] = (uint8_t)((path[i] << 4) | path[i + 1]);
  return olen;
}

/* ---- recursive build ---- */

static int node_build(const Ctx *ctx, uint64_t lo, uint64_t hi, uint64_t depth,
                      uint8_t *out, uint64_t *olen);

/* child reference into parent payload: raw rlp if <32 else keccak string */
static int child_ref(const Ctx *ctx, uint64_t lo, uint64_t hi, uint64_t depth,
                     uint8_t *out, uint64_t *olen) {
  uint8_t buf[NODE_BUF];
  uint64_t blen;
  int rc = node_build(ctx, lo, hi, depth, buf, &blen);
  if (rc) return rc;
  if (blen < 32) {
    memcpy(out, buf, blen);
    *olen = blen;
  } else {
    uint8_t h[32];
    gs_keccak256(buf, blen, h);
    *olen = rlp_str(h, 32, out);
  }
  return 0;
}

static int node_build(const Ctx *ctx, uint64_t lo, uint64_t hi, uint64_t depth,
                      uint8_t *out, uint64_t *olen) {
  uint8_t payload[NODE_BUF];
  uint64_t plen = 0;
  const uint8_t *nib0 = ctx->nib + (uint64_t)ctx->idx[lo] * MAX_NIB;
  uint64_t len0 = ctx->nlen[ctx->idx[lo]];

  if (hi - lo == 1) { /* leaf */
    uint8_t hp[KEY_CAP + 1];
    uint64_t hplen = hp_encode(nib0 + depth, len0 - depth, 1, hp);
    plen += rlp_str(hp, hplen, payload + plen);
    const uint8_t *val = ctx->val + (uint64_t)ctx->idx[lo] * VAL_CAP;
    uint64_t vlen = ctx->vlen[ctx->idx[lo]];
    plen += rlp_str(val, vlen, payload + plen);
    *olen = rlp_list_wrap(payload, plen, out);
    return 0;
  }

  /* common prefix below depth across the (sorted) range: compare the
   * first and last paths; an exhausted first path forces a branch */
  const uint8_t *nibL = ctx->nib + (uint64_t)ctx->idx[hi - 1] * MAX_NIB;
  uint64_t lenL = ctx->nlen[ctx->idx[hi - 1]];
  uint64_t common = 0;
  uint64_t maxc = (len0 < lenL ? len0 : lenL) - depth;
  if (len0 > depth) {
    while (common < maxc && nib0[depth + common] == nibL[depth + common])
      common++;
  }

  if (common > 0) { /* extension */
    uint8_t hp[KEY_CAP + 1];
    uint64_t hplen = hp_encode(nib0 + depth, common, 0, hp);
    plen += rlp_str(hp, hplen, payload + plen);
    uint64_t clen;
    int rc = child_ref(ctx, lo, hi, depth + common, payload + plen, &clen);
    if (rc) return rc;
    plen += clen;
    *olen = rlp_list_wrap(payload, plen, out);
    return 0;
  }

  /* branch: value slot if the first entry's path is exhausted */
  uint64_t vstart = lo;
  const uint8_t *bval = NULL;
  uint64_t bvlen = 0;
  if (len0 == depth) {
    bval = ctx->val + (uint64_t)ctx->idx[lo] * VAL_CAP;
    bvlen = ctx->vlen[ctx->idx[lo]];
    vstart = lo + 1;
  }
  uint64_t pos = vstart;
  for (int nibble = 0; nibble < 16; nibble++) {
    uint64_t start = pos;
    while (pos < hi) {
      const uint8_t *p = ctx->nib + (uint64_t)ctx->idx[pos] * MAX_NIB;
      if (p[depth] != (uint8_t)nibble) break;
      pos++;
    }
    if (pos == start) {
      payload[plen++] = 0x80; /* empty child */
    } else {
      uint64_t clen;
      int rc = child_ref(ctx, start, pos, depth + 1, payload + plen, &clen);
      if (rc) return rc;
      plen += clen;
    }
  }
  if (bval != NULL) {
    plen += rlp_str(bval, bvlen, payload + plen);
  } else {
    payload[plen++] = 0x80;
  }
  if (pos != hi) return 2; /* unsorted input */
  *olen = rlp_list_wrap(payload, plen, out);
  return 0;
}

/* ---- sorting ---- */

static const Ctx *g_sort_ctx;

static int cmp_entries(const void *a, const void *b) {
  uint32_t ia = *(const uint32_t *)a, ib = *(const uint32_t *)b;
  const uint8_t *pa = g_sort_ctx->nib + (uint64_t)ia * MAX_NIB;
  const uint8_t *pb = g_sort_ctx->nib + (uint64_t)ib * MAX_NIB;
  uint64_t la = g_sort_ctx->nlen[ia], lb = g_sort_ctx->nlen[ib];
  uint64_t n = la < lb ? la : lb;
  int c = memcmp(pa, pb, n);
  if (c) return c;
  if (la != lb) return la < lb ? -1 : 1;
  /* equal keys: later original index wins (stable "last update") */
  return ia < ib ? -1 : 1;
}

int gs_mpt_root(const uint8_t *keys, uint64_t key_stride,
                const uint8_t *key_lens, const uint8_t *vals,
                uint64_t val_stride, const uint8_t *val_lens, uint64_t n,
                uint8_t *out32) {
  if (n == 0) {
    uint8_t empty = 0x80; /* rlp(b"") */
    gs_keccak256(&empty, 1, out32);
    return 0;
  }
  uint8_t *nib = malloc(n * MAX_NIB);
  uint8_t *nlen = malloc(n);
  uint8_t *val = malloc(n * VAL_CAP);
  uint8_t *vlen = malloc(n);
  uint32_t *idx = malloc(n * sizeof(uint32_t));
  if (!nib || !nlen || !val || !vlen || !idx) {
    free(nib); free(nlen); free(val); free(vlen); free(idx);
    return 3;
  }
  int rc = 0;
  for (uint64_t i = 0; i < n; i++) {
    uint64_t kl = key_lens[i], vl = val_lens[i];
    if (kl > KEY_CAP || vl > VAL_CAP) {
      rc = 1;
      goto done;
    }
    const uint8_t *k = keys + i * key_stride;
    for (uint64_t j = 0; j < kl; j++) {
      nib[i * MAX_NIB + 2 * j] = k[j] >> 4;
      nib[i * MAX_NIB + 2 * j + 1] = k[j] & 0x0F;
    }
    nlen[i] = (uint8_t)(2 * kl);
    memcpy(val + i * VAL_CAP, vals + i * val_stride, vl);
    vlen[i] = (uint8_t)vl;
    idx[i] = (uint32_t)i;
  }
  Ctx ctx = {nib, nlen, val, vlen, idx};
  g_sort_ctx = &ctx;
  qsort(idx, n, sizeof(uint32_t), cmp_entries);
  /* dedupe equal paths: keep the last (highest original index) */
  uint64_t w = 0;
  for (uint64_t i = 0; i < n; i++) {
    if (w > 0) {
      uint32_t prev = idx[w - 1], cur = idx[i];
      if (nlen[prev] == nlen[cur] &&
          memcmp(nib + (uint64_t)prev * MAX_NIB,
                 nib + (uint64_t)cur * MAX_NIB, nlen[prev]) == 0) {
        idx[w - 1] = cur; /* later update wins */
        continue;
      }
    }
    idx[w++] = idx[i];
  }
  {
    uint8_t buf[NODE_BUF];
    uint64_t blen;
    rc = node_build(&ctx, 0, w, 0, buf, &blen);
    if (rc == 0) gs_keccak256(buf, blen, out32); /* root always hashed */
  }
done:
  free(nib); free(nlen); free(val); free(vlen); free(idx);
  return rc;
}
