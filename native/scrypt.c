/* scrypt ROMix (RFC 7914) — the sequential-memory-hard core.
 *
 * Why this exists: OpenSSL (hashlib.scrypt) enforces N < 2^(128*r/8),
 * which rejects the Ethereum Web3 Secret Storage "light/wiki" profile
 * (n=262144, r=1, p=8) that geth's Go scrypt accepts — so real key
 * files exist that the OpenSSL path cannot decrypt. The outer PBKDF2
 * layers stay in Python (hashlib); only ROMix lives here.
 *
 * Layout contract: `blocks` is p consecutive 128*r-byte blocks (the
 * PBKDF2 output B), transformed in place. Little-endian host assumed
 * (matches every other native module in this tree).
 */

#include <stdint.h>
#include <stdlib.h>
#include <string.h>

#define R32(x, n) (((x) << (n)) | ((x) >> (32 - (n))))

static void salsa8(uint32_t B[16]) {
    uint32_t x[16];
    memcpy(x, B, 64);
    for (int i = 0; i < 4; i++) {
        x[ 4] ^= R32(x[ 0] + x[12], 7);  x[ 8] ^= R32(x[ 4] + x[ 0], 9);
        x[12] ^= R32(x[ 8] + x[ 4], 13); x[ 0] ^= R32(x[12] + x[ 8], 18);
        x[ 9] ^= R32(x[ 5] + x[ 1], 7);  x[13] ^= R32(x[ 9] + x[ 5], 9);
        x[ 1] ^= R32(x[13] + x[ 9], 13); x[ 5] ^= R32(x[ 1] + x[13], 18);
        x[14] ^= R32(x[10] + x[ 6], 7);  x[ 2] ^= R32(x[14] + x[10], 9);
        x[ 6] ^= R32(x[ 2] + x[14], 13); x[10] ^= R32(x[ 6] + x[ 2], 18);
        x[ 3] ^= R32(x[15] + x[11], 7);  x[ 7] ^= R32(x[ 3] + x[15], 9);
        x[11] ^= R32(x[ 7] + x[ 3], 13); x[15] ^= R32(x[11] + x[ 7], 18);
        x[ 1] ^= R32(x[ 0] + x[ 3], 7);  x[ 2] ^= R32(x[ 1] + x[ 0], 9);
        x[ 3] ^= R32(x[ 2] + x[ 1], 13); x[ 0] ^= R32(x[ 3] + x[ 2], 18);
        x[ 6] ^= R32(x[ 5] + x[ 4], 7);  x[ 7] ^= R32(x[ 6] + x[ 5], 9);
        x[ 4] ^= R32(x[ 7] + x[ 6], 13); x[ 5] ^= R32(x[ 4] + x[ 7], 18);
        x[11] ^= R32(x[10] + x[ 9], 7);  x[ 8] ^= R32(x[11] + x[10], 9);
        x[ 9] ^= R32(x[ 8] + x[11], 13); x[10] ^= R32(x[ 9] + x[ 8], 18);
        x[12] ^= R32(x[15] + x[14], 7);  x[13] ^= R32(x[12] + x[15], 9);
        x[14] ^= R32(x[13] + x[12], 13); x[15] ^= R32(x[14] + x[13], 18);
    }
    for (int i = 0; i < 16; i++) B[i] += x[i];
}

/* BlockMix: B (2r 64-byte sub-blocks) -> Y, with the even/odd shuffle. */
static void blockmix(const uint32_t *B, uint32_t *Y, uint32_t r) {
    uint32_t X[16];
    memcpy(X, &B[(2 * r - 1) * 16], 64);
    for (uint32_t i = 0; i < 2 * r; i++) {
        for (int k = 0; k < 16; k++) X[k] ^= B[i * 16 + k];
        salsa8(X);
        /* Y layout: even sub-blocks first, then odd */
        uint32_t dst = (i / 2) + (i & 1) * r;
        memcpy(&Y[dst * 16], X, 64);
    }
}

/* ROMix over p blocks of 128*r bytes each, in place. Returns 0, or -1
 * when the V table cannot be allocated. */
int gs_scrypt_romix(uint8_t *blocks, uint64_t p, uint32_t N, uint32_t r) {
    size_t words = 32 * (size_t)r;            /* uint32s per block */
    uint32_t *V = malloc((size_t)N * words * 4);
    uint32_t *X = malloc(words * 4);
    uint32_t *Y = malloc(words * 4);
    if (!V || !X || !Y) { free(V); free(X); free(Y); return -1; }
    for (uint64_t b = 0; b < p; b++) {
        memcpy(X, blocks + b * words * 4, words * 4);
        for (uint32_t i = 0; i < N; i++) {
            memcpy(&V[(size_t)i * words], X, words * 4);
            blockmix(X, Y, r);
            uint32_t *t = X; X = Y; Y = t;
        }
        for (uint32_t i = 0; i < N; i++) {
            uint32_t j = X[(2 * r - 1) * 16] & (N - 1); /* N is a pow2 */
            const uint32_t *Vj = &V[(size_t)j * words];
            for (size_t k = 0; k < words; k++) X[k] ^= Vj[k];
            blockmix(X, Y, r);
            uint32_t *t = X; X = Y; Y = t;
        }
        memcpy(blocks + b * words * 4, X, words * 4);
    }
    free(V); free(X); free(Y);
    return 0;
}
