#!/bin/bash
# Experiment-queue watcher for the flaky accelerator tunnel.
#
# The tunnel dies for hours at a time and any in-process jax init against
# a dead tunnel hangs forever, so every probe is a bounded subprocess
# (see bench.py _probe_backend). Whenever the tunnel is up, this runs the
# next pending experiment from .tpu_queue/*.sh (lexicographic order) and
# archives it to .tpu_queue/done/. Each experiment script gets the output
# prefix as $1 and must exit 0 on success; failures are retried on later
# windows up to 3 times (a mid-experiment tunnel death looks like a
# failure — the retry gets a fresh window).
#
# Drop new experiment scripts into .tpu_queue/ at any time; the watcher
# never exits on its own.
#
# Experiment contract: exit 0 ONLY on evidence of a real TPU result —
# the watcher trusts the exit code, and bench.py exits 0 even on its
# CPU/replay fallbacks. grep your own output for '"platform": "tpu' AND
# run full `bench.py` with GETHSHARDING_BENCH_NO_REPLAY=1 (a replayed
# capture also says platform tpu; `bench.py --single` never replays).
#
# On first start the queue is seeded from the tracked templates in
# scripts/tpu_experiments/ (breakdown + kernel-knob probes + the full
# bench-with-extras refresh).
cd /root/repo || exit 1
LOG=.tpu_watch.log
QUEUE=.tpu_queue
mkdir -p "$QUEUE/done" .tpu_results
if [ ! -e "$QUEUE/.seeded" ] && [ -d scripts/tpu_experiments ]; then
  cp -n scripts/tpu_experiments/*.sh "$QUEUE/" 2>/dev/null
  touch "$QUEUE/.seeded"
fi
echo "$(date +%F\ %T) watcher v2 start (pid $$)" >>"$LOG"
while true; do
  if [ -z "$(ls "$QUEUE"/*.sh 2>/dev/null | head -1)" ]; then sleep 60; continue; fi
  plat=$(timeout 120 python -c 'import jax; print(jax.devices()[0].platform)' 2>/dev/null | tail -1)
  ts=$(date +%F\ %T)
  if [ -n "$plat" ] && [ "$plat" != "cpu" ]; then
    echo "$ts tunnel UP ($plat); running queue pass" >>"$LOG"
    # the TPU window is precious: pause CPU-hogging suite runs so the
    # experiments' compiles aren't starved on the 1-core host
    pids=$(pgrep -f "pytest tests/" || true)
    [ -n "$pids" ] && kill -STOP $pids 2>/dev/null
    # never leave suites frozen if the watcher dies mid-pass
    trap '[ -n "$pids" ] && kill -CONT $pids 2>/dev/null' EXIT
    # one pass over the WHOLE pending queue per window: a failing
    # experiment moves on to the next instead of burning the window
    for next in "$QUEUE"/*.sh; do
      [ -e "$next" ] || continue
      # the tunnel can die mid-pass: re-probe before each experiment so
      # the rest of the queue doesn't hang to its timeouts and burn
      # retry strikes on a dead tunnel
      plat=$(timeout 120 python -c 'import jax; print(jax.devices()[0].platform)' 2>/dev/null | tail -1)
      if [ -z "$plat" ] || [ "$plat" = "cpu" ]; then
        echo "$(date +%F\ %T) tunnel died mid-pass; abandoning window" >>"$LOG"
        break
      fi
      name=$(basename "$next" .sh)
      out=".tpu_results/${name}_$(date +%s)"
      # own process group so a timeout kills the experiment's python
      # grandchildren too (a hung jax init survives a plain `timeout`)
      setsid bash "$next" "$out" >>"$out.log" 2>&1 &
      exp=$!
      waited=0
      while kill -0 "$exp" 2>/dev/null && [ $waited -lt 7200 ]; do
        sleep 30
        waited=$((waited + 30))
      done
      if kill -0 "$exp" 2>/dev/null; then
        kill -TERM -- "-$exp" 2>/dev/null
        sleep 10
        kill -KILL -- "-$exp" 2>/dev/null
        rc=124
      else
        wait "$exp"
        rc=$?
      fi
      echo "$(date +%F\ %T) $name rc=$rc -> $out.log" >>"$LOG"
      if [ $rc -eq 0 ]; then
        mv "$next" "$QUEUE/done/${name}_$(date +%s).sh"
        rm -f "$QUEUE/.retries_$name"
      else
        n=$(cat "$QUEUE/.retries_$name" 2>/dev/null || echo 0)
        n=$((n + 1))
        echo $n >"$QUEUE/.retries_$name"
        if [ "$n" -ge 3 ]; then
          park="$QUEUE/done/FAILED_${name}_$(date +%s).sh"
          mv "$next" "$park"
          # mv preserves the script's old edit mtime; the finalize
          # re-queue guard compares park-file mtimes, so stamp NOW
          touch "$park"
          rm -f "$QUEUE/.retries_$name"
          echo "$(date +%F\ %T) $name parked after $n failures" >>"$LOG"
        fi
      fi
    done
    [ -n "$pids" ] && kill -CONT $pids 2>/dev/null
    # a cfg probe that SUCCEEDED after the finalize capture may change
    # the winner: re-queue the finalize experiment so the canonical
    # capture (winner + extras) is refreshed on a later pass. Only
    # platform-tpu results count (a failed probe leaves an empty .out),
    # and a parked (3-strike) finalize is only revived by cfg evidence
    # NEWER than its last failure — never in an unconditional loop.
    if [ ! -e "$QUEUE"/89_finalize_winner.sh ] \
        && [ -e scripts/tpu_experiments/89_finalize_winner.sh ]; then
      newest_cfg=$(grep -l '"platform": "tpu' .tpu_results/*_cfg_*.out \
        2>/dev/null | xargs -r ls -t 2>/dev/null | head -1)
      newest_cap=$(ls -t bench_results/tpu_capture_*.json 2>/dev/null | head -1)
      newest_park=$(ls -t "$QUEUE"/done/FAILED_89_finalize_winner_*.sh \
        2>/dev/null | head -1)
      if [ -n "$newest_cfg" ] \
          && { [ -z "$newest_cap" ] || [ "$newest_cfg" -nt "$newest_cap" ]; } \
          && { [ -z "$newest_park" ] || [ "$newest_cfg" -nt "$newest_park" ]; }
      then
        cp -p scripts/tpu_experiments/89_finalize_winner.sh "$QUEUE/"
        echo "$(date +%F\ %T) re-queued 89_finalize (newer cfg result)" >>"$LOG"
      fi
    fi
    # retries of still-pending failures wait for the next pass
    sleep 600
  else
    echo "$ts tunnel down ($(ls "$QUEUE"/*.sh 2>/dev/null | wc -l) pending)" >>"$LOG"
    sleep 240
  fi
done
