#!/bin/bash
# Probe the accelerator tunnel throughout the round; the moment it is up,
# run the full bench sweep and capture the result. The tunnel dies for
# hours at a time and any in-process jax init against a dead tunnel hangs
# forever, so every probe is a bounded subprocess (see bench.py
# _probe_backend). Exits 0 once a non-CPU bench result is captured.
cd /root/repo || exit 1
LOG=.tpu_watch.log
mkdir -p .tpu_results
echo "$(date +%F\ %T) watcher start (pid $$)" >>"$LOG"
while true; do
  plat=$(timeout 120 python -c 'import jax; print(jax.devices()[0].platform)' 2>/dev/null | tail -1)
  ts=$(date +%F\ %T)
  if [ -n "$plat" ] && [ "$plat" != "cpu" ]; then
    echo "$ts tunnel UP ($plat) - running bench sweep" >>"$LOG"
    # the TPU window is precious: pause CPU-hogging suite runs so the
    # sweep's compiles and probes aren't starved on the 1-core host
    pids=$(pgrep -f "pytest tests/" || true)
    [ -n "$pids" ] && kill -STOP $pids 2>/dev/null
    out=".tpu_results/bench_$(date +%s)"
    timeout 7200 python bench.py >"$out.json" 2>"$out.log"
    rc=$?
    [ -n "$pids" ] && kill -CONT $pids 2>/dev/null
    tail -c 400 "$out.json" >>"$LOG"
    if [ $rc -eq 0 ] && grep -q '"platform": "tpu' "$out.json"; then
      echo "$ts CAPTURED TPU BENCH -> $out.json" >>"$LOG"
      exit 0
    fi
    echo "$ts bench rc=$rc but no TPU result; looping" >>"$LOG"
  else
    echo "$ts tunnel down" >>"$LOG"
  fi
  sleep 240
done
