#!/bin/bash
# Probe the accelerator tunnel throughout the round; the moment it is up,
# run the full bench sweep and capture the result. The tunnel dies for
# hours at a time and any in-process jax init against a dead tunnel hangs
# forever, so every probe is a bounded subprocess (see bench.py
# _probe_backend). Exits 0 once a non-CPU bench result is captured.
cd /root/repo || exit 1
LOG=.tpu_watch.log
mkdir -p .tpu_results
echo "$(date +%F\ %T) watcher start (pid $$)" >>"$LOG"
while true; do
  plat=$(timeout 120 python -c 'import jax; print(jax.devices()[0].platform)' 2>/dev/null | tail -1)
  ts=$(date +%F\ %T)
  if [ -n "$plat" ] && [ "$plat" != "cpu" ]; then
    echo "$ts tunnel UP ($plat) - running bench sweep" >>"$LOG"
    # the TPU window is precious: pause CPU-hogging suite runs so the
    # sweep's compiles and probes aren't starved on the 1-core host
    pids=$(pgrep -f "pytest tests/" || true)
    [ -n "$pids" ] && kill -STOP $pids 2>/dev/null
    out=".tpu_results/bench_$(date +%s)"
    bench_start=$(date +%s)
    timeout 7200 python bench.py >"$out.json" 2>"$out.log"
    rc=$?
    tail -c 400 "$out.json" >>"$LOG"
    if [ $rc -eq 0 ] && grep -q '"platform": "tpu' "$out.json"; then
      echo "$ts CAPTURED TPU BENCH -> $out.json" >>"$LOG"
      # while the window is open (and the suite is still paused — the
      # breakdown compiles four kernels on the 1-core host): a stage
      # breakdown so a <100k number comes with attackable per-stage
      # costs. Knobs come from the autotune cache ONLY if this bench
      # run wrote it (the in-process fallback path leaves a stale
      # cache whose config wouldn't match the number just captured).
      knobs=""
      cache_mtime=$(stat -c %Y .bench_autotune.json 2>/dev/null || echo 0)
      if [ "$cache_mtime" -ge "$bench_start" ]; then
        knobs=$(python - <<'PYEOF'
import json
try:
    cache = json.load(open(".bench_autotune.json"))
    if cache.get("platform") not in (None, "cpu"):
        print(" ".join(f"{k}={v}"
                       for k, v in cache.get("config", {}).items()))
except Exception:
    pass
PYEOF
)
      fi
      env $knobs timeout 1800 python scripts/tpu_breakdown.py \
        >"$out.breakdown.json" 2>>"$LOG" \
        && echo "$ts breakdown -> $out.breakdown.json" >>"$LOG"
      [ -n "$pids" ] && kill -CONT $pids 2>/dev/null
      exit 0
    fi
    [ -n "$pids" ] && kill -CONT $pids 2>/dev/null
    echo "$ts bench rc=$rc but no TPU result; looping" >>"$LOG"
  else
    echo "$ts tunnel down" >>"$LOG"
  fi
  sleep 240
done
