"""Stage-level cost breakdown of the notary audit kernel on the live
backend (the `--profile` companion: where jax.profiler gives a trace,
this prints attackable numbers per pipeline stage).

Stages of `bls_aggregate_verify_committee_batch` at the bench shape
(100 shards x 135 committee slots):
  aggregate  - masked projective tree reduction of committee G1 sigs
               + G2 pubkeys
  miller     - shared-accumulator optimal-ate Miller loop on the
               aggregates
  final_exp  - inversion-free final-exponentiation check
  full       - the production single-dispatch kernel (all of the above
               fused by XLA)

Timing uses random in-range limb data: every stage is integer-only with
static shapes and no data-dependent control flow, so wall-clock does not
depend on the values. Prints ONE JSON line.

With ``--stacks FILE`` (a collapsed-stack file from the devscope
sampling profiler — ``/profile/stacks`` or ``shard_profileStacks``)
the breakdown also prints a HOST-side top-N table next to the device
stages: self samples per leaf frame plus inclusive samples per frame,
so "the chip spends 60% in miller" and "the host spends 40% in
marshalling" read off one artifact.

Usage: python scripts/tpu_breakdown.py [--shards N] [--committee C]
                                       [--stacks FILE [--stacks-top N]]
Honors the same GETHSHARDING_TPU_* kernel knobs as bench.py.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def parse_collapsed(text: str):
    """Collapsed-stack lines (``frame;frame;frame count``) ->
    (total_samples, self_counts, inclusive_counts). Malformed lines and
    the sampler's ``[stacks-over-budget]`` overflow marker are skipped;
    inclusive counts credit every frame on a stack once per sample (a
    frame repeated by recursion still counts once)."""
    total = 0
    self_counts: dict = {}
    incl_counts: dict = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("["):
            continue
        stack, _, count_s = line.rpartition(" ")
        try:
            count = int(count_s)
        except ValueError:
            continue
        if not stack:
            continue
        frames = stack.split(";")
        total += count
        leaf = frames[-1]
        self_counts[leaf] = self_counts.get(leaf, 0) + count
        for frame in set(frames):
            incl_counts[frame] = incl_counts.get(frame, 0) + count
    return total, self_counts, incl_counts


def host_topn(text: str, n: int = 10):
    """The host-side top-N rows: ``[{frame, self, self_pct, incl,
    incl_pct}]`` ordered by self samples — what the --stacks table and
    the JSON payload carry."""
    total, self_counts, incl_counts = parse_collapsed(text)
    rows = []
    for frame, count in sorted(self_counts.items(),
                               key=lambda kv: -kv[1])[:n]:
        rows.append({
            "frame": frame,
            "self": count,
            "self_pct": round(100.0 * count / total, 1) if total else 0.0,
            "incl": incl_counts.get(frame, count),
            "incl_pct": round(100.0 * incl_counts.get(frame, count)
                              / total, 1) if total else 0.0,
        })
    return total, rows


def _print_host_table(total: int, rows: list) -> None:
    print(f"# host sampling profile: {total} samples", file=sys.stderr)
    print(f"# {'self%':>6} {'incl%':>6} {'self':>7}  frame",
          file=sys.stderr)
    for row in rows:
        print(f"# {row['self_pct']:>5.1f}% {row['incl_pct']:>5.1f}% "
              f"{row['self']:>7}  {row['frame']}", file=sys.stderr)


def _time(fn, args, repeats=5):
    """Median seconds per call, post-compile.

    Completion is forced with a device->host pull (jax.device_get), NOT
    block_until_ready: under the tunnel's remote-execution plugin the
    r4 capture showed block_until_ready returning in ~µs for dispatches
    the production path measures at ~0.4 s (stage 'full' timed BELOW its
    own 'aggregate' sub-stage) — i.e. it does not actually wait. The
    pull adds output-transfer time, but stage outputs here are ~100 KB,
    negligible against the stage costs being attributed."""
    out = fn(*args)
    jax_tree_block(out)
    samples = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = fn(*args)
        jax_tree_block(out)
        samples.append(time.perf_counter() - t0)
    return float(np.median(samples))


def jax_tree_block(out):
    import jax

    jax.device_get(out)


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--shards", type=int, default=100)
    parser.add_argument("--committee", type=int, default=135)
    parser.add_argument("--repeats", type=int, default=5)
    parser.add_argument("--cpu", action="store_true",
                        help="force the hermetic CPU backend (a plain "
                             "JAX_PLATFORMS=cpu still hangs on a dead "
                             "accelerator tunnel under the axon site hook)")
    parser.add_argument("--stacks", default="",
                        help="collapsed-stack file from the devscope "
                             "sampling profiler (/profile/stacks); prints "
                             "a host-side top-N table next to the device "
                             "breakdown and folds it into the JSON line")
    parser.add_argument("--stacks-top", type=int, default=10,
                        help="rows in the host-side table")
    args = parser.parse_args()

    host_total, host_rows = 0, []
    if args.stacks:
        # parse BEFORE the device work: a bad path must fail fast, not
        # after minutes of kernel compiles
        with open(args.stacks) as fh:
            host_total, host_rows = host_topn(fh.read(), args.stacks_top)
        _print_host_table(host_total, host_rows)

    from gethsharding_tpu.parallel.virtual import (configure_compile_cache,
                                                   force_virtual_cpu_devices)

    if args.cpu:
        force_virtual_cpu_devices(1)
    configure_compile_cache()

    import jax
    import jax.numpy as jnp

    from gethsharding_tpu.ops import bn256_jax as k

    platform = jax.devices()[0].platform
    B, C = args.shards, args.committee
    rng = np.random.default_rng(7)
    # the limb count depends on the active form knob (22 exact/25 wide):
    # read it off the engine instead of assuming
    n_limbs = int(np.asarray(k.FP.one).shape[-1])

    def limbs(*shape):
        return jnp.asarray(rng.integers(0, 1 << 12, shape + (n_limbs,),
                                        dtype=np.int32))

    hx, hy = limbs(B), limbs(B)
    sigx, sigy = limbs(B, C), limbs(B, C)
    pkx, pky = limbs(B, C, 2), limbs(B, C, 2)
    sig_mask = jnp.ones((B, C), bool)
    pk_mask = jnp.ones((B, C), bool)
    valid = jnp.ones((B,), bool)

    agg = jax.jit(lambda sx, sy, sm, px, py, pm: (
        k.aggregate_g1_proj(sx, sy, sm), k.aggregate_g2_proj(px, py, pm)))
    (sX, sY, sZ), (pX, pY, pZ) = agg(sigx, sigy, sig_mask, pkx, pky, pk_mask)

    miller = jax.jit(lambda a, b, c, x, y, d, e, f:
                     k._bls_miller_opt((a, b, c), x, y, (d, e, f)))
    f12 = miller(sX, sY, sZ, hx, hy, pX, pY, pZ)

    finalexp = jax.jit(k.pairing_is_one)
    full = jax.jit(k.bls_aggregate_verify_committee_batch)

    timings = {
        "aggregate": _time(agg, (sigx, sigy, sig_mask, pkx, pky, pk_mask),
                           args.repeats),
        "miller": _time(miller, (sX, sY, sZ, hx, hy, pX, pY, pZ),
                        args.repeats),
        "final_exp": _time(finalexp, (f12,), args.repeats),
        "full": _time(full, (hx, hy, sigx, sigy, sig_mask,
                             pkx, pky, pk_mask, valid), args.repeats),
    }
    # sanity: how long does the same 'full' call appear to take when
    # "timed" with block_until_ready only? A large pull/block ratio is
    # direct evidence the plugin's block is a no-op (the r4 artifact's
    # µs-level stages) and the pull-timed numbers above are the real ones
    out = full(hx, hy, sigx, sigy, sig_mask, pkx, pky, pk_mask, valid)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    out = full(hx, hy, sigx, sigy, sig_mask, pkx, pky, pk_mask, valid)
    jax.block_until_ready(out)
    block_timed = time.perf_counter() - t0

    sigs = B * C
    knobs = {key: os.environ.get(key, "") for key in (
        "GETHSHARDING_TPU_LIMB_FORM", "GETHSHARDING_TPU_CARRY",
        "GETHSHARDING_TPU_CONV", "GETHSHARDING_TPU_PAIRCONV",
        "GETHSHARDING_TPU_PALLAS")}
    payload = {
        "platform": platform,
        "shards": B,
        "committee": C,
        "stage_seconds": timings,
        "stage_pct_of_full": {
            name: round(100 * sec / timings["full"], 1)
            for name, sec in timings.items()},
        "sigs_per_sec_full": round(sigs / timings["full"], 1),
        "full_block_timed_s": round(block_timed, 6),
        "knobs": knobs,
    }
    if args.stacks:
        payload["host_samples"] = host_total
        payload["host_stacks_top"] = host_rows
    print(json.dumps(payload))
    return 0


if __name__ == "__main__":
    sys.exit(main())
