#!/bin/bash
# Probe unrolled carries + final-exp-only static unroll (exact form).
cd /root/repo || exit 1
env GETHSHARDING_TPU_LIMB_FORM=exact GETHSHARDING_TPU_CARRY=unroll \
    GETHSHARDING_TPU_PAIR_UNROLL=finalexp \
  timeout 3000 python bench.py --single >"$1.out" 2>"$1.err"
grep -q sig_rate "$1.out" && grep -q '"platform": "tpu' "$1.out"
