#!/bin/bash
# Fixed-base pairing precomputation on the real chip: the bench.py
# --precomp closed loop under the champion knobs. Cold audit pays ONE
# precompute dispatch per new committee row; the warm audit ships ZERO
# G2 pubkey bytes AND skips the Miller-loop point arithmetic entirely
# (the HLO multiply census asserts the shrink), with verdicts
# bit-identical to the scalar twin and the recompute path — including
# empty rows, infinity points and forged rows. The config-5 stress
# record rides along on the precomp-era tree.
#
# Acceptance runs through the perfwatch ledger, not a stdout grep
# alone: bench.py --precomp emits precomp_audit_sig_rate through
# record_bench with the device-timer validity stamp, and
# probe_ledger_check.py fails the probe if the record never landed or
# landed invalid. Until a tunnel window opens,
# PROBE_VIRTUAL_DEVICES=N runs the SAME closed loop hermetically on
# the N-device virtual CPU mesh (GETHSHARDING_MESH_DEVICES lays the
# backend over it; the platform check relaxes to cpu).
cd /root/repo || exit 1
PLATFORM='"platform": "tpu'
VIRT_ENV=()
if [ -n "$PROBE_VIRTUAL_DEVICES" ]; then
  PLATFORM='"platform": "cpu'
  VIRT_ENV=(JAX_PLATFORMS=cpu
    XLA_FLAGS="--xla_force_host_platform_device_count=$PROBE_VIRTUAL_DEVICES"
    GETHSHARDING_MESH_DEVICES="$PROBE_VIRTUAL_DEVICES")
fi
env "${VIRT_ENV[@]}" \
    GETHSHARDING_TPU_LIMB_FORM=exact GETHSHARDING_TPU_CARRY=scan \
    GETHSHARDING_TPU_FINALEXP=mega GETHSHARDING_TPU_MILLER=mega \
    GETHSHARDING_TPU_WIRE=u16 GETHSHARDING_TPU_RESIDENT=1 \
    GETHSHARDING_PRECOMP=1 \
  timeout 4800 python bench.py --precomp >"$1.out" 2>"$1.err"
grep -q '"g2_wire_bytes_warm": 0' "$1.out" \
  && grep -q precomp_audit_sig_rate "$1.out" \
  && grep -q "$PLATFORM" "$1.out" \
  && python scripts/probe_ledger_check.py precomp_audit --max-age 7200 \
  || exit 1
# Composed rider: precomp stacked with resident + overlap in the one
# K-period pipeline (bench.py --composed). Same ledger-gated
# acceptance as the solo run.
env "${VIRT_ENV[@]}" \
    GETHSHARDING_TPU_LIMB_FORM=exact GETHSHARDING_TPU_CARRY=scan \
    GETHSHARDING_TPU_FINALEXP=mega GETHSHARDING_TPU_MILLER=mega \
    GETHSHARDING_TPU_WIRE=u16 GETHSHARDING_TPU_RESIDENT=1 \
    GETHSHARDING_PRECOMP=1 \
  timeout 4800 python bench.py --composed \
    >"$1.composed.out" 2>"$1.composed.err"
grep -q composed_audit_sig_rate "$1.composed.out" \
  && grep -q "$PLATFORM" "$1.composed.out" \
  && python scripts/probe_ledger_check.py composed_audit --max-age 7200
