#!/bin/bash
# Probe bounded scan unrolling (8 steps per While iteration) + unrolled
# carries — the compile-cheap approximation of the full static unroll.
cd /root/repo || exit 1
env GETHSHARDING_TPU_LIMB_FORM=exact GETHSHARDING_TPU_CARRY=unroll \
    GETHSHARDING_TPU_SCAN_UNROLL=8 \
  timeout 2400 python bench.py --single >"$1.out" 2>"$1.err"
grep -q sig_rate "$1.out" && grep -q '"platform": "tpu' "$1.out"
