#!/bin/bash
# The protocol-level lever: audit_periods K-period catch-up batching
# under the champion mega knobs. K in {1,4,8} periods' rows share ONE
# signature dispatch — on a latency-bound kernel K periods cost nearly
# one, so the honest aggregate rate scales with K while the per-period
# latency (reported alongside in extra.kperiod_sweep) shows the cost.
# The workload build signs 8 periods x 13,500 BLS sigs on first run
# (~24 min host scalar crypto, cached in .bench_workload.npz) — hence
# the long timeout; repeats load from disk.
cd /root/repo || exit 1
env GETHSHARDING_TPU_LIMB_FORM=exact GETHSHARDING_TPU_CARRY=scan \
    GETHSHARDING_TPU_FINALEXP=mega GETHSHARDING_TPU_MILLER=mega \
  timeout 6900 python bench.py --kperiod >"$1.out" 2>"$1.err"
grep -q kperiod_sweep "$1.out" && grep -q '"platform": "tpu' "$1.out"
