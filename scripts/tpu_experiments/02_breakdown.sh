#!/bin/bash
# Stage breakdown of the audit kernel with the champion knobs ($1 = out prefix).
cd /root/repo || exit 1
env GETHSHARDING_TPU_LIMB_FORM=exact GETHSHARDING_TPU_CARRY=scan \
  timeout 2400 python scripts/tpu_breakdown.py >"$1.json" 2>"$1.err"
grep -q stage_seconds "$1.json" && grep -q '"platform": "tpu' "$1.json"
