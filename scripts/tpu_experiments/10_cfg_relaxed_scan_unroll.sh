#!/bin/bash
# Probe relaxed normalize + bounded scan unrolling together.
cd /root/repo || exit 1
env GETHSHARDING_TPU_LIMB_FORM=wide GETHSHARDING_TPU_NORM=relaxed \
    GETHSHARDING_TPU_SCAN_UNROLL=8 \
  timeout 2400 python bench.py --single >"$1.out" 2>"$1.err"
grep -q sig_rate "$1.out" && grep -q '"platform": "tpu' "$1.out"
