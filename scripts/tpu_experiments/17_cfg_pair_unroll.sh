#!/bin/bash
# Probe the statically unrolled pairing drivers alone (scan carries).
cd /root/repo || exit 1
env GETHSHARDING_TPU_LIMB_FORM=exact GETHSHARDING_TPU_CARRY=scan \
    GETHSHARDING_TPU_PAIR_UNROLL=1 \
  timeout 3600 python bench.py --single >"$1.out" 2>"$1.err"
grep -q sig_rate "$1.out" && grep -q '"platform": "tpu' "$1.out"
