#!/bin/bash
# Stage breakdown under the r4 sweep champion (slices conv), with the
# fixed pull-forced timing ($1 = out prefix).
cd /root/repo || exit 1
env GETHSHARDING_TPU_LIMB_FORM=exact GETHSHARDING_TPU_CARRY=scan \
    GETHSHARDING_TPU_CONV=slices \
  timeout 2400 python scripts/tpu_breakdown.py >"$1.json" 2>"$1.err"
grep -q stage_seconds "$1.json" && grep -q '"platform": "tpu' "$1.json"
