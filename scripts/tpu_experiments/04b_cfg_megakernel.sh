#!/bin/bash
# Probe the final-exp mega-kernel on the production audit dispatch
# (champion ambient knobs + FINALEXP=mega). On success, re-queue the
# finalize experiment so the canonical capture reflects the new winner.
cd /root/repo || exit 1
env GETHSHARDING_TPU_LIMB_FORM=exact GETHSHARDING_TPU_CARRY=scan \
    GETHSHARDING_TPU_FINALEXP=mega \
  timeout 4800 python bench.py --single >"$1.out" 2>"$1.err"
grep -q sig_rate "$1.out" && grep -q '"platform": "tpu' "$1.out"
