#!/bin/bash
# The two-launch mega pairing with the uint16 wire format (halves the
# audit's host->device bytes) + the marshal/transfer/dispatch split in
# one probe: measures the rate AND attributes where the win (if any)
# lands.
cd /root/repo || exit 1
env GETHSHARDING_TPU_LIMB_FORM=exact GETHSHARDING_TPU_CARRY=scan \
    GETHSHARDING_TPU_FINALEXP=mega GETHSHARDING_TPU_MILLER=mega \
    GETHSHARDING_TPU_WIRE=u16 GETHSHARDING_SIG_TIMING=1 \
  timeout 4800 python bench.py --single >"$1.out" 2>"$1.err"
grep -q sig_rate "$1.out" && grep -q '"platform": "tpu' "$1.out"
