#!/bin/bash
# Probe the relaxed normalize (wide form, no exact carry ripple anywhere).
cd /root/repo || exit 1
env GETHSHARDING_TPU_LIMB_FORM=wide GETHSHARDING_TPU_NORM=relaxed \
  timeout 2400 python bench.py --single >"$1.out" 2>"$1.err"
grep -q sig_rate "$1.out" && grep -q '"platform": "tpu' "$1.out"
