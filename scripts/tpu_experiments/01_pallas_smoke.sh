#!/bin/bash
# FIRST in the window: does Mosaic compile + run the Pallas kernels
# correctly on this backend? Tiny shapes, minutes — answers the
# mega-kernel plan's blocking question before any big probe runs.
cd /root/repo || exit 1
timeout 1800 python scripts/tpu_pallas_smoke.py >"$1.json" 2>"$1.err"
rc=$?
[ $rc -eq 0 ] && grep -Eq '"platform": "(tpu|axon)' "$1.json"
