#!/bin/bash
# Does Mosaic compile + run the final-exp mega-kernel correctly on this
# backend? Tiny batch, isolated from the full bench probe so a compile
# failure is learned cheaply ($1 = out prefix).
cd /root/repo || exit 1
timeout 3600 python scripts/tpu_megakernel_smoke.py >"$1.json" 2>"$1.err"
rc=$?
[ $rc -eq 0 ] && grep -Eq '"platform": "(tpu|axon)' "$1.json"
