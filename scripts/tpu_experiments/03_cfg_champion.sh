#!/bin/bash
# Re-probe the r3 sweep champion (exact/scan) so every candidate has a
# comparable --single stats record for scripts/tpu_pick_winner.py.
cd /root/repo || exit 1
env GETHSHARDING_TPU_LIMB_FORM=exact GETHSHARDING_TPU_CARRY=scan \
  timeout 2400 python bench.py --single >"$1.out" 2>"$1.err"
grep -q sig_rate "$1.out" && grep -q '"platform": "tpu' "$1.out"
