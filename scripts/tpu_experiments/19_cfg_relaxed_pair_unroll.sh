#!/bin/bash
# Probe the no-sequential-anything configuration: relaxed normalize (no
# carry ripple) + fully unrolled pairing drivers (no scan/cond/switch).
cd /root/repo || exit 1
env GETHSHARDING_TPU_LIMB_FORM=wide GETHSHARDING_TPU_NORM=relaxed \
    GETHSHARDING_TPU_PAIR_UNROLL=1 \
  timeout 3600 python bench.py --single >"$1.out" 2>"$1.err"
grep -q sig_rate "$1.out" && grep -q '"platform": "tpu' "$1.out"
