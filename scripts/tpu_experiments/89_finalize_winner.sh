#!/bin/bash
# After the per-config probes: rebuild the autotune cache from the best
# TPU probe, then capture the canonical round result (winner + config
# 1/2/4/5 extras) — the record bench.py replays if the tunnel is dead at
# the driver's end-of-round run. The enlarged budget lifts the extras
# subprocess timeout (bench.py _run_config) so the config-5 stress
# compile cannot silently drop the extras from the capture again
# (r2/r3's unresolved Weak item).
cd /root/repo || exit 1
python scripts/tpu_pick_winner.py || exit 1
# every bench stage derives its subprocess timeout from this absolute
# deadline (bench.py _remaining), so extras + retry + sweep cannot
# cascade past the outer timeout and lose the capture mid-write
env GETHSHARDING_BENCH_NO_REPLAY=1 GETHSHARDING_BENCH_BUDGET_S=3000 \
    GETHSHARDING_BENCH_DEADLINE_TS=$(( $(date +%s) + 6700 )) \
  timeout 7000 python bench.py >"$1.json" 2>"$1.err"
grep '"platform": "tpu' "$1.json" | grep -qv "tunnel unreachable" || exit 1
grep -q config1_pairing_check_s "$1.json" \
  || echo "WARNING: capture landed without the extras pass" >>"$1.err"
# promote to the tracked captures (bench.py embeds captured_at + git on
# every fresh run, so the promoted record is replayable after checkout)
cp -p "$1.json" "bench_results/tpu_capture_$(date +%Y%m%d_%H%M).json"
