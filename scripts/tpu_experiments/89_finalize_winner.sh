#!/bin/bash
# After the per-config probes: rebuild the autotune cache from the best
# TPU probe, then capture the canonical round result (winner + config
# 1/2/4/5 extras) — the record bench.py replays if the tunnel is dead at
# the driver's end-of-round run.
cd /root/repo || exit 1
python scripts/tpu_pick_winner.py || exit 1
env GETHSHARDING_BENCH_NO_REPLAY=1 timeout 7000 python bench.py \
  >"$1.json" 2>"$1.err"
grep '"platform": "tpu' "$1.json" | grep -qv "tunnel unreachable" || exit 1
# promote to the tracked captures (provenance embedded by bench.py)
cp -p "$1.json" "bench_results/tpu_capture_$(date +%Y%m%d_%H%M).json"
