#!/bin/bash
# Refresh the round's canonical capture WITH the config 1/2/4/5 extras
# (the autotune cache already holds the sweep winner, so bench.py goes
# straight to the winner + extras run).
cd /root/repo || exit 1
env GETHSHARDING_BENCH_NO_REPLAY=1 timeout 7000 python bench.py >"$1.json" 2>"$1.err"
# success requires a FRESH TPU measurement, not a replayed capture (the
# mid-run fallback prints the old capture, which also says platform tpu)
grep '"platform": "tpu' "$1.json" | grep -qv "tunnel unreachable"
