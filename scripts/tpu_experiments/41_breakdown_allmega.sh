#!/bin/bash
# Stage breakdown with all three mega-kernels active over the slices
# ambient: attributes whatever remains of the dispatch after the
# aggregation/Miller/final-exp stages each collapse to one launch.
cd /root/repo || exit 1
env GETHSHARDING_TPU_LIMB_FORM=exact GETHSHARDING_TPU_CARRY=scan \
    GETHSHARDING_TPU_CONV=slices \
    GETHSHARDING_TPU_FINALEXP=mega GETHSHARDING_TPU_MILLER=mega \
    GETHSHARDING_TPU_AGG=mega \
  timeout 3600 python scripts/tpu_breakdown.py >"$1.json" 2>"$1.err"
grep -q stage_seconds "$1.json" && grep -q '"platform": "tpu' "$1.json"
