#!/bin/bash
# Mega-kernel + relaxed ambient normalize (the no-sequential-carry
# Miller side): the other mega composition bench.py sweeps.
cd /root/repo || exit 1
env GETHSHARDING_TPU_LIMB_FORM=wide GETHSHARDING_TPU_NORM=relaxed \
    GETHSHARDING_TPU_FINALEXP=mega \
  timeout 4800 python bench.py --single >"$1.out" 2>"$1.err"
grep -q sig_rate "$1.out" && grep -q '"platform": "tpu' "$1.out"
