#!/bin/bash
# Device-resident pk planes on the real chip: cold-vs-warm wire ledger
# of the audit dispatch under the champion knobs. The warm dispatch
# must ship ZERO G2 pubkey bytes (bench asserts it); the cold/warm
# wall delta bounds the transfer share of the 0.297 s dispatch — the
# number that closes probe 42's "transfer dominates" branch. u16 wire
# stacked on top so the fresh-per-period buffers ship narrow too.
#
# Acceptance runs through the perfwatch ledger, not a stdout grep
# alone: bench.py --resident emits audit_warm_wire_bytes_per_dispatch
# through record_bench with the device-timer validity stamp, and
# probe_ledger_check.py fails the probe if the record never landed or
# landed invalid. Until a tunnel window opens,
# PROBE_VIRTUAL_DEVICES=N runs the SAME closed loop hermetically on
# the N-device virtual CPU mesh (GETHSHARDING_MESH_DEVICES lays the
# backend over it; the platform check relaxes to cpu).
cd /root/repo || exit 1
PLATFORM='"platform": "tpu'
VIRT_ENV=()
if [ -n "$PROBE_VIRTUAL_DEVICES" ]; then
  PLATFORM='"platform": "cpu'
  VIRT_ENV=(JAX_PLATFORMS=cpu
    XLA_FLAGS="--xla_force_host_platform_device_count=$PROBE_VIRTUAL_DEVICES"
    GETHSHARDING_MESH_DEVICES="$PROBE_VIRTUAL_DEVICES")
fi
env "${VIRT_ENV[@]}" \
    GETHSHARDING_TPU_LIMB_FORM=exact GETHSHARDING_TPU_CARRY=scan \
    GETHSHARDING_TPU_FINALEXP=mega GETHSHARDING_TPU_MILLER=mega \
    GETHSHARDING_TPU_WIRE=u16 GETHSHARDING_TPU_RESIDENT=1 \
  timeout 4800 python bench.py --resident >"$1.out" 2>"$1.err"
grep -q '"g2_wire_bytes_warm": 0' "$1.out" \
  && grep -q "$PLATFORM" "$1.out" \
  && python scripts/probe_ledger_check.py \
       audit_warm_wire_bytes_per_dispatch --max-age 7200
