#!/bin/bash
# Device-resident pk planes on the real chip: cold-vs-warm wire ledger
# of the audit dispatch under the champion knobs. The warm dispatch
# must ship ZERO G2 pubkey bytes (bench asserts it); the cold/warm
# wall delta bounds the transfer share of the 0.297 s dispatch — the
# number that closes probe 42's "transfer dominates" branch. u16 wire
# stacked on top so the fresh-per-period buffers ship narrow too.
cd /root/repo || exit 1
env GETHSHARDING_TPU_LIMB_FORM=exact GETHSHARDING_TPU_CARRY=scan \
    GETHSHARDING_TPU_FINALEXP=mega GETHSHARDING_TPU_MILLER=mega \
    GETHSHARDING_TPU_WIRE=u16 GETHSHARDING_TPU_RESIDENT=1 \
  timeout 4800 python bench.py --resident >"$1.out" 2>"$1.err"
grep -q '"g2_wire_bytes_warm": 0' "$1.out" \
  && grep -q '"platform": "tpu' "$1.out"
