#!/bin/bash
# The two-launch mega pairing with the in-kernel slice-accumulate conv
# (GETHSHARDING_TPU_MEGA_CONV=slices): each schoolbook MAC lands in its
# column window via static-offset dynamic_update_slice instead of a
# zero-padded concatenate copy — the in-kernel analog of the XLA-land
# CONV=slices sweep winner. First Mosaic compile of the re-traced
# kernels can be slow; value-parity is pinned by
# tests/test_pallas_finalexp.py (bit-identical columns + interpret e2e).
cd /root/repo || exit 1
env GETHSHARDING_TPU_LIMB_FORM=exact GETHSHARDING_TPU_CARRY=scan \
    GETHSHARDING_TPU_FINALEXP=mega GETHSHARDING_TPU_MILLER=mega \
    GETHSHARDING_TPU_MEGA_CONV=slices \
  timeout 4800 python bench.py --single >"$1.out" 2>"$1.err"
grep -q sig_rate "$1.out" && grep -q '"platform": "tpu' "$1.out"
