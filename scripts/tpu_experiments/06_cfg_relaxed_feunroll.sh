#!/bin/bash
# Probe relaxed normalize + final-exp-only static unroll (the 66%-of-
# dispatch driver unrolled at ~half the full-unroll compile cost).
cd /root/repo || exit 1
env GETHSHARDING_TPU_LIMB_FORM=wide GETHSHARDING_TPU_NORM=relaxed \
    GETHSHARDING_TPU_PAIR_UNROLL=finalexp \
  timeout 3000 python bench.py --single >"$1.out" 2>"$1.err"
grep -q sig_rate "$1.out" && grep -q '"platform": "tpu' "$1.out"
