#!/bin/bash
# The four-launch audit dispatch (all mega-kernels) over the slices
# conv ambient — today's sweep champion — so the non-pairing remainder
# of the dispatch also runs its fastest measured form.
cd /root/repo || exit 1
env GETHSHARDING_TPU_LIMB_FORM=exact GETHSHARDING_TPU_CARRY=scan \
    GETHSHARDING_TPU_CONV=slices \
    GETHSHARDING_TPU_FINALEXP=mega GETHSHARDING_TPU_MILLER=mega \
    GETHSHARDING_TPU_AGG=mega \
  timeout 4800 python bench.py --single >"$1.out" 2>"$1.err"
grep -q sig_rate "$1.out" && grep -q '"platform": "tpu' "$1.out"
