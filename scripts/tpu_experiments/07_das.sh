#!/bin/bash
# Data-availability sampling on the real chip: the sampled-notary
# acceptance run (zero body fetches, bytes within the k-sample budget)
# plus batched das_verify_samples throughput — the keccak-lane dispatch
# (BMT recompute + path fold over samples x shards) that is
# emulation-bound on hermetic CPU and only shows its real rows/sec on
# the TPU VPU. Success: the acceptance asserts held (bench exits 0,
# votes == periods) AND the metric line reports a tpu platform.
cd /root/repo || exit 1
env GETHSHARDING_BENCH_DAS_BODY=1048576 \
    GETHSHARDING_BENCH_DAS_SAMPLES=16 \
    GETHSHARDING_BENCH_DAS_ROWS=512 \
  timeout 4800 python bench.py --das >"$1.out" 2>"$1.err"
grep -q '"platform": "tpu' "$1.out" \
  && grep -q '"metric": "das_sampled_bytes_per_collation"' "$1.out"
