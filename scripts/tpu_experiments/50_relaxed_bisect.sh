#!/bin/bash
# Find the first field op where wide/relaxed diverges from the host
# goldens on this backend (passes on CPU, fails the audit gate on TPU —
# r4). $1 = out prefix.
cd /root/repo || exit 1
env GETHSHARDING_TPU_LIMB_FORM=wide GETHSHARDING_TPU_NORM=relaxed \
  timeout 3600 python scripts/tpu_relaxed_bisect.py >"$1.json" 2>"$1.err"
rc=$?
[ $rc -eq 0 ] && grep -Eq '"platform": "(tpu|axon)' "$1.json"
