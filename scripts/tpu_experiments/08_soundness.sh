#!/bin/bash
# Continuous soundness audit on the REAL device path: the overhead of
# the spot-check audit (always-on invariant sweep + rate-amortized
# scalar re-verification) measured against a real jax ecrecover
# dispatch — asserted <2% inside bench.py — plus the closed-loop
# proof that an every-dispatch silent corruptor (chaos mode=corrupt,
# no exception ever raised) trips the failover breaker within the
# dispatch budget detection_probability predicts.
cd /root/repo || exit 1
env GETHSHARDING_BENCH_SOUNDNESS_BACKEND=jax \
  timeout 1800 python bench.py --soundness >"$1.out" 2>"$1.err"
grep -q soundness_overhead_pct "$1.out" \
    && grep -q '"dispatches_to_trip"' "$1.out"
