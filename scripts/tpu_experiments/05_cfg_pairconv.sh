#!/bin/bash
# Probe the fused Pallas pair-conv kernel (unmeasured on TPU; r3 addition).
cd /root/repo || exit 1
env GETHSHARDING_TPU_LIMB_FORM=exact GETHSHARDING_TPU_CARRY=scan \
    GETHSHARDING_TPU_PAIRCONV=pallas \
  timeout 2400 python bench.py --single >"$1.out" 2>"$1.err"
grep -q sig_rate "$1.out" && grep -q '"platform": "tpu' "$1.out"
