#!/bin/bash
# Layout micro-bench: limbs-minor vs batch-minor elementwise/carry
# throughput on the real chip (tiny compiles; answers whether a limb-
# engine layout refactor is the next 10x).
cd /root/repo || exit 1
timeout 1200 python scripts/tpu_layout_micro.py >"$1.json" 2>"$1.err"
grep -q '"platform": "tpu' "$1.json"
