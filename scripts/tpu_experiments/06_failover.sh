#!/bin/bash
# Breaker failover under deterministic chaos with the REAL device path
# as the primary: seeded injected faults hit the jax backend, every
# call must still answer correctly from the scalar fallback, and the
# breaker must ride the full open -> half-open differential probe ->
# closed cycle (breaker_reclosed). The availability number is the
# paper's always-vote contract measured under failure.
cd /root/repo || exit 1
env GETHSHARDING_BENCH_CHAOS_BACKEND=jax GETHSHARDING_CHAOS_RATE=0.3 \
    GETHSHARDING_BENCH_CHAOS_CALLS=45 \
  timeout 1800 python bench.py --chaos >"$1.out" 2>"$1.err"
grep -q chaos_availability "$1.out" && grep -q '"breaker_reclosed": true' "$1.out"
