#!/bin/bash
# Marshal/dispatch overlap on the real chip: sequential vs overlapped
# K=4 period audit pipeline under the champion knobs. The overlapped
# form marshals+stages period N+1 while N executes on device; the
# ratio bounds how much host marshal + tunnel RTT the dispatch hides.
# The 4-period signature workload loads from .bench_workload.npz when
# the 03e/kperiod pre-builder has run (~12 min host build otherwise —
# hence the long timeout; repeats are cheap).
cd /root/repo || exit 1
env GETHSHARDING_TPU_LIMB_FORM=exact GETHSHARDING_TPU_CARRY=scan \
    GETHSHARDING_TPU_FINALEXP=mega GETHSHARDING_TPU_MILLER=mega \
    GETHSHARDING_BENCH_OVERLAP_K=4 \
  timeout 6900 python bench.py --overlap >"$1.out" 2>"$1.err"
grep -q overlap_ratio "$1.out" && grep -q '"platform": "tpu' "$1.out"
