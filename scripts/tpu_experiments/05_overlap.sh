#!/bin/bash
# Marshal/dispatch overlap on the real chip: sequential vs overlapped
# K=4 period audit pipeline under the champion knobs. The overlapped
# form marshals+stages period N+1 while N executes on device; the
# ratio bounds how much host marshal + tunnel RTT the dispatch hides.
# The 4-period signature workload loads from .bench_workload.npz when
# the 03e/kperiod pre-builder has run (~12 min host build otherwise —
# hence the long timeout; repeats are cheap).
#
# Acceptance runs through the perfwatch ledger, not a stdout grep
# alone: bench.py --overlap emits audit_overlap_ratio through
# record_bench with the device-timer validity stamp, and
# probe_ledger_check.py fails the probe if the record never landed or
# landed invalid. Until a tunnel window opens,
# PROBE_VIRTUAL_DEVICES=N runs the SAME closed loop hermetically on
# the N-device virtual CPU mesh (GETHSHARDING_MESH_DEVICES lays the
# backend over it; the platform check relaxes to cpu).
cd /root/repo || exit 1
PLATFORM='"platform": "tpu'
VIRT_ENV=()
if [ -n "$PROBE_VIRTUAL_DEVICES" ]; then
  PLATFORM='"platform": "cpu'
  VIRT_ENV=(JAX_PLATFORMS=cpu
    XLA_FLAGS="--xla_force_host_platform_device_count=$PROBE_VIRTUAL_DEVICES"
    GETHSHARDING_MESH_DEVICES="$PROBE_VIRTUAL_DEVICES")
fi
env "${VIRT_ENV[@]}" \
    GETHSHARDING_TPU_LIMB_FORM=exact GETHSHARDING_TPU_CARRY=scan \
    GETHSHARDING_TPU_FINALEXP=mega GETHSHARDING_TPU_MILLER=mega \
    GETHSHARDING_BENCH_OVERLAP_K=4 \
  timeout 6900 python bench.py --overlap >"$1.out" 2>"$1.err"
grep -q overlap_ratio "$1.out" \
  && grep -q "$PLATFORM" "$1.out" \
  && python scripts/probe_ledger_check.py audit_overlap_ratio \
       --max-age 7200 \
  || exit 1
# Composed rider: the overlapped K-period pipeline against warm
# resident pk planes AND warm fixed-base line tables (bench.py
# --composed) — overlap's steady-state production shape. Same
# ledger-gated acceptance as the solo run.
env "${VIRT_ENV[@]}" \
    GETHSHARDING_TPU_LIMB_FORM=exact GETHSHARDING_TPU_CARRY=scan \
    GETHSHARDING_TPU_FINALEXP=mega GETHSHARDING_TPU_MILLER=mega \
    GETHSHARDING_TPU_RESIDENT=1 GETHSHARDING_BENCH_COMPOSED_K=4 \
  timeout 6900 python bench.py --composed \
    >"$1.composed.out" 2>"$1.composed.err"
grep -q composed_audit_sig_rate "$1.composed.out" \
  && grep -q "$PLATFORM" "$1.composed.out" \
  && python scripts/probe_ledger_check.py composed_audit --max-age 7200
