#!/bin/bash
# Host-marshal / tunnel-transfer / device-dispatch split of the audit
# call under the champion knobs: decides whether the next lever belongs
# on the device side (kernels) or the host side (marshalling, transfer
# width, device-resident rows).
cd /root/repo || exit 1
env GETHSHARDING_TPU_LIMB_FORM=exact GETHSHARDING_TPU_CARRY=scan \
    GETHSHARDING_TPU_CONV=slices GETHSHARDING_SIG_TIMING=1 \
  timeout 4800 python bench.py --single >"$1.out" 2>"$1.err"
grep -q sig_timing "$1.out" && grep -q '"platform": "tpu' "$1.out"
