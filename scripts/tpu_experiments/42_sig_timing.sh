#!/bin/bash
# Host-marshal / tunnel-transfer / device-dispatch split of the audit
# call under the CHAMPION knobs (exact/scan + two-launch mega pairing,
# the 45.5k r4 config): decides whether the next lever belongs on the
# device side (kernels) or the host side (marshalling, transfer width,
# device-resident rows). The timing path syncs transfers with ONE fused
# pull (r5), so transfer_s reflects bandwidth, not per-buffer RTTs.
cd /root/repo || exit 1
env GETHSHARDING_TPU_LIMB_FORM=exact GETHSHARDING_TPU_CARRY=scan \
    GETHSHARDING_TPU_FINALEXP=mega GETHSHARDING_TPU_MILLER=mega \
    GETHSHARDING_SIG_TIMING=1 \
  timeout 4800 python bench.py --single >"$1.out" 2>"$1.err"
grep -q sig_timing "$1.out" && grep -q '"platform": "tpu' "$1.out"
