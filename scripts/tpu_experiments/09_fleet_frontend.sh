#!/bin/bash
# Fleet frontend hardening on the REAL device path: the bench.py
# --fleet acceptance chain with jax replicas — the traffic-model soak
# (breaker trip + drain + re-entry), the hedging closed loop (one
# replica transport-delayed 10x must see interactive p99 improve >=2x
# at <=15% wasted duplicate dispatches, asserted inside bench), and
# the partition/kill soak (zero incorrect verdicts, typed failures
# only). Emits through the perfwatch ledger like every bench mode.
cd /root/repo || exit 1
env GETHSHARDING_BENCH_FLEET_BACKEND=jax \
  GETHSHARDING_PERFWATCH_DIR=/tmp/pw_fleet_probe \
  timeout 1800 python bench.py --fleet >"$1.out" 2>"$1.err"
grep -q fleet_hedge_p99_improvement "$1.out" \
    && grep -q fleet_partition_soak_completed "$1.out"
