// Sharding-domain golden-vector generator — run on a Go-equipped host
// against go-ethereum 1.8.9 + sharding (the reference this framework
// re-implements) to produce cross-implementation vectors for
// tests/testdata/go_sharding_vectors.json.
//
// THIS ENVIRONMENT HAS NO GO TOOLCHAIN (see README.md in this
// directory): the byte-identity demanded by BASELINE.md ("byte-identical
// vote outcomes vs. the pure-Go path") is closed today by the
// conformance suites (RLP / keccak / trie / EIP-155 / FIPS-202 KATs /
// Web3 keystore v3) plus self-generated drift pins; THIS program closes
// the remaining sharding-domain leg (collation-header hash, blob codec,
// POC) the moment someone runs it where Go exists.
//
// Usage (GOPATH layout, as 1.8.9 predates modules):
//   mkdir -p $GOPATH/src/github.com/ethereum
//   ln -s /path/to/reference $GOPATH/src/github.com/ethereum/go-ethereum
//   go run main.go > go_sharding_vectors.json
//
// Output schema (consumed by tests/test_conformance.py once present):
//   {"collation_headers": [{shardID, period, chunkRoot, proposer,
//                           sig, hash}],
//    "blob_codec": [{payloads: [hex], serialized: hex}],
//    "poc": [{body: hex, salt: hex, poc: hex}]}
package main

import (
	"encoding/hex"
	"encoding/json"
	"fmt"
	"math/big"
	"os"

	"github.com/ethereum/go-ethereum/common"
	"github.com/ethereum/go-ethereum/sharding"
	"github.com/ethereum/go-ethereum/sharding/utils"
)

func hexb(b []byte) string { return hex.EncodeToString(b) }

func main() {
	out := map[string]interface{}{}

	// 1. collation-header hashes: the consensus identity of a collation
	//    (sharding/collation.go:66 Hash = keccak256(rlp(header data)))
	headers := []map[string]string{}
	for i := 0; i < 8; i++ {
		shard := big.NewInt(int64(i))
		period := big.NewInt(int64(100 + i))
		var root common.Hash
		for j := range root {
			root[j] = byte(i*31 + j)
		}
		addr := common.BytesToAddress([]byte{byte(i), 0xAA, 0xBB})
		sig := []byte{}
		if i%2 == 1 {
			sig = make([]byte, 65)
			for j := range sig {
				sig[j] = byte(i + j)
			}
		}
		h := sharding.NewCollationHeader(shard, &root, period, &addr, sig)
		headers = append(headers, map[string]string{
			"shardID":   shard.String(),
			"period":    period.String(),
			"chunkRoot": hexb(root[:]),
			"proposer":  hexb(addr[:]),
			"sig":       hexb(sig),
			"hash":      hexb(h.Hash().Bytes()),
		})
	}
	out["collation_headers"] = headers

	// 2. blob codec at the RawBlob layer (sharding/utils/marshal.go:71
	//    Serialize): NewRawBlob RLP-wraps the payload, so the Python
	//    twin is RawBlob(data=rlp_encode(payload), skip_evm=flag)
	blobs := []map[string]interface{}{}
	for _, spec := range []struct {
		payloads [][]byte
		skips    []bool
	}{
		{[][]byte{{0x01}}, []bool{false}},
		{[][]byte{{0xFF, 0xFE}, make([]byte, 31)}, []bool{true, false}},
		{[][]byte{make([]byte, 62), {0xAB}}, []bool{false, true}},
	} {
		raw := []*utils.RawBlob{}
		pl := []map[string]interface{}{}
		for n, p := range spec.payloads {
			blob, err := utils.NewRawBlob(p, spec.skips[n])
			if err != nil {
				fmt.Fprintln(os.Stderr, "rawblob:", err)
				os.Exit(1)
			}
			raw = append(raw, blob)
			pl = append(pl, map[string]interface{}{
				"payload": hexb(p), "skip_evm": spec.skips[n]})
		}
		serialized, err := utils.Serialize(raw)
		if err != nil {
			fmt.Fprintln(os.Stderr, "serialize:", err)
			os.Exit(1)
		}
		blobs = append(blobs, map[string]interface{}{
			"blobs": pl, "serialized": hexb(serialized)})
	}
	out["blob_codec"] = blobs

	// 3. proof-of-custody values over fixed bodies + salts
	//    (sharding/collation.go:124 CalculatePOC)
	pocs := []map[string]string{}
	for i, body := range [][]byte{
		{0x01, 0x02, 0x03},
		make([]byte, 100),
	} {
		salt := []byte{byte(i), 0x55}
		header := sharding.NewCollationHeader(
			big.NewInt(0), nil, big.NewInt(1), nil, nil)
		c := sharding.NewCollation(header, body, nil)
		poc := c.CalculatePOC(salt)
		pocs = append(pocs, map[string]string{
			"body": hexb(body), "salt": hexb(salt),
			"poc": hexb(poc.Bytes())})
	}
	out["poc"] = pocs

	enc := json.NewEncoder(os.Stdout)
	enc.SetIndent("", " ")
	if err := enc.Encode(out); err != nil {
		os.Exit(1)
	}
}
