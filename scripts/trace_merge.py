#!/usr/bin/env python
"""Merge per-process Chrome trace exports into ONE Perfetto file.

Each process in a fleet topology (router frontend, N chain_server
replicas) exports its own Chrome trace JSON via
``tracing.write_chrome_trace`` / ``chain_server --trace-out``. Those
files share span/trace ids for stitched requests (the RPC trace
envelope carries the caller's context; tracer ids are process-unique),
but their timestamps are raw per-process monotonic clocks with
unrelated origins — loaded together as-is they would not line up.

This tool rebases every file onto the common wall clock using the
``clock_offset_us`` anchor the export writes into ``otherData``
(``wall_us = mono_us + offset``), keeps each file's ``pid`` lane
(reassigning on collision so two replicas on different hosts with the
same pid still get separate lanes), and emits one merged
``{"traceEvents": [...]}`` file: open it in https://ui.perfetto.dev
and a routed request reads router route → replica handler → serving
dispatch end to end, one trace id across process lanes.

Usage::

    python scripts/trace_merge.py router.json replica0.json \
        replica1.json -o merged.json

Files written by older exports (no ``otherData`` anchor) merge with a
zero offset and a warning — lanes appear, alignment is best-effort.

Cross-host merging: the ``clock_offset_us`` anchor maps each process's
monotonic clock onto *its own host's* wall clock, so dumps from two
hosts still disagree by the inter-host wall-clock skew. The fleettrace
export plane measures exactly that number per connection — the
``shard_traceHandshake`` NTP-midpoint exchange — and reports it as
``skew_us`` on each exporter's stats (``/status`` →
``fleettrace.export.skew_us`` on the exporting process). Feed it back
here with a per-file ``--skew-us`` override, one value per input in
order (missing trailing values default to 0)::

    python scripts/trace_merge.py frontend.json replicaA.json \
        replicaB.json --skew-us 0 --skew-us 1250 --skew-us -840 \
        -o merged.json

where 1250/-840 are the handshake-measured skews of replica A/B's
hosts relative to the frontend host. Same-host merges need no
override — the anchors already agree.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List


def merge_traces(payloads: List[dict],
                 skews_us: List[float] = None) -> dict:
    """Merge loaded Chrome-trace payloads (the testable core).

    Timestamps are rebased to wall microseconds via each payload's
    ``otherData.clock_offset_us`` plus an optional per-payload
    ``skews_us[i]`` (handshake-measured inter-host skew), then shifted
    so the merged origin is the earliest event (Perfetto renders small
    positive timestamps better than epoch-sized ones)."""
    merged: List[dict] = []
    used_pids: dict = {}
    rebased: List[tuple] = []
    skews_us = list(skews_us or [])
    for i, payload in enumerate(payloads):
        other = payload.get("otherData", {}) or {}
        offset = float(other.get("clock_offset_us", 0.0))
        if i < len(skews_us):
            offset += float(skews_us[i])
        if "clock_offset_us" not in other:
            print(f"warning: input {i} has no clock anchor; merging "
                  f"with zero offset (lanes align only within it)",
                  file=sys.stderr)
        pid = other.get("pid", i)
        # lane collision (same pid from two hosts, or anchorless files
        # defaulting): reassign a fresh lane, keep the label
        lane = pid
        while lane in used_pids and used_pids[lane] != i:
            lane = max(used_pids) + 1
        used_pids[lane] = i
        for event in payload.get("traceEvents", []):
            event = dict(event)
            if event.get("pid") == pid or "pid" not in event:
                event["pid"] = lane
            if event.get("ph") != "M":
                event["ts"] = event.get("ts", 0) + offset
            rebased.append((lane, event))
    spans = [e for _, e in rebased if e.get("ph") != "M"]
    if not spans:
        # metadata-only inputs (idle processes exported before any
        # span finished): emit the lanes, nothing to rebase
        return {"traceEvents": [e for _, e in rebased],
                "displayTimeUnit": "ms",
                "otherData": {"merged_from": len(payloads),
                              "origin_wall_us": 0.0}}
    origin = min(e["ts"] for e in spans)
    for _, event in rebased:
        if event.get("ph") != "M":
            event["ts"] = round(event["ts"] - origin, 1)
        merged.append(event)
    return {"traceEvents": merged, "displayTimeUnit": "ms",
            "otherData": {"merged_from": len(payloads),
                          "origin_wall_us": origin}}


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="trace-merge", description=__doc__.splitlines()[0])
    parser.add_argument("inputs", nargs="+",
                        help="per-process Chrome trace JSON files")
    parser.add_argument("-o", "--out", default="merged_trace.json")
    parser.add_argument("--skew-us", action="append", type=float,
                        default=[], metavar="US",
                        help="per-input wall-clock skew override in "
                             "microseconds, repeatable, matched to "
                             "INPUTS in order (missing trailing values "
                             "= 0); use the handshake-measured skew_us "
                             "from the exporting process's /status "
                             "fleettrace.export section when merging "
                             "dumps from different hosts")
    args = parser.parse_args(argv)
    if len(args.skew_us) > len(args.inputs):
        parser.error("more --skew-us values than inputs")
    payloads = []
    for path in args.inputs:
        with open(path) as fh:
            payloads.append(json.load(fh))
    merged = merge_traces(payloads, skews_us=args.skew_us)
    with open(args.out, "w") as fh:
        json.dump(merged, fh)
    spans = sum(1 for e in merged["traceEvents"] if e.get("ph") == "X")
    traces = len({e["args"]["trace_id"]
                  for e in merged["traceEvents"]
                  if e.get("ph") == "X" and "trace_id" in e.get("args", {})})
    print(json.dumps({"out": args.out, "inputs": len(payloads),
                      "events": spans, "traces": traces}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
