#!/bin/bash
# The canonical full-suite run: one short-lived pytest process per test
# file, each with the host-keyed persistent compile cache enabled.
#
# Why not one big `pytest tests/`? XLA:CPU deterministically segfaults
# (de)serializing one of the large mesh executables once a process holds
# ~150 compiled programs (see tests/conftest.py) — and without the cache
# a monolithic run pays every heavyweight kernel compile cold. Per-file
# processes sidestep the crash AND keep the cache speedup. Coverage is
# identical; a failing file fails the script.
set -u
cd "$(dirname "$0")/.."
fail=0
for f in tests/test_*.py; do
    echo "== $f"
    GETHSHARDING_CACHE_WRITES=1 python -m pytest "$f" -q --no-header || fail=1
done
exit $fail
