#!/bin/bash
# Maximally isolated full-suite run: one short-lived pytest process per
# test file, each with the host-keyed persistent compile cache enabled.
#
# Since r3 a plain one-process `pytest tests/` is ALSO green (conftest
# bounds XLA:CPU's executable-count pressure with jax.clear_caches()
# per module — the root cause of the old segfault); this script remains
# as the fully isolated equivalent (one crash cannot take out the whole
# run). Coverage is identical; a failing file fails the script.
set -u
cd "$(dirname "$0")/.."
fail=0
for f in tests/test_*.py; do
    echo "== $f"
    python -m pytest "$f" -q --no-header || fail=1
done
exit $fail
