#!/bin/bash
# Maximally isolated full-suite run: one short-lived pytest process per
# test file, each with the host-keyed persistent compile cache enabled.
#
# Since r3 a plain one-process `pytest tests/` is ALSO green (conftest
# bounds XLA:CPU's executable-count pressure with jax.clear_caches()
# per module — the root cause of the old segfault); this script remains
# as the fully isolated equivalent (one crash cannot take out the whole
# run). Coverage is identical; a failing file fails the script.
set -u
cd "$(dirname "$0")/.."
fail=0

# -- observability smoke: boot an observer node, scrape every surface ------
# A real `tpu-sharding sharding` process must answer /healthz, Prometheus
# /metrics?format=prom and /trace with 200 + non-empty payloads — the
# curl-level contract the dashboards/scrapers depend on, checked against
# a live process rather than an in-process test double.
obs_port=$(python -c "import socket; s = socket.socket(); \
s.bind(('127.0.0.1', 0)); print(s.getsockname()[1]); s.close()")
echo "== observability smoke (http://127.0.0.1:$obs_port)"
JAX_PLATFORMS=cpu python -m gethsharding_tpu.node.cli sharding \
    --actor observer --http "$obs_port" --trace --runtime 60 \
    --blocktime 0.2 --txinterval 1.0 --verbosity error &
obs_pid=$!
up=0
for _ in $(seq 1 100); do
    if curl -sf "http://127.0.0.1:$obs_port/healthz" >/dev/null 2>&1; then
        up=1; break
    fi
    sleep 0.2
done
if [ "$up" = 1 ]; then
    for ep in "/healthz" "/metrics?format=prom" "/trace"; do
        body=$(curl -sf "http://127.0.0.1:$obs_port$ep") || body=""
        if [ -z "$body" ]; then
            echo "observability smoke FAILED: $ep returned non-200 or empty"
            fail=1
        fi
    done
else
    echo "observability smoke FAILED: node never answered /healthz"
    fail=1
fi
kill "$obs_pid" 2>/dev/null
wait "$obs_pid" 2>/dev/null

# -- resident/overlap parity smoke: the device-resident pk cache and the
# async committee path, exercised end-to-end on hermetic CPU at a small
# shape — warm dispatch must ship zero G2 bytes, async == sync == scalar
echo "== resident/overlap smoke"
# pin the knob under test: an ambient GETHSHARDING_TPU_RESIDENT=0 A/B
# setting must not fail the suite's zero-G2 assertion
JAX_PLATFORMS=cpu GETHSHARDING_TPU_RESIDENT=1 python - <<'PYEOF' || fail=1
from gethsharding_tpu.crypto import bn256 as bls
from gethsharding_tpu.sigbackend import get_backend

py, jx = get_backend("python"), get_backend("jax")
msgs, sig_rows, pk_rows, keys = [], [], [], []
for i in range(3):
    tag = b"suite-%d" % i
    ks = [bls.bls_keygen(tag + bytes([j])) for j in range(2)]
    sigs = [bls.bls_sign(tag, sk) for sk, _ in ks]
    if i == 1:
        sigs[0] = bls.bls_sign(b"tampered", ks[0][0])
    msgs.append(tag); sig_rows.append(sigs)
    pk_rows.append([pk for _, pk in ks]); keys.append(("suite", i))
want = py.bls_verify_committees(msgs, sig_rows, pk_rows)
assert jx.bls_verify_committees(
    msgs, sig_rows, pk_rows, pk_row_keys=keys) == want
fut = jx.bls_verify_committees_async(
    msgs, sig_rows, pk_rows, pk_row_keys=keys)
assert fut.result() == want
assert jx.last_wire["g2_wire_bytes"] == 0, jx.last_wire  # warm = resident
print("resident/overlap smoke OK:", jx.last_wire)
PYEOF

for f in tests/test_*.py; do
    echo "== $f"
    python -m pytest "$f" -q --no-header || fail=1
done
exit $fail
