#!/bin/bash
# Maximally isolated full-suite run: one short-lived pytest process per
# test file, each with the host-keyed persistent compile cache enabled.
#
# Since r3 a plain one-process `pytest tests/` is ALSO green (conftest
# bounds XLA:CPU's executable-count pressure with jax.clear_caches()
# per module — the root cause of the old segfault); this script remains
# as the fully isolated equivalent (one crash cannot take out the whole
# run). Coverage is identical; a failing file fails the script.
set -u
cd "$(dirname "$0")/.."
fail=0

# -- observability smoke: boot an observer node, scrape every surface ------
# A real `tpu-sharding sharding` process must answer /healthz, Prometheus
# /metrics?format=prom and /trace with 200 + non-empty payloads — the
# curl-level contract the dashboards/scrapers depend on, checked against
# a live process rather than an in-process test double.
obs_port=$(python -c "import socket; s = socket.socket(); \
s.bind(('127.0.0.1', 0)); print(s.getsockname()[1]); s.close()")
echo "== observability smoke (http://127.0.0.1:$obs_port)"
JAX_PLATFORMS=cpu python -m gethsharding_tpu.node.cli sharding \
    --actor observer --http "$obs_port" --trace --fleettrace --runtime 60 \
    --blocktime 0.2 --txinterval 1.0 --verbosity error &
obs_pid=$!
up=0
for _ in $(seq 1 100); do
    if curl -sf "http://127.0.0.1:$obs_port/healthz" >/dev/null 2>&1; then
        up=1; break
    fi
    sleep 0.2
done
if [ "$up" = 1 ]; then
    for ep in "/healthz" "/metrics?format=prom" "/trace"; do
        body=$(curl -sf "http://127.0.0.1:$obs_port$ep") || body=""
        if [ -z "$body" ]; then
            echo "observability smoke FAILED: $ep returned non-200 or empty"
            fail=1
        fi
    done
    # the SLO plane boots with the node: its burn-rate gauges must be
    # present on the Prometheus exposition from the first scrape
    prom=$(curl -sf "http://127.0.0.1:$obs_port/metrics?format=prom") || prom=""
    if ! echo "$prom" | grep -q "gethsharding_slo_interactive_burn_rate"; then
        echo "observability smoke FAILED: slo/interactive/burn_rate missing" \
             "from /metrics?format=prom"
        fail=1
    fi
    # ... and so must the perfwatch trust counters (timer self-check +
    # flight recorder), registered at package import
    if ! echo "$prom" | grep -q "gethsharding_perfwatch_timer_suspect_total"
    then
        echo "observability smoke FAILED: perfwatch/timer_suspect missing" \
             "from /metrics?format=prom"
        fail=1
    fi
    # ... and the fleettrace collector booted by --fleettrace: its
    # ingest counters must reach the exposition from the first scrape
    if ! echo "$prom" | grep -q "gethsharding_fleettrace_ingest_spans_total"
    then
        echo "observability smoke FAILED: fleettrace/ingest/spans missing" \
             "from /metrics?format=prom"
        fail=1
    fi
    # the /status perf section renders (last ledger record + gate +
    # recorder state)
    if ! curl -sf "http://127.0.0.1:$obs_port/status" \
            | grep -q '"perf"'; then
        echo "observability smoke FAILED: /status has no perf section"
        fail=1
    fi
    # ... and so does the fleettrace section, live (active collector)
    if ! curl -sf "http://127.0.0.1:$obs_port/status" | python -c "
import json, sys
status = json.load(sys.stdin)
assert status['fleettrace']['active'], status.get('fleettrace')
"; then
        echo "observability smoke FAILED: /status fleettrace section" \
             "missing or inactive under --fleettrace"
        fail=1
    fi
else
    echo "observability smoke FAILED: node never answered /healthz"
    fail=1
fi
kill "$obs_pid" 2>/dev/null
wait "$obs_pid" 2>/dev/null

# -- resident/overlap parity smoke: the device-resident pk cache and the
# async committee path, exercised end-to-end on hermetic CPU at a small
# shape — warm dispatch must ship zero G2 bytes, async == sync == scalar
echo "== resident/overlap smoke"
# pin the knob under test: an ambient GETHSHARDING_TPU_RESIDENT=0 A/B
# setting must not fail the suite's zero-G2 assertion
JAX_PLATFORMS=cpu GETHSHARDING_TPU_RESIDENT=1 python - <<'PYEOF' || fail=1
from gethsharding_tpu.crypto import bn256 as bls
from gethsharding_tpu.sigbackend import get_backend

py, jx = get_backend("python"), get_backend("jax")
msgs, sig_rows, pk_rows, keys = [], [], [], []
for i in range(3):
    tag = b"suite-%d" % i
    ks = [bls.bls_keygen(tag + bytes([j])) for j in range(2)]
    sigs = [bls.bls_sign(tag, sk) for sk, _ in ks]
    if i == 1:
        sigs[0] = bls.bls_sign(b"tampered", ks[0][0])
    msgs.append(tag); sig_rows.append(sigs)
    pk_rows.append([pk for _, pk in ks]); keys.append(("suite", i))
want = py.bls_verify_committees(msgs, sig_rows, pk_rows)
assert jx.bls_verify_committees(
    msgs, sig_rows, pk_rows, pk_row_keys=keys) == want
fut = jx.bls_verify_committees_async(
    msgs, sig_rows, pk_rows, pk_row_keys=keys)
assert fut.result() == want
assert jx.last_wire["g2_wire_bytes"] == 0, jx.last_wire  # warm = resident
print("resident/overlap smoke OK:", jx.last_wire)
PYEOF

# -- precomp smoke: fixed-base line tables end-to-end on hermetic CPU —
# ONE audit with precomp on vs off, verdicts bit-identical to the
# scalar reference (incl. a forged row), the warm dispatch ships zero
# G2 bytes AND runs from the cached line tables (precomp wire stamp),
# and the flag-off backend takes today's recompute path unchanged
echo "== precomp smoke"
JAX_PLATFORMS=cpu GETHSHARDING_TPU_RESIDENT=1 GETHSHARDING_PRECOMP=1 \
python - <<'PYEOF' || fail=1
import os

from gethsharding_tpu.crypto import bn256 as bls
from gethsharding_tpu.sigbackend import PythonSigBackend
from gethsharding_tpu.sigbackend.dispatch import JaxSigBackend

py = PythonSigBackend()
msgs, sig_rows, pk_rows, keys = [], [], [], []
for i in range(3):
    tag = b"pre-suite-%d" % i
    ks = [bls.bls_keygen(tag + bytes([j])) for j in range(2)]
    sigs = [bls.bls_sign(tag, sk) for sk, _ in ks]
    if i == 1:
        sigs[0] = bls.bls_sign(b"tampered", ks[0][0])
    msgs.append(tag); sig_rows.append(sigs)
    pk_rows.append([pk for _, pk in ks]); keys.append(("pre-suite", i))
want = py.bls_verify_committees(msgs, sig_rows, pk_rows)
on = JaxSigBackend()
assert on._precomp, "GETHSHARDING_PRECOMP=1 did not engage"
cold = on.bls_verify_committees(msgs, sig_rows, pk_rows, pk_row_keys=keys)
warm = on.bls_verify_committees(msgs, sig_rows, pk_rows, pk_row_keys=keys)
assert cold == warm == want, (cold, warm, want)
assert on.last_wire["precomp"] is True, on.last_wire
assert on.last_wire["g2_wire_bytes"] == 0, on.last_wire  # warm line tables
os.environ["GETHSHARDING_PRECOMP"] = "0"
off = JaxSigBackend()
assert not off._precomp
assert off.bls_verify_committees(
    msgs, sig_rows, pk_rows, pk_row_keys=keys) == want
assert off.last_wire["precomp"] is False, off.last_wire
print("precomp smoke OK:", on.last_wire)
PYEOF

# -- mesh smoke: the multi-chip dispatch core on a 2-device virtual
# mesh — ONE audit through scalar / single-device / mesh (bench.py
# --mesh asserts bit-identity, exactly one cross-device collective,
# sharded verdicts and disjoint per-device cache shards), emitting the
# multichip_audit record into a THROWAWAY ledger that the probe
# acceptance gate (scripts/probe_ledger_check.py) must then pass.
# The virtual-mesh dryrun used to be driver-only; this is its suite
# home. Compile-heavy (two audit executables, XLA:CPU): the host-keyed
# persistent compile cache makes repeats fast, the timeout covers cold.
echo "== mesh smoke (2-device virtual mesh: one audit, bit-identity)"
mesh_tmp=$(mktemp -d)
JAX_PLATFORMS=cpu GETHSHARDING_BENCH_MESH_DEVICES=2 \
GETHSHARDING_BENCH_MESH_ITERS=1 \
GETHSHARDING_PERFWATCH_LEDGER="$mesh_tmp/ledger.jsonl" \
GETHSHARDING_PERFWATCH_DIR="$mesh_tmp/blackbox" \
    timeout 1800 python bench.py --mesh > "$mesh_tmp/mesh.json" || {
    echo "mesh smoke FAILED: bench.py --mesh exited nonzero"
    tail -5 "$mesh_tmp/mesh.json" 2>/dev/null; fail=1; }
grep -q '"collectives_per_step": 1' "$mesh_tmp/mesh.json" || {
    echo "mesh smoke FAILED: no single-collective step in the output"
    fail=1; }
grep -q '"n_devices": 2' "$mesh_tmp/mesh.json" || {
    echo "mesh smoke FAILED: audit did not run on the 2-device mesh"
    fail=1; }
GETHSHARDING_PERFWATCH_LEDGER="$mesh_tmp/ledger.jsonl" JAX_PLATFORMS=cpu \
    python scripts/probe_ledger_check.py multichip_audit \
    --max-age 3600 || {
    echo "mesh smoke FAILED: no valid multichip_audit ledger record"
    fail=1; }
rm -rf "$mesh_tmp"

# -- DAS smoke: erasure-extend a body, publish, sampled-vote end-to-end
# on hermetic CPU — batched das_verify_samples must agree with the
# scalar reference bit-for-bit, the sampled notary must vote with ZERO
# body fetches inside the k-sample byte budget, and the das counters
# must reach the Prometheus exposition
echo "== DAS smoke"
JAX_PLATFORMS=cpu GETHSHARDING_BENCH_DAS_BODY=65536 \
GETHSHARDING_BENCH_DAS_PERIODS=2 GETHSHARDING_BENCH_DAS_ROWS=32 \
    python bench.py --das >/tmp/_das_smoke.json || fail=1
grep -q '"votes": 2' /tmp/_das_smoke.json || {
    echo "DAS smoke FAILED: sampled notary did not vote every period"
    cat /tmp/_das_smoke.json; fail=1; }
JAX_PLATFORMS=cpu python - <<'PYEOF' || fail=1
from gethsharding_tpu import metrics
from gethsharding_tpu.metrics import prometheus_text

metrics.counter("das/samples_verified").inc(3)
metrics.counter("das/sample_failures").inc(0)
text = prometheus_text()
for needle in ("gethsharding_das_samples_verified_total",
               "gethsharding_das_sample_failures_total"):
    assert needle in text, needle
print("DAS prometheus exposition OK")
PYEOF

# -- das-poly smoke: polynomial-multiproof DAS end-to-end on hermetic
# CPU — a sampled notary under --da-proofs=poly must vote with ZERO
# body fetches, every sampled set arriving under ONE constant-size
# multiproof; then a corrupt-multiproof chaos run must trip the
# breaker through the soundness spot-checker while the verdict stays
# correct on the scalar fallback
echo "== das-poly smoke"
JAX_PLATFORMS=cpu python - <<'PYEOF' || fail=1
import random

from gethsharding_tpu.actors.notary import Notary
from gethsharding_tpu.actors.proposer import create_collation
from gethsharding_tpu.core.shard import Shard
from gethsharding_tpu.core.types import Transaction
from gethsharding_tpu.das.service import DASService
from gethsharding_tpu.db.kv import MemoryKV
from gethsharding_tpu.mainchain.client import SMCClient
from gethsharding_tpu.p2p.messages import CollationBodyRequest
from gethsharding_tpu.p2p.service import Hub, P2PServer
from gethsharding_tpu.params import Config, ETHER
from gethsharding_tpu.sigbackend import get_backend
from gethsharding_tpu.smc.chain import SimulatedMainchain

config = Config(quorum_size=1, period_length=4)
chain = SimulatedMainchain(config=config)
prop_client = SMCClient(backend=chain, config=config)
not_client = SMCClient(backend=chain, config=config)
chain.fund(prop_client.account(), 2000 * ETHER)
chain.fund(not_client.account(), 2000 * ETHER)
hub = Hub()
watch = P2PServer(hub)
watch.start()
body_watch = watch.subscribe(CollationBodyRequest)
svc_prop = DASService(client=prop_client, p2p=P2PServer(hub), samples=4,
                      proof_mode="poly", fetch_timeout=4.0)
svc_not = DASService(client=not_client, p2p=P2PServer(hub), samples=4,
                     proof_mode="poly", fetch_timeout=4.0)
svc_prop.start()
svc_not.start()
notary = Notary(client=not_client, shard=Shard(0, MemoryKV()),
                p2p=svc_not.p2p, config=config, deposit_flag=True,
                all_shards=False, sig_backend=get_backend("python"),
                das=svc_not, da_mode="sampled")
notary.start()
chain.fast_forward(1)
rng = random.Random(5)
periods = 2
try:
    for _ in range(periods):
        period = chain.current_period()
        collation = create_collation(
            prop_client, 0, period,
            [Transaction(nonce=period,
                         payload=bytes(rng.randrange(256)
                                       for _ in range(20000)))])
        svc_prop.publish(0, period, collation.header.chunk_root,
                         collation.body)
        prop_client.add_header(0, period, collation.header.chunk_root,
                               collation.header.proposer_signature)
        chain.commit()
        notary.notarize_collations(head=chain.block_number)
        while chain.current_period() == period:
            chain.commit()
    assert notary.votes_submitted == periods, notary.errors
    assert body_watch.try_get() is None, \
        "a CollationBodyRequest left the poly-sampled notary"
    assert svc_not.m_multiproofs_fetched.value >= periods
finally:
    notary.stop()
    svc_prop.stop()
    svc_not.stop()
    watch.stop()
print("das-poly e2e OK:", periods, "poly-sampled votes, zero body fetches")
PYEOF
JAX_PLATFORMS=cpu python - <<'PYEOF' || fail=1
import random

from gethsharding_tpu.das import pcs
from gethsharding_tpu.metrics import DEFAULT_REGISTRY
from gethsharding_tpu.resilience.breaker import (OPEN, CircuitBreaker,
                                                 FailoverSigBackend)
from gethsharding_tpu.resilience.chaos import ChaosSigBackend, parse_spec
from gethsharding_tpu.resilience.soundness import SpotCheckSigBackend
from gethsharding_tpu.sigbackend import PythonSigBackend

rng = random.Random(9)
values = [rng.randrange(pcs.N) for _ in range(8)]
proof, evals = pcs.open_multi(values, (1, 5))
cols = ([pcs.g1_to_bytes(pcs.commit(values))], [[1, 5]], [evals],
        [pcs.g1_to_bytes(proof)], [8])
schedule = parse_spec("seed=7,backend.das_verify_multiproofs:mode=corrupt")
breaker = CircuitBreaker(name="das-poly", fault_threshold=1, reset_s=60.0)
backend = FailoverSigBackend(
    SpotCheckSigBackend(ChaosSigBackend(PythonSigBackend(), schedule),
                        rate=1.0, rows=1),
    PythonSigBackend(), breaker=breaker)
got = backend.das_verify_multiproofs(*[list(c) for c in cols])
assert got == [True], got  # detected -> served correct from the fallback
assert breaker.state == OPEN, breaker.state_name
assert DEFAULT_REGISTRY.counter(
    "resilience/soundness/das_verify_multiproofs/mismatches").value >= 1
print("das-poly chaos OK: corrupt multiproof verdict tripped the"
      " breaker, verdict stayed correct")
PYEOF

# -- chaos/failover smoke: a devnet-style notary rides a seeded failure
# schedule end-to-end — injected device faults mid-audit must trip the
# breaker, every period's votes must land on the scalar fallback, the
# breaker must re-close through a matching differential probe, and the
# breaker counters must appear in the Prometheus exposition
echo "== chaos failover smoke"
JAX_PLATFORMS=cpu python - <<'PYEOF' || fail=1
import time

from gethsharding_tpu.actors.notary import Notary
from gethsharding_tpu.actors.proposer import create_collation
from gethsharding_tpu.core.shard import Shard
from gethsharding_tpu.core.types import Transaction
from gethsharding_tpu.db.kv import MemoryKV
from gethsharding_tpu.mainchain.client import SMCClient
from gethsharding_tpu.metrics import prometheus_text
from gethsharding_tpu.params import Config, ETHER
from gethsharding_tpu.resilience.breaker import (
    CLOSED, CircuitBreaker, FailoverSigBackend)
from gethsharding_tpu.resilience.chaos import (ChaosSchedule,
                                               ChaosSigBackend, parse_spec)
from gethsharding_tpu.sigbackend import PythonSigBackend
from gethsharding_tpu.smc.chain import SimulatedMainchain

config = Config(quorum_size=1, period_length=4)
backend = SimulatedMainchain(config=config)
client = SMCClient(backend=backend, config=config)
backend.fund(client.account(), 2000 * ETHER)
schedule = parse_spec("seed=7,backend.bls_verify_committees=2")
breaker = CircuitBreaker(name="sigbackend", fault_threshold=1,
                         reset_s=0.005)
failover = FailoverSigBackend(
    ChaosSigBackend(PythonSigBackend(), schedule),
    PythonSigBackend(), breaker=breaker)
notary = Notary(client=client, shard=Shard(0, MemoryKV()), config=config,
                deposit_flag=True, all_shards=False, sig_backend=failover)
notary.start()
backend.fast_forward(1)
periods = []
for _ in range(5):
    period = backend.current_period()
    collation = create_collation(
        client, 0, period, [Transaction(nonce=period, payload=b"c")])
    notary.shard.save_collation(collation)
    client.add_header(0, period, collation.header.chunk_root,
                      collation.header.proposer_signature)
    while backend.current_period() == period:
        backend.commit()
    periods.append(period)
    time.sleep(0.01)
notary.stop()
assert notary.votes_submitted == len(periods), notary.errors
assert backend.last_approved_collation(0) == periods[-1]  # on fallback
assert schedule.injected.get("backend.bls_verify_committees") == 2
assert breaker.state == CLOSED, breaker.state_name  # probed + re-closed
prom = prometheus_text()
for needle in ("gethsharding_resilience_breaker_sigbackend_trips_total",
               "gethsharding_resilience_breaker_sigbackend_closes_total",
               "gethsharding_resilience_breaker_sigbackend_state"):
    assert needle in prom, needle
print("chaos failover smoke OK: periods", periods,
      "injected", schedule.injected)
PYEOF

# -- soundness smoke: silent corruption (chaos mode=corrupt — wrong
# answers, NO exception from the device path) must trip the breaker
# through the spot-checker, every answer must still come back correct
# from the scalar fallback, and the soundness counters must reach the
# Prometheus exposition
echo "== soundness smoke"
JAX_PLATFORMS=cpu python - <<'PYEOF' || fail=1
from gethsharding_tpu.metrics import DEFAULT_REGISTRY, prometheus_text
from gethsharding_tpu.resilience.breaker import (
    OPEN, CircuitBreaker, FailoverSigBackend)
from gethsharding_tpu.resilience.chaos import ChaosSigBackend, parse_spec
from gethsharding_tpu.resilience.soundness import SpotCheckSigBackend
from gethsharding_tpu.sigbackend import PythonSigBackend

schedule = parse_spec("seed=7,backend.ecrecover_addresses:mode=corrupt")
breaker = CircuitBreaker(name="soundness", fault_threshold=1,
                         reset_s=60.0)
backend = FailoverSigBackend(
    SpotCheckSigBackend(ChaosSigBackend(PythonSigBackend(), schedule),
                        rate=1.0),
    PythonSigBackend(), breaker=breaker)
digests, sigs = [b"\x11" * 32] * 4, [b"\x22" * 65] * 4
want = PythonSigBackend().ecrecover_addresses(digests, sigs)
got = backend.ecrecover_addresses(digests, sigs)
assert got == want, got  # detected -> served correct from the fallback
assert breaker.state == OPEN, breaker.state_name  # tripped on SILENT
assert DEFAULT_REGISTRY.counter(
    "resilience/soundness/ecrecover_addresses/mismatches").value >= 1
assert schedule.injected.get("backend.ecrecover_addresses") == 1
# ... and the counters reach the scrape surface
prom = prometheus_text()
for needle in ("gethsharding_resilience_soundness_ecrecover_addresses_"
               "checks_total",
               "gethsharding_resilience_soundness_ecrecover_addresses_"
               "mismatches_total",
               "gethsharding_resilience_breaker_soundness_trips_total"):
    assert needle in prom, needle
print("soundness smoke OK: silent corruption tripped the breaker,"
      " answers stayed correct")
PYEOF

# -- fleet router smoke: two breaker-guarded serving replicas behind the
# shard-aware router — seeded chaos trips r0's breaker, every answer
# stays correct, the router drains r0 and its refresh-side probe
# re-promotes it through the half-open differential, and the fleet
# counters reach the Prometheus exposition
echo "== fleet router smoke"
JAX_PLATFORMS=cpu python - <<'PYEOF' || fail=1
import time

from gethsharding_tpu.crypto import secp256k1 as ecdsa
from gethsharding_tpu.crypto.keccak import keccak256
from gethsharding_tpu.fleet import FleetRouter, Replica, RouterSigBackend
from gethsharding_tpu.metrics import prometheus_text
from gethsharding_tpu.resilience.breaker import (CircuitBreaker,
                                                 FailoverSigBackend)
from gethsharding_tpu.resilience.chaos import ChaosSchedule, ChaosSigBackend
from gethsharding_tpu.serving import ServingConfig, ServingSigBackend
from gethsharding_tpu.sigbackend import PythonSigBackend

schedule = ChaosSchedule(seed=7, rules={"backend.ecrecover_addresses": 3})
servings = [
    ServingSigBackend(ChaosSigBackend(PythonSigBackend(), schedule),
                      ServingConfig(flush_us=200)),
    ServingSigBackend(PythonSigBackend(), ServingConfig(flush_us=200)),
]
breaker0 = CircuitBreaker(name="smoke-r0", fault_threshold=3, reset_s=0.2)
router = FleetRouter([
    Replica("r0", FailoverSigBackend(servings[0], PythonSigBackend(),
                                     breaker=breaker0)),
    Replica("r1", FailoverSigBackend(servings[1], PythonSigBackend(),
                                     breaker=CircuitBreaker(
                                         name="smoke-r1"))),
], health_interval_s=0.0)
back = RouterSigBackend(router)
cases = []
for i in range(6):
    priv = int.from_bytes(keccak256(b"smoke-%d" % i), "big") % ecdsa.N
    digest = keccak256(b"smoke-msg-%d" % i)
    cases.append((digest, ecdsa.sign(digest, priv).to_bytes65(),
                  ecdsa.priv_to_address(priv)))
for digest, sig, want in cases[:4]:
    assert back.ecrecover_addresses([digest], [sig]) == [want]
router.refresh(force=True)
r0 = router.replicas[0]
assert r0.state == "draining", r0.state  # breaker tripped -> drained
assert schedule.injected.get("backend.ecrecover_addresses") == 3
time.sleep(0.25)
deadline = time.monotonic() + 5
while r0.state != "healthy" and time.monotonic() < deadline:
    router.refresh(force=True)
    time.sleep(0.02)
assert r0.state == "healthy", r0.state  # probe re-promoted -> re-entered
assert r0.reentries == 1
for digest, sig, want in cases[4:]:
    assert back.ecrecover_addresses([digest], [sig]) == [want]
prom = prometheus_text()
for needle in ("gethsharding_fleet_replica_r0_state",
               "gethsharding_fleet_replica_r0_routed_total",
               "gethsharding_fleet_router_calls_total",
               "gethsharding_resilience_retry_fleet_route_retries_total"):
    assert needle in prom, needle
for serving in servings:
    serving.close()
print("fleet router smoke OK: drain ->", r0.drain_events,
      "reentry ->", r0.reentries)
PYEOF

# -- fleet observability smoke: a chain_server replica + a router-side
# client in separate processes — the router's trace ships over the RPC
# trace envelope, both sides export Chrome traces, trace_merge.py folds
# them into ONE file where the stitched request's spans share a trace
# id across pid lanes; the router side's Prometheus payload carries the
# slo/<class> burn gauges and the fleet/replica federation rollups
echo "== fleet observability smoke"
obsfleet_dir=$(mktemp -d)
JAX_PLATFORMS=cpu python -m gethsharding_tpu.rpc.chain_server \
    --sigbackend python --trace \
    --trace-out "$obsfleet_dir/replica.json" --runtime 60 \
    --verbosity error > "$obsfleet_dir/server.json" &
obsfleet_pid=$!
for _ in $(seq 1 100); do
    [ -s "$obsfleet_dir/server.json" ] && break
    sleep 0.2
done
JAX_PLATFORMS=cpu OBSFLEET_DIR="$obsfleet_dir" python - <<'PYEOF' || fail=1
import json, os

from gethsharding_tpu import tracing
from gethsharding_tpu.crypto import secp256k1 as ecdsa
from gethsharding_tpu.crypto.keccak import keccak256
from gethsharding_tpu.fleet import FleetRouter, Replica, RouterSigBackend
from gethsharding_tpu.fleet.router import RpcReplicaBackend
from gethsharding_tpu.metrics import prometheus_text

out = os.environ["OBSFLEET_DIR"]
addr = json.load(open(os.path.join(out, "server.json")))
tracing.enable(ring_spans=16384)
backend = RpcReplicaBackend.dial(addr["host"], addr["port"])
router = FleetRouter([Replica("r0", backend, health=backend.health,
                              probe=None)], health_interval_s=0.0)
back = RouterSigBackend(router)
for i in range(4):
    priv = int.from_bytes(keccak256(b"obsf-%d" % i), "big") % ecdsa.N
    digest = keccak256(b"obsf-msg-%d" % i)
    got = back.ecrecover_addresses([digest],
                                   [ecdsa.sign(digest, priv).to_bytes65()])
    assert got == [ecdsa.priv_to_address(priv)], "wrong answer via router"
router.refresh(force=True)  # health + shard_metrics federation scrape
prom = prometheus_text()
for needle in ("gethsharding_slo_interactive_burn_rate",
               "gethsharding_fleet_replica_r0_serving_ecrecover_"
               "requests_count",
               "gethsharding_fleet_total_inflight"):
    assert needle in prom, needle
tracing.write_chrome_trace(os.path.join(out, "router.json"),
                           label="router")
backend.close()
print("fleet observability client OK")
PYEOF
kill -INT "$obsfleet_pid" 2>/dev/null
wait "$obsfleet_pid" 2>/dev/null
if [ -s "$obsfleet_dir/replica.json" ] && [ -s "$obsfleet_dir/router.json" ]
then
    JAX_PLATFORMS=cpu python scripts/trace_merge.py \
        "$obsfleet_dir/router.json" "$obsfleet_dir/replica.json" \
        -o "$obsfleet_dir/merged.json" >/dev/null || fail=1
    JAX_PLATFORMS=cpu OBSFLEET_DIR="$obsfleet_dir" python - <<'PYEOF' || fail=1
import json, os
from collections import defaultdict

merged = json.load(open(os.path.join(os.environ["OBSFLEET_DIR"],
                                     "merged.json")))
events = [e for e in merged["traceEvents"] if e.get("ph") == "X"]
by_trace = defaultdict(lambda: defaultdict(set))
for e in events:
    by_trace[e["args"].get("trace_id")][e["pid"]].add(e["name"])
stitched = [t for t, pids in by_trace.items() if len(pids) >= 2]
assert stitched, "no trace id spans both processes in the merged export"
names = set()
for t in stitched:
    for pid_names in by_trace[t].values():
        names |= pid_names
assert "fleet/route" in names and "rpc/shard_ecrecover" in names, names
print("fleet observability smoke OK:", len(stitched),
      "stitched trace(s) across", len({e['pid'] for e in events}),
      "process lanes")
PYEOF
else
    echo "fleet observability smoke FAILED: missing trace exports"
    fail=1
fi
rm -rf "$obsfleet_dir"

# -- fleet frontend smoke: the REAL process topology — 2 chain_server
# replicas + 1 standalone fleet.frontend balancing them. Verdicts
# through the frontend must be bit-identical to the scalar backend
# (ecrecover AND the committee plane over the new shard_verifyCommittees
# wire), then replica r0 is KILLED mid-traffic (answers must stay
# correct via the survivor), restarted on the SAME endpoint, and must
# re-enter the rotation through the frontend's health sweep.
echo "== fleet frontend smoke (kill + restart a replica under traffic)"
ff_dir=$(mktemp -d)
ff_pa=$(python -c "import socket; s = socket.socket(); \
s.bind(('127.0.0.1', 0)); print(s.getsockname()[1]); s.close()")
ff_pb=$(python -c "import socket; s = socket.socket(); \
s.bind(('127.0.0.1', 0)); print(s.getsockname()[1]); s.close()")
JAX_PLATFORMS=cpu python -m gethsharding_tpu.rpc.chain_server \
    --sigbackend python --port "$ff_pa" --runtime 120 \
    --verbosity error > "$ff_dir/ra.json" &
ff_pid_a=$!
JAX_PLATFORMS=cpu python -m gethsharding_tpu.rpc.chain_server \
    --sigbackend python --port "$ff_pb" --runtime 120 \
    --verbosity error > "$ff_dir/rb.json" &
ff_pid_b=$!
for _ in $(seq 1 100); do
    [ -s "$ff_dir/ra.json" ] && [ -s "$ff_dir/rb.json" ] && break
    sleep 0.2
done
GETHSHARDING_PERFWATCH_DIR="$ff_dir/blackbox" JAX_PLATFORMS=cpu \
python -m gethsharding_tpu.fleet.frontend \
    --replica "127.0.0.1:$ff_pa" --replica "127.0.0.1:$ff_pb" \
    --fleet-hedge-ms 25 --health-interval 0.1 --runtime 120 \
    --verbosity error > "$ff_dir/fe.json" &
ff_pid_fe=$!
for _ in $(seq 1 100); do
    [ -s "$ff_dir/fe.json" ] && break
    sleep 0.2
done
# phase 1: verdict bit-identity through the frontend (ecrecover + the
# committee plane), against the scalar reference
JAX_PLATFORMS=cpu FF_DIR="$ff_dir" python - <<'PYEOF' || fail=1
import json, os

from gethsharding_tpu.crypto import bn256 as bls
from gethsharding_tpu.crypto import secp256k1 as ecdsa
from gethsharding_tpu.crypto.keccak import keccak256
from gethsharding_tpu.rpc import codec
from gethsharding_tpu.rpc.client import RPCClient
from gethsharding_tpu.sigbackend import PythonSigBackend

addr = json.load(open(os.path.join(os.environ["FF_DIR"], "fe.json")))
rpc = RPCClient(addr["host"], addr["port"])
py = PythonSigBackend()
for i in range(8):
    priv = int.from_bytes(keccak256(b"ffs-%d" % i), "big") % ecdsa.N
    digest = keccak256(b"ffs-msg-%d" % i)
    sig = ecdsa.sign(digest, priv).to_bytes65()
    got = rpc.call("shard_ecrecover", [codec.enc_bytes(digest)],
                   [codec.enc_bytes(sig)])
    want = py.ecrecover_addresses([digest], [sig])
    assert got == [codec.enc_bytes(bytes(want[0]))], (i, got)
msgs, sig_rows, pk_rows, keys = [], [], [], []
for i in range(3):
    tag = b"ffc-%d" % i
    ks = [bls.bls_keygen(tag + bytes([j])) for j in range(2)]
    sigs = [bls.bls_sign(tag, sk) for sk, _ in ks]
    if i == 1:
        sigs[0] = bls.bls_sign(b"tampered", ks[0][0])
    msgs.append(tag); sig_rows.append(sigs)
    pk_rows.append([pk for _, pk in ks]); keys.append(("ff", i))
want = py.bls_verify_committees(msgs, sig_rows, pk_rows)
got = rpc.call("shard_verifyCommittees",
               [codec.enc_bytes(m) for m in msgs],
               codec.enc_g1_rows(sig_rows), codec.enc_g2_rows(pk_rows),
               codec.enc_pk_row_keys(keys))
assert got == want, (got, want)
rpc.close()
print("fleet frontend phase 1 OK: ecrecover + committee plane"
      " bit-identical to scalar")
PYEOF
# phase 2: kill replica A under traffic — every answer must keep coming
# (routed to the survivor), and the frontend must mark r0 unhealthy
kill -9 "$ff_pid_a" 2>/dev/null
JAX_PLATFORMS=cpu FF_DIR="$ff_dir" python - <<'PYEOF' || fail=1
import json, os, time

from gethsharding_tpu.crypto import secp256k1 as ecdsa
from gethsharding_tpu.crypto.keccak import keccak256
from gethsharding_tpu.rpc import codec
from gethsharding_tpu.rpc.client import RPCClient

addr = json.load(open(os.path.join(os.environ["FF_DIR"], "fe.json")))
rpc = RPCClient(addr["host"], addr["port"])
for i in range(12):
    priv = int.from_bytes(keccak256(b"ffk-%d" % i), "big") % ecdsa.N
    digest = keccak256(b"ffk-msg-%d" % i)
    sig = ecdsa.sign(digest, priv).to_bytes65()
    got = rpc.call("shard_ecrecover", [codec.enc_bytes(digest)],
                   [codec.enc_bytes(sig)])
    assert got == [codec.enc_bytes(ecdsa.priv_to_address(priv))], (i, got)
    time.sleep(0.05)
deadline = time.monotonic() + 10
state = None
while time.monotonic() < deadline:
    state = rpc.call("shard_fleetStatus")["replicas"]["r0"]["state"]
    if state != "healthy":
        break
    time.sleep(0.1)
assert state != "healthy", f"frontend never noticed the kill: {state}"
rpc.close()
print("fleet frontend phase 2 OK: replica killed, answers stayed"
      " correct, r0 ->", state)
PYEOF
# phase 3: restart replica A on the SAME endpoint; the frontend's
# health sweep must re-enter it, and traffic must stay correct
JAX_PLATFORMS=cpu python -m gethsharding_tpu.rpc.chain_server \
    --sigbackend python --port "$ff_pa" --runtime 60 \
    --verbosity error > "$ff_dir/ra2.json" &
ff_pid_a2=$!
JAX_PLATFORMS=cpu FF_DIR="$ff_dir" python - <<'PYEOF' || fail=1
import json, os, time

from gethsharding_tpu.crypto import secp256k1 as ecdsa
from gethsharding_tpu.crypto.keccak import keccak256
from gethsharding_tpu.rpc import codec
from gethsharding_tpu.rpc.client import RPCClient

addr = json.load(open(os.path.join(os.environ["FF_DIR"], "fe.json")))
rpc = RPCClient(addr["host"], addr["port"])
deadline = time.monotonic() + 20
status = None
while time.monotonic() < deadline:
    status = rpc.call("shard_fleetStatus")["replicas"]["r0"]
    if status["state"] == "healthy":
        break
    time.sleep(0.2)
assert status and status["state"] == "healthy", \
    f"killed replica never re-entered after restart: {status}"
assert status["reentries"] >= 1, status
for i in range(6):
    priv = int.from_bytes(keccak256(b"ffr-%d" % i), "big") % ecdsa.N
    digest = keccak256(b"ffr-msg-%d" % i)
    sig = ecdsa.sign(digest, priv).to_bytes65()
    got = rpc.call("shard_ecrecover", [codec.enc_bytes(digest)],
                   [codec.enc_bytes(sig)])
    assert got == [codec.enc_bytes(ecdsa.priv_to_address(priv))], (i, got)
rpc.close()
print("fleet frontend smoke OK: killed replica re-entered after",
      status["reentries"], "re-entries; verdicts stayed bit-identical")
PYEOF
kill "$ff_pid_fe" "$ff_pid_b" "$ff_pid_a2" 2>/dev/null
wait "$ff_pid_fe" "$ff_pid_b" "$ff_pid_a2" 2>/dev/null
rm -rf "$ff_dir"

# -- fleettrace smoke: cross-process trace assembly on the REAL process
# topology — 2 chain_server replicas ship spans to a fleet frontend
# collector over shard_traceExport, this client exports its own spans
# the same way, and ONE interactive shard_verifyAggregates must come
# back as ONE assembled trace whose spans carry >= 3 distinct pids
# (client + frontend + replica), with the interactive class present in
# the critical-path attribution tables
echo "== fleettrace smoke (one request -> one trace across 3 processes)"
ft_dir=$(mktemp -d)
ft_fe=$(python -c "import socket; s = socket.socket(); \
s.bind(('127.0.0.1', 0)); print(s.getsockname()[1]); s.close()")
# replicas first: their export sink absorbs + retries until the
# frontend (their collector) binds the reserved port
JAX_PLATFORMS=cpu GETHSHARDING_FLEETTRACE_INTERVAL_MS=50 \
python -m gethsharding_tpu.rpc.chain_server \
    --sigbackend python --fleettrace-export "127.0.0.1:$ft_fe" \
    --runtime 120 --verbosity error > "$ft_dir/ra.json" &
ft_pid_a=$!
JAX_PLATFORMS=cpu GETHSHARDING_FLEETTRACE_INTERVAL_MS=50 \
python -m gethsharding_tpu.rpc.chain_server \
    --sigbackend python --fleettrace-export "127.0.0.1:$ft_fe" \
    --runtime 120 --verbosity error > "$ft_dir/rb.json" &
ft_pid_b=$!
for _ in $(seq 1 100); do
    [ -s "$ft_dir/ra.json" ] && [ -s "$ft_dir/rb.json" ] && break
    sleep 0.2
done
ft_ra=$(python -c "import json; a = json.load(open('$ft_dir/ra.json')); \
print('%s:%s' % (a['host'], a['port']))")
ft_rb=$(python -c "import json; a = json.load(open('$ft_dir/rb.json')); \
print('%s:%s' % (a['host'], a['port']))")
JAX_PLATFORMS=cpu GETHSHARDING_FLEETTRACE_INTERVAL_MS=50 \
GETHSHARDING_FLEETTRACE_SAMPLE=1.0 GETHSHARDING_FLEETTRACE_LINGER_S=0.4 \
python -m gethsharding_tpu.fleet.frontend \
    --port "$ft_fe" --fleettrace --replica "$ft_ra" --replica "$ft_rb" \
    --runtime 120 --verbosity error > "$ft_dir/fe.json" &
ft_pid_fe=$!
for _ in $(seq 1 100); do
    [ -s "$ft_dir/fe.json" ] && break
    sleep 0.2
done
JAX_PLATFORMS=cpu GETHSHARDING_FLEETTRACE_INTERVAL_MS=50 \
FT_DIR="$ft_dir" python - <<'PYEOF' || fail=1
import json, os, time

from gethsharding_tpu import fleettrace, tracing
from gethsharding_tpu.crypto import bn256 as bls
from gethsharding_tpu.rpc import codec
from gethsharding_tpu.rpc.client import RPCClient

addr = json.load(open(os.path.join(os.environ["FT_DIR"], "fe.json")))
fleettrace.boot_exporter("%s:%s" % (addr["host"], addr["port"]),
                         label="smoke-client")
client = RPCClient(addr["host"], addr["port"], timeout=30.0)
header = b"fleettrace-smoke"
keys = [bls.bls_keygen(bytes([i + 1])) for i in range(2)]
agg_sig = bls.bls_aggregate_sigs(
    [bls.bls_sign(header, sk) for sk, _ in keys])
agg_pk = bls.bls_aggregate_pks([pk for _, pk in keys])
call_args = ([codec.enc_bytes(header)], [codec.enc_g1(agg_sig)],
             [codec.enc_g2(agg_pk)], "interactive")
assert client.call("shard_verifyAggregates", *call_args) == [True]
with tracing.span("smoke/fleettrace_request") as probe:
    assert client.call("shard_verifyAggregates", *call_args) == [True]
trace_id = probe.trace_id
fleettrace.EXPORTER.flush()
exemplar = None
deadline = time.monotonic() + 30.0
while time.monotonic() < deadline and exemplar is None:
    for ex in client.call("shard_traceExemplars", 32):
        if ex["trace_id"] == trace_id:
            exemplar = ex
            break
    if exemplar is None:
        time.sleep(0.2)
assert exemplar is not None, \
    "the measured request never assembled into a retained trace"
pids = {span.get("pid") for span in exemplar["spans"]} - {None}
assert len(pids) >= 3, (
    "assembled trace spans %d processes, want >= 3 "
    "(client + frontend + replica): %s" % (len(pids), sorted(pids)))
attr = client.call("shard_traceAttribution")
assert attr["classes"].get("interactive"), attr["classes"]
client.close()
fleettrace.shutdown()
print("fleettrace smoke OK: one trace,", len(exemplar["spans"]),
      "spans across", len(pids), "processes")
PYEOF
kill "$ft_pid_fe" "$ft_pid_a" "$ft_pid_b" 2>/dev/null
wait "$ft_pid_fe" "$ft_pid_a" "$ft_pid_b" 2>/dev/null
rm -rf "$ft_dir"

# -- perfwatch smoke: the CPU-quick micro suite + the noise-aware
# regression gate, closed loop — seed a FRESH ledger with clean runs,
# the gate must pass; inject a labeled 1.5x slowdown into one
# registered microbench, the gate must trip (exit 1); a clean rerun
# must pass again (the outlier cannot poison the rolling median)
echo "== perfwatch smoke (micro suite + regression gate)"
pw_tmp=$(mktemp -d)
pw_led="$pw_tmp/ledger.jsonl"
pw_ok=1
for _ in 1 2 3 4; do
    JAX_PLATFORMS=cpu GETHSHARDING_PERFWATCH_LEDGER="$pw_led" \
        python -m gethsharding_tpu.perfwatch --run --check \
        >/dev/null 2>&1 || pw_ok=0
done
if [ "$pw_ok" != 1 ]; then
    # one settle retry: a cold/loaded host can scatter the first runs
    # past the band; a REAL regression persists into the next clean run
    if JAX_PLATFORMS=cpu GETHSHARDING_PERFWATCH_LEDGER="$pw_led" \
        python -m gethsharding_tpu.perfwatch --run --check >/dev/null 2>&1
    then
        pw_ok=1
    fi
fi
if [ "$pw_ok" != 1 ]; then
    echo "perfwatch smoke FAILED: clean micro-suite runs tripped the gate"
    fail=1
fi
if JAX_PLATFORMS=cpu GETHSHARDING_PERFWATCH_LEDGER="$pw_led" \
    GETHSHARDING_PERFWATCH_INJECT="clock_spin_5ms:1.5" \
    python -m gethsharding_tpu.perfwatch --run --check >/dev/null 2>&1
then
    echo "perfwatch smoke FAILED: injected 1.5x slowdown did NOT trip" \
         "the regression gate"
    fail=1
fi
# the heal step gets the SAME settle allowance as the clean loop: the
# full-suite check includes the real workload benches, whose ~20% host
# drift can organically brush the band — a REAL regression persists
# into a second clean run, a load blip does not
if ! JAX_PLATFORMS=cpu GETHSHARDING_PERFWATCH_LEDGER="$pw_led" \
    python -m gethsharding_tpu.perfwatch --run --check >/dev/null 2>&1
then
    if ! JAX_PLATFORMS=cpu GETHSHARDING_PERFWATCH_LEDGER="$pw_led" \
        python -m gethsharding_tpu.perfwatch --run --check >/dev/null 2>&1
    then
        echo "perfwatch smoke FAILED: clean rerun after the injected" \
             "record still trips the gate"
        fail=1
    fi
fi
rm -rf "$pw_tmp"
[ "$fail" = 0 ] && echo "perfwatch smoke OK: gate passes clean, trips on" \
    "the injected slowdown, heals on the clean rerun"

# -- devscope smoke: the device introspection plane end to end — an RPC
# server whose shard_profileStart/Stop toggles a sampling session (the
# collapsed-stack download must be non-empty), and a StatusServer node
# whose /profile control route, /profile/stacks download, /status
# devscope section and devscope/* Prometheus rows all answer
echo "== devscope smoke (profile toggle over RPC + devscope surfaces)"
ds_tmp=$(mktemp -d)
JAX_PLATFORMS=cpu GETHSHARDING_DEVSCOPE_PROFILE_DIR="$ds_tmp/profile" \
GETHSHARDING_PERFWATCH_DIR="$ds_tmp/blackbox" \
GETHSHARDING_PERFWATCH_LEDGER="$ds_tmp/ledger.jsonl" \
python - <<'PY' || fail=1
import json
import time
import urllib.request

# 1. the RPC face: toggle a sampler session on a chain-style RPCServer
from gethsharding_tpu.params import Config
from gethsharding_tpu.rpc.client import RPCClient
from gethsharding_tpu.rpc.server import RPCServer
from gethsharding_tpu.smc.chain import SimulatedMainchain

server = RPCServer(SimulatedMainchain(config=Config()))
server.start()
client = RPCClient(*server.address)
started = client.call("shard_profileStart", "sampler", 400)
assert started.get("started"), started
again = client.call("shard_profileStart", "sampler", 400)
assert again.get("already_running"), again
deadline = time.monotonic() + 5.0
while time.monotonic() < deadline:  # sample the RPC threads themselves
    client.call("shard_blockNumber")
    if client.call("shard_profileStacks"):
        break
stopped = client.call("shard_profileStop")
assert stopped.get("stopped"), stopped
stacks = client.call("shard_profileStacks")
assert stacks and "gethsharding" in stacks, (
    f"collapsed-stack download empty or foreign: {stacks[:120]!r}")
status = client.call("shard_devscopeStatus")
assert status["profiler"]["sessions"] >= 1, status
client.close()
server.stop()
print("devscope RPC toggle OK:", len(stacks.splitlines()), "stack lines")

# 2. the node face: /profile control + stacks download + prom rows
from gethsharding_tpu.node.backend import ShardNode
from gethsharding_tpu.node.http_status import StatusServer
from gethsharding_tpu import devscope

devscope.boot()
node = ShardNode(actor="observer", txpool_interval=None, http_port=0)
node.start()
try:
    port = node.service(StatusServer).port

    def get(path):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10) as resp:
            return resp.read().decode()

    out = json.loads(get("/profile?action=start&mode=sampler&hz=400"))
    assert out.get("started"), out
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        get("/status")  # keep threads busy so the sampler sees stacks
        if get("/profile/stacks"):
            break
    out = json.loads(get("/profile?action=stop"))
    assert out.get("stopped"), out
    stacks = get("/profile/stacks")
    assert stacks, "/profile/stacks empty after a sampled session"
    status = json.loads(get("/status"))
    assert "devscope" in status, sorted(status)
    assert status["devscope"]["memory"]["running"], status["devscope"]
    prom = get("/metrics?format=prom")
    for row in ("devscope_mem_polls", "devscope_profiler_sessions",
                "devscope_compile_count"):
        assert row in prom, f"{row} missing from the prom exposition"
finally:
    node.stop()
    devscope.shutdown()
print("devscope smoke OK: RPC + /profile toggles, stacks served,"
      " prom rows present")
PY
rm -rf "$ds_tmp"

# -- elastic fleet smoke: the runtime-membership control plane against
# real processes — 2 chain_server replicas behind 2 peered frontends,
# one frontend killed -9 under FrontendPool traffic (actors must fail
# over), a third replica added LIVE via shard_addReplica on the
# survivor's peer and gossiped across before the kill; asserts the
# survivor converged (epoch bumped, added endpoint healthy) with zero
# wrong answers throughout
echo "== elastic fleet smoke (2 frontends + 2 replicas, kill one + live add)"
JAX_PLATFORMS=cpu python - <<'PYEOF' || fail=1
import os, sys, threading, time
sys.path.insert(0, "scripts")
from serving_stress import _spawn, _free_port, build_cases

env = {**os.environ, "JAX_PLATFORMS": "cpu"}
procs = []
try:
    eps = []
    for _ in range(3):  # 2 registered at boot + 1 added live
        p, a = _spawn([sys.executable,
                       "-m", "gethsharding_tpu.rpc.chain_server",
                       "--sigbackend", "python", "--verbosity", "error"],
                      env=env)
        procs.append(p)
        eps.append("%s:%d" % (a["host"], a["port"]))
    pa, pb = _free_port(), _free_port()

    def fe(port, peer):
        return _spawn([sys.executable, "-m",
                       "gethsharding_tpu.fleet.frontend",
                       "--verbosity", "error", "--port", str(port),
                       "--health-interval", "0.1",
                       "--gossip-interval", "0.25",
                       "--peer", "127.0.0.1:%d" % peer,
                       "--replica", eps[0], "--replica", eps[1]],
                      env=env)

    fa_p, fa = fe(pa, pb)
    procs.append(fa_p)
    fb_p, fb = fe(pb, pa)
    procs.append(fb_p)

    from gethsharding_tpu.rpc.client import FrontendPool, RPCClient
    # primary on B so the kill is felt by the pool, not just a spare
    pool = FrontendPool(["%s:%d" % (fb["host"], fb["port"]),
                         "%s:%d" % (fa["host"], fa["port"])], timeout=10.0)
    cases = build_cases(32)
    stop = threading.Event()
    wrong, done = [], [0]

    def traffic():
        i = 0
        while not stop.is_set():
            d, s, w = cases[i % len(cases)]
            i += 1
            try:
                got = pool.ecrecover_addresses([d], [s])
            except Exception:
                continue  # typed refusal/failover window
            if got != [w]:
                wrong.append(got)
                return
            done[0] += 1
            time.sleep(0.005)

    t = threading.Thread(target=traffic, daemon=True)
    t.start()
    time.sleep(1.0)

    # live add through frontend B (the pool's primary), then assert the
    # epoch GOSSIPS to frontend A
    res = pool.call("shard_addReplica", eps[2])
    assert res["name"] == eps[2] and res["epoch"] >= 1, res
    ra = RPCClient(fa["host"], fa["port"])
    snap = {}
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        snap = ra.call("shard_membership")
        if eps[2] in snap.get("endpoints", []) and snap.get("epoch", 0) >= 1:
            break
        time.sleep(0.2)
    assert eps[2] in snap.get("endpoints", []), snap

    # kill frontend B -9 mid-traffic: actors must fail over to A
    before = done[0]
    fb_p.kill()
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline and not (
            pool.failovers >= 1 and done[0] > before):
        time.sleep(0.2)
    assert pool.failovers >= 1, "pool never failed over"
    assert done[0] > before, "no verified traffic after the kill"

    # convergence on the survivor: the live-added replica reaches
    # HEALTHY in A's sweep and answers are still correct
    state = {}
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        status = ra.call("shard_fleetStatus")
        state = {n: s["state"] for n, s in status["replicas"].items()}
        if state.get(eps[2]) == "healthy" and len(state) == 3:
            break
        time.sleep(0.2)
    assert state.get(eps[2]) == "healthy", state
    stop.set()
    t.join(timeout=10)
    assert not wrong, wrong
    ra.close()
    pool.close()
    print("elastic smoke OK: add gossiped, kill -9 failed over,"
          " survivor converged (%d verified)" % done[0])
finally:
    for p in procs:
        p.terminate()
PYEOF

# -- shardlint: the repo-wide static analysis gate (jit-purity,
# host-sync, lock-order, race-guard, layering, backend-contract,
# thread-lifecycle, flag-doc, export-completeness) — fails on any
# finding outside the committed baseline
# (gethsharding_tpu/analysis/baseline.json)
echo "== shardlint (static analysis gate)"
JAX_PLATFORMS=cpu python -m gethsharding_tpu.analysis || fail=1

# -- lockcheck + racecheck smoke: the concurrency-heavy suites run
# ONCE with BOTH runtime recorders patched in (one run on purpose:
# GETHSHARDING_RACECHECK requires the lock recorder anyway, so both
# session gates fire — re-running the suites under LOCKCHECK alone
# would duplicate ~26 s for no extra coverage). The lockcheck gate
# fails the run on any observed AB/BA inversion or an order that
# contradicts the static lock graph; the racecheck gate fails it on
# any runtime write lockset that CONTRADICTS the static race-guard
# model (a "guarded" attr written shared with no lock, an "init-only"
# attr written from two threads) and prints the honest coverage gaps —
# statically-flagged attrs this run never drove shared.
echo "== lockcheck+racecheck smoke (fleet/serving/concurrency under both recorders)"
GETHSHARDING_LOCKCHECK=1 GETHSHARDING_RACECHECK=1 JAX_PLATFORMS=cpu \
    python -m pytest \
    tests/test_concurrency.py tests/test_serving.py tests/test_fleet.py \
    tests/test_fleet_frontend.py tests/test_fleet_elastic.py \
    -q --no-header -m 'not slow' || fail=1

for f in tests/test_*.py; do
    echo "== $f"
    python -m pytest "$f" -q --no-header || fail=1
done
exit $fail
