#!/bin/bash
# Maximally isolated full-suite run: one short-lived pytest process per
# test file, each with the host-keyed persistent compile cache enabled.
#
# Since r3 a plain one-process `pytest tests/` is ALSO green (conftest
# bounds XLA:CPU's executable-count pressure with jax.clear_caches()
# per module — the root cause of the old segfault); this script remains
# as the fully isolated equivalent (one crash cannot take out the whole
# run). Coverage is identical; a failing file fails the script.
set -u
cd "$(dirname "$0")/.."
fail=0

# -- observability smoke: boot an observer node, scrape every surface ------
# A real `tpu-sharding sharding` process must answer /healthz, Prometheus
# /metrics?format=prom and /trace with 200 + non-empty payloads — the
# curl-level contract the dashboards/scrapers depend on, checked against
# a live process rather than an in-process test double.
obs_port=$(python -c "import socket; s = socket.socket(); \
s.bind(('127.0.0.1', 0)); print(s.getsockname()[1]); s.close()")
echo "== observability smoke (http://127.0.0.1:$obs_port)"
JAX_PLATFORMS=cpu python -m gethsharding_tpu.node.cli sharding \
    --actor observer --http "$obs_port" --trace --runtime 60 \
    --blocktime 0.2 --txinterval 1.0 --verbosity error &
obs_pid=$!
up=0
for _ in $(seq 1 100); do
    if curl -sf "http://127.0.0.1:$obs_port/healthz" >/dev/null 2>&1; then
        up=1; break
    fi
    sleep 0.2
done
if [ "$up" = 1 ]; then
    for ep in "/healthz" "/metrics?format=prom" "/trace"; do
        body=$(curl -sf "http://127.0.0.1:$obs_port$ep") || body=""
        if [ -z "$body" ]; then
            echo "observability smoke FAILED: $ep returned non-200 or empty"
            fail=1
        fi
    done
else
    echo "observability smoke FAILED: node never answered /healthz"
    fail=1
fi
kill "$obs_pid" 2>/dev/null
wait "$obs_pid" 2>/dev/null

for f in tests/test_*.py; do
    echo "== $f"
    python -m pytest "$f" -q --no-header || fail=1
done
exit $fail
