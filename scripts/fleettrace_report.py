#!/usr/bin/env python
"""Render a fleettrace critical-path report off a live collector.

Dials the process hosting the fleettrace collector (a fleet frontend
run with ``--fleettrace``, or a node booted the same way), pulls
``shard_traceAttribution`` + ``shard_traceExemplars`` over the normal
JSON-RPC framing, and prints the per-class critical-path table —
where end-to-end wall time actually went, segment by segment
(actor_queue, wire, frontend_route, queue_wait, batch_assembly,
device_dispatch, ...) — plus the retained tail exemplars (trace id,
why it was kept, processes spanned, slowest segments).

Usage::

    python scripts/fleettrace_report.py --port 8545 [--host H]
        [--exemplars N] [--json]

``--json`` dumps the raw RPC payloads for piping; the default output
is the human table. Exit code 1 when the target serves no collector
(``accepted: false`` shape / empty attribution with no traces seen).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from gethsharding_tpu.rpc.client import RPCClient  # noqa: E402


def render_attribution(attr: dict) -> str:
    """Format the per-class segment table (the testable core)."""
    lines = []
    traces = attr.get("traces", {})
    lines.append("traces: assembled=%s retained=%s sampled_out=%s "
                 "incomplete=%s" % (traces.get("assembled", 0),
                                    traces.get("retained", 0),
                                    traces.get("sampled_out", 0),
                                    traces.get("incomplete", 0)))
    classes = attr.get("classes", {})
    if not classes:
        lines.append("(no attributed traces yet)")
        return "\n".join(lines)
    for klass in sorted(classes):
        lines.append("")
        lines.append("class %s" % klass)
        lines.append("  %-18s %7s %10s %10s %10s"
                     % ("segment", "count", "mean_ms", "p50_ms",
                        "p99_ms"))
        segments = classes[klass]
        order = attr.get("segments") or sorted(segments)
        for seg in order:
            row = segments.get(seg)
            if not row or not row.get("count"):
                continue
            lines.append("  %-18s %7d %10.3f %10.3f %10.3f"
                         % (seg, row["count"], row["mean_ms"],
                            row["p50_ms"], row["p99_ms"]))
        extra = [seg for seg in segments if seg not in order]
        for seg in sorted(extra):
            row = segments[seg]
            lines.append("  %-18s %7d %10.3f %10.3f %10.3f"
                         % (seg, row["count"], row["mean_ms"],
                            row["p50_ms"], row["p99_ms"]))
    return "\n".join(lines)


def render_exemplars(exemplars: list) -> str:
    lines = []
    for ex in exemplars:
        attr = ex.get("attribution") or {}
        segs = attr.get("segments") or {}
        top = sorted(segs.items(), key=lambda kv: kv[1],
                     reverse=True)[:3]
        lines.append(
            "trace %x klass=%s total=%.3fms processes=%s reasons=%s%s"
            % (int(ex.get("trace_id", 0)),
               ex.get("klass", "?"),
               float(attr.get("total_s", 0.0)) * 1e3,
               attr.get("processes", "?"),
               ",".join(ex.get("reasons", [])),
               " INCOMPLETE" if ex.get("incomplete") else ""))
        for seg, sec in top:
            if sec > 0:
                lines.append("    %-18s %10.3f ms" % (seg, sec * 1e3))
    return "\n".join(lines) if lines else "(no retained exemplars)"


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="fleettrace-report", description=__doc__.splitlines()[0])
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, required=True,
                        help="RPC port of the collector-hosting process "
                             "(fleet frontend --fleettrace)")
    parser.add_argument("--exemplars", type=int, default=8,
                        help="retained tail exemplars to show")
    parser.add_argument("--json", action="store_true",
                        help="dump the raw RPC payloads instead of the "
                             "human table")
    args = parser.parse_args(argv)
    client = RPCClient(args.host, args.port, timeout=10.0)
    try:
        attr = client.call("shard_traceAttribution")
        exemplars = client.call("shard_traceExemplars",
                                args.exemplars)
    finally:
        client.close()
    if args.json:
        print(json.dumps({"attribution": attr,
                          "exemplars": exemplars}, indent=2))
    else:
        print(render_attribution(attr or {}))
        print()
        print("retained exemplars (newest first):")
        print(render_exemplars(exemplars or []))
    active = bool(attr) and (attr.get("classes")
                             or attr.get("traces", {}).get("assembled"))
    return 0 if active else 1


if __name__ == "__main__":
    sys.exit(main())
