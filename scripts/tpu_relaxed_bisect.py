"""Bisect the relaxed-normalize wrong-result on the live backend.

r4 finding: `GETHSHARDING_TPU_LIMB_FORM=wide GETHSHARDING_TPU_NORM=
relaxed` fails the audit correctness gate on TPU (every shard's
aggregate rejected) while the IDENTICAL knobs pass on CPU at the same
shape — a backend-specific numeric divergence, not a bound violation.
This probe runs the field stack bottom-up under the ambient knobs and
compares every stage against host scalar bigint goldens, printing the
FIRST diverging stage: the r5 fix (or the formal parking justification)
starts from that op instead of the whole dispatch.

Run under the relaxed env:
  GETHSHARDING_TPU_LIMB_FORM=wide GETHSHARDING_TPU_NORM=relaxed \
    python scripts/tpu_relaxed_bisect.py
Prints ONE JSON line {platform, stages: {name: ok}, first_bad}.
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    from gethsharding_tpu.parallel.virtual import configure_compile_cache

    configure_compile_cache()

    import jax
    import jax.numpy as jnp

    from gethsharding_tpu.crypto import bn256 as ref
    from gethsharding_tpu.ops import bn256_jax as k
    from gethsharding_tpu.ops.limb import NLIMBS, ints_to_limbs, limbs_to_int

    P = ref.P
    rng = np.random.default_rng(1234)
    out = {"platform": jax.devices()[0].platform,
           "knobs": {key: val for key, val in os.environ.items()
                     if key.startswith("GETHSHARDING_TPU_")}}

    def rand_fp(n):
        # full 32-byte range mod P: the top-limb carry paths are the most
        # likely home of a relaxed-normalization bound bug
        return [int.from_bytes(rng.bytes(32), "big") % P for _ in range(n)]

    def to_limbs(vals):
        return jnp.asarray(ints_to_limbs(vals, NLIMBS))

    def ints_of(arr):
        arr = np.asarray(k.FP.canon(jnp.asarray(arr)))
        flat = arr.reshape(-1, arr.shape[-1])
        return [limbs_to_int(row) % P for row in flat]

    stages = {}
    first_bad = None
    B = 16

    def check(name, got_limbs, want_ints):
        nonlocal first_bad
        ok = ints_of(got_limbs) == [w % P for w in want_ints]
        stages[name] = bool(ok)
        if not ok and first_bad is None:
            first_bad = name
        return ok

    xs, ys = rand_fp(B), rand_fp(B)
    xa, ya = to_limbs(xs), to_limbs(ys)

    # 1: one normalize of a plain canonical value (identity)
    check("normalize_identity", jax.jit(k.FP.normalize)(xa), xs)
    # 2: add -> normalize
    check("add", jax.jit(lambda a, b: k.FP.normalize(a + b))(xa, ya),
          [a + b for a, b in zip(xs, ys)])
    # 3: sub (negative intermediates + pad lift)
    check("sub", jax.jit(k.FP.sub)(xa, ya),
          [a - b for a, b in zip(xs, ys)])
    # 4: single product (fold matrix + relaxed rounds)
    check("mul", jax.jit(k.FP.mul)(xa, ya),
          [a * b for a, b in zip(xs, ys)])
    # 5: product CHAIN (quasi-canonical inputs feeding the next mul —
    # the case the one-shot tests miss)
    def chain(a, b):
        c = k.FP.mul(a, b)
        d = k.FP.mul(c, a)
        return k.FP.mul(d, c)
    check("mul_chain", jax.jit(chain)(xa, ya),
          [((a * b % P) * a % P) * (a * b % P) for a, b in zip(xs, ys)])
    # 6: fp2 mul with four INDEPENDENT components (a symmetric operand
    # pair makes the real part identically zero and hides cancellation
    # bugs in the subtracting path)
    cs, ds = rand_fp(B), rand_fp(B)
    ca, da = to_limbs(cs), to_limbs(ds)
    f2a = jnp.stack([xa, ya], axis=-2)
    f2b = jnp.stack([ca, da], axis=-2)
    got = jax.jit(k.fp2_mul)(f2a, f2b)
    want = []
    for a, b, c, d in zip(xs, ys, cs, ds):
        want.extend([(a * c - b * d) % P, (a * d + b * c) % P])
    check("fp2_mul", got, want)
    # 7: fp2 square
    got = jax.jit(k.fp2_sqr)(f2a)
    want = []
    for a, b in zip(xs, ys):
        want.extend([(a * a - b * b) % P, (2 * a * b) % P])
    check("fp2_sqr", got, want)
    # 8: full pairing check on a protocol-valid product (the gate that
    # fails in the audit)
    sk = 987654321
    p1 = ref.g1_mul(sk, ref.G1_GEN)
    q2 = ref.g2_mul(sk, ref.G2_GEN)
    px, py, _ = k.g1_to_limbs([p1, ref.g1_neg(ref.G1_GEN)])
    qx, qy, _ = k.g2_to_limbs([ref.G2_GEN, q2])
    got = jax.jit(k.pairing_check)(
        jnp.asarray(px)[None], jnp.asarray(py)[None],
        jnp.asarray(qx)[None], jnp.asarray(qy)[None],
        jnp.ones((1, 2), bool))
    ok = bool(np.asarray(got)[0])
    stages["pairing_check_valid"] = ok
    if not ok and first_bad is None:
        first_bad = "pairing_check_valid"

    out["stages"] = stages
    out["first_bad"] = first_bad
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
