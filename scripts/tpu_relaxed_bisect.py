"""Bisect the relaxed-normalize wrong-result on the live backend.

r4 finding: `GETHSHARDING_TPU_LIMB_FORM=wide GETHSHARDING_TPU_NORM=
relaxed` fails the audit correctness gate on TPU (every shard's
aggregate rejected) while the IDENTICAL knobs pass on CPU at the same
shape — a backend-specific numeric divergence, not a bound violation.
This probe runs the field stack bottom-up under the ambient knobs and
compares every stage against host scalar bigint goldens, printing the
FIRST diverging stage: the r5 fix (or the formal parking justification)
starts from that op instead of the whole dispatch.

Run under the relaxed env:
  GETHSHARDING_TPU_LIMB_FORM=wide GETHSHARDING_TPU_NORM=relaxed \
    python scripts/tpu_relaxed_bisect.py
Prints ONE JSON line {platform, stages: {name: ok}, first_bad}.
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    if os.environ.get("GETHSHARDING_BENCH_CPU") == "1":
        # hermetic validation runs: JAX_PLATFORMS=cpu alone is NOT
        # enough — the tunnel PJRT plugin can hang at registration when
        # the tunnel is half-open; this drops the plugin factories
        from gethsharding_tpu.parallel.virtual import (
            force_virtual_cpu_devices)

        force_virtual_cpu_devices(1)
    from gethsharding_tpu.parallel.virtual import configure_compile_cache

    configure_compile_cache()

    import jax
    import jax.numpy as jnp

    from gethsharding_tpu.crypto import bn256 as ref
    from gethsharding_tpu.ops import bn256_jax as k
    from gethsharding_tpu.ops.limb import NLIMBS, ints_to_limbs, limbs_to_int

    P = ref.P
    rng = np.random.default_rng(1234)
    out = {"platform": jax.devices()[0].platform,
           "knobs": {key: val for key, val in os.environ.items()
                     if key.startswith("GETHSHARDING_TPU_")}}

    def rand_fp(n):
        # full 32-byte range mod P: the top-limb carry paths are the most
        # likely home of a relaxed-normalization bound bug
        return [int.from_bytes(rng.bytes(32), "big") % P for _ in range(n)]

    def to_limbs(vals):
        return jnp.asarray(ints_to_limbs(vals, NLIMBS))

    def ints_of(arr):
        arr = np.asarray(k.FP.canon(jnp.asarray(arr)))
        flat = arr.reshape(-1, arr.shape[-1])
        return [limbs_to_int(row) % P for row in flat]

    stages = {}
    first_bad = None
    B = 16

    def check(name, got_limbs, want_ints):
        nonlocal first_bad
        # breadcrumb BEFORE the device pull: a timed-out probe's .err
        # then shows the stage it died in
        print(f"# stage {name}...", file=sys.stderr, flush=True)
        got_ints = ints_of(got_limbs)
        want_mod = [w % P for w in want_ints]
        ok = got_ints == want_mod
        stages[name] = bool(ok)
        if not ok and first_bad is None:
            first_bad = name
            # evidence for the mechanism, not just the location: the
            # first mismatching element's value pair + its raw limbs
            # (pre-canon) — a bound violation shows as an out-of-range
            # limb, a backend arithmetic quirk as a wrong in-range one
            idx = next((i for i, (g, w)
                        in enumerate(zip(got_ints, want_mod)) if g != w),
                       None)
            raw = np.asarray(got_limbs).reshape(-1,
                                                np.asarray(got_limbs).shape[-1])
            out["first_bad_evidence"] = {
                "raw_limb_min": int(raw.min()),
                "raw_limb_max": int(raw.max()),
            }
            if idx is None:  # lengths differ with an equal prefix
                out["first_bad_evidence"]["length_mismatch"] = [
                    len(got_ints), len(want_mod)]
            else:
                out["first_bad_evidence"].update({
                    "element": idx,
                    "got": hex(got_ints[idx]),
                    "want": hex(want_mod[idx]),
                    "raw_limbs": raw[idx].tolist(),
                })
        return ok

    xs, ys = rand_fp(B), rand_fp(B)
    xa, ya = to_limbs(xs), to_limbs(ys)

    # 1: one normalize of a plain canonical value (identity)
    check("normalize_identity", jax.jit(k.FP.normalize)(xa), xs)
    # 2: add -> normalize
    check("add", jax.jit(lambda a, b: k.FP.normalize(a + b))(xa, ya),
          [a + b for a, b in zip(xs, ys)])
    # 3: sub (negative intermediates + pad lift)
    check("sub", jax.jit(k.FP.sub)(xa, ya),
          [a - b for a, b in zip(xs, ys)])
    # 4: single product (fold matrix + relaxed rounds)
    check("mul", jax.jit(k.FP.mul)(xa, ya),
          [a * b for a, b in zip(xs, ys)])
    # 5: product CHAIN (quasi-canonical inputs feeding the next mul —
    # the case the one-shot tests miss)
    def chain(a, b):
        c = k.FP.mul(a, b)
        d = k.FP.mul(c, a)
        return k.FP.mul(d, c)
    check("mul_chain", jax.jit(chain)(xa, ya),
          [((a * b % P) * a % P) * (a * b % P) for a, b in zip(xs, ys)])
    # 6: fp2 mul with four INDEPENDENT components (a symmetric operand
    # pair makes the real part identically zero and hides cancellation
    # bugs in the subtracting path)
    cs, ds = rand_fp(B), rand_fp(B)
    ca, da = to_limbs(cs), to_limbs(ds)
    f2a = jnp.stack([xa, ya], axis=-2)
    f2b = jnp.stack([ca, da], axis=-2)
    got = jax.jit(k.fp2_mul)(f2a, f2b)
    want = []
    for a, b, c, d in zip(xs, ys, cs, ds):
        want.extend([(a * c - b * d) % P, (a * d + b * c) % P])
    check("fp2_mul", got, want)
    # 7: fp2 square
    got = jax.jit(k.fp2_sqr)(f2a)
    want = []
    for a, b in zip(xs, ys):
        want.extend([(a * a - b * b) % P, (2 * a * b) % P])
    check("fp2_sqr", got, want)

    # 7b: DEPTH sweep — a divergence that accumulates (quasi-canonical
    # growth feeding the next op past a bound) shows at some chain depth
    # between the 3-deep unit chain and the ~600-op pairing; the first
    # failing depth IS the bisect. Each step multiplies by a fresh
    # random operand so cancellation can't mask drift.
    ops = [rand_fp(B) for _ in range(128)]
    ops_l = [to_limbs(o) for o in ops]

    def chain_n(n):
        # lax.scan, not an unrolled loop: ONE compiled body per depth
        # (an unrolled depth-128 jit costs many minutes of compile — too
        # slow for a tunnel window) and the same sequential structure the
        # production Miller/final-exp drivers use
        from jax import lax

        ops_arr = jnp.stack(ops_l[:n])          # (n, B, NL)

        def step(acc, o):
            return k.FP.mul(acc, o), None

        def f(a, os):
            out, _ = lax.scan(step, a, os)
            return out

        return jax.jit(f)(xa, ops_arr)

    for depth in (8, 32, 128):
        want = []
        for i, a in enumerate(xs):
            acc = a
            for o in ops[:depth]:
                acc = acc * o[i] % P
            want.append(acc)
        check(f"mul_chain_depth_{depth}", chain_n(depth), want)

    # 7c: fp12 product (the cyclic-convolution + xi-wrap layer the fp2
    # stages never reach)
    f12a = jnp.stack([jnp.stack([to_limbs(rand_fp(B)) for _ in range(2)],
                                axis=-2) for _ in range(6)], axis=-3)
    f12b = jnp.stack([jnp.stack([to_limbs(rand_fp(B)) for _ in range(2)],
                                axis=-2) for _ in range(6)], axis=-3)
    got12 = jax.jit(k.fp12_mul)(f12a, f12b)

    # host goldens via the scalar tower classes; the SHARED w-basis<->
    # tower mapping (`fp12_to_int_coeffs` / `_WSLOT`, ops/bn256_jax) does
    # the basis work — ONE whole-array canon per operand, no per-lane
    # device round-trips, no third copy of the slot convention
    ca_all = k.fp12_to_int_coeffs(f12a)     # (B, 2, 3, 2) object ints
    cb_all = k.fp12_to_int_coeffs(f12b)

    def tower_fp12(c):
        halves = [ref.Fp6(ref.Fp2(int(c[h, 0, 0]), int(c[h, 0, 1])),
                          ref.Fp2(int(c[h, 1, 0]), int(c[h, 1, 1])),
                          ref.Fp2(int(c[h, 2, 0]), int(c[h, 2, 1])))
                  for h in range(2)]
        return ref.Fp12(halves[0], halves[1])

    # every lane, flat in ints_of's (b, w-slot, component) row order,
    # through check() so a divergence HERE also carries the evidence
    want12 = []
    for b in range(B):
        prod = tower_fp12(ca_all[b]) * tower_fp12(cb_all[b])
        for (h, l) in k._WSLOT:
            fp2c = ((prod.c0 if h == 0 else prod.c1).c0,
                    (prod.c0 if h == 0 else prod.c1).c1,
                    (prod.c0 if h == 0 else prod.c1).c2)[l]
            want12.extend([fp2c.a % P, fp2c.b % P])
    check("fp12_mul", got12, want12)

    # 8: full pairing check on a protocol-valid product (the gate that
    # fails in the audit). The heaviest compile in the script — LAST on
    # purpose, and skippable for quick smoke validation of the cheaper
    # stages (GETHSHARDING_BISECT_QUICK=1).
    if os.environ.get("GETHSHARDING_BISECT_QUICK") == "1":
        out["stages"] = stages
        out["first_bad"] = first_bad
        print(json.dumps(out))
        return 0
    print("# stage pairing_check_valid...", file=sys.stderr, flush=True)
    sk = 987654321
    p1 = ref.g1_mul(sk, ref.G1_GEN)
    q2 = ref.g2_mul(sk, ref.G2_GEN)
    px, py, _ = k.g1_to_limbs([p1, ref.g1_neg(ref.G1_GEN)])
    qx, qy, _ = k.g2_to_limbs([ref.G2_GEN, q2])
    got = jax.jit(k.pairing_check)(
        jnp.asarray(px)[None], jnp.asarray(py)[None],
        jnp.asarray(qx)[None], jnp.asarray(qy)[None],
        jnp.ones((1, 2), bool))
    ok = bool(np.asarray(got)[0])
    stages["pairing_check_valid"] = ok
    if not ok and first_bad is None:
        first_bad = "pairing_check_valid"

    out["stages"] = stages
    out["first_bad"] = first_bad
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
