"""Compile + correctness probe of the final-exp mega-kernel on the live
backend: two real pairing products (one valid, one tampered) through
`finalexp_is_one` COMPILED, compared against the XLA `pairing_is_one`.
Prints ONE JSON line with ok / compile+run walls / error. Small batch on
purpose — this answers "does Mosaic take the mega-kernel at all, and is
it correct on silicon" before the full bench probe spends a window on it.
"""

from __future__ import annotations

import json
import os
import sys
import time
import traceback

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    from gethsharding_tpu.parallel.virtual import configure_compile_cache

    configure_compile_cache()

    import jax
    import jax.numpy as jnp

    out = {"platform": jax.devices()[0].platform}
    try:
        from gethsharding_tpu.crypto import bn256 as ref
        from gethsharding_tpu.ops import bn256_jax as k
        from gethsharding_tpu.ops.pallas_finalexp import finalexp_is_one

        rng = np.random.default_rng(61)
        fs, wants = [], []
        for j in range(2):
            a = int.from_bytes(rng.bytes(31), "big") % (ref.N - 3) + 2
            p1 = ref.g1_mul(a, ref.G1_GEN)
            q2 = ref.g2_mul(a, ref.G2_GEN)
            if j == 1:
                p1 = ref.g1_add(p1, ref.G1_GEN)
            px, py, _ = k.g1_to_limbs([p1, ref.g1_neg(ref.G1_GEN)])
            qx, qy, _ = k.g2_to_limbs([ref.G2_GEN, q2])
            f = k.pairing_product(
                jnp.asarray(px)[None], jnp.asarray(py)[None],
                jnp.asarray(qx)[None], jnp.asarray(qy)[None],
                jnp.ones((1, 2), bool))
            fs.append(np.asarray(f)[0])
            wants.append(j == 0)
        f = jnp.asarray(np.stack(fs))

        t0 = time.perf_counter()
        got = np.asarray(finalexp_is_one(f))
        out["mega_wall_s"] = round(time.perf_counter() - t0, 2)
        t0 = time.perf_counter()
        got2 = np.asarray(finalexp_is_one(f))
        out["mega_warm_s"] = round(time.perf_counter() - t0, 4)
        base = np.asarray(k.pairing_is_one(f))
        out["ok"] = bool((got == wants).all() and (got2 == wants).all()
                         and (base == wants).all())
        out["got"] = [bool(v) for v in got]
    except Exception:
        out["ok"] = False
        out["error"] = traceback.format_exc()[-1200:]

    # phase 2: the Miller mega-kernel on a real aggregated committee
    try:
        import jax.numpy as jnp

        from gethsharding_tpu.crypto import bn256 as ref
        from gethsharding_tpu.ops import bn256_jax as k
        from gethsharding_tpu.ops.pallas_finalexp import miller_f

        tag = b"smoke-miller"
        keys = [ref.bls_keygen(tag + bytes([j])) for j in range(3)]
        sigs = [ref.bls_sign(tag, sk) for sk, _ in keys]
        pks = [pk for _, pk in keys]
        hx, hy, _ = k.g1_to_limbs([ref.hash_to_g1(tag)] * 2)
        sx, sy, sm = k.g1_committee_to_limbs([sigs, sigs[:2]], 3)
        gx, gy, gm = k.g2_committee_to_limbs([pks, pks[:2]], 3)
        sig = k.aggregate_g1_proj(jnp.asarray(sx), jnp.asarray(sy),
                                  jnp.asarray(sm))
        pk = k.aggregate_g2_proj(jnp.asarray(gx), jnp.asarray(gy),
                                 jnp.asarray(gm))
        t0 = time.perf_counter()
        fm = np.asarray(miller_f(sig, jnp.asarray(hx), jnp.asarray(hy),
                                 pk))
        out["miller_wall_s"] = round(time.perf_counter() - t0, 2)
        fw = np.asarray(k._bls_miller_opt(sig, jnp.asarray(hx),
                                          jnp.asarray(hy), pk))
        same = bool(np.asarray(k.fp12_eq(jnp.asarray(fm),
                                         jnp.asarray(fw))).all())
        out["miller_ok"] = same
        out["ok"] = bool(out.get("ok")) and same
    except Exception:
        out["miller_ok"] = False
        out["ok"] = False
        out["miller_error"] = traceback.format_exc()[-1200:]
    print(json.dumps(out))
    # evidence contract: exit 0 means "answered on a real accelerator"
    # (a Mosaic failure IS an answer); only a CPU fallback is a non-result
    return 1 if out["platform"] == "cpu" else 0


if __name__ == "__main__":
    sys.exit(main())
