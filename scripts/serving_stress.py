#!/usr/bin/env python
"""Soak driver for the serving tier — single-backend or fleet.

Default mode (unchanged since PR 1): M client threads hammer ONE
serving backend with small ecrecover requests for a fixed duration,
verifying EVERY result against the known signer (zero-divergence soak,
not just throughput), while a reporter prints one JSON stats line per
interval:

    python scripts/serving_stress.py --clients 32 --duration 30 \
        --policy shed --queue-cap 256 --flush-us 500

Fleet traffic-model mode (`--replicas N`): an in-process fleet of N
breaker-guarded serving replicas behind the shard-aware router
(gethsharding_tpu/fleet/), driven by a production-shaped load model:

- **admission-class mix** (`--classes interactive=8,bulk_audit=3,...`):
  each client thread carries a class; bulk/catchup issue multi-row
  requests, interactive issues 1-row requests and must never be shed;
- **diurnal curve** (`--diurnal-s`): the active-client fraction swings
  sinusoidally between 30% and 100% over one period — load is a wave,
  not a constant;
- **hot-shard skew** (`--hot-shard`): that fraction of catchup/bulk
  requests carries ONE affinity key, overloading a single replica the
  way a popular shard does;
- **thundering herd** (`--herd-at`): at that second every client
  pauses, then re-bursts simultaneously — the reconnect stampede;
- optional seeded chaos (`--chaos-trip`) trips replica r0's breaker
  mid-soak so the drain→probe→re-enter cycle runs under load.

Per-class p99 latencies are reported and (when `--slo-interactive-ms`
etc. are nonzero) GATED: `bench.py --fleet` runs this model with SLOs
on. Exit code 1 on any divergence, hung client, interactive shed, or
SLO breach.

Light-client traffic model (`--light-clients N`): N threads drive
1-row `das_verify_multiproofs` requests (polynomial-multiproof DAS,
das/pcs.py) through the fleet router as interactive-class traffic
under their own `light` tenant quota bucket. Every row has a KNOWN
verdict (honest openings and tampered evals interleaved), so the soak
gates on correctness — one wrong verdict fails the run — as well as
the das_light p99 when `--slo-interactive-ms` is set.

Frontend process mode (`--frontend`, with `--replicas N`): the REAL
topology — N `chain_server` replica processes, one standalone
`fleet.frontend` process balancing them (hedging armed via
`--hedge-ms`), M client threads dialing the FRONTEND over JSON-RPC.
Every answer is verified against the known signer; the summary reports
the frontend's hedge win/waste rates from `shard_fleetStatus`. Exit 1
on any divergence or hung client.

Elastic closed-loop mode (`--elastic`): 2 chain_server replicas
behind TWO peered frontend processes (frontend A runs the SLO-driven
autoscaler), clients on `rpc.client.FrontendPool` driving a 10x
diurnal swing; frontend B is killed -9 mid-swing. Gates: zero
incorrect verdicts, pool failover observed, the autoscaler scales OUT
at the peak AND back IN during the trough (countered via
`shard_fleetStatus`), interactive p99 under `--slo-interactive-ms`.
Emits a `fleet_elastic` workload record through
`perfwatch.record_bench`.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from gethsharding_tpu import metrics
from gethsharding_tpu.crypto import secp256k1 as ecdsa
from gethsharding_tpu.crypto.keccak import keccak256
from gethsharding_tpu.serving import (ServingConfig, ServingOverloadError,
                                      ServingSigBackend)
from gethsharding_tpu.sigbackend import get_backend

CLASS_MIX_DEFAULT = "interactive=8,bulk_audit=3,catchup_replay=1"
CLASS_ROWS = {"interactive": 1, "bulk_audit": 4, "catchup_replay": 8}


def build_cases(n: int):
    """n distinct (digest, sig65, expected address) rows."""
    cases = []
    for i in range(n):
        priv = int.from_bytes(keccak256(b"soak-%d" % i), "big") % ecdsa.N
        digest = keccak256(b"soak-msg-%d" % i)
        cases.append((digest, ecdsa.sign(digest, priv).to_bytes65(),
                      ecdsa.priv_to_address(priv)))
    return cases


def parse_class_mix(spec: str):
    mix = []
    for part in filter(None, (p.strip() for p in spec.split(","))):
        name, _, weight = part.partition("=")
        mix.extend([name] * int(weight or 1))
    return mix


def percentile(samples, q: float) -> float:
    if not samples:
        return 0.0
    ordered = sorted(samples)
    return ordered[min(int(q * len(ordered)), len(ordered) - 1)]


def run_single(args) -> int:
    """The original single-backend soak (PR 1 behavior, unchanged)."""
    cases = build_cases(args.cases)
    serving = ServingSigBackend(
        get_backend(args.backend),
        ServingConfig(max_batch=args.max_batch, flush_us=args.flush_us,
                      queue_cap=args.queue_cap, policy=args.policy))

    done = [0] * args.clients
    shed = [0] * args.clients
    divergences: list = []
    deadline = time.monotonic() + args.duration
    stop = threading.Event()

    def client(c: int) -> None:
        i = c  # stagger the case cycle per client
        while time.monotonic() < deadline and not stop.is_set():
            digest, sig, want = cases[i % len(cases)]
            i += args.clients
            try:
                got = serving.ecrecover_addresses([digest], [sig])
            except ServingOverloadError:
                shed[c] += 1
                continue
            if got != [want]:
                divergences.append((c, i))
                stop.set()
                return
            done[c] += 1

    threads = [threading.Thread(target=client, args=(c,), daemon=True)
               for c in range(args.clients)]
    t0 = time.monotonic()
    for t in threads:
        t.start()

    wait_timer = metrics.DEFAULT_REGISTRY.timer("serving/ecrecover/wait_time")
    last_done = 0
    while time.monotonic() < deadline and not stop.is_set():
        time.sleep(min(args.report_interval, deadline - time.monotonic())
                   if deadline > time.monotonic() else 0)
        total = sum(done)
        print(json.dumps({
            "t_s": round(time.monotonic() - t0, 1),
            "done": total,
            "rate": round((total - last_done) / args.report_interval, 1),
            "shed": sum(shed),
            "dispatches": serving.dispatch_count,
            "coalesce_ratio": round(total / max(1, serving.dispatch_count),
                                    1),
            "queue_depth": serving.batcher.queue_depth_rows(
                "ecrecover_addresses"),
            "wait_p50_ms": round(wait_timer.percentile(0.5) * 1e3, 2),
        }), flush=True)
        last_done = total

    for t in threads:
        t.join(timeout=30)
    hung = [t for t in threads if t.is_alive()]
    wall = time.monotonic() - t0
    serving.close()

    total = sum(done)
    print(json.dumps({
        "summary": True,
        "clients": args.clients,
        "policy": args.policy,
        "wall_s": round(wall, 2),
        "done": total,
        "rate": round(total / wall, 1) if wall else 0.0,
        "shed": sum(shed),
        "dispatches": serving.dispatch_count,
        "coalesce_ratio": round(total / max(1, serving.dispatch_count), 1),
        "divergences": len(divergences),
        "hung_clients": len(hung),
    }), flush=True)
    return 1 if divergences or hung else 0


def build_fleet(args):
    """N breaker-guarded serving replicas behind the shard router; r0
    optionally carries a seeded chaos schedule that trips its breaker
    mid-soak."""
    from gethsharding_tpu.fleet import FleetRouter, Replica, RouterSigBackend
    from gethsharding_tpu.resilience.breaker import (CircuitBreaker,
                                                     FailoverSigBackend)
    from gethsharding_tpu.resilience.chaos import (ChaosSchedule,
                                                   ChaosSigBackend)

    servings, replicas, schedule = [], [], None
    for i in range(args.replicas):
        inner = get_backend(args.backend)
        if i == 0 and args.chaos_trip > 0:
            start = args.chaos_trip
            schedule = ChaosSchedule(
                seed=args.chaos_seed,
                rules={"backend.ecrecover_addresses":
                       lambda idx, start=start: start <= idx < start + 8})
            inner = ChaosSigBackend(inner, schedule)
        serving = ServingSigBackend(
            inner,
            ServingConfig(max_batch=args.max_batch, flush_us=args.flush_us,
                          queue_cap=args.queue_cap, policy=args.policy))
        servings.append(serving)
        replicas.append(Replica(
            f"r{i}",
            FailoverSigBackend(
                serving, get_backend("python"),
                breaker=CircuitBreaker(name=f"soak-r{i}",
                                       fault_threshold=3,
                                       reset_s=args.breaker_reset_s))))
    router = FleetRouter(replicas, health_interval_s=0.05)
    return router, RouterSigBackend(router), servings, replicas, schedule


def run_fleet(args) -> int:
    from gethsharding_tpu.fleet import AllReplicasDraining
    from gethsharding_tpu.serving.classes import CLASS_INTERACTIVE

    router, back, servings, replicas, schedule = build_fleet(args)
    cases = build_cases(args.cases)
    mix = parse_class_mix(args.classes)
    lat = {name: [] for name in CLASS_ROWS}
    done = {name: 0 for name in CLASS_ROWS}
    shed = {name: 0 for name in CLASS_ROWS}
    divergences: list = []
    stop = threading.Event()
    t0 = time.monotonic()
    deadline = t0 + args.duration
    herd_gate = threading.Event()
    herd_gate.set()

    def active_fraction(now: float) -> float:
        if args.diurnal_s <= 0:
            return 1.0
        phase = 2 * math.pi * ((now - t0) % args.diurnal_s) / args.diurnal_s
        return 0.65 + 0.35 * math.sin(phase)  # 30%..100%

    def client(c: int) -> None:
        klass = mix[c % len(mix)]
        rows = CLASS_ROWS[klass]
        rng_i = c
        while time.monotonic() < deadline and not stop.is_set():
            herd_gate.wait()
            # diurnal gating: clients beyond the active fraction sleep
            if (c / max(1, args.clients)) > active_fraction(
                    time.monotonic()):
                time.sleep(0.01)
                continue
            batch = [cases[(rng_i + j) % len(cases)] for j in range(rows)]
            rng_i += rows * args.clients
            # hot-shard skew applies to the bulk planes
            affinity = None
            if klass != CLASS_INTERACTIVE \
                    and (rng_i % 100) < args.hot_shard * 100:
                affinity = "hot-shard"
            t_req = time.monotonic()
            try:
                got = router.call("ecrecover_addresses",
                                  [b[0] for b in batch],
                                  [b[1] for b in batch],
                                  affinity=affinity, klass=klass)
            except (ServingOverloadError, AllReplicasDraining):
                shed[klass] += 1
                continue
            lat[klass].append(time.monotonic() - t_req)
            if got != [b[2] for b in batch]:
                divergences.append((c, rng_i))
                stop.set()
                return
            done[klass] += 1
            if klass == CLASS_INTERACTIVE:
                time.sleep(0.001)

    threads = [threading.Thread(target=client, args=(c,), daemon=True)
               for c in range(args.clients)]
    for t in threads:
        t.start()

    herd_done = args.herd_at <= 0
    last_report = t0
    while time.monotonic() < deadline and not stop.is_set():
        time.sleep(0.1)
        now = time.monotonic()
        if not herd_done and now - t0 >= args.herd_at:
            # thundering herd: everyone disconnects, then re-bursts at
            # the same instant
            herd_gate.clear()
            time.sleep(args.herd_pause_s)
            herd_gate.set()
            herd_done = True
            print(json.dumps({"herd": True, "t_s": round(now - t0, 1)}),
                  flush=True)
        if now - last_report >= args.report_interval:
            last_report = now
            print(json.dumps({
                "t_s": round(now - t0, 1),
                "active_fraction": round(active_fraction(now), 2),
                "done": dict(done),
                "shed": dict(shed),
                "states": {name: state["state"]
                           for name, state in router.states().items()},
            }), flush=True)

    for t in threads:
        t.join(timeout=60)
    hung = [t for t in threads if t.is_alive()]
    stop.set()

    # let a tripped replica finish its probe-driven re-entry
    reentered = True
    if schedule is not None:
        reentry_deadline = time.monotonic() + 10
        while replicas[0].state != "healthy" \
                and time.monotonic() < reentry_deadline:
            router.refresh(force=True)
            time.sleep(0.05)
        reentered = replicas[0].state == "healthy"

    shed_by_class = {name: 0 for name in CLASS_ROWS}
    for serving in servings:
        for klass, count in serving.batcher.shed_by_class().items():
            shed_by_class[klass] += count
    p99_ms = {name: round(percentile(samples, 0.99) * 1e3, 2)
              for name, samples in lat.items()}
    slo = {"interactive": args.slo_interactive_ms,
           "bulk_audit": args.slo_bulk_ms,
           "catchup_replay": args.slo_catchup_ms}
    slo_breaches = [name for name, limit in slo.items()
                    if limit > 0 and p99_ms[name] > limit]

    summary = {
        "summary": True,
        "fleet": True,
        "replicas": args.replicas,
        "clients": args.clients,
        "wall_s": round(time.monotonic() - t0, 2),
        "done": dict(done),
        "caller_shed": dict(shed),
        "replica_shed_by_class": shed_by_class,
        "p99_ms": p99_ms,
        "slo_ms": slo,
        "slo_breaches": slo_breaches,
        "divergences": len(divergences),
        "hung_clients": len(hung),
        "interactive_shed": shed["interactive"]
        + shed_by_class["interactive"],
        "drain_events": replicas[0].drain_events,
        "reentries": replicas[0].reentries,
        "chaos_injected": (0 if schedule is None else
                           schedule.injected.get(
                               "backend.ecrecover_addresses", 0)),
        "reentered": reentered,
        "states": {name: state["state"]
                   for name, state in router.states().items()},
    }
    print(json.dumps(summary), flush=True)
    for serving in servings:
        serving.close()

    failed = bool(divergences or hung or slo_breaches
                  or summary["interactive_shed"]
                  or (schedule is not None
                      and (summary["drain_events"] < 1 or not reentered)))
    return 1 if failed else 0


def build_poly_cases(n_cases: int, k: int):
    """Known-verdict multiproof rows: honest openings (expected True)
    interleaved with tampered evals (expected False) — a light-client
    check whose CORRECTNESS the soak verifies on every response, not
    just its latency."""
    import random as _random

    from gethsharding_tpu.das import pcs

    rng = _random.Random(7)
    cases = []
    for i in range(n_cases):
        n = 12
        values = [rng.randrange(pcs.N) for _ in range(n)]
        indices = sorted(rng.sample(range(n), min(k, n)))
        proof, evals = pcs.open_multi(values, indices)
        commitment = pcs.g1_to_bytes(pcs.commit(values))
        proof_bytes = pcs.g1_to_bytes(proof)
        cases.append((commitment, indices, evals, proof_bytes, n, True))
        if i % 2:
            bad = list(evals)
            bad[0] = (bad[0] + 1) % pcs.N
            cases.append((commitment, indices, bad, proof_bytes, n,
                          False))
    return cases


def run_light_clients(args) -> int:
    """The light-client sampling tier under load: M client threads
    drive 1-row `das_verify_multiproofs` requests through the fleet
    router as INTERACTIVE traffic under their own tenant quota bucket
    (`tenant="light"`), every verdict checked against the known truth.
    Gates: zero incorrect verdicts, zero hung clients, and (when
    `--slo-interactive-ms` is nonzero) the das_light p99. Latencies
    also feed the process `das_light` SLO objective (slo/tracker.py),
    so /status on a long-lived node shows the same series."""
    from gethsharding_tpu import slo
    from gethsharding_tpu.fleet import AllReplicasDraining

    router, _back, servings, _replicas, _schedule = build_fleet(args)
    cases = build_poly_cases(args.cases if args.cases <= 16 else 8,
                             args.light_k)
    lat: list = []
    done = [0]
    incorrect: list = []
    shed = [0]
    stop = threading.Event()
    t0 = time.monotonic()
    deadline = t0 + args.duration

    def client(c: int) -> None:
        i = c
        while time.monotonic() < deadline and not stop.is_set():
            commitment, indices, evals, proof, n, want = \
                cases[i % len(cases)]
            i += args.light_clients
            t_req = time.monotonic()
            try:
                got = router.call("das_verify_multiproofs",
                                  [commitment], [indices], [evals],
                                  [proof], [n],
                                  affinity=commitment.hex(),
                                  klass="interactive", tenant="light")
            except (ServingOverloadError, AllReplicasDraining):
                shed[0] += 1
                slo.record("das_light", ok=False)
                continue
            elapsed = time.monotonic() - t_req
            lat.append(elapsed)
            slo.record("das_light", ok=got == [want],
                       latency_s=elapsed)
            if got != [want]:
                incorrect.append((c, i, got, want))
                stop.set()
                return
            done[0] += 1

    threads = [threading.Thread(target=client, args=(c,), daemon=True)
               for c in range(args.light_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=args.duration + 60)
    hung = [t for t in threads if t.is_alive()]
    stop.set()
    wall = time.monotonic() - t0

    quota_rejections = sum(s.batcher.quota_rejections()
                           for s in servings)
    p99_ms = round(percentile(lat, 0.99) * 1e3, 2)
    slo_breach = bool(args.slo_interactive_ms > 0
                      and p99_ms > args.slo_interactive_ms)
    summary = {
        "summary": True,
        "light_clients": args.light_clients,
        "replicas": args.replicas,
        "wall_s": round(wall, 2),
        "done": done[0],
        "rate": round(done[0] / wall, 2) if wall else 0.0,
        "shed": shed[0],
        "quota_rejections": quota_rejections,
        "p99_ms": p99_ms,
        "slo_ms": args.slo_interactive_ms,
        "slo_breach": slo_breach,
        "incorrect_verdicts": len(incorrect),
        "hung_clients": len(hung),
    }
    print(json.dumps(summary), flush=True)
    for serving in servings:
        serving.close()
    return 1 if incorrect or hung or slo_breach else 0


def _spawn(cmd, env=None):
    import subprocess

    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                            stderr=subprocess.DEVNULL, text=True,
                            env=env or os.environ.copy())
    line = proc.stdout.readline().strip()
    if not line:
        proc.terminate()
        raise RuntimeError(f"{cmd[:4]}... printed no address line")
    addr = json.loads(line)
    return proc, addr


def run_frontend(args) -> int:
    """The cross-process topology soak: N chain_server replicas + ONE
    standalone frontend process, clients dialing the frontend."""
    from gethsharding_tpu.rpc import codec
    from gethsharding_tpu.rpc.client import RPCClient, RPCError

    n = max(2, args.replicas)
    env = {**os.environ}
    env.setdefault("JAX_PLATFORMS", "cpu")
    replicas, endpoints = [], []
    frontend = None
    try:
        for _ in range(n):
            proc, addr = _spawn(
                [sys.executable, "-m", "gethsharding_tpu.rpc.chain_server",
                 "--sigbackend", "python", "--verbosity", "error"],
                env=env)
            replicas.append(proc)
            endpoints.append("%s:%d" % (addr["host"], addr["port"]))
        fe_cmd = [sys.executable, "-m", "gethsharding_tpu.fleet.frontend",
                  "--verbosity", "error",
                  "--health-interval", "0.1",
                  "--fleet-hedge-ms", str(args.hedge_ms)]
        for endpoint in endpoints:
            fe_cmd += ["--replica", endpoint]
        frontend, fe_addr = _spawn(fe_cmd, env=env)

        cases = build_cases(args.cases)
        done = [0] * args.clients
        divergences: list = []
        typed_errors = [0]
        stop = threading.Event()
        deadline = time.monotonic() + args.duration

        def client(c: int) -> None:
            rpc = RPCClient(fe_addr["host"], fe_addr["port"])
            i = c
            try:
                while time.monotonic() < deadline and not stop.is_set():
                    digest, sig, want = cases[i % len(cases)]
                    i += args.clients
                    try:
                        got = rpc.call("shard_ecrecover",
                                       [codec.enc_bytes(digest)],
                                       [codec.enc_bytes(sig)])
                    except RPCError:
                        typed_errors[0] += 1
                        continue
                    if got != [codec.enc_bytes(want)]:
                        divergences.append((c, i))
                        stop.set()
                        return
                    done[c] += 1
            finally:
                rpc.close()

        threads = [threading.Thread(target=client, args=(c,), daemon=True)
                   for c in range(args.clients)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=args.duration + 60)
        hung = [t for t in threads if t.is_alive()]
        wall = time.monotonic() - t0

        status_rpc = RPCClient(fe_addr["host"], fe_addr["port"])
        status = status_rpc.call("shard_fleetStatus")
        status_rpc.close()
        hedge = status["hedge"]
        total = sum(done)
        dispatches = total + hedge["issued"]
        summary = {
            "summary": True,
            "frontend": True,
            "replicas": n,
            "clients": args.clients,
            "wall_s": round(wall, 2),
            "done": total,
            "rate": round(total / wall, 1) if wall else 0.0,
            "typed_errors": typed_errors[0],
            "divergences": len(divergences),
            "hung_clients": len(hung),
            "hedge": hedge,
            "hedge_win_rate": round(
                hedge["won"] / max(1, hedge["issued"]), 3),
            "hedge_waste_rate": round(
                hedge["wasted"] / max(1, dispatches), 3),
            "replica_states": {name: s["state"]
                               for name, s in status["replicas"].items()},
        }
        print(json.dumps(summary), flush=True)
        return 1 if divergences or hung else 0
    finally:
        if frontend is not None:
            frontend.terminate()
        for proc in replicas:
            proc.terminate()


def _free_port() -> int:
    """Pre-pick a listening port (bind/release) so two frontends can be
    started with --peer pointing at each other before either is up."""
    import socket

    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    port = sock.getsockname()[1]
    sock.close()
    return port


def run_elastic(args) -> int:
    """The elastic closed-loop soak (ISSUE 20 acceptance): 2
    chain_server replica processes behind TWO peered frontend
    processes — frontend A runs the SLO-driven autoscaler — while
    clients on `rpc.client.FrontendPool` drive a 10x diurnal swing
    (offered load decays 100% -> 10% over the run). Mid-swing frontend
    B is killed -9; its clients must fail over to A without one
    incorrect verdict. The autoscaler must be OBSERVED acting in both
    directions: scale-OUT during the peak (sustained queue depth
    federated from the replicas' serving gauges) and scale-IN during
    the trough, both read back COUNTERED from frontend A's
    `shard_fleetStatus`. Gates: zero incorrect verdicts, zero hung
    clients, failovers >= 1, out >= 1 AND in >= 1, and (when
    `--slo-interactive-ms` is set) the interactive p99. The result is
    emitted as a `fleet_elastic` workload record through
    `perfwatch.record_bench` into the perf ledger."""
    from gethsharding_tpu.rpc.client import FrontendPool, RPCClient, RPCError

    n = max(2, args.replicas or 2)
    env = {**os.environ}
    env.setdefault("JAX_PLATFORMS", "cpu")
    procs: list = []
    frontends: list = []
    try:
        endpoints = []
        for _ in range(n):
            proc, addr = _spawn(
                [sys.executable, "-m", "gethsharding_tpu.rpc.chain_server",
                 "--sigbackend", "python", "--verbosity", "error"],
                env=env)
            procs.append(proc)
            endpoints.append("%s:%d" % (addr["host"], addr["port"]))

        # peered frontends need each other's address BEFORE either is
        # up: pre-pick both ports
        ports = (_free_port(), _free_port())
        scaler_env = {
            **env,
            "GETHSHARDING_AUTOSCALE_MIN": str(n),
            "GETHSHARDING_AUTOSCALE_MAX": str(n + 1),
            "GETHSHARDING_AUTOSCALE_INTERVAL_S": "0.25",
            "GETHSHARDING_AUTOSCALE_OUT_DEPTH": str(args.elastic_out_depth),
            "GETHSHARDING_AUTOSCALE_IN_DEPTH": "2",
            "GETHSHARDING_AUTOSCALE_SUSTAIN_S": "0.75",
            "GETHSHARDING_AUTOSCALE_COOLDOWN_S": "2.0",
        }

        def fe_cmd(port: int, peer_port: int, autoscale: bool):
            cmd = [sys.executable, "-m", "gethsharding_tpu.fleet.frontend",
                   "--verbosity", "error", "--port", str(port),
                   "--health-interval", "0.1",
                   "--gossip-interval", "0.25",
                   "--peer", "127.0.0.1:%d" % peer_port]
            for endpoint in endpoints:
                cmd += ["--replica", endpoint]
            if autoscale:
                cmd += ["--autoscale", "--autoscale-backend", "python"]
            return cmd

        fe_a, addr_a = _spawn(fe_cmd(ports[0], ports[1], True),
                              env=scaler_env)
        frontends.append(fe_a)
        fe_b, addr_b = _spawn(fe_cmd(ports[1], ports[0], False), env=env)
        frontends.append(fe_b)
        ep_a = "%s:%d" % (addr_a["host"], addr_a["port"])
        ep_b = "%s:%d" % (addr_b["host"], addr_b["port"])

        cases = build_cases(args.cases)
        done = [0] * args.clients
        lat: list = []
        lat_lock = threading.Lock()
        divergences: list = []
        typed_errors = [0]
        stop = threading.Event()
        t0 = time.monotonic()
        deadline = t0 + args.duration
        # half the clients hold B as their sticky primary so the kill
        # actually exercises pool failover, not just a spare
        pools = (FrontendPool([ep_a, ep_b], timeout=15.0),
                 FrontendPool([ep_b, ep_a], timeout=15.0))

        def active_fraction(now: float) -> float:
            # one peak->trough half-cycle: 100% offered at t0 decaying
            # to 10% at the deadline — the 10x diurnal swing the
            # autoscaler must absorb (out near the peak, in during the
            # trough)
            phase = min(1.0, max(0.0, (now - t0) / args.duration))
            return 0.55 + 0.45 * math.cos(math.pi * phase)

        def client(c: int) -> None:
            pool = pools[c % 2]
            i = c
            while time.monotonic() < deadline and not stop.is_set():
                if (c / max(1, args.clients)) > active_fraction(
                        time.monotonic()):
                    time.sleep(0.02)
                    continue
                digest, sig, want = cases[i % len(cases)]
                i += args.clients
                t_req = time.monotonic()
                try:
                    got = pool.ecrecover_addresses([digest], [sig])
                except (ConnectionError, TimeoutError, RPCError, OSError):
                    typed_errors[0] += 1
                    continue
                with lat_lock:
                    lat.append(time.monotonic() - t_req)
                if got != [want]:
                    divergences.append((c, i))
                    stop.set()
                    return
                done[c] += 1

        threads = [threading.Thread(target=client, args=(c,), daemon=True)
                   for c in range(args.clients)]
        for t in threads:
            t.start()

        killed = False
        last_report = t0
        while time.monotonic() < deadline and not stop.is_set():
            time.sleep(0.1)
            now = time.monotonic()
            if not killed and now - t0 >= args.duration / 2:
                fe_b.kill()  # SIGKILL: no drain notice, no goodbyes
                killed = True
                print(json.dumps({"killed_frontend": ep_b,
                                  "t_s": round(now - t0, 1)}), flush=True)
            if now - last_report >= args.report_interval:
                last_report = now
                print(json.dumps({
                    "t_s": round(now - t0, 1),
                    "active_fraction": round(active_fraction(now), 2),
                    "done": sum(done),
                    "typed_errors": typed_errors[0],
                    "failovers": sum(p.failovers for p in pools),
                }), flush=True)

        for t in threads:
            t.join(timeout=args.duration + 60)
        hung = [t for t in threads if t.is_alive()]
        stop.set()
        wall = time.monotonic() - t0

        # give the controller a calm tail to finish the scale-in leg
        # (trough depth ~0 once the clients stop) and reap the drained
        # spawn, then read the countered evidence off frontend A
        status = None
        status_rpc = RPCClient(addr_a["host"], addr_a["port"])
        try:
            settle_deadline = time.monotonic() + 15.0
            while time.monotonic() < settle_deadline:
                status = status_rpc.call("shard_fleetStatus")
                scale = status.get("autoscale") or {}
                if scale.get("out", 0) >= 1 and scale.get("in", 0) >= 1 \
                        and not scale.get("retiring"):
                    break
                time.sleep(0.25)
        finally:
            status_rpc.close()
        scale = (status or {}).get("autoscale") or {}
        membership = (status or {}).get("membership") or {}

        total = sum(done)
        failovers = sum(p.failovers for p in pools)
        for pool in pools:
            pool.close()
        p99_ms = round(percentile(lat, 0.99) * 1e3, 2)
        slo_breach = bool(args.slo_interactive_ms > 0
                          and p99_ms > args.slo_interactive_ms)
        summary = {
            "summary": True,
            "elastic": True,
            "replicas": n,
            "clients": args.clients,
            "wall_s": round(wall, 2),
            "done": total,
            "rate": round(total / wall, 1) if wall else 0.0,
            "typed_errors": typed_errors[0],
            "divergences": len(divergences),
            "hung_clients": len(hung),
            "frontend_killed": killed,
            "failovers": failovers,
            "scale_out": scale.get("out", 0),
            "scale_in": scale.get("in", 0),
            "scale_held": scale.get("held", 0),
            "epoch": membership.get("epoch", 0),
            "endpoints": membership.get("endpoints", []),
            "p99_ms": p99_ms,
            "slo_ms": args.slo_interactive_ms,
            "slo_breach": slo_breach,
        }
        print(json.dumps(summary), flush=True)

        failed = bool(divergences or hung or slo_breach
                      or failovers < 1
                      or summary["scale_out"] < 1
                      or summary["scale_in"] < 1)
        try:  # the perfwatch gate's fleet_elastic workload record
            from gethsharding_tpu.perfwatch import record_bench

            record_bench(
                "fleet_elastic_interactive_p99_ms", p99_ms, unit="ms",
                vs_baseline=(round(p99_ms / args.slo_interactive_ms, 4)
                             if args.slo_interactive_ms > 0 else None),
                workload="fleet_elastic", valid=not failed,
                extra={k: v for k, v in summary.items()
                       if k not in ("summary", "p99_ms", "endpoints")})
        except Exception as exc:  # noqa: BLE001 - ledger is best-effort
            print(json.dumps({"ledger_error": repr(exc)}), flush=True)
        return 1 if failed else 0
    finally:
        for proc in frontends:
            proc.terminate()
        for proc in frontends:
            try:
                proc.wait(timeout=10)
            except Exception:  # noqa: BLE001
                proc.kill()
        for proc in procs:
            proc.terminate()


def main() -> int:
    parser = argparse.ArgumentParser(
        description="soak the serving tier (single backend or fleet)")
    parser.add_argument("--clients", type=int, default=16)
    parser.add_argument("--duration", type=float, default=10.0,
                        help="seconds of offered load")
    parser.add_argument("--backend", default="python",
                        choices=("python", "jax"),
                        help="wrapped backend (jax needs an accelerator)")
    parser.add_argument("--max-batch", type=int, default=128)
    parser.add_argument("--flush-us", type=float, default=500.0)
    parser.add_argument("--queue-cap", type=int, default=4096)
    parser.add_argument("--policy", default="block",
                        choices=("block", "shed"))
    parser.add_argument("--report-interval", type=float, default=2.0)
    parser.add_argument("--cases", type=int, default=256,
                        help="distinct signed rows cycled by the clients")
    # -- fleet traffic model ------------------------------------------------
    parser.add_argument("--replicas", type=int, default=0,
                        help="> 0: run the FLEET soak — this many "
                             "breaker-guarded serving replicas behind "
                             "the shard router (gethsharding_tpu/fleet/)")
    parser.add_argument("--classes", default=CLASS_MIX_DEFAULT,
                        help="admission-class client mix, e.g. "
                             "'interactive=8,bulk_audit=3,"
                             "catchup_replay=1'")
    parser.add_argument("--diurnal-s", type=float, default=0.0,
                        help="sinusoidal load period in seconds (0 = "
                             "flat load): active clients swing 30%%-100%%")
    parser.add_argument("--hot-shard", type=float, default=0.0,
                        help="fraction of bulk/catchup requests keyed to "
                             "ONE hot affinity (0..1)")
    parser.add_argument("--herd-at", type=float, default=0.0,
                        help="seconds into the soak to fire a thundering-"
                             "herd reconnect burst (0 = off)")
    parser.add_argument("--herd-pause-s", type=float, default=0.3,
                        help="how long the herd holds its breath")
    parser.add_argument("--chaos-trip", type=int, default=0,
                        help="> 0: seed a chaos run of 8 consecutive "
                             "device faults on replica r0 starting at "
                             "this dispatch index — trips its breaker "
                             "mid-soak")
    parser.add_argument("--frontend", action="store_true",
                        help="cross-process mode: spawn --replicas N "
                             "chain_server processes plus ONE standalone "
                             "fleet.frontend process and drive traffic "
                             "through the frontend over JSON-RPC, "
                             "reporting hedge win/waste rates")
    parser.add_argument("--hedge-ms", type=float, default=15.0,
                        help="frontend mode: the frontend's "
                             "--fleet-hedge-ms floor")
    parser.add_argument("--elastic", action="store_true",
                        help="elastic closed-loop soak: 2 chain_server "
                             "replicas behind TWO peered frontends "
                             "(frontend A autoscaling), FrontendPool "
                             "clients riding a 10x diurnal swing, one "
                             "frontend killed -9 mid-swing; gates on "
                             "zero incorrect verdicts, pool failover, "
                             "and the autoscaler scaling out AND in")
    parser.add_argument("--elastic-out-depth", type=float, default=3.0,
                        help="elastic mode: the autoscaler's scale-out "
                             "queue-depth threshold "
                             "(GETHSHARDING_AUTOSCALE_OUT_DEPTH for "
                             "the spawned frontend)")
    parser.add_argument("--light-clients", type=int, default=0,
                        help="> 0: run the LIGHT-CLIENT soak — this many "
                             "threads drive 1-row das_verify_multiproofs "
                             "requests (known verdicts, tenant 'light', "
                             "interactive class) through a --replicas "
                             "fleet; exit 1 on any incorrect verdict, "
                             "hung client, or p99 SLO breach")
    parser.add_argument("--light-k", type=int, default=2,
                        help="sampled indices per light-client "
                             "multiproof row")
    parser.add_argument("--chaos-seed", type=int, default=11)
    parser.add_argument("--breaker-reset-s", type=float, default=0.5)
    parser.add_argument("--slo-interactive-ms", type=float, default=0.0,
                        help="gate: interactive p99 must stay under this "
                             "(0 = report only)")
    parser.add_argument("--slo-bulk-ms", type=float, default=0.0)
    parser.add_argument("--slo-catchup-ms", type=float, default=0.0)
    args = parser.parse_args()

    if args.elastic:
        return run_elastic(args)
    if args.frontend:
        return run_frontend(args)
    if args.light_clients > 0:
        args.replicas = max(1, args.replicas)
        return run_light_clients(args)
    if args.replicas > 0:
        return run_fleet(args)
    return run_single(args)


if __name__ == "__main__":
    raise SystemExit(main())
