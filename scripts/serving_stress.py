#!/usr/bin/env python
"""Soak driver for the serving tier — single-backend or fleet.

Default mode (unchanged since PR 1): M client threads hammer ONE
serving backend with small ecrecover requests for a fixed duration,
verifying EVERY result against the known signer (zero-divergence soak,
not just throughput), while a reporter prints one JSON stats line per
interval:

    python scripts/serving_stress.py --clients 32 --duration 30 \
        --policy shed --queue-cap 256 --flush-us 500

Fleet traffic-model mode (`--replicas N`): an in-process fleet of N
breaker-guarded serving replicas behind the shard-aware router
(gethsharding_tpu/fleet/), driven by a production-shaped load model:

- **admission-class mix** (`--classes interactive=8,bulk_audit=3,...`):
  each client thread carries a class; bulk/catchup issue multi-row
  requests, interactive issues 1-row requests and must never be shed;
- **diurnal curve** (`--diurnal-s`): the active-client fraction swings
  sinusoidally between 30% and 100% over one period — load is a wave,
  not a constant;
- **hot-shard skew** (`--hot-shard`): that fraction of catchup/bulk
  requests carries ONE affinity key, overloading a single replica the
  way a popular shard does;
- **thundering herd** (`--herd-at`): at that second every client
  pauses, then re-bursts simultaneously — the reconnect stampede;
- optional seeded chaos (`--chaos-trip`) trips replica r0's breaker
  mid-soak so the drain→probe→re-enter cycle runs under load.

Per-class p99 latencies are reported and (when `--slo-interactive-ms`
etc. are nonzero) GATED: `bench.py --fleet` runs this model with SLOs
on. Exit code 1 on any divergence, hung client, interactive shed, or
SLO breach.

Light-client traffic model (`--light-clients N`): N threads drive
1-row `das_verify_multiproofs` requests (polynomial-multiproof DAS,
das/pcs.py) through the fleet router as interactive-class traffic
under their own `light` tenant quota bucket. Every row has a KNOWN
verdict (honest openings and tampered evals interleaved), so the soak
gates on correctness — one wrong verdict fails the run — as well as
the das_light p99 when `--slo-interactive-ms` is set.

Frontend process mode (`--frontend`, with `--replicas N`): the REAL
topology — N `chain_server` replica processes, one standalone
`fleet.frontend` process balancing them (hedging armed via
`--hedge-ms`), M client threads dialing the FRONTEND over JSON-RPC.
Every answer is verified against the known signer; the summary reports
the frontend's hedge win/waste rates from `shard_fleetStatus`. Exit 1
on any divergence or hung client.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from gethsharding_tpu import metrics
from gethsharding_tpu.crypto import secp256k1 as ecdsa
from gethsharding_tpu.crypto.keccak import keccak256
from gethsharding_tpu.serving import (ServingConfig, ServingOverloadError,
                                      ServingSigBackend)
from gethsharding_tpu.sigbackend import get_backend

CLASS_MIX_DEFAULT = "interactive=8,bulk_audit=3,catchup_replay=1"
CLASS_ROWS = {"interactive": 1, "bulk_audit": 4, "catchup_replay": 8}


def build_cases(n: int):
    """n distinct (digest, sig65, expected address) rows."""
    cases = []
    for i in range(n):
        priv = int.from_bytes(keccak256(b"soak-%d" % i), "big") % ecdsa.N
        digest = keccak256(b"soak-msg-%d" % i)
        cases.append((digest, ecdsa.sign(digest, priv).to_bytes65(),
                      ecdsa.priv_to_address(priv)))
    return cases


def parse_class_mix(spec: str):
    mix = []
    for part in filter(None, (p.strip() for p in spec.split(","))):
        name, _, weight = part.partition("=")
        mix.extend([name] * int(weight or 1))
    return mix


def percentile(samples, q: float) -> float:
    if not samples:
        return 0.0
    ordered = sorted(samples)
    return ordered[min(int(q * len(ordered)), len(ordered) - 1)]


def run_single(args) -> int:
    """The original single-backend soak (PR 1 behavior, unchanged)."""
    cases = build_cases(args.cases)
    serving = ServingSigBackend(
        get_backend(args.backend),
        ServingConfig(max_batch=args.max_batch, flush_us=args.flush_us,
                      queue_cap=args.queue_cap, policy=args.policy))

    done = [0] * args.clients
    shed = [0] * args.clients
    divergences: list = []
    deadline = time.monotonic() + args.duration
    stop = threading.Event()

    def client(c: int) -> None:
        i = c  # stagger the case cycle per client
        while time.monotonic() < deadline and not stop.is_set():
            digest, sig, want = cases[i % len(cases)]
            i += args.clients
            try:
                got = serving.ecrecover_addresses([digest], [sig])
            except ServingOverloadError:
                shed[c] += 1
                continue
            if got != [want]:
                divergences.append((c, i))
                stop.set()
                return
            done[c] += 1

    threads = [threading.Thread(target=client, args=(c,), daemon=True)
               for c in range(args.clients)]
    t0 = time.monotonic()
    for t in threads:
        t.start()

    wait_timer = metrics.DEFAULT_REGISTRY.timer("serving/ecrecover/wait_time")
    last_done = 0
    while time.monotonic() < deadline and not stop.is_set():
        time.sleep(min(args.report_interval, deadline - time.monotonic())
                   if deadline > time.monotonic() else 0)
        total = sum(done)
        print(json.dumps({
            "t_s": round(time.monotonic() - t0, 1),
            "done": total,
            "rate": round((total - last_done) / args.report_interval, 1),
            "shed": sum(shed),
            "dispatches": serving.dispatch_count,
            "coalesce_ratio": round(total / max(1, serving.dispatch_count),
                                    1),
            "queue_depth": serving.batcher.queue_depth_rows(
                "ecrecover_addresses"),
            "wait_p50_ms": round(wait_timer.percentile(0.5) * 1e3, 2),
        }), flush=True)
        last_done = total

    for t in threads:
        t.join(timeout=30)
    hung = [t for t in threads if t.is_alive()]
    wall = time.monotonic() - t0
    serving.close()

    total = sum(done)
    print(json.dumps({
        "summary": True,
        "clients": args.clients,
        "policy": args.policy,
        "wall_s": round(wall, 2),
        "done": total,
        "rate": round(total / wall, 1) if wall else 0.0,
        "shed": sum(shed),
        "dispatches": serving.dispatch_count,
        "coalesce_ratio": round(total / max(1, serving.dispatch_count), 1),
        "divergences": len(divergences),
        "hung_clients": len(hung),
    }), flush=True)
    return 1 if divergences or hung else 0


def build_fleet(args):
    """N breaker-guarded serving replicas behind the shard router; r0
    optionally carries a seeded chaos schedule that trips its breaker
    mid-soak."""
    from gethsharding_tpu.fleet import FleetRouter, Replica, RouterSigBackend
    from gethsharding_tpu.resilience.breaker import (CircuitBreaker,
                                                     FailoverSigBackend)
    from gethsharding_tpu.resilience.chaos import (ChaosSchedule,
                                                   ChaosSigBackend)

    servings, replicas, schedule = [], [], None
    for i in range(args.replicas):
        inner = get_backend(args.backend)
        if i == 0 and args.chaos_trip > 0:
            start = args.chaos_trip
            schedule = ChaosSchedule(
                seed=args.chaos_seed,
                rules={"backend.ecrecover_addresses":
                       lambda idx, start=start: start <= idx < start + 8})
            inner = ChaosSigBackend(inner, schedule)
        serving = ServingSigBackend(
            inner,
            ServingConfig(max_batch=args.max_batch, flush_us=args.flush_us,
                          queue_cap=args.queue_cap, policy=args.policy))
        servings.append(serving)
        replicas.append(Replica(
            f"r{i}",
            FailoverSigBackend(
                serving, get_backend("python"),
                breaker=CircuitBreaker(name=f"soak-r{i}",
                                       fault_threshold=3,
                                       reset_s=args.breaker_reset_s))))
    router = FleetRouter(replicas, health_interval_s=0.05)
    return router, RouterSigBackend(router), servings, replicas, schedule


def run_fleet(args) -> int:
    from gethsharding_tpu.fleet import AllReplicasDraining
    from gethsharding_tpu.serving.classes import CLASS_INTERACTIVE

    router, back, servings, replicas, schedule = build_fleet(args)
    cases = build_cases(args.cases)
    mix = parse_class_mix(args.classes)
    lat = {name: [] for name in CLASS_ROWS}
    done = {name: 0 for name in CLASS_ROWS}
    shed = {name: 0 for name in CLASS_ROWS}
    divergences: list = []
    stop = threading.Event()
    t0 = time.monotonic()
    deadline = t0 + args.duration
    herd_gate = threading.Event()
    herd_gate.set()

    def active_fraction(now: float) -> float:
        if args.diurnal_s <= 0:
            return 1.0
        phase = 2 * math.pi * ((now - t0) % args.diurnal_s) / args.diurnal_s
        return 0.65 + 0.35 * math.sin(phase)  # 30%..100%

    def client(c: int) -> None:
        klass = mix[c % len(mix)]
        rows = CLASS_ROWS[klass]
        rng_i = c
        while time.monotonic() < deadline and not stop.is_set():
            herd_gate.wait()
            # diurnal gating: clients beyond the active fraction sleep
            if (c / max(1, args.clients)) > active_fraction(
                    time.monotonic()):
                time.sleep(0.01)
                continue
            batch = [cases[(rng_i + j) % len(cases)] for j in range(rows)]
            rng_i += rows * args.clients
            # hot-shard skew applies to the bulk planes
            affinity = None
            if klass != CLASS_INTERACTIVE \
                    and (rng_i % 100) < args.hot_shard * 100:
                affinity = "hot-shard"
            t_req = time.monotonic()
            try:
                got = router.call("ecrecover_addresses",
                                  [b[0] for b in batch],
                                  [b[1] for b in batch],
                                  affinity=affinity, klass=klass)
            except (ServingOverloadError, AllReplicasDraining):
                shed[klass] += 1
                continue
            lat[klass].append(time.monotonic() - t_req)
            if got != [b[2] for b in batch]:
                divergences.append((c, rng_i))
                stop.set()
                return
            done[klass] += 1
            if klass == CLASS_INTERACTIVE:
                time.sleep(0.001)

    threads = [threading.Thread(target=client, args=(c,), daemon=True)
               for c in range(args.clients)]
    for t in threads:
        t.start()

    herd_done = args.herd_at <= 0
    last_report = t0
    while time.monotonic() < deadline and not stop.is_set():
        time.sleep(0.1)
        now = time.monotonic()
        if not herd_done and now - t0 >= args.herd_at:
            # thundering herd: everyone disconnects, then re-bursts at
            # the same instant
            herd_gate.clear()
            time.sleep(args.herd_pause_s)
            herd_gate.set()
            herd_done = True
            print(json.dumps({"herd": True, "t_s": round(now - t0, 1)}),
                  flush=True)
        if now - last_report >= args.report_interval:
            last_report = now
            print(json.dumps({
                "t_s": round(now - t0, 1),
                "active_fraction": round(active_fraction(now), 2),
                "done": dict(done),
                "shed": dict(shed),
                "states": {name: state["state"]
                           for name, state in router.states().items()},
            }), flush=True)

    for t in threads:
        t.join(timeout=60)
    hung = [t for t in threads if t.is_alive()]
    stop.set()

    # let a tripped replica finish its probe-driven re-entry
    reentered = True
    if schedule is not None:
        reentry_deadline = time.monotonic() + 10
        while replicas[0].state != "healthy" \
                and time.monotonic() < reentry_deadline:
            router.refresh(force=True)
            time.sleep(0.05)
        reentered = replicas[0].state == "healthy"

    shed_by_class = {name: 0 for name in CLASS_ROWS}
    for serving in servings:
        for klass, count in serving.batcher.shed_by_class().items():
            shed_by_class[klass] += count
    p99_ms = {name: round(percentile(samples, 0.99) * 1e3, 2)
              for name, samples in lat.items()}
    slo = {"interactive": args.slo_interactive_ms,
           "bulk_audit": args.slo_bulk_ms,
           "catchup_replay": args.slo_catchup_ms}
    slo_breaches = [name for name, limit in slo.items()
                    if limit > 0 and p99_ms[name] > limit]

    summary = {
        "summary": True,
        "fleet": True,
        "replicas": args.replicas,
        "clients": args.clients,
        "wall_s": round(time.monotonic() - t0, 2),
        "done": dict(done),
        "caller_shed": dict(shed),
        "replica_shed_by_class": shed_by_class,
        "p99_ms": p99_ms,
        "slo_ms": slo,
        "slo_breaches": slo_breaches,
        "divergences": len(divergences),
        "hung_clients": len(hung),
        "interactive_shed": shed["interactive"]
        + shed_by_class["interactive"],
        "drain_events": replicas[0].drain_events,
        "reentries": replicas[0].reentries,
        "chaos_injected": (0 if schedule is None else
                           schedule.injected.get(
                               "backend.ecrecover_addresses", 0)),
        "reentered": reentered,
        "states": {name: state["state"]
                   for name, state in router.states().items()},
    }
    print(json.dumps(summary), flush=True)
    for serving in servings:
        serving.close()

    failed = bool(divergences or hung or slo_breaches
                  or summary["interactive_shed"]
                  or (schedule is not None
                      and (summary["drain_events"] < 1 or not reentered)))
    return 1 if failed else 0


def build_poly_cases(n_cases: int, k: int):
    """Known-verdict multiproof rows: honest openings (expected True)
    interleaved with tampered evals (expected False) — a light-client
    check whose CORRECTNESS the soak verifies on every response, not
    just its latency."""
    import random as _random

    from gethsharding_tpu.das import pcs

    rng = _random.Random(7)
    cases = []
    for i in range(n_cases):
        n = 12
        values = [rng.randrange(pcs.N) for _ in range(n)]
        indices = sorted(rng.sample(range(n), min(k, n)))
        proof, evals = pcs.open_multi(values, indices)
        commitment = pcs.g1_to_bytes(pcs.commit(values))
        proof_bytes = pcs.g1_to_bytes(proof)
        cases.append((commitment, indices, evals, proof_bytes, n, True))
        if i % 2:
            bad = list(evals)
            bad[0] = (bad[0] + 1) % pcs.N
            cases.append((commitment, indices, bad, proof_bytes, n,
                          False))
    return cases


def run_light_clients(args) -> int:
    """The light-client sampling tier under load: M client threads
    drive 1-row `das_verify_multiproofs` requests through the fleet
    router as INTERACTIVE traffic under their own tenant quota bucket
    (`tenant="light"`), every verdict checked against the known truth.
    Gates: zero incorrect verdicts, zero hung clients, and (when
    `--slo-interactive-ms` is nonzero) the das_light p99. Latencies
    also feed the process `das_light` SLO objective (slo/tracker.py),
    so /status on a long-lived node shows the same series."""
    from gethsharding_tpu import slo
    from gethsharding_tpu.fleet import AllReplicasDraining

    router, _back, servings, _replicas, _schedule = build_fleet(args)
    cases = build_poly_cases(args.cases if args.cases <= 16 else 8,
                             args.light_k)
    lat: list = []
    done = [0]
    incorrect: list = []
    shed = [0]
    stop = threading.Event()
    t0 = time.monotonic()
    deadline = t0 + args.duration

    def client(c: int) -> None:
        i = c
        while time.monotonic() < deadline and not stop.is_set():
            commitment, indices, evals, proof, n, want = \
                cases[i % len(cases)]
            i += args.light_clients
            t_req = time.monotonic()
            try:
                got = router.call("das_verify_multiproofs",
                                  [commitment], [indices], [evals],
                                  [proof], [n],
                                  affinity=commitment.hex(),
                                  klass="interactive", tenant="light")
            except (ServingOverloadError, AllReplicasDraining):
                shed[0] += 1
                slo.record("das_light", ok=False)
                continue
            elapsed = time.monotonic() - t_req
            lat.append(elapsed)
            slo.record("das_light", ok=got == [want],
                       latency_s=elapsed)
            if got != [want]:
                incorrect.append((c, i, got, want))
                stop.set()
                return
            done[0] += 1

    threads = [threading.Thread(target=client, args=(c,), daemon=True)
               for c in range(args.light_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=args.duration + 60)
    hung = [t for t in threads if t.is_alive()]
    stop.set()
    wall = time.monotonic() - t0

    quota_rejections = sum(s.batcher.quota_rejections()
                           for s in servings)
    p99_ms = round(percentile(lat, 0.99) * 1e3, 2)
    slo_breach = bool(args.slo_interactive_ms > 0
                      and p99_ms > args.slo_interactive_ms)
    summary = {
        "summary": True,
        "light_clients": args.light_clients,
        "replicas": args.replicas,
        "wall_s": round(wall, 2),
        "done": done[0],
        "rate": round(done[0] / wall, 2) if wall else 0.0,
        "shed": shed[0],
        "quota_rejections": quota_rejections,
        "p99_ms": p99_ms,
        "slo_ms": args.slo_interactive_ms,
        "slo_breach": slo_breach,
        "incorrect_verdicts": len(incorrect),
        "hung_clients": len(hung),
    }
    print(json.dumps(summary), flush=True)
    for serving in servings:
        serving.close()
    return 1 if incorrect or hung or slo_breach else 0


def _spawn(cmd, env=None):
    import subprocess

    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                            stderr=subprocess.DEVNULL, text=True,
                            env=env or os.environ.copy())
    line = proc.stdout.readline().strip()
    if not line:
        proc.terminate()
        raise RuntimeError(f"{cmd[:4]}... printed no address line")
    addr = json.loads(line)
    return proc, addr


def run_frontend(args) -> int:
    """The cross-process topology soak: N chain_server replicas + ONE
    standalone frontend process, clients dialing the frontend."""
    from gethsharding_tpu.rpc import codec
    from gethsharding_tpu.rpc.client import RPCClient, RPCError

    n = max(2, args.replicas)
    env = {**os.environ}
    env.setdefault("JAX_PLATFORMS", "cpu")
    replicas, endpoints = [], []
    frontend = None
    try:
        for _ in range(n):
            proc, addr = _spawn(
                [sys.executable, "-m", "gethsharding_tpu.rpc.chain_server",
                 "--sigbackend", "python", "--verbosity", "error"],
                env=env)
            replicas.append(proc)
            endpoints.append("%s:%d" % (addr["host"], addr["port"]))
        fe_cmd = [sys.executable, "-m", "gethsharding_tpu.fleet.frontend",
                  "--verbosity", "error",
                  "--health-interval", "0.1",
                  "--fleet-hedge-ms", str(args.hedge_ms)]
        for endpoint in endpoints:
            fe_cmd += ["--replica", endpoint]
        frontend, fe_addr = _spawn(fe_cmd, env=env)

        cases = build_cases(args.cases)
        done = [0] * args.clients
        divergences: list = []
        typed_errors = [0]
        stop = threading.Event()
        deadline = time.monotonic() + args.duration

        def client(c: int) -> None:
            rpc = RPCClient(fe_addr["host"], fe_addr["port"])
            i = c
            try:
                while time.monotonic() < deadline and not stop.is_set():
                    digest, sig, want = cases[i % len(cases)]
                    i += args.clients
                    try:
                        got = rpc.call("shard_ecrecover",
                                       [codec.enc_bytes(digest)],
                                       [codec.enc_bytes(sig)])
                    except RPCError:
                        typed_errors[0] += 1
                        continue
                    if got != [codec.enc_bytes(want)]:
                        divergences.append((c, i))
                        stop.set()
                        return
                    done[c] += 1
            finally:
                rpc.close()

        threads = [threading.Thread(target=client, args=(c,), daemon=True)
                   for c in range(args.clients)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=args.duration + 60)
        hung = [t for t in threads if t.is_alive()]
        wall = time.monotonic() - t0

        status_rpc = RPCClient(fe_addr["host"], fe_addr["port"])
        status = status_rpc.call("shard_fleetStatus")
        status_rpc.close()
        hedge = status["hedge"]
        total = sum(done)
        dispatches = total + hedge["issued"]
        summary = {
            "summary": True,
            "frontend": True,
            "replicas": n,
            "clients": args.clients,
            "wall_s": round(wall, 2),
            "done": total,
            "rate": round(total / wall, 1) if wall else 0.0,
            "typed_errors": typed_errors[0],
            "divergences": len(divergences),
            "hung_clients": len(hung),
            "hedge": hedge,
            "hedge_win_rate": round(
                hedge["won"] / max(1, hedge["issued"]), 3),
            "hedge_waste_rate": round(
                hedge["wasted"] / max(1, dispatches), 3),
            "replica_states": {name: s["state"]
                               for name, s in status["replicas"].items()},
        }
        print(json.dumps(summary), flush=True)
        return 1 if divergences or hung else 0
    finally:
        if frontend is not None:
            frontend.terminate()
        for proc in replicas:
            proc.terminate()


def main() -> int:
    parser = argparse.ArgumentParser(
        description="soak the serving tier (single backend or fleet)")
    parser.add_argument("--clients", type=int, default=16)
    parser.add_argument("--duration", type=float, default=10.0,
                        help="seconds of offered load")
    parser.add_argument("--backend", default="python",
                        choices=("python", "jax"),
                        help="wrapped backend (jax needs an accelerator)")
    parser.add_argument("--max-batch", type=int, default=128)
    parser.add_argument("--flush-us", type=float, default=500.0)
    parser.add_argument("--queue-cap", type=int, default=4096)
    parser.add_argument("--policy", default="block",
                        choices=("block", "shed"))
    parser.add_argument("--report-interval", type=float, default=2.0)
    parser.add_argument("--cases", type=int, default=256,
                        help="distinct signed rows cycled by the clients")
    # -- fleet traffic model ------------------------------------------------
    parser.add_argument("--replicas", type=int, default=0,
                        help="> 0: run the FLEET soak — this many "
                             "breaker-guarded serving replicas behind "
                             "the shard router (gethsharding_tpu/fleet/)")
    parser.add_argument("--classes", default=CLASS_MIX_DEFAULT,
                        help="admission-class client mix, e.g. "
                             "'interactive=8,bulk_audit=3,"
                             "catchup_replay=1'")
    parser.add_argument("--diurnal-s", type=float, default=0.0,
                        help="sinusoidal load period in seconds (0 = "
                             "flat load): active clients swing 30%%-100%%")
    parser.add_argument("--hot-shard", type=float, default=0.0,
                        help="fraction of bulk/catchup requests keyed to "
                             "ONE hot affinity (0..1)")
    parser.add_argument("--herd-at", type=float, default=0.0,
                        help="seconds into the soak to fire a thundering-"
                             "herd reconnect burst (0 = off)")
    parser.add_argument("--herd-pause-s", type=float, default=0.3,
                        help="how long the herd holds its breath")
    parser.add_argument("--chaos-trip", type=int, default=0,
                        help="> 0: seed a chaos run of 8 consecutive "
                             "device faults on replica r0 starting at "
                             "this dispatch index — trips its breaker "
                             "mid-soak")
    parser.add_argument("--frontend", action="store_true",
                        help="cross-process mode: spawn --replicas N "
                             "chain_server processes plus ONE standalone "
                             "fleet.frontend process and drive traffic "
                             "through the frontend over JSON-RPC, "
                             "reporting hedge win/waste rates")
    parser.add_argument("--hedge-ms", type=float, default=15.0,
                        help="frontend mode: the frontend's "
                             "--fleet-hedge-ms floor")
    parser.add_argument("--light-clients", type=int, default=0,
                        help="> 0: run the LIGHT-CLIENT soak — this many "
                             "threads drive 1-row das_verify_multiproofs "
                             "requests (known verdicts, tenant 'light', "
                             "interactive class) through a --replicas "
                             "fleet; exit 1 on any incorrect verdict, "
                             "hung client, or p99 SLO breach")
    parser.add_argument("--light-k", type=int, default=2,
                        help="sampled indices per light-client "
                             "multiproof row")
    parser.add_argument("--chaos-seed", type=int, default=11)
    parser.add_argument("--breaker-reset-s", type=float, default=0.5)
    parser.add_argument("--slo-interactive-ms", type=float, default=0.0,
                        help="gate: interactive p99 must stay under this "
                             "(0 = report only)")
    parser.add_argument("--slo-bulk-ms", type=float, default=0.0)
    parser.add_argument("--slo-catchup-ms", type=float, default=0.0)
    args = parser.parse_args()

    if args.frontend:
        return run_frontend(args)
    if args.light_clients > 0:
        args.replicas = max(1, args.replicas)
        return run_light_clients(args)
    if args.replicas > 0:
        return run_fleet(args)
    return run_single(args)


if __name__ == "__main__":
    raise SystemExit(main())
