#!/usr/bin/env python
"""Soak driver for the verification serving tier.

M client threads hammer the serving backend with small ecrecover
requests for a fixed duration, verifying EVERY result against the
known signer (zero-divergence soak, not just throughput), while a
reporter prints one JSON stats line per interval:

    python scripts/serving_stress.py --clients 32 --duration 30 \
        --policy shed --queue-cap 256 --flush-us 500

What to look for:
- `rate`: served verifications/sec (coalesced) — should sit well above
  the direct-backend rate for the same client count (bench.py --serving
  reports that baseline next to it);
- `coalesce_ratio`: requests per device dispatch — the amortization;
- `shed`: with --policy shed, how much traffic the admission cap
  refused (should be zero until the offered load exceeds the device);
- `queue_depth` / `wait_p50_ms`: the backpressure state.

Exit code 1 on any result divergence or hung client.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from gethsharding_tpu import metrics
from gethsharding_tpu.crypto import secp256k1 as ecdsa
from gethsharding_tpu.crypto.keccak import keccak256
from gethsharding_tpu.serving import (ServingConfig, ServingOverloadError,
                                      ServingSigBackend)
from gethsharding_tpu.sigbackend import get_backend


def build_cases(n: int):
    """n distinct (digest, sig65, expected address) rows."""
    cases = []
    for i in range(n):
        priv = int.from_bytes(keccak256(b"soak-%d" % i), "big") % ecdsa.N
        digest = keccak256(b"soak-msg-%d" % i)
        cases.append((digest, ecdsa.sign(digest, priv).to_bytes65(),
                      ecdsa.priv_to_address(priv)))
    return cases


def main() -> int:
    parser = argparse.ArgumentParser(
        description="soak the verification serving tier")
    parser.add_argument("--clients", type=int, default=16)
    parser.add_argument("--duration", type=float, default=10.0,
                        help="seconds of offered load")
    parser.add_argument("--backend", default="python",
                        choices=("python", "jax"),
                        help="wrapped backend (jax needs an accelerator)")
    parser.add_argument("--max-batch", type=int, default=128)
    parser.add_argument("--flush-us", type=float, default=500.0)
    parser.add_argument("--queue-cap", type=int, default=4096)
    parser.add_argument("--policy", default="block",
                        choices=("block", "shed"))
    parser.add_argument("--report-interval", type=float, default=2.0)
    parser.add_argument("--cases", type=int, default=256,
                        help="distinct signed rows cycled by the clients")
    args = parser.parse_args()

    cases = build_cases(args.cases)
    serving = ServingSigBackend(
        get_backend(args.backend),
        ServingConfig(max_batch=args.max_batch, flush_us=args.flush_us,
                      queue_cap=args.queue_cap, policy=args.policy))

    done = [0] * args.clients
    shed = [0] * args.clients
    divergences: list = []
    deadline = time.monotonic() + args.duration
    stop = threading.Event()

    def client(c: int) -> None:
        i = c  # stagger the case cycle per client
        while time.monotonic() < deadline and not stop.is_set():
            digest, sig, want = cases[i % len(cases)]
            i += args.clients
            try:
                got = serving.ecrecover_addresses([digest], [sig])
            except ServingOverloadError:
                shed[c] += 1
                continue
            if got != [want]:
                divergences.append((c, i))
                stop.set()
                return
            done[c] += 1

    threads = [threading.Thread(target=client, args=(c,), daemon=True)
               for c in range(args.clients)]
    t0 = time.monotonic()
    for t in threads:
        t.start()

    wait_timer = metrics.DEFAULT_REGISTRY.timer("serving/ecrecover/wait_time")
    last_done = 0
    while time.monotonic() < deadline and not stop.is_set():
        time.sleep(min(args.report_interval, deadline - time.monotonic())
                   if deadline > time.monotonic() else 0)
        total = sum(done)
        print(json.dumps({
            "t_s": round(time.monotonic() - t0, 1),
            "done": total,
            "rate": round((total - last_done) / args.report_interval, 1),
            "shed": sum(shed),
            "dispatches": serving.dispatch_count,
            "coalesce_ratio": round(total / max(1, serving.dispatch_count),
                                    1),
            "queue_depth": serving.batcher.queue_depth_rows(
                "ecrecover_addresses"),
            "wait_p50_ms": round(wait_timer.percentile(0.5) * 1e3, 2),
        }), flush=True)
        last_done = total

    for t in threads:
        t.join(timeout=30)
    hung = [t for t in threads if t.is_alive()]
    wall = time.monotonic() - t0
    serving.close()

    total = sum(done)
    print(json.dumps({
        "summary": True,
        "clients": args.clients,
        "policy": args.policy,
        "wall_s": round(wall, 2),
        "done": total,
        "rate": round(total / wall, 1) if wall else 0.0,
        "shed": sum(shed),
        "dispatches": serving.dispatch_count,
        "coalesce_ratio": round(total / max(1, serving.dispatch_count), 1),
        "divergences": len(divergences),
        "hung_clients": len(hung),
    }), flush=True)
    return 1 if divergences or hung else 0


if __name__ == "__main__":
    raise SystemExit(main())
