"""Rebuild the autotune cache from the watcher's per-config TPU probes.

The tunnel-window experiments (scripts/tpu_experiments/*_cfg_*.sh) each
run `bench.py --single` under one knob configuration and leave a stats
JSON (with `knobs` since r3) in .tpu_results/<name>_<ts>.out. This
picks the fastest TPU-platform probe and writes .bench_autotune.json
with the CURRENT sweep fingerprint, so the next full `bench.py` run
(the driver's end-of-round invocation, or 89_finalize's) goes straight
to the winner + extras instead of re-sweeping.

Prints the chosen config; exits 1 when no TPU probe exists.
"""

from __future__ import annotations

import glob
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402


def main() -> int:
    best = None
    for path in glob.glob(os.path.join(bench.REPO, ".tpu_results",
                                       "*_cfg_*.out")):
        try:
            with open(path) as fh:
                lines = fh.read().strip().splitlines()
            mtime = os.path.getmtime(path)
        except OSError:
            continue
        if time.time() - mtime > 24 * 3600:
            continue  # stale probe from an earlier round / older code
        for line in reversed(lines):
            try:
                stats = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(stats, dict) and "sig_rate" in stats:
                break
        else:
            continue
        if not str(stats.get("platform", "")).startswith(("tpu", "axon")):
            continue
        if "knobs" not in stats:
            continue  # an empty config would masquerade as the champion
        if best is None or stats["sig_rate"] > best[0]["sig_rate"]:
            best = (stats, path)
    if best is None:
        print("no TPU probe results found", file=sys.stderr)
        return 1
    stats, path = best
    payload = {"config": stats["knobs"], "platform": stats["platform"],
               "sweep": bench._sweep_fingerprint()}
    try:
        with open(bench._cache_path()) as fh:
            cached = json.load(fh)
        if cached.get("sweep") == payload["sweep"]:
            # keep bench.py's negative cache of known-fatal configs
            payload["failed"] = cached.get("failed", [])
    except (OSError, ValueError):
        pass
    with open(bench._cache_path(), "w") as fh:
        json.dump(payload, fh)
    print(json.dumps({"winner": stats["knobs"], "sig_rate": stats["sig_rate"],
                      "from": os.path.basename(path)}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
