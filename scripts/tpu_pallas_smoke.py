"""Minute-one Mosaic validation on the live backend.

The Pallas kernels (ops/pallas_norm, ops/pallas_conv) are fully
differentially tested in interpreter mode, but whether Mosaic compiles
and runs them CORRECTLY on this backend (TPU v5 lite behind the axon
tunnel) has never been witnessed — and the round-4 mega-kernel plan
stands on them. This probe runs each kernel COMPILED (interpret=False)
on tiny shapes against the XLA path and prints ONE JSON line with a
per-kernel ok/error so a single short tunnel window settles the
question (VERDICT r3 "What's weak" #4).
"""

from __future__ import annotations

import json
import os
import sys
import time
import traceback

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

P_BN = 21888242871839275222246405745257275088696311157297823662689037894645226208583


def main() -> int:
    from gethsharding_tpu.parallel.virtual import configure_compile_cache

    configure_compile_cache()

    import jax
    import jax.numpy as jnp

    from gethsharding_tpu.ops import limb

    platform = jax.devices()[0].platform
    rng = np.random.default_rng(41)
    out = {"platform": platform, "kernels": {}}

    def run(name, fn):
        t0 = time.perf_counter()
        try:
            ok = bool(fn())
            out["kernels"][name] = {"ok": ok,
                                    "wall_s": round(time.perf_counter() - t0,
                                                    2)}
        except Exception:
            out["kernels"][name] = {
                "ok": False,
                "wall_s": round(time.perf_counter() - t0, 2),
                "error": traceback.format_exc()[-800:]}

    def norm_probe():
        from gethsharding_tpu.ops.pallas_norm import (BLOCK_ROWS,
                                                      normalize_pallas)

        arith = limb.ModArith(P_BN)
        z = rng.integers(0, 1 << 28, (BLOCK_ROWS, 49)).astype(np.int32)
        want = np.asarray(arith.normalize(jnp.asarray(z)))
        got = np.asarray(normalize_pallas(arith, jnp.asarray(z)))
        return (want == got).all()

    def conv_probe():
        from gethsharding_tpu.ops import bn256_jax as k
        from gethsharding_tpu.ops.pallas_conv import pair_conv_combine

        def xla_ref(x, y, comb):
            prod = x[..., :, :, None, :, None] * y[..., :, None, :, None, :]
            cols = limb.conv_cols(prod)
            return jnp.einsum("...iabn,iabcg->...cgn", cols,
                              jnp.asarray(comb))

        ok = True
        for comb in (k._COMB, k._LCOMB):
            G, A, B, _, _ = comb.shape
            x = rng.integers(0, 1 << 12,
                             (8, G, A, limb.NLIMBS)).astype(np.int32)
            y = rng.integers(0, 1 << 12,
                             (8, G, B, limb.NLIMBS)).astype(np.int32)
            want = np.asarray(xla_ref(jnp.asarray(x), jnp.asarray(y), comb))
            got = np.asarray(pair_conv_combine(
                jnp.asarray(x), jnp.asarray(y), comb))
            ok = ok and (want == got).all()
        return ok

    run("pallas_norm", norm_probe)
    run("pallas_conv", conv_probe)
    print(json.dumps(out))
    # exit 0 whenever the question was ANSWERED on a real accelerator —
    # a Mosaic failure is exactly the evidence this probe exists to
    # collect, so it must not be retried as if the run were lost; only a
    # CPU fallback (dead tunnel) counts as "no result"
    return 1 if platform == "cpu" else 0


if __name__ == "__main__":
    sys.exit(main())
