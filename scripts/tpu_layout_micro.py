"""Micro-benchmark: int32 elementwise + carry-scan throughput by layout.

The pairing kernels keep the limb axis (22) minor and the batch (100)
major — on TPU the minor axis maps to the 128 VPU lanes and the
second-minor to 8 sublanes, so (100, ..., 22) uses ~22/128 lanes x 2/8
sublanes. This measures the SAME op chains at limbs-minor vs
batch-minor layouts to quantify what a layout refactor of the limb
engine would buy on the real chip. Prints ONE JSON line.

Chains modeled on the hot path: (a) a 200-op mul/add/shift/mask chain
(normalize-ish work), (b) a 22-step sequential carry as lax.scan vs
statically unrolled.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
from jax import lax

LIMB_MASK = 0xFFF


def chain200(x):
    for _ in range(200):
        x = ((x * 3 + 5) >> 2) & LIMB_MASK
    return x


def carry_scan(z, axis):
    zs = jnp.moveaxis(z, axis, 0)

    def step(c, v):
        t = v + c
        return t >> 12, t & LIMB_MASK

    carry, out = lax.scan(step, zs[0] * 0, zs)
    return jnp.moveaxis(out, 0, axis)


def carry_unroll(z, axis):
    zs = jnp.moveaxis(z, axis, 0)
    c = zs[0] * 0
    outs = []
    for i in range(zs.shape[0]):
        t = zs[i] + c
        c = t >> 12
        outs.append(t & LIMB_MASK)
    return jnp.moveaxis(jnp.stack(outs), 0, axis)


def _time(fn, x, repeats=20):
    out = fn(x)
    out.block_until_ready()
    t0 = time.perf_counter()
    for _ in range(repeats):
        out = fn(x)
    out.block_until_ready()
    return (time.perf_counter() - t0) / repeats


def main() -> int:
    if "--cpu" in sys.argv:
        from gethsharding_tpu.parallel.virtual import force_virtual_cpu_devices

        force_virtual_cpu_devices(1)
    rng = np.random.default_rng(5)
    results = {}
    # pairing-stage shape: B=100 rows of fp12 (12 coeffs x 22 limbs);
    # aggregate-stage shape: 13500 rows of one Fp element
    cases = {
        "pair_limbs_minor": (100, 12, 22),
        "pair_batch_minor": (12, 22, 100),
        "pair_batch_minor_pad128": (12, 22, 128),
        "agg_limbs_minor": (13500, 22),
        "agg_batch_minor": (22, 13504),
    }
    for name, shape in cases.items():
        x = jnp.asarray(rng.integers(0, LIMB_MASK, shape, dtype=np.int32))
        limb_axis = -1 if "limbs_minor" in name else (-2 if name.startswith("pair") else 0)
        results[name] = {
            "chain200_s": round(_time(jax.jit(chain200), x), 6),
            "carry_scan_s": round(_time(
                jax.jit(lambda v, a=limb_axis: carry_scan(v, a)), x), 6),
            "carry_unroll_s": round(_time(
                jax.jit(lambda v, a=limb_axis: carry_unroll(v, a)), x), 6),
            "elements": int(np.prod(shape)),
        }
    print(json.dumps({"platform": jax.devices()[0].platform,
                      "cases": results}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
