#!/usr/bin/env python
"""One-shot migration of the historical perf record into the perfwatch
ledger: ``BENCH_r01–r05.json`` (driver round artifacts) plus
``bench_results/*.json`` (the tunnel watcher's live TPU captures) become
ledger records, so the regression baseline starts from the REAL
measured history instead of an empty file.

Input shapes handled:

- driver artifacts: ``{"n": round, "cmd": ..., "rc": ..., "tail": ...,
  "parsed": {metric, value, unit, vs_baseline, extra?}}`` — the parsed
  metric line is the record, the round number becomes ``round``;
- capture files: the bare ``{metric, value, unit, vs_baseline, extra}``
  line shape `bench.py` prints.

Timestamps come from ``extra.captured_at`` when embedded (the honest
provenance stamp), else the file's mtime. Records that would duplicate
an already-imported measurement (same metric, value and capture stamp —
BENCH_r05 re-reports r04's capture, and the capture files are the same
runs) are skipped, as are records already present in the target ledger,
so the import is idempotent.

Usage::

    python scripts/ledger_import.py [--ledger PATH] [--dry-run]

then ``python -m gethsharding_tpu.perfwatch --check --report`` renders
the measured-history table (the machine-generated twin of PERF.md's
hand-kept one) from what landed.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from gethsharding_tpu.perfwatch.ledger import (  # noqa: E402
    Ledger, build_record)


def _parse_ts(extra: dict, path: str) -> float:
    stamp = (extra or {}).get("captured_at")
    if stamp:
        try:
            return time.mktime(time.strptime(stamp, "%Y-%m-%d %H:%M:%S"))
        except ValueError:
            pass
    return os.path.getmtime(path)


def _to_record(parsed: dict, path: str, round_n=None) -> "dict | None":
    if not isinstance(parsed, dict) or "metric" not in parsed \
            or "value" not in parsed:
        return None
    if not isinstance(parsed["value"], (int, float)):
        return None
    extra = parsed.get("extra") or {}
    # ONE schema adapter (perfwatch.ledger.build_record) — the importer
    # must never re-implement the extras-splitting rules, or imported
    # history would drift from live records
    rec = build_record(
        metric=parsed["metric"], value=parsed["value"],
        unit=parsed.get("unit"), vs_baseline=parsed.get("vs_baseline"),
        extra=extra, source="import")
    if not isinstance(extra.get("knobs"), dict):
        # a stamp-less historical record must NOT inherit the importing
        # process's current knob env (build_record's live default)
        rec["knobs"] = {}
    ts_unix = _parse_ts(extra, path)
    rec["ts_unix"] = ts_unix
    rec["ts"] = time.strftime("%Y-%m-%d %H:%M:%S",
                              time.localtime(ts_unix))
    if round_n is not None:
        rec["extra"]["round"] = round_n
    rec["extra"]["imported_from"] = os.path.relpath(path, REPO)
    return rec


def _fingerprint(rec: dict) -> tuple:
    base = (rec.get("workload"), rec.get("metrics", {}).get("value"))
    extra = rec.get("extra") or {}
    if extra.get("captured_at"):
        # embedded stamp: stable across checkouts, and SHARED by a
        # round that re-reports an earlier round's capture — exactly
        # the cross-file dedup the provenance stamp exists for
        return base + (rec.get("ts"),)
    # stamp-less history (cpu-era rounds, the retired MULTICHIP
    # snapshots): mtime is checkout-fragile — a fresh checkout resets
    # it, and a ts-keyed fingerprint would re-import every stamp-less
    # record as "new". The source file itself is the stable identity:
    # one ledger record per imported file, idempotent forever.
    return base + (extra.get("imported_from"),)


def collect() -> list:
    records = []
    for path in sorted(glob.glob(os.path.join(REPO, "BENCH_r*.json"))):
        try:
            data = json.load(open(path))
        except (OSError, ValueError) as exc:
            print(f"# skipping {path}: {exc!r}", file=sys.stderr)
            continue
        rec = _to_record(data.get("parsed"), path, round_n=data.get("n"))
        if rec is None:
            print(f"# skipping {path}: no parsed metric line",
                  file=sys.stderr)
            continue
        records.append(rec)
    for path in sorted(glob.glob(os.path.join(REPO,
                                              "MULTICHIP_r0*.json"))):
        # the retired multi-chip dryrun snapshots (PR 18 made
        # tests/test_multichip_dryrun.py the evidence path): the
        # rc/ok/tail contract folds into the ledger as the
        # `multichip_dryrun` workload, value = device count, so the
        # classifier test keeps real recorded tails to chew on after
        # the root JSON files are deleted
        try:
            data = json.load(open(path))
        except (OSError, ValueError) as exc:
            print(f"# skipping {path}: {exc!r}", file=sys.stderr)
            continue
        if not isinstance(data, dict) or "n_devices" not in data:
            print(f"# skipping {path}: not a dryrun snapshot",
                  file=sys.stderr)
            continue
        stem = os.path.basename(path)[len("MULTICHIP_r"):].split(".")[0]
        try:
            round_n = int(stem)
        except ValueError:
            round_n = None
        rec = _to_record(
            {"metric": "multichip_dryrun_devices",
             "value": data.get("n_devices"),
             "unit": "devices (dryrun_multichip child snapshot: rc/ok "
                     "ride as metrics/extra, stderr tail verbatim)",
             "extra": {"rc": data.get("rc"),
                       "ok": bool(data.get("ok")),
                       "skipped": bool(data.get("skipped")),
                       "tail": data.get("tail", "")}},
            path, round_n=round_n)
        if rec is None:
            print(f"# skipping {path}: malformed snapshot",
                  file=sys.stderr)
            continue
        rec["workload"] = "multichip_dryrun"
        records.append(rec)
    for path in sorted(glob.glob(os.path.join(REPO, "bench_results",
                                              "*.json"))):
        try:
            data = json.load(open(path))
        except (OSError, ValueError) as exc:
            print(f"# skipping {path}: {exc!r}", file=sys.stderr)
            continue
        rec = _to_record(data, path)
        if rec is None:
            print(f"# skipping {path}: not a metric line", file=sys.stderr)
            continue
        records.append(rec)
    records.sort(key=lambda r: r["ts_unix"])
    # dedup: a capture re-reported by a later round is ONE measurement
    seen, unique = set(), []
    for rec in records:
        fp = _fingerprint(rec)
        if fp in seen:
            print(f"# dedup: {rec['extra']['imported_from']} repeats "
                  f"{fp[0]}={fp[1]} @ {fp[2]}", file=sys.stderr)
            continue
        seen.add(fp)
        unique.append(rec)
    return unique


def main() -> int:
    parser = argparse.ArgumentParser(
        description="import BENCH_r*/bench_results history into the "
                    "perfwatch ledger")
    parser.add_argument("--ledger", default=None, metavar="PATH",
                        help="target ledger (default: the perfwatch "
                             "default path)")
    parser.add_argument("--dry-run", action="store_true",
                        help="print what would be appended, write nothing")
    args = parser.parse_args()
    ledger = Ledger(args.ledger)
    existing = {_fingerprint(rec) for rec in ledger.records()}
    records = [rec for rec in collect()
               if _fingerprint(rec) not in existing]
    for rec in records:
        print(f"{rec['ts']}  {rec['workload']:44s} "
              f"{rec['metrics']['value']:>12g}  "
              f"[{rec.get('platform') or 'cpu-era'}] "
              f"<- {rec['extra']['imported_from']}")
        if not args.dry_run:
            ledger.append(rec)
    verb = "would import" if args.dry_run else "imported"
    print(f"# {verb} {len(records)} record(s) into {ledger.path}",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
