#!/usr/bin/env python
"""Probe acceptance gate: the measurement LANDED, and it is VALID.

The tpu_experiments probes used to pass on a stdout grep alone — a run
whose record never reached the perfwatch benchmark ledger (or reached
it stamped ``valid: false`` because the device-timer self-check fired
mid-measurement, the r4 block_until_ready no-op hazard) still counted
as green. This gate closes that: a probe passes only when the NEWEST
ledger record for its workload exists, is stamped valid, and was
written by this run (``--max-age`` seconds, default one day).

Usage: probe_ledger_check.py WORKLOAD [--max-age SECONDS]

Reads the same ledger the bench emitter writes
(``GETHSHARDING_PERFWATCH_LEDGER`` or ./perf_ledger.jsonl).
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    max_age = 24 * 3600.0
    if "--max-age" in args:
        i = args.index("--max-age")
        max_age = float(args[i + 1])
        del args[i:i + 2]
    if len(args) != 1:
        print(__doc__, file=sys.stderr)
        return 2
    workload = args[0]

    from gethsharding_tpu.perfwatch.ledger import Ledger

    ledger = Ledger()
    recs = ledger.records(workload=workload)
    if not recs:
        print(f"probe_ledger_check: no {workload!r} record in "
              f"{ledger.path} — the probe's emit never landed",
              file=sys.stderr)
        return 1
    rec = recs[-1]
    age = time.time() - float(rec.get("ts_unix", 0))
    if age > max_age:
        print(f"probe_ledger_check: newest {workload!r} record is "
              f"{age / 3600:.1f}h old (> {max_age / 3600:.1f}h) — this "
              f"run's emit never landed", file=sys.stderr)
        return 1
    if rec.get("valid") is False:
        print(f"probe_ledger_check: newest {workload!r} record is "
              f"stamped INVALID (device-timer self-check fired "
              f"{rec.get('suspects')} time(s) during the measurement): "
              f"{rec.get('metrics')}", file=sys.stderr)
        return 1
    print(f"probe_ledger_check: {workload} ok "
          f"(valid record, {rec.get('backend') or 'n/a'} backend, "
          f"{rec.get('platform') or 'n/a'} platform, "
          f"metrics={rec.get('metrics')})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
