"""Shard storage façade: CRUD, availability, canonical index, key scheme."""

import pytest

from gethsharding_tpu.core.shard import (
    Shard,
    ShardError,
    canonical_collation_lookup_key,
    data_availability_lookup_key,
)
from gethsharding_tpu.core.types import Collation, CollationHeader, Transaction, \
    serialize_txs_to_blob
from gethsharding_tpu.db.kv import MemoryKV
from gethsharding_tpu.utils.hexbytes import Address20, Hash32


def make_collation(shard_id=1, period=2, n_txs=3) -> Collation:
    txs = [Transaction(gas_limit=i) for i in range(n_txs)]
    body = serialize_txs_to_blob(txs)
    header = CollationHeader(
        shard_id=shard_id,
        period=period,
        proposer_address=Address20(b"\x0a" * 20),
    )
    collation = Collation(header=header, body=body, transactions=txs)
    collation.calculate_chunk_root()
    return collation


@pytest.fixture
def shard():
    return Shard(shard_id=1, shard_db=MemoryKV())


def test_lookup_keys_are_last_32_bytes_of_formatted_string():
    root = Hash32(b"\x01" * 32)
    key = data_availability_lookup_key(root)
    formatted = f"availability-lookup:0x{'01' * 32}".encode()
    assert bytes(key) == formatted[-32:]

    ckey = canonical_collation_lookup_key(5, 17)
    cformatted = b"canonical-collation-lookup:shardID=5,period=17"
    assert bytes(ckey) == cformatted[-32:]


def test_save_and_fetch_collation(shard):
    collation = make_collation()
    shard.save_collation(collation)
    fetched = shard.collation_by_header_hash(collation.header.hash())
    assert fetched.header == collation.header
    assert fetched.body == collation.body
    assert fetched.transactions == collation.transactions


def test_availability_bit(shard):
    collation = make_collation()
    shard.save_collation(collation)
    assert shard.check_availability(collation.header) is True
    shard.set_availability(collation.header.chunk_root, False)
    assert shard.check_availability(collation.header) is False


def test_availability_unset_raises(shard):
    header = CollationHeader(shard_id=1, period=1, chunk_root=Hash32(b"\x05" * 32))
    with pytest.raises(ShardError, match="availability not set"):
        shard.check_availability(header)


def test_wrong_shard_rejected(shard):
    collation = make_collation(shard_id=2)
    with pytest.raises(ShardError, match="does not belong"):
        shard.save_collation(collation)


def test_save_header_requires_chunk_root(shard):
    header = CollationHeader(shard_id=1, period=1)
    with pytest.raises(ShardError, match="chunk root"):
        shard.save_header(header)


def test_canonical_flow(shard):
    collation = make_collation(shard_id=1, period=7)
    shard.save_collation(collation)
    shard.set_canonical(collation.header)
    assert shard.canonical_header_hash(1, 7) == collation.header.hash()
    canonical = shard.canonical_collation(1, 7)
    assert canonical.header == collation.header


def test_set_canonical_requires_saved_header(shard):
    collation = make_collation()
    with pytest.raises(ShardError, match="no value set for header hash"):
        shard.set_canonical(collation.header)


def test_canonical_missing_raises(shard):
    with pytest.raises(ShardError, match="no canonical collation header"):
        shard.canonical_header_hash(1, 99)


def test_check_availability_without_chunk_root(shard):
    header = CollationHeader(shard_id=1, period=1)
    with pytest.raises(ShardError, match="no chunk root"):
        shard.check_availability(header)


def test_concurrent_db_access_smoke():
    """Concurrent readers/writers on one shard DB (the reference's
    Test_DBConcurrent smoke, sharding/database/database_test.go:49)."""
    import threading

    from gethsharding_tpu.core.shard import Shard
    from gethsharding_tpu.core.types import Collation, CollationHeader, Transaction
    from gethsharding_tpu.db.kv import MemoryKV

    shard = Shard(shard_id=0, shard_db=MemoryKV())
    errors = []

    def worker(worker_id: int):
        try:
            for i in range(25):
                txs = [Transaction(nonce=i, payload=bytes([worker_id, i]))]
                from gethsharding_tpu.core.types import serialize_txs_to_blob

                header = CollationHeader(shard_id=0, period=i)
                collation = Collation(header=header,
                                      body=serialize_txs_to_blob(txs),
                                      transactions=txs)
                collation.calculate_chunk_root()
                shard.save_collation(collation)
                assert shard.check_availability(header)
        except Exception as exc:  # noqa: BLE001
            errors.append(exc)

    threads = [threading.Thread(target=worker, args=(w,)) for w in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors


def test_shard_zero_canonical_roundtrip():
    """Regression: shard_id=0 / period encode as the empty RLP string;
    decode must map that back to 0 (big.Int parity), or the canonical
    lookup key written after a DB round-trip embeds shardID=None and
    shard 0 can never resolve its canonical collations."""
    collation = make_collation(shard_id=0, period=1)
    header = collation.header
    decoded = CollationHeader.decode_rlp(header.encode_rlp())
    assert decoded.shard_id == 0
    assert decoded.period == 1
    assert decoded.hash() == header.hash()

    shard = Shard(shard_id=0, shard_db=MemoryKV())
    shard.save_collation(collation)
    shard.set_canonical(header)
    assert shard.canonical_collation(0, 1).header.hash() == header.hash()
