"""The verification serving layer: dynamic micro-batching, backpressure,
pipelined dispatch (gethsharding_tpu/serving/).

Four contracts:
- COALESCING: N concurrent single-item callers share device dispatches
  (the acceptance bar: >= 4x fewer dispatches than requests at 64
  callers, zero result divergence).
- BACKPRESSURE: at the queue cap, policy 'shed' fails fast with counted
  ServingOverloadError while already-admitted requests still complete.
- LATENCY: a lone request flushes at the deadline, never waits for a
  full bucket.
- PARITY: `ServingSigBackend` is a drop-in `SigBackend` — byte-identical
  results to the wrapped python backend on every operation, including
  the invalid/tampered rows of the sigbackend differential contract.
"""

import threading
import time

import pytest

from gethsharding_tpu import metrics
from gethsharding_tpu.crypto import bn256 as bls
from gethsharding_tpu.crypto import secp256k1 as ecdsa
from gethsharding_tpu.crypto.keccak import keccak256
from gethsharding_tpu.serving import (
    AdmissionQueue,
    ServingConfig,
    ServingOverloadError,
    ServingSigBackend,
)
from gethsharding_tpu.sigbackend import SigBackend, bucket_size, get_backend


class CountingSigBackend(SigBackend):
    """Deterministic fake: records every dispatch's batch size; results
    are a pure function of the row so divergence is detectable."""

    name = "counting"

    def __init__(self, delay_s: float = 0.0):
        self.calls = []
        self.delay_s = delay_s
        self._lock = threading.Lock()

    def _record(self, n: int) -> None:
        with self._lock:
            self.calls.append(n)
        if self.delay_s:
            time.sleep(self.delay_s)

    def ecrecover_addresses(self, digests, sigs65):
        self._record(len(digests))
        return [bytes(d)[:20] for d in digests]

    def bls_verify_aggregates(self, messages, agg_sigs, agg_pks):
        self._record(len(messages))
        return [len(bytes(m)) % 2 == 0 for m in messages]

    def bls_verify_committees(self, messages, sig_rows, pk_rows,
                              pk_row_keys=None):
        self._record(len(messages))
        return [len(r) > 0 for r in sig_rows]

    @property
    def dispatches(self) -> int:
        return len(self.calls)


def _registry() -> metrics.Registry:
    """A private registry per test: assertions must not see other tests'
    serving traffic through the process-default registry."""
    return metrics.Registry()


# -- the padding-policy export ---------------------------------------------


def test_bucket_size_public_helper():
    """bucket_size is the single padding policy, exported: quarter-pow2
    above 8, pow2 below, and the jax backend's staticmethod IS it."""
    from gethsharding_tpu.sigbackend import JaxSigBackend

    assert [bucket_size(n) for n in (1, 2, 3, 5, 8)] == [1, 2, 4, 8, 8]
    assert bucket_size(65) == 80
    assert bucket_size(100) == 112
    assert bucket_size(128) == 128
    assert bucket_size(129) == 160
    assert JaxSigBackend._bucket is bucket_size
    # monotone and never shrinking: a coalesced batch can only land on
    # the same-or-larger compiled shape as its pieces
    sizes = [bucket_size(n) for n in range(1, 300)]
    assert all(s >= n for n, s in enumerate(sizes, start=1))
    assert sizes == sorted(sizes)


# -- coalescing (the acceptance criterion) ---------------------------------


def test_concurrent_callers_coalesce():
    """64 concurrent single-item callers -> >= 4x fewer dispatches than
    requests, zero result divergence."""
    fake = CountingSigBackend(delay_s=0.005)
    serving = ServingSigBackend(
        fake, ServingConfig(max_batch=64, flush_us=50_000),
        registry=_registry())
    n = 64
    digests = [keccak256(b"co-%d" % i) for i in range(n)]
    sigs = [bytes([i]) * 65 for i in range(n)]
    barrier = threading.Barrier(n)
    results: dict = {}

    def caller(i: int) -> None:
        barrier.wait()
        results[i] = serving.ecrecover_addresses([digests[i]], [sigs[i]])

    threads = [threading.Thread(target=caller, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    try:
        assert len(results) == n
        for i in range(n):  # zero divergence vs the fake's pure function
            assert results[i] == [digests[i][:20]]
        assert serving.dispatch_count == fake.dispatches
        assert serving.dispatch_count * 4 <= n, (
            f"{serving.dispatch_count} dispatches for {n} requests")
        assert sum(fake.calls) == n  # every row dispatched exactly once
    finally:
        serving.close()


def test_mixed_size_requests_preserve_row_order():
    """Coalescing concatenates many callers' rows; each future must get
    back exactly its own slice, in its own order."""
    fake = CountingSigBackend()
    serving = ServingSigBackend(
        fake, ServingConfig(max_batch=128, flush_us=20_000),
        registry=_registry())
    futures = []
    expected = []
    for size in (3, 1, 5, 2, 8):
        digests = [keccak256(b"mix-%d-%d" % (size, j)) for j in range(size)]
        sigs = [b"\x00" * 65] * size
        futures.append(serving.submit("ecrecover_addresses", digests, sigs))
        expected.append([d[:20] for d in digests])
    try:
        for future, want in zip(futures, expected):
            assert future.result(timeout=10) == want
    finally:
        serving.close()


def test_empty_request_resolves_immediately():
    fake = CountingSigBackend()
    serving = ServingSigBackend(fake, registry=_registry())
    try:
        assert serving.ecrecover_addresses([], []) == []
        assert fake.dispatches == 0
    finally:
        serving.close()


# -- backpressure ----------------------------------------------------------


def test_shed_policy_at_queue_cap():
    """With policy 'shed', overload fails fast (counted), and every
    ADMITTED request still completes correctly."""
    registry = _registry()
    fake = CountingSigBackend(delay_s=0.15)  # slow device: queue backs up
    serving = ServingSigBackend(
        fake, ServingConfig(max_batch=4, flush_us=0, queue_cap=4,
                            policy="shed"),
        registry=registry)
    futures, shed = [], 0
    digest = keccak256(b"shed")
    try:
        for _ in range(64):
            try:
                futures.append(serving.submit(
                    "ecrecover_addresses", [digest], [b"\x00" * 65]))
            except ServingOverloadError:
                shed += 1
        assert shed > 0
        assert futures  # the in-flight window was admitted
        for future in futures:
            assert future.result(timeout=30) == [digest[:20]]
        assert serving.batcher.shed_counts()["ecrecover_addresses"] == shed
        assert registry.counter("serving/ecrecover/shed").value == shed
    finally:
        serving.close()


def test_block_policy_absorbs_overload():
    """Policy 'block' never sheds: all requests complete once the device
    drains the backlog."""
    fake = CountingSigBackend(delay_s=0.01)
    serving = ServingSigBackend(
        fake, ServingConfig(max_batch=8, flush_us=0, queue_cap=8,
                            policy="block"),
        registry=_registry())
    digest = keccak256(b"block")
    try:
        futures = [serving.submit("ecrecover_addresses", [digest],
                                  [b"\x00" * 65]) for _ in range(64)]
        for future in futures:
            assert future.result(timeout=30) == [digest[:20]]
        assert sum(fake.calls) == 64
    finally:
        serving.close()


def test_admission_queue_oversized_request_never_deadlocks():
    """A request larger than the cap is admitted when the queue is below
    the cap (the cap is a high-water mark, not a hard ceiling) and is
    dispatched alone."""
    queue = AdmissionQueue(cap_rows=4, policy="block", max_batch=4,
                           flush_us=0)
    from gethsharding_tpu.serving.queue import Request

    big = Request("ecrecover_addresses", ((), ()), rows=16)
    queue.put(big)
    batch, reason = queue.take_batch()
    assert batch == [big] and reason == "full"  # >= max_batch rows queued
    assert queue.depth_rows == 0


# -- deadline flush --------------------------------------------------------


def test_deadline_flush_latency():
    """A lone request must flush at the deadline, not wait for a full
    bucket; the flush-reason counter attributes it."""
    registry = _registry()
    fake = CountingSigBackend()
    serving = ServingSigBackend(
        fake, ServingConfig(max_batch=1024, flush_us=5_000),
        registry=registry)
    digest = keccak256(b"deadline")
    try:
        t0 = time.monotonic()
        out = serving.ecrecover_addresses([digest], [b"\x00" * 65])
        elapsed = time.monotonic() - t0
        assert out == [digest[:20]]
        assert elapsed < 2.0  # 5 ms deadline + scheduling slack, not "never"
        assert registry.counter("serving/ecrecover/flush_deadline").value >= 1
        assert registry.counter("serving/ecrecover/flush_full").value == 0
        hist = registry.histogram("serving/ecrecover/batch_rows")
        assert hist.count == 1 and hist.bucket_counts()["le_1"] == 1
    finally:
        serving.close()


# -- drop-in parity with the wrapped backend -------------------------------


def _ecdsa_cases():
    """Valid + invalid recovery rows (the test_sigbackend contract)."""
    digests, sigs = [], []
    for i in range(4):
        priv = int.from_bytes(keccak256(b"sv" + bytes([i])), "big") % ecdsa.N
        msg = keccak256(b"m" + bytes([i]))
        digests.append(msg)
        sigs.append(ecdsa.sign(msg, priv).to_bytes65())
    digests.append(keccak256(b"x"))
    sigs.append(b"\x00" * 10)  # truncated
    digests.append(keccak256(b"y"))
    sigs.append(b"\x00" * 64 + b"\x00")  # zeroed r
    return digests, sigs


def test_serving_matches_python_backend_ecrecover():
    python = get_backend("python")
    serving = ServingSigBackend(python, registry=_registry())
    digests, sigs = _ecdsa_cases()
    try:
        assert (serving.ecrecover_addresses(digests, sigs)
                == python.ecrecover_addresses(digests, sigs))
    finally:
        serving.close()


def test_serving_matches_python_backend_bls():
    """Aggregate + committee ops byte-identical through the serving
    tier, including reject rows (tampered sig, empty committee)."""
    python = get_backend("python")
    serving = ServingSigBackend(python, registry=_registry())
    header = b"serve-agg"
    keys = [bls.bls_keygen(bytes([i])) for i in range(2)]
    agg_sig = bls.bls_aggregate_sigs([bls.bls_sign(header, sk)
                                      for sk, _ in keys])
    agg_pk = bls.bls_aggregate_pks([pk for _, pk in keys])
    tampered = bls.g1_add(agg_sig, bls.G1_GEN)
    agg_args = ([header, header, header], [agg_sig, tampered, None],
                [agg_pk, agg_pk, agg_pk])

    msgs, sig_rows, pk_rows = [], [], []
    for i, n in enumerate((2, 1)):
        tag = b"serve-row%d" % i
        committee = [bls.bls_keygen(tag + bytes([j])) for j in range(n)]
        msgs.append(tag)
        sig_rows.append([bls.bls_sign(tag, sk) for sk, _ in committee])
        pk_rows.append([pk for _, pk in committee])
    msgs.append(b"serve-empty")
    sig_rows.append([])
    pk_rows.append([])  # empty committee proves nothing: reject

    try:
        assert (serving.bls_verify_aggregates(*agg_args)
                == python.bls_verify_aggregates(*agg_args)
                == [True, False, False])
        assert (serving.bls_verify_committees(msgs, sig_rows, pk_rows)
                == python.bls_verify_committees(msgs, sig_rows, pk_rows)
                == [True, True, False])
        # pk_row_keys pass through the coalescer per row (python backend
        # ignores them; the call shape is the jax cache contract)
        assert serving.bls_verify_committees(
            msgs, sig_rows, pk_rows,
            pk_row_keys=["k0", "k1", None]) == [True, True, False]
    finally:
        serving.close()


def test_registry_exposes_serving_wrappers():
    """get_backend('serving-python') is the drop-in registered form."""
    serving = get_backend("serving-python")
    assert isinstance(serving, ServingSigBackend)
    assert isinstance(serving, SigBackend)
    assert serving.inner is get_backend("python")
    assert serving.name == "serving+python"
    assert get_backend("serving-python") is serving  # cached singleton
    with pytest.raises(ValueError):
        ServingSigBackend(serving)  # no nested admission tiers


def test_surplus_pk_row_keys_do_not_shift_batch_mates():
    """A caller passing MORE keys than rows must not misalign the keys
    of other requests coalesced into the same dispatch (the jax pk-row
    cache resolves rows BY key: a shift would verify against the wrong
    cached committee)."""

    class KeyRecorder(SigBackend):
        name = "keyrec"

        def __init__(self):
            self.seen_keys = None

        def bls_verify_committees(self, messages, sig_rows, pk_rows,
                                  pk_row_keys=None):
            self.seen_keys = list(pk_row_keys)
            return [True] * len(messages)

    fake = KeyRecorder()
    serving = ServingSigBackend(
        fake, ServingConfig(max_batch=64, flush_us=50_000),
        registry=_registry())
    try:
        future_a = serving.submit(
            "bls_verify_committees", [b"a0", b"a1"], [[], []], [[], []],
            pk_row_keys=["a0", "a1", "surplus"])  # one key too many
        future_b = serving.submit(
            "bls_verify_committees", [b"b0", b"b1"], [[], []], [[], []],
            pk_row_keys=["b0", "b1"])
        assert future_a.result(timeout=10) == [True, True]
        assert future_b.result(timeout=10) == [True, True]
        assert fake.seen_keys == ["a0", "a1", "b0", "b1"]
    finally:
        serving.close()


def test_ragged_request_rejected_and_flusher_survives_poison():
    """Misaligned columns are rejected at submit; a poison request that
    reaches the queue anyway fails ITS OWN future, and the flusher keeps
    serving later requests."""
    from gethsharding_tpu.serving.queue import Request

    fake = CountingSigBackend()
    serving = ServingSigBackend(fake, ServingConfig(flush_us=1_000),
                                registry=_registry())
    try:
        with pytest.raises(ValueError, match="ragged"):
            serving.submit("ecrecover_addresses",
                           [keccak256(b"r")], [b"\x00" * 65] * 2)
        with pytest.raises(ValueError, match="rows"):
            serving.batcher.submit(
                "ecrecover_addresses",
                (([keccak256(b"r")]), [b"\x00" * 65] * 2), 2)
        # poison past the validation (white box): rows claims 2, columns
        # hold 1 — the flusher must fail this future and stay alive
        poison = Request("ecrecover_addresses",
                         ([keccak256(b"p")], [b"\x00" * 65]), rows=2)
        serving.batcher._queues["ecrecover_addresses"].put(poison)
        with pytest.raises(RuntimeError, match="results for"):
            poison.future.result(timeout=10)
        digest = keccak256(b"after-poison")
        assert serving.ecrecover_addresses(
            [digest], [b"\x00" * 65]) == [digest[:20]]
    finally:
        serving.close()


def test_serving_error_propagates_to_all_requests():
    class Broken(SigBackend):
        name = "broken"

        def ecrecover_addresses(self, digests, sigs65):
            raise RuntimeError("device on fire")

    serving = ServingSigBackend(Broken(), ServingConfig(flush_us=1_000),
                                registry=_registry())
    try:
        futures = [serving.submit("ecrecover_addresses",
                                  [keccak256(b"err")], [b"\x00" * 65])
                   for _ in range(3)]
        for future in futures:
            with pytest.raises(RuntimeError, match="device on fire"):
                future.result(timeout=10)
    finally:
        serving.close()


# -- the notary through the serving tier -----------------------------------


def test_notary_proposer_gate_through_serving():
    """The notary's proposer-signature gate is byte-identical through a
    serving backend (the async-submit overlap path resolves to the same
    verdicts as the inline path)."""
    from gethsharding_tpu.actors.notary import Notary
    from gethsharding_tpu.core.shard import Shard
    from gethsharding_tpu.core.types import CollationHeader
    from gethsharding_tpu.db.kv import MemoryKV
    from gethsharding_tpu.mainchain.client import SMCClient
    from gethsharding_tpu.params import ETHER
    from gethsharding_tpu.smc.chain import SimulatedMainchain
    from gethsharding_tpu.smc.state_machine import CollationRecord
    from gethsharding_tpu.utils.hexbytes import Hash32

    serving = ServingSigBackend(get_backend("python"), registry=_registry())
    chain = SimulatedMainchain()
    client = SMCClient(backend=chain)
    chain.fund(client.account(), 2000 * ETHER)
    notary = Notary(client=client, shard=Shard(0, MemoryKV()),
                    sig_backend=serving)
    try:
        priv = 0xBEEF
        proposer = ecdsa.priv_to_address(priv)
        root = Hash32(keccak256(b"root"))
        unsigned = CollationHeader(shard_id=0, chunk_root=root, period=1,
                                   proposer_address=proposer)
        good = CollationRecord(
            chunk_root=root, proposer=proposer,
            signature=ecdsa.sign(bytes(unsigned.hash()), priv).to_bytes65())
        bad = CollationRecord(
            chunk_root=root, proposer=proposer,
            signature=ecdsa.sign(bytes(unsigned.hash()),
                                 priv + 1).to_bytes65())
        assert notary.verify_proposer_signatures(
            [(0, 1, good), (0, 1, bad)]) == [True, False]
    finally:
        serving.close()


# -- the txpool through the serving tier -----------------------------------


def test_txpool_serving_cache_and_error_contract():
    """Sender recovery dispatches once at admission (removal uses the
    admission-time cache), and serving failures surface as TxPoolError —
    the pool's only documented exception."""
    from gethsharding_tpu.actors.txpool import TXPool, TxPoolError
    from gethsharding_tpu.core.state_processor import sign_transaction
    from gethsharding_tpu.core.types import Transaction

    fake = CountingSigBackend()
    serving = ServingSigBackend(fake, ServingConfig(flush_us=1_000),
                                registry=_registry())
    pool = TXPool(simulate_interval=None, sig_backend=serving)
    tx = sign_transaction(
        Transaction(nonce=0, gas_price=1, gas_limit=30000, payload=b"t"),
        0xAB)
    pool.submit(tx)
    assert pool.known_count() == 1
    admit_dispatches = fake.dispatches
    pool.remove([tx])  # the take_pending() hot path
    assert pool.known_count() == 0
    assert fake.dispatches == admit_dispatches, (
        "remove() must use the admission-time sender cache, not re-recover")
    serving.close()
    tx2 = sign_transaction(
        Transaction(nonce=1, gas_price=1, gas_limit=30000, payload=b"t"),
        0xAB)
    with pytest.raises(TxPoolError, match="unavailable"):
        pool.submit(tx2)  # closed/overloaded tier = pool rejection


# -- the RPC handler-thread path -------------------------------------------


def test_rpc_handlers_submit_through_serving():
    """Concurrent shard_ecrecover calls from separate connections share
    serving dispatches (handler threads submit, not call inline), and
    results match the python backend."""
    from gethsharding_tpu.rpc import codec
    from gethsharding_tpu.rpc.client import RPCClient
    from gethsharding_tpu.rpc.server import RPCServer
    from gethsharding_tpu.smc.chain import SimulatedMainchain

    fake = CountingSigBackend(delay_s=0.005)
    serving = ServingSigBackend(
        fake, ServingConfig(max_batch=64, flush_us=50_000),
        registry=_registry())
    server = RPCServer(SimulatedMainchain(), sig_backend=serving)
    server.start()
    n = 8
    digests = [keccak256(b"rpc-%d" % i) for i in range(n)]
    results: dict = {}
    barrier = threading.Barrier(n)

    def call(i: int) -> None:
        client = RPCClient(*server.address)
        try:
            barrier.wait()
            results[i] = server_call = client.call(
                "shard_ecrecover",
                [codec.enc_bytes(digests[i])],
                [codec.enc_bytes(b"\x00" * 65)])
            assert server_call is not None
        finally:
            client.close()

    threads = [threading.Thread(target=call, args=(i,)) for i in range(n)]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert len(results) == n
        for i in range(n):
            assert results[i] == [codec.enc_bytes(digests[i][:20])]
        stats = RPCClient(*server.address)
        try:
            served = stats.call("shard_servingStats")
        finally:
            stats.close()
        dispatches = sum(served["dispatches"].values())
        assert 0 < dispatches < n  # coalesced across handler threads
    finally:
        server.stop()


# -- metrics + status surfaces ---------------------------------------------


def test_histogram_metric():
    hist = metrics.Histogram(buckets=(1, 4, 16))
    for value in (1, 1, 3, 9, 100):
        hist.observe(value)
    assert hist.count == 5
    # le_* counts are CUMULATIVE (Prometheus semantics: at-or-below)
    assert hist.bucket_counts() == {"le_1": 2, "le_4": 3, "le_16": 4,
                                    "le_inf": 5}
    # the exact per-slot counts stay available under bucket_* keys
    assert hist.slot_counts() == {"bucket_1": 2, "bucket_4": 1,
                                  "bucket_16": 1, "bucket_inf": 1}
    snap = hist.snapshot()
    assert snap["type"] == "histogram" and snap["count"] == 5
    assert snap["le_inf"] == 5  # flat fields: exporter/dashboard ready
    assert snap["bucket_inf"] == 1
    registry = metrics.Registry()
    assert (registry.histogram("h", buckets=(1, 2))
            is registry.histogram("h"))
    assert "h" in registry.snapshot()


def test_status_page_surfaces_serving_metrics():
    """/status carries the serving/ namespace once serving traffic
    exists (default-registry metrics, as a node runs them)."""
    from gethsharding_tpu.node.http_status import StatusServer
    from gethsharding_tpu.node.backend import ShardNode
    from gethsharding_tpu.smc.chain import SimulatedMainchain

    serving = ServingSigBackend(CountingSigBackend())  # DEFAULT_REGISTRY
    try:
        serving.ecrecover_addresses([keccak256(b"status")], [b"\x00" * 65])
    finally:
        serving.close()
    node = ShardNode(actor="observer", backend=SimulatedMainchain())
    status = StatusServer(node)
    payload = status.status_payload()
    assert any(name.startswith("serving/ecrecover/")
               for name in payload.get("serving", {}))
