"""Keccak-256 conformance against well-known Ethereum test vectors."""

import pytest

from gethsharding_tpu.crypto.keccak import keccak256

# Standard Keccak-256 (pre-NIST padding) vectors.
VECTORS = [
    (b"", "c5d2460186f7233c927e7db2dcc703c0e500b653ca82273b7bfad8045d85a470"),
    (b"abc", "4e03657aea45a94fc7d47ba826c8d667c0d1e6e33a64a036ec44f58fa12d6c45"),
    (
        b"The quick brown fox jumps over the lazy dog",
        "4d741b6f1eb29cb2a9b9911c82f56fa8d73b04959d3d9d222895df6c0b28aa15",
    ),
]


@pytest.mark.parametrize("msg,digest", VECTORS)
def test_known_vectors(msg, digest):
    assert keccak256(msg).hex() == digest


def test_multiblock():
    # > 136-byte rate forces multiple permutations
    msg = b"x" * 500
    digest = keccak256(msg)
    assert len(digest) == 32
    # self-consistency: equal inputs hash equal, prefix change diffuses
    assert keccak256(b"y" + msg[1:]) != digest


def test_rate_boundaries():
    for n in (135, 136, 137, 271, 272, 273):
        assert len(keccak256(b"\xab" * n)) == 32
