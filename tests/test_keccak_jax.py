"""Differential tests: batched keccak (ops/keccak_jax) vs scalar reference.

The scalar `crypto/keccak.py` is itself pinned by golden vectors
(tests/test_keccak.py: keccak256(b"") = c5d24601...), so byte-equality here
transitively pins the TPU kernel to Ethereum's keccak256.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from gethsharding_tpu.crypto.keccak import keccak256, keccak_f1600 as scalar_f1600
from gethsharding_tpu.ops.keccak_jax import keccak256_fixed, keccak_f1600


def _lanes_from_ints(lanes64):
    lo = [v & 0xFFFFFFFF for v in lanes64]
    hi = [v >> 32 for v in lanes64]
    return np.stack([np.array(lo, np.uint32), np.array(hi, np.uint32)], axis=-1)


def test_permutation_matches_scalar():
    rng = np.random.default_rng(7)
    batch = 5
    states = [[int(v) for v in rng.integers(0, 1 << 64, 25, dtype=np.uint64)]
              for _ in range(batch)]
    packed = jnp.asarray(np.stack([_lanes_from_ints(s) for s in states]))
    out = np.asarray(jax.jit(keccak_f1600)(packed))
    for i, s in enumerate(states):
        expect = scalar_f1600(list(s))
        got = [int(out[i, j, 0]) | (int(out[i, j, 1]) << 32) for j in range(25)]
        assert got == expect


@pytest.mark.parametrize("length", [0, 1, 31, 32, 96, 135, 136, 137, 200, 272])
def test_digest_matches_scalar(length):
    rng = np.random.default_rng(length)
    batch = 4
    msgs = [rng.integers(0, 256, length, dtype=np.uint8).tobytes()
            for _ in range(batch)]
    data = jnp.asarray(
        np.stack([np.frombuffer(m, np.uint8) for m in msgs])
        if length else np.zeros((batch, 0), np.uint8))
    got = np.asarray(jax.jit(keccak256_fixed)(data))
    for i, m in enumerate(msgs):
        assert got[i].tobytes() == keccak256(m)


def test_empty_message_golden():
    out = np.asarray(keccak256_fixed(jnp.zeros((0,), jnp.uint8)))
    assert out.tobytes().hex() == (
        "c5d2460186f7233c927e7db2dcc703c0e500b653ca82273b7bfad8045d85a470"
    )


def test_vmap_over_messages():
    data = jnp.asarray(
        np.arange(3 * 96, dtype=np.uint8).reshape(3, 96))
    direct = np.asarray(keccak256_fixed(data))
    vmapped = np.asarray(jax.vmap(keccak256_fixed)(data))
    np.testing.assert_array_equal(direct, vmapped)
