"""Fleet observability plane: cross-process trace stitching, metrics
federation, and per-class SLO burn-rate tracking (ISSUE 9).

Four contracts:

- STITCHING: a request issued through `RouterSigBackend` against an
  RPC replica produces ONE trace id spanning router route/attempt
  spans, the replica's RPC handler span (adopted from the wire trace
  envelope) and the serving request/dispatch spans — and the dispatch
  span carries `device_ms`/`wire_bytes` tags. The per-process Chrome
  exports merge into one Perfetto file (scripts/trace_merge.py).
- FEDERATION: after one health-sweep pass the router's registry (and
  its Prometheus exposition) contains `fleet/replica/<name>/` rollups
  scraped over the new `shard_metrics` RPC, plus the fleet aggregates.
- SLO: objectives window good/bad events into fast/slow burn rates
  with deterministic clocks; the serving tier and router record events;
  a seeded chaos breaker trip measurably moves the affected class's
  burn rate in the closed loop; soundness violations burn the
  integrity budget.
- RING: the bounded finished-span ring counts overwritten spans
  (`trace/dropped`).
"""

import json
import time

import pytest

from gethsharding_tpu import metrics, slo, tracing
from gethsharding_tpu.crypto import secp256k1 as ecdsa
from gethsharding_tpu.crypto.keccak import keccak256
from gethsharding_tpu.fleet import (
    FleetRouter,
    Replica,
    RouterSigBackend,
)
from gethsharding_tpu.fleet.router import RpcReplicaBackend
from gethsharding_tpu.rpc.server import RPCServer
from gethsharding_tpu.serving import ServingConfig, ServingSigBackend
from gethsharding_tpu.sigbackend import PythonSigBackend
from gethsharding_tpu.slo.tracker import BUCKET_S, Objective, SLOTracker
from gethsharding_tpu.smc.chain import SimulatedMainchain


@pytest.fixture
def tracer():
    tracing.enable(ring_spans=65536)
    tracing.TRACER.clear()
    yield tracing.TRACER
    tracing.disable()
    tracing.TRACER.clear()


@pytest.fixture
def fresh_slo():
    """A fresh process SLO tracker on a fresh registry, restored
    afterwards — burn state must not leak between tests."""
    import importlib

    # the package re-exports `tracker` (the accessor), shadowing the
    # submodule attribute — reach the module itself for the global
    tracker_mod = importlib.import_module("gethsharding_tpu.slo.tracker")
    saved = tracker_mod.TRACKER
    fresh = slo.configure(registry=metrics.Registry())
    yield fresh
    tracker_mod.TRACKER = saved


def _ecdsa_cases(n: int, tag: bytes = b"fleetobs"):
    cases = []
    for i in range(n):
        priv = int.from_bytes(keccak256(tag + b"-%d" % i), "big") % ecdsa.N
        digest = keccak256(tag + b"-msg-%d" % i)
        cases.append((digest, ecdsa.sign(digest, priv).to_bytes65(),
                      ecdsa.priv_to_address(priv)))
    return cases


def _rpc_fleet(n_replicas: int = 2, registry=None):
    """Router over `n_replicas` RPCServer replicas dialed through
    `RpcReplicaBackend` — the cross-process shape, in-process."""
    registry = registry or metrics.Registry()
    servers, replicas = [], []
    for i in range(n_replicas):
        serving = ServingSigBackend(PythonSigBackend(),
                                    ServingConfig(flush_us=200))
        server = RPCServer(SimulatedMainchain(), sig_backend=serving)
        server.start()
        servers.append((server, serving))
        backend = RpcReplicaBackend.dial(*server.address)
        replicas.append(Replica(f"r{i}", backend, health=backend.health,
                                probe=None, registry=registry))
    router = FleetRouter(replicas, health_interval_s=0.0,
                         registry=registry)
    return router, replicas, servers, registry


def _close_fleet(router, replicas, servers):
    for replica in replicas:
        replica.backend.close()
    for server, serving in servers:
        server.stop()
        serving.close()
    del router


# == cross-process trace stitching ==========================================


def test_routed_request_stitches_one_trace_end_to_end(tracer, fresh_slo):
    """THE acceptance path: RouterSigBackend -> RPC replica. One trace
    id covers fleet/route -> fleet/attempt -> rpc/client ->
    rpc/shard_ecrecover (adopted from the wire envelope) ->
    serving/ecrecover/request -> device_dispatch, and the dispatch
    span carries device_ms/wire_bytes tags."""
    router, replicas, servers, _ = _rpc_fleet(2)
    back = RouterSigBackend(router)
    try:
        digest, sig, want = _ecdsa_cases(1)[0]
        assert back.ecrecover_addresses([digest], [sig]) == [want]
    finally:
        _close_fleet(router, replicas, servers)

    spans = tracer.recent_spans()
    routes = [s for s in spans if s["name"] == "fleet/route"]
    assert len(routes) == 1
    trace_id = routes[0]["trace"]
    by_name = {}
    for s in spans:
        if s["trace"] == trace_id:
            by_name.setdefault(s["name"], []).append(s)
    # the whole ladder shares the route's trace id
    for name in ("fleet/attempt", "rpc/client/shard_ecrecover",
                 "rpc/shard_ecrecover", "serving/ecrecover/request",
                 "serving/ecrecover/device_dispatch"):
        assert name in by_name, (name, sorted(by_name))
    # parentage: attempt under route, client under attempt, handler
    # (cross-"process" via the trace envelope) under the client span
    attempt = by_name["fleet/attempt"][0]
    assert attempt["parent"] == routes[0]["span"]
    assert attempt["tags"]["replica"] in ("r0", "r1")
    assert attempt["tags"]["attempt"] == 1
    client = by_name["rpc/client/shard_ecrecover"][0]
    assert client["parent"] == attempt["span"]
    handler = by_name["rpc/shard_ecrecover"][0]
    assert handler["parent"] == client["span"]
    # the client-side correlation tag points at the stitched trace
    assert client["tags"]["remote_trace"] == trace_id
    # the serving request hangs off the handler; its dispatch span
    # carries the device-time attribution tags
    request = by_name["serving/ecrecover/request"][0]
    assert request["parent"] == handler["span"]
    dispatch = by_name["serving/ecrecover/device_dispatch"][0]
    assert dispatch["parent"] == request["span"]
    assert dispatch["tags"]["device_ms"] >= 0.0
    assert dispatch["tags"]["wire_bytes"] >= 32 + 65  # digest + sig
    assert request["tags"]["device_ms"] >= 0.0


def test_trace_merge_tool_aligns_pid_lanes(tracer, tmp_path):
    """Two per-process exports (distinct pids, wall anchors) merge into
    one Perfetto file: both lanes present, stitched trace ids intact,
    timestamps on one common axis."""
    import os
    import sys

    scripts = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts")
    sys.path.insert(0, scripts)
    try:
        from trace_merge import merge_traces
    finally:
        sys.path.remove(scripts)

    with tracing.span("router/work"):
        pass
    path_a = str(tmp_path / "a.json")
    tracing.write_chrome_trace(path_a, pid=1001, label="router")
    tracer.clear()
    with tracing.span("replica/work"):
        pass
    path_b = str(tmp_path / "b.json")
    tracing.write_chrome_trace(path_b, pid=2002, label="replica-0")

    merged = merge_traces([json.load(open(path_a)),
                           json.load(open(path_b))])
    events = merged["traceEvents"]
    span_events = [e for e in events if e.get("ph") == "X"]
    assert {e["pid"] for e in span_events} == {1001, 2002}
    names = {e["name"] for e in span_events}
    assert {"router/work", "replica/work"} <= names
    # process_name metadata survives per lane
    lanes = {e["pid"]: e["args"]["name"] for e in events
             if e.get("ph") == "M" and e.get("name") == "process_name"}
    assert lanes[1001] == "router" and lanes[2002] == "replica-0"
    # one common, near-zero-based time axis
    assert all(e["ts"] >= 0 for e in span_events)


def test_rpc_client_surfaces_remote_trace_tag(tracer):
    """SATELLITE: the `trace` field the server has always returned on
    the response envelope (and the client silently discarded) is now
    surfaced as the client span's `remote_trace` tag, so caller logs
    correlate to replica traces even against a replica that does not
    stitch. Every RPC method gets this — not just the serving ops."""
    server = RPCServer(SimulatedMainchain())
    server.start()
    from gethsharding_tpu.rpc.client import RPCClient

    client = RPCClient(*server.address)
    try:
        assert isinstance(client.call("shard_blockNumber"), int)
    finally:
        client.close()
        server.stop()
    clients = [s for s in tracer.recent_spans()
               if s["name"] == "rpc/client/shard_blockNumber"]
    assert len(clients) == 1
    handler = [s for s in tracer.recent_spans()
               if s["name"] == "rpc/shard_blockNumber"]
    assert len(handler) == 1
    assert clients[0]["tags"]["remote_trace"] == handler[0]["trace"]
    # the client span itself was the outbound context, so the handler
    # adopted it: one trace id across the wire, both directions
    assert clients[0]["trace"] == handler[0]["trace"]


def test_dropped_span_counter_on_ring_overflow(tracer):
    """SATELLITE: ring overflow is counted, not silent."""
    registry = metrics.Registry()
    t = tracing.Tracer(ring_spans=4, registry=registry)
    t.enabled = True
    for i in range(10):
        t.record(f"s{i}", 0.0, 1.0)
    assert t.spans_dropped == 6
    assert registry.counter("trace/dropped").value == 6
    assert t.spans_recorded == 10


# == metrics federation =====================================================


def test_health_sweep_federates_replica_metrics(fresh_slo):
    """After one sweep, the router registry holds
    fleet/replica/<name>/ rollups scraped via shard_metrics, the
    fleet aggregates, and the Prometheus exposition carries them."""
    router, replicas, servers, registry = _rpc_fleet(2)
    back = RouterSigBackend(router)
    try:
        for digest, sig, want in _ecdsa_cases(4, b"fed"):
            assert back.ecrecover_addresses([digest], [sig]) == [want]
        router.refresh(force=True)  # ONE sweep pass: health + scrape
        # the replicas share this test process's DEFAULT_REGISTRY, so
        # the scrape sees the serving counters the traffic just moved
        gauge = registry.get("fleet/replica/r0/serving/ecrecover/"
                             "requests/count")
        assert gauge is not None and gauge.value >= 1
        lat = registry.get("fleet/replica/r0/serving/ecrecover/"
                           "dispatch_latency/p99_s")
        assert lat is not None and lat.value >= 0.0
        # fleet aggregates
        assert registry.get("fleet/total_inflight") is not None
        assert registry.get("fleet/worst_replica_p99_s").value >= 0.0
        for klass in ("interactive", "bulk_audit", "catchup_replay"):
            assert registry.get(f"fleet/class/{klass}/queue_depth") \
                is not None
        # and the exposition renders them
        prom = metrics.prometheus_text(registry)
        assert "gethsharding_fleet_replica_r0_serving_ecrecover_" \
            "requests_count" in prom
        assert "gethsharding_fleet_total_inflight" in prom
        assert replicas[0].last_metrics  # scrape retained for debugging
    finally:
        _close_fleet(router, replicas, servers)


def test_shard_metrics_rpc_serves_registry_snapshot():
    server = RPCServer(SimulatedMainchain())
    server.start()
    backend = RpcReplicaBackend.dial(*server.address)
    try:
        snap = backend.metrics()
        assert isinstance(snap, dict)
    finally:
        backend.close()
        server.stop()


# == the SLO layer ==========================================================


def _tracker(**kw) -> SLOTracker:
    objectives = kw.pop("objectives", None) or {
        "interactive": Objective("interactive", availability=0.999,
                                 latency_target_s=0.5),
        "integrity": Objective("integrity", availability=0.9999),
    }
    return SLOTracker(objectives=objectives,
                      registry=kw.pop("registry", metrics.Registry()),
                      **kw)


def test_burn_rate_windows_and_budget():
    """Deterministic clock: burn = error_ratio / budget per window;
    fast window forgets, slow window remembers; budget_remaining
    mirrors the slow burn."""
    t = _tracker()
    now = 1000.0
    # 10 events, 1 bad: error ratio 0.1, budget 0.001 -> burn 100x
    for i in range(9):
        t.record("interactive", ok=True, latency_s=0.01, now=now)
    t.record("interactive", ok=False, now=now)
    assert t.burn_rate("interactive", "fast", now=now) == \
        pytest.approx(100.0)
    assert t.burn_rate("interactive", "slow", now=now) == \
        pytest.approx(100.0)
    assert t.budget_remaining("interactive", now=now) == 0.0
    # after the fast window passes (good traffic meanwhile), the fast
    # burn recovers while the slow window still remembers the bad event
    later = now + t.fast_window_s + BUCKET_S
    for i in range(90):
        t.record("interactive", ok=True, latency_s=0.01, now=later)
    fast = t.burn_rate("interactive", "fast", now=later)
    slow = t.burn_rate("interactive", "slow", now=later)
    assert fast == 0.0
    assert slow == pytest.approx((1 / 100) / 0.001)  # 10x
    # ... and after the slow window rolls past, the budget recovers
    much_later = later + t.slow_window_s + BUCKET_S
    t.record("interactive", ok=True, latency_s=0.01, now=much_later)
    assert t.burn_rate("interactive", "slow", now=much_later) == 0.0
    assert t.budget_remaining("interactive", now=much_later) == 1.0


def test_latency_target_counts_slow_successes_as_bad():
    t = _tracker()
    now = 2000.0
    t.record("interactive", ok=True, latency_s=0.9, now=now)  # > 0.5s
    t.record("interactive", ok=True, latency_s=0.1, now=now)
    assert t.burn_rate("interactive", "fast", now=now) == \
        pytest.approx(0.5 / 0.001)


def test_breach_hook_fires_once_with_hysteresis():
    t = _tracker(min_events=5)
    fired = []
    t.on_breach(lambda name, fast, slow: fired.append((name, fast, slow)))
    now = 3000.0
    for i in range(20):
        t.record("interactive", ok=False, now=now + i * 0.01)
    t.sweep(now=now + 1.0)
    t.sweep(now=now + 2.0)  # still breached: must NOT re-fire
    assert len(fired) == 1
    name, fast, slow = fired[0]
    assert name == "interactive" and fast >= t.breach_fast
    assert t._series["interactive"].m_breaches.value == 1


def test_slo_gauges_reach_registry_and_prom():
    registry = metrics.Registry()
    t = _tracker(registry=registry)
    now = 4000.0
    t.record("interactive", ok=False, now=now)
    t.sweep(now=now)
    assert registry.get("slo/interactive/burn_rate").value > 0
    assert registry.get("slo/interactive/budget_remaining") is not None
    prom = metrics.prometheus_text(registry)
    assert "gethsharding_slo_interactive_burn_rate" in prom
    assert "gethsharding_slo_interactive_breaches_total" in prom


def test_objective_env_overrides(monkeypatch):
    monkeypatch.setenv("GETHSHARDING_SLO_INTERACTIVE_P99_MS", "250")
    monkeypatch.setenv("GETHSHARDING_SLO_INTERACTIVE_AVAILABILITY",
                       "0.95")
    objectives = slo.default_objectives()
    assert objectives["interactive"].latency_target_s == \
        pytest.approx(0.25)
    assert objectives["interactive"].availability == 0.95
    # all three admission classes + integrity + the light-client DAS
    # sampling tier exist
    assert set(objectives) == {"interactive", "bulk_audit",
                               "catchup_replay", "das_light",
                               "integrity"}


def test_serving_records_slo_events(fresh_slo):
    """The serving tier marks every completed request good with its
    end-to-end latency — visible as slo/<class> counters."""
    serving = ServingSigBackend(PythonSigBackend(),
                                ServingConfig(flush_us=200))
    try:
        digest, sig, want = _ecdsa_cases(1, b"slo-serving")[0]
        assert serving.ecrecover_addresses([digest], [sig]) == [want]
    finally:
        serving.close()
    assert fresh_slo._series["interactive"].m_good.value >= 1
    assert fresh_slo._series["interactive"].latency.count >= 1


def test_queue_shed_and_expiry_burn_victim_class_budget(fresh_slo):
    """Displacement and class-deadline expiry inside the admission
    queue charge the VICTIM class's error budget — overload is exactly
    what the burn-rate plane exists to see."""
    from gethsharding_tpu.crypto.keccak import keccak256
    from gethsharding_tpu.serving import (
        AdmissionQueue,
        ClassDeadlineExceeded,
        Request,
        ServingOverloadError,
    )
    from gethsharding_tpu.serving.classes import (
        CLASS_CATCHUP,
        ClassPolicy,
        default_policies,
    )

    def req(klass):
        return Request("ecrecover_addresses",
                       ((keccak256(b"q"),), (b"\x00" * 65,)), 1,
                       klass=klass)

    queue = AdmissionQueue(cap_rows=2, policy="shed", max_batch=2,
                           flush_us=1_000_000)
    victims = [req(CLASS_CATCHUP) for _ in range(2)]
    for request in victims:
        queue.put(request)
    queue.put(req("interactive"))  # displaces the newest catchup
    with pytest.raises(ServingOverloadError):
        victims[-1].future.result(timeout=1)
    assert fresh_slo._series[CLASS_CATCHUP].m_bad.value == 1
    assert fresh_slo.burn_rate(CLASS_CATCHUP, "fast") > 0

    policies = default_policies()
    policies[CLASS_CATCHUP] = ClassPolicy(
        CLASS_CATCHUP, priority=2, weight=1, flush_mult=8.0,
        deadline_s=0.01)
    expiring = AdmissionQueue(cap_rows=8, max_batch=8,
                              flush_us=1_000_000, policies=policies)
    stale = req(CLASS_CATCHUP)
    expiring.put(stale)
    time.sleep(0.05)
    done = []
    t = __import__("threading").Thread(
        target=lambda: done.append(expiring.take_batch()), daemon=True)
    t.start()
    with pytest.raises(ClassDeadlineExceeded):
        stale.future.result(timeout=5)
    assert fresh_slo._series[CLASS_CATCHUP].m_bad.value == 2
    expiring.close()
    t.join(timeout=5)


def test_soundness_violation_burns_integrity_budget(fresh_slo):
    from gethsharding_tpu.resilience import SoundnessViolation
    from gethsharding_tpu.resilience.soundness import SpotCheckSigBackend

    class LyingBackend(PythonSigBackend):
        name = "liar"

        def ecrecover_addresses(self, digests, sigs65):
            out = super().ecrecover_addresses(digests, sigs65)
            return [None] * len(out)  # silently wrong

    audited = SpotCheckSigBackend(LyingBackend(), rate=1.0, rows=1,
                                  registry=metrics.Registry())
    digest, sig, want = _ecdsa_cases(1, b"slo-integrity")[0]
    with pytest.raises(SoundnessViolation):
        audited.ecrecover_addresses([digest], [sig])
    series = fresh_slo._series["integrity"]
    assert series.m_bad.value == 1
    assert fresh_slo.burn_rate("integrity", "fast") > 0


# == the closed loop: breaker trip moves the burn rate ======================


def test_seeded_breaker_trip_moves_interactive_burn_rate(fresh_slo):
    """ACCEPTANCE: a seeded chaos schedule trips replica r0's breaker;
    the failed attempts burn the interactive class's error budget, so
    the burn-rate gauge measurably rises even though failover answers
    every caller correctly."""
    from gethsharding_tpu.resilience.breaker import (CircuitBreaker,
                                                     FailoverSigBackend)
    from gethsharding_tpu.resilience.chaos import (ChaosSchedule,
                                                   ChaosSigBackend)

    registry = metrics.Registry()
    schedule = ChaosSchedule(seed=7,
                             rules={"backend.ecrecover_addresses": 3})
    r0_serving = ServingSigBackend(
        ChaosSigBackend(PythonSigBackend(), schedule),
        ServingConfig(flush_us=200), registry=registry)
    r1_serving = ServingSigBackend(PythonSigBackend(),
                                   ServingConfig(flush_us=200),
                                   registry=registry)
    router = FleetRouter([
        Replica("c0", FailoverSigBackend(
            r0_serving, PythonSigBackend(),
            breaker=CircuitBreaker(name="slo-c0", fault_threshold=3,
                                   reset_s=60.0)), registry=registry),
        Replica("c1", FailoverSigBackend(
            r1_serving, PythonSigBackend(),
            breaker=CircuitBreaker(name="slo-c1")), registry=registry),
    ], health_interval_s=0.0, registry=registry)
    back = RouterSigBackend(router)
    try:
        before = fresh_slo.burn_rate("interactive", "fast")
        assert before == 0.0
        for digest, sig, want in _ecdsa_cases(8, b"slo-chaos"):
            # every answer stays correct (failover/fallback covers the
            # injected faults) — burn comes from the fleet's attempts
            assert back.ecrecover_addresses([digest], [sig]) == [want]
        assert schedule.injected.get("backend.ecrecover_addresses") == 3
        fresh_slo.sweep()
        after = fresh_slo.burn_rate("interactive", "fast")
        assert after > before
        assert fresh_slo._series["interactive"].m_bad.value >= 1
        gauge = fresh_slo._series["interactive"].g_fast
        assert gauge.value > 0
    finally:
        router.close()
        # router.close() closes the replica backends (and the serving
        # tiers under them)
