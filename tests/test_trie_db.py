"""Trie node database tests: persistence round trips, structure-shared
commits, ref-counted GC (trie/database.go parity) and by-hash sync with
verification (trie/sync.go parity)."""

import pytest

from gethsharding_tpu.core.trie import EMPTY_ROOT, Trie
from gethsharding_tpu.core.trie_db import TrieDatabase, TrieSync, _NODE
from gethsharding_tpu.crypto.keccak import keccak256
from gethsharding_tpu.db.kv import MemoryKV, SqliteKV


def _node_count(db: TrieDatabase) -> int:
    return sum(1 for k, _ in db.kv.items() if k.startswith(_NODE))


def _build(pairs) -> Trie:
    trie = Trie()
    for key, value in pairs:
        trie.update(key, value)
    return trie


PAIRS = [(b"do", b"verb"), (b"dog", b"puppy"), (b"doge", b"coin"),
         (b"horse", b"stallion"), (b"dodge", b"car"),
         (b"h" * 40, b"x" * 100)]


@pytest.mark.parametrize("engine", ["memory", "sqlite"])
def test_commit_load_round_trip(engine, tmp_path):
    kv = (MemoryKV() if engine == "memory"
          else SqliteKV(str(tmp_path / "t.sqlite")))
    db = TrieDatabase(kv)
    trie = _build(PAIRS)
    root = db.commit(trie)
    assert root == trie.root_hash()

    loaded = db.load(root)
    assert loaded.root_hash() == root
    for key, value in PAIRS:
        assert loaded.get(key) == value
    assert loaded.get(b"absent") is None


def test_empty_root_commits_nothing():
    db = TrieDatabase()
    assert db.commit(Trie()) == EMPTY_ROOT
    assert _node_count(db) == 0
    assert db.load(EMPTY_ROOT).root_hash() == EMPTY_ROOT
    assert db.dereference(EMPTY_ROOT) == 0


def test_structure_sharing_and_gc():
    """Two committed versions share unchanged subtrees; dropping one
    root collects exactly its unshared nodes, the survivor stays fully
    loadable; dropping the last root empties the store."""
    db = TrieDatabase()
    v1 = _build(PAIRS)
    root1 = db.commit(v1)
    n1 = _node_count(db)

    v2 = _build(PAIRS)
    v2.update(b"dog", b"wolf")  # touch one path only
    root2 = db.commit(v2)
    assert root2 != root1
    n_both = _node_count(db)
    # the delta is far smaller than a full second trie
    assert n_both < 2 * n1

    assert db.dereference(root1) > 0
    survivor = db.load(root2)  # must not have lost shared nodes
    assert survivor.get(b"dog") == b"wolf"
    assert survivor.get(b"horse") == b"stallion"
    with pytest.raises(KeyError):
        db.load(root1)

    assert db.dereference(root2) > 0
    assert _node_count(db) == 0  # full GC: nothing leaks


def test_multiple_references_are_sticky():
    db = TrieDatabase()
    trie = _build(PAIRS)
    root = db.commit(trie)
    db.reference(root)  # second external ref
    assert db.dereference(root) == 0  # still held
    assert db.load(root).get(b"doge") == b"coin"
    assert db.dereference(root) > 0
    assert _node_count(db) == 0


def test_trie_sync_pulls_and_verifies():
    """Sync a trie from a source database by node hash; every blob is
    verified; a corrupted source blob is rejected."""
    source = TrieDatabase()
    trie = _build(PAIRS)
    root = source.commit(trie)

    fetches = []

    def fetch(h):
        fetches.append(h)
        return source.node(h)

    target = TrieDatabase()
    sync = TrieSync(target)
    assert sync.missing(root) == [root]
    n = sync.run(root, fetch)
    assert n == len(fetches) == _node_count(target) == _node_count(source)
    assert sync.missing(root) == []
    loaded = target.load(root)
    for key, value in PAIRS:
        assert loaded.get(key) == value
    # the synced trie has consistent refcounts: GC empties the store
    assert target.dereference(root) == n
    assert _node_count(target) == 0

    # a corrupt blob fails hash verification
    bad = TrieSync(TrieDatabase())
    with pytest.raises(ValueError, match="verification"):
        bad.run(root, lambda h: b"\x00" + (source.node(h) or b"")[1:])

    # a source that cannot provide a node raises KeyError
    with pytest.raises(KeyError):
        TrieSync(TrieDatabase()).run(root, lambda h: None)


def test_sync_on_top_of_partial_overlap():
    """Syncing a second root into a database that already holds a
    shared subtree fetches only the delta and keeps GC consistent."""
    source = TrieDatabase()
    v1 = _build(PAIRS)
    r1 = source.commit(v1)
    v2 = _build(PAIRS)
    v2.update(b"dog", b"wolf")
    r2 = source.commit(v2)

    target = TrieDatabase()
    TrieSync(target).run(r1, source.node)
    delta = TrieSync(target).run(r2, source.node)
    assert 0 < delta < _node_count(source)
    assert target.load(r2).get(b"dog") == b"wolf"
    # drop both roots: everything collects
    assert target.dereference(r1) > 0
    assert target.load(r2).get(b"horse") == b"stallion"
    target.dereference(r2)
    assert _node_count(target) == 0
