"""Device introspection plane (gethsharding_tpu/devscope/).

Coverage map (the ISSUE 14 checklist):
- memory poller gauges on fake devices, totals, watermark ring bounds;
- buffer census: owner attribution, unattributed remainder, and the
  LRU-vs-census drift cross-check (agreeing books are silent, lying
  books count);
- the seeded recompile-storm detector: fires exactly once per episode,
  silent on steady state, re-arms after the window drains;
- compile-span wall-time booking + the sigbackend _note_shape feed;
- profiler start/stop idempotence, bounded+pruned session directory,
  sampler collapsed stacks + unique-stack budget + overhead guard;
- the RPC surface (shard_profileStart/Stop/Stacks/devscopeStatus), the
  StatusServer /profile routes + /status devscope section, Prometheus
  rows;
- near-OOM -> flight-recorder bundle containing the census;
- perfwatch ledger records carrying peak-HBM/compile-time fields;
- the log<->trace correlation filter.
"""

from __future__ import annotations

import json
import logging
import os
import time
import urllib.request

import pytest

from gethsharding_tpu import devscope, metrics, tracing
from gethsharding_tpu.devscope import (
    COMPILES,
    CompileWatch,
    MemoryPoller,
    PROFILER,
    ProfileManager,
    SamplingProfiler,
)
from gethsharding_tpu.devscope import memory as devscope_memory


class FakeDevice:
    def __init__(self, device_id=0, in_use=100 << 20, peak=150 << 20,
                 limit=16 << 30, platform="tpu"):
        self.id = device_id
        self.platform = platform
        self.in_use = in_use
        self.peak = peak
        self.limit = limit

    def memory_stats(self):
        return {"bytes_in_use": self.in_use,
                "peak_bytes_in_use": self.peak,
                "bytes_limit": self.limit}


class FakeBuffer:
    def __init__(self, nbytes, shape=(8, 8), dtype="int32"):
        self.nbytes = nbytes
        self.shape = shape
        self.dtype = dtype


@pytest.fixture(autouse=True)
def _clean_owners_and_profiler():
    yield
    for name in devscope.owners():
        if name.startswith("test_"):
            devscope.unregister_owner(name)
    PROFILER.stop()


# == memory poller =========================================================


def test_poller_gauges_on_fake_devices():
    devs = [FakeDevice(0, in_use=10, peak=20, limit=100),
            FakeDevice(3, in_use=30, peak=40, limit=200)]
    poller = MemoryPoller(interval_s=60, devices_fn=lambda: devs,
                          buffers_fn=lambda: [])
    readings = poller.poll_once()
    assert readings == {
        "d0": {"bytes_in_use": 10, "peak_bytes": 20, "limit": 100,
               "platform": "tpu"},
        "d3": {"bytes_in_use": 30, "peak_bytes": 40, "limit": 200,
               "platform": "tpu"},
    }
    reg = metrics.DEFAULT_REGISTRY
    assert reg.gauge("devscope/mem/d0/bytes_in_use").value == 10
    assert reg.gauge("devscope/mem/d3/peak_bytes").value == 40
    assert reg.gauge("devscope/mem/d3/limit").value == 200
    # process totals span the devices
    assert metrics.gauge("devscope/mem/bytes_in_use").value == 40
    assert metrics.gauge("devscope/mem/limit").value == 300
    assert poller.peak_bytes() == 40


def test_poller_devices_without_stats_are_skipped():
    class Bare:
        pass

    poller = MemoryPoller(interval_s=60, devices_fn=lambda: [Bare()],
                          buffers_fn=lambda: [])
    assert poller.poll_once() == {}


def test_poller_thread_start_stop_idempotent():
    poller = MemoryPoller(interval_s=0.01, devices_fn=lambda: [FakeDevice()],
                          buffers_fn=lambda: [])
    poller.start()
    poller.start()  # second start is a no-op, not a second thread
    deadline = time.monotonic() + 5.0
    while poller.polls == 0 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert poller.polls > 0
    poller.stop()
    assert not poller.running
    poller.stop()  # idempotent


def test_watermark_ring_records_and_bounds(monkeypatch):
    monkeypatch.setenv("GETHSHARDING_DEVSCOPE_WATERMARKS", "4")
    dev = FakeDevice(0, in_use=0, peak=0, limit=1000)
    poller = MemoryPoller(interval_s=60, devices_fn=lambda: [dev],
                          buffers_fn=lambda: [])
    for peak in range(1, 10):
        dev.peak = peak
        poller.poll_once()
    marks = poller.watermarks()
    assert len(marks) == 4  # bounded
    assert [m["bytes"] for m in marks] == [6, 7, 8, 9]  # newest kept
    dev.peak = 9  # no new high-watermark -> no new entry
    poller.poll_once()
    assert len(poller.watermarks()) == 4
    assert poller.watermarks()[-1]["bytes"] == 9


# == census + drift ========================================================


def test_census_attributes_owned_and_unattributed():
    owned = [FakeBuffer(100), FakeBuffer(50)]
    stray = [FakeBuffer(7, shape=(7,), dtype="uint8")]
    devscope.register_owner("test_plane",
                            claimed_fn=lambda: 150,
                            buffers_fn=lambda: list(owned))
    poller = MemoryPoller(interval_s=60, devices_fn=lambda: [],
                          buffers_fn=lambda: owned + stray)
    census = poller.census()
    assert census["live_buffers"] == 3
    assert census["live_bytes"] == 157
    assert census["by_owner"]["test_plane"] == {"buffers": 2, "bytes": 150}
    assert census["by_owner"]["unattributed"] == {"buffers": 1, "bytes": 7}
    assert census["owners"]["test_plane"]["drifted"] is False
    assert census["top_groups"][0]["bytes"] == 150  # (int32, (8,8)) group


def test_census_drift_detection():
    """An owner whose claimed bytes disagree with what the census sees
    beyond the tolerance is a drift count; honest books are silent."""
    bufs = [FakeBuffer(10 << 20)]
    claimed = {"v": 10 << 20}
    devscope.register_owner("test_lru",
                            claimed_fn=lambda: claimed["v"],
                            buffers_fn=lambda: list(bufs))
    poller = MemoryPoller(interval_s=60, devices_fn=lambda: [],
                          buffers_fn=lambda: list(bufs))
    before = metrics.counter("devscope/mem/drift").value
    census = poller.census()
    assert census["owners"]["test_lru"]["drifted"] is False
    assert metrics.counter("devscope/mem/drift").value == before
    claimed["v"] = 30 << 20  # the books now lie by 20 MiB
    census = poller.census()
    assert census["owners"]["test_lru"]["drifted"] is True
    assert census["owners"]["test_lru"]["drift_bytes"] == 20 << 20
    assert metrics.counter("devscope/mem/drift").value == before + 1
    # PERSISTENT drift is one episode, not one count per census
    poller.census()
    assert metrics.counter("devscope/mem/drift").value == before + 1
    claimed["v"] = 10 << 20  # books heal -> latch re-arms
    poller.census()
    claimed["v"] = 30 << 20  # a NEW drift episode counts again
    poller.census()
    assert metrics.counter("devscope/mem/drift").value == before + 2


def test_drift_detected_by_plain_polling():
    """The census (and its drift cross-check) runs on EVERY poll, not
    only when a near-OOM fires — a leak with a bookkeeper must not
    need the device to already be on fire to show up."""
    bufs = [FakeBuffer(10 << 20)]
    claimed = {"v": 10 << 20}
    devscope.register_owner("test_poll_drift",
                            claimed_fn=lambda: claimed["v"],
                            buffers_fn=lambda: list(bufs))
    reg = metrics.Registry()
    poller = MemoryPoller(interval_s=60,
                          devices_fn=lambda: [FakeDevice()],
                          buffers_fn=lambda: list(bufs), registry=reg)
    poller.poll_once()
    assert poller.describe()["last_census"] is not None
    assert poller.describe()["drift_events"] == 0
    claimed["v"] = 40 << 20  # the books start lying
    poller.poll_once()
    assert poller.describe()["drift_events"] == 1


def test_isolated_registry_poller_never_touches_process_rows():
    reg = metrics.Registry()
    poller = MemoryPoller(
        interval_s=60,
        devices_fn=lambda: [FakeDevice(in_use=990, peak=995, limit=1000)],
        buffers_fn=lambda: [], registry=reg)
    polls_before = metrics.counter("devscope/mem/polls").value
    oom_before = metrics.counter("devscope/mem/near_oom").value
    in_use_before = metrics.gauge("devscope/mem/bytes_in_use").value
    poller.poll_once()  # fake device at 99% utilization
    assert metrics.counter("devscope/mem/polls").value == polls_before
    assert metrics.counter("devscope/mem/near_oom").value == oom_before
    assert metrics.gauge(
        "devscope/mem/bytes_in_use").value == in_use_before
    assert reg.counter("devscope/mem/polls").value == 1
    assert reg.counter("devscope/mem/near_oom").value == 1


def test_observe_peaks_has_no_side_effects():
    """The ledger stamp's read path: peaks/watermarks advance, but no
    gauges publish, no census runs and no near-OOM dump can fire from
    inside the ledger writer."""
    reg = metrics.Registry()
    poller = MemoryPoller(
        interval_s=60,
        devices_fn=lambda: [FakeDevice(in_use=990, peak=995, limit=1000)],
        buffers_fn=lambda: [], registry=reg)
    assert poller.observe_peaks() == 995
    assert poller.watermarks()[-1]["bytes"] == 995
    assert reg.counter("devscope/mem/polls").value == 0
    assert reg.counter("devscope/mem/near_oom").value == 0  # 99% util!
    assert poller.describe()["last_census"] is None


def test_census_keyless_owner_never_drifts():
    """An owner with no buffers_fn cannot be censused — claimed bytes
    are reported but never cross-checked (no false drift)."""
    devscope.register_owner("test_blind", claimed_fn=lambda: 123)
    poller = MemoryPoller(interval_s=60, devices_fn=lambda: [],
                          buffers_fn=lambda: [FakeBuffer(1)])
    census = poller.census()
    assert census["owners"]["test_blind"]["claimed_bytes"] == 123
    assert census["owners"]["test_blind"]["drifted"] is False


def test_resident_lru_registers_as_owner():
    """The jax backend's resident pk-plane LRU registers at
    construction (no dispatch needed: the claimed/buffers callbacks
    read the cache state directly)."""
    pytest.importorskip("jax")
    from gethsharding_tpu.sigbackend import JaxSigBackend

    backend = object.__new__(JaxSigBackend)
    import threading
    from collections import OrderedDict

    backend._pk_dev_lock = threading.Lock()
    backend._pk_dev_cache = OrderedDict()
    backend._pk_dev_bytes = 0
    backend._pk_batch_memo = None
    backend._pk_zero_rows = {}
    devscope.register_owner("pk_plane_lru",
                            claimed_fn=backend._resident_claimed_bytes,
                            buffers_fn=backend._resident_buffers)
    assert "pk_plane_lru" in devscope.owners()
    assert backend._resident_claimed_bytes() == 0
    assert backend._resident_buffers() == []
    entry = (FakeBuffer(10), FakeBuffer(10), FakeBuffer(2), 22)
    backend._pk_dev_cache["k"] = entry
    backend._pk_dev_bytes = 22
    assert backend._resident_claimed_bytes() == 22
    assert len(backend._resident_buffers()) == 3
    devscope.unregister_owner("pk_plane_lru")  # stub backend, not the
    # process singleton — later censuses must not read it


# == near-OOM -> flight-recorder bundle ====================================


def test_near_oom_dumps_census_bundle(tmp_path, monkeypatch):
    monkeypatch.setenv("GETHSHARDING_PERFWATCH_DIR", str(tmp_path / "bb"))
    monkeypatch.setenv("GETHSHARDING_PERFWATCH_DUMP_S", "0")
    from gethsharding_tpu.perfwatch.recorder import RECORDER

    bufs = [FakeBuffer(48 << 20), FakeBuffer(4 << 20)]
    devscope.register_owner("test_oom_plane",
                            claimed_fn=lambda: sum(b.nbytes for b in bufs),
                            buffers_fn=lambda: list(bufs))
    dev = FakeDevice(0, in_use=950, peak=960, limit=1000)
    poller = MemoryPoller(interval_s=60, devices_fn=lambda: [dev],
                          buffers_fn=lambda: list(bufs))
    before = metrics.counter("devscope/mem/near_oom").value
    poller.poll_once()
    assert metrics.counter("devscope/mem/near_oom").value == before + 1
    deadline = time.monotonic() + 10.0
    bundle = None
    while time.monotonic() < deadline:
        RECORDER.flush()
        base = str(tmp_path / "bb")
        dirs = sorted(os.listdir(base)) if os.path.isdir(base) else []
        if dirs:
            bundle = os.path.join(base, dirs[-1])
            break
        time.sleep(0.05)
    assert bundle is not None, "near-OOM produced no bundle"
    events = json.load(open(os.path.join(bundle, "events.json")))
    oom = [e for e in events if e["kind"] == "hbm_near_oom"]
    assert oom, sorted({e["kind"] for e in events})
    detail = oom[-1]["detail"]
    assert detail["device"] == "d0"
    assert detail["utilization"] == 0.95
    census = detail["census"]
    assert census["by_owner"]["test_oom_plane"]["bytes"] == 52 << 20
    assert detail["watermarks"], "watermark tail missing from the event"
    # the episode latch: same utilization again must not re-fire
    poller.poll_once()
    assert metrics.counter("devscope/mem/near_oom").value == before + 1
    # hysteresis: clear well below the line, then cross again -> refires
    dev.in_use = 100
    poller.poll_once()
    dev.in_use = 950
    poller.poll_once()
    assert metrics.counter("devscope/mem/near_oom").value == before + 2


# == compile watch =========================================================


def _seeded_watch(threshold=4, window=30.0):
    clock = {"t": 1000.0}
    watch = CompileWatch(storm_shapes=threshold, storm_window_s=window,
                         clock=lambda: clock["t"])
    return watch, clock


def test_storm_detector_fires_once_and_rearms():
    watch, clock = _seeded_watch(threshold=4, window=30.0)
    from gethsharding_tpu.perfwatch.recorder import RECORDER

    def storm_events():
        return sum(1 for e in RECORDER.events()
                   if e["kind"] == "recompile_storm")

    before = storm_events()
    # steady state: repeats of known shapes never storm
    for _ in range(100):
        watch.saw("op", (128,), False)
    assert watch.storms == 0
    # 3 fresh shapes spread over hours: under threshold, silent
    for i in range(3):
        clock["t"] += 3600
        watch.saw("op", (i,), True)
    assert watch.storms == 0 and storm_events() == before
    # the storm: threshold fresh shapes inside one window, fires ONCE
    for i in range(10, 20):
        clock["t"] += 0.1
        watch.saw("op", (i,), True)
    assert watch.storms == 1
    assert storm_events() == before + 1
    assert watch.storm_active() is True
    assert metrics.gauge("devscope/compile/storm").value == 1
    # the window drains -> verdict clears, gauge resets
    clock["t"] += 31.0
    assert watch.storm_active() is False
    assert metrics.gauge("devscope/compile/storm").value == 0
    # a SECOND storm is a new episode: fires exactly once again
    for i in range(30, 40):
        clock["t"] += 0.1
        watch.saw("op", (i,), True)
    assert watch.storms == 2
    assert storm_events() == before + 2


def test_compile_span_books_wall_per_shape():
    watch, _ = _seeded_watch()
    with watch.compile_span("ecrecover", (64,), True):
        time.sleep(0.02)
    with watch.compile_span("ecrecover", (64,), False):
        time.sleep(0.05)  # a HIT is never booked as compile time
    desc = watch.describe()
    assert desc["compiles"] == 1
    assert 0.015 < desc["total_s"] < 0.05
    top = desc["top_shapes"][0]
    assert top["op"] == "ecrecover" and top["shape"] == [64]
    assert top["compiles"] == 1


def test_note_shape_feeds_process_compile_watch():
    """The sigbackend per-shape cache feeds the process COMPILES
    singleton (storm window + per-shape ledger) on fresh shapes."""
    import threading

    from gethsharding_tpu.sigbackend import JaxSigBackend

    backend = object.__new__(JaxSigBackend)
    backend._shape_seen = set()
    backend._shape_lock = threading.Lock()
    backend._m_shape_hit = metrics.counter("jax/compile_cache/hits")
    backend._m_shape_miss = metrics.counter("jax/compile_cache/misses")
    backend._compiles = COMPILES
    key = ("test_note_shape_op", time.monotonic())
    before = COMPILES.describe()["unique_shapes"]
    assert backend._note_shape(*key) is True
    assert backend._note_shape(*key) is False  # the hit path early-outs
    assert COMPILES.describe()["unique_shapes"] == before + 1


def test_ledger_records_carry_devscope_fields(tmp_path):
    from gethsharding_tpu.perfwatch import Ledger, record_bench

    COMPILES.note_compile("test_ledger_op", (1,), 0.5)
    ledger = Ledger(str(tmp_path / "ledger.jsonl"))
    rec = record_bench(metric="test_metric_per_sec", value=10.0,
                       extra={}, ledger=ledger)
    # peak-HBM is a GATED metric (memory creep flags like latency)...
    assert "peak_hbm_bytes" in rec["metrics"]
    from gethsharding_tpu.perfwatch import direction_for

    assert direction_for("peak_hbm_bytes") == "lower"
    # ...while the process-cumulative compile attribution rides in
    # extra: gating it would flag invocation composition, not growth
    assert rec["extra"]["compile_total_s"] > 0
    assert rec["extra"]["compile_count"] >= 1
    assert "compile_total_s" not in rec["metrics"]
    # replayed captures measured ANOTHER process's device: stamping
    # this host's peak (0) into their group would poison the baseline
    replay = record_bench(metric="test_metric_per_sec", value=10.0,
                          extra={"platform": "tpu"}, source="replay",
                          ledger=ledger)
    assert "peak_hbm_bytes" not in replay["metrics"]
    assert "compile_total_s" not in replay["extra"]


# == profiler ==============================================================


def test_profiler_start_stop_idempotent(tmp_path, monkeypatch):
    monkeypatch.setenv("GETHSHARDING_DEVSCOPE_PROFILE_DIR",
                       str(tmp_path / "prof"))
    manager = ProfileManager()
    out = manager.start(mode="sampler", hz=500)
    assert out["started"] is True
    again = manager.start(mode="sampler")
    assert again.get("already_running") is True
    assert manager.sessions == 1  # the double start opened ONE session
    stopped = manager.stop()
    assert stopped["stopped"] is True
    assert manager.stop() == {"stopped": False, "reason": "not running"}


def test_jax_only_stop_preserves_last_sampler_stacks(tmp_path,
                                                     monkeypatch):
    """A mode=jax session has no sampler; its stop() must not wipe the
    previous sampler session's downloadable stacks."""
    monkeypatch.setenv("GETHSHARDING_DEVSCOPE_PROFILE_DIR",
                       str(tmp_path / "prof"))
    monkeypatch.setattr(ProfileManager, "_start_jax_trace",
                        lambda self: (str(tmp_path / "prof" / "s1"), None))
    monkeypatch.setattr(ProfileManager, "_stop_jax_trace",
                        staticmethod(lambda: True))
    manager = ProfileManager()
    manager.start(mode="sampler", hz=500)
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and not manager.stacks():
        time.sleep(0.01)
    manager.stop()
    stacks = manager.stacks()
    assert stacks
    manager.start(mode="jax")
    manager.stop()
    assert manager.stacks() == stacks  # the artifact survived


def test_storm_gauge_clears_via_booted_poller_heartbeat():
    """The booted poller's tick drains the storm verdict, so a
    prom-only scraper sees devscope/compile/storm reset without anyone
    hitting /status."""
    inst = devscope.boot(start_poller=False)
    try:
        inst._devices_fn = lambda: []
        inst._buffers_fn = lambda: []
        gauge = metrics.gauge("devscope/compile/storm")
        gauge.set(1)  # a storm latched earlier, window since drained
        inst.poll_once()
        assert gauge.value == 0
    finally:
        devscope.shutdown()


def test_profiler_bad_mode_rejected():
    with pytest.raises(ValueError):
        ProfileManager().start(mode="flamegraph")


def test_profiler_build_failure_does_not_wedge(monkeypatch):
    """A throw mid-build (bad sample-rate env) must roll the session
    claim back — the next corrected start works, no phantom
    already_running."""
    manager = ProfileManager()
    monkeypatch.setenv("GETHSHARDING_DEVSCOPE_SAMPLE_HZ", "abc")
    with pytest.raises(ValueError):
        manager.start(mode="sampler")  # hz=None reads the broken env
    assert manager.describe()["active"] is False
    monkeypatch.delenv("GETHSHARDING_DEVSCOPE_SAMPLE_HZ")
    out = manager.start(mode="sampler", hz=500)
    assert out["started"] is True
    manager.stop()


def test_default_devices_require_initialized_backend(monkeypatch):
    """The poller must never be the thing that initializes a jax
    backend (a first init over a dead tunnel hangs): with jax imported
    but the bridge's backend cache empty, device/buffer enumeration
    reads as no devices."""
    import sys as _sys

    monkeypatch.setitem(_sys.modules, "jax._src.xla_bridge",
                        type("B", (), {"_backends": {}})())
    assert devscope_memory._default_devices() == []
    assert devscope_memory._default_buffers() == []


def test_profiler_session_dir_bounded(tmp_path, monkeypatch):
    base = tmp_path / "prof"
    base.mkdir()
    monkeypatch.setenv("GETHSHARDING_DEVSCOPE_PROFILE_DIR", str(base))
    monkeypatch.setenv("GETHSHARDING_DEVSCOPE_PROFILE_KEEP", "3")
    for i in range(7):
        (base / f"2026010{i}_000000_1").mkdir()
    ProfileManager._prune(str(base))
    kept = sorted(os.listdir(base))
    assert len(kept) == 3
    assert kept == ["20260104_000000_1", "20260105_000000_1",
                    "20260106_000000_1"]  # newest survive


def test_profiler_stacks_survive_stop(tmp_path, monkeypatch):
    monkeypatch.setenv("GETHSHARDING_DEVSCOPE_PROFILE_DIR",
                       str(tmp_path / "prof"))
    manager = ProfileManager()
    manager.start(mode="sampler", hz=500)
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and not manager.stacks():
        time.sleep(0.01)
    manager.stop()
    assert manager.stacks(), "last session's stacks must stay downloadable"


def _with_sibling_thread(fn):
    """Run `fn` while one parked sibling thread exists — the sampler
    excludes its OWN thread, so a single-threaded test process would
    have nothing to sample."""
    import threading

    release = threading.Event()
    thread = threading.Thread(target=release.wait, daemon=True,
                              name="devscope-test-sleeper")
    thread.start()
    try:
        return fn()
    finally:
        release.set()
        thread.join(timeout=5.0)


def test_sampler_collapsed_stacks_and_budget():
    sampler = SamplingProfiler(hz=1000, max_stacks=1)

    def drive():
        for _ in range(20):
            sampler.sample_once()

    _with_sibling_thread(drive)
    text = sampler.collapsed()
    assert text, "a sibling thread's stack must be visible"
    head = text.splitlines()[0]
    stack, _, count = head.rpartition(" ")
    assert int(count) > 0 and stack  # "a;b;c N" shape
    desc = sampler.describe()
    assert desc["unique_stacks"] <= 1  # the budget held
    assert desc["samples"] == 20


def test_sampler_overhead_guard():
    """The duty cycle the sampler charges at its configured rate stays
    under the 2%-of-a-request budget (the bench closed loop asserts
    the same bound against a real serving request)."""
    sampler = SamplingProfiler()  # default hz
    sampler.sample_once()  # warm
    n = 200
    t0 = time.perf_counter()
    for _ in range(n):
        sampler.sample_once()
    tick_s = (time.perf_counter() - t0) / n
    duty_pct = 100.0 * sampler.hz * tick_s
    assert duty_pct < 2.0, (
        f"sampler duty cycle {duty_pct:.3f}% at {sampler.hz}Hz "
        f"({tick_s * 1e6:.1f}us/tick)")


def test_sampler_chrome_export_merges(tmp_path):
    sampler = SamplingProfiler(hz=100)
    _with_sibling_thread(lambda: [sampler.sample_once()
                                  for _ in range(5)])
    path = tmp_path / "samples.json"
    events = sampler.write_chrome_trace(str(path))
    assert events > 0
    payload = json.loads(path.read_text())
    assert "clock_offset_us" in payload["otherData"]  # the merge anchor
    assert payload["traceEvents"][0]["ph"] == "M"  # process_name lane
    # the span export and the sampler export fold into one view
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "trace_merge", os.path.join(os.path.dirname(__file__), "..",
                                    "scripts", "trace_merge.py"))
    trace_merge = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(trace_merge)
    merged = trace_merge.merge_traces([payload])
    assert sum(1 for e in merged["traceEvents"] if e["ph"] == "X") == events


# == surfaces: RPC, StatusServer, Prometheus ===============================


def test_rpc_profile_surface(tmp_path, monkeypatch):
    monkeypatch.setenv("GETHSHARDING_DEVSCOPE_PROFILE_DIR",
                       str(tmp_path / "prof"))
    from gethsharding_tpu.params import Config
    from gethsharding_tpu.rpc.client import RPCClient
    from gethsharding_tpu.rpc.server import RPCServer
    from gethsharding_tpu.smc.chain import SimulatedMainchain

    server = RPCServer(SimulatedMainchain(config=Config()))
    server.start()
    client = RPCClient(*server.address)
    try:
        out = client.call("shard_profileStart", "sampler", 500)
        assert out["started"] is True
        assert client.call("shard_profileStart")["already_running"] is True
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            client.call("shard_blockNumber")
            if client.call("shard_profileStacks"):
                break
        assert client.call("shard_profileStop")["stopped"] is True
        stacks = client.call("shard_profileStacks")
        assert stacks and "gethsharding" in stacks
        status = client.call("shard_devscopeStatus")
        assert status["profiler"]["sessions"] >= 1
        assert "compile" in status and "memory" in status
    finally:
        client.close()
        server.stop()


def test_status_server_devscope_surfaces(tmp_path, monkeypatch):
    monkeypatch.setenv("GETHSHARDING_DEVSCOPE_PROFILE_DIR",
                       str(tmp_path / "prof"))
    from gethsharding_tpu.node.backend import ShardNode
    from gethsharding_tpu.node.http_status import StatusServer

    node = ShardNode(actor="observer", txpool_interval=None, http_port=0)
    node.start()
    try:
        port = node.service(StatusServer).port

        def get(path):
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}{path}",
                        timeout=10) as resp:
                    return resp.read().decode()
            except urllib.error.HTTPError as exc:
                # degraded-but-answering routes return 500 + a JSON body
                return exc.read().decode()

        status = json.loads(get("/status"))
        assert "devscope" in status
        assert "compile" in status["devscope"]
        assert "profiler" in status["devscope"]
        out = json.loads(get("/profile?action=start&mode=sampler&hz=500"))
        assert out["started"] is True
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            get("/healthz")
            if get("/profile/stacks"):
                break
        out = json.loads(get("/profile?action=stop"))
        assert out["stopped"] is True
        assert get("/profile/stacks"), "stacks download empty"
        desc = json.loads(get("/profile"))
        assert desc["active"] is False and desc["sessions"] >= 1
        bad = json.loads(get("/profile?action=explode"))
        assert "error" in bad
        prom = get("/metrics?format=prom")
        for row in ("devscope_profiler_sessions",
                    "devscope_compile_count",
                    "devscope_mem_polls"):
            assert row in prom, f"{row} missing from prom exposition"
    finally:
        node.stop()


def test_devscope_status_shape():
    status = devscope.devscope_status()
    assert set(status) == {"memory", "compile", "profiler"}
    assert "storm_active" in status["compile"]
    assert "sessions" in status["profiler"]


def test_boot_disabled_by_env(monkeypatch):
    monkeypatch.setenv("GETHSHARDING_DEVSCOPE", "0")
    assert devscope.boot() is None


def test_boot_idempotent_and_shutdown():
    first = devscope.boot(start_poller=False)
    second = devscope.boot(start_poller=False)
    assert first is second
    assert devscope.poller() is first
    devscope.shutdown()
    assert devscope.poller() is None


# == log <-> trace correlation =============================================


def test_log_filter_stamps_trace_ids(caplog):
    logger = logging.getLogger("sharding.node.test_devscope")
    handler = logging.Handler()
    records = []
    handler.emit = records.append
    handler.addFilter(tracing.LOG_FILTER)
    logger.addHandler(handler)
    was_enabled = tracing.TRACER.enabled
    try:
        tracing.enable()
        with tracing.span("devscope/test") as span:
            logger.warning("inside a span")
        logger.warning("outside any span")
    finally:
        tracing.TRACER.enabled = was_enabled
        logger.removeHandler(handler)
    inside, outside = records
    assert inside.trace_id == str(span.trace_id)
    assert inside.span_id == str(span.span_id)
    assert outside.trace_id == "-"
    assert outside.span_id == "-"
    # the CLI format string renders against the stamped record
    fmt = logging.Formatter("%(levelname)s [%(trace_id)s] %(message)s")
    assert f"[{span.trace_id}]" in fmt.format(inside)
    assert "[-]" in fmt.format(outside)


def test_install_log_correlation_idempotent():
    root = logging.getLogger()
    handler = logging.NullHandler()
    root.addHandler(handler)
    try:
        tracing.install_log_correlation()
        tracing.install_log_correlation()
        assert handler.filters.count(tracing.LOG_FILTER) == 1
    finally:
        root.removeHandler(handler)
