"""Elastic fleet: runtime membership, replicated frontends, and the
SLO-driven autoscaler (gethsharding_tpu/fleet/membership.py,
fleet/autoscaler.py, the frontend's membership RPC plane, and
rpc/client.py's FrontendPool).

The contracts:

- MEMBERSHIP: the registry mutates at runtime under the routing
  invariants — a new replica enters DRAINING and earns HEALTHY through
  the health sweep, a removal drains first and detaches only once
  nothing is in flight, duplicates/unknowns are typed errors, and the
  journal restores the last acked topology across a restart.
- SWEEP TOLERANCE (the regression): a replica removed while the sweep
  is blocked in another replica's health read gets NO stale probe and
  NO stale health fold — its backend is closed and never touched again.
- RENDEZVOUS-MINIMAL RESHUFFLE: admitting (or removing) a replica
  moves ONLY the keys whose rendezvous top choice is the new (gone)
  replica; every other key keeps its exact route.
- CHURN HAMMER: a seeded add/remove loop under concurrent traffic
  produces zero incorrect verdicts and zero non-typed errors.
- REPLICATED FRONTENDS: membership epochs gossip last-writer-wins
  (eager push on local mutations, pull convergence after divergence),
  and `FrontendPool` fails over on the typed draining refusal a
  stopping frontend serves during its drain-notice window — no retry
  burned on a bare connection reset.
- AUTOSCALER: scale-out on fast burn or sustained depth, scale-in only
  when calm is sustained, cooldowns hold (and count) repeat triggers,
  the boot topology is never scaled away, and retired processes are
  reaped once the router lets go.
"""

import threading
import time

import pytest

from gethsharding_tpu import metrics
from gethsharding_tpu.crypto import secp256k1 as ecdsa
from gethsharding_tpu.crypto.keccak import keccak256
from gethsharding_tpu.fleet import (
    AllReplicasDraining,
    FleetRouter,
    Replica,
    ReplicaState,
    RouterSigBackend,
)
from gethsharding_tpu.fleet.autoscaler import AutoscaleConfig, Autoscaler
from gethsharding_tpu.fleet.membership import (
    DuplicateReplicaError,
    FleetMembership,
    MembershipJournal,
    UnknownReplicaError,
)
from gethsharding_tpu.db.kv import MemoryKV
from gethsharding_tpu.serving.classes import CLASS_BULK_AUDIT
from gethsharding_tpu.sigbackend import PythonSigBackend


def _registry() -> metrics.Registry:
    return metrics.Registry()


def _ecdsa_cases(n: int):
    cases = []
    for i in range(n):
        priv = int.from_bytes(keccak256(b"elastic-%d" % i), "big") % ecdsa.N
        digest = keccak256(b"elastic-msg-%d" % i)
        cases.append((digest, ecdsa.sign(digest, priv).to_bytes65(),
                      ecdsa.priv_to_address(priv)))
    return cases


def _boot_fleet(registry, n: int = 2, health_interval_s: float = 0.0):
    """A router over `n` in-proc replicas plus its membership plane
    (make_replica builds in-proc replicas named by their endpoint)."""
    def make(endpoint: str) -> Replica:
        return Replica(endpoint, PythonSigBackend(), probe=None,
                       registry=registry)

    boot = [Replica(f"r{i}", PythonSigBackend(), probe=None,
                    registry=registry) for i in range(n)]
    router = FleetRouter(boot, health_interval_s=health_interval_s,
                         registry=registry)
    membership = FleetMembership(
        router, make, seed={f"r{i}": f"boot:{i}" for i in range(n)},
        registry=registry)
    return router, membership


# == runtime membership =====================================================


def test_admission_enters_draining_and_sweep_promotes():
    registry = _registry()
    router, membership = _boot_fleet(registry)
    try:
        out = membership.add("ep:new")
        assert out["epoch"] == 1
        assert out["state"] == ReplicaState.DRAINING
        # not offered work yet: route() only walks accepting replicas
        assert all(r.name != "ep:new"
                   for r in router.route(affinity="some-key"))
        router.refresh(force=True)  # the sweep reads real health
        states = router.states()
        assert states["ep:new"]["state"] == ReplicaState.HEALTHY
    finally:
        router.close()


def test_removal_drains_then_detaches_and_typed_errors():
    registry = _registry()
    router, membership = _boot_fleet(registry)
    try:
        membership.add("ep:new")
        with pytest.raises(DuplicateReplicaError):
            membership.add("ep:new")
        out = membership.remove("ep:new")
        assert out["detached"] is True  # idle: detached immediately
        assert "ep:new" not in membership.endpoints()
        with pytest.raises(UnknownReplicaError):
            membership.remove("ep:new")
        # the boot seed removes by NAME too (names predate endpoints)
        out = membership.remove("r1")
        assert out["detached"] is True
        assert len(router.members()) == 1
    finally:
        router.close()


def test_removal_waits_for_in_flight_work():
    """A busy replica drains (no new work) but detaches only once its
    in-flight call finishes — no live request sees the endpoint die."""
    registry = _registry()
    router, membership = _boot_fleet(registry, n=1)
    try:
        membership.add("ep:busy")
        router.refresh(force=True)
        busy = router._replica("ep:busy")
        with busy.flight():
            out = membership.remove("ep:busy")
            assert out["detached"] is False
            assert busy.state == ReplicaState.DRAINING
            assert not busy.detached
            router.refresh(force=True)  # sweep must NOT detach it yet
            assert not busy.detached
        router.refresh(force=True)  # flight done: the sweep completes it
        assert busy.detached
        assert all(r.name != "ep:busy" for r in router.members())
    finally:
        router.close()


def test_journal_restores_last_acked_topology():
    registry = _registry()
    kv = MemoryKV()
    router, _ = _boot_fleet(registry, n=1)
    journal = MembershipJournal(kv, registry=registry)
    try:
        membership = FleetMembership(
            router, lambda e: Replica(e, PythonSigBackend(), probe=None,
                                      registry=registry),
            journal=journal, seed={"r0": "boot:0"}, registry=registry)
        assert membership.restore() is False  # fresh journal: seed acked
        membership.add("ep:a")
        membership.add("ep:b")
        membership.remove("ep:a")
        epoch = membership.epoch
        assert epoch == 3
    finally:
        router.close()
    # "restart": a new process boots from the stale command line
    registry2 = _registry()
    router2, _ = _boot_fleet(registry2, n=1)
    try:
        membership2 = FleetMembership(
            router2, lambda e: Replica(e, PythonSigBackend(), probe=None,
                                       registry=registry2),
            journal=MembershipJournal(kv, registry=registry2),
            seed={"r0": "boot:0"}, registry=registry2)
        assert membership2.restore() is True
        assert membership2.epoch == epoch
        assert "ep:b" in membership2.endpoints()
        assert "ep:a" not in membership2.endpoints()
    finally:
        router2.close()


# == the sweep tolerates concurrent mutation (the regression) ===============


def test_mid_sweep_removal_skips_stale_replica():
    """Remove a replica while the sweep is BLOCKED in the previous
    replica's health read: the removed replica must get no stale health
    read and no stale probe, and its backend must be closed."""
    registry = _registry()
    entered = threading.Event()
    release = threading.Event()

    def blocking_health():
        entered.set()
        assert release.wait(5)
        return {"breaker": None, "draining": False}

    b_calls = {"health": 0, "probe": 0}

    class Closable(PythonSigBackend):
        closed = False

        def close(self):
            self.closed = True

    def b_health():
        b_calls["health"] += 1
        return {"breaker": "open", "draining": True}

    def b_probe():
        b_calls["probe"] += 1

    backend_b = Closable()
    replicas = [
        Replica("A", PythonSigBackend(), health=blocking_health,
                probe=None, registry=registry),
        Replica("B", backend_b, health=b_health, probe=b_probe,
                registry=registry),
    ]
    router = FleetRouter(replicas, health_interval_s=0.0,
                         registry=registry)
    try:
        sweep = threading.Thread(
            target=lambda: router.refresh(force=True))
        sweep.start()
        assert entered.wait(5)  # the sweep holds A's health read
        state = router.remove_replica("B")  # mid-sweep removal
        assert state["detached"] is True
        assert backend_b.closed
        release.set()
        sweep.join(timeout=5)
        assert not sweep.is_alive()
        # the regression: no stale health read, no probe-back-to-life
        assert b_calls == {"health": 0, "probe": 0}
        assert [r.name for r in router.members()] == ["A"]
    finally:
        release.set()
        router.close()


# == rendezvous-minimal reshuffle ===========================================


def test_admission_moves_only_rendezvous_minimal_keys():
    registry = _registry()
    router, membership = _boot_fleet(registry, n=3)
    keys = [f"shard-{i}" for i in range(64)]
    try:
        before = {k: router.route(affinity=k)[0].name for k in keys}
        membership.add("ep:new")
        router.refresh(force=True)  # promote the admission
        after = {k: router.route(affinity=k)[0].name for k in keys}
        moved = {k for k in keys if after[k] != before[k]}
        assert moved, "rendezvous should hand SOME keys to the new node"
        # minimality: every moved key moved TO the new replica, every
        # other key kept its exact first choice
        assert all(after[k] == "ep:new" for k in moved)
        # and removal restores the original assignment exactly
        membership.remove("ep:new")
        restored = {k: router.route(affinity=k)[0].name for k in keys}
        assert restored == before
    finally:
        router.close()


# == churn hammer ===========================================================


def test_membership_churn_hammer_zero_incorrect_verdicts():
    """Seeded add/remove churn under concurrent traffic: every verdict
    correct, every error typed (AllReplicasDraining only)."""
    import random

    registry = _registry()
    router, membership = _boot_fleet(registry, n=2,
                                     health_interval_s=0.02)
    back = RouterSigBackend(router)
    cases = _ecdsa_cases(8)
    stop = threading.Event()
    wrong: list = []
    untyped: list = []

    def traffic():
        i = 0
        while not stop.is_set():
            digest, sig, want = cases[i % len(cases)]
            i += 1
            try:
                out = back.ecrecover_addresses([digest], [sig])
                if out != [want]:
                    wrong.append((want, out))
            except AllReplicasDraining:
                pass  # typed fleet weather
            except Exception as exc:  # noqa: BLE001 - the assertion
                untyped.append(exc)

    threads = [threading.Thread(target=traffic) for _ in range(3)]
    for thread in threads:
        thread.start()
    rnd = random.Random(0x5EED)
    extra: list = []
    try:
        for step in range(40):
            if extra and rnd.random() < 0.45:
                membership.remove(extra.pop(rnd.randrange(len(extra))))
            else:
                endpoint = f"ep:{step}"
                membership.add(endpoint)
                extra.append(endpoint)
            if rnd.random() < 0.5:
                router.refresh(force=True)
            time.sleep(0.002)
    finally:
        stop.set()
        for thread in threads:
            thread.join(timeout=10)
        router.close()
    assert not wrong, f"incorrect verdicts under churn: {wrong[:3]}"
    assert not untyped, f"non-typed errors under churn: {untyped[:3]}"
    # the boot replicas never left
    assert membership.epoch == 40
    names = {r.name for r in router.members()}
    assert {"r0", "r1"} <= names


# == replicated frontends: gossip + FrontendPool ============================


def _frontend(registry, peers=None):
    from gethsharding_tpu.fleet.frontend import FrontendServer

    router, membership = _boot_fleet(registry, n=1,
                                     health_interval_s=0.05)
    server = FrontendServer(router, port=0, membership=membership,
                            peers=peers or [], gossip_interval_s=30.0)
    server.start()
    return server


def test_membership_epochs_gossip_last_writer_wins():
    from gethsharding_tpu.rpc.client import RPCClient, RPCError

    reg_a, reg_b = _registry(), _registry()
    server_b = _frontend(reg_b)
    server_a = _frontend(
        reg_a, peers=[f"127.0.0.1:{server_b.address[1]}"])
    client = RPCClient("127.0.0.1", server_a.address[1], timeout=10.0)
    try:
        # local mutation on A pushes eagerly to B
        out = client.call("shard_addReplica", "ep:pushed")
        assert out["epoch"] == 1
        assert "ep:pushed" in server_b.membership.endpoints()
        assert server_b.membership.epoch == 1
        # typed wire errors for operator mistakes
        with pytest.raises(RPCError) as excinfo:
            client.call("shard_addReplica", "ep:pushed")
        assert excinfo.value.code == -32011
        assert "DuplicateReplicaError" in excinfo.value.message
        with pytest.raises(RPCError) as excinfo:
            client.call("shard_removeReplica", "ep:never")
        assert excinfo.value.code == -32011
        assert "UnknownReplicaError" in excinfo.value.message
        # B diverges ahead (epoch 2); A's pull adopts the newer epoch
        server_b.membership.add("ep:pulled")
        assert server_a.gossip_once() == 1
        assert server_a.membership.epoch == 2
        assert "ep:pulled" in server_a.membership.endpoints()
        # stale gossip is a no-op: re-offering A's own epoch changes
        # nothing (no ping-pong between converged peers)
        snap = server_a.membership.snapshot()
        out = client.call("shard_fleetReconfigure", snap["endpoints"],
                          snap["epoch"])
        assert out["adopted"] is False
        # the control plane shows through shard_health/shard_fleetStatus
        assert client.call("shard_health")["epoch"] == 2
        status = client.call("shard_fleetStatus")
        assert status["membership"]["epoch"] == 2
    finally:
        client.close()
        server_a.stop(grace_s=1.0, notice_s=0.0)
        server_b.stop(grace_s=1.0, notice_s=0.0)


def test_frontend_pool_fails_over_on_drain_notice():
    """A stopping frontend answers its drain-notice window with the
    typed refusal: the pool fails over to the peer without burning a
    retry on a connection reset, and stays on the survivor."""
    from gethsharding_tpu.rpc.client import FrontendPool

    reg_a, reg_b = _registry(), _registry()
    server_a = _frontend(reg_a)
    server_b = _frontend(reg_b)
    pool = FrontendPool([f"127.0.0.1:{server_a.address[1]}",
                         f"127.0.0.1:{server_b.address[1]}"],
                        timeout=10.0)
    (digest, sig, want), = _ecdsa_cases(1)
    stopped = threading.Event()

    def stop_a():
        server_a.stop(grace_s=2.0, notice_s=0.6)
        stopped.set()

    try:
        assert pool.ecrecover_addresses([digest], [sig]) == [want]
        assert pool.failovers == 0
        stopper = threading.Thread(target=stop_a)
        stopper.start()
        time.sleep(0.15)  # inside A's drain-notice window
        assert pool.ecrecover_addresses([digest], [sig]) == [want]
        assert pool.failovers >= 1  # typed refusal, not a reset
        assert pool.primary().endswith(str(server_b.address[1]))
        assert stopped.wait(10)
        stopper.join(timeout=5)
        # A is fully gone now; the pool is sticky on B
        assert pool.ecrecover_addresses([digest], [sig]) == [want]
    finally:
        pool.close()
        if not stopped.is_set():
            server_a.stop(grace_s=1.0, notice_s=0.0)
        server_b.stop(grace_s=1.0, notice_s=0.0)


# == the autoscaler control law =============================================


class FakeSpawner:
    def __init__(self):
        self.count = 0
        self.retired: list = []

    def spawn(self) -> str:
        endpoint = f"spawn:{self.count}"
        self.count += 1
        return endpoint

    def retire(self, endpoint: str) -> None:
        self.retired.append(endpoint)

    def close(self) -> None:
        pass


def _scaler(registry, signals, **cfg_kwargs):
    router, membership = _boot_fleet(registry, n=1)
    base = dict(min_replicas=1, max_replicas=3, sustain_s=3.0,
                cooldown_s=10.0)
    base.update(cfg_kwargs)
    cfg = AutoscaleConfig(**base)
    spawner = FakeSpawner()
    scaler = Autoscaler(membership, spawner, config=cfg,
                        registry=registry, signals=lambda: dict(signals))
    return router, membership, spawner, scaler, signals


CALM = {"burn_fast": 0.0, "burn_slow": 0.0, "depth": 0.0, "p99": 0.0}


def test_autoscaler_out_on_fast_burn_then_in_when_calm():
    registry = _registry()
    signals = {"burn_fast": 5.0, "burn_slow": 3.0, "depth": 10.0,
               "p99": 0.5}
    router, membership, spawner, scaler, signals = _scaler(
        registry, signals)
    try:
        decision = scaler.tick(now=0.0)
        assert decision["action"] == "out"
        assert membership.endpoints() == ["boot:0", "spawn:0"]
        # still burning one second later: held by the cooldown
        decision = scaler.tick(now=1.0)
        assert decision["action"] == "held"
        assert "cooling down" in decision["reason"]
        # calm arrives; the in-gate needs calm SUSTAINED
        signals.update(CALM)
        assert scaler.tick(now=11.0)["action"] == "none"
        decision = scaler.tick(now=14.5)
        assert decision["action"] == "in"
        assert decision["candidate"] == "spawn:0"
        assert membership.endpoints() == ["boot:0"]
        # the drained removal is reaped on the next tick
        scaler.tick(now=15.5)
        assert spawner.retired == ["spawn:0"]
        assert registry.counter("fleet/autoscale/out").value == 1
        assert registry.counter("fleet/autoscale/in").value == 1
        assert registry.counter("fleet/autoscale/held").value >= 1
        assert scaler.status()["spawned"] == []
    finally:
        router.close()


def test_autoscaler_out_on_sustained_depth_only():
    """Queue depth must HOLD for sustain_s — a momentary spike does not
    scale; and the boot replica is never a scale-in candidate."""
    registry = _registry()
    signals = {"burn_fast": 0.0, "burn_slow": 0.0, "depth": 100.0,
               "p99": 0.0}
    router, membership, spawner, scaler, signals = _scaler(
        registry, signals, out_depth=64.0)
    try:
        assert scaler.tick(now=0.0)["action"] == "none"  # band started
        signals["depth"] = 0.0  # spike over before sustain_s
        assert scaler.tick(now=1.0)["action"] == "none"
        signals["depth"] = 100.0
        assert scaler.tick(now=2.0)["action"] == "none"  # band restarts
        decision = scaler.tick(now=5.5)
        assert decision["action"] == "out"
        assert "queue depth" in decision["reason"]
        # calm sustained at the floor: nothing to scale in (only the
        # boot replica would remain after reaping the spawned one)
        signals.update(CALM)
        scaler.tick(now=16.0)
        decision = scaler.tick(now=19.5)
        assert decision["action"] == "in"
        scaler.tick(now=20.5)  # reap
        signals.update(CALM)
        scaler.tick(now=31.0)
        decision = scaler.tick(now=34.5)
        assert decision["action"] == "none"
        assert "at floor" in decision["reason"]
        assert membership.endpoints() == ["boot:0"]
    finally:
        router.close()


def test_autoscaler_held_at_max():
    registry = _registry()
    signals = {"burn_fast": 9.0, "burn_slow": 9.0, "depth": 500.0,
               "p99": 2.0}
    router, membership, spawner, scaler, signals = _scaler(
        registry, signals, max_replicas=2, cooldown_s=0.0)
    try:
        assert scaler.tick(now=0.0)["action"] == "out"
        decision = scaler.tick(now=1.0)
        assert decision["action"] == "held"
        assert "at max" in decision["reason"]
        assert len(membership.endpoints()) == 2
    finally:
        router.close()


# == budget-aware bulk hedging ==============================================


def test_bulk_hedge_gated_on_slo_budget(monkeypatch):
    """Keyed bulk_audit planes hedge only while the class's SLO budget
    says the duplicate is free; a starved budget holds the hedge (and
    counts the hold). Default (0) keeps bulk hedging off entirely."""
    registry = _registry()
    replica = Replica("r0", PythonSigBackend(), probe=None,
                      registry=registry)

    def build(min_budget):
        monkeypatch.setenv("GETHSHARDING_FLEET_HEDGE_BULK_MIN_BUDGET",
                           str(min_budget))
        return FleetRouter([replica], health_interval_s=0.0,
                           hedge_ms=5.0, registry=_registry())

    # a fresh tracker has its full budget (remaining 1.0): armed
    router = build(0.5)
    try:
        delay = router._hedge_delay_s(replica, CLASS_BULK_AUDIT,
                                      keyed=True)
        assert delay == pytest.approx(0.005)
        # unkeyed bulk work never hedges (no affinity, no second choice)
        assert router._hedge_delay_s(replica, CLASS_BULK_AUDIT,
                                     keyed=False) == 0.0
    finally:
        router.close()
    # an unattainable floor: the hedge is HELD and the hold is counted
    router = build(2.0)
    try:
        assert router._hedge_delay_s(replica, CLASS_BULK_AUDIT,
                                     keyed=True) == 0.0
        assert router.hedge_stats()["bulk_budget_held"] == 1
    finally:
        router.close()
    # default: bulk hedging stays off (pre-elastic behavior)
    monkeypatch.delenv("GETHSHARDING_FLEET_HEDGE_BULK_MIN_BUDGET")
    router = FleetRouter([replica], health_interval_s=0.0, hedge_ms=5.0,
                         registry=_registry())
    try:
        assert router._hedge_delay_s(replica, CLASS_BULK_AUDIT,
                                     keyed=True) == 0.0
        assert router.hedge_stats()["bulk_budget_held"] == 0
    finally:
        router.close()
