"""Fleet frontend hardening: full wire planes, hedged dispatch, the
standalone frontend process, and the hard interleavings
(ISSUE 15 / gethsharding_tpu/fleet/frontend.py + router hedging).

Contracts:

- WIRE PLANES: `RpcReplicaBackend` serves the FULL SigBackend surface
  over JSON-RPC — the committee plane (`shard_verifyCommittees`) and
  the DAS sample plane (`shard_dasVerify`) return verdicts
  bit-identical to the scalar reference, hostile rows included, and
  the plane codecs roundtrip.
- TRANSPORT CHAOS: seeded ``fleet.transport`` delay/partition modes
  stall or cut a replica's wire deterministically; invalid mode/seam
  combinations fail fast.
- HEDGING: an interactive call outliving its hedge delay is re-issued
  to the next affinity replica, first verdict wins, losers are
  discarded with accounting; bulk traffic never hedges; hedges ride
  untenanted (quota idempotence); a hedged pair detecting the same
  corruption charges the audit-fault path ONCE; a replica draining
  while its hedge is in flight finishes cleanly; a sustained wasted-
  rate storm latches and lands in the flight recorder.
- FRONTEND: the standalone server routes every plane, orchestrates
  drains, refuses typed while draining, and an actor dialing it
  RECOVERS through its retry policy after a frontend restart
  mid-request (typed error in between, redial after).
- WFQ: inside one admission class, a heavy tenant cannot starve a
  light one (deficit round-robin; see also test_fleet.py's queue
  suite).
"""

import threading
import time

import pytest

from gethsharding_tpu import metrics
from gethsharding_tpu.crypto import bn256 as bls
from gethsharding_tpu.crypto import secp256k1 as ecdsa
from gethsharding_tpu.crypto.keccak import keccak256
from gethsharding_tpu.fleet import (
    FleetRouter,
    FrontendServer,
    Replica,
    RouterSigBackend,
    build_frontend,
)
from gethsharding_tpu.fleet.router import RpcReplicaBackend
from gethsharding_tpu.resilience.chaos import (
    ChaosSchedule,
    ChaosSigBackend,
    InjectedFault,
    TransportChaos,
    parse_spec,
    transport_disturb,
)
from gethsharding_tpu.resilience.errors import SoundnessViolation
from gethsharding_tpu.resilience.soundness import SpotCheckSigBackend
from gethsharding_tpu.rpc import codec
from gethsharding_tpu.rpc.client import RPCClient, RPCError
from gethsharding_tpu.rpc.server import RPCServer
from gethsharding_tpu.serving import (
    AdmissionQueue,
    Request,
    ServingConfig,
    ServingSigBackend,
)
from gethsharding_tpu.serving.classes import CLASS_BULK_AUDIT
from gethsharding_tpu.sigbackend import PythonSigBackend
from gethsharding_tpu.smc.chain import SimulatedMainchain


def _registry() -> metrics.Registry:
    return metrics.Registry()


def _ecdsa_cases(n: int, tag: bytes = b"ff"):
    cases = []
    for i in range(n):
        priv = int.from_bytes(keccak256(tag + b"-%d" % i), "big") % ecdsa.N
        digest = keccak256(tag + b"-msg-%d" % i)
        cases.append((digest, ecdsa.sign(digest, priv).to_bytes65(),
                      ecdsa.priv_to_address(priv)))
    return cases


def _committee_rows(n: int = 3, tamper: int = 1):
    msgs, sig_rows, pk_rows, keys = [], [], [], []
    for i in range(n):
        tag = b"ffc-%d" % i
        ks = [bls.bls_keygen(tag + bytes([j])) for j in range(2)]
        sigs = [bls.bls_sign(tag, sk) for sk, _ in ks]
        if i == tamper:
            sigs[0] = bls.bls_sign(b"tampered", ks[0][0])
        msgs.append(tag)
        sig_rows.append(sigs)
        pk_rows.append([pk for _, pk in ks])
        keys.append((i, i * 7))
    return msgs, sig_rows, pk_rows, keys


def _das_rows():
    from gethsharding_tpu.das.erasure import extend_body
    from gethsharding_tpu.das.proofs import (chunk_leaf, merkle_levels,
                                             merkle_proof)

    xb = extend_body(b"\x07" * 9000, parity_ratio=0.5)
    levels = merkle_levels([chunk_leaf(c) for c in xb.chunks])
    root = levels[-1][0]
    good0, good1 = merkle_proof(levels, 0), merkle_proof(levels, 1)
    # valid, valid, withheld, truncated proof, wrong root
    chunks = [xb.chunks[0], xb.chunks[1], b"", xb.chunks[1],
              xb.chunks[0]]
    indices = [0, 1, 1, 1, 0]
    proofs = [good0, good1, (), good1[:-1], good0]
    roots = [root, root, root, root, b"\x02" * 32]
    return chunks, indices, proofs, roots


@pytest.fixture
def rpc_replica():
    """One chain_server-shaped RPC replica + its dialed backend."""
    serving = ServingSigBackend(PythonSigBackend(),
                                ServingConfig(flush_us=200),
                                registry=_registry())
    server = RPCServer(SimulatedMainchain(), sig_backend=serving)
    server.start()
    backend = RpcReplicaBackend.dial(*server.address)
    yield backend
    backend.close()
    server.stop()
    serving.close()


# == the wire planes ========================================================


def test_committee_plane_over_the_wire_bit_identical(rpc_replica):
    """`shard_verifyCommittees` through a real RPC replica returns the
    scalar reference's verdicts bit-for-bit — tampered and empty rows
    included — and the async face keeps the VerdictFuture contract."""
    msgs, sig_rows, pk_rows, keys = _committee_rows()
    want = PythonSigBackend().bls_verify_committees(msgs, sig_rows,
                                                    pk_rows)
    assert want == [True, False, True]
    got = rpc_replica.bls_verify_committees(msgs, sig_rows, pk_rows,
                                            pk_row_keys=keys)
    assert got == want
    # keyless + keyed agree; an empty committee row is a rejection
    assert rpc_replica.bls_verify_committees(msgs, sig_rows,
                                             pk_rows) == want
    assert rpc_replica.bls_verify_committees([b"m"], [[]], [[]]) == [False]
    future = rpc_replica.bls_verify_committees_async(
        msgs, sig_rows, pk_rows, pk_row_keys=keys)
    assert future.done() and future.result() == want


def test_das_plane_over_the_wire_bit_identical(rpc_replica):
    """`shard_dasVerify` verdicts equal the scalar reference — hostile
    rows (withheld chunk, truncated proof, wrong root) cost a False,
    never an error, exactly as in-process."""
    chunks, indices, proofs, roots = _das_rows()
    want = PythonSigBackend().das_verify_samples(chunks, indices,
                                                 proofs, roots)
    assert want == [True, True, False, False, False]
    got = rpc_replica.das_verify_samples(chunks, indices, proofs, roots)
    assert got == want
    assert rpc_replica.das_verify_samples([], [], [], []) == []


def test_plane_codecs_roundtrip():
    msgs, sig_rows, pk_rows, keys = _committee_rows()
    assert codec.dec_g1_rows(codec.enc_g1_rows(sig_rows)) == sig_rows
    assert codec.dec_g2_rows(codec.enc_g2_rows(pk_rows)) == pk_rows
    # pk-row keys ship as repr strings: injective for the int-tuple
    # keys the notary uses, None preserved, stable across processes
    wire = codec.enc_pk_row_keys([None, (1, 2), ("a", 3)])
    assert wire[0] is None and wire[1] != wire[2]
    assert codec.enc_pk_row_keys(None) is None
    chunks, indices, proofs, roots = _das_rows()
    enc = codec.enc_das_call(chunks, indices, proofs, roots)
    dec = codec.dec_das_call(*enc)
    assert dec == (list(chunks), list(indices),
                   [list(p) for p in proofs], list(roots))


def test_rpc_replica_maps_connection_loss_to_typed_transport_error():
    """A replica killed under a dialed backend surfaces
    `ConnectionError` (the router's retryable/trip class), and the
    backend REDIALS once the endpoint is back."""
    serving = ServingSigBackend(PythonSigBackend(),
                                ServingConfig(flush_us=200),
                                registry=_registry())
    server = RPCServer(SimulatedMainchain(), sig_backend=serving)
    server.start()
    host, port = server.address
    backend = RpcReplicaBackend.dial(host, port)
    (digest, sig, want), = _ecdsa_cases(1)
    assert backend.ecrecover_addresses([digest], [sig]) == [want]
    server.stop()
    serving.close()
    with pytest.raises(ConnectionError):
        backend.ecrecover_addresses([digest], [sig])
    # restart on the SAME endpoint: the next call redials and succeeds
    serving2 = ServingSigBackend(PythonSigBackend(),
                                 ServingConfig(flush_us=200),
                                 registry=_registry())
    server2 = RPCServer(SimulatedMainchain(), host=host, port=port,
                        sig_backend=serving2)
    server2.start()
    try:
        deadline = time.monotonic() + 5
        while True:
            try:
                assert backend.ecrecover_addresses([digest],
                                                   [sig]) == [want]
                break
            except ConnectionError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.05)
    finally:
        backend.close()
        server2.stop()
        serving2.close()


# == transport chaos ========================================================


def test_transport_chaos_delay_and_partition_modes():
    delayed = ChaosSchedule(seed=3, rules={"fleet.transport": 1},
                            modes={"fleet.transport": "delay"},
                            delay_s=0.15)
    front = TransportChaos(PythonSigBackend(), delayed)
    (digest, sig, want), = _ecdsa_cases(1)
    t0 = time.monotonic()
    assert front.ecrecover_addresses([digest], [sig]) == [want]
    assert time.monotonic() - t0 >= 0.15  # first call stalled
    t0 = time.monotonic()
    assert front.ecrecover_addresses([digest], [sig]) == [want]
    assert time.monotonic() - t0 < 0.1  # rule healed (first-n)

    cut = ChaosSchedule(seed=3, rules={"fleet.transport": 1},
                        modes={"fleet.transport": "partition"})
    front = TransportChaos(PythonSigBackend(), cut)
    with pytest.raises(InjectedFault):
        front.ecrecover_addresses([digest], [sig])
    assert isinstance(InjectedFault("x"), ConnectionError)  # trip class
    assert front.ecrecover_addresses([digest], [sig]) == [want]
    # transport_disturb with no schedule / no rule is a no-op
    transport_disturb(None)
    transport_disturb(ChaosSchedule(seed=1))


def test_transport_mode_validation_fails_fast():
    with pytest.raises(ValueError, match="fleet.transport"):
        ChaosSchedule(modes={"backend.ecrecover_addresses": "delay"})
    with pytest.raises(ValueError, match="fleet.transport"):
        parse_spec("dispatch.ecrecover_addresses:mode=partition")
    schedule = parse_spec(
        "seed=5,fleet.transport=0.5,fleet.transport:mode=delay,"
        "delay_s=0.02")
    assert schedule.delay_s == 0.02
    assert schedule.mode_for("fleet.transport") == "delay"


# == hedged dispatch ========================================================


def _slow_fast_fleet(registry, delay_s=0.4, hedge_ms=30.0,
                     slow_backend=None, fast_backend=None):
    slow_sched = ChaosSchedule(seed=1, rules={"fleet.transport": True},
                               modes={"fleet.transport": "delay"},
                               delay_s=delay_s)
    r0 = Replica("r0", TransportChaos(slow_backend or PythonSigBackend(),
                                      slow_sched),
                 probe=None, registry=registry)
    r1 = Replica("r1", fast_backend or PythonSigBackend(), probe=None,
                 registry=registry)
    router = FleetRouter([r0, r1], health_interval_s=0.0,
                         hedge_ms=hedge_ms, registry=registry)
    return router, r0, r1


def _r0_key(router) -> str:
    return next(k for k in (f"shard-{i}" for i in range(64))
                if router.route(k)[0].name == "r0")


def test_hedge_first_verdict_wins_and_losses_are_accounted():
    """A slow primary's interactive call is answered by the hedge
    after the floor delay; the loser's verdict is discarded with
    accounting, and bulk traffic never hedges."""
    registry = _registry()
    router, r0, r1 = _slow_fast_fleet(registry)
    (digest, sig, want), = _ecdsa_cases(1)
    key = _r0_key(router)
    try:
        t0 = time.monotonic()
        got = router.call("ecrecover_addresses", [digest], [sig],
                          affinity=key)
        took = time.monotonic() - t0
        assert got == [want]
        assert took < 0.3, f"sat out the slow replica: {took:.3f}s"
        deadline = time.monotonic() + 3
        while router.hedge_stats()["wasted"] < 1 \
                and time.monotonic() < deadline:
            time.sleep(0.02)  # the loser finishes in the pool
        stats = router.hedge_stats()
        assert stats["issued"] == 1 and stats["won"] == 1
        assert stats["wasted"] == 1
        # bulk never hedges: the same slow-affinity call under
        # bulk_audit waits the primary out
        t0 = time.monotonic()
        got = router.call("ecrecover_addresses", [digest], [sig],
                          affinity=key, klass=CLASS_BULK_AUDIT)
        assert got == [want]
        assert time.monotonic() - t0 >= 0.35
        assert router.hedge_stats()["issued"] == 1
    finally:
        router.close()


def test_hedge_rides_untenanted_for_quota_idempotence():
    """The hedged duplicate must NOT charge the caller's tenant quota:
    a serving replica with a 1-row tenant quota still answers a hedged
    call whose primary is stalled ON that tenant's only quota slot."""
    registry = _registry()
    # r1 (the hedge target) enforces a 1-row quota for every tenant;
    # the hedge rides untenanted so it is admitted regardless
    serving1 = ServingSigBackend(
        PythonSigBackend(),
        ServingConfig(flush_us=200, tenant_quota_rows=1),
        registry=_registry())
    router, r0, r1 = _slow_fast_fleet(registry, fast_backend=serving1)
    (digest, sig, want), = _ecdsa_cases(1)
    key = _r0_key(router)
    try:
        got = router.call("ecrecover_addresses", [digest], [sig],
                          affinity=key, tenant="t-hedge")
        assert got == [want]
        assert router.hedge_stats()["won"] == 1
        # the quota bucket saw no queued rows from the hedge once the
        # dispatch drained — and crucially no TenantQuotaExceeded
        queue = serving1.batcher._queues["ecrecover_addresses"]
        assert queue.quota_rejections == 0
    finally:
        router.close()
        serving1.close()


def test_hedge_duplicate_suppression_fires_audit_once():
    """Both sides of a hedged pair detect the SAME silent corruption
    (soundness spot-check on two corrupt replicas): the audit-fault
    accounting charges ONCE per logical request, the ladder still
    recovers from the clean third replica."""
    registry = _registry()

    def corrupt_backend():
        schedule = ChaosSchedule(
            seed=9, rules={"backend.ecrecover_addresses": True},
            modes={"backend.ecrecover_addresses": "corrupt"})
        return SpotCheckSigBackend(
            ChaosSigBackend(PythonSigBackend(), schedule), rate=1.0)

    slow_sched = ChaosSchedule(seed=1, rules={"fleet.transport": True},
                               modes={"fleet.transport": "delay"},
                               delay_s=0.25)
    r0 = Replica("r0", TransportChaos(corrupt_backend(), slow_sched),
                 probe=None, registry=registry)
    r1 = Replica("r1", corrupt_backend(), probe=None, registry=registry)
    r2 = Replica("r2", PythonSigBackend(), probe=None, registry=registry)
    router = FleetRouter([r0, r1, r2], health_interval_s=0.0,
                         hedge_ms=30, registry=registry)
    cases = _ecdsa_cases(4, tag=b"aud")
    # an affinity whose preference order is exactly r0, r1, r2: the
    # hedged pair is corrupt+corrupt and the ladder lands on clean r2
    key = next(k for k in (f"shard-{i}" for i in range(256))
               if [r.name for r in router.route(k)] == ["r0", "r1", "r2"])
    mismatches = metrics.DEFAULT_REGISTRY.counter(
        "resilience/soundness/ecrecover_addresses/mismatches")
    mark = mismatches.value
    try:
        got = router.call("ecrecover_addresses",
                          [c[0] for c in cases], [c[1] for c in cases],
                          affinity=key)
        assert got == [c[2] for c in cases]  # the clean replica answered
        stats = router.hedge_stats()
        assert stats["issued"] == 1
        # BOTH duplicates raised SoundnessViolation; the audit-fault
        # path was charged exactly once for the logical request. A
        # both-failed pair discards no verdict: nothing is counted
        # wasted — the pair's failure drove the retry ladder instead
        assert stats["audit_faults"] == 1, stats
        assert stats["wasted"] == 0 and stats["loser_failures"] == 0, stats
        # each replica's audit really did fire (the spot-checker's
        # counters live in the default registry)
        assert mismatches.value - mark >= 2
    finally:
        router.close()


def test_hedge_loser_failing_before_verdict_is_counted_wasted():
    """A hedge duplicate that fails FAST (partitioned hedge target)
    while the slow primary eventually answers is still a wasted
    dispatch — it must feed the storm watch's wasted rate, not vanish
    into the race bookkeeping."""
    registry = _registry()
    slow_sched = ChaosSchedule(seed=4, rules={"fleet.transport": True},
                               modes={"fleet.transport": "delay"},
                               delay_s=0.3)
    cut_sched = ChaosSchedule(seed=4, rules={"fleet.transport": True},
                              modes={"fleet.transport": "partition"})
    r0 = Replica("r0", TransportChaos(PythonSigBackend(), slow_sched),
                 probe=None, registry=registry)
    r1 = Replica("r1", TransportChaos(PythonSigBackend(), cut_sched),
                 probe=None, registry=registry)
    router = FleetRouter([r0, r1], health_interval_s=0.0, hedge_ms=30,
                         registry=registry)
    (digest, sig, want), = _ecdsa_cases(1, tag=b"lf")
    key = _r0_key(router)
    try:
        got = router.call("ecrecover_addresses", [digest], [sig],
                          affinity=key)
        assert got == [want]  # the slow primary's verdict, waited out
        stats = router.hedge_stats()
        assert stats["issued"] == 1 and stats["won"] == 0
        assert stats["wasted"] == 1, stats   # the dead duplicate
        assert stats["loser_failures"] == 1, stats
    finally:
        router.close()


def test_hedge_vs_drain_interleaving():
    """The primary's replica is DRAINED while its hedge duplicate is
    still in flight: the caller's verdict is unaffected, the stale
    dispatch finishes inside the drain (flight accounting), and the
    replica reaches drained-empty state."""
    registry = _registry()
    router, r0, r1 = _slow_fast_fleet(registry, delay_s=0.4)
    (digest, sig, want), = _ecdsa_cases(1)
    key = _r0_key(router)
    try:
        got = router.call("ecrecover_addresses", [digest], [sig],
                          affinity=key)
        assert got == [want]  # hedge answered; r0's dispatch still live
        assert r0.in_flight == 1
        router.drain("r0")
        assert r0.state == "draining"
        assert not r0.drained  # the hedged loser is still in flight
        deadline = time.monotonic() + 3
        while not r0.drained and time.monotonic() < deadline:
            time.sleep(0.02)
        assert r0.drained  # in-flight loser finished inside the drain
        assert router.hedge_stats()["wasted"] == 1
        # traffic keeps flowing on the survivor
        assert router.call("ecrecover_addresses", [digest], [sig],
                           affinity=key) == [want]
    finally:
        router.close()


def test_hedge_storm_latches_and_lands_in_the_flight_recorder():
    """A sustained wasted-duplicate rate over the threshold is a
    fleet-health event: the storm latch sets (gauge + hedge_stats),
    and the flight recorder captures a hedge_storm event like a
    breaker trip."""
    from gethsharding_tpu.perfwatch import RECORDER

    registry = _registry()
    # every call hedges (sub-ms fuse against ~ms scalar calls) and the
    # primary usually wins -> near-100% wasted rate
    router, r0, r1 = _slow_fast_fleet(registry, delay_s=0.0,
                                      hedge_ms=0.01)
    cases = _ecdsa_cases(4, tag=b"storm")
    key = _r0_key(router)
    try:
        for i in range(24):
            digest, sig, want = cases[i % len(cases)]
            assert router.call("ecrecover_addresses", [digest], [sig],
                               affinity=key) == [want]
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            router.refresh(force=True)  # the sweep runs the storm watch
            if router.hedge_stats()["storm"]:
                break
            time.sleep(0.05)
        stats = router.hedge_stats()
        assert stats["storm"] == 1, stats
        assert registry.gauge("fleet/hedge/storm").value == 1
        assert any(e["kind"] == "hedge_storm"
                   for e in RECORDER.events()), "no recorder event"
    finally:
        router.close()


def test_hedged_spans_link_one_trace_and_attribute_wasted_work(monkeypatch):
    """Winner and loser of a hedged pair are linked on ONE logical
    trace: both fleet/attempt spans parent under the route span, the
    loser's discard records a fleet/hedge_wasted span (replica +
    winner + wasted tags) with the SAME trace id, the hedge flags that
    trace for the fleet collector's tail retention, and the critical-
    path analyzer reports the duplicate as the hedge_wasted segment
    OUTSIDE the wall-time identity."""
    from gethsharding_tpu import fleettrace, tracing
    from gethsharding_tpu.fleettrace.critical_path import attribute

    registry = _registry()
    tracing.enable(ring_spans=16384)
    tracing.TRACER.clear()
    collector = fleettrace.TraceCollector(registry, sample=0.0)
    monkeypatch.setattr(fleettrace, "COLLECTOR", collector)
    router, r0, r1 = _slow_fast_fleet(registry)
    (digest, sig, want), = _ecdsa_cases(1, tag=b"link")
    key = _r0_key(router)
    try:
        assert router.call("ecrecover_addresses", [digest], [sig],
                           affinity=key) == [want]
        deadline = time.monotonic() + 3
        while router.hedge_stats()["wasted"] < 1 \
                and time.monotonic() < deadline:
            time.sleep(0.02)  # the loser's discard records the span
        assert router.hedge_stats()["wasted"] == 1
        spans = tracing.TRACER.recent_spans()
        route = next(s for s in spans if s["name"] == "fleet/route")
        trace = [s for s in spans if s["trace"] == route["trace"]]
        attempts = [s for s in trace if s["name"] == "fleet/attempt"]
        # primary + hedge, both under the route span, one trace id
        assert len(attempts) == 2, [s["name"] for s in trace]
        assert {a["tags"]["replica"] for a in attempts} == {"r0", "r1"}
        assert {a["tags"]["hedged"] for a in attempts} == {False, True}
        assert all(a["parent"] == route["span"] for a in attempts)
        wasted = next(s for s in trace
                      if s["name"] == "fleet/hedge_wasted")
        assert wasted["parent"] == route["span"]
        assert wasted["tags"]["replica"] == "r0"
        assert wasted["tags"]["winner"] == "r1"
        assert wasted["tags"]["wasted"] is True
        # winner linkage is tagged on the logical request's span
        assert route["tags"]["hedge_winner"] == "r1"
        # ... and the hedge flagged the trace for tail retention (the
        # spans have not reached this collector, so the mark is staged)
        assert collector._marks.get(route["trace"]) == "hedged"
        # attribution: the duplicate is its own segment, outside the
        # telescoping identity (it ran CONCURRENTLY, it is not wall
        # time), and the tree walk reaches every span
        attr = attribute(trace)
        assert attr["root"] == "fleet/route"
        assert attr["orphan_spans"] == 0
        assert "hedge_wasted" not in attr["segments"]
        # the loser sat out the ~0.4 s transport delay after the ~30 ms
        # hedge verdict: its discarded interval dwarfs the route span
        assert attr["hedge_wasted_s"] > attr["total_s"]
    finally:
        router.close()
        tracing.TRACER.clear()
        tracing.disable()


# == WFQ: tenant fairness inside a class ====================================


def _req(rows: int, tenant: str) -> Request:
    digests = tuple(keccak256(b"w-%d" % i) for i in range(rows))
    sigs = tuple(b"\x00" * 65 for _ in range(rows))
    return Request("ecrecover_addresses", (digests, sigs), rows,
                   klass=CLASS_BULK_AUDIT, tenant=tenant)


def test_wfq_heavy_tenant_cannot_starve_light_tenant():
    """The starvation bound: with a heavy tenant's 100-request backlog
    queued FIRST, a light tenant's 4 requests still ride the very next
    batch (deficit round-robin share), and over the whole drain the
    light tenant's wait is bounded by its share, not the heavy
    backlog."""
    queue = AdmissionQueue(cap_rows=4096, max_batch=16, flush_us=0)
    for _ in range(100):
        queue.put(_req(1, "heavy"))
    for _ in range(4):
        queue.put(_req(1, "light"))
    batch, reason = queue.take_batch()
    assert reason == "full"
    counts: dict = {}
    for request in batch:
        counts[request.tenant] = counts.get(request.tenant, 0) + 1
    assert counts.get("light", 0) == 4, counts  # full share, batch ONE
    assert counts["heavy"] == len(batch) - 4


def test_wfq_big_requests_clear_via_carried_deficit():
    """A tenant whose requests are larger than one quantum is not
    starved by size: its deficit carries across batches until the big
    request clears."""
    queue = AdmissionQueue(cap_rows=4096, max_batch=8, flush_us=0)
    for _ in range(40):
        queue.put(_req(1, "small"))
    queue.put(_req(6, "big"))
    for i in range(4):
        batch, _ = queue.take_batch()
        if any(r.tenant == "big" for r in batch):
            break
    else:
        pytest.fail("the 6-row request never cleared in 4 batches")
    assert i <= 2, f"big request starved for {i} batches"


def test_wfq_single_tenant_drains_fifo():
    """Untenanted (or single-tenant) backlogs keep the exact pre-WFQ
    FIFO drain order."""
    queue = AdmissionQueue(cap_rows=4096, max_batch=8, flush_us=0)
    marks = []
    for i in range(12):
        request = _req(1, "")
        marks.append(request)
        queue.put(request)
    batch, _ = queue.take_batch()
    assert batch == marks[:8]


# == the standalone frontend ================================================


def _frontend_fixture(registry, n=2):
    servings, replicas = [], []
    for i in range(n):
        serving = ServingSigBackend(PythonSigBackend(),
                                    ServingConfig(flush_us=200),
                                    registry=_registry())
        servings.append(serving)
        replicas.append(Replica(f"r{i}", serving, probe=None,
                                registry=registry))
    router = FleetRouter(replicas, health_interval_s=0.05,
                         registry=registry)
    frontend = FrontendServer(router)
    frontend.start()
    return frontend, servings


def test_frontend_serves_all_planes_and_orchestrates_drains():
    registry = _registry()
    frontend, servings = _frontend_fixture(registry)
    client = RPCClient(*frontend.address)
    try:
        (digest, sig, want), = _ecdsa_cases(1)
        out = client.call("shard_ecrecover", [codec.enc_bytes(digest)],
                          [codec.enc_bytes(sig)])
        assert out == [codec.enc_bytes(want)]
        msgs, sig_rows, pk_rows, keys = _committee_rows()
        got = client.call("shard_verifyCommittees",
                          [codec.enc_bytes(m) for m in msgs],
                          codec.enc_g1_rows(sig_rows),
                          codec.enc_g2_rows(pk_rows),
                          codec.enc_pk_row_keys(keys))
        assert got == [True, False, True]
        chunks, indices, proofs, roots = _das_rows()
        got = client.call("shard_dasVerify",
                          *codec.enc_das_call(chunks, indices, proofs,
                                              roots))
        assert got == [True, True, False, False, False]
        # control plane: health, status, per-replica drain/undrain
        health = client.call("shard_health")
        assert health["draining"] is False
        assert health["accepting_replicas"] == 2
        client.call("shard_drainReplica", "r0")
        status = client.call("shard_fleetStatus")
        assert status["replicas"]["r0"]["state"] == "draining"
        out = client.call("shard_ecrecover", [codec.enc_bytes(digest)],
                          [codec.enc_bytes(sig)])
        assert out == [codec.enc_bytes(want)]  # survivor answers
        client.call("shard_undrainReplica", "r0")
        assert client.call(
            "shard_fleetStatus")["replicas"]["r0"]["state"] == "healthy"
        # frontend-level drain: typed refusal with the routing phrase
        client.call("shard_drain")
        with pytest.raises(RPCError, match="replica draining"):
            client.call("shard_ecrecover", [codec.enc_bytes(digest)],
                        [codec.enc_bytes(sig)])
        assert client.call("shard_health")["draining"] is True
    finally:
        client.close()
        frontend.stop()
        for serving in servings:
            serving.close()


def test_frontend_restart_with_actor_mid_request_recovers():
    """An actor (an `RpcReplicaBackend` dialing the FRONTEND) whose
    in-flight request dies with the frontend gets a TYPED transport
    error, and its retry policy recovers once the frontend restarts on
    the same endpoint — no actor rebuild, no stranded future."""
    registry = _registry()
    # a slow replica keeps the actor's request in flight across the
    # frontend's shutdown window
    slow_sched = ChaosSchedule(seed=2, rules={"fleet.transport": 2},
                               modes={"fleet.transport": "delay"},
                               delay_s=0.6)
    replica_backend = TransportChaos(PythonSigBackend(), slow_sched)
    router = FleetRouter(
        [Replica("r0", replica_backend, probe=None, registry=registry)],
        health_interval_s=0.0, registry=registry)
    frontend = FrontendServer(router)
    frontend.start()
    host, port = frontend.address
    actor = RpcReplicaBackend.dial(host, port)
    (digest, sig, want), = _ecdsa_cases(1)
    outcome: dict = {}

    def mid_request() -> None:
        try:
            outcome["result"] = actor.ecrecover_addresses([digest], [sig])
        except ConnectionError as exc:
            outcome["typed"] = exc
        except Exception as exc:  # noqa: BLE001 - the assertion target
            outcome["untyped"] = exc

    thread = threading.Thread(target=mid_request)
    thread.start()
    time.sleep(0.15)  # the request is inside the 0.6 s replica stall
    frontend.stop(grace_s=0.1)
    thread.join(timeout=10)
    assert not thread.is_alive()
    assert "typed" in outcome, outcome  # ConnectionError, nothing else
    # restart on the SAME endpoint (fresh router over the same replica)
    router2 = FleetRouter(
        [Replica("r0", replica_backend, probe=None, registry=registry)],
        health_interval_s=0.0, registry=registry)
    frontend2 = FrontendServer(router2, host=host, port=port)
    frontend2.start()
    try:
        # the actor's ordinary retry shape: redial-and-retry on the
        # typed transport error recovers without rebuilding the actor
        from gethsharding_tpu.resilience.policy import (RetryExecutor,
                                                        RetryPolicy)

        executor = RetryExecutor(
            "test.frontend_recover",
            RetryPolicy(attempts=30, base_s=0.05, jitter=0.0,
                        retryable=(ConnectionError,)),
            registry=registry)
        got = executor.call(
            lambda: actor.ecrecover_addresses([digest], [sig]))
        assert got == [want]
    finally:
        actor.close()
        frontend2.stop()


def test_build_frontend_dials_real_replicas_end_to_end():
    """`build_frontend` (the CLI's constructor): two RPC replica
    processes-worth of servers, one frontend, verdicts bit-identical
    through the whole chain — and the frontend's shard_metrics carries
    the fleet/hedge counters for federation."""
    servers = []
    endpoints = []
    for _ in range(2):
        serving = ServingSigBackend(PythonSigBackend(),
                                    ServingConfig(flush_us=200),
                                    registry=_registry())
        server = RPCServer(SimulatedMainchain(), sig_backend=serving)
        server.start()
        servers.append((server, serving))
        endpoints.append("%s:%d" % server.address)
    frontend = build_frontend(endpoints, hedge_ms=0,
                              health_interval_s=0.05,
                              registry=metrics.DEFAULT_REGISTRY)
    frontend.start()
    client = RPCClient(*frontend.address)
    try:
        cases = _ecdsa_cases(4, tag=b"bf")
        for digest, sig, want in cases:
            out = client.call("shard_ecrecover",
                              [codec.enc_bytes(digest)],
                              [codec.enc_bytes(sig)])
            assert out == [codec.enc_bytes(want)]
        snapshot = client.call("shard_metrics")
        assert "fleet/hedge/issued" in snapshot
        assert "fleet/router/calls" in snapshot
    finally:
        client.close()
        frontend.stop()
        for server, serving in servers:
            server.stop()
            serving.close()
