"""Chain rollback + reorg tests (core/blockchain.go SetHead / reorg
parity, scoped to the dev chain): state restores to the rolled-back
head, competing branches win only by length, and the state mirror
follows a reorg instead of treating it as a stale read."""

import pytest

from gethsharding_tpu.crypto.keccak import keccak256
from gethsharding_tpu.mainchain.accounts import AccountManager
from gethsharding_tpu.params import Config, ETHER
from gethsharding_tpu.smc.chain import Block, SimulatedMainchain
from gethsharding_tpu.utils.hexbytes import Hash32


def _chain(**kw):
    return SimulatedMainchain(config=Config(shard_count=4, **kw))


def _accounts(n):
    manager = AccountManager()
    return manager, [manager.new_account(seed=b"reorg-%d" % i)
                     for i in range(n)]


def test_set_head_rolls_back_state_and_notifies():
    chain = _chain()
    manager, (a, b) = _accounts(2)
    chain.fund(a.address, 2000 * ETHER)
    chain.fund(b.address, 2000 * ETHER)

    chain.register_notary(a.address)
    for _ in range(4):
        chain.commit()
    mark = chain.block_number  # a registered, b not yet
    balance_mark = chain.balance_of(b.address)
    chain.register_notary(b.address)
    for _ in range(4):
        chain.commit()
    assert chain.notary_registry(b.address) is not None

    heads = []
    chain.subscribe_new_head(lambda blk: heads.append(blk.number))
    head = chain.set_head(mark)
    assert head.number == mark == chain.block_number
    assert heads == [mark]  # subscribers saw the rollback head
    # state restored: b's registration (and its deposit debit) undone
    assert chain.notary_registry(a.address) is not None
    assert chain.notary_registry(b.address) is None
    assert chain.balance_of(b.address) == balance_mark
    assert chain.current_period() == mark // chain.config.period_length
    assert chain.reorg_generation == 1
    # the chain keeps working after a rollback
    chain.register_notary(b.address)
    chain.commit()
    assert chain.notary_registry(b.address) is not None


def test_set_head_bounds_and_pruning():
    chain = _chain()
    with pytest.raises(ValueError, match="head is"):
        chain.set_head(5)
    chain.SNAPSHOT_HORIZON = 4
    for _ in range(8):
        chain.commit()
    with pytest.raises(ValueError, match="pruned"):
        chain.set_head(1)  # beyond the snapshot horizon
    chain.set_head(chain.block_number - 2)  # inside: fine


def _fork(chain, attach: int, length: int):
    """A foreign branch of empty blocks linked at `attach`. A distinct
    `extra` keeps the branch's hashes different from the incumbent's
    (the fake engine hashes extra when present), so reorg assertions
    prove the FOREIGN blocks were adopted — while still carrying valid
    seals for InsertChain's engine verification."""
    parent = chain.block_by_number(attach)
    out = []
    for i in range(length):
        extra = b"fork-%d-%d" % (attach, i)
        block_hash = chain.engine.hash_header(parent.number + 1,
                                              parent.hash, extra)
        block = Block(number=parent.number + 1, hash=block_hash,
                      parent_hash=parent.hash, extra=extra)
        out.append(block)
        parent = block
    return out


def test_import_chain_reorg_longest_wins():
    chain = _chain()
    manager, (a,) = _accounts(1)
    chain.fund(a.address, 2000 * ETHER)
    for _ in range(3):
        chain.commit()
    chain.register_notary(a.address)  # executes in pending block 4
    for _ in range(3):
        chain.commit()
    assert chain.block_number == 6

    # an equal-length branch from block 3 loses (incumbent stays)
    assert chain.import_chain(_fork(chain, 3, 3)) == 0
    assert chain.notary_registry(a.address) is not None

    # a LONGER branch from block 3 reorgs: the registration (sealed in
    # block 4 of the old branch) is rolled away
    branch = _fork(chain, 3, 5)
    assert chain.import_chain(branch) == 5
    assert chain.block_number == 8
    assert bytes(chain.block_by_number(8).hash) == bytes(branch[-1].hash)
    assert chain.notary_registry(a.address) is None
    assert chain.reorg_generation >= 1

    # rejected branches: unknown attach point, broken linkage
    orphan = _fork(chain, 2, 2)
    orphan[0] = Block(number=3, hash=orphan[0].hash,
                      parent_hash=Hash32(b"\xee" * 32))
    with pytest.raises(ValueError, match="link"):
        chain.import_chain(orphan)
    broken = _fork(chain, 2, 3)
    broken[2] = Block(number=9, hash=broken[2].hash,
                      parent_hash=broken[1].hash)
    with pytest.raises(ValueError, match="linkage"):
        chain.import_chain(broken)


def test_mirror_follows_reorg():
    """The state mirror's never-regress guard must accept a LOWER head
    from a later reorg generation (a rollback is new truth, not a stale
    racing refresh)."""
    from gethsharding_tpu.mainchain.client import SMCClient
    from gethsharding_tpu.mainchain.mirror import StateMirror

    chain = _chain()
    manager, (a,) = _accounts(1)
    chain.fund(a.address, 2000 * ETHER)
    client = SMCClient(backend=chain, accounts=manager, account=a,
                       config=chain.config)
    mirror = StateMirror(client=client)
    mirror.start()
    try:
        for _ in range(8):
            chain.commit()
        assert mirror.snapshot()["block_number"] == 8
        chain.set_head(4)  # head event -> mirror refresh
        snap = mirror.snapshot()
        assert snap["block_number"] == 4
        assert snap["reorg_gen"] == 1
        # ...and the chain keeps advancing from the rolled-back head
        chain.commit()
        assert mirror.refresh()["block_number"] == 5
    finally:
        mirror.stop()


def test_mirror_rejects_stale_pre_reorg_snapshot():
    """The race the reorg generation exists for: a refresh assembled
    BEFORE a rollback (older generation, higher block number) lands
    late — it must NOT overwrite the post-reorg truth."""
    from gethsharding_tpu.mainchain.client import SMCClient
    from gethsharding_tpu.mainchain.mirror import StateMirror

    chain = _chain()
    manager, (a,) = _accounts(1)
    client = SMCClient(backend=chain, accounts=manager, account=a,
                       config=chain.config)
    mirror = StateMirror(client=client)
    for _ in range(8):
        chain.commit()
    stale = mirror.refresh()  # gen 0, block 8
    assert (stale["reorg_gen"], stale["block_number"]) == (0, 8)
    chain.set_head(4)
    fresh = mirror.refresh()  # gen 1, block 4
    assert (fresh["reorg_gen"], fresh["block_number"]) == (1, 4)

    real_pull = client.mirror_snapshot
    client.mirror_snapshot = lambda: dict(stale)  # the late delivery
    try:
        assert mirror.refresh() is fresh  # held; stale gen rejected
    finally:
        client.mirror_snapshot = real_pull
    assert mirror.snapshot()["reorg_gen"] == 1
