"""Whisper-analog tests: envelope PoW, sym/asym encryption, filters,
spam/expiry/dup dropping, two-node delivery over the hub, and the wire
codec round-trip for the cross-process tier."""

import time

import pytest

from gethsharding_tpu.p2p.service import Hub, P2PServer
from gethsharding_tpu.p2p.whisper import (
    DEFAULT_MIN_POW, Envelope, Whisper, WhisperError, public_key_bytes,
    seal)
from gethsharding_tpu.rpc import codec

TOPIC = b"shrd"
KEY = bytes(range(32))


def test_seal_open_symmetric_roundtrip():
    env = seal(b"hello shard", TOPIC, sym_key=KEY)
    assert env.pow() >= DEFAULT_MIN_POW
    assert env.topic == TOPIC
    assert b"hello shard" not in env.ciphertext  # actually encrypted

    from gethsharding_tpu.p2p.whisper import _open_sym

    assert _open_sym(env.ciphertext, KEY, TOPIC) == b"hello shard"
    with pytest.raises(WhisperError, match="wrong key"):
        _open_sym(env.ciphertext, bytes(32), TOPIC)


def test_seal_open_asymmetric_roundtrip():
    from gethsharding_tpu.p2p.whisper import _open_asym

    priv = 0x1234567890ABCDEF
    env = seal(b"for your eyes", TOPIC, to_pub=public_key_bytes(priv))
    assert _open_asym(env.ciphertext, priv, TOPIC) == b"for your eyes"
    with pytest.raises(WhisperError):
        _open_asym(env.ciphertext, priv + 1, TOPIC)


def test_seal_validates_arguments():
    with pytest.raises(WhisperError, match="topic"):
        seal(b"x", b"toolong!", sym_key=KEY)
    with pytest.raises(WhisperError, match="exactly one"):
        seal(b"x", TOPIC)
    with pytest.raises(WhisperError, match="exactly one"):
        seal(b"x", TOPIC, sym_key=KEY, to_pub=b"\x01" * 64)


def test_pow_minting_scales_with_target():
    cheap = seal(b"msg", TOPIC, sym_key=KEY, min_pow=0.001)
    dear = seal(b"msg", TOPIC, sym_key=KEY, min_pow=64.0)
    assert dear.pow() >= 64.0
    assert cheap.pow() >= 0.001
    # the PoW value is intrinsic to the envelope: recomputable by relays
    clone = Envelope(expiry=dear.expiry, ttl=dear.ttl, topic=dear.topic,
                     ciphertext=dear.ciphertext, nonce=dear.nonce)
    assert clone.pow() == dear.pow()


def test_two_nodes_deliver_over_hub():
    hub = Hub()
    alice_p2p, bob_p2p = P2PServer(hub=hub), P2PServer(hub=hub)
    alice, bob = Whisper(alice_p2p), Whisper(bob_p2p)
    alice.start()
    bob.start()
    try:
        flt = bob.subscribe(TOPIC, sym_key=KEY)
        # an eavesdropper on the same topic with the wrong key sees nothing
        snoop = bob.subscribe(TOPIC, sym_key=bytes(32))
        alice.post(b"over the wire", TOPIC, sym_key=KEY)
        message = flt.get(timeout=10)
        assert message.payload == b"over the wire"
        assert snoop.queue.empty()
        # sender's own filters also see the post (local delivery)
        own = alice.subscribe(TOPIC, sym_key=KEY)
        alice.post(b"to myself too", TOPIC, sym_key=KEY)
        assert own.get(timeout=10).payload == b"to myself too"
    finally:
        alice.stop()
        bob.stop()


def test_ingest_drops_spam_expired_and_duplicates():
    w = Whisper(P2PServer(hub=Hub()), min_pow=8.0)
    flt = w.subscribe(TOPIC, sym_key=KEY)

    weak = seal(b"spam", TOPIC, sym_key=KEY, min_pow=0.0001)
    while weak.pow() >= 8.0:  # ensure genuinely below threshold
        weak = seal(b"spam" + bytes([len(weak.ciphertext) % 251]),
                    TOPIC, sym_key=KEY, min_pow=0.0001)
    w._ingest(weak)
    assert w.stats["dropped_pow"] == 1

    stale = seal(b"old", TOPIC, sym_key=KEY, min_pow=8.0,
                 ttl=5, now=time.time() - 100)
    w._ingest(stale)
    assert w.stats["dropped_expired"] == 1

    good = seal(b"fresh", TOPIC, sym_key=KEY, min_pow=8.0)
    w._ingest(good)
    w._ingest(good)
    assert w.stats["dropped_dup"] == 1
    assert flt.get(timeout=1).payload == b"fresh"
    assert flt.queue.empty()

    # unsubscribe stops delivery
    w.unsubscribe(flt)
    w._ingest(seal(b"later", TOPIC, sym_key=KEY, min_pow=8.0))
    assert flt.queue.empty()


def test_whisper_crosses_the_authenticated_relay():
    """The cross-process tier: envelopes flood between two RemoteHub
    clients attached to a chain relay, staying ciphertext on the wire."""
    from gethsharding_tpu.mainchain.accounts import AccountManager
    from gethsharding_tpu.p2p.remote import RemoteHub
    from gethsharding_tpu.params import Config
    from gethsharding_tpu.rpc.server import RPCServer
    from gethsharding_tpu.smc.chain import SimulatedMainchain

    backend = SimulatedMainchain(config=Config(network_id=11))
    server = RPCServer(backend, port=0)
    server.start()
    whispers = []
    hubs = []
    try:
        host, port = server.address
        for seed in (b"whisper-a", b"whisper-b"):
            manager = AccountManager()
            acct = manager.new_account(seed=seed)
            hub = RemoteHub.dial(host, port, accounts=manager,
                                 account=acct.address)
            hubs.append(hub)
            w = Whisper(P2PServer(hub=hub))
            w.start()
            whispers.append(w)
        alice, bob = whispers
        flt = bob.subscribe(TOPIC, sym_key=KEY)
        alice.post(b"across processes", TOPIC, sym_key=KEY)
        assert flt.get(timeout=10).payload == b"across processes"
    finally:
        for w in whispers:
            w.stop()
        for hub in hubs:
            hub.close()
        server.stop()


def test_ingest_bounds_and_local_delivery():
    """TTL-inconsistent expiry is refused (dedup-cache pinning defense);
    a node's own sub-threshold post still reaches its own filters; and
    stop() before start() is harmless."""
    w = Whisper(P2PServer(hub=Hub()), min_pow=8.0)
    flt = w.subscribe(TOPIC, sym_key=KEY)

    pinned = Envelope(expiry=2 ** 40, ttl=1, topic=TOPIC,
                      ciphertext=b"\x00" * 13, nonce=0)
    w._ingest(pinned)
    assert w.stats["dropped_future"] == 1
    assert not w._seen  # nothing cached for the hostile envelope

    # a local post below the relay threshold still self-delivers
    w.p2p.start()
    w.post(b"quiet note", TOPIC, sym_key=KEY, pow_target=0.0001)
    assert flt.get(timeout=1).payload == b"quiet note"

    Whisper(P2PServer(hub=Hub())).stop()  # no start(): no AttributeError


def test_malformed_envelope_does_not_kill_the_daemon():
    """A hostile peer's garbage must be dropped at the wire boundary
    (codec coercion) and, defense-in-depth, must not kill the delivery
    loop even if something slips through."""
    with pytest.raises((TypeError, ValueError)):
        codec.dec_p2p("WhisperEnvelope", {
            "expiry": "not-an-int", "ttl": 60, "topic": "73687264",
            "ciphertext": "00", "nonce": 0})

    hub = Hub()
    w = Whisper(P2PServer(hub=hub))
    w.start()
    try:
        # inject a poisoned Envelope object straight into the bus
        poisoned = Envelope(expiry="x", ttl=60, topic=TOPIC,
                            ciphertext=b"\x00", nonce=0)
        flt = w.subscribe(TOPIC, sym_key=KEY)
        w.p2p.loopback(poisoned)
        w.post(b"still alive", TOPIC, sym_key=KEY)
        assert flt.get(timeout=10).payload == b"still alive"
    finally:
        w.stop()


def test_envelope_wire_codec_roundtrip():
    env = seal(b"cross-process", TOPIC, sym_key=KEY)
    kind, payload = codec.enc_p2p(env)
    assert kind == "WhisperEnvelope"
    back = codec.dec_p2p(kind, payload)
    assert back == env
    assert back.hash() == env.hash()
    assert back.pow() == env.pow()
