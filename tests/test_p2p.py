"""Typed feed bus + hub transport."""

from gethsharding_tpu.p2p import (
    CollationBodyRequest,
    CollationBodyResponse,
    Feed,
    Hub,
    Message,
    P2PServer,
)
from gethsharding_tpu.utils.hexbytes import Address20, Hash32


def test_feed_fanout():
    feed = Feed()
    s1, s2 = feed.subscribe(), feed.subscribe()
    assert feed.send("x") == 2
    assert s1.get(timeout=1) == "x"
    assert s2.get(timeout=1) == "x"
    s1.unsubscribe()
    assert feed.send("y") == 1
    assert s2.get(timeout=1) == "y"


def test_feed_drop_oldest_when_full():
    feed = Feed()
    sub = feed.subscribe(maxsize=2)
    for i in range(5):
        feed.send(i)
    assert sub.get(timeout=1) == 3
    assert sub.get(timeout=1) == 4


def test_hub_directed_send():
    hub = Hub()
    a, b = P2PServer(hub), P2PServer(hub)
    a.start()
    b.start()
    sub = b.subscribe(CollationBodyRequest)
    request = CollationBodyRequest(
        chunk_root=Hash32(b"\x01" * 32), shard_id=1, period=2,
        proposer=Address20(b"\x02" * 20),
    )
    assert a.send(request, b.self_peer)
    msg = sub.get(timeout=1)
    assert isinstance(msg, Message)
    assert msg.data == request
    assert msg.peer == a.self_peer


def test_hub_broadcast_excludes_sender():
    hub = Hub()
    servers = [P2PServer(hub) for _ in range(3)]
    for s in servers:
        s.start()
    subs = [s.subscribe(CollationBodyResponse) for s in servers]
    response = CollationBodyResponse(header_hash=Hash32(), body=b"zz")
    assert servers[0].broadcast(response) == 2
    assert subs[1].get(timeout=1).data == response
    assert subs[2].get(timeout=1).data == response
    assert subs[0].try_get() is None


def test_loopback_reaches_own_feed():
    server = P2PServer()
    server.start()
    sub = server.subscribe(CollationBodyRequest)
    request = CollationBodyRequest(chunk_root=None, shard_id=0, period=0,
                                   proposer=None)
    server.loopback(request)
    assert sub.get(timeout=1).data == request


def test_detach_stops_delivery():
    hub = Hub()
    a, b = P2PServer(hub), P2PServer(hub)
    a.start()
    b.start()
    target = b.self_peer
    b.stop()
    assert not a.send("gone", target)


def test_peer_directory_merge_is_sybil_bounded():
    """One verified announce per account (freshest wins) and a hard
    table cap — a single key cannot mint unbounded peer_ids into the
    directory (p2p/discovery.py merge rules)."""
    from gethsharding_tpu.mainchain.accounts import AccountManager
    from gethsharding_tpu.p2p import discovery as disc

    mgr = AccountManager()
    addr = mgr.new_account(seed=b"sybil").address
    acct = bytes(addr).hex()
    d = disc.PeerDirectory(network_id=5)

    def ann(pid, seq, port=4000):
        digest = disc.announce_digest(5, pid, acct, "127.0.0.1", port, seq)
        return disc.PeerAnnounce(peer_id=pid, account=acct,
                                 host="127.0.0.1", port=port, seq=seq,
                                 sig=mgr.sign_hash(addr, digest))

    # many peer_ids signed by ONE account: only the freshest survives
    assert d.merge([ann(pid, seq=pid) for pid in range(1, 40)]) >= 1
    table = d.gossip_set()
    assert len(table) == 1 and table[0].peer_id == 39
    # a stale announce for the same account does not resurrect
    assert d.merge([ann(7, seq=7)]) == 0
    assert len(d.gossip_set()) == 1
    # forged signature never enters
    fake = disc.PeerAnnounce(peer_id=99, account=acct, host="127.0.0.1",
                             port=4000, seq=10 ** 6, sig=b"\x00" * 65)
    assert d.merge([fake]) == 0
    assert len(d.gossip_set()) == 1
