"""Typed feed bus + hub transport."""

from gethsharding_tpu.p2p import (
    CollationBodyRequest,
    CollationBodyResponse,
    Feed,
    Hub,
    Message,
    P2PServer,
)
from gethsharding_tpu.utils.hexbytes import Address20, Hash32


def test_feed_fanout():
    feed = Feed()
    s1, s2 = feed.subscribe(), feed.subscribe()
    assert feed.send("x") == 2
    assert s1.get(timeout=1) == "x"
    assert s2.get(timeout=1) == "x"
    s1.unsubscribe()
    assert feed.send("y") == 1
    assert s2.get(timeout=1) == "y"


def test_feed_drop_oldest_when_full():
    feed = Feed()
    sub = feed.subscribe(maxsize=2)
    for i in range(5):
        feed.send(i)
    assert sub.get(timeout=1) == 3
    assert sub.get(timeout=1) == 4


def test_hub_directed_send():
    hub = Hub()
    a, b = P2PServer(hub), P2PServer(hub)
    a.start()
    b.start()
    sub = b.subscribe(CollationBodyRequest)
    request = CollationBodyRequest(
        chunk_root=Hash32(b"\x01" * 32), shard_id=1, period=2,
        proposer=Address20(b"\x02" * 20),
    )
    assert a.send(request, b.self_peer)
    msg = sub.get(timeout=1)
    assert isinstance(msg, Message)
    assert msg.data == request
    assert msg.peer == a.self_peer


def test_hub_broadcast_excludes_sender():
    hub = Hub()
    servers = [P2PServer(hub) for _ in range(3)]
    for s in servers:
        s.start()
    subs = [s.subscribe(CollationBodyResponse) for s in servers]
    response = CollationBodyResponse(header_hash=Hash32(), body=b"zz")
    assert servers[0].broadcast(response) == 2
    assert subs[1].get(timeout=1).data == response
    assert subs[2].get(timeout=1).data == response
    assert subs[0].try_get() is None


def test_loopback_reaches_own_feed():
    server = P2PServer()
    server.start()
    sub = server.subscribe(CollationBodyRequest)
    request = CollationBodyRequest(chunk_root=None, shard_id=0, period=0,
                                   proposer=None)
    server.loopback(request)
    assert sub.get(timeout=1).data == request


def test_detach_stops_delivery():
    hub = Hub()
    a, b = P2PServer(hub), P2PServer(hub)
    a.start()
    b.start()
    target = b.self_peer
    b.stop()
    assert not a.send("gone", target)
