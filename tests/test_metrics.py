"""Metrics registry tests (parity: metrics/metrics.go registry semantics,
scoped to the native counters/gauges/timers the framework instruments)."""

import time

from gethsharding_tpu.metrics import (
    Counter,
    Gauge,
    PeriodicReporter,
    Registry,
    Timer,
)


def test_counter_and_rate():
    c = Counter()
    c.inc()
    c.inc(4)
    assert c.value == 5
    assert c.rate() > 0
    snap = c.snapshot()
    assert snap["type"] == "counter" and snap["count"] == 5


def test_gauge():
    g = Gauge()
    g.set(3.5)
    assert g.value == 3.5


def test_timer_percentiles_and_context():
    t = Timer()
    for ms in (1, 2, 3, 4, 100):
        t.observe(ms / 1000)
    assert t.count == 5
    assert 0.001 <= t.percentile(0.5) <= 0.004
    assert t.percentile(0.99) == 0.1
    with t.time():
        time.sleep(0.01)
    assert t.count == 6


def test_timer_ring_buffer_recent_window():
    t = Timer(reservoir=4)
    for v in (1.0, 1.0, 1.0, 1.0, 0.001, 0.001, 0.001, 0.001):
        t.observe(v)
    # old 1.0s samples were overwritten by the recent window
    assert t.percentile(0.99) == 0.001
    assert t.count == 8


def test_registry_get_or_register_and_snapshot():
    r = Registry()
    c1 = r.counter("a/ops")
    c2 = r.counter("a/ops")
    assert c1 is c2
    r.timer("a/latency").observe(0.5)
    snap = r.snapshot()
    assert set(snap) == {"a/ops", "a/latency"}
    assert snap["a/latency"]["p50_s"] == 0.5


def test_periodic_reporter_logs(caplog):
    import logging

    r = Registry()
    r.counter("x").inc()
    reporter = PeriodicReporter(registry=r, interval=0.05,
                                logger=logging.getLogger("test-metrics"))
    with caplog.at_level(logging.INFO, logger="test-metrics"):
        reporter.start()
        time.sleep(0.2)
        reporter.stop()
    assert any("x" in rec.message for rec in caplog.records)


def test_notary_instruments_baseline_metrics():
    """The notary registers the two BASELINE metrics on the default
    registry (sig-verifs counter + validate-latency timer)."""
    from gethsharding_tpu.metrics import DEFAULT_REGISTRY
    from gethsharding_tpu.actors.notary import Notary
    from gethsharding_tpu.core.shard import Shard
    from gethsharding_tpu.db.kv import MemoryKV
    from gethsharding_tpu.mainchain.client import SMCClient

    client = SMCClient()
    notary = Notary(client=client,
                    shard=Shard(shard_id=0, shard_db=MemoryKV()))
    assert DEFAULT_REGISTRY.get("notary/aggregate_sig_verifications") is not None
    assert DEFAULT_REGISTRY.get("notary/validate_latency") is not None
    assert notary.m_votes is DEFAULT_REGISTRY.get("notary/votes_submitted")


def test_influx_line_exporter_file_and_udp(tmp_path):
    """metrics/influxdb exporter analog: registry snapshots as line
    protocol, pushed to a file sink and over UDP."""
    import socket

    from gethsharding_tpu.metrics import InfluxLineExporter, Registry

    registry = Registry()
    registry.counter("notary/votes").inc(3)
    registry.gauge("pool size").set(2.5)
    with registry.timer("audit/latency").time():
        pass

    # file sink
    path = str(tmp_path / "metrics.influx")
    exporter = InfluxLineExporter(registry=registry, path=path)
    exporter.push()
    exporter.push()
    lines = open(path).read().strip().splitlines()
    assert len(lines) >= 6  # 3 metrics x 2 pushes
    sample = [ln for ln in lines if ln.startswith("gethsharding.notary.votes ")]
    assert sample, lines
    measurement, fields, ts = sample[0].split(" ")
    assert measurement == "gethsharding.notary.votes"
    assert "count=3.0" in fields.split(",")
    assert int(ts) > 0
    # names with separators/spaces are escaped, never break the protocol
    assert any(ln.startswith("gethsharding.pool_size ") for ln in lines)

    # UDP sink
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    sock.bind(("127.0.0.1", 0))
    sock.settimeout(5.0)
    udp_exporter = InfluxLineExporter(registry=registry,
                                      udp=sock.getsockname())
    udp_exporter.push()
    payload = sock.recv(65536).decode()
    assert "gethsharding.audit.latency " in payload
    udp_exporter.stop()
    sock.close()

    import pytest

    with pytest.raises(ValueError):
        InfluxLineExporter(registry=registry)  # no sink
