"""Metrics registry tests (parity: metrics/metrics.go registry semantics,
scoped to the native counters/gauges/timers the framework instruments)."""

import time

from gethsharding_tpu.metrics import (
    Counter,
    Gauge,
    PeriodicReporter,
    Registry,
    Timer,
)


def test_counter_and_rate():
    c = Counter()
    c.inc()
    c.inc(4)
    assert c.value == 5
    assert c.rate() > 0
    snap = c.snapshot()
    assert snap["type"] == "counter" and snap["count"] == 5


def test_gauge():
    g = Gauge()
    g.set(3.5)
    assert g.value == 3.5


def test_timer_percentiles_and_context():
    t = Timer()
    for ms in (1, 2, 3, 4, 100):
        t.observe(ms / 1000)
    assert t.count == 5
    assert 0.001 <= t.percentile(0.5) <= 0.004
    assert t.percentile(0.99) == 0.1
    with t.time():
        time.sleep(0.01)
    assert t.count == 6


def test_timer_ring_buffer_recent_window():
    t = Timer(reservoir=4)
    for v in (1.0, 1.0, 1.0, 1.0, 0.001, 0.001, 0.001, 0.001):
        t.observe(v)
    # old 1.0s samples were overwritten by the recent window
    assert t.percentile(0.99) == 0.001
    assert t.count == 8


def test_registry_get_or_register_and_snapshot():
    r = Registry()
    c1 = r.counter("a/ops")
    c2 = r.counter("a/ops")
    assert c1 is c2
    r.timer("a/latency").observe(0.5)
    snap = r.snapshot()
    assert set(snap) == {"a/ops", "a/latency"}
    assert snap["a/latency"]["p50_s"] == 0.5


def test_periodic_reporter_logs(caplog):
    import logging

    r = Registry()
    r.counter("x").inc()
    reporter = PeriodicReporter(registry=r, interval=0.05,
                                logger=logging.getLogger("test-metrics"))
    with caplog.at_level(logging.INFO, logger="test-metrics"):
        reporter.start()
        time.sleep(0.2)
        reporter.stop()
    assert any("x" in rec.message for rec in caplog.records)


def test_notary_instruments_baseline_metrics():
    """The notary registers the two BASELINE metrics on the default
    registry (sig-verifs counter + validate-latency timer)."""
    from gethsharding_tpu.metrics import DEFAULT_REGISTRY
    from gethsharding_tpu.actors.notary import Notary
    from gethsharding_tpu.core.shard import Shard
    from gethsharding_tpu.db.kv import MemoryKV
    from gethsharding_tpu.mainchain.client import SMCClient

    client = SMCClient()
    notary = Notary(client=client,
                    shard=Shard(shard_id=0, shard_db=MemoryKV()))
    assert DEFAULT_REGISTRY.get("notary/aggregate_sig_verifications") is not None
    assert DEFAULT_REGISTRY.get("notary/validate_latency") is not None
    assert notary.m_votes is DEFAULT_REGISTRY.get("notary/votes_submitted")
