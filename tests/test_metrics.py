"""Metrics registry tests (parity: metrics/metrics.go registry semantics,
scoped to the native counters/gauges/timers the framework instruments)."""

import time

import pytest

from gethsharding_tpu.metrics import (
    Counter,
    Gauge,
    PeriodicReporter,
    Registry,
    Timer,
)


def test_counter_and_rate():
    c = Counter()
    c.inc()
    c.inc(4)
    assert c.value == 5
    assert c.rate() > 0
    snap = c.snapshot()
    assert snap["type"] == "counter" and snap["count"] == 5
    assert "rate_1m" in snap  # the EWMA meter rides every snapshot


def test_counter_rate_1m_ewma():
    """The go-metrics Meter analog: a 1-minute EWMA over 5 s ticks that
    tracks recent traffic and decays when it stops — unlike `rate()`,
    which averages over the counter's whole lifetime."""
    c = Counter()
    t0 = c._last_tick
    assert c.rate_1m(now=t0 + 1.0) == 0.0  # before the first tick
    c.inc(300)
    # nudge past the tick boundaries: t0 + exactly N*5.0 can round a
    # hair below the boundary at large monotonic values (float binade)
    first = c.rate_1m(now=t0 + 5.1)
    assert first == 300 / 5.0  # first tick seeds the EWMA
    # a minute of silence: the rate decays toward zero instead of the
    # since-creation average's slow drift
    decayed = c.rate_1m(now=t0 + 65.1)
    assert 0.0 < decayed < first / 2
    # fresh traffic pulls it back up
    c.inc(600)
    assert c.rate_1m(now=t0 + 70.2) > decayed


def test_gauge():
    g = Gauge()
    g.set(3.5)
    assert g.value == 3.5


def test_timer_percentiles_and_context():
    t = Timer()
    for ms in (1, 2, 3, 4, 100):
        t.observe(ms / 1000)
    assert t.count == 5
    assert 0.001 <= t.percentile(0.5) <= 0.004
    assert t.percentile(0.99) == 0.1
    with t.time():
        time.sleep(0.01)
    assert t.count == 6


def test_timer_ring_buffer_recent_window():
    t = Timer(reservoir=4)
    for v in (1.0, 1.0, 1.0, 1.0, 0.001, 0.001, 0.001, 0.001):
        t.observe(v)
    # old 1.0s samples were overwritten by the recent window
    assert t.percentile(0.99) == 0.001
    assert t.count == 8


def test_histogram_quantile_known_distributions():
    """`Histogram.quantile(q)` interpolates linearly within the
    cumulative bucket the target rank falls in — checked against
    distributions whose quantiles are known exactly."""
    from gethsharding_tpu.metrics import Histogram

    # uniform over (0, 10]: 100 observations, one per 0.1 step, in a
    # single-bucket histogram (bounds 10) — the q-quantile of uniform
    # data interpolates to ~10q
    h = Histogram(buckets=(10,))
    for i in range(1, 101):
        h.observe(i / 10)
    assert h.quantile(0.5) == pytest.approx(5.0)
    assert h.quantile(0.9) == pytest.approx(9.0)
    assert h.quantile(1.0) == pytest.approx(10.0)

    # two buckets, skewed mass: 90 in (0,1], 10 in (1,2] — p50 sits at
    # 5/9 through the first bucket, p95 midway through the second
    h = Histogram(buckets=(1, 2))
    for _ in range(90):
        h.observe(0.5)
    for _ in range(10):
        h.observe(1.5)
    assert h.quantile(0.5) == pytest.approx(50 / 90)
    assert h.quantile(0.95) == pytest.approx(1.5)

    # overflow clamps to the largest finite bound (no +Inf edge to
    # interpolate toward), empty histogram reads 0
    h = Histogram(buckets=(1, 2))
    h.observe(100.0)
    assert h.quantile(0.99) == 2.0
    assert Histogram(buckets=(1,)).quantile(0.5) == 0.0
    with pytest.raises(ValueError):
        h.quantile(1.5)


def test_histogram_snapshot_carries_percentiles():
    from gethsharding_tpu.metrics import Histogram

    h = Histogram(buckets=(1, 2, 4))
    for v in (0.5, 1.5, 3.0, 3.5):
        h.observe(v)
    snap = h.snapshot()
    for key in ("p50", "p95", "p99"):
        assert key in snap and snap[key] > 0
    assert snap["p50"] <= snap["p95"] <= snap["p99"]


def test_registry_get_or_register_and_snapshot():
    r = Registry()
    c1 = r.counter("a/ops")
    c2 = r.counter("a/ops")
    assert c1 is c2
    r.timer("a/latency").observe(0.5)
    snap = r.snapshot()
    assert set(snap) == {"a/ops", "a/latency"}
    assert snap["a/latency"]["p50_s"] == 0.5


def test_periodic_reporter_logs(caplog):
    import logging

    r = Registry()
    r.counter("x").inc()
    reporter = PeriodicReporter(registry=r, interval=0.05,
                                logger=logging.getLogger("test-metrics"))
    with caplog.at_level(logging.INFO, logger="test-metrics"):
        reporter.start()
        time.sleep(0.2)
        reporter.stop()
    assert any("x" in rec.message for rec in caplog.records)


def test_notary_instruments_baseline_metrics():
    """The notary registers the two BASELINE metrics on the default
    registry (sig-verifs counter + validate-latency timer)."""
    from gethsharding_tpu.metrics import DEFAULT_REGISTRY
    from gethsharding_tpu.actors.notary import Notary
    from gethsharding_tpu.core.shard import Shard
    from gethsharding_tpu.db.kv import MemoryKV
    from gethsharding_tpu.mainchain.client import SMCClient

    client = SMCClient()
    notary = Notary(client=client,
                    shard=Shard(shard_id=0, shard_db=MemoryKV()))
    assert DEFAULT_REGISTRY.get("notary/aggregate_sig_verifications") is not None
    assert DEFAULT_REGISTRY.get("notary/validate_latency") is not None
    assert notary.m_votes is DEFAULT_REGISTRY.get("notary/votes_submitted")


def test_influx_line_exporter_file_and_udp(tmp_path):
    """metrics/influxdb exporter analog: registry snapshots as line
    protocol, pushed to a file sink and over UDP."""
    import socket

    from gethsharding_tpu.metrics import InfluxLineExporter, Registry

    registry = Registry()
    registry.counter("notary/votes").inc(3)
    registry.gauge("pool size").set(2.5)
    with registry.timer("audit/latency").time():
        pass

    # file sink
    path = str(tmp_path / "metrics.influx")
    exporter = InfluxLineExporter(registry=registry, path=path)
    exporter.push()
    exporter.push()
    lines = open(path).read().strip().splitlines()
    assert len(lines) >= 6  # 3 metrics x 2 pushes
    sample = [ln for ln in lines if ln.startswith("gethsharding.notary.votes ")]
    assert sample, lines
    measurement, fields, ts = sample[0].split(" ")
    assert measurement == "gethsharding.notary.votes"
    assert "count=3.0" in fields.split(",")
    assert int(ts) > 0
    # names with separators/spaces are escaped, never break the protocol
    assert any(ln.startswith("gethsharding.pool_size ") for ln in lines)

    # UDP sink
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    sock.bind(("127.0.0.1", 0))
    sock.settimeout(5.0)
    udp_exporter = InfluxLineExporter(registry=registry,
                                      udp=sock.getsockname())
    udp_exporter.push()
    payload = sock.recv(65536).decode()
    assert "gethsharding.audit.latency " in payload
    udp_exporter.stop()
    sock.close()

    import pytest

    with pytest.raises(ValueError):
        InfluxLineExporter(registry=registry)  # no sink


def test_influx_udp_sink_periodic_and_final_flush():
    """The UDP sink end to end: the background thread pushes on its
    interval, and stop() sends one FINAL flush so the last interval's
    activity is never lost (the exporter contract the file sink's tests
    already pin)."""
    import socket

    from gethsharding_tpu.metrics import InfluxLineExporter, Registry

    registry = Registry()
    registry.counter("udp/events").inc(7)
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    sock.bind(("127.0.0.1", 0))
    sock.settimeout(5.0)
    exporter = InfluxLineExporter(registry=registry, interval=0.05,
                                  udp=sock.getsockname())
    exporter.start()
    payload = sock.recv(65536).decode()  # a periodic push arrived
    assert "gethsharding.udp.events" in payload
    assert "count=7.0" in payload
    # activity in the final window, then stop: the final flush carries it
    registry.counter("udp/events").inc(1)
    pushed_before = exporter.pushes
    exporter.stop()
    assert exporter.pushes > pushed_before  # stop() flushed once more
    final = b""
    try:
        while True:
            final = sock.recv(65536)  # drain to the newest datagram
            sock.settimeout(0.2)
    except socket.timeout:
        pass
    assert b"count=8.0" in final
    assert exporter._sock is None  # socket released
    sock.close()


def test_influx_file_sink_final_flush_on_stop(tmp_path):
    """stop() on a file-sink exporter performs the final flush even when
    the interval never elapsed."""
    from gethsharding_tpu.metrics import InfluxLineExporter, Registry

    registry = Registry()
    registry.counter("f/events").inc(3)
    path = str(tmp_path / "final.influx")
    exporter = InfluxLineExporter(registry=registry, interval=600.0,
                                  path=path)
    exporter.start()
    exporter.stop()  # interval (10 min) never fired: only the final flush
    lines = open(path).read().strip().splitlines()
    assert len(lines) == 1 and "count=3.0" in lines[0]


def test_influx_histogram_fields_are_cumulative_and_exact():
    """The exporter's histogram lines carry BOTH bucket views: the
    cumulative Prometheus-style le_* fields and the exact per-slot
    bucket_* fields."""
    from gethsharding_tpu.metrics import InfluxLineExporter, Registry

    registry = Registry()
    hist = registry.histogram("h/rows", buckets=(1, 4))
    for value in (1, 3, 9):
        hist.observe(value)
    payload = InfluxLineExporter(
        registry=registry, udp=("127.0.0.1", 1)).encode_snapshot(
        timestamp_ns=1)
    fields = payload.decode().split(" ")[1].split(",")
    assert "le_4=2.0" in fields and "le_inf=3.0" in fields  # cumulative
    assert "bucket_4=1.0" in fields and "bucket_inf=1.0" in fields


def test_prometheus_text_exposition():
    """The /metrics?format=prom payload: every metric kind rendered in
    text exposition format with legal names, counters as _total,
    histograms with cumulative le buckets ending at +Inf == count."""
    from gethsharding_tpu.metrics import Registry, prometheus_text

    registry = Registry()
    registry.counter("notary/votes submitted").inc(4)
    registry.gauge("pool/depth").set(2.5)
    registry.timer("audit/latency").observe(0.25)
    hist = registry.histogram("serving/rows", buckets=(1, 4))
    for value in (1, 3, 9):
        hist.observe(value)

    text = prometheus_text(registry)
    lines = text.strip().splitlines()
    assert "gethsharding_notary_votes_submitted_total 4" in lines
    assert "# TYPE gethsharding_notary_votes_submitted_total counter" in lines
    assert "gethsharding_pool_depth 2.5" in lines
    assert 'gethsharding_audit_latency{quantile="0.5"} 0.25' in lines
    assert "gethsharding_audit_latency_count 1" in lines
    assert 'gethsharding_serving_rows_bucket{le="1"} 1' in lines
    assert 'gethsharding_serving_rows_bucket{le="4"} 2' in lines
    assert 'gethsharding_serving_rows_bucket{le="+Inf"} 3' in lines
    assert "gethsharding_serving_rows_count 3" in lines
    assert text.endswith("\n")
    # an empty registry still yields a non-empty scrape body
    assert prometheus_text(Registry()).strip()
