"""shardlint: the static analysis pass + runtime lockcheck.

Three layers of coverage:

- the LIVE TREE gate: every rule over the real repo must report zero
  findings outside the committed baseline (this is the same gate
  `run_suite.sh` and the CLI enforce), the baseline must carry real
  justifications, and the pass must be fast and non-vacuous (the lock
  graph actually has nodes/edges, the jit collector actually finds the
  kernels, the contract rule actually sees all six wrappers);
- per-rule FIXTURES: one known-bad and one known-good snippet per
  rule, run over throwaway corpus trees;
- the RUNTIME lockcheck: a deliberate A->B / B->A inversion must be
  detected, re-entrant locks must not self-report, and the
  observed-vs-static cross-check must flag a reversed static edge.
"""

import json
import textwrap
import threading
from pathlib import Path

import pytest

from gethsharding_tpu.analysis import (
    Baseline, Corpus, Finding, RULES, run, run_rules)
from gethsharding_tpu.analysis.__main__ import main as cli_main
from gethsharding_tpu.analysis.contract import wrapper_report
from gethsharding_tpu.analysis.locks import build_lock_model

REPO = Path(__file__).resolve().parents[1]


def make_corpus(tmp_path, files):
    for rel, src in files.items():
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(src), encoding="utf-8")
    return Corpus.load(tmp_path)


def idents(findings, rule=None):
    return {f.ident for f in findings if rule is None or f.rule == rule}


# -- the live-tree gate ------------------------------------------------------

@pytest.fixture(scope="module")
def live_report():
    return run(REPO)


@pytest.fixture(scope="module")
def live_corpus():
    return Corpus.load(REPO)


def test_live_tree_zero_new_findings(live_report):
    """THE gate: the committed tree is clean modulo the baseline."""
    assert not live_report.new, (
        "shardlint found new findings — fix them or baseline with a "
        "justification:\n" + "\n".join(f.render() for f in live_report.new))


def test_live_tree_no_stale_baseline(live_report):
    assert not live_report.stale, (
        "baseline entries whose finding no longer fires — delete them:\n"
        + "\n".join(live_report.stale))


def test_live_tree_within_budget(live_report):
    assert live_report.elapsed_s < 30.0, (
        f"shardlint took {live_report.elapsed_s:.1f}s; the acceptance "
        f"budget is 30s")


def test_baseline_entries_are_justified():
    data = json.loads(
        (REPO / "gethsharding_tpu/analysis/baseline.json").read_text())
    for key, why in data["findings"].items():
        assert why and not why.startswith("TODO"), (
            f"baseline entry {key} has no real justification")


def test_live_lock_graph_is_nonvacuous_and_acyclic(live_corpus):
    model = build_lock_model(live_corpus)
    assert len(model.nodes) >= 10  # the threaded subsystems all show up
    assert "gethsharding_tpu/serving/queue.py::AdmissionQueue._lock" \
        in model.nodes
    assert "gethsharding_tpu/metrics.py::Counter._lock" in model.nodes
    # cross-module edges exist (subsystem locks call into metrics)
    assert any(b.startswith("gethsharding_tpu/metrics.py::")
               for (_, b) in model.edges), model.edges
    assert model.cycles() == []


def test_live_backend_contract_covers_all_six_wrappers(live_corpus):
    """Acceptance: the rule PROVES the six SigBackend wrappers expose the
    full PythonSigBackend surface (modulo the baselined RPC-replica
    stubs, which are deliberate and justified)."""
    report = wrapper_report(live_corpus)
    expect = {
        "gethsharding_tpu/serving/backend.py::ServingSigBackend",
        "gethsharding_tpu/serving/backend.py::ClassedSigBackend",
        "gethsharding_tpu/resilience/breaker.py::FailoverSigBackend",
        "gethsharding_tpu/resilience/soundness.py::SpotCheckSigBackend",
        "gethsharding_tpu/resilience/chaos.py::ChaosSigBackend",
        "gethsharding_tpu/fleet/router.py::RouterSigBackend",
        "gethsharding_tpu/fleet/router.py::RpcReplicaBackend",
    }
    assert expect <= set(report), sorted(report)
    for qual in expect - {"gethsharding_tpu/fleet/router.py::"
                          "RpcReplicaBackend"}:
        assert report[qual] == {}, f"{qual}: {report[qual]}"
    # the replica face: nothing MISSING (explicit stubs only, baselined)
    assert "missing" not in report[
        "gethsharding_tpu/fleet/router.py::RpcReplicaBackend"].values()


def test_live_jit_collector_finds_the_kernel_surface(live_corpus):
    from gethsharding_tpu.analysis.purity import _collect_jitted

    jitted = _collect_jitted(live_corpus)
    names = {fn.name for _, fn, _ in jitted}
    # the three faces: decorated kernels, jit() call sites resolved
    # cross-module, pallas kernels behind functools.partial
    assert "ecrecover_batch" in names
    assert "bls_aggregate_verify_committee_batch" in names
    assert any(how == "pallas_call" for _, _, how in jitted)
    assert len(jitted) >= 15


# -- jit-purity fixtures -----------------------------------------------------

def test_jit_purity_flags_impure_kernel(tmp_path):
    corpus = make_corpus(tmp_path, {"gethsharding_tpu/bad.py": """
        import time, random, threading
        import jax

        @jax.jit
        def kernel(x):
            t = time.time()
            r = random.random()
            threading.Event()
            return x + t + r
    """})
    got = idents(run_rules(corpus, ["jit-purity"]))
    assert "kernel:call:time.time" in got
    assert "kernel:call:random.random" in got
    assert "kernel:call:threading.Event" in got


def test_jit_purity_flags_global_and_captured_mutation(tmp_path):
    corpus = make_corpus(tmp_path, {"gethsharding_tpu/bad2.py": """
        import jax

        CACHE = {}
        COUNT = 0

        def impure(x):
            global COUNT
            COUNT += 1
            CACHE[1] = x
            return x

        wrapped = jax.jit(impure)
    """})
    got = idents(run_rules(corpus, ["jit-purity"]))
    assert "impure:global:COUNT" in got
    assert "impure:mutate:CACHE" in got


def test_jit_purity_flags_from_imported_impurity(tmp_path):
    """Review regression: `from time import time; time()` must be
    flagged exactly like `time.time()` — the from-import form is the
    idiomatic one and used to slip through."""
    corpus = make_corpus(tmp_path, {"gethsharding_tpu/bad3.py": """
        from time import time
        from random import random as rnd
        import jax

        @jax.jit
        def kernel(x):
            return x + time() + rnd()
    """})
    got = idents(run_rules(corpus, ["jit-purity"]))
    assert "kernel:call:time" in got
    assert "kernel:call:rnd" in got


def test_jit_purity_clean_kernel_passes(tmp_path):
    corpus = make_corpus(tmp_path, {"gethsharding_tpu/good.py": """
        import functools
        import jax
        import jax.numpy as jnp

        @jax.jit
        def pure(x):
            out = jnp.zeros_like(x)       # local mutation is fine
            out = out.at[0].set(1)
            acc = {}
            acc["k"] = x                  # local dict is fine
            return out + acc["k"]

        def _kernel(ref, o_ref):
            o_ref[...] = ref[...] * 2     # params are local

        kernel = functools.partial(_kernel)
    """})
    assert run_rules(corpus, ["jit-purity"]) == []


def test_jit_purity_resolves_cross_module_jit_targets(tmp_path):
    corpus = make_corpus(tmp_path, {
        "gethsharding_tpu/ops2/__init__.py": "",
        "gethsharding_tpu/ops2/kern.py": """
            import time

            def batch(x):
                return x + time.time()
        """,
        "gethsharding_tpu/backend2.py": """
            import jax
            from gethsharding_tpu.ops2 import kern

            recover = jax.jit(kern.batch)
        """,
    })
    got = idents(run_rules(corpus, ["jit-purity"]))
    assert "batch:call:time.time" in got


# -- host-sync fixtures ------------------------------------------------------

def test_host_sync_flags_pulls_outside_marshal_layer(tmp_path):
    corpus = make_corpus(tmp_path, {"gethsharding_tpu/actors2.py": """
        import jax
        import numpy as np

        def hot_loop(arr):
            v = arr.sum().item()
            w = np.asarray(arr)
            jax.device_get(arr)
            arr.block_until_ready()
            return v, w
    """})
    got = idents(run_rules(corpus, ["host-sync"]))
    assert got == {"hot_loop:.item()", "hot_loop:np.asarray",
                   "hot_loop:jax.device_get",
                   "hot_loop:.block_until_ready()"}


def test_host_sync_allows_marshal_zones_and_numpy_only_files(tmp_path):
    corpus = make_corpus(tmp_path, {
        # ops/ is the marshal layer: pulls are its job
        "gethsharding_tpu/ops/marshal2.py": """
            import jax
            import numpy as np

            def finalize(arr):
                return np.asarray(arr).item()
        """,
        # no jax anywhere near: np.asarray is host->host
        "gethsharding_tpu/utils2.py": """
            import numpy as np

            def pack(rows):
                return np.asarray(rows)
        """,
    })
    assert run_rules(corpus, ["host-sync"]) == []


# -- lock-order fixtures -----------------------------------------------------

_CYCLE_A = """
    import threading

    class Alpha:
        def __init__(self, beta=None):
            self._lock = threading.Lock()
            self.beta = Beta(self)

        def hit(self):
            with self._lock:
                self.beta.poke()

        def poke(self):
            with self._lock:
                pass

    class Beta:
        def __init__(self, alpha):
            self._lock = threading.Lock()
            self.alpha: "Alpha" = alpha

        def hit(self):
            with self._lock:
                self.alpha.poke()

        def poke(self):
            with self._lock:
                pass
"""


def test_lock_order_detects_ab_ba_cycle(tmp_path):
    corpus = make_corpus(
        tmp_path, {"gethsharding_tpu/serving/tangle.py": _CYCLE_A})
    findings = run_rules(corpus, ["lock-order"])
    assert len(findings) == 1
    assert findings[0].ident.startswith("cycle:")
    assert "Alpha._lock" in findings[0].message
    assert "Beta._lock" in findings[0].message


def test_lock_order_consistent_nesting_is_clean(tmp_path):
    corpus = make_corpus(tmp_path, {"gethsharding_tpu/serving/neat.py": """
        import threading

        class Inner:
            def __init__(self):
                self._lock = threading.Lock()

            def poke(self):
                with self._lock:
                    pass

        class Outer:
            def __init__(self):
                self._lock = threading.Lock()
                self.inner = Inner()

            def hit(self):
                with self._lock:
                    self.inner.poke()
    """})
    findings = run_rules(corpus, ["lock-order"])
    assert findings == []
    model = build_lock_model(corpus)
    # one direction only: Outer -> Inner
    assert ("gethsharding_tpu/serving/neat.py::Outer._lock",
            "gethsharding_tpu/serving/neat.py::Inner._lock") in model.edges


def test_lock_order_detects_nonreentrant_self_deadlock(tmp_path):
    corpus = make_corpus(tmp_path, {"gethsharding_tpu/serving/selfd.py": """
        import threading

        class Oops:
            def __init__(self):
                self._lock = threading.Lock()

            def outer(self):
                with self._lock:
                    self.inner()

            def inner(self):
                with self._lock:
                    pass
    """})
    got = idents(run_rules(corpus, ["lock-order"]))
    assert any(i.startswith("self-deadlock:") for i in got), got


def test_lock_order_rlock_reentry_is_fine(tmp_path):
    corpus = make_corpus(tmp_path, {"gethsharding_tpu/serving/reent.py": """
        import threading

        class Fine:
            def __init__(self):
                self._lock = threading.RLock()

            def outer(self):
                with self._lock:
                    self.inner()

            def inner(self):
                with self._lock:
                    pass
    """})
    assert run_rules(corpus, ["lock-order"]) == []


def test_lock_order_multi_item_with_orders_its_own_items(tmp_path):
    """Review regression: `with self._a, self._b:` orders a before b
    exactly like nested withs — combined with a b-then-a method it must
    be reported as a cycle."""
    corpus = make_corpus(tmp_path, {"gethsharding_tpu/serving/multi.py": """
        import threading

        class Pair:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def forward(self):
                with self._a, self._b:
                    pass

            def backward(self):
                with self._b:
                    with self._a:
                        pass
    """})
    model = build_lock_model(corpus)
    a = "gethsharding_tpu/serving/multi.py::Pair._a"
    b = "gethsharding_tpu/serving/multi.py::Pair._b"
    assert (a, b) in model.edges and (b, a) in model.edges
    findings = run_rules(corpus, ["lock-order"])
    assert len(findings) == 1 and findings[0].ident.startswith("cycle:")


def test_lock_order_condition_aliases_to_its_lock(tmp_path):
    corpus = make_corpus(tmp_path, {"gethsharding_tpu/serving/cond.py": """
        import threading

        class Q:
            def __init__(self):
                self._lock = threading.Lock()
                self._not_empty = threading.Condition(self._lock)
    """})
    model = build_lock_model(corpus)
    nodes = {n for n in model.nodes if "cond.py" in n}
    # the Condition is the SAME node as the lock it wraps, not a second one
    assert nodes == {"gethsharding_tpu/serving/cond.py::Q._lock"}


# -- backend-contract fixtures -----------------------------------------------

_MINI_SIGBACKEND = """
    class SigBackend:
        def ecrecover_addresses(self, digests, sigs):
            raise NotImplementedError

        def bls_verify_aggregates(self, messages, sigs, pks):
            raise NotImplementedError

    class PythonSigBackend(SigBackend):
        def ecrecover_addresses(self, digests, sigs):
            return []

        def bls_verify_aggregates(self, messages, sigs, pks):
            return []
"""


def test_backend_contract_catches_broken_fixture_wrapper(tmp_path):
    corpus = make_corpus(tmp_path, {
        "gethsharding_tpu/sigbackend.py": _MINI_SIGBACKEND,
        "gethsharding_tpu/wrap.py": """
            from gethsharding_tpu.sigbackend import SigBackend

            class BrokenWrapper(SigBackend):
                def ecrecover_addresses(self, digests, sigs):
                    return list(digests)

            class StubWrapper(SigBackend):
                def ecrecover_addresses(self, digests, sigs):
                    return list(digests)

                def bls_verify_aggregates(self, messages, sigs, pks):
                    raise NotImplementedError("not here")
        """,
    })
    got = idents(run_rules(corpus, ["backend-contract"]))
    assert "BrokenWrapper.bls_verify_aggregates:missing" in got
    assert "StubWrapper.bls_verify_aggregates:stub" in got
    assert not any(i.startswith("BrokenWrapper.ecrecover") for i in got)


def test_backend_contract_complete_wrapper_is_clean(tmp_path):
    corpus = make_corpus(tmp_path, {
        "gethsharding_tpu/sigbackend.py": _MINI_SIGBACKEND,
        "gethsharding_tpu/wrap.py": """
            from gethsharding_tpu.sigbackend import SigBackend

            class GoodWrapper(SigBackend):
                def __init__(self, inner):
                    self.inner = inner

                def ecrecover_addresses(self, digests, sigs):
                    return self.inner.ecrecover_addresses(digests, sigs)

                def bls_verify_aggregates(self, messages, sigs, pks):
                    return self.inner.bls_verify_aggregates(
                        messages, sigs, pks)
        """,
    })
    assert run_rules(corpus, ["backend-contract"]) == []


def test_backend_contract_catches_ducktyped_wrapper(tmp_path):
    """A wrapper that never subclasses SigBackend (the RouterSigBackend
    shape) is still held to the contract."""
    corpus = make_corpus(tmp_path, {
        "gethsharding_tpu/sigbackend.py": _MINI_SIGBACKEND,
        "gethsharding_tpu/duck.py": """
            class DuckRouter:
                def ecrecover_addresses(self, digests, sigs):
                    return []
        """,
    })
    got = idents(run_rules(corpus, ["backend-contract"]))
    assert "DuckRouter.bls_verify_aggregates:missing" in got


# -- thread-lifecycle fixtures -----------------------------------------------

def test_thread_lifecycle_flags_unjoined_and_anonymous(tmp_path):
    corpus = make_corpus(tmp_path, {"gethsharding_tpu/svc.py": """
        import threading

        class Service:
            def start(self):
                self._worker = threading.Thread(target=self._run,
                                                daemon=True)
                self._worker.start()
                threading.Thread(target=self._run, daemon=True).start()

            def _run(self):
                pass
    """})
    got = idents(run_rules(corpus, ["thread-lifecycle"]))
    assert "start:self._worker" in got
    assert "start:anonymous" in got


def test_thread_lifecycle_joined_and_escaping_threads_pass(tmp_path):
    corpus = make_corpus(tmp_path, {"gethsharding_tpu/svc2.py": """
        import threading

        class Service:
            def start(self):
                thread = threading.Thread(target=self._run, daemon=True)
                self._worker = thread
                thread.start()
                pooled = threading.Thread(target=self._run, daemon=True)
                self._threads.append(pooled)   # handed to the joining pool

            def stop(self):
                worker = self._worker
                worker.join(timeout=5.0)

            def _run(self):
                pass
    """})
    assert run_rules(corpus, ["thread-lifecycle"]) == []


def test_thread_lifecycle_nested_def_reported_once_and_module_scope(tmp_path):
    """Review regressions: a thread spawned in a NESTED def is reported
    by its own scope only (one finding, one baseline key), and a
    module-level fire-and-forget spawn is visible at all."""
    corpus = make_corpus(tmp_path, {"gethsharding_tpu/svc3.py": """
        import threading

        threading.Thread(target=print, daemon=True).start()

        class Service:
            def start(self):
                def spawn():
                    runner = threading.Thread(target=print, daemon=True)
                    runner.start()
                spawn()
    """})
    findings = run_rules(corpus, ["thread-lifecycle"])
    got = idents(findings)
    assert got == {"<module>:anonymous", "spawn:runner"}, got
    assert len(findings) == 2


# -- flag-doc fixtures -------------------------------------------------------

def test_flag_doc_both_directions(tmp_path):
    corpus = make_corpus(tmp_path, {
        "gethsharding_tpu/knobs.py": """
            import os
            import argparse

            DOCUMENTED = os.environ.get("GETHSHARDING_DOCUMENTED")
            SECRET = os.environ.get("GETHSHARDING_SECRET_KNOB")

            def cli():
                p = argparse.ArgumentParser()
                p.add_argument("--documented-flag")
                p.add_argument("--secret-flag")
                return p
        """,
    })
    (tmp_path / "README.md").write_text(
        "Use `GETHSHARDING_DOCUMENTED` and `--documented-flag`.\n"
        "`GETHSHARDING_GHOST` and `--ghost-flag` do not exist.\n")
    got = idents(run_rules(corpus, ["flag-doc"]))
    assert got == {
        "undocumented-env:GETHSHARDING_SECRET_KNOB",
        "undocumented-flag:--secret-flag",
        "stale-env-doc:GETHSHARDING_GHOST",
        "stale-flag-doc:--ghost-flag",
    }


def test_flag_doc_counts_every_flag_in_a_shared_backtick_span(tmp_path):
    """Review regression: `--alpha --beta PATH` inside ONE backtick span
    documents both flags."""
    corpus = make_corpus(tmp_path, {
        "gethsharding_tpu/cli2.py": """
            import argparse

            def cli():
                p = argparse.ArgumentParser()
                p.add_argument("--alpha")
                p.add_argument("--beta")
                return p
        """,
    })
    (tmp_path / "README.md").write_text("Run with `--alpha --beta PATH`.\n")
    assert run_rules(corpus, ["flag-doc"]) == []


def test_flag_doc_matches_placeholder_skeletons(tmp_path):
    corpus = make_corpus(tmp_path, {
        "gethsharding_tpu/knobs2.py": """
            import os

            def deadline(name):
                return os.environ.get(
                    f"GETHSHARDING_KLASS_{name.upper()}_DEADLINE_S")
        """,
    })
    (tmp_path / "README.md").write_text(
        "| `GETHSHARDING_KLASS_<NAME>_DEADLINE_S` | unset | expiry |\n")
    assert run_rules(corpus, ["flag-doc"]) == []


# -- export-completeness fixtures --------------------------------------------

def test_export_completeness_dangling_and_unexported(tmp_path):
    corpus = make_corpus(tmp_path, {
        "gethsharding_tpu/pkg2/__init__.py": """
            from gethsharding_tpu.pkg2.errors import KnownError

            __all__ = ["KnownError", "Phantom"]
        """,
        "gethsharding_tpu/pkg2/errors.py": """
            class KnownError(RuntimeError):
                pass

            class ForgottenError(RuntimeError):
                pass

            class _Private(RuntimeError):
                pass
        """,
    })
    got = idents(run_rules(corpus, ["export-completeness"]))
    assert "dangling-export:gethsharding_tpu/pkg2:Phantom" in got
    assert "unexported-error:gethsharding_tpu/pkg2:ForgottenError" in got
    assert not any("_Private" in i for i in got)


def test_export_completeness_live_resilience_contract():
    """The migrated PR 7 one-off: every public errors.py exception is in
    resilience.__all__ — now enforced corpus-wide by the rule, checked
    here against the live import to keep the AST view honest."""
    import gethsharding_tpu.resilience as resilience
    from gethsharding_tpu.resilience import errors

    public = [name for name in dir(errors)
              if not name.startswith("_")
              and isinstance(getattr(errors, name), type)
              and issubclass(getattr(errors, name), BaseException)
              and getattr(errors, name).__module__ == errors.__name__]
    assert public
    for name in public:
        assert name in resilience.__all__
        assert getattr(resilience, name) is getattr(errors, name)


# -- baseline + CLI ----------------------------------------------------------

def test_finding_keys_are_line_free():
    f1 = Finding("r", "a/b.py", 10, "msg", "Sym.x")
    f2 = Finding("r", "a/b.py", 99, "other msg", "Sym.x")
    assert f1.key == f2.key == "r::a/b.py::Sym.x"


def test_baseline_split_and_roundtrip(tmp_path):
    f_new = Finding("r", "p.py", 1, "m", "new-one")
    f_old = Finding("r", "p.py", 2, "m", "known")
    baseline = Baseline({"r::p.py::known": "because",
                         "r::p.py::gone": "stale entry"})
    new, accepted, stale = baseline.split([f_new, f_old])
    assert [f.ident for f in new] == ["new-one"]
    assert [f.ident for f in accepted] == ["known"]
    assert stale == ["r::p.py::gone"]
    path = tmp_path / "b.json"
    baseline.save(path)
    assert Baseline.load(path).entries == baseline.entries


def test_cli_gate_and_write_baseline(tmp_path, capsys):
    (tmp_path / "gethsharding_tpu").mkdir()
    (tmp_path / "gethsharding_tpu/svc.py").write_text(textwrap.dedent("""
        import threading

        class S:
            def start(self):
                threading.Thread(target=print, daemon=True).start()
    """))
    (tmp_path / "README.md").write_text("nothing\n")
    baseline = tmp_path / "baseline.json"
    argv = ["--root", str(tmp_path), "--baseline", str(baseline)]
    assert cli_main(argv) == 1  # new finding -> gate fails
    assert cli_main(argv + ["--write-baseline"]) == 0
    data = json.loads(baseline.read_text())
    assert any("thread-lifecycle" in k for k in data["findings"])
    assert cli_main(argv) == 0  # accepted -> gate passes
    out = capsys.readouterr().out
    assert "0 new" in out


def test_cli_partial_write_baseline_preserves_other_rules(tmp_path):
    """Review regression: `--rule X --write-baseline` must not wipe the
    other rules' justified entries."""
    (tmp_path / "gethsharding_tpu").mkdir()
    (tmp_path / "gethsharding_tpu/svc.py").write_text(textwrap.dedent("""
        import threading

        class S:
            def start(self):
                threading.Thread(target=print, daemon=True).start()
    """))
    (tmp_path / "README.md").write_text("nothing\n")
    baseline = tmp_path / "baseline.json"
    Baseline({"flag-doc::gethsharding_tpu/other.py::undocumented-env:X":
              "justified elsewhere"}).save(baseline)
    argv = ["--root", str(tmp_path), "--baseline", str(baseline),
            "--rule", "thread-lifecycle", "--write-baseline"]
    assert cli_main(argv) == 0
    data = json.loads(baseline.read_text())["findings"]
    assert any(k.startswith("thread-lifecycle::") for k in data)
    assert "flag-doc::gethsharding_tpu/other.py::undocumented-env:X" in data


def test_cli_unknown_rule_is_usage_error(tmp_path):
    (tmp_path / "gethsharding_tpu").mkdir()
    assert cli_main(["--root", str(tmp_path), "--rule", "nope"]) == 2


# -- runtime lockcheck -------------------------------------------------------

@pytest.fixture
def lockcheck_env():
    from gethsharding_tpu.analysis import lockcheck

    if lockcheck.active():
        # GETHSHARDING_LOCKCHECK=1 session mode: the conftest recorder
        # owns the patch (with repo-only record paths); installing over
        # it is a no-op and uninstalling here would silently disable
        # the session gate for every later test file
        pytest.skip("lockcheck session mode active; wrapper tests need "
                    "an exclusive install")
    # record locks created from this test file too
    lockcheck.install(record_paths=("gethsharding_tpu", "tests"))
    try:
        yield lockcheck
    finally:
        lockcheck.uninstall()


def test_lockcheck_detects_deliberate_inversion(lockcheck_env):
    """The acceptance regression: inject A->B in one thread and B->A in
    another (sequentially, so no deadlock happens) — the checker must
    still report the inversion."""
    lock_a = threading.Lock()
    lock_b = threading.Lock()

    with lock_a:
        with lock_b:
            pass

    def reversed_order():
        with lock_b:
            with lock_a:
                pass

    t = threading.Thread(target=reversed_order)
    t.start()
    t.join()
    rep = lockcheck_env.report()
    assert len(rep["inversions"]) == 1
    inv = rep["inversions"][0]
    assert inv.first != inv.second
    assert set(inv.first) == set(inv.second)


def test_lockcheck_consistent_order_is_clean(lockcheck_env):
    lock_a = threading.Lock()
    lock_b = threading.Lock()
    for _ in range(3):
        with lock_a:
            with lock_b:
                pass
    rep = lockcheck_env.report()
    assert rep["inversions"] == []
    assert len(rep["edges"]) == 1


def test_lockcheck_rlock_reentry_records_nothing(lockcheck_env):
    lock = threading.RLock()
    with lock:
        with lock:
            pass
    assert lockcheck_env.report()["edges"] == {}


def test_lockcheck_condition_wait_releases_held_set(lockcheck_env):
    """A Condition.wait() must drop the underlying lock from the held
    set while parked — otherwise the waker's re-acquire order would be
    reported as an inversion."""
    lock = threading.Lock()
    cond = threading.Condition(lock)
    other = threading.Lock()
    woke = threading.Event()

    def waiter():
        with cond:
            cond.wait(timeout=5.0)
        woke.set()

    t = threading.Thread(target=waiter)
    t.start()
    # give the waiter time to park, then take the locks in an order
    # that would invert IF the parked lock were still considered held
    import time as _time
    _time.sleep(0.1)
    with other:
        with cond:
            cond.notify()
    t.join()
    assert woke.is_set()
    assert lockcheck_env.report()["inversions"] == []


def test_lockcheck_condition_over_rlock_releases_full_depth(lockcheck_env):
    """Review regression: a bare `threading.Condition()` (hidden RLock)
    waited on while the lock is held RECURSIVELY must release every
    level — the fallback single-release would leave the waiter parked
    holding the lock and deadlock the notifier."""
    cond = threading.Condition()  # hidden lock is a _TracedRLock
    woke = threading.Event()

    def waiter():
        with cond:
            with cond:  # recursion depth 2 across the wait
                cond.wait(timeout=5.0)
        woke.set()

    t = threading.Thread(target=waiter)
    t.start()
    import time as _time
    _time.sleep(0.1)
    with cond:  # deadlocks here if wait() released only one level
        cond.notify()
    t.join(timeout=5.0)
    assert woke.is_set()
    assert not t.is_alive()


def test_lockcheck_verify_against_static_flags_reversed_edge(lockcheck_env):
    """Static model says B->A; observing A->B must be a violation."""
    from gethsharding_tpu.analysis.locks import LockModel

    lock_a = threading.Lock()  # labeled tests/test_analysis.py:<line>
    lock_b = threading.Lock()
    with lock_a:
        with lock_b:
            pass
    rep = lockcheck_env.report()
    (label_a, label_b), = rep["edges"].keys()
    model = LockModel()
    model.nodes = {"A", "B"}
    model.edges = {("B", "A"): "static-site"}

    def site(label):
        rel, _, line = label.rpartition(":")
        return (rel, int(line))

    model.site_map = {site(label_a): "A", site(label_b): "B"}
    verdict = lockcheck_env.verify_against_static(model)
    assert not verdict.ok
    assert len(verdict.static_violations) == 1
    assert "disagree" in verdict.static_violations[0]


def test_lockcheck_real_subsystems_match_static_graph(lockcheck_env):
    """Drive real serving/resilience objects and cross-check: observed
    orders must be consistent with the static lock graph."""
    from gethsharding_tpu.resilience.breaker import CircuitBreaker
    from gethsharding_tpu.serving.queue import AdmissionQueue, Request

    q = AdmissionQueue(cap_rows=256)

    def producer():
        for _ in range(10):
            q.put(Request(op="ecrecover_addresses",
                          args=([b"x" * 32], [b"y" * 65]), rows=1))

    threads = [threading.Thread(target=producer) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    batch, _reason = q.take_batch()
    assert batch
    breaker = CircuitBreaker("lockcheck-test")
    breaker.record_fault(RuntimeError("x"))
    breaker.record_success()

    verdict = lockcheck_env.verify_against_static()
    assert verdict.inversions == []
    assert verdict.static_violations == []


def test_lockcheck_uninstall_restores_real_locks():
    from gethsharding_tpu.analysis import lockcheck

    if lockcheck.active():
        pytest.skip("lockcheck session mode active; install/uninstall "
                    "cycle would tear down the session recorder")
    real = threading.Lock
    lockcheck.install()
    assert threading.Lock is not real
    lockcheck.uninstall()
    assert threading.Lock is real
    assert not lockcheck.active()


def test_rule_registry_is_complete():
    # keep the README rule catalog and the registry in sync by count
    assert set(RULES) == {
        "jit-purity", "host-sync", "lock-order", "race-guard",
        "layering", "backend-contract", "thread-lifecycle", "flag-doc",
        "export-completeness",
    }
