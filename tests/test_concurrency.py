"""Concurrency stress tests (the `database_test.go:49 Test_DBConcurrent`
analog, extended per SURVEY.md §5.2): parallel DB access on both KV
engines, feed pub/sub under subscriber churn, the shard persistence
façade under concurrent writers, and the supervisor's heal racing a
live head loop. Each runs multiple threads against shared state and
asserts no exception, no lost update, and consistent final state."""

import threading
import time

import pytest

from gethsharding_tpu.db.kv import MemoryKV, SqliteKV

THREADS = 8
OPS = 120


def _run_threads(worker, n=THREADS, timeout=120):
    errors = []

    def wrap(i):
        try:
            worker(i)
        except Exception as exc:  # noqa: BLE001 - collected for assert
            errors.append((i, exc))

    threads = [threading.Thread(target=wrap, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    deadline = time.time() + timeout
    for t in threads:
        t.join(timeout=max(0.0, deadline - time.time()))
        assert not t.is_alive(), "worker deadlocked"
    assert not errors, errors


@pytest.mark.parametrize("engine", ["memory", "sqlite"])
def test_concurrent_kv_access(engine, tmp_path):
    """database_test.go:49 parity: N writers+readers on one store; every
    thread's writes are all present afterwards (no lost updates, no
    corruption, no crash)."""
    db = (MemoryKV() if engine == "memory"
          else SqliteKV(str(tmp_path / "kv.sqlite")))

    def worker(i):
        for j in range(OPS):
            key = b"k-%d-%d" % (i, j)
            db.put(key, b"v-%d-%d" % (i, j))
            assert db.get(key) == b"v-%d-%d" % (i, j)
            db.get(b"k-%d-%d" % ((i + 1) % THREADS, j))  # cross reads
            if j % 3 == 0:
                db.delete(key)
                db.put(key, b"v2-%d-%d" % (i, j))

    _run_threads(worker)
    for i in range(THREADS):
        for j in range(OPS):
            want = b"v2-%d-%d" % (i, j) if j % 3 == 0 else b"v-%d-%d" % (i, j)
            assert db.get(b"k-%d-%d" % (i, j)) == want
    db.close()


def test_concurrent_shard_saves_and_canonical(tmp_path):
    """The Shard persistence façade under concurrent writers: N threads
    save collations + set canonical for disjoint periods; every period's
    canonical header survives."""
    from gethsharding_tpu.core.shard import Shard
    from gethsharding_tpu.core.types import Collation, CollationHeader
    from gethsharding_tpu.crypto.keccak import keccak256
    from gethsharding_tpu.utils.hexbytes import Hash32

    shard = Shard(shard_id=3, shard_db=SqliteKV(str(tmp_path / "s.sqlite")))

    def worker(i):
        for j in range(20):
            period = i * 100 + j
            col = Collation(header=CollationHeader(shard_id=3, period=period),
                            body=b"body-%d-%d" % (i, j))
            col.calculate_chunk_root()
            shard.save_collation(col)
            shard.set_canonical(col.header)
            got = shard.canonical_collation(3, period)
            assert got.body == b"body-%d-%d" % (i, j)

    _run_threads(worker)
    for i in range(THREADS):
        for j in range(20):
            period = i * 100 + j
            col = shard.canonical_collation(3, period)
            assert col.body == b"body-%d-%d" % (i, j)


def test_feed_pubsub_under_subscriber_churn():
    """event.Feed parity under stress: concurrent senders while
    subscribers continuously join and leave. Stable subscribers receive
    every message exactly once, in order per sender."""
    from gethsharding_tpu.p2p.feed import Feed

    feed = Feed()
    n_senders, per_sender = 4, 150
    stable = [feed.subscribe(maxsize=n_senders * per_sender + 8)
              for _ in range(3)]
    stop_churn = threading.Event()

    def churner():
        while not stop_churn.is_set():
            sub = feed.subscribe(maxsize=16)
            time.sleep(0.001)
            sub.unsubscribe()

    churn_threads = [threading.Thread(target=churner) for _ in range(2)]
    for t in churn_threads:
        t.start()

    def sender(i):
        for j in range(per_sender):
            feed.send((i, j))

    _run_threads(sender, n=n_senders)
    stop_churn.set()
    for t in churn_threads:
        t.join(timeout=10)
        assert not t.is_alive()

    for sub in stable:
        seen = []
        while True:
            try:
                seen.append(sub.get(timeout=0.2))
            except Exception:
                break
        assert len(seen) == n_senders * per_sender
        # per-sender order preserved
        for i in range(n_senders):
            js = [j for (s, j) in seen if s == i]
            assert js == list(range(per_sender)), i


def test_supervisor_heal_races_live_head_loop():
    """Failure detection racing live traffic: heads keep arriving and
    driving the notary while the syncer crash-loops and the supervisor
    replaces it repeatedly. No deadlock, no cross-service damage: the
    notary keeps consuming heads afterwards and the node stops cleanly."""
    from gethsharding_tpu.actors.notary import Notary
    from gethsharding_tpu.actors.syncer import Syncer
    from gethsharding_tpu.node.backend import ShardNode
    from gethsharding_tpu.params import Config, ETHER
    from gethsharding_tpu.smc.chain import SimulatedMainchain

    config = Config(shard_count=4, quorum_size=1)
    chain = SimulatedMainchain(config=config)
    node = ShardNode(actor="notary", backend=chain, config=config,
                     txpool_interval=None, supervise=True,
                     supervise_interval=0.02)
    chain.fund(node.client.account(), 2000 * ETHER)
    node.start()
    try:
        node.client.register_notary()
        stop = threading.Event()

        def head_driver():
            while not stop.is_set():
                chain.commit()
                time.sleep(0.005)

        def crasher():
            # repeatedly crash the CURRENT syncer instance (the supervisor
            # keeps swapping fresh ones in underneath us)
            for _ in range(2 * ShardNode.MAX_RESTARTS):
                try:
                    node.service(Syncer).spawn(
                        lambda: (_ for _ in ()).throw(RuntimeError("x")),
                        name="crash-loop")
                except Exception:
                    pass
                time.sleep(0.03)

        driver = threading.Thread(target=head_driver)
        crash = threading.Thread(target=crasher)
        driver.start()
        crash.start()
        crash.join(timeout=20)
        assert not crash.is_alive()
        # let a few more heads land after the crash storm
        from gethsharding_tpu.mainchain.mirror import StateMirror

        notary = node.service(Notary)
        mirror = node.service(StateMirror)
        mark = mirror.refreshes
        deadline = time.time() + 5
        while time.time() < deadline and mirror.refreshes <= mark + 3:
            time.sleep(0.02)
        stop.set()
        driver.join(timeout=10)
        assert not driver.is_alive()
        assert node.restarts.get("syncer", 0) >= 1
        # head-driven services kept consuming heads through the churn
        assert mirror.refreshes > mark + 3
        assert not notary.crashed
        assert not mirror.crashed
    finally:
        node.stop()
    # clean shutdown: no lingering non-daemon service threads
    lingering = [t for t in threading.enumerate()
                 if t.name.startswith(("syncer", "notary")) and t.is_alive()]
    assert not lingering, lingering
