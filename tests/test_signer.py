"""External-signer (clef analog) tests: custody split over RPC, rules,
audit trail, and a full notary flow where the node process holds NO
private key material."""

import pytest

from gethsharding_tpu.crypto import bn256, secp256k1
from gethsharding_tpu.mainchain.keystore import Keystore
from gethsharding_tpu.params import Config, ETHER
from gethsharding_tpu.signer import RemoteSigner, SignerRefused, SignerServer
from gethsharding_tpu.utils.hexbytes import Address20


@pytest.fixture()
def signer_pair(tmp_path):
    keystore = Keystore(str(tmp_path))
    keystore.store(0xA11CE, "pw")
    server = SignerServer(str(tmp_path), "pw")
    server.start()
    remote = RemoteSigner.dial(*server.address)
    yield server, remote
    remote.close()
    server.stop()


def test_remote_sign_and_verify(signer_pair):
    server, remote = signer_pair
    (acct,) = remote.accounts()
    digest = b"\x37" * 32
    sig = remote.sign_hash(acct.address, digest)
    assert len(sig) == 65
    recovered = secp256k1.ecrecover_address(
        digest, secp256k1.Signature.from_bytes65(sig))
    assert bytes(recovered) == bytes(acct.address)

    # BLS: remote signature verifies against the remote-reported pubkey
    point = remote.bls_sign(acct.address, b"vote message")
    assert bn256.bls_verify(b"vote message", point, acct.bls_pubkey)
    pop = remote.bls_proof_of_possession(acct.address)
    assert pop is not None

    audit = remote.audit_log()
    assert [e["verdict"] for e in audit] == ["approved"] * 3
    assert audit[0]["method"] == "signer_signHash"


def test_rules_allowlist_and_hook(tmp_path):
    keystore = Keystore(str(tmp_path))
    keystore.store(0xB0B, "pw")
    keystore.store(0xCA401, "pw")
    addr_bob = secp256k1.priv_to_address(0xB0B)
    addr_carol = secp256k1.priv_to_address(0xCA401)

    refused_payloads = []

    def approve(method, address, payload):
        if payload == b"\xbb" * 32:
            refused_payloads.append((method, bytes(address)))
            return False
        return True

    server = SignerServer(str(tmp_path), "pw", allow=[addr_bob],
                          approve=approve)
    server.start()
    remote = RemoteSigner.dial(*server.address)
    try:
        assert len(remote.sign_hash(addr_bob, b"\x01" * 32)) == 65
        # not in allowlist
        with pytest.raises(SignerRefused, match="allowlist"):
            remote.sign_hash(addr_carol, b"\x01" * 32)
        # unknown account
        with pytest.raises(SignerRefused, match="unknown"):
            remote.sign_hash(Address20(b"\x99" * 20), b"\x01" * 32)
        # the approval hook refuses a specific payload
        with pytest.raises(SignerRefused, match="approval hook"):
            remote.sign_hash(addr_bob, b"\xbb" * 32)
        assert refused_payloads == [("signer_signHash", bytes(addr_bob))]
        verdicts = [e["verdict"] for e in remote.audit_log()]
        assert verdicts == ["approved", "rejected", "rejected", "rejected"]
    finally:
        remote.close()
        server.stop()


def test_new_account_goes_through_rules(tmp_path):
    """Account creation is gated like signing: refused under a pinned
    allowlist, reviewed by the approval hook, audited either way."""
    keystore = Keystore(str(tmp_path))
    keystore.store(0xB0B, "pw")
    addr_bob = secp256k1.priv_to_address(0xB0B)

    server = SignerServer(str(tmp_path), "pw", allow=[addr_bob])
    server.start()
    remote = RemoteSigner.dial(*server.address)
    try:
        with pytest.raises(SignerRefused, match="allowlist"):
            remote.new_account()
        assert remote.audit_log()[-1]["verdict"] == "rejected"
    finally:
        remote.close()
        server.stop()

    server = SignerServer(str(tmp_path), "pw",
                          approve=lambda m, a, p: m != "signer_newAccount")
    server.start()
    remote = RemoteSigner.dial(*server.address)
    try:
        with pytest.raises(SignerRefused, match="approval hook"):
            remote.new_account()
        assert len(Keystore(str(tmp_path)).accounts()) == 1  # no new file
    finally:
        remote.close()
        server.stop()


def test_node_runs_with_remote_custody(tmp_path):
    """SMCClient + notary registration with accounts=RemoteSigner: the
    whole protocol-side flow works without a priv key in-process."""
    from gethsharding_tpu.mainchain.client import SMCClient
    from gethsharding_tpu.smc.chain import SimulatedMainchain
    from gethsharding_tpu.smc.state_machine import vote_digest
    from gethsharding_tpu.utils.hexbytes import Hash32

    server = SignerServer(str(tmp_path), "pw")
    server.start()
    remote = RemoteSigner.dial(*server.address)
    try:
        acct = remote.new_account(seed=b"custody-notary")
        assert not hasattr(acct, "priv")  # nothing to leak
        chain = SimulatedMainchain(config=Config(quorum_size=1))
        client = SMCClient(backend=chain, accounts=remote, account=acct,
                           config=chain.config)
        client.start()
        chain.fund(acct.address, 2000 * ETHER)
        client.register_notary()
        entry = chain.notary_registry(acct.address)
        assert entry is not None and entry.deposited
        # PoP registered remotely verifies under the registered pubkey
        chain.fast_forward(1)
        # vote end-to-end when this notary samples itself somewhere
        period = chain.current_period()
        shard = next(
            (s for s in range(chain.shard_count())
             if chain.get_notary_in_committee(acct.address, s)
             == acct.address), None)
        assert shard is not None
        root = Hash32(b"\x55" * 32)
        chain.add_header(acct.address, shard, period, root)
        sig = remote.bls_sign(acct.address,
                              bytes(vote_digest(shard, period, root)))
        chain.submit_vote(acct.address, shard, period, entry.pool_index,
                          root, bls_sig=sig)
        assert chain.last_approved_collation(shard) == period
        # keystore file persisted on the signer side
        assert len(Keystore(str(tmp_path)).accounts()) == 1
    finally:
        remote.close()
        server.stop()
