"""Chain-process sync (smc/sync.py): follower replicates leader — the
eth/handler + downloader leg between chain nodes (SURVEY §1 topology),
at dev-chain scale: engine-verified header import + checkpoint state."""

import time

import pytest

from gethsharding_tpu.mainchain.accounts import AccountManager
from gethsharding_tpu.params import Config, ETHER
from gethsharding_tpu.rpc.server import RPCServer
from gethsharding_tpu.smc.chain import SimulatedMainchain
from gethsharding_tpu.smc.sync import ChainFollower
from gethsharding_tpu.utils.hexbytes import Hash32


def _pair(config=None):
    config = config or Config(shard_count=4, quorum_size=1)
    leader = SimulatedMainchain(config=config)
    server = RPCServer(leader, port=0)
    server.start()
    follower_chain = SimulatedMainchain(config=config)
    follower = ChainFollower(follower_chain, *server.address,
                             poll_interval=0.05)
    return leader, server, follower_chain, follower


def _wait_sync(leader, follower_chain, timeout=10.0, follower=None):
    """Heads equal AND (when the service is given) the leader's state
    checkpoint installed — header import and state install are two
    steps of one sync round, and a bare head match can be observed
    between them."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        heads_match = (follower_chain.block_number == leader.block_number
                       and bytes(follower_chain.blocks[-1].hash)
                       == bytes(leader.blocks[-1].hash))
        state_match = (follower is None
                       or follower._installed_seq == leader.state_seq())
        if heads_match and state_match:
            return True
        time.sleep(0.02)
    return False


def test_follower_replicates_chain_and_smc_state():
    leader, server, follower_chain, follower = _pair()
    manager = AccountManager()
    acct = manager.new_account(seed=b"sync-notary")
    try:
        follower.start()
        # leader does real SMC work: registration, header, vote
        leader.fund(acct.address, 2000 * ETHER)
        from gethsharding_tpu.smc.state_machine import vote_digest

        leader.register_notary(
            acct.address, bls_pubkey=acct.bls_pubkey,
            bls_pop=manager.bls_proof_of_possession(acct.address))
        leader.fast_forward(1)
        period = leader.current_period()
        root = Hash32(b"\x42" * 32)
        leader.add_header(acct.address, 2, period, root)
        leader.submit_vote(
            acct.address, 2, period, 0, root,
            bls_sig=manager.bls_sign(acct.address,
                                     bytes(vote_digest(2, period, root))))
        leader.commit()

        assert _wait_sync(leader, follower_chain, follower=follower)
        # block-level identity
        assert [bytes(b.hash) for b in follower_chain.blocks] == \
            [bytes(b.hash) for b in leader.blocks]
        # SMC state replicated: registry, record, votes, watermarks
        entry = follower_chain.notary_registry(acct.address)
        assert entry is not None and entry.deposited
        record = follower_chain.collation_record(2, period)
        assert record is not None
        assert bytes(record.chunk_root) == bytes(root)
        assert record.vote_count == leader.collation_record(2,
                                                            period).vote_count
        assert follower_chain.last_approved_collation(2) == \
            leader.last_approved_collation(2)
        assert follower_chain.balance_of(acct.address) == \
            leader.balance_of(acct.address)
    finally:
        follower.stop()
        server.stop()


def test_follower_tracks_leader_reorg():
    leader, server, follower_chain, follower = _pair()
    try:
        follower.start()
        for _ in range(6):
            leader.commit()
        assert _wait_sync(leader, follower_chain, follower=follower)

        # the leader rolls back and grows a DIFFERENT branch: dev blocks
        # hash only on (number, parent) so we must change the branch
        # point to fork — roll deeper then regrow longer
        leader.set_head(3)
        acct = AccountManager().new_account(seed=b"forker")
        leader.fund(acct.address, 1 * ETHER)  # state divergence marker
        for _ in range(5):
            leader.commit()
        assert _wait_sync(leader, follower_chain, follower=follower)
        assert follower.reorgs_followed >= 0  # reorg may resolve as
        # a pure extension if the follower saw set_head before regrow
        assert follower_chain.balance_of(acct.address) == 1 * ETHER
    finally:
        follower.stop()
        server.stop()


def test_follower_rejects_forged_seals_via_engine():
    """Imported headers pass through the consensus engine: a block whose
    seal the engine rejects never enters the follower."""
    from gethsharding_tpu.smc.chain import Block
    from gethsharding_tpu.smc.engine import DevPoWEngine

    config = Config(shard_count=2)
    leader = SimulatedMainchain(config=config, engine=DevPoWEngine())
    forged = Block(number=1, hash=Hash32(b"\x66" * 32),
                   parent_hash=leader.blocks[0].hash, extra=b"\x00" * 8)
    follower_chain = SimulatedMainchain(config=config,
                                        engine=DevPoWEngine())
    with pytest.raises(Exception):
        follower_chain.import_chain([forged, Block(
            number=2, hash=Hash32(b"\x67" * 32),
            parent_hash=Hash32(b"\x66" * 32), extra=b"\x00" * 8)])
    assert follower_chain.block_number == 0


def test_checkpoint_refuses_mismatched_head():
    config = Config(shard_count=2)
    leader = SimulatedMainchain(config=config)
    other = SimulatedMainchain(config=config)
    leader.commit()
    checkpoint = leader.state_checkpoint()
    # `other` is still at genesis: the checkpoint must be refused
    assert other.install_checkpoint(checkpoint) is False


def test_follower_over_real_chain_server_process():
    """Cross-process shape: a follower chain process (--follow) tracks a
    leader chain process; reads served by the follower match."""
    import json as _json
    import subprocess
    import sys

    from gethsharding_tpu.parallel.virtual import build_virtual_env
    from gethsharding_tpu.rpc.client import RPCClient

    env = build_virtual_env(1)
    leader_proc = subprocess.Popen(
        [sys.executable, "-m", "gethsharding_tpu.rpc.chain_server",
         "--shardcount", "2", "--runtime", "60"],
        stdout=subprocess.PIPE, text=True, env=env)
    follower_proc = None
    try:
        lead = _json.loads(leader_proc.stdout.readline())
        follower_proc = subprocess.Popen(
            [sys.executable, "-m", "gethsharding_tpu.rpc.chain_server",
             "--shardcount", "2", "--runtime", "60",
             "--follow", f"{lead['host']}:{lead['port']}"],
            stdout=subprocess.PIPE, text=True, env=env)
        fol = _json.loads(follower_proc.stdout.readline())
        leader_rpc = RPCClient(lead["host"], lead["port"])
        follower_rpc = RPCClient(fol["host"], fol["port"])
        for _ in range(4):
            leader_rpc.call("shard_commit")
        want = leader_rpc.call("shard_blockByNumber", 4)
        deadline = time.time() + 15
        got = None
        while time.time() < deadline:
            if follower_rpc.call("shard_blockNumber") >= 4:
                got = follower_rpc.call("shard_blockByNumber", 4)
                break
            time.sleep(0.1)
        assert got == want, "follower did not replicate the leader's block"
        leader_rpc.close()
        follower_rpc.close()
    finally:
        for proc in (leader_proc, follower_proc):
            if proc is not None:
                proc.terminate()
                proc.wait(timeout=10)
