"""ShardNode service container + CLI."""

import pytest

from gethsharding_tpu.actors import Notary, Observer, Proposer, Simulator, Syncer, TXPool
from gethsharding_tpu.db.shard_db import ShardDB
from gethsharding_tpu.mainchain.client import SMCClient
from gethsharding_tpu.node.backend import ShardNode
from gethsharding_tpu.node.cli import build_parser
from gethsharding_tpu.p2p.service import Hub, P2PServer
from gethsharding_tpu.params import Config, ETHER
from gethsharding_tpu.smc.chain import SimulatedMainchain


def test_registry_composition_per_actor():
    backend = SimulatedMainchain()
    proposer_node = ShardNode(actor="proposer", backend=backend,
                              txpool_interval=None)
    assert isinstance(proposer_node.service(Proposer), Proposer)
    assert isinstance(proposer_node.service(TXPool), TXPool)
    assert isinstance(proposer_node.service(Simulator), Simulator)
    with pytest.raises(KeyError):
        proposer_node.service(Notary)

    notary_node = ShardNode(actor="notary", backend=backend)
    assert isinstance(notary_node.service(Notary), Notary)
    with pytest.raises(KeyError):
        notary_node.service(Simulator)  # notaries don't run the simulator

    observer_node = ShardNode(actor="observer", backend=backend)
    assert isinstance(observer_node.service(Observer), Observer)
    assert isinstance(observer_node.service(Syncer), Syncer)


def test_unknown_actor_rejected():
    with pytest.raises(ValueError, match="unknown actor"):
        ShardNode(actor="validator")


def test_start_stop_lifecycle():
    backend = SimulatedMainchain()
    node = ShardNode(actor="observer", backend=backend,
                     simulator_interval=0.05)
    node.start()
    assert node.service(Syncer).running
    node.stop()
    assert not node.service(Syncer).running
    assert node.errors() == []


def test_nodes_share_hub_and_backend():
    config = Config(quorum_size=1)
    backend = SimulatedMainchain(config=config)
    hub = Hub()
    a = ShardNode(actor="proposer", shard_id=0, config=config,
                  backend=backend, hub=hub, txpool_interval=None)
    b = ShardNode(actor="notary", shard_id=0, config=config,
                  backend=backend, hub=hub)
    assert a.client.backend is b.client.backend
    assert a.p2p.hub is b.p2p.hub


def test_cli_parser_flags():
    parser = build_parser()
    args = parser.parse_args(
        ["sharding", "--actor", "notary", "--shardid", "7", "--deposit",
         "--runtime", "2"]
    )
    assert args.actor == "notary"
    assert args.shardid == 7
    assert args.deposit is True
    with pytest.raises(SystemExit):
        parser.parse_args(["sharding", "--actor", "miner"])


def test_supervisor_restarts_crashed_service_as_fresh_instance():
    """Failure detection + elastic recovery: a crashed actor loop is
    replaced by a FRESH instance (node/service.go:78-83 restart
    semantics), bounded by MAX_RESTARTS."""
    import time

    from gethsharding_tpu.actors.syncer import Syncer
    from gethsharding_tpu.smc.chain import SimulatedMainchain

    node = ShardNode(actor="observer", backend=SimulatedMainchain(),
                     txpool_interval=None, supervise=True,
                     supervise_interval=0.05)
    node.start()
    try:
        victim = node.service(Syncer)
        assert victim.running and not victim.crashed

        # simulate a loop crash: a spawned thread that raises
        victim.spawn(lambda: (_ for _ in ()).throw(RuntimeError("boom")),
                     name="crash-loop")
        deadline = time.time() + 5.0
        while time.time() < deadline:
            fresh = node.service(Syncer)
            if fresh is not victim:
                break
            time.sleep(0.02)
        fresh = node.service(Syncer)
        assert fresh is not victim, "supervisor must replace the instance"
        assert fresh.running and not fresh.crashed
        assert node.restarts["syncer"] == 1
        assert node.supervisor.restarts_performed >= 1
        # crash history carried forward for observability
        assert any("crashed" in e for e in fresh.errors)
    finally:
        node.stop()


def test_supervisor_gives_up_after_max_restarts():
    import time

    from gethsharding_tpu.actors.syncer import Syncer
    from gethsharding_tpu.smc.chain import SimulatedMainchain

    node = ShardNode(actor="observer", backend=SimulatedMainchain(),
                     txpool_interval=None, supervise=True,
                     supervise_interval=0.02)
    node.start()
    try:
        # every fresh instance crashes immediately: patch the factory
        real_factory = node._factories[Syncer]

        def crashing_factory():
            service = real_factory()
            orig = service.on_start

            def bad_start():
                orig()
                service.spawn(lambda: (_ for _ in ()).throw(
                    RuntimeError("systemic")), name="crash-loop")

            service.on_start = bad_start
            return service

        node._factories[Syncer] = crashing_factory
        node.service(Syncer).spawn(
            lambda: (_ for _ in ()).throw(RuntimeError("first")),
            name="crash-loop")
        deadline = time.time() + 6.0
        while time.time() < deadline:
            if node.restarts.get("syncer", 0) >= node.MAX_RESTARTS:
                break
            time.sleep(0.02)
        time.sleep(0.3)  # a few more supervisor passes
        assert node.restarts["syncer"] == node.MAX_RESTARTS  # capped
        # budget exhausted: the final crashed instance is left DOWN, not
        # half-alive (threads/subscriptions stopped)
        assert not node.service(Syncer).running
        # the give-up is STICKY: even after the restart timestamps age
        # out of RESTART_WINDOW, a systemically broken service stays down
        node._restart_times["syncer"] = []
        assert "syncer" not in node.heal()
        assert not node.service(Syncer).running
    finally:
        node.stop()


def test_consecutive_callback_failures_mark_crashed():
    """Head-driven actors have no loop threads; a run of consecutive
    callback failures marks them crashed for the supervisor."""
    from gethsharding_tpu.actors.base import Service

    class Flaky(Service):
        name = "flaky"
        supervisable = True

    service = Flaky()
    for _ in range(Service.FAILURE_THRESHOLD - 1):
        service.record_failure("boom")
    assert not service.crashed
    service.record_success()  # a success resets the run
    for _ in range(Service.FAILURE_THRESHOLD - 1):
        service.record_failure("boom")
    assert not service.crashed
    service.record_failure("boom")
    assert service.crashed


def test_state_mirror_tracks_and_resumes():
    """Downloader-analog: the mirror snapshots SMC state per head, serves
    local reads, persists to the shard DB, and a fresh instance over the
    same DB warm-starts from the snapshot before any head arrives."""
    from gethsharding_tpu.crypto.keccak import keccak256
    from gethsharding_tpu.db.kv import MemoryKV
    from gethsharding_tpu.mainchain.accounts import AccountManager
    from gethsharding_tpu.mainchain.client import SMCClient
    from gethsharding_tpu.mainchain.mirror import StateMirror
    from gethsharding_tpu.params import Config, ETHER
    from gethsharding_tpu.smc.chain import SimulatedMainchain
    from gethsharding_tpu.utils.hexbytes import Hash32

    config = Config(shard_count=4)
    chain = SimulatedMainchain(config=config)
    manager = AccountManager()
    acct = manager.new_account(seed=b"mirror")
    chain.fund(acct.address, 2000 * ETHER)
    client = SMCClient(backend=chain, accounts=manager, account=acct,
                       config=config)
    db = MemoryKV()
    mirror = StateMirror(client=client, shard_db=db)
    mirror.start()
    try:
        assert mirror.refreshes >= 1  # initial refresh at start
        chain.fast_forward(1)
        period = chain.current_period()
        root = Hash32(keccak256(b"mirror-root"))
        chain.add_header(acct.address, 2, period, root)
        chain.commit()  # head -> refresh
        snap = mirror.snapshot()
        assert snap["period"] == period
        assert snap["last_submitted"][2] == period
        assert mirror.record(2)["chunk_root"] == bytes(root).hex()
        assert mirror.record(2)["vote_count"] == 0
        assert mirror.record(0) is None
        assert snap["committee_context"] is not None
    finally:
        mirror.stop()

    # a new instance over the same DB resumes before any head
    cold = StateMirror(client=client, shard_db=db)
    assert cold.resumed_from_disk
    assert cold.record(2)["chunk_root"] == bytes(root).hex()
    assert cold.period() == period

    # without a DB: cold start, no resume
    assert not StateMirror(client=client).resumed_from_disk


def test_state_mirror_tolerates_none_block_number():
    """A backend surfacing block_number=None must not TypeError the
    regression guard; None compares as 0."""
    from gethsharding_tpu.mainchain.mirror import StateMirror

    class Stub:
        def __init__(self):
            self.calls = 0

        def mirror_snapshot(self):
            self.calls += 1
            return {"block_number": 5 if self.calls == 1 else None,
                    "period": 1, "records": {}, "last_submitted": {},
                    "committee_context": None}

    mirror = StateMirror(client=Stub())
    first = mirror.refresh()
    assert first["block_number"] == 5
    # a later None-numbered snapshot never regresses the held one
    assert mirror.refresh() is first


def test_node_runs_a_state_mirror():
    from gethsharding_tpu.mainchain.mirror import StateMirror
    from gethsharding_tpu.node.backend import ShardNode
    from gethsharding_tpu.smc.chain import SimulatedMainchain

    backend = SimulatedMainchain()
    node = ShardNode(actor="observer", backend=backend, txpool_interval=None)
    node.start()
    try:
        mirror = node.service(StateMirror)
        backend.commit()
        assert mirror.snapshot() is not None
        assert mirror.period() == backend.current_period()
    finally:
        node.stop()


def test_compile_cache_disable_is_sticky():
    """A multi-file run pins the compile cache OFF; later default
    enables (force_virtual_cpu_devices mid-suite) must not resurrect it
    — only an explicit force may (the single-module fast path)."""
    import jax

    from gethsharding_tpu.parallel import virtual

    before_sticky = virtual._cache_off_sticky
    before_dir = jax.config.jax_compilation_cache_dir
    try:
        virtual.configure_compile_cache(enabled=False)
        assert jax.config.jax_compilation_cache_dir is None
        virtual.configure_compile_cache()  # default enable: ignored
        assert jax.config.jax_compilation_cache_dir is None
        virtual.configure_compile_cache(force=True)  # explicit: wins
        assert jax.config.jax_compilation_cache_dir is not None
    finally:
        virtual._cache_off_sticky = before_sticky
        jax.config.update("jax_compilation_cache_dir", before_dir)
