"""ShardNode service container + CLI."""

import pytest

from gethsharding_tpu.actors import Notary, Observer, Proposer, Simulator, Syncer, TXPool
from gethsharding_tpu.db.shard_db import ShardDB
from gethsharding_tpu.mainchain.client import SMCClient
from gethsharding_tpu.node.backend import ShardNode
from gethsharding_tpu.node.cli import build_parser
from gethsharding_tpu.p2p.service import Hub, P2PServer
from gethsharding_tpu.params import Config, ETHER
from gethsharding_tpu.smc.chain import SimulatedMainchain


def test_registry_composition_per_actor():
    backend = SimulatedMainchain()
    proposer_node = ShardNode(actor="proposer", backend=backend,
                              txpool_interval=None)
    assert isinstance(proposer_node.service(Proposer), Proposer)
    assert isinstance(proposer_node.service(TXPool), TXPool)
    assert isinstance(proposer_node.service(Simulator), Simulator)
    with pytest.raises(KeyError):
        proposer_node.service(Notary)

    notary_node = ShardNode(actor="notary", backend=backend)
    assert isinstance(notary_node.service(Notary), Notary)
    with pytest.raises(KeyError):
        notary_node.service(Simulator)  # notaries don't run the simulator

    observer_node = ShardNode(actor="observer", backend=backend)
    assert isinstance(observer_node.service(Observer), Observer)
    assert isinstance(observer_node.service(Syncer), Syncer)


def test_unknown_actor_rejected():
    with pytest.raises(ValueError, match="unknown actor"):
        ShardNode(actor="validator")


def test_start_stop_lifecycle():
    backend = SimulatedMainchain()
    node = ShardNode(actor="observer", backend=backend,
                     simulator_interval=0.05)
    node.start()
    assert node.service(Syncer).running
    node.stop()
    assert not node.service(Syncer).running
    assert node.errors() == []


def test_nodes_share_hub_and_backend():
    config = Config(quorum_size=1)
    backend = SimulatedMainchain(config=config)
    hub = Hub()
    a = ShardNode(actor="proposer", shard_id=0, config=config,
                  backend=backend, hub=hub, txpool_interval=None)
    b = ShardNode(actor="notary", shard_id=0, config=config,
                  backend=backend, hub=hub)
    assert a.client.backend is b.client.backend
    assert a.p2p.hub is b.p2p.hub


def test_cli_parser_flags():
    parser = build_parser()
    args = parser.parse_args(
        ["sharding", "--actor", "notary", "--shardid", "7", "--deposit",
         "--runtime", "2"]
    )
    assert args.actor == "notary"
    assert args.shardid == 7
    assert args.deposit is True
    with pytest.raises(SystemExit):
        parser.parse_args(["sharding", "--actor", "miner"])
