"""Real-pool TxPool tests: validation, dedup, nonce runs, price ordering,
eviction, journal replay (core/tx_pool.go + core/tx_journal.go parity)."""

import pytest

from gethsharding_tpu.actors.txpool import TXPool, TxPoolError
from gethsharding_tpu.core.state_processor import sign_transaction
from gethsharding_tpu.core.types import Transaction
from gethsharding_tpu.crypto import secp256k1


def signed(priv, nonce, price=1, payload=b"x"):
    return sign_transaction(
        Transaction(nonce=nonce, gas_price=price, gas_limit=21000,
                    to=secp256k1.priv_to_address(0xBEEF), payload=payload),
        priv)


def make_pool(**kw):
    kw.setdefault("simulate_interval", None)
    return TXPool(**kw)


def test_dedup_and_replacement_pricing():
    pool = make_pool()
    tx = signed(0xA1, 0, price=5)
    pool.submit(tx)
    with pytest.raises(TxPoolError, match="already known"):
        pool.submit(tx)
    with pytest.raises(TxPoolError, match="underpriced"):
        pool.submit(signed(0xA1, 0, price=5, payload=b"y"))
    pool.submit(signed(0xA1, 0, price=9, payload=b"y"))  # replacement
    assert pool.known_count() == 1
    assert pool.pending()[0].gas_price == 9


def test_invalid_signature_rejected():
    # r = 0 is outside the valid signature range: recovery must fail
    # (a merely TAMPERED in-range sig recovers to a different sender —
    # sender-binding is the replay engine's nonce/balance checks' job)
    tx = signed(0xA2, 0)
    bad = Transaction(nonce=tx.nonce, gas_price=tx.gas_price,
                      gas_limit=tx.gas_limit, to=tx.to, value=tx.value,
                      payload=tx.payload, v=tx.v, r=0, s=tx.s)
    with pytest.raises(TxPoolError, match="invalid signature"):
        make_pool().submit(bad)


def test_pending_nonce_runs_and_queueing():
    pool = make_pool()
    for nonce in (0, 1, 3):  # gap at 2
        pool.submit(signed(0xA3, nonce))
    pending = pool.pending()
    assert [t.nonce for t in pending] == [0, 1]
    assert pool.queued_count() == 1
    pool.submit(signed(0xA3, 2))  # the gap closes
    assert [t.nonce for t in pool.pending()] == [0, 1, 2, 3]
    assert pool.queued_count() == 0


def test_pending_price_ordering_across_senders():
    pool = make_pool()
    pool.submit(signed(0xA4, 0, price=1))
    pool.submit(signed(0xA5, 0, price=50))
    pool.submit(signed(0xA5, 1, price=2))
    pool.submit(signed(0xA6, 0, price=10))
    prices = [t.gas_price for t in pool.pending()]
    assert prices == [50, 10, 2, 1] or prices == [50, 2, 10, 1]
    # nonce order within a sender is never violated
    a5 = [t.nonce for t in pool.pending()
          if t.gas_price in (50, 2)]
    assert a5 == sorted(a5)


def test_capacity_evicts_cheapest():
    pool = make_pool(capacity=3)
    pool.submit(signed(0xA7, 0, price=100))
    pool.submit(signed(0xA8, 0, price=50))
    pool.submit(signed(0xA9, 0, price=10))
    pool.submit(signed(0xAA, 0, price=70))  # evicts the price-10 tx
    assert pool.known_count() == 3
    assert all(t.gas_price != 10 for t in pool.pending())
    assert pool.m_dropped.value >= 1


def test_payload_cap():
    pool = make_pool(max_payload=8)
    with pytest.raises(TxPoolError, match="size cap"):
        pool.submit(Transaction(nonce=0, payload=b"x" * 9))


def test_journal_replay_survives_restart(tmp_path):
    journal = str(tmp_path / "journal.rlp")
    pool = make_pool(journal_path=journal)
    pool.start()
    for nonce in range(3):
        pool.submit(signed(0xAB, nonce, price=nonce + 1))
    pool.stop()

    # a torn tail (crash mid-write) must not break replay
    with open(journal, "ab") as fh:
        fh.write((1 << 20).to_bytes(4, "big") + b"torn")

    revived = make_pool(journal_path=journal)
    revived.start()
    try:
        assert revived.known_count() == 3
        assert [t.nonce for t in revived.pending()] == [0, 1, 2]
    finally:
        revived.stop()
