"""Light-client / ODR tests (the les + light role): proof-verified byte
sampling against SMC-anchored chunk roots over shardp2p, proven body
lengths via boundary absence proofs, forged proofs rejected."""

import pytest

from gethsharding_tpu.actors.light import LightClient
from gethsharding_tpu.actors.syncer import Syncer
from gethsharding_tpu.core.derive_sha import chunk_proof, chunk_root, verify_chunk
from gethsharding_tpu.core.shard import Shard
from gethsharding_tpu.core.types import Collation, CollationHeader
from gethsharding_tpu.db.kv import MemoryKV
from gethsharding_tpu.mainchain.client import SMCClient
from gethsharding_tpu.p2p.messages import ChunkProofRequest, ChunkProofResponse
from gethsharding_tpu.p2p.service import Hub, P2PServer
from gethsharding_tpu.params import Config, ETHER
from gethsharding_tpu.smc.chain import SimulatedMainchain
from gethsharding_tpu.utils.hexbytes import Hash32

BODY = bytes(range(256)) * 3 + b"tail-of-the-collation"


def test_chunk_proof_round_trip_and_absence():
    root = chunk_root(BODY)
    for index in (0, 1, 127, len(BODY) - 1):
        value = verify_chunk(root, index, chunk_proof(BODY, index))
        assert value == BODY[index], index
    # absence proof at the boundary pins the length
    assert verify_chunk(root, len(BODY), chunk_proof(BODY, len(BODY))) is None
    # a tampered proof raises, never returns a value
    proof = chunk_proof(BODY, 5)
    bad = [b"\x00" + proof[0][1:]] + proof[1:]
    with pytest.raises(ValueError):
        verify_chunk(root, 5, bad)


def _light_setup():
    """One full node (syncer holding a canonical body) + one light node
    on a shared in-process hub, both anchored on the same chain."""
    config = Config(shard_count=4, quorum_size=1)
    chain = SimulatedMainchain(config=config)
    hub = Hub()

    full_p2p = P2PServer(hub=hub)
    full_client = SMCClient(backend=chain, config=config)
    chain.fund(full_client.account(), 2000 * ETHER)
    shard = Shard(shard_id=2, shard_db=MemoryKV())
    collation = Collation(
        header=CollationHeader(shard_id=2, period=1), body=BODY)
    root = Hash32(collation.calculate_chunk_root())
    shard.save_collation(collation)
    syncer = Syncer(client=full_client, shard=shard, p2p=full_p2p,
                    poll_interval=0.01)

    chain.fast_forward(1)
    chain.add_header(full_client.account(), 2, 1, root)

    light_p2p = P2PServer(hub=hub)
    light = LightClient(client=SMCClient(backend=chain, config=config),
                        p2p=light_p2p)
    return chain, syncer, light, root


def test_light_client_samples_and_proves_length():
    chain, syncer, light, root = _light_setup()
    syncer.p2p.start()
    light.p2p.start()
    syncer.start()
    light.start()
    try:
        assert bytes(light.canonical_chunk_root(2, 1)) == bytes(root)
        got = light.sample(2, 1, [0, 7, 100, len(BODY) - 1], timeout=5.0)
        assert got == {0: BODY[0], 7: BODY[7], 100: BODY[100],
                       len(BODY) - 1: BODY[-1]}
        assert light.samples_verified >= 4
        assert syncer.proofs_served >= 4

        # the length is PROVEN, not trusted
        assert light.proven_length(2, 1, timeout=5.0) == len(BODY)

        # full availability sampling
        assert light.availability_check(2, 1, k=8, timeout=5.0) is True
        assert light.proofs_rejected == 0
    finally:
        light.stop()
        syncer.stop()
        light.p2p.stop()
        syncer.p2p.stop()


def test_light_client_rejects_forged_proofs():
    """A lying server cannot make the light client accept wrong bytes:
    proofs for a DIFFERENT body fail against the anchored root."""
    chain, syncer, light, root = _light_setup()
    fake = b"forged body that the SMC never anchored"

    class LyingServer:
        def __init__(self, p2p):
            self.p2p = p2p
            self.sub = p2p.subscribe(ChunkProofRequest)

        def answer(self):
            msg = self.sub.get(timeout=5.0)
            request = msg.data
            self.p2p.send(ChunkProofResponse(
                chunk_root=request.chunk_root, index=request.index,
                proof=tuple(chunk_proof(fake, request.index)),
                body_len=len(fake)), msg.peer)

    liar_p2p = P2PServer(hub=light.p2p.hub)
    liar_p2p.start()
    light.p2p.start()
    liar = LyingServer(liar_p2p)
    light.start()
    try:
        import threading

        answering = threading.Thread(target=liar.answer, daemon=True)
        answering.start()
        got = light.sample(2, 1, [3], timeout=2.0)
        answering.join(timeout=5.0)
        assert got == {}  # nothing verified
        assert light.proofs_rejected >= 1
        assert light.availability_check(2, 1, k=4, timeout=1.0) is False
    finally:
        light.stop()
        light.p2p.stop()
        liar_p2p.stop()


def test_light_client_empty_body_is_trivially_available():
    config = Config(shard_count=4, quorum_size=1)
    chain = SimulatedMainchain(config=config)
    client = SMCClient(backend=chain, config=config)
    chain.fund(client.account(), 2000 * ETHER)
    chain.fast_forward(1)
    empty_root = Hash32(chunk_root(b""))
    chain.add_header(client.account(), 1, 1, empty_root)
    light = LightClient(client=client, p2p=P2PServer(hub=Hub()))
    light.start()
    try:
        assert light.proven_length(1, 1) == 0
        assert light.availability_check(1, 1) is True
    finally:
        light.stop()


def test_light_node_end_to_end_over_node_containers():
    """`--actor light` as a ShardNode: a full observer node (syncer owns
    the body) and a LIGHT node sharing a hub; the light node verifies
    availability of the canonical collation without holding any shard
    data."""
    from gethsharding_tpu.node.backend import ShardNode

    config = Config(shard_count=4, quorum_size=1)
    chain = SimulatedMainchain(config=config)
    hub = Hub()
    full = ShardNode(actor="observer", shard_id=2, config=config,
                     backend=chain, hub=hub, txpool_interval=None)
    light_node = ShardNode(actor="light", shard_id=2, config=config,
                           backend=chain, hub=hub, txpool_interval=None)
    full.start()
    light_node.start()
    try:
        body = b"node-level light client drive " * 9
        collation = Collation(
            header=CollationHeader(shard_id=2, period=1), body=body)
        root = Hash32(collation.calculate_chunk_root())
        full.shard.save_collation(collation)
        chain.fast_forward(1)
        chain.add_header(full.client.account(), 2, 1, root)

        light = light_node.service(LightClient)
        assert light.proven_length(2, 1, timeout=5.0) == len(body)
        assert light.availability_check(2, 1, k=6, timeout=5.0) is True
        got = light.sample(2, 1, [11], timeout=5.0)
        assert got == {11: body[11]}
    finally:
        light_node.stop()
        full.stop()


def test_proof_serving_capped_for_untrusted_large_bodies():
    """DoS guard: proofs are refused above Syncer.PROOF_BODY_CAP (the
    full-body path still serves such collations)."""
    chain, syncer, light, root = _light_setup()
    syncer.p2p.start()
    light.p2p.start()
    syncer.start()
    light.start()
    try:
        syncer.PROOF_BODY_CAP = len(BODY) - 1  # force the refusal path
        got = light.sample(2, 1, [0], timeout=0.5)
        assert got == {}
        assert syncer.proofs_served == 0
    finally:
        light.stop()
        syncer.stop()
        light.p2p.stop()
        syncer.p2p.stop()
