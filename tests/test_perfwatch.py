"""perfwatch: trustworthy timing, the benchmark ledger + regression
gate, and the black-box flight recorder.

The ISSUE-13 acceptance coverage:

- the regression detector passes 20 seeded-noise clean runs and flags
  an injected 1.3x slowdown (and recovers on the next clean run);
- the device-timer self-check detects a simulated no-op
  ``block_until_ready`` (the r4 tunnel-plugin hazard), increments
  ``perfwatch/timer_suspect`` and invalidates the enclosing record;
- a chaos-injected dispatch hang under the serving watchdog produces a
  COMPLETE flight-recorder bundle (event ring + span ring + metrics
  snapshot + wire ring + ledger tail);
- the resilience seams (breaker trip, soundness violation) feed the
  recorder; the single ledger writer normalizes every bench emission;
  the historical import is idempotent; /status's perf section renders.
"""

import json
import os
import random
import time

import numpy as np
import pytest

from gethsharding_tpu import metrics, perfwatch
from gethsharding_tpu.perfwatch import gate as pgate
from gethsharding_tpu.perfwatch import registry as pregistry
from gethsharding_tpu.perfwatch.ledger import Ledger, record_bench
from gethsharding_tpu.perfwatch.recorder import RECORDER, FlightRecorder
from gethsharding_tpu.perfwatch.timer import (DeviceTimer, checked_pull,
                                              ensure_host)


# == ledger ================================================================


def test_ledger_append_and_read_roundtrip(tmp_path):
    led = Ledger(str(tmp_path / "ledger.jsonl"))
    rec = led.append({"workload": "w", "metrics": {"wall_s": 0.5}})
    assert rec["schema"] == 1 and rec["valid"] is True
    assert rec["ts"] and rec["env"].get("python")
    got = led.records()
    assert len(got) == 1 and got[0]["workload"] == "w"
    assert got[0]["metrics"]["wall_s"] == 0.5


def test_ledger_rejects_malformed_records(tmp_path):
    led = Ledger(str(tmp_path / "ledger.jsonl"))
    with pytest.raises(ValueError):
        led.append({"metrics": {"wall_s": 1.0}})  # no workload
    with pytest.raises(ValueError):
        led.append({"workload": "w", "metrics": {}})  # empty metrics
    with pytest.raises(ValueError):
        led.append({"workload": "w", "metrics": {"x": "fast"}})  # non-num


def test_ledger_skips_corrupt_lines(tmp_path):
    path = tmp_path / "ledger.jsonl"
    led = Ledger(str(path))
    led.append({"workload": "w", "metrics": {"v_s": 1.0}})
    with open(path, "a") as fh:
        fh.write("{truncated-mid-append\n")
    led.append({"workload": "w", "metrics": {"v_s": 2.0}})
    assert [r["metrics"]["v_s"] for r in led.records()] == [1.0, 2.0]


def test_ledger_last_is_tail_read(tmp_path):
    """last() parses only the file tail (the /status scrape path) and
    agrees with records()[-1], skipping a torn trailing line."""
    path = tmp_path / "ledger.jsonl"
    led = Ledger(str(path))
    assert led.last() is None  # no file yet
    for i in range(5):
        led.append({"workload": f"w{i}", "metrics": {"v_s": float(i)}})
    assert led.last()["workload"] == "w4"
    with open(path, "a") as fh:
        fh.write('{"torn')  # interrupted append must not break /status
    assert led.last()["workload"] == "w4"


def test_record_bench_one_writer_schema(tmp_path):
    led = Ledger(str(tmp_path / "ledger.jsonl"))
    rec = record_bench(
        metric="das_sampled_bytes_per_collation", value=69760,
        unit="bytes", vs_baseline=0.266,
        extra={"platform": "cpu", "k_samples": 16, "bytes_ratio": 0.266,
               "verify_backend": "jax", "knobs": {"K": "V"}},
        ledger=led)
    assert rec["workload"] == "das_sampled_bytes_per_collation"
    assert rec["platform"] == "cpu"
    assert rec["metrics"]["value"] == 69760.0
    assert rec["metrics"]["bytes_ratio"] == 0.266  # numeric extra -> metric
    assert rec["extra"]["verify_backend"] == "jax"  # string stays extra
    assert rec["knobs"] == {"K": "V"}
    assert rec["shape"]["k_samples"] == 16


def test_record_bench_suspect_invalidates(tmp_path):
    led = Ledger(str(tmp_path / "ledger.jsonl"))
    rec = record_bench(metric="m", value=1.0, suspects=2, ledger=led)
    assert rec["valid"] is False and rec["suspects"] == 2


# == regression gate =======================================================


def _seeded_history(led, n, base=0.1, noise=0.03, seed=0,
                    workload="micro/demo"):
    rng = random.Random(seed)
    for _ in range(n):
        wall = base * (1.0 + rng.uniform(-noise, noise))
        led.append({"workload": workload, "backend": "host",
                    "platform": "host", "source": "micro",
                    "metrics": {"wall_s": round(wall, 9),
                                "rows_per_s": round(8 / wall, 6)}})


def test_gate_20_clean_seeded_runs_pass(tmp_path):
    """The ISSUE acceptance: 20 consecutive clean checks over seeded
    +/-3% noise must all pass."""
    led = Ledger(str(tmp_path / "ledger.jsonl"))
    _seeded_history(led, 10)  # baseline build-up
    rng = random.Random(99)
    for i in range(20):
        wall = 0.1 * (1.0 + rng.uniform(-0.03, 0.03))
        led.append({"workload": "micro/demo", "backend": "host",
                    "platform": "host", "source": "micro",
                    "metrics": {"wall_s": round(wall, 9),
                                "rows_per_s": round(8 / wall, 6)}})
        result = pgate.check(led)
        assert not result.failed, (i, [vars(v) for v in
                                       result.regressions])


def test_gate_flags_injected_13x_slowdown(tmp_path):
    led = Ledger(str(tmp_path / "ledger.jsonl"))
    _seeded_history(led, 10)
    led.append({"workload": "micro/demo", "backend": "host",
                "platform": "host", "source": "micro",
                "metrics": {"wall_s": 0.1 * 1.3,
                            "rows_per_s": 8 / (0.1 * 1.3)}})
    result = pgate.check(led)
    assert result.failed
    flagged = {(v.workload, v.metric) for v in result.regressions}
    assert ("micro/demo", "wall_s") in flagged
    # direction is honored: the rate metric regressed DOWNWARD
    assert ("micro/demo", "rows_per_s") in flagged
    # ... and the next clean run heals (the outlier cannot drag the
    # rolling median)
    _seeded_history(led, 1, seed=7)
    assert not pgate.check(led).failed


def test_gate_improvement_and_building_statuses(tmp_path):
    led = Ledger(str(tmp_path / "ledger.jsonl"))
    _seeded_history(led, 2)
    building = pgate.check(led)
    assert not building.failed
    assert all(v.status == "baseline_building" for v in building.verdicts)
    _seeded_history(led, 8)
    led.append({"workload": "micro/demo", "backend": "host",
                "platform": "host", "source": "micro",
                "metrics": {"wall_s": 0.05, "rows_per_s": 160.0}})
    result = pgate.check(led)
    assert not result.failed
    assert {v.status for v in result.verdicts} == {"improvement"}


def test_gate_excludes_injected_drills_from_baselines(tmp_path):
    """Labeled injection drills never join a baseline — repeated CI
    drills must not MAD-inflate the band until real regressions hide
    under the cap."""
    led = Ledger(str(tmp_path / "ledger.jsonl"))
    _seeded_history(led, 8)
    for _ in range(4):  # four drills against the same ledger
        led.append({"workload": "micro/demo", "backend": "host",
                    "platform": "host", "source": "micro",
                    "extra": {"injected": 1.5},
                    "metrics": {"wall_s": 0.15, "rows_per_s": 8 / 0.15}})
    # a real 22% regression must STILL trip (band stays at the floor,
    # not widened by the drills' scatter)
    led.append({"workload": "micro/demo", "backend": "host",
                "platform": "host", "source": "micro",
                "metrics": {"wall_s": 0.122, "rows_per_s": 8 / 0.122}})
    result = pgate.check(led)
    assert result.failed, [vars(v) for v in result.verdicts]


def test_gate_excludes_invalid_records(tmp_path):
    """A suspect (invalid) record neither fails the gate nor joins the
    baseline."""
    led = Ledger(str(tmp_path / "ledger.jsonl"))
    _seeded_history(led, 8)
    led.append({"workload": "micro/demo", "backend": "host",
                "platform": "host", "valid": False, "source": "micro",
                "metrics": {"wall_s": 50.0, "rows_per_s": 0.1}})
    assert not pgate.check(led).failed


def test_gate_groups_by_platform(tmp_path):
    """A CPU run is never judged against TPU history."""
    led = Ledger(str(tmp_path / "ledger.jsonl"))
    for _ in range(6):
        led.append({"workload": "w", "backend": "jax", "platform": "tpu",
                    "metrics": {"dispatch_s": 0.3}})
    led.append({"workload": "w", "backend": "jax", "platform": "cpu",
                "metrics": {"dispatch_s": 30.0}})  # 100x "slower": new group
    result = pgate.check(led)
    assert not result.failed


def test_gate_checks_the_headline_value_metric(tmp_path):
    """The bench record's primary number lands under metrics['value'];
    its direction comes from the WORKLOAD name — a 2x sig-rate drop
    must trip the gate, not pass as 'informational'."""
    led = Ledger(str(tmp_path / "ledger.jsonl"))
    for _ in range(6):
        led.append({"workload": "notary_sig_verifications_per_sec",
                    "backend": "jax", "platform": "tpu",
                    "metrics": {"value": 45000.0}})
    led.append({"workload": "notary_sig_verifications_per_sec",
                "backend": "jax", "platform": "tpu",
                "metrics": {"value": 20000.0}})
    result = pgate.check(led)
    assert result.failed
    assert any(v.metric == "value" for v in result.regressions)
    # ... and byte workloads gate upward (wire growth is a regression)
    led2 = Ledger(str(tmp_path / "ledger2.jsonl"))
    for _ in range(6):
        led2.append({"workload": "das_sampled_bytes_per_collation",
                     "backend": "jax", "platform": "cpu",
                     "metrics": {"value": 69760.0}})
    led2.append({"workload": "das_sampled_bytes_per_collation",
                 "backend": "jax", "platform": "cpu",
                 "metrics": {"value": 262144.0}})
    assert pgate.check(led2).failed


def test_gate_direction_inference():
    assert pgate.direction_for("dispatch_s") == "lower"
    assert pgate.direction_for("wire_bytes") == "lower"
    assert pgate.direction_for("overhead_pct") == "lower"
    assert pgate.direction_for("sig_rate") == "higher"
    assert pgate.direction_for("rows_per_s") == "higher"
    assert pgate.direction_for("chaos_availability") == "higher"
    assert pgate.direction_for("verify_speedup") == "higher"
    assert pgate.direction_for("watchdog_deadline_s") is None  # a knob
    assert pgate.direction_for("k_periods") is None  # no direction
    # workload-name forms of the headline metrics
    assert pgate.direction_for(
        "notary_sig_verifications_per_sec") == "higher"
    assert pgate.direction_for(
        "das_sampled_bytes_per_collation") == "lower"
    assert pgate.direction_for(
        "audit_warm_wire_bytes_per_dispatch") == "lower"
    # cache-HIT bytes: more saved is better — never gated lower
    assert pgate.direction_for("pk_hit_bytes_warm") is None


def test_gate_report_renders_tables(tmp_path):
    led = Ledger(str(tmp_path / "ledger.jsonl"))
    led.append({"workload": "notary_sig_verifications_per_sec",
                "platform": "tpu", "backend": "jax",
                "metrics": {"value": 45487.7, "dispatch_s": 0.2968}})
    result = pgate.check(led)
    text = pgate.report(led, result=result)
    assert "45487.7" in text and "measured history" in text
    assert "| workload |" in text


# == microbench registry ===================================================


def test_micro_suite_runs_and_records(tmp_path):
    led = Ledger(str(tmp_path / "ledger.jsonl"))
    records = pregistry.run_suite(ledger=led, quick=True, inject={},
                                  names=["bucket_policy_10k",
                                         "keccak_256x64"])
    assert len(records) == 2
    for rec in records:
        assert rec["workload"].startswith("micro/")
        assert rec["metrics"]["wall_s"] > 0
        assert rec["source"] == "micro" and rec["valid"] is True


def test_micro_injection_scales_and_labels(tmp_path):
    led = Ledger(str(tmp_path / "ledger.jsonl"))
    clean = pregistry.run(
        "bucket_policy_10k", ledger=led, inject={})["metrics"]["wall_s"]
    injected = pregistry.run("bucket_policy_10k", ledger=led,
                             inject={"bucket_policy_10k": 3.0})
    assert injected["extra"]["injected"] == 3.0
    assert injected["metrics"]["wall_s"] > clean * 1.5  # honestly scaled
    # rates scale the OPPOSITE way (a slowdown must never record as a
    # rate improvement — "_per_s" also ends with "_s")
    assert injected["metrics"]["calls_per_s"] < (10_000 / clean) / 1.5
    assert pregistry.parse_inject("a:1.3,b:2") == {"a": 1.3, "b": 2.0}
    with pytest.raises(ValueError):
        pregistry.parse_inject("garbage")


# == DeviceTimer self-check ================================================


class _NoopBlockValue:
    """block_until_ready no-ops; the real pull pays the latency — the
    simulated r4 tunnel-plugin hazard (a hidden sub-second DISPATCH,
    above the 0.25 s suspect floor; a mere link-RTT pull stays below
    it on purpose)."""

    def __init__(self, pull_s=0.3):
        self.pull_s = pull_s

    def block_until_ready(self):
        return self

    def __array__(self, dtype=None, copy=None):
        time.sleep(self.pull_s)
        return np.zeros(4, dtype=dtype or np.int32)


class _HonestBlockValue:
    """block waits for the 'device'; the pull is then instant."""

    def block_until_ready(self):
        time.sleep(0.08)
        return self

    def __array__(self, dtype=None, copy=None):
        return np.zeros(4, dtype=dtype or np.int32)


def test_timer_detects_noop_block():
    before = perfwatch.suspect_count()
    dt = DeviceTimer("test_op")
    dt.dispatched()
    arr = dt.pull(_NoopBlockValue())
    dt.done()
    assert arr.shape == (4,)
    assert dt.suspect is True
    assert perfwatch.suspect_count() == before + 1
    # the event landed in the flight-recorder ring
    kinds = [e for e in RECORDER.events() if e["kind"] == "timer_suspect"
             and e["detail"].get("op") == "test_op"]
    assert kinds, "timer_suspect event missing from the recorder ring"


def test_timer_trusts_honest_block():
    before = perfwatch.suspect_count()
    dt = DeviceTimer("test_op_honest")
    dt.dispatched()
    dt.pull(_HonestBlockValue())
    dt.done()
    assert dt.suspect is False
    assert perfwatch.suspect_count() == before
    assert dt.device_s >= 0.08  # the block time counts as device time


def test_timer_fast_pull_never_suspect():
    """Sub-floor pulls (healthy fast dispatches, overlapped audits
    where the device finished early) are never suspect."""
    before = perfwatch.suspect_count()
    dt = DeviceTimer("test_op_fast")
    dt.dispatched()
    dt.pull(np.arange(8))
    dt.done()
    assert dt.suspect is False
    assert perfwatch.suspect_count() == before


def test_timer_rtt_scale_pull_not_suspect():
    """An overlapped audit over a high-RTT tunnel: the device finished
    before the pull, so the block is near-instant and the pull pays
    one link round trip (~0.08 s) — an HONEST reading below the 0.25 s
    floor, never flagged (only a block hiding a whole sub-second
    dispatch is the hazard)."""
    before = perfwatch.suspect_count()
    dt = DeviceTimer("test_op_rtt")
    dt.dispatched()
    dt.pull(_NoopBlockValue(pull_s=0.08))
    dt.done()
    assert dt.suspect is False
    assert perfwatch.suspect_count() == before


def test_timer_feeds_sig_rollups():
    t_m = metrics.timer("sig/marshal_time")
    t_d = metrics.timer("sig/device_time")
    before_m, before_d = t_m.count, t_d.count
    dt = DeviceTimer("rollup_probe")
    dt.dispatched()
    dt.pull(np.arange(4))
    dt.done()
    assert t_m.count == before_m + 1
    assert t_d.count == before_d + 1


def test_checked_pull_and_ensure_host():
    assert checked_pull(np.arange(3)).tolist() == [0, 1, 2]
    assert ensure_host([1, 2]) == [1, 2]  # host containers untouched
    assert ensure_host(None) is None
    out = ensure_host(_NoopBlockValue(pull_s=0.0), op="eh")
    assert isinstance(out, np.ndarray)


def test_jax_dispatch_goes_through_device_timer():
    """The adopted sigbackend path: a real (CPU) jax ecrecover dispatch
    must observe the rollup timers via DeviceTimer."""
    from gethsharding_tpu.crypto import secp256k1 as ecdsa
    from gethsharding_tpu.crypto.keccak import keccak256
    from gethsharding_tpu.sigbackend import get_backend

    t_d = metrics.timer("sig/device_time")
    before = t_d.count
    priv = int.from_bytes(keccak256(b"pw-jax"), "big") % ecdsa.N
    digest = keccak256(b"pw-jax-msg")
    backend = get_backend("jax")
    got = backend.ecrecover_addresses(
        [digest], [ecdsa.sign(digest, priv).to_bytes65()])
    assert got == [ecdsa.priv_to_address(priv)]
    assert t_d.count > before


# == flight recorder =======================================================


def test_recorder_ring_bounded_and_ordered():
    rec = FlightRecorder(ring=4)
    for i in range(10):
        rec.record("k", i=i)
    events = rec.events()
    assert len(events) == 4
    assert [e["detail"]["i"] for e in events] == [6, 7, 8, 9]


def test_recorder_wire_ring():
    rec = FlightRecorder(ring=8, wire_ring=2)
    rec.record_wire("op", {"wire_bytes": 1})
    rec.record_wire("op", {"wire_bytes": 2})
    rec.record_wire("op", {"wire_bytes": 3})
    assert [w["wire_bytes"] for w in rec.wires()] == [2, 3]
    rec.record_wire("op", None)  # empty ledgers are dropped, not stored
    assert len(rec.wires()) == 2


def test_recorder_dump_bundle_complete(tmp_path, monkeypatch):
    monkeypatch.setenv("GETHSHARDING_PERFWATCH_DIR", str(tmp_path))
    monkeypatch.setenv("GETHSHARDING_PERFWATCH_DUMP_S", "0")
    rec = FlightRecorder(ring=8)
    rec.record("something", x=1)
    rec.record_wire("op", {"wire_bytes": 7})
    path = rec.dump("unit_test")
    assert path is not None
    files = sorted(os.listdir(path))
    assert files == ["events.json", "ledger_tail.jsonl", "manifest.json",
                     "metrics.json", "spans.json", "wire.json"]
    manifest = json.load(open(os.path.join(path, "manifest.json")))
    assert manifest["reason"] == "unit_test"
    events = json.load(open(os.path.join(path, "events.json")))
    assert events and events[-1]["kind"] == "something"
    wires = json.load(open(os.path.join(path, "wire.json")))
    assert wires[0]["wire_bytes"] == 7


def test_recorder_rate_limit_and_prune(tmp_path, monkeypatch):
    monkeypatch.setenv("GETHSHARDING_PERFWATCH_DIR", str(tmp_path))
    monkeypatch.setenv("GETHSHARDING_PERFWATCH_DUMP_S", "3600")
    monkeypatch.setenv("GETHSHARDING_PERFWATCH_BUNDLES", "2")
    rec = FlightRecorder(ring=8)
    assert rec.dump("first") is not None
    assert rec.dump("suppressed") is None  # inside the min interval
    assert rec.dump("forced", force=True) is not None
    assert rec.dump("forced2", force=True) is not None
    assert len(os.listdir(tmp_path)) == 2  # pruned to the newest 2


def test_recorder_disabled_is_noop(tmp_path, monkeypatch):
    monkeypatch.setenv("GETHSHARDING_PERFWATCH_RECORDER", "0")
    monkeypatch.setenv("GETHSHARDING_PERFWATCH_DIR", str(tmp_path))
    rec = FlightRecorder(ring=8)
    rec.record("k")
    rec.trigger("k", dump=True)
    rec.flush()
    assert rec.events() == []
    assert os.listdir(tmp_path) == []


# == the resilience seams feed the recorder ================================


def test_breaker_trip_records_and_dumps(tmp_path, monkeypatch):
    from gethsharding_tpu.metrics import Registry
    from gethsharding_tpu.resilience.breaker import (OPEN, CircuitBreaker)

    monkeypatch.setenv("GETHSHARDING_PERFWATCH_DIR", str(tmp_path))
    monkeypatch.setenv("GETHSHARDING_PERFWATCH_DUMP_S", "0")
    breaker = CircuitBreaker(name="pw-test", fault_threshold=1,
                             reset_s=60.0, registry=Registry())
    breaker.record_fault(RuntimeError("boom"))
    assert breaker.state == OPEN
    trips = [e for e in RECORDER.events()
             if e["kind"] == "breaker_trip"
             and e["detail"].get("breaker") == "pw-test"]
    assert trips, "breaker trip missing from the recorder ring"
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and not os.listdir(tmp_path):
        RECORDER.flush()
        time.sleep(0.02)
    assert os.listdir(tmp_path), "breaker trip produced no bundle"


def test_soundness_violation_records_event(monkeypatch, tmp_path):
    from gethsharding_tpu.metrics import Registry
    from gethsharding_tpu.resilience.errors import SoundnessViolation
    from gethsharding_tpu.resilience.soundness import SpotCheckSigBackend
    from gethsharding_tpu.sigbackend import PythonSigBackend

    monkeypatch.setenv("GETHSHARDING_PERFWATCH_DIR", str(tmp_path))

    class _Corrupt(PythonSigBackend):
        name = "corrupt"

        def ecrecover_addresses(self, digests, sigs65):
            out = super().ecrecover_addresses(digests, sigs65)
            return [None] * len(out)  # silently wrong

    spot = SpotCheckSigBackend(_Corrupt(), rate=1.0, registry=Registry())
    from gethsharding_tpu.crypto import secp256k1 as ecdsa
    from gethsharding_tpu.crypto.keccak import keccak256

    priv = int.from_bytes(keccak256(b"pw-sound"), "big") % ecdsa.N
    digest = keccak256(b"pw-sound-msg")
    with pytest.raises(SoundnessViolation):
        spot.ecrecover_addresses([digest],
                                 [ecdsa.sign(digest, priv).to_bytes65()])
    events = [e for e in RECORDER.events()
              if e["kind"] == "soundness_violation"]
    assert events and events[-1]["detail"]["op"] == "ecrecover_addresses"
    RECORDER.flush()


def test_chaos_hang_watchdog_bundle_complete(tmp_path, monkeypatch):
    """THE ISSUE acceptance: a chaos-injected dispatch hang must leave
    a complete black-box bundle (events + spans + metrics + wire +
    ledger tail), with the watchdog_timeout and chaos_decision events
    in the ring."""
    from gethsharding_tpu.resilience.chaos import (ChaosSchedule,
                                                   ChaosSigBackend)
    from gethsharding_tpu.resilience.errors import DeadlineExceeded
    from gethsharding_tpu.serving import ServingConfig, ServingSigBackend
    from gethsharding_tpu.sigbackend import PythonSigBackend

    monkeypatch.setenv("GETHSHARDING_PERFWATCH_DIR", str(tmp_path))
    monkeypatch.setenv("GETHSHARDING_PERFWATCH_DUMP_S", "0")
    schedule = ChaosSchedule(seed=7,
                             rules={"dispatch.ecrecover_addresses": 1})
    serving = ServingSigBackend(
        ChaosSigBackend(PythonSigBackend(), schedule, hang_s=2.0),
        ServingConfig(flush_us=200.0, watchdog_s=0.15))
    try:
        with pytest.raises(DeadlineExceeded):
            serving.ecrecover_addresses([b"\x11" * 32], [b"\x22" * 65])
        deadline = time.monotonic() + 10.0
        bundle = None
        while time.monotonic() < deadline:
            RECORDER.flush()
            dirs = sorted(os.listdir(tmp_path))
            if dirs:
                bundle = tmp_path / dirs[-1]
                break
            time.sleep(0.02)
        assert bundle is not None, "watchdog fired but no bundle appeared"
        files = sorted(os.listdir(bundle))
        for required in ("manifest.json", "events.json", "spans.json",
                         "metrics.json", "wire.json", "ledger_tail.jsonl"):
            assert required in files, (required, files)
        events = json.load(open(bundle / "events.json"))
        kinds = {e["kind"] for e in events}
        assert "watchdog_timeout" in kinds, kinds
        assert "chaos_decision" in kinds, kinds
        snapshot = json.load(open(bundle / "metrics.json"))
        assert "resilience/watchdog/timeouts" in snapshot
    finally:
        serving.close()


# == history import + surfaces =============================================


def test_ledger_import_idempotent(tmp_path):
    import subprocess
    import sys as _sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    target = tmp_path / "imported.jsonl"
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    first = subprocess.run(
        [_sys.executable, os.path.join(repo, "scripts", "ledger_import.py"),
         "--ledger", str(target)],
        capture_output=True, text=True, timeout=120, cwd=repo, env=env)
    assert first.returncode == 0, first.stderr
    led = Ledger(str(target))
    records = led.records()
    assert len(records) >= 5, [r.get("extra") for r in records]
    heads = [r for r in records
             if r["workload"] == "notary_sig_verifications_per_sec"]
    assert heads, "headline history missing"
    assert any(r.get("platform") == "tpu" for r in heads)
    assert all(r["source"] == "import" for r in records)
    # idempotent: a second run appends nothing
    second = subprocess.run(
        [_sys.executable, os.path.join(repo, "scripts", "ledger_import.py"),
         "--ledger", str(target)],
        capture_output=True, text=True, timeout=120, cwd=repo, env=env)
    assert second.returncode == 0, second.stderr
    assert len(led.records()) == len(records)
    # ... and the report twin renders the imported history
    text = pgate.report(led)
    assert "45487.7" in text


def test_cli_check_exit_codes(tmp_path):
    import subprocess
    import sys as _sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = tmp_path / "ledger.jsonl"
    led = Ledger(str(path))
    _seeded_history(led, 8)
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    ok = subprocess.run(
        [_sys.executable, "-m", "gethsharding_tpu.perfwatch", "--check",
         "--ledger", str(path)],
        capture_output=True, text=True, timeout=120, cwd=repo, env=env)
    assert ok.returncode == 0, ok.stderr
    led.append({"workload": "micro/demo", "backend": "host",
                "platform": "host", "metrics": {"wall_s": 0.2}})
    bad = subprocess.run(
        [_sys.executable, "-m", "gethsharding_tpu.perfwatch", "--check",
         "--json", "--ledger", str(path)],
        capture_output=True, text=True, timeout=120, cwd=repo, env=env)
    assert bad.returncode == 1, (bad.stdout, bad.stderr)
    verdicts = json.loads(bad.stdout.strip().splitlines()[-1])
    assert verdicts["failed"] is True


def test_perf_status_section(tmp_path, monkeypatch):
    path = tmp_path / "ledger.jsonl"
    monkeypatch.setenv("GETHSHARDING_PERFWATCH_LEDGER", str(path))
    led = Ledger(str(path))
    led.append({"workload": "w", "platform": "host",
                "metrics": {"value": 42.0}})
    pgate.check(led)
    status = perfwatch.perf_status()
    assert status["ledger"]["last"]["workload"] == "w"
    assert status["ledger"]["last"]["value"] == 42.0
    assert status["gate"] is not None and "failed" in status["gate"]
    assert "timer_suspect" in status
    assert "events" in status["recorder"]


def test_perfwatch_prometheus_rows():
    from gethsharding_tpu.metrics import prometheus_text

    text = prometheus_text()
    for needle in ("gethsharding_perfwatch_timer_suspect_total",
                   "gethsharding_perfwatch_pulls_total",
                   "gethsharding_perfwatch_events_total",
                   "gethsharding_perfwatch_bundles_total",
                   "gethsharding_perfwatch_ledger_records_total"):
        assert needle in text, needle
