"""Differential tests: batched SMC vote kernel (ops/smc_jax) vs the scalar
state machine (smc/state_machine.py), which is itself contract-test-pinned
to sharding_manager.sol semantics.

The contract: applying a period's submitVote attempts through
`submit_votes_batch` must reproduce, byte-identically, the state the scalar
SMC reaches applying them in order — packed uint256 vote words, per-attempt
accept/revert, is_elected flips, lastApproved — including first-wins
resolution of in-batch (shard, index) collisions.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from gethsharding_tpu.crypto.keccak import keccak256
from gethsharding_tpu.ops.smc_jax import (
    VoteAttempts, add_header_reset, export_vote_word, init_vote_state,
    sample_committee, submit_votes_batch,
)
from gethsharding_tpu.params import Config
from gethsharding_tpu.smc.state_machine import SMC, SMCRevert, Notary
from gethsharding_tpu.utils.hexbytes import Address20, Hash32

CFG = Config(shard_count=6, committee_size=9, quorum_size=3)
POOL_CAP = 16


def _addr(i: int) -> Address20:
    return Address20(keccak256(b"notary" + bytes([i]))[:20])


def _blockhash_fn(n: int) -> Hash32:
    return Hash32(keccak256(b"block" + n.to_bytes(8, "big")))


def _pool_array(smc: SMC) -> np.ndarray:
    pool = np.zeros((POOL_CAP, 20), np.uint8)
    for i, a in enumerate(smc.notary_pool):
        if a is not None:
            pool[i] = np.frombuffer(bytes(a), np.uint8)
    return pool


def _setup():
    smc = SMC(CFG, blockhash_fn=_blockhash_fn)
    notaries = [_addr(i) for i in range(10)]
    for a in notaries:
        smc.register_notary(a, CFG.notary_deposit, block_number=0)
    # one deregistration: slot emptied, registry stays deposited (.sol quirk)
    smc.deregister_notary(notaries[3], block_number=1)
    return smc, notaries


def test_sample_committee_matches_scalar():
    smc, notaries = _setup()
    block_number = 5  # period 1
    # mirror the sample-size update the scalar performs inside submit_vote
    smc._update_notary_sample_size(block_number)
    sample_size = smc.current_period_notary_sample_size
    bh = np.frombuffer(
        bytes(_blockhash_fn(1 * CFG.period_length - 1)), np.uint8)

    pool_idx, shards, expect = [], [], []
    for a in notaries:
        entry = smc.notary_registry[a]
        for s in range(CFG.shard_count):
            pool_idx.append(entry.pool_index)
            shards.append(s)
            expect.append(bytes(
                smc.get_notary_in_committee_view(a, s, block_number)))
    slots = np.asarray(jax.jit(sample_committee)(
        jnp.asarray(bh), jnp.asarray(pool_idx, jnp.int32),
        jnp.asarray(shards, jnp.int32), jnp.int32(sample_size)))
    pool = _pool_array(smc)
    for k, slot in enumerate(slots):
        member = pool[slot] if slot < POOL_CAP else np.zeros(20, np.uint8)
        assert member.tobytes() == expect[k], k


def test_vote_batch_matches_scalar_sequential():
    smc, notaries = _setup()
    period, block_number = 1, 5
    roots = {s: Hash32(keccak256(b"root" + bytes([s])))
             for s in range(CFG.shard_count)}
    state = init_vote_state(CFG.shard_count, CFG.committee_size)
    for s in range(CFG.shard_count - 1):  # last shard: no header this period
        smc.add_header(notaries[0], s, period, roots[s], b"", block_number)
    state = add_header_reset(
        state,
        jnp.asarray(list(range(CFG.shard_count - 1)), jnp.int32),
        jnp.int32(period),
        jnp.asarray(np.stack([
            np.frombuffer(bytes(roots[s]), np.uint8)
            for s in range(CFG.shard_count - 1)])))

    smc._update_notary_sample_size(block_number)
    sample_size = smc.current_period_notary_sample_size
    bh = np.frombuffer(
        bytes(_blockhash_fn(period * CFG.period_length - 1)), np.uint8)

    # craft attempts: all eligible (sender, shard) pairs voting at rolling
    # indices, plus adversarial cases
    rng = np.random.default_rng(0)
    attempts = []  # (sender, shard, index, chunk_root, deposited)
    idx_counter = 0
    for a in notaries:
        for s in range(CFG.shard_count):
            if smc.get_notary_in_committee_view(a, s, block_number) == a:
                attempts.append((a, s, idx_counter % CFG.committee_size,
                                 roots[s], True))
                idx_counter += 1
    assert attempts, "need at least one eligible vote"
    sh0 = attempts[0][1]
    attempts.append((attempts[0][0], sh0, attempts[0][2], roots[sh0], True))  # dup (shard,index)
    attempts.append((attempts[0][0], sh0, CFG.committee_size, roots[sh0], True))  # index OOR
    attempts.append((attempts[0][0], sh0, 5, Hash32(b"\xff" * 32), True))  # bad root
    stranger = _addr(99)
    attempts.append((stranger, sh0, 6, roots[sh0], False))  # undeposited
    attempts.append((attempts[0][0], CFG.shard_count - 1, 7,
                     roots[CFG.shard_count - 1], True))  # no header shard
    dereg = notaries[3]
    attempts.append((dereg, sh0, 8, roots[sh0], True))  # deregistered: slot empty
    rng.shuffle(attempts)

    scalar_ok = []
    for (a, s, i, root, dep) in attempts:
        try:
            smc.submit_vote(a, s, period, i, root, block_number)
            scalar_ok.append(True)
        except SMCRevert:
            scalar_ok.append(False)

    batch = VoteAttempts(
        shard=jnp.asarray([t[1] for t in attempts], jnp.int32),
        index=jnp.asarray([t[2] for t in attempts], jnp.int32),
        pool_index=jnp.asarray(
            [smc.notary_registry.get(t[0], Notary()).pool_index
             for t in attempts], jnp.int32),
        sender=jnp.asarray(np.stack(
            [np.frombuffer(bytes(t[0]), np.uint8) for t in attempts])),
        chunk_root=jnp.asarray(np.stack(
            [np.frombuffer(bytes(t[3]), np.uint8) for t in attempts])),
        deposited=jnp.asarray([t[4] for t in attempts], jnp.bool_),
        valid=jnp.ones(len(attempts), jnp.bool_),
    )
    new_state, accepted = jax.jit(
        submit_votes_batch,
        static_argnames=("committee_size", "quorum_size"))(
        state, jnp.asarray(_pool_array(smc)), batch,
        period=jnp.int32(period), blockhash=jnp.asarray(bh),
        sample_size=jnp.int32(sample_size),
        committee_size=CFG.committee_size, quorum_size=CFG.quorum_size)

    assert list(np.asarray(accepted)) == scalar_ok

    words = export_vote_word(np.asarray(new_state.has_voted),
                             np.asarray(new_state.vote_count))
    for s in range(CFG.shard_count):
        assert words[s] == smc.current_vote.get(s, 0), f"shard {s}"
        rec = smc.collation_records.get((s, period))
        kernel_elected = bool(np.asarray(new_state.is_elected)[s])
        assert kernel_elected == (rec.is_elected if rec else False), f"shard {s}"
        assert int(np.asarray(new_state.last_approved)[s]) == \
            smc.last_approved_collation.get(s, 0), f"shard {s}"
        assert int(np.asarray(new_state.last_submitted)[s]) == \
            smc.last_submitted_collation.get(s, 0), f"shard {s}"


@pytest.mark.slow  # ~9 s vmap compile; the scalar-parity pair above guards the kernel fast
def test_vmap_over_period_batches():
    """The kernel vmaps: independent periods in parallel give the same
    result as one-at-a-time application (shard axis stays inside)."""
    state = init_vote_state(4, 5)
    state = add_header_reset(
        state, jnp.asarray([0, 1, 2, 3], jnp.int32), jnp.int32(1),
        jnp.zeros((4, 32), jnp.uint8))
    pool = np.zeros((4, 20), np.uint8)
    pool[0] = 7
    bh = np.zeros(32, np.uint8)

    def mk(shards):
        n = len(shards)
        return VoteAttempts(
            shard=jnp.asarray(shards, jnp.int32),
            index=jnp.asarray(list(range(n)), jnp.int32),
            pool_index=jnp.zeros(n, jnp.int32),
            sender=jnp.asarray(np.broadcast_to(pool[0], (n, 20))),
            chunk_root=jnp.zeros((n, 32), jnp.uint8),
            deposited=jnp.ones(n, jnp.bool_),
            valid=jnp.ones(n, jnp.bool_),
        )

    def run(attempts):
        return submit_votes_batch(
            state, jnp.asarray(pool), attempts, period=jnp.int32(1),
            blockhash=jnp.asarray(bh), sample_size=jnp.int32(1),
            committee_size=5, quorum_size=3)

    batches = [mk([0, 1, 2]), mk([3, 3, 3])]
    stacked = VoteAttempts(*[
        jnp.stack([getattr(batches[0], f), getattr(batches[1], f)])
        for f in VoteAttempts._fields])
    vs, va = jax.vmap(run)(stacked)
    for bi, b in enumerate(batches):
        s1, a1 = run(b)
        np.testing.assert_array_equal(np.asarray(va)[bi], np.asarray(a1))
        np.testing.assert_array_equal(
            np.asarray(vs.vote_count)[bi], np.asarray(s1.vote_count))


@pytest.mark.slow  # ~6 s multi-period batch compile
def test_no_quorum_carryover_across_periods():
    """A shard that reached quorum in period 1 and has NO header in period 2
    must keep last_approved = 1 when a period-2 batch (for other shards)
    is applied — parity with the scalar rule that lastApproved/isElected
    only move inside an accepted submitVote (.sol:215-218)."""
    state = init_vote_state(2, 5)
    pool = np.zeros((4, 20), np.uint8)
    pool[0] = 7
    bh = np.zeros(32, np.uint8)

    def attempts(shards, n0=0):
        n = len(shards)
        return VoteAttempts(
            shard=jnp.asarray(shards, jnp.int32),
            index=jnp.asarray(list(range(n0, n0 + n)), jnp.int32),
            pool_index=jnp.zeros(n, jnp.int32),
            sender=jnp.asarray(np.broadcast_to(pool[0], (n, 20))),
            chunk_root=jnp.zeros((n, 32), jnp.uint8),
            deposited=jnp.ones(n, jnp.bool_),
            valid=jnp.ones(n, jnp.bool_),
        )

    def submit(state, batch, period):
        return submit_votes_batch(
            state, jnp.asarray(pool), batch, period=jnp.int32(period),
            blockhash=jnp.asarray(bh), sample_size=jnp.int32(1),
            committee_size=5, quorum_size=2)

    # period 1: header + quorum on shard 0
    state = add_header_reset(state, jnp.asarray([0], jnp.int32),
                             jnp.int32(1), jnp.zeros((1, 32), jnp.uint8))
    state, acc = submit(state, attempts([0, 0]), 1)
    assert list(np.asarray(acc)) == [True, True]
    assert int(np.asarray(state.last_approved)[0]) == 1

    # period 2: header only on shard 1; batch votes only shard 1
    state = add_header_reset(state, jnp.asarray([1], jnp.int32),
                             jnp.int32(2), jnp.zeros((1, 32), jnp.uint8))
    state, acc = submit(state, attempts([1], n0=0), 2)
    assert int(np.asarray(state.last_approved)[0]) == 1, \
        "stale quorum count must not re-approve shard 0 in period 2"
    assert int(np.asarray(state.last_approved)[1]) == 0  # 1 vote < quorum 2
