"""The minimum end-to-end slice (SURVEY.md §7.4): one process, no network —
txpool -> proposer builds/signs collation -> addHeader -> period advance ->
notary committee check -> availability sync over shardp2p -> vote ->
quorum -> canonical header in the notary's shardDB.

Two ShardNodes share only the simulated mainchain (consensus) and the p2p
hub (data availability); shard databases are per-node, so the notary MUST
fetch the body over p2p before it can vote.
"""

import time

import pytest

from gethsharding_tpu.actors import Notary, Proposer, Syncer, TXPool
from gethsharding_tpu.core.types import Transaction
from gethsharding_tpu.node.backend import ShardNode
from gethsharding_tpu.p2p.service import Hub
from gethsharding_tpu.params import Config, ETHER
from gethsharding_tpu.smc.chain import SimulatedMainchain

SHARD = 4


@pytest.fixture(scope="module")
def warm_jax_backend():
    """Compile the batch-1/4 kernel shapes the jax sig backend uses before
    any notary needs them mid-period: a cold compile inside the head
    callback would eat the whole vote window (a few commits)."""
    from gethsharding_tpu.crypto import bn256 as bls
    from gethsharding_tpu.crypto import secp256k1
    from gethsharding_tpu.sigbackend import get_backend

    backend = get_backend("jax")
    sig = secp256k1.sign(b"\x11" * 32, 0xA11CE)
    sk, pk = bls.bls_keygen(b"warm")
    message = b"warm-up"
    signature = bls.bls_sign(message, sk)
    for n in (1, 4):  # the power-of-two buckets the tests dispatch at
        backend.ecrecover_addresses([b"\x11" * 32] * n,
                                    [sig.to_bytes65()] * n)
        backend.bls_verify_aggregates([message] * n, [signature] * n,
                                      [pk] * n)
    return backend


def wait_until(predicate, timeout=10.0, step=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(step)
    return predicate()


def test_full_period_pipeline_two_nodes():
    config = Config(quorum_size=1)
    backend = SimulatedMainchain(config=config)
    hub = Hub()

    proposer_node = ShardNode(actor="proposer", shard_id=SHARD, config=config,
                              backend=backend, hub=hub, txpool_interval=None)
    notary_node = ShardNode(actor="notary", shard_id=SHARD, config=config,
                            backend=backend, hub=hub, deposit=True)
    backend.fund(proposer_node.client.account(), 2000 * ETHER)
    backend.fund(notary_node.client.account(), 2000 * ETHER)

    proposer_node.start()
    notary_node.start()
    try:
        notary = notary_node.service(Notary)
        proposer = proposer_node.service(Proposer)
        assert notary.is_account_in_notary_pool()

        # enter period 1 so addHeader is legal (period must be > 0)
        backend.fast_forward(1)
        period = backend.current_period()

        # a real transaction enters the shard txpool
        proposer_node.service(TXPool).submit(
            Transaction(nonce=1, payload=b"end-to-end tx payload")
        )
        assert wait_until(lambda: proposer.collations_proposed >= 1)
        assert backend.last_submitted_collation(SHARD) == period

        # next heads drive the notary: first head may miss the body (p2p
        # fetch is async) but retries land within the same period
        approved = False
        for _ in range(config.period_length - 1):
            backend.commit()
            if wait_until(
                lambda: backend.last_approved_collation(SHARD) == period,
                timeout=2.0,
            ):
                approved = True
                break
        assert approved, f"errors: {notary_node.errors()}"
        assert notary.votes_submitted >= 1

        # the notary synced the body over the hub and set the canonical header
        assert wait_until(lambda: notary.canonical_set >= 1, timeout=5.0), \
            f"errors: {notary_node.errors()}"
        canonical = notary_node.shard.canonical_collation(SHARD, period)
        record = backend.collation_record(SHARD, period)
        assert canonical.header.chunk_root == record.chunk_root
        assert record.is_elected is True
        # body round-tripped through p2p: payload recovered tx-for-tx
        assert canonical.transactions[0].payload == b"end-to-end tx payload"
        assert notary_node.service(Syncer).bodies_stored >= 1
    finally:
        notary_node.stop()
        proposer_node.stop()


def test_multi_shard_lockstep_two_periods():
    """Proposers on 3 shards + one notary voting across all shards for two
    consecutive periods — the lockstep-period pattern the TPU path batches."""
    n_shards = 3
    config = Config(quorum_size=1)
    backend = SimulatedMainchain(config=config)
    hub = Hub()
    proposers = [
        ShardNode(actor="proposer", shard_id=s, config=config,
                  backend=backend, hub=hub, txpool_interval=None)
        for s in range(n_shards)
    ]
    notary_node = ShardNode(actor="notary", shard_id=0, config=config,
                            backend=backend, hub=hub, deposit=True)
    backend.fund(notary_node.client.account(), 2000 * ETHER)
    for node in proposers:
        node.start()
    notary_node.start()
    try:
        for _ in range(2):  # two consecutive periods
            backend.fast_forward(1)
            period = backend.current_period()
            for s, node in enumerate(proposers):
                node.service(TXPool).submit(Transaction(nonce=period,
                                                        payload=bytes([s])))
            assert wait_until(
                lambda: all(backend.last_submitted_collation(s) == period
                            for s in range(n_shards))
            )
            for _ in range(config.period_length - 1):
                backend.commit()
                if all(backend.last_approved_collation(s) == period
                       for s in range(n_shards)):
                    break
                time.sleep(0.05)
            assert all(backend.last_approved_collation(s) == period
                       for s in range(n_shards)), notary_node.errors()
    finally:
        notary_node.stop()
        for node in proposers:
            node.stop()


def test_period_audit_one_batched_dispatch(warm_jax_backend):
    """The re-architected hot loop, in the RUNNING node: a multi-shard
    period's committee votes (real BLS signatures produced by the voting
    path) are verified by the notary in ONE sig-backend dispatch at the
    next period boundary, the quorum outcome matches the SMC byte-for-byte,
    and the chain's vote log replays cleanly through
    ops/smc_jax.submit_votes_batch."""
    from gethsharding_tpu.crypto import bn256 as bls

    n_shards = 3
    config = Config(quorum_size=1)
    backend = SimulatedMainchain(config=config)
    hub = Hub()
    proposers = [
        ShardNode(actor="proposer", shard_id=s, config=config,
                  backend=backend, hub=hub, txpool_interval=None)
        for s in range(n_shards)
    ]
    notary_node = ShardNode(actor="notary", shard_id=0, config=config,
                            backend=backend, hub=hub, deposit=True,
                            sig_backend="jax")
    backend.fund(notary_node.client.account(), 2000 * ETHER)
    for node in proposers:
        node.start()
    notary_node.start()
    try:
        notary = notary_node.service(Notary)
        backend.fast_forward(1)
        period = backend.current_period()
        for s, node in enumerate(proposers):
            node.service(TXPool).submit(
                Transaction(nonce=period, payload=bytes([s])))
        assert wait_until(
            lambda: all(backend.last_submitted_collation(s) == period
                        for s in range(n_shards)))
        for _ in range(config.period_length - 1):
            backend.commit()
            if all(backend.last_approved_collation(s) == period
                   for s in range(n_shards)):
                break
            time.sleep(0.05)
        assert all(backend.last_approved_collation(s) == period
                   for s in range(n_shards)), notary_node.errors()
        # every vote carried a real BLS signature
        for s in range(n_shards):
            assert backend.collation_record(s, period).vote_sigs

        # crossing into the next period triggers the in-node audit:
        # one batched pairing dispatch over all shards + the vote-log
        # replay through the fixed-shape SMC kernel
        backend.fast_forward(1)
        assert wait_until(lambda: notary.audits_run >= 1, timeout=120.0), \
            notary_node.errors()
        assert notary.audit_mismatches == 0
        assert notary.aggregate_sigs_verified >= n_shards
        assert backend.verify_period_batch(period) is True

        # a forged stored signature must be caught by the audit
        record = backend.collation_record(0, period)
        idx = next(iter(record.vote_sigs))
        vote = record.vote_sigs[idx]
        record.vote_sigs[idx] = type(vote)(
            sig=bls.g1_add(vote.sig, bls.G1_GEN), signer=vote.signer)
        assert notary.audit_period(period) is False
        assert notary.audit_mismatches >= 1
    finally:
        notary_node.stop()
        for node in proposers:
            node.stop()


def test_multi_notary_quorum_aggregate_audit(warm_jax_backend):
    """Three notaries, quorum 2: several committee members vote on one
    shard (real BLS signatures from distinct keys), the SMC elects on
    quorum, and the period audit verifies the MULTI-SIGNER aggregate in
    one dispatch — the aggregation path exercised end-to-end through the
    protocol rather than synthesized."""
    config = Config(quorum_size=2)
    backend = SimulatedMainchain(config=config)
    hub = Hub()

    notary_nodes = [
        ShardNode(actor="notary", shard_id=0, config=config, backend=backend,
                  hub=hub, deposit=True, sig_backend="jax")
        for _ in range(3)
    ]
    for node in notary_nodes:
        backend.fund(node.client.account(), 2000 * ETHER)
    for node in notary_nodes:
        node.start()
    try:
        # find a (period, shard) where >= quorum of our notaries are
        # sampled eligible (committee sampling is deterministic)
        addresses = [n.client.account() for n in notary_nodes]
        target_shard = None
        for _ in range(12):  # periods to scan
            backend.fast_forward(1)
            for shard in range(config.shard_count):
                eligible = sum(
                    backend.get_notary_in_committee(addr, shard) == addr
                    for addr in addresses)
                if eligible >= config.quorum_size:
                    target_shard = shard
                    break
            if target_shard is not None:
                break
        assert target_shard is not None, "no quorum-eligible shard sampled"

        # reconfigure the actor nodes' shard + propose on the target shard
        period = backend.current_period()
        proposer = ShardNode(actor="proposer", shard_id=target_shard,
                             config=config, backend=backend, hub=hub,
                             txpool_interval=None)
        proposer.start()
        proposer.service(TXPool).submit(
            Transaction(nonce=1, payload=b"quorum tx"))
        assert wait_until(
            lambda: backend.last_submitted_collation(target_shard) == period)

        approved = False
        for _ in range(config.period_length - 1):
            backend.commit()
            if wait_until(lambda: backend.last_approved_collation(
                    target_shard) == period, timeout=3.0):
                approved = True
                break
        errors = sum((n.errors() for n in notary_nodes), [])
        assert approved, errors
        record = backend.collation_record(target_shard, period)
        assert len(record.vote_sigs) >= config.quorum_size  # multi-signer
        signers = {bytes(v.signer) for v in record.vote_sigs.values()}
        assert len(signers) >= 2

        # the audit verifies the multi-signer aggregate
        notary = notary_nodes[0].service(Notary)
        assert notary.audit_period(period) is True
        assert notary.audit_mismatches == 0
        proposer.stop()
    finally:
        for node in notary_nodes:
            node.stop()


def test_multi_period_catchup_audit_single_dispatch(warm_jax_backend):
    """audit_periods: TWO voted periods + one empty period audited in ONE
    sig-backend dispatch (the observer catch-up path), with per-period
    outcomes identical to audit_period's."""
    n_shards = 2
    config = Config(quorum_size=1)
    backend = SimulatedMainchain(config=config)
    hub = Hub()
    proposers = [
        ShardNode(actor="proposer", shard_id=s, config=config,
                  backend=backend, hub=hub, txpool_interval=None)
        for s in range(n_shards)
    ]
    notary_node = ShardNode(actor="notary", shard_id=0, config=config,
                            backend=backend, hub=hub, deposit=True,
                            sig_backend="jax")
    backend.fund(notary_node.client.account(), 2000 * ETHER)
    for node in proposers:
        node.start()
    notary_node.start()
    try:
        notary = notary_node.service(Notary)
        voted = []
        for _ in range(2):
            backend.fast_forward(1)
            period = backend.current_period()
            for s, node in enumerate(proposers):
                node.service(TXPool).submit(
                    Transaction(nonce=period, payload=bytes([s])))
            assert wait_until(
                lambda: all(backend.last_submitted_collation(s) == period
                            for s in range(n_shards)))
            for _ in range(config.period_length - 1):
                backend.commit()
                if all(backend.last_approved_collation(s) == period
                       for s in range(n_shards)):
                    break
                time.sleep(0.05)
            assert all(backend.last_approved_collation(s) == period
                       for s in range(n_shards)), notary_node.errors()
            voted.append(period)

        backend.fast_forward(2)
        empty = backend.current_period()  # no records in this period
        before = notary.m_audit_latency.count
        results = notary.audit_periods(voted + [empty])
        assert notary.m_audit_latency.count == before + 1  # ONE dispatch
        assert results == {voted[0]: True, voted[1]: True, empty: None}
        # per-period equivalence with the single-period form
        assert notary.audit_period(voted[0]) is True
        assert notary.audit_period(empty) is None
        assert notary.audit_mismatches == 0
    finally:
        notary_node.stop()
        for node in proposers:
            node.stop()
