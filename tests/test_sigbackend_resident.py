"""Device-resident pk planes + the async committee path (ISSUE 4).

Randomized differential test against the scalar backend over the full
matrix: empty rows, infinity (None) points inside rows, row-key churn
forcing memory-accounted eviction, the u16 wire on and off, and the
sync vs async (overlapped) dispatch path — every verdict pinned
byte-identical to `PythonSigBackend`. Plus the steady-state ledger
claim the perf work rests on: a warm device cache ships ZERO G2 pubkey
bytes per dispatch, and the notary's overlapped `audit_periods`
pipeline returns exactly the batched form's results.
"""

import random

import pytest

from gethsharding_tpu import metrics
from gethsharding_tpu.crypto import bn256 as bls
from gethsharding_tpu.sigbackend import JaxSigBackend, get_backend

# one shared key pool: rows drawn from it recur across rounds, so the
# device cache sees hits, misses AND churn under a tiny byte budget
KEYPOOL = [bls.bls_keygen(b"res-pool-%d" % i) for i in range(8)]


def _rand_round(rng, n_rows=4, max_k=3):
    """One randomized batch: (msgs, sig_rows, pk_rows, row_keys).

    Rows cover empty committees, infinity (None) signature/pubkey
    slots, tampered signatures, and honest rows. Shapes stay inside one
    compile bucket (n_rows=4, width<=4) so the randomized rounds reuse
    one compiled program. Row keys are derived from the pk row CONTENT
    (member set + None pattern) — the caller contract that keys
    uniquely determine the row's points."""
    msgs, sig_rows, pk_rows, keys = [], [], [], []
    for _ in range(n_rows):
        kind = rng.random()
        tag = b"res-msg-%d" % rng.randrange(6)
        if kind < 0.15:
            msgs.append(tag)
            sig_rows.append([])
            pk_rows.append([])
            keys.append(None)
            continue
        k = rng.randrange(1, max_k + 1)
        members = rng.sample(range(len(KEYPOOL)), k)
        sigs = [bls.bls_sign(tag, KEYPOOL[i][0]) for i in members]
        pks = [KEYPOOL[i][1] for i in members]
        if kind < 0.3 and k >= 2:
            sigs[0] = None  # infinity signature slot (skipped, both paths)
        elif kind < 0.45 and k >= 2:
            pks[1] = None  # infinity pubkey slot
        elif kind < 0.6:
            sigs[-1] = bls.bls_sign(b"tampered", KEYPOOL[members[-1]][0])
        msgs.append(tag)
        sig_rows.append(sigs)
        pk_rows.append(pks)
        keys.append((tuple(members),
                     tuple(i for i, p in enumerate(pks) if p is None)))
    return msgs, sig_rows, pk_rows, keys


@pytest.mark.parametrize("wire", ["i32", "u16"])
def test_randomized_resident_parity_and_eviction(monkeypatch, wire):
    """Randomized rounds under a ~2 KB device budget: sync and async
    resident verdicts match the scalar backend bit-for-bit while the
    LRU evicts under churn and the byte accounting stays bounded."""
    if wire == "u16":
        monkeypatch.setenv("GETHSHARDING_TPU_WIRE", "u16")
    else:
        monkeypatch.delenv("GETHSHARDING_TPU_WIRE", raising=False)
    monkeypatch.setenv("GETHSHARDING_TPU_RESIDENT", "1")
    monkeypatch.setenv("GETHSHARDING_TPU_RESIDENT_MB", "0.002")
    backend = JaxSigBackend()
    py = get_backend("python")
    evictions = metrics.counter("jax/pk_device_cache/evictions")
    before = evictions.value
    rng = random.Random(1234 if wire == "i32" else 4321)
    for _ in range(3):
        msgs, sig_rows, pk_rows, keys = _rand_round(rng)
        want = py.bls_verify_committees(msgs, sig_rows, pk_rows)
        sync = backend.bls_verify_committees(
            msgs, sig_rows, pk_rows, pk_row_keys=keys)
        future = backend.bls_verify_committees_async(
            msgs, sig_rows, pk_rows, pk_row_keys=keys)
        assert sync == future.result() == want
        assert future.done()
    # row-key churn under the tiny budget must have evicted, and the
    # accounted row bytes must respect it
    assert evictions.value > before
    assert backend._pk_dev_bytes <= backend._resident_budget


def test_warm_device_cache_ships_zero_g2_bytes():
    """The steady-state audit shape: identical keyed committees every
    dispatch. Cold ships the G2 planes; warm must ship ZERO G2 bytes
    (full device-cache hit) with an unchanged verdict — the acceptance
    ledger `bench.py --resident` asserts at protocol scale."""
    backend = JaxSigBackend()  # fresh cache; defaults (resident on)
    assert backend._resident
    rng = random.Random(99)
    msgs, sig_rows, pk_rows, keys = _rand_round(rng)
    while not any(pk_rows):  # need at least one pointful row
        msgs, sig_rows, pk_rows, keys = _rand_round(rng)
    want = get_backend("python").bls_verify_committees(
        msgs, sig_rows, pk_rows)
    cold = backend.bls_verify_committees(
        msgs, sig_rows, pk_rows, pk_row_keys=keys)
    assert cold == want
    assert backend.last_wire["g2_wire_bytes"] > 0
    # the committee compile-cache key carries the wire dtype: flipping
    # GETHSHARDING_TPU_WIRE compiles a DIFFERENT program for the same
    # (bucket, width), which must count as a miss, not a hit (keyed
    # dispatches run the precomp op when GETHSHARDING_PRECOMP is on)
    assert any(k[0] in ("bls_committee", "bls_committee_precomp")
               and backend._wire in k[1:]
               for k in backend._shape_seen)
    warm = backend.bls_verify_committees(
        msgs, sig_rows, pk_rows, pk_row_keys=keys)
    assert warm == want
    assert backend.last_wire["g2_wire_bytes"] == 0
    assert (backend.last_wire["pk_hit_rows"]
            == backend.last_wire["pk_rows"]
            == sum(1 for r in pk_rows if r))
    assert backend.last_wire["pk_hit_bytes"] > 0
    # a SHORT key list (fewer keys than rows) marks the trailing rows
    # uncached instead of dropping them — the host row cache's contract,
    # kept by the resident path
    assert backend.bls_verify_committees(
        msgs, sig_rows, pk_rows, pk_row_keys=keys[:1]) == want
    # resident off: every dispatch re-ships the planes (the A/B the
    # bench reports), verdict still identical
    import os

    os.environ["GETHSHARDING_TPU_RESIDENT"] = "0"
    try:
        off = JaxSigBackend()
        assert off.bls_verify_committees(
            msgs, sig_rows, pk_rows, pk_row_keys=keys) == want
        assert off.last_wire["g2_wire_bytes"] > 0
    finally:
        del os.environ["GETHSHARDING_TPU_RESIDENT"]


def test_notary_overlapped_audit_matches_batched():
    """`audit_periods(..., overlap=True)` (the marshal/dispatch
    pipeline) must return exactly the batched single-dispatch form's
    per-period results, including the nothing-auditable period."""
    from gethsharding_tpu.actors.notary import Notary
    from gethsharding_tpu.core.shard import Shard
    from gethsharding_tpu.db.kv import MemoryKV
    from gethsharding_tpu.mainchain.client import SMCClient
    from gethsharding_tpu.smc.chain import SimulatedMainchain

    notary = Notary(client=SMCClient(backend=SimulatedMainchain()),
                    shard=Shard(0, MemoryKV()),
                    sig_backend=get_backend("python"))
    rng = random.Random(7)
    rows_by_period = {3: None}  # period 3: nothing auditable
    for p in (1, 2):
        msgs, sig_rows, pk_rows, keys = _rand_round(rng, n_rows=3)
        rows_by_period[p] = {
            "shards": list(range(len(msgs))),
            "msgs": msgs, "sig_rows": sig_rows, "pk_rows": pk_rows,
            "pk_keys": keys,
            "signed_counts": [len(s) for s in sig_rows],
            "total_counts": [len(s) for s in sig_rows],
            "expected": [len(s) >= notary.config.quorum_size
                         for s in sig_rows],
        }
    notary._collect_audit_rows = lambda p: rows_by_period[p]

    batched = notary.audit_periods([1, 2, 3])
    mismatches_after_batched = notary.audit_mismatches
    overlapped = notary.audit_periods([1, 2, 3], overlap=True)
    assert overlapped == batched
    assert batched[3] is None
    # both passes judged the same rows the same way
    assert (notary.audit_mismatches - mismatches_after_batched
            == mismatches_after_batched)
    assert notary.audits_run == 4  # 2 auditable periods x 2 passes
