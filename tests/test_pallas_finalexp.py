"""The final-exponentiation mega-kernel (ops/pallas_finalexp.py) vs the
XLA path, layer by layer:

1. helper differentials — the kernel's relaxed normalize / conv / xi /
   fp12-mul / frobenius as plain XLA ops, value-compared (mod p) against
   ops/bn256_jax + host scalar crypto;
2. program oracle — the full instruction stream executed with the same
   helpers as unrolled XLA (`run_program_xla`) must reproduce
   `pairing_is_one` bit-for-bit on real Miller products;
3. the Pallas kernel in interpreter mode must match the oracle.

All CPU (conftest forces virtual devices); on TPU the queued probe
(scripts/tpu_experiments) runs the same checks compiled."""

import os

import numpy as np
import jax.numpy as jnp
import pytest

from gethsharding_tpu.crypto import bn256 as ref
from gethsharding_tpu.ops import bn256_jax as k
from gethsharding_tpu.ops import pallas_finalexp as m
from gethsharding_tpu.ops.limb import NLIMBS, int_to_limbs, limbs_to_int

def slow(fn):
    """Heavy differential: excluded from BOTH fast tiers (the `-m "not
    slow"` marker tier and the GETHSHARDING_SKIP_SLOW env tier); the
    module's cheap helper-parity tests stay fast in both."""
    fn = pytest.mark.skipif(
        os.environ.get("GETHSHARDING_SKIP_SLOW") == "1",
        reason="GETHSHARDING_SKIP_SLOW=1")(fn)
    return pytest.mark.slow(fn)


def _vals_mod_p(limbs_rows) -> np.ndarray:
    """(..., W, B) kernel-layout limbs -> (..., B) integers mod p."""
    arr = np.asarray(limbs_rows)
    out = np.zeros(arr.shape[:-2] + arr.shape[-1:], dtype=object)
    for i in range(arr.shape[-2]):
        out = out + (arr[..., i, :].astype(object) << (12 * i))
    return out % m.P


def _rand_quasi(rng, shape):
    """Quasi-canonical kernel-form limbs: values in [-1, 4160]."""
    return rng.integers(-1, 4161, shape + (m.KNL,)).astype(np.int32)


_C = None


def _consts():
    global _C
    if _C is None:
        _C = m.Consts(*(jnp.asarray(c) for c in m._NP_CONSTS))
    return _C


def _to_rows(x):
    """(..., W) -> (..., W, 1) single-lane kernel layout."""
    return jnp.asarray(np.asarray(x)[..., None])


def test_normalize_value_and_bounds():
    rng = np.random.default_rng(51)
    z = rng.integers(-(1 << 29), 1 << 29, (8, m.KNCOLS)).astype(np.int32)
    # make represented values non-negative: add the conv pad
    z = z + np.pad(m._PAD547, (0, m.KNCOLS - m._PAD547.shape[0]))
    out = np.asarray(m._normalize(_to_rows(z), _consts()))
    assert out.shape == (8, m.KNL, 1)
    assert out.min() >= -1 and out.max() <= (1 << 12) + 64
    want = _vals_mod_p(_to_rows(z))
    got = _vals_mod_p(out)
    assert (want == got).all()


def test_conv_matches_schoolbook():
    rng = np.random.default_rng(52)
    a = rng.integers(0, 1 << 12, (3, m.KNL)).astype(np.int32)
    b = rng.integers(0, 1 << 12, (3, m.KNL)).astype(np.int32)
    for impl in ("shift", "slices"):
        got = np.asarray(m._conv(_to_rows(a), _to_rows(b),
                                 impl=impl))[..., 0]
        for i in range(3):
            va = limbs_to_int(a[i])
            vb = limbs_to_int(b[i])
            assert limbs_to_int(got[i].astype(object)) == va * vb, impl


def test_conv_impls_bit_identical():
    """Every MEGA_CONV implementation produces the SAME columns on
    quasi-canonical inputs (incl. the -1 limbs relaxed normalize can
    leave) and with broadcast leading dims — the shapes the fp12 paths
    actually use."""
    rng = np.random.default_rng(57)
    u = rng.integers(-1, (1 << 12) + 65, (2, 3, m.KNL, 4)).astype(np.int32)
    v = rng.integers(-1, (1 << 12) + 65, (3, m.KNL, 4)).astype(np.int32)
    ref_cols = np.asarray(m._conv(jnp.asarray(u), jnp.asarray(v),
                                  impl="shift"))
    got = np.asarray(m._conv(jnp.asarray(u), jnp.asarray(v), impl="slices"))
    assert (got == ref_cols).all()
    assert got.shape == (2, 3, m.KNCOLS, 4)


def test_mul_xi_value_parity():
    rng = np.random.default_rng(53)
    x = _rand_quasi(rng, (4, 6, 2))
    out = np.asarray(m._mul_xi(jnp.asarray(x[..., None]), _consts()))
    vals = _vals_mod_p(out)[..., 0]
    xv = _vals_mod_p(x[..., None])[..., 0]
    for idx in np.ndindex(4, 6):
        a, b = int(xv[idx + (0,)]), int(xv[idx + (1,)])
        assert int(vals[idx + (0,)]) == (9 * a - b) % m.P
        assert int(vals[idx + (1,)]) == (a + 9 * b) % m.P


def _host_fp12_from_vals(vals):
    """vals (6, 2) ints -> ref.Fp12 (w-basis -> tower), for the scalar
    oracle. w-coeff k (a + b i) contributes to c_{k%2} v^{k//2}."""
    c0 = [None] * 3
    c1 = [None] * 3
    for kk in range(6):
        t = ref.Fp2(int(vals[kk, 0]), int(vals[kk, 1]))
        if kk % 2 == 0:
            c0[kk // 2] = t
        else:
            c1[kk // 2] = t
    return ref.Fp12(ref.Fp6(*c0), ref.Fp6(*c1))


def _fp12_to_vals(f):
    """ref.Fp12 -> (6, 2) object ints in the w-basis."""
    out = np.zeros((6, 2), dtype=object)
    for kk in range(6):
        six = f.c0 if kk % 2 == 0 else f.c1
        c = (six.c0, six.c1, six.c2)[kk // 2]
        out[kk] = (c.a % m.P, c.b % m.P)
    return out


@slow  # ~5 s of eager host fp12 parity; conv/normalize/mul_xi stay as the fast guards
def test_fp12_mul_value_parity():
    rng = np.random.default_rng(54)
    x = _rand_quasi(rng, (3, 6, 2))
    y = _rand_quasi(rng, (3, 6, 2))
    out = np.asarray(m._fp12_mul(jnp.asarray(x[..., None]),
                                 jnp.asarray(y[..., None]), _consts()))
    assert out.min() >= -1 and out.max() <= (1 << 12) + 64
    got = _vals_mod_p(out)[..., 0]
    xv = _vals_mod_p(x[..., None])[..., 0]
    yv = _vals_mod_p(y[..., None])[..., 0]
    for i in range(3):
        want = _host_fp12_from_vals(xv[i]) * _host_fp12_from_vals(yv[i])
        wv = _fp12_to_vals(want)
        assert (got[i] == wv).all()


@slow  # ~10 s (three frobenius powers through the XLA oracle)
def test_frobenius_value_parity():
    """Oracle: bn256_jax.fp12_frobenius (itself pinned to the scalar
    reference in test_bn256_jax) on the same values in ambient limbs."""
    rng = np.random.default_rng(55)
    x = _rand_quasi(rng, (2, 6, 2))
    xv = _vals_mod_p(x[..., None])[..., 0]
    amb = np.zeros((2, 6, 2, NLIMBS), np.int32)
    for idx in np.ndindex(2, 6, 2):
        amb[idx] = int_to_limbs(int(xv[idx]), NLIMBS)
    for n in (1, 2, 3):
        out = np.asarray(m._frob(jnp.asarray(x[..., None]), jnp.int32(n), _consts()))
        got = _vals_mod_p(out)[..., 0]
        want = np.asarray(k.FP.canon(k.fp12_frobenius(jnp.asarray(amb), n)))
        for idx in np.ndindex(2, 6, 2):
            assert int(got[idx]) == limbs_to_int(want[idx]), (n, idx)


def _miller_products(n_good: int, n_bad: int):
    """Real pairing workloads: miller products whose final exp is one
    (valid BLS-style checks) and ones where it is not."""
    rng = np.random.default_rng(56)
    fs, wants = [], []
    for j in range(n_good + n_bad):
        a = int.from_bytes(rng.bytes(31), "big") % (ref.N - 3) + 2
        p1 = ref.g1_mul(a, ref.G1_GEN)
        q2 = ref.g2_mul(a, ref.G2_GEN)
        if j >= n_good:  # tamper: shift the G1 point
            p1 = ref.g1_add(p1, ref.G1_GEN)
        px, py, _ = k.g1_to_limbs([p1, ref.g1_neg(ref.G1_GEN)])
        qx, qy, _ = k.g2_to_limbs([ref.G2_GEN, q2])
        f = k.pairing_product(
            jnp.asarray(px)[None], jnp.asarray(py)[None],
            jnp.asarray(qx)[None], jnp.asarray(qy)[None],
            jnp.ones((1, 2), bool))
        fs.append(np.asarray(f)[0])
        wants.append(j < n_good)
    return np.stack(fs), np.asarray(wants)


@slow
def test_program_oracle_matches_pairing_is_one():
    fs, wants = _miller_products(2, 2)
    f = jnp.asarray(fs)
    base = np.asarray(k.pairing_is_one(f))
    assert (base == wants).all(), "XLA baseline disagrees with protocol"
    nd = jnp.stack([k.fp12_conj(f), k.FP.normalize(f)])
    if NLIMBS < m.KNL:
        nd = jnp.concatenate(
            [nd, jnp.zeros(nd.shape[:-1] + (m.KNL - NLIMBS,), jnp.int32)],
            axis=-1)
    out = m.run_program_xla(nd)
    num = k.FP.normalize(out[0])
    den = k.FP.normalize(out[1])
    got = np.asarray(k.fp12_eq(num, den))
    assert (got == wants).all()


@slow
def test_mega_kernel_interpret_matches_pairing_is_one():
    fs, wants = _miller_products(2, 1)
    got = np.asarray(m.finalexp_is_one(jnp.asarray(fs), interpret=True))
    assert (got == wants).all()


class _mega_conv:
    """Flip the trace-time MEGA_CONV knob and drop every compiled-kernel
    cache (finalexp, miller, agg) so the next call re-traces under it."""

    def __init__(self, impl):
        self.impl = impl

    @staticmethod
    def _clear():
        m._compiled.cache_clear()
        m._miller_compiled.cache_clear()
        m._agg_compiled.cache_clear()

    def __enter__(self):
        self.old = m.MEGA_CONV
        m.MEGA_CONV = self.impl
        self._clear()

    def __exit__(self, *exc):
        m.MEGA_CONV = self.old
        self._clear()


@slow
def test_mega_kernel_interpret_slices_conv():
    """The whole final-exp kernel under MEGA_CONV=slices agrees with the
    pairing oracle."""
    fs, wants = _miller_products(1, 1)
    with _mega_conv("slices"):
        got = np.asarray(m.finalexp_is_one(jnp.asarray(fs), interpret=True))
    assert (got == wants).all()


# == the Miller mega-kernel (same module) ==================================


def _committee_workload():
    """Real aggregated projective inputs: (sig, h, pk) for two shards —
    one fully valid, one with a tampered signature set."""
    tag = b"miller-mega"
    keys = [ref.bls_keygen(tag + bytes([j])) for j in range(3)]
    sigs = [ref.bls_sign(tag, sk) for sk, _ in keys]
    bad = [sigs[0], sigs[1], ref.g1_add(sigs[2], ref.G1_GEN)]
    pks = [pk for _, pk in keys]
    hx, hy, _ = k.g1_to_limbs([ref.hash_to_g1(tag)] * 2)
    sx, sy, sm = k.g1_committee_to_limbs([sigs, bad], 3)
    gx, gy, gm = k.g2_committee_to_limbs([pks, pks], 3)
    sig = k.aggregate_g1_proj(jnp.asarray(sx), jnp.asarray(sy),
                              jnp.asarray(sm))
    pk = k.aggregate_g2_proj(jnp.asarray(gx), jnp.asarray(gy),
                             jnp.asarray(gm))
    return sig, (jnp.asarray(hx), jnp.asarray(hy)), pk


def _f_vals(arr):
    out = np.zeros(arr.shape[:-1], dtype=object)
    for i in range(arr.shape[-1]):
        out = out + (arr[..., i].astype(object) << (12 * i))
    return out % m.P


@slow
def test_miller_oracle_matches_xla_path():
    sig, (hx, hy), pk = _committee_workload()

    def widen(v):
        v = np.asarray(v)
        if v.shape[-1] < m.KNL:
            v = np.concatenate(
                [v, np.zeros(v.shape[:-1] + (m.KNL - v.shape[-1],),
                             np.int32)], axis=-1)
        return v

    want = np.asarray(k._bls_miller_opt(sig, hx, hy, pk))
    got = np.asarray(m.run_miller_xla(
        tuple(widen(v) for v in sig), (widen(hx), widen(hy)),
        tuple(widen(v) for v in pk)))
    assert (_f_vals(want) == _f_vals(got)).all()


@slow
def test_miller_mega_kernel_interpret_matches_xla():
    sig, (hx, hy), pk = _committee_workload()
    want = np.asarray(k._bls_miller_opt(sig, hx, hy, pk))
    got = np.asarray(m.miller_f(sig, hx, hy, pk, interpret=True))
    assert (_f_vals(want) == _f_vals(got)).all()
    # end-to-end boolean parity through the final exponentiation
    assert list(np.asarray(k.pairing_is_one(jnp.asarray(got)))) == \
        [True, False]


@slow
def test_miller_and_agg_kernels_interpret_slices_conv():
    """MEGA_CONV=slices switches _conv inside the Miller AND aggregation
    kernels too (the line-eval and tree-reduction shapes the unit
    bit-identity test can't reach) — both must stay value-identical to
    the XLA path under the knob, and the whole two-kernel pairing must
    still separate valid from tampered."""
    sig, (hx, hy), pk = _committee_workload()
    want = np.asarray(k._bls_miller_opt(sig, hx, hy, pk))
    tag = b"agg-mega-slices"
    keys = [ref.bls_keygen(tag + bytes([j])) for j in range(4)]
    sigs = [ref.bls_sign(tag, sk) for sk, _ in keys]
    sx, sy, sm = k.g1_committee_to_limbs([sigs, sigs[:2]], 4)
    want_g1 = k.aggregate_g1_proj(jnp.asarray(sx), jnp.asarray(sy),
                                  jnp.asarray(sm))
    with _mega_conv("slices"):
        got = np.asarray(m.miller_f(sig, hx, hy, pk, interpret=True))
        got_g1 = m.aggregate_proj(jnp.asarray(sx), jnp.asarray(sy),
                                  jnp.asarray(sm), fp2=False,
                                  interpret=True)
    assert (_f_vals(want) == _f_vals(got)).all()
    assert list(np.asarray(k.pairing_is_one(jnp.asarray(got)))) == \
        [True, False]
    assert np.asarray(k.FP.eq(k.FP.mul(want_g1[0], got_g1[2]),
                              k.FP.mul(got_g1[0], want_g1[2]))).all()
    assert np.asarray(k.FP.eq(k.FP.mul(want_g1[1], got_g1[2]),
                              k.FP.mul(got_g1[1], want_g1[2]))).all()


@slow
def test_aggregation_mega_kernel_interpret_matches_xla():
    """The tree-reduction kernels reproduce the XLA masked projective
    sums (same rational point: affine cross-multiplication equality),
    and the aggregates verify end-to-end."""
    tag = b"agg-mega"
    keys = [ref.bls_keygen(tag + bytes([j])) for j in range(5)]
    sigs = [ref.bls_sign(tag, sk) for sk, _ in keys]
    pks = [pk for _, pk in keys]
    sx, sy, sm = k.g1_committee_to_limbs([sigs, sigs[:3]], 5)
    gx, gy, gm = k.g2_committee_to_limbs([pks, pks[:3]], 5)
    want_g1 = k.aggregate_g1_proj(jnp.asarray(sx), jnp.asarray(sy),
                                  jnp.asarray(sm))
    got_g1 = m.aggregate_proj(jnp.asarray(sx), jnp.asarray(sy),
                              jnp.asarray(sm), fp2=False, interpret=True)
    want_g2 = k.aggregate_g2_proj(jnp.asarray(gx), jnp.asarray(gy),
                                  jnp.asarray(gm))
    got_g2 = m.aggregate_proj(jnp.asarray(gx), jnp.asarray(gy),
                              jnp.asarray(gm), fp2=True, interpret=True)
    assert np.asarray(k.FP.eq(k.FP.mul(want_g1[0], got_g1[2]),
                              k.FP.mul(got_g1[0], want_g1[2]))).all()
    assert np.asarray(k.FP.eq(k.FP.mul(want_g1[1], got_g1[2]),
                              k.FP.mul(got_g1[1], want_g1[2]))).all()
    assert np.asarray(k.fp2_eq(k.fp2_mul(want_g2[0], got_g2[2]),
                               k.fp2_mul(got_g2[0], want_g2[2]))).all()
    assert np.asarray(k.fp2_eq(k.fp2_mul(want_g2[1], got_g2[2]),
                               k.fp2_mul(got_g2[1], want_g2[2]))).all()
    hx, hy, _ = k.g1_to_limbs([ref.hash_to_g1(tag)] * 2)
    f = k._bls_miller_opt(got_g1, jnp.asarray(hx), jnp.asarray(hy), got_g2)
    assert list(np.asarray(k.pairing_is_one(f))) == [True, True]


@slow
def test_aggregation_mega_kernel_multi_group_batch():
    """Batches above AGG_LANES split into multiple lane groups walked by
    the pallas grid (Mosaic rejects lane blocks smaller than the array's
    lane dim — the r4 TPU probe failure); the grouped path must agree
    with the XLA reduction on every lane, including the pad tail."""
    tag = b"agg-mega-groups"
    keys = [ref.bls_keygen(tag + bytes([j])) for j in range(3)]
    sigs = [ref.bls_sign(tag, sk) for sk, _ in keys]
    B = m.AGG_LANES + 6  # two groups, non-multiple batch -> pad tail
    rows = [sigs if b % 3 else sigs[:2] for b in range(B)]
    sx, sy, sm = k.g1_committee_to_limbs(rows, 3)
    want = k.aggregate_g1_proj(jnp.asarray(sx), jnp.asarray(sy),
                               jnp.asarray(sm))
    got = m.aggregate_proj(jnp.asarray(sx), jnp.asarray(sy),
                           jnp.asarray(sm), fp2=False, interpret=True)
    # cross-multiplication equality is vacuous at Z == 0: first prove no
    # lane came back as the unwritten all-zero block (the exact failure
    # this test guards — a group whose output block is never written)
    assert not np.asarray(k.FP.is_zero(got[2])).any()
    assert np.asarray(k.FP.eq(k.FP.mul(want[0], got[2]),
                              k.FP.mul(got[0], want[2]))).all()
    assert np.asarray(k.FP.eq(k.FP.mul(want[1], got[2]),
                              k.FP.mul(got[1], want[2]))).all()
