"""Blob chunk codec: round-trips, layout, and reference edge cases."""

import random

import numpy as np
import pytest

from gethsharding_tpu.utils.blob import (
    CHUNK_SIZE,
    RawBlob,
    deserialize_blobs,
    serialize_blobs,
    serialize_blobs_np,
)


def test_single_small_blob_layout():
    blob = RawBlob(data=b"\x01\x02\x03", skip_evm=False)
    out = serialize_blobs([blob])
    assert len(out) == 32
    assert out[0] == 3  # terminal length indicator
    assert out[1:4] == b"\x01\x02\x03"
    assert out[4:] == b"\x00" * 28


def test_skip_evm_flag_bit():
    blob = RawBlob(data=b"\xff", skip_evm=True)
    out = serialize_blobs([blob])
    assert out[0] == 0x80 | 1
    round_tripped = deserialize_blobs(out)
    assert round_tripped[0].skip_evm is True
    assert round_tripped[0].data == b"\xff"


def test_exact_multiple_of_31():
    blob = RawBlob(data=bytes(range(62)))  # exactly 2 chunks
    out = serialize_blobs([blob])
    assert len(out) == 64
    assert out[0] == 0  # non-terminal
    assert out[32] == 31  # terminal with full 31 bytes
    assert deserialize_blobs(out)[0].data == blob.data


def test_multi_blob_roundtrip_randomized():
    rng = random.Random(42)
    for _ in range(20):
        blobs = [
            RawBlob(
                data=rng.randbytes(rng.randint(1, 200)),
                skip_evm=rng.random() < 0.5,
            )
            for _ in range(rng.randint(1, 8))
        ]
        out = serialize_blobs(blobs)
        assert len(out) % CHUNK_SIZE == 0
        back = deserialize_blobs(out)
        assert [b.data for b in back] == [b.data for b in blobs]
        assert [b.skip_evm for b in back] == [b.skip_evm for b in blobs]


def test_numpy_serializer_matches_scalar():
    rng = random.Random(7)
    blobs = [RawBlob(data=rng.randbytes(n), skip_evm=n % 2 == 0)
             for n in (1, 30, 31, 32, 61, 62, 63, 100)]
    scalar = serialize_blobs(blobs)
    vec = serialize_blobs_np(blobs)
    assert vec.shape == (len(scalar) // 32, 32)
    assert bytes(vec.tobytes()) == scalar


def test_empty_blob_emits_no_chunks():
    assert serialize_blobs([RawBlob(data=b"")]) == b""
    assert serialize_blobs_np([RawBlob(data=b"")]).shape == (0, 32)
