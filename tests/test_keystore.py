"""Encrypted keystore tests: V3 round-trip, wrong password, identity
persistence across restarts (parity: accounts/keystore passphrase_test.go
patterns; light scrypt params for speed)."""

import json

import pytest

from gethsharding_tpu.crypto import secp256k1
from gethsharding_tpu.mainchain.keystore import (
    LIGHT_SCRYPT_N,
    LIGHT_SCRYPT_P,
    Keystore,
    KeystoreError,
    decrypt_key,
    encrypt_key,
)


def light_store(tmp_path):
    return Keystore(tmp_path / "keystore", scrypt_n=LIGHT_SCRYPT_N,
                    scrypt_p=LIGHT_SCRYPT_P)


def test_encrypt_decrypt_round_trip():
    priv = 0xDEADBEEF1234
    obj = encrypt_key(priv, "pass-phrase", scrypt_n=LIGHT_SCRYPT_N,
                      scrypt_p=LIGHT_SCRYPT_P)
    assert obj["version"] == 3
    assert obj["crypto"]["cipher"] == "aes-128-ctr"
    assert obj["address"] == secp256k1.priv_to_address(priv).hex_str[2:]
    assert decrypt_key(obj, "pass-phrase") == priv


def test_wrong_password_rejected_by_mac():
    obj = encrypt_key(7, "right", scrypt_n=LIGHT_SCRYPT_N,
                      scrypt_p=LIGHT_SCRYPT_P)
    with pytest.raises(KeystoreError, match="could not decrypt"):
        decrypt_key(obj, "wrong")


def test_pbkdf2_kdf_supported():
    import hashlib
    import secrets as s

    # construct a pbkdf2 V3 file by hand (geth's alternate KDF)
    priv = 0x1234
    password, salt, iv = "pw", s.token_bytes(32), s.token_bytes(16)
    derived = hashlib.pbkdf2_hmac("sha256", password.encode(), salt, 1024, 32)
    from gethsharding_tpu.crypto.keccak import keccak256
    from gethsharding_tpu.mainchain.keystore import _aes128_ctr

    ciphertext = _aes128_ctr(derived[:16], iv, priv.to_bytes(32, "big"))
    obj = {
        "address": secp256k1.priv_to_address(priv).hex_str[2:],
        "crypto": {
            "cipher": "aes-128-ctr",
            "ciphertext": ciphertext.hex(),
            "cipherparams": {"iv": iv.hex()},
            "kdf": "pbkdf2",
            "kdfparams": {"dklen": 32, "c": 1024, "prf": "hmac-sha256",
                          "salt": salt.hex()},
            "mac": keccak256(derived[16:32] + ciphertext).hex(),
        },
        "id": "x", "version": 3,
    }
    assert decrypt_key(obj, password) == priv


def test_store_unlock_and_accounts_listing(tmp_path):
    ks = light_store(tmp_path)
    stored = ks.store(42, "hunter2")
    assert ks.accounts()[0].address == stored.address
    assert ks.unlock(stored.address, "hunter2") == 42
    with pytest.raises(KeystoreError):
        ks.unlock(stored.address, "wrong")
    # file content is valid V3 JSON with restrictive permissions
    obj = json.loads(stored.path.read_text())
    assert obj["version"] == 3


def test_identity_survives_restart(tmp_path):
    ks = light_store(tmp_path)
    priv1 = ks.load_or_create("node-password")
    # "restart": a fresh Keystore over the same directory
    ks2 = light_store(tmp_path)
    priv2 = ks2.load_or_create("node-password")
    assert priv1 == priv2
    assert (secp256k1.priv_to_address(priv1)
            == secp256k1.priv_to_address(priv2))


def test_corrupt_files_skipped(tmp_path):
    ks = light_store(tmp_path)
    ks.store(9, "pw")
    (tmp_path / "keystore" / "garbage").write_text("not json")
    assert len(ks.accounts()) == 1


def test_node_identity_persists_across_restart(tmp_path, monkeypatch):
    """A ShardNode with --datadir/--password keeps its address (and thus its
    notary registration) across a restart."""
    import gethsharding_tpu.mainchain.keystore as ksmod
    from gethsharding_tpu.node.backend import ShardNode
    from gethsharding_tpu.smc.chain import SimulatedMainchain

    # light scrypt for test speed
    monkeypatch.setattr(ksmod, "STANDARD_SCRYPT_N", LIGHT_SCRYPT_N)
    monkeypatch.setattr(ksmod, "STANDARD_SCRYPT_P", LIGHT_SCRYPT_P)

    backend = SimulatedMainchain()
    node = ShardNode(actor="observer", backend=backend,
                     data_dir=str(tmp_path), password="pw")
    addr1 = node.client.account()
    node2 = ShardNode(actor="observer", backend=backend,
                      data_dir=str(tmp_path), password="pw")
    assert node2.client.account() == addr1

    with pytest.raises(KeystoreError):
        ShardNode(actor="observer", backend=backend,
                  data_dir=str(tmp_path), password="wrong")
