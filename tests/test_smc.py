"""SMC state machine tests, mirroring the reference contract suite
(`sharding/contracts/sharding_manager_test.go:233-742`) scenario by scenario
on the SimulatedMainchain fixture."""

import pytest

from gethsharding_tpu.params import Config, ETHER
from gethsharding_tpu.smc import SMC, SMCRevert, SimulatedMainchain
from gethsharding_tpu.utils.hexbytes import Address20, Hash32


DEPOSIT = 1000 * ETHER


def make_accounts(n):
    return [Address20(i + 1) for i in range(n)]


def helper(n_accounts=1, config=None):
    chain = SimulatedMainchain(config=config or Config())
    accounts = make_accounts(n_accounts)
    for acct in accounts:
        chain.fund(acct, 2000 * ETHER)
    return chain, accounts


def register_notaries(chain, accounts, start, end):
    for acct in accounts[start:end]:
        chain.register_notary(acct)
        chain.commit()


# -- registration (TestNotaryRegister & co) -------------------------------


def test_contract_creation():
    chain, _ = helper()
    assert chain.smc.notary_pool_length == 0
    assert chain.smc.shard_count == 100


def test_default_config():
    # mirrors SMC constants (.sol:56-73) — the single source of truth check
    config = Config()
    assert config.shard_count == 100
    assert config.period_length == 5
    assert config.notary_deposit == 1000 * ETHER
    assert config.notary_lockup_length == 16128
    assert config.committee_size == 135
    assert config.quorum_size == 90
    assert config.lookahead_length == 4
    assert config.challenge_period == 25


def test_notary_register():
    chain, accounts = helper(3)
    register_notaries(chain, accounts, 0, 3)
    assert chain.smc.notary_pool_length == 3
    for i, acct in enumerate(accounts):
        entry = chain.notary_registry(acct)
        assert entry.deposited is True
        assert entry.pool_index == i
    assert chain.smc.balance == 3 * DEPOSIT


def test_notary_register_insufficient_ether():
    chain, accounts = helper(1)
    with pytest.raises(SMCRevert, match="NOTARY_DEPOSIT"):
        chain.register_notary(accounts[0], value=100 * ETHER)
    assert chain.smc.notary_pool_length == 0


def test_notary_double_registers():
    chain, accounts = helper(1)
    chain.register_notary(accounts[0])
    chain.commit()
    with pytest.raises(SMCRevert, match="already deposited"):
        chain.register_notary(accounts[0])
    assert chain.smc.notary_pool_length == 1


def test_notary_deregister():
    chain, accounts = helper(1)
    register_notaries(chain, accounts, 0, 1)
    chain.fast_forward(1)
    chain.deregister_notary(accounts[0])
    chain.commit()
    assert chain.smc.notary_pool_length == 0
    entry = chain.notary_registry(accounts[0])
    assert entry.deregistered_period == chain.current_period()


def test_notary_deregister_then_register():
    # the empty-slot stack quirk: with only one freed slot, stackPop reverts
    # (`require(emptySlotsStackTop > 1)`, .sol:262), so re-registration
    # appends a fresh slot instead of reusing index 0
    chain, accounts = helper(2)
    register_notaries(chain, accounts, 0, 1)
    chain.fast_forward(1)
    chain.deregister_notary(accounts[0])
    chain.commit()
    assert chain.smc.notary_pool_length == 0
    with pytest.raises(SMCRevert, match="stackPop"):
        chain.register_notary(accounts[1])


def test_slot_reuse_with_two_freed_slots():
    chain, accounts = helper(3)
    register_notaries(chain, accounts, 0, 2)
    chain.fast_forward(1)
    chain.deregister_notary(accounts[0])
    chain.commit()
    chain.deregister_notary(accounts[1])
    chain.commit()
    # two freed slots: top == 2, pop returns the most recently freed (index 1)
    chain.register_notary(accounts[2])
    chain.commit()
    assert chain.notary_registry(accounts[2]).pool_index == 1
    assert chain.smc.notary_pool[1] == accounts[2]


def test_notary_release():
    # lockup shrunk via config so the test doesn't mine 80k blocks; the
    # default 16128-period value is asserted in test_default_config
    config = Config(notary_lockup_length=4)
    chain, accounts = helper(1, config)
    register_notaries(chain, accounts, 0, 1)
    balance_after_deposit = chain.balance_of(accounts[0])
    chain.fast_forward(1)
    chain.deregister_notary(accounts[0])
    chain.commit()
    chain.fast_forward(config.notary_lockup_length + 1)
    chain.release_notary(accounts[0])
    chain.commit()
    assert chain.notary_registry(accounts[0]) is None
    assert chain.balance_of(accounts[0]) == balance_after_deposit + DEPOSIT


def test_notary_instant_release():
    chain, accounts = helper(1)
    register_notaries(chain, accounts, 0, 1)
    chain.fast_forward(1)
    chain.deregister_notary(accounts[0])
    chain.commit()
    with pytest.raises(SMCRevert, match="lockup"):
        chain.release_notary(accounts[0])
    assert chain.notary_registry(accounts[0]).deposited is True


def test_release_without_deregister():
    chain, accounts = helper(1)
    register_notaries(chain, accounts, 0, 1)
    with pytest.raises(SMCRevert, match="not deregistered"):
        chain.release_notary(accounts[0])


# -- committee sampling (TestCommitteeListsAreDifferent & co) --------------


def test_committee_lists_are_different():
    chain, accounts = helper(100)
    register_notaries(chain, accounts, 0, 100)
    # sampled committees for shard 0 vs shard 1 must differ somewhere
    sampled0 = [
        chain.smc.get_notary_in_committee_view(accounts[i], 0, chain.block_number)
        for i in range(5)
    ]
    sampled1 = [
        chain.smc.get_notary_in_committee_view(accounts[i], 1, chain.block_number)
        for i in range(5)
    ]
    assert sampled0 != sampled1


def test_get_committee_with_non_member():
    chain, accounts = helper(11)
    register_notaries(chain, accounts, 0, 10)
    for _ in range(10):
        sampled = chain.get_notary_in_committee(accounts[10], 0)
        assert sampled != accounts[10]


def test_get_committee_within_same_period():
    chain, accounts = helper(1)
    register_notaries(chain, accounts, 0, 1)
    sampled = chain.get_notary_in_committee(accounts[0], 0)
    assert sampled == accounts[0]


def test_get_committee_after_deregister():
    chain, accounts = helper(10)
    register_notaries(chain, accounts, 0, 10)
    chain.fast_forward(1)
    chain.deregister_notary(accounts[0])
    chain.commit()
    chain.fast_forward(1)
    # deregistered notary's slot is zeroed; sampling may hit the hole but
    # must never return the deregistered address as an active member
    for i in range(1, 10):
        sampled = chain.get_notary_in_committee(accounts[i], 0)
        assert sampled != accounts[0]


def test_sampling_is_deterministic():
    chain, accounts = helper(20)
    register_notaries(chain, accounts, 0, 20)
    a = chain.get_notary_in_committee(accounts[3], 7)
    b = chain.get_notary_in_committee(accounts[3], 7)
    assert a == b


# -- addHeader (TestNormalAddHeader & co) ----------------------------------


def test_normal_add_header():
    chain, accounts = helper(1)
    chain.fast_forward(1)
    period = chain.current_period()
    root = Hash32(b"\x01" * 32)
    chain.add_header(accounts[0], 0, period, root)
    chain.commit()
    record = chain.collation_record(0, period)
    assert record.chunk_root == root
    assert record.proposer == accounts[0]
    assert record.is_elected is False
    assert chain.last_submitted_collation(0) == period


def test_add_two_headers_at_same_period():
    chain, accounts = helper(2)
    chain.fast_forward(1)
    period = chain.current_period()
    chain.add_header(accounts[0], 0, period, Hash32(b"\x01" * 32))
    with pytest.raises(SMCRevert, match="already has"):
        chain.add_header(accounts[1], 0, period, Hash32(b"\x02" * 32))


def test_add_headers_at_wrong_period():
    chain, accounts = helper(1)
    chain.fast_forward(1)
    wrong = chain.current_period() + 1
    with pytest.raises(SMCRevert, match="not current"):
        chain.add_header(accounts[0], 0, wrong, Hash32(b"\x01" * 32))


def test_add_header_shard_range():
    chain, accounts = helper(1)
    chain.fast_forward(1)
    with pytest.raises(SMCRevert, match="out of range"):
        chain.add_header(accounts[0], 100, chain.current_period(), Hash32())


def test_add_header_resets_votes():
    chain, accounts = helper(1)
    register_notaries(chain, accounts, 0, 1)
    chain.fast_forward(1)
    period = chain.current_period()
    root = Hash32(b"\x01" * 32)
    chain.add_header(accounts[0], 0, period, root)
    chain.commit()
    chain.submit_vote(accounts[0], 0, period, 0, root)
    assert chain.smc.get_vote_count(0) == 1
    chain.fast_forward(1)
    chain.add_header(accounts[0], 0, chain.current_period(), Hash32(b"\x02" * 32))
    assert chain.smc.get_vote_count(0) == 0


# -- submitVote (TestSubmitVote & co) --------------------------------------


def vote_setup(quorum=None):
    config = Config(quorum_size=quorum) if quorum else Config()
    chain, accounts = helper(1, config)
    register_notaries(chain, accounts, 0, 1)
    chain.fast_forward(1)
    period = chain.current_period()
    root = Hash32(b"\x09" * 32)
    chain.add_header(accounts[0], 0, period, root)
    chain.commit()
    return chain, accounts, period, root


def test_submit_vote():
    chain, accounts, period, root = vote_setup()
    chain.submit_vote(accounts[0], 0, period, 0, root)
    assert chain.smc.get_vote_count(0) == 1
    assert chain.smc.has_voted(0, 0) is True
    # vote word: bit 255 set + count 1 in low byte
    assert chain.smc.current_vote[0] == (1 << 255) + 1


def test_submit_vote_twice():
    chain, accounts, period, root = vote_setup()
    chain.submit_vote(accounts[0], 0, period, 0, root)
    with pytest.raises(SMCRevert, match="already voted"):
        chain.submit_vote(accounts[0], 0, period, 0, root)
    assert chain.smc.get_vote_count(0) == 1


def test_submit_vote_by_non_eligible_notary():
    chain, accounts, period, root = vote_setup()
    outsider = Address20(0xBEEF)
    chain.fund(outsider, 2000 * ETHER)
    with pytest.raises(SMCRevert, match="not a deposited notary"):
        chain.submit_vote(outsider, 0, period, 0, root)


def test_submit_vote_without_a_header():
    chain, accounts = helper(1)
    register_notaries(chain, accounts, 0, 1)
    chain.fast_forward(1)
    period = chain.current_period()
    with pytest.raises(SMCRevert, match="no collation submitted"):
        chain.submit_vote(accounts[0], 1, period, 0, Hash32(b"\x09" * 32))


def test_submit_vote_with_invalid_args():
    chain, accounts, period, root = vote_setup()
    with pytest.raises(SMCRevert, match="out of range"):
        chain.submit_vote(accounts[0], 100, period, 0, root)
    with pytest.raises(SMCRevert, match="committee range"):
        chain.submit_vote(accounts[0], 0, period, 135, root)
    with pytest.raises(SMCRevert, match="chunk root"):
        chain.submit_vote(accounts[0], 0, period, 0, Hash32(b"\xaa" * 32))
    with pytest.raises(SMCRevert, match="not current"):
        chain.submit_vote(accounts[0], 0, period + 1, 0, root)


def test_quorum_marks_elected():
    # lower quorum to 2 so a single-notary committee can reach it via two
    # distinct committee indices (sample size 1 => always eligible)
    chain, accounts, period, root = vote_setup(quorum=2)
    chain.submit_vote(accounts[0], 0, period, 0, root)
    assert chain.collation_record(0, period).is_elected is False
    assert chain.last_approved_collation(0) == 0
    chain.submit_vote(accounts[0], 0, period, 1, root)
    assert chain.smc.get_vote_count(0) == 2
    assert chain.collation_record(0, period).is_elected is True
    assert chain.last_approved_collation(0) == period


def test_vote_word_bitfield_layout():
    chain, accounts, period, root = vote_setup(quorum=135)
    for index in (0, 1, 7, 100, 134):
        chain.submit_vote(accounts[0], 0, period, index, root)
    votes = chain.smc.current_vote[0]
    assert votes % 256 == 5  # count in low byte
    for index in (0, 1, 7, 100, 134):
        assert (votes >> (255 - index)) & 1 == 1
    assert chain.smc.has_voted(0, 2) is False


def test_events_emitted():
    chain, accounts, period, root = vote_setup()
    names = [e.name for e in chain.smc.events]
    assert "NotaryRegistered" in names
    assert "HeaderAdded" in names
    chain.submit_vote(accounts[0], 0, period, 0, root)
    assert chain.smc.events[-1].name == "VoteSubmitted"


def test_committee_context_matches_per_shard_view():
    """Local all-shard eligibility from committee_context must agree with
    the per-shard get_notary_in_committee view for every (notary, shard)."""
    from gethsharding_tpu.crypto.keccak import keccak256
    from gethsharding_tpu.mainchain.accounts import AccountManager
    from gethsharding_tpu.params import Config, ETHER
    from gethsharding_tpu.smc.chain import SimulatedMainchain

    config = Config(shard_count=16)
    chain = SimulatedMainchain(config=config)
    manager = AccountManager()
    accounts = [manager.new_account(seed=bytes([i])) for i in range(7)]
    for acct in accounts:
        chain.fund(acct.address, 2000 * ETHER)
        chain.register_notary(acct.address)
    chain.fast_forward(2)
    # a deregistration mid-stream exercises the emptied-slot path
    chain.deregister_notary(accounts[2].address)
    chain.fast_forward(1)

    ctx = chain.committee_context()
    for acct in accounts:
        entry = chain.notary_registry(acct.address)
        pool_index = entry.pool_index if entry is not None else 0
        for shard in range(config.shard_count):
            digest = keccak256(ctx["blockhash"]
                               + pool_index.to_bytes(32, "big")
                               + shard.to_bytes(32, "big"))
            slot = int.from_bytes(digest, "big") % ctx["sample_size"]
            member = (ctx["pool"][slot]
                      if slot < len(ctx["pool"]) else None)
            local = member is not None and member == bytes(acct.address)
            view = chain.get_notary_in_committee(acct.address, shard)
            assert local == (view == acct.address), (pool_index, shard)
