"""Data-availability sampling: erasure code, proofs, batched op, wiring.

The acceptance contracts under test:

- RS encode -> drop ANY n-k chunks -> decode reproduces the body;
- batched `das_verify_samples` agrees bit-for-bit with the scalar
  python reference across randomized bodies, withheld chunks, and
  corrupted proofs — including through the serving and failover
  backends;
- a notary in sampled DA mode reaches availability votes with ZERO
  full-body fetches, within the k·chunk_size + proof-overhead byte
  budget per collation, and REFUSES to vote when a sampled chunk is
  corrupted;
- the das.* chaos seams inject (and the retry ladder absorbs) faults,
  and a spec naming them on a node that never wired them is reported
  by `unwired_seams`;
- the `shard_getSample` / `shard_daStatus` RPC surface serves
  proof-carrying samples a light client can verify locally.
"""

import itertools
import os
import random

import pytest

from gethsharding_tpu.das import erasure, proofs, sampler
from gethsharding_tpu.das.erasure import (DAS_CHUNK_SIZE, ErasureError,
                                          extend_body, recover_body,
                                          rs_decode, rs_encode)
from gethsharding_tpu.das.proofs import (MAX_PROOF_DEPTH, chunk_leaf,
                                         merkle_levels, merkle_proof,
                                         verify_sample, verify_samples)
from gethsharding_tpu.das.service import (DASService, commitment_digest,
                                          verify_commitment)
from gethsharding_tpu.sigbackend import get_backend


# -- the erasure code ------------------------------------------------------


def test_gf_tables_roundtrip():
    for a in range(1, 256):
        assert erasure.gf_mul(a, erasure.gf_inv(a)) == 1
    assert erasure.gf_mul(0, 77) == 0
    assert erasure.gf_mul(77, 1) == 77
    with pytest.raises(ZeroDivisionError):
        erasure.gf_inv(0)


@pytest.mark.parametrize("k,m", [(1, 1), (2, 1), (3, 2), (4, 4)])
def test_rs_any_k_of_n_roundtrip(k, m):
    """Drop EVERY possible n-k subset; any k survivors reconstruct."""
    rng = random.Random(k * 100 + m)
    chunks = [bytes(rng.randrange(256) for _ in range(48))
              for _ in range(k)]
    ext = rs_encode(chunks, m)
    assert ext[:k] == chunks  # systematic
    n = k + m
    for drop in itertools.combinations(range(n), m):
        shares = {i: ext[i] for i in range(n) if i not in drop}
        assert rs_decode(shares, k, n) == chunks, drop


def test_rs_too_few_shares_is_an_error():
    ext = rs_encode([b"\x01" * 16, b"\x02" * 16], 2)
    with pytest.raises(ErasureError):
        rs_decode({0: ext[0]}, 2, 4)


@pytest.mark.parametrize("size", [
    0, 1, DAS_CHUNK_SIZE - 1, DAS_CHUNK_SIZE, DAS_CHUNK_SIZE + 1,
    3 * DAS_CHUNK_SIZE + 117,
])
def test_extend_recover_body_roundtrip(size):
    body = os.urandom(size)
    xb = extend_body(body)
    assert xb.n > xb.k >= 1
    assert all(len(c) == DAS_CHUNK_SIZE for c in xb.chunks)
    # drop the maximum survivable set: n - k arbitrary chunks
    rng = random.Random(size)
    keep = sorted(rng.sample(range(xb.n), xb.k))
    shares = {i: xb.chunks[i] for i in keep}
    assert recover_body(shares, xb.k, xb.n, xb.body_len) == body


def test_extend_body_caps_total_chunks():
    with pytest.raises(ErasureError):
        extend_body(b"\x00" * (200 * DAS_CHUNK_SIZE), parity_ratio=0.5)


# -- the sampler + soundness accounting ------------------------------------


def test_sampler_is_deterministic_distinct_and_in_range():
    seed = sampler.sample_seed(b"\xaa" * 20, 5, 17, b"\x01" * 32)
    got = sampler.sample_indices(seed, 16, 96)
    assert got == sampler.sample_indices(seed, 16, 96)
    assert len(got) == 16 == len(set(got))
    assert all(0 <= i < 96 for i in got)
    # a different notary samples a different set
    other = sampler.sample_indices(
        sampler.sample_seed(b"\xbb" * 20, 5, 17, b"\x01" * 32), 16, 96)
    assert got != other
    # degenerate shapes
    assert sampler.sample_indices(seed, 99, 7) == list(range(7))
    assert sampler.sample_indices(seed, 4, 0) == []


def test_detection_probability_accounting():
    # n=4, k_data=2: the minimal adversary withholds 3, leaving 1
    # available; one sample misses with 1/4 -> detects with 3/4
    assert abs(sampler.detection_probability(1, 4, 2) - 0.75) < 1e-12
    # monotone in k and in checkers
    p8 = sampler.detection_probability(8, 96, 64)
    p16 = sampler.detection_probability(16, 96, 64)
    assert p16 > p8
    committee = sampler.detection_probability(8, 96, 64, checkers=5)
    assert committee > p8
    rows = sampler.soundness_table(96, 64, ks=(4, 8), checkers=3)
    assert rows[0]["k"] == 4 and "p_detect_committee" in rows[0]
    with pytest.raises(ValueError):
        sampler.detection_probability(4, 0, 0)


# -- scalar proofs ---------------------------------------------------------


def _committed_blob(size=30000, seed=7):
    rng = random.Random(seed)
    body = bytes(rng.randrange(256) for _ in range(size))
    xb = extend_body(body)
    levels = merkle_levels([chunk_leaf(c) for c in xb.chunks])
    return body, xb, levels, levels[-1][0]


def test_scalar_sample_proofs_roundtrip_and_reject():
    _, xb, levels, root = _committed_blob()
    for i in range(xb.n):
        proof = merkle_proof(levels, i)
        assert len(proof) <= MAX_PROOF_DEPTH
        assert verify_sample(root, i, xb.chunks[i], proof)
        # tampered chunk, wrong index, truncated proof: all fail
        bad = bytes([xb.chunks[i][0] ^ 1]) + xb.chunks[i][1:]
        assert not verify_sample(root, i, bad, proof)
        assert not verify_sample(root, (i + 1) % xb.n, xb.chunks[i],
                                 proof)
        if proof:
            assert not verify_sample(root, i, xb.chunks[i], proof[:-1])
    # malformed rows are verdicts, never exceptions
    proof0 = merkle_proof(levels, 0)
    assert not verify_sample(root, 0, xb.chunks[0][:-1], proof0)
    assert not verify_sample(root, -1, xb.chunks[0], proof0)
    assert not verify_sample(root, 0, xb.chunks[0],
                             (b"\x00" * 31,) + proof0[1:])
    assert not verify_sample(root, 0, xb.chunks[0],
                             proof0 + (b"\x00" * 32,) * MAX_PROOF_DEPTH)
    assert not verify_sample(b"\x01" * 32, 0, xb.chunks[0], proof0)
    assert not verify_sample(root, "zero", xb.chunks[0], proof0)


def test_single_chunk_tree_has_empty_proof():
    xb = extend_body(b"tiny", parity_ratio=0.01)  # k=1, parity>=1 -> n=2
    levels = merkle_levels([chunk_leaf(c) for c in xb.chunks])
    root = levels[-1][0]
    proof = merkle_proof(levels, 0)
    assert len(proof) == 1  # n=2 -> depth-1 tree
    assert verify_sample(root, 0, xb.chunks[0], proof)


# -- the batched op, through every backend layer ---------------------------


def _sample_rows(with_faults=True, seed=13):
    """(chunks, indices, proofs, roots) rows: valid samples from two
    distinct blobs, plus (optionally) every malformed-row class."""
    rng = random.Random(seed)
    rows = []
    for blob_seed in (seed, seed + 1):
        _, xb, levels, root = _committed_blob(
            size=9000 + 7000 * (blob_seed % 2), seed=blob_seed)
        for i in rng.sample(range(xb.n), min(4, xb.n)):
            rows.append((xb.chunks[i], i, merkle_proof(levels, i), root))
    if with_faults:
        _, xb, levels, root = _committed_blob(seed=seed + 2)
        good = merkle_proof(levels, 1)
        tampered = bytes([xb.chunks[1][0] ^ 0xFF]) + xb.chunks[1][1:]
        rows += [
            (tampered, 1, good, root),                       # corrupted
            (b"", 1, (), root),                              # withheld
            (xb.chunks[1], 1, good[:-1], root),              # truncated
            (xb.chunks[1], 1, (b"\x00" * 31,) + good[1:], root),  # ragged
            (xb.chunks[1], 1,
             good + (b"\x00" * 32,) * MAX_PROOF_DEPTH, root),  # too deep
            (xb.chunks[1], 2, good, root),                   # wrong index
            (xb.chunks[1], 1 << 20, good, root),             # out of tree
            (xb.chunks[1], 1, good, b"\x02" * 32),           # wrong root
        ]
    return tuple(map(list, zip(*rows)))


def test_das_verify_samples_scalar_vs_jax_bit_for_bit():
    chunks, indices, prfs, roots = _sample_rows()
    want = get_backend("python").das_verify_samples(
        chunks, indices, prfs, roots)
    assert want.count(False) == 8 and want.count(True) == 8
    jax_backend = get_backend("jax")
    got = jax_backend.das_verify_samples(chunks, indices, prfs, roots)
    assert got == want
    # the per-dispatch wire ledger records the sample plane bytes
    ledger = jax_backend.last_wire
    assert ledger["op"] == "das_verify_samples"
    assert ledger["sample_wire_bytes"] == ledger["wire_bytes"] > 0
    assert ledger["rows"] == len(chunks)
    # empty batch: no dispatch, clean ledger
    assert jax_backend.das_verify_samples([], [], [], []) == []
    assert jax_backend.last_wire is None


def test_das_verify_samples_through_serving_and_failover():
    from gethsharding_tpu.resilience.breaker import FailoverSigBackend
    from gethsharding_tpu.serving import ServingSigBackend
    from gethsharding_tpu.serving.batcher import SERVING_OPS

    assert "das_verify_samples" in SERVING_OPS
    chunks, indices, prfs, roots = _sample_rows()
    want = get_backend("python").das_verify_samples(
        chunks, indices, prfs, roots)
    serving = ServingSigBackend(get_backend("jax"))
    try:
        assert serving.das_verify_samples(chunks, indices, prfs,
                                          roots) == want
        assert serving.batcher.dispatch_counts["das_verify_samples"] == 1
    finally:
        serving.close()
    failover = FailoverSigBackend(get_backend("jax"),
                                  get_backend("python"))
    assert failover.das_verify_samples(chunks, indices, prfs,
                                       roots) == want


def test_das_verify_samples_failover_rides_through_faults():
    """An injected das_verify_samples device fault is served from the
    scalar fallback with identical verdicts."""
    from gethsharding_tpu.metrics import Registry
    from gethsharding_tpu.resilience.breaker import (CircuitBreaker,
                                                     FailoverSigBackend)
    from gethsharding_tpu.resilience.chaos import (ChaosSchedule,
                                                   ChaosSigBackend)

    chunks, indices, prfs, roots = _sample_rows()
    want = get_backend("python").das_verify_samples(
        chunks, indices, prfs, roots)
    schedule = ChaosSchedule(
        seed=3, rules={"backend.das_verify_samples": 2})
    registry = Registry()
    backend = FailoverSigBackend(
        ChaosSigBackend(get_backend("python"), schedule),
        get_backend("python"),
        breaker=CircuitBreaker(name="das-test", fault_threshold=1,
                               reset_s=0.001, registry=registry),
        registry=registry)
    for _ in range(4):  # fault, open, probe, re-closed
        assert backend.das_verify_samples(chunks, indices, prfs,
                                          roots) == want
    assert schedule.injected["backend.das_verify_samples"] >= 1


# -- the service: publish / serve / fetch over shardp2p --------------------


def _service_pair(samples=6, **kwargs):
    from gethsharding_tpu.mainchain.client import SMCClient
    from gethsharding_tpu.p2p.service import Hub, P2PServer
    from gethsharding_tpu.params import Config
    from gethsharding_tpu.smc.chain import SimulatedMainchain

    config = Config()
    chain = SimulatedMainchain(config=config)
    hub = Hub()
    out = []
    for _ in range(2):
        client = SMCClient(backend=chain, config=config)
        svc = DASService(client=client, p2p=P2PServer(hub),
                         samples=samples, fetch_timeout=1.0,
                         fetch_attempts=2, **kwargs)
        svc.start()
        out.append((client, svc))
    return chain, out


class _Record:
    def __init__(self, chunk_root, proposer):
        self.chunk_root = chunk_root
        self.proposer = proposer


def test_service_publish_fetch_verify_end_to_end():
    from gethsharding_tpu.utils.hexbytes import Hash32

    chain, ((prop_client, svc_prop), (not_client, svc_not)) = \
        _service_pair()
    try:
        body = os.urandom(21000)
        root32 = Hash32(b"\x07" * 32)
        commitment = svc_prop.publish(2, 5, root32, body)
        assert verify_commitment(commitment, prop_client.account())
        record = _Record(root32, prop_client.account())
        rows = svc_not.collect_rows(2, 5, record,
                                    bytes(not_client.account()))
        assert rows is not None and len(rows["chunks"]) == 6
        ok = get_backend("python").das_verify_samples(
            rows["chunks"], rows["indices"], rows["proofs"],
            rows["roots"])
        assert all(ok)
        assert svc_not.note_verdicts(ok) == 0
        # fetched bytes stay within the k-sample budget
        assert svc_not.bytes_fetched <= 6 * (DAS_CHUNK_SIZE
                                             + 32 * MAX_PROOF_DEPTH + 40)
        # wrong proposer: the commitment is rejected, never returned
        svc_not._commitments.clear()
        impostor = _Record(root32, not_client.account())
        assert svc_not.fetch_commitment(2, 5, root32,
                                        impostor.proposer) is None
        assert svc_not.m_commitments_rejected.value >= 1
    finally:
        for _, svc in ((None, svc_prop), (None, svc_not)):
            svc.stop()


def test_service_withheld_and_corrupted_chunks_fail_the_check():
    from dataclasses import replace

    from gethsharding_tpu.utils.hexbytes import Hash32

    chain, ((prop_client, svc_prop), (not_client, svc_not)) = \
        _service_pair()
    try:
        body = os.urandom(15000)
        root32 = Hash32(b"\x09" * 32)
        commitment = svc_prop.publish(1, 3, root32, body)
        record = _Record(root32, prop_client.account())
        das_root = bytes(commitment.das_root)

        # CORRUPTED PARITY: the publisher serves a tampered parity
        # chunk for the signed commitment — its recomputed leaf no
        # longer folds to das_root, so the sample verdict is False
        xb, levels = svc_prop._blobs[das_root]
        tampered = list(xb.chunks)
        tampered[-1] = b"\xee" * DAS_CHUNK_SIZE  # last chunk IS parity
        svc_prop._blobs[das_root] = (replace(xb,
                                             chunks=tuple(tampered)),
                                     levels)
        rows = svc_not.collect_rows(1, 3, record,
                                    bytes(not_client.account()))
        assert rows is not None
        # force the corrupted index into the sampled set
        rows["chunks"].append(tampered[-1])
        rows["indices"].append(xb.n - 1)
        rows["proofs"].append(merkle_proof(levels, xb.n - 1))
        rows["roots"].append(das_root)
        ok = get_backend("python").das_verify_samples(
            rows["chunks"], rows["indices"], rows["proofs"],
            rows["roots"])
        assert ok[-1] is False  # the corrupted chunk is detected
        assert svc_not.note_verdicts(ok) >= 1

        # WITHHELD: the publisher forgets the blob entirely — samples
        # never arrive, collect_rows synthesizes invalid rows, and the
        # whole check fails instead of silently shrinking k
        svc_not._recv_samples.clear()
        del svc_prop._blobs[das_root]
        rows = svc_not.collect_rows(1, 3, record,
                                    bytes(not_client.account()))
        assert rows is not None  # the commitment is still known
        ok = verify_samples(rows["chunks"], rows["indices"],
                            rows["proofs"], rows["roots"])
        assert not any(ok)
    finally:
        svc_prop.stop()
        svc_not.stop()


def test_sample_admission_rejects_garbage_first_responder():
    """Content-verified delivery: a hostile peer that answers a sample
    request FIRST with garbage must not occupy the slot — the honest
    response behind it still lands, and the garbage costs a counter."""
    from gethsharding_tpu.p2p.messages import DASampleResponse
    from gethsharding_tpu.p2p.service import Message, Peer
    from gethsharding_tpu.utils.hexbytes import Hash32

    chain, ((prop_client, svc_prop), (not_client, svc_not)) = \
        _service_pair()
    try:
        commitment = svc_prop.publish(3, 1, Hash32(b"\x0d" * 32),
                                      os.urandom(9000))
        root = bytes(commitment.das_root)
        xb, levels = svc_prop._blobs[root]
        key = (root, 0)
        svc_not._want_samples.add(key)
        hostile = Message(Peer(99), DASampleResponse(
            das_root=root, index=0, chunk=b"\xaa" * DAS_CHUNK_SIZE,
            proof=merkle_proof(levels, 0)))
        svc_not._on_sample_response(hostile)
        assert key not in svc_not._recv_samples  # garbage NOT admitted
        assert svc_not.m_samples_rejected.value >= 1
        honest = Message(Peer(1), DASampleResponse(
            das_root=root, index=0, chunk=xb.chunks[0],
            proof=merkle_proof(levels, 0)))
        svc_not._on_sample_response(honest)
        assert svc_not._recv_samples[key][0] == xb.chunks[0]
    finally:
        svc_prop.stop()
        svc_not.stop()


def test_commitment_admission_forged_first_does_not_shadow():
    """A forged commitment response that wins the race must not evict
    the genuine one: both park, validation picks the genuine one."""
    from dataclasses import replace as dc_replace

    from gethsharding_tpu.p2p.messages import DASCommitmentResponse
    from gethsharding_tpu.p2p.service import Message, Peer
    from gethsharding_tpu.utils.hexbytes import Hash32

    chain, ((prop_client, svc_prop), (not_client, svc_not)) = \
        _service_pair()
    try:
        root32 = Hash32(b"\x0e" * 32)
        commitment = svc_prop.publish(4, 2, root32, os.urandom(9000))
        genuine = DASCommitmentResponse(
            shard_id=4, period=2, chunk_root=commitment.chunk_root,
            das_root=commitment.das_root, k=commitment.k,
            n=commitment.n, body_len=commitment.body_len,
            signature=commitment.signature)
        forged = dc_replace(genuine, das_root=b"\x66" * 32)
        key = (4, 2)
        svc_not._want_commitments.add(key)
        svc_not._on_commitment_response(Message(Peer(99), forged))
        svc_not._on_commitment_response(Message(Peer(1), genuine))
        got = svc_not.fetch_commitment(4, 2, root32,
                                       prop_client.account())
        assert got is not None
        assert bytes(got.das_root) == bytes(commitment.das_root)
        assert svc_not.m_commitments_rejected.value >= 1
    finally:
        svc_prop.stop()
        svc_not.stop()


def test_chaos_das_seams_inject_and_retries_absorb():
    """A das.sample_fetch=1 rule faults the FIRST fetch attempt; the
    retry ladder re-broadcasts and the check still completes. The
    das.parity_publish seam faults the publish itself."""
    from gethsharding_tpu.resilience.chaos import parse_spec
    from gethsharding_tpu.utils.hexbytes import Hash32

    schedule = parse_spec(
        "seed=5,das.sample_fetch=1,das.parity_publish=1")
    chain, ((prop_client, svc_prop), (not_client, svc_not)) = \
        _service_pair()
    svc_prop.chaos = schedule
    svc_not.chaos = schedule
    try:
        root32 = Hash32(b"\x0c" * 32)
        # first publish faults at the parity_publish seam
        with pytest.raises(ConnectionError):
            svc_prop.publish(0, 2, root32, b"x" * 9000)
        commitment = svc_prop.publish(0, 2, root32, b"x" * 9000)
        record = _Record(root32, prop_client.account())
        rows = svc_not.collect_rows(0, 2, record,
                                    bytes(not_client.account()))
        assert rows is not None
        assert all(verify_samples(rows["chunks"], rows["indices"],
                                  rows["proofs"], rows["roots"]))
        assert schedule.injected.get("das.sample_fetch") == 1
        assert schedule.injected.get("das.parity_publish") == 1
    finally:
        svc_prop.stop()
        svc_not.stop()


def test_chaos_unwired_das_seams_are_reported():
    """A chaos spec naming das.* seams on a node that never wires the
    das injector must be surfaced, not silently inert — the CLI warns
    from exactly this list."""
    from gethsharding_tpu.resilience.chaos import parse_spec, unwired_seams

    schedule = parse_spec(
        "seed=1,das.sample_fetch=2,backend.ecrecover_addresses=1")
    # a --da-mode=full node wires only the classic three
    assert unwired_seams(schedule, ("mainchain", "backend",
                                    "dispatch")) == ["das.sample_fetch"]
    # a --da-mode=sampled node wires das.* too: nothing unwired
    assert unwired_seams(schedule, ("mainchain", "backend", "dispatch",
                                    "das")) == []


# -- the notary in sampled mode --------------------------------------------


def _sampled_network(body_size=9000, samples=5, tamper=False):
    from gethsharding_tpu.actors.notary import Notary
    from gethsharding_tpu.actors.proposer import create_collation
    from gethsharding_tpu.core.shard import Shard
    from gethsharding_tpu.core.types import Transaction
    from gethsharding_tpu.db.kv import MemoryKV
    from gethsharding_tpu.mainchain.client import SMCClient
    from gethsharding_tpu.p2p.messages import CollationBodyRequest
    from gethsharding_tpu.p2p.service import Hub, P2PServer
    from gethsharding_tpu.params import Config, ETHER
    from gethsharding_tpu.smc.chain import SimulatedMainchain

    config = Config(quorum_size=1, period_length=4)
    chain = SimulatedMainchain(config=config)
    prop_client = SMCClient(backend=chain, config=config)
    not_client = SMCClient(backend=chain, config=config)
    chain.fund(prop_client.account(), 2000 * ETHER)
    chain.fund(not_client.account(), 2000 * ETHER)
    hub = Hub()
    watch = P2PServer(hub)
    watch.start()  # must be hub-attached or broadcasts never reach it
    body_watch = watch.subscribe(CollationBodyRequest)
    svc_prop = DASService(client=prop_client, p2p=P2PServer(hub),
                          samples=samples, fetch_timeout=1.0,
                          fetch_attempts=2)
    svc_not = DASService(client=not_client, p2p=P2PServer(hub),
                         samples=samples, fetch_timeout=1.0,
                         fetch_attempts=2)
    svc_prop.start()
    svc_not.start()
    notary = Notary(client=not_client, shard=Shard(0, MemoryKV()),
                    p2p=svc_not.p2p, config=config, deposit_flag=True,
                    all_shards=False, sig_backend=get_backend("python"),
                    das=svc_not, da_mode="sampled")
    notary.start()
    chain.fast_forward(1)

    prop_shard = Shard(0, MemoryKV())
    periods = []
    rng = random.Random(body_size)
    for _ in range(2):
        period = chain.current_period()
        collation = create_collation(
            prop_client, 0, period,
            [Transaction(nonce=period,
                         payload=bytes(rng.randrange(256)
                                       for _ in range(body_size)))])
        prop_shard.save_collation(collation)
        commitment = svc_prop.publish(0, period,
                                      collation.header.chunk_root,
                                      collation.body)
        if tamper:
            from dataclasses import replace

            root = bytes(commitment.das_root)
            xb, levels = svc_prop._blobs[root]
            chunks = [b"\xbb" * DAS_CHUNK_SIZE for _ in xb.chunks]
            svc_prop._blobs[root] = (replace(xb, chunks=tuple(chunks)),
                                     levels)
        prop_client.add_header(0, period, collation.header.chunk_root,
                               collation.header.proposer_signature)
        chain.commit()
        notary.notarize_collations(head=chain.block_number)
        periods.append(period)
        while chain.current_period() == period:
            chain.commit()
    services = (notary, svc_prop, svc_not, watch)
    return chain, notary, svc_not, body_watch, periods, services


def test_notary_sampled_mode_votes_with_zero_body_fetches():
    chain, notary, svc_not, body_watch, periods, services = \
        _sampled_network()
    try:
        assert notary.votes_submitted == len(periods), notary.errors
        # THE acceptance bar: not one CollationBodyRequest left the
        # sampled notary
        assert body_watch.try_get() is None
        # and the sampled bytes stayed within the per-collation budget
        per_collation = svc_not.bytes_fetched / len(periods)
        assert per_collation <= 5 * (DAS_CHUNK_SIZE
                                     + 32 * MAX_PROOF_DEPTH + 40)
        # quorum reached on sampled votes alone
        assert chain.last_approved_collation(0) == periods[-1]
    finally:
        for svc in services:
            svc.stop()


def test_notary_sampled_mode_refuses_corrupted_blobs():
    """Every served chunk is garbage (commitment signed over the real
    blob): sample proofs fail, the notary votes on NOTHING."""
    chain, notary, svc_not, body_watch, periods, services = \
        _sampled_network(tamper=True)
    try:
        assert notary.votes_submitted == 0
        assert any("unavailable" in e for e in notary.errors)
        assert body_watch.try_get() is None  # still zero body fetches
    finally:
        for svc in services:
            svc.stop()


# -- RPC + light client ----------------------------------------------------


def test_rpc_get_sample_and_da_status():
    from gethsharding_tpu.mainchain.client import SMCClient
    from gethsharding_tpu.params import Config
    from gethsharding_tpu.rpc import codec
    from gethsharding_tpu.rpc.server import RPCServer
    from gethsharding_tpu.smc.chain import SimulatedMainchain
    from gethsharding_tpu.utils.hexbytes import Hash32

    config = Config()
    chain = SimulatedMainchain(config=config)
    client = SMCClient(backend=chain, config=config)
    provider = DASService(client=client)  # local-only: no p2p
    provider.start()
    server = RPCServer(chain, das=provider)
    server.start()  # stop() blocks unless serve_forever is running
    try:
        # no commitment yet
        assert server.rpc_daStatus(0, 1) == {
            "known": False, "shard_id": 0, "period": 1,
            "provider": True}
        assert server.rpc_getSample(0, 1, [0]) is None
        commitment = provider.publish(0, 1, Hash32(b"\x03" * 32),
                                      os.urandom(12000))
        status = server.rpc_daStatus(0, 1)
        assert status["known"] and status["provider"]
        assert status["k"] == commitment.k and status["n"] == commitment.n
        got = server.rpc_getSample(0, 1, [0, commitment.n - 1, 999])
        assert got["k"] == commitment.k
        assert len(got["samples"]) == 2  # 999 is out of range
        for sample in got["samples"]:
            assert verify_sample(
                codec.dec_bytes(got["dasRoot"]), sample["index"],
                codec.dec_bytes(sample["chunk"]),
                [codec.dec_bytes(node) for node in sample["proof"]])
        # a provider-less server answers "no provider", never raises
        bare = RPCServer(chain)
        bare.start()
        try:
            assert bare.rpc_daStatus(0, 1)["provider"] is False
            assert bare.rpc_getSample(0, 1, [0]) is None
        finally:
            bare.stop()
    finally:
        server.stop()
        provider.stop()


def test_light_client_das_check_over_p2p():
    from gethsharding_tpu.actors.light import LightClient
    from gethsharding_tpu.actors.proposer import create_collation
    from gethsharding_tpu.core.shard import Shard
    from gethsharding_tpu.core.types import Transaction
    from gethsharding_tpu.db.kv import MemoryKV
    from gethsharding_tpu.mainchain.client import SMCClient
    from gethsharding_tpu.p2p.service import Hub, P2PServer
    from gethsharding_tpu.params import Config, ETHER
    from gethsharding_tpu.smc.chain import SimulatedMainchain

    config = Config(period_length=4)
    chain = SimulatedMainchain(config=config)
    prop_client = SMCClient(backend=chain, config=config)
    light_client_smc = SMCClient(backend=chain, config=config)
    chain.fund(prop_client.account(), 2000 * ETHER)
    hub = Hub()
    svc_prop = DASService(client=prop_client, p2p=P2PServer(hub),
                          samples=4, fetch_timeout=1.0)
    svc_light = DASService(client=light_client_smc, p2p=P2PServer(hub),
                           samples=4, fetch_timeout=1.0,
                           fetch_attempts=2)
    svc_prop.start()
    svc_light.start()
    light = LightClient(client=light_client_smc, p2p=svc_light.p2p,
                        das=svc_light)
    light.start()
    try:
        chain.fast_forward(1)
        period = chain.current_period()
        shard = Shard(0, MemoryKV())
        collation = create_collation(
            prop_client, 0, period,
            [Transaction(nonce=1, payload=os.urandom(13000))])
        shard.save_collation(collation)
        svc_prop.publish(0, period, collation.header.chunk_root,
                         collation.body)
        prop_client.add_header(0, period, collation.header.chunk_root,
                               collation.header.proposer_signature)
        chain.commit()
        assert light.das_check(0, period, seed=b"\x42" * 32) is True
        assert light.samples_verified >= 4
        # an unknown period fails closed
        assert light.das_check(0, period + 7) is False
    finally:
        light.stop()
        svc_prop.stop()
        svc_light.stop()


def test_das_counters_reach_prometheus_exposition():
    from gethsharding_tpu import metrics
    from gethsharding_tpu.metrics import prometheus_text

    metrics.counter("das/samples_verified").inc(0)
    metrics.counter("das/sample_failures").inc(0)
    metrics.counter("das/sample_wire_bytes").inc(0)
    text = prometheus_text()
    for needle in ("gethsharding_das_samples_verified_total",
                   "gethsharding_das_sample_failures_total",
                   "gethsharding_das_sample_wire_bytes_total"):
        assert needle in text, needle
