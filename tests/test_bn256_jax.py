"""Differential tests: batched pairing kernel (ops/bn256_jax) vs the scalar
reference (crypto/bn256.py, itself EIP-196/197-parameterized and
golden-tested in tests/test_bn256.py).

Raw Miller outputs are NOT comparable (the kernel's inversion-free lines
carry Fp2 scale factors the final exponentiation kills), so comparisons
happen at pairing value / PairingCheck / BLS-verify level — exactly the
surfaces the framework consumes.
"""

import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from gethsharding_tpu.crypto import bn256 as ref
from gethsharding_tpu.ops import bn256_jax as k
from gethsharding_tpu.ops.limb import NLIMBS, ints_to_limbs

# The full Miller-loop/final-exponentiation kernels take ~20-90 s each to
# compile on XLA:CPU (near-instant on repeat runs via the persistent cache
# in conftest.py). They run by default — the suite must exercise the
# north-star kernel end to end — but GETHSHARDING_SKIP_SLOW=1 skips them
# for quick local loops.
slow = pytest.mark.skipif(
    os.environ.get("GETHSHARDING_SKIP_SLOW") == "1",
    reason="GETHSHARDING_SKIP_SLOW=1",
)


def _rand_fp12(rng) -> ref.Fp12:
    def fp2():
        return ref.Fp2(int(rng.integers(0, 1 << 62)) * int(rng.integers(0, 1 << 62)) % ref.P,
                       int(rng.integers(0, 1 << 62)) % ref.P)
    def fp6():
        return ref.Fp6(fp2(), fp2(), fp2())
    return ref.Fp12(fp6(), fp6())


def _fp12_to_arr(x: ref.Fp12) -> np.ndarray:
    """Scalar Fp12 -> the kernel's w-basis (6, 2, 22) layout."""
    tower = np.zeros((2, 3, 2, NLIMBS), np.int32)
    for h, c6 in enumerate((x.c0, x.c1)):
        for l, c2 in enumerate((c6.c0, c6.c1, c6.c2)):
            tower[h, l, 0] = ints_to_limbs([c2.a])[0]
            tower[h, l, 1] = ints_to_limbs([c2.b])[0]
    return k.fp12_from_tower(tower)


def _arr_to_coeffs(arr) -> np.ndarray:
    return k.fp12_to_int_coeffs(arr)


def _fp12_coeffs(x: ref.Fp12) -> np.ndarray:
    out = np.zeros((2, 3, 2), object)
    for h, c6 in enumerate((x.c0, x.c1)):
        for l, c2 in enumerate((c6.c0, c6.c1, c6.c2)):
            out[h, l, 0], out[h, l, 1] = c2.a, c2.b
    return out


def test_fp12_mul_inv_matches_scalar():
    rng = np.random.default_rng(1)
    a, b = _rand_fp12(rng), _rand_fp12(rng)
    arr = jnp.asarray(np.stack([_fp12_to_arr(a), _fp12_to_arr(b)]))
    prod = np.asarray(_arr_to_coeffs(k.fp12_mul(arr[0], arr[1])))
    assert (prod == _fp12_coeffs(a * b)).all()
    inv = np.asarray(_arr_to_coeffs(jax.jit(k.fp12_inv)(arr[0])))
    assert (inv == _fp12_coeffs(a.inv())).all()


@pytest.mark.parametrize("n", [1, 2, 3])
def test_frobenius_matches_scalar_pow(n):
    rng = np.random.default_rng(10 + n)
    a = _rand_fp12(rng)
    got = np.asarray(_arr_to_coeffs(
        k.fp12_frobenius(jnp.asarray(_fp12_to_arr(a)), n)))
    expect = _fp12_coeffs(a.pow(ref.P ** n))
    assert (got == expect).all()


@slow
def test_final_exponentiation_matches_scalar():
    rng = np.random.default_rng(2)
    a = _rand_fp12(rng)
    got = np.asarray(_arr_to_coeffs(
        jax.jit(k.final_exponentiation)(jnp.asarray(_fp12_to_arr(a)))))
    expect = _fp12_coeffs(a.pow(ref.FINAL_EXP))
    assert (got == expect).all()


@slow
def test_pairing_value_matches_scalar():
    g1 = ref.g1_mul(7, ref.G1_GEN)
    g2 = ref.g2_mul(11, ref.G2_GEN)
    px, py, _ = k.g1_to_limbs([g1])
    qx, qy, _ = k.g2_to_limbs([g2])
    f = k.final_exponentiation(
        k.miller_loop(jnp.asarray(px[0]), jnp.asarray(py[0]),
                      jnp.asarray(qx[0]), jnp.asarray(qy[0])))
    got = np.asarray(_arr_to_coeffs(f))
    expect = _fp12_coeffs(ref.pairing(g1, g2))
    assert (got == expect).all()


@slow
def test_pairing_check_parity_batch():
    # rows: [bilinear identity: e(aP, Q)·e(-P, aQ) = 1] and [broken pair]
    a = 123456789
    p1, q1 = ref.g1_mul(a, ref.G1_GEN), ref.G2_GEN
    p2, q2 = ref.g1_neg(ref.G1_GEN), ref.g2_mul(a, ref.G2_GEN)
    bad_p2 = ref.g1_neg(ref.g1_mul(2, ref.G1_GEN))
    rows_p = [[p1, p2], [p1, bad_p2]]
    rows_q = [[q1, q2], [q1, q2]]
    px, py, qx, qy = [], [], [], []
    for rp, rq in zip(rows_p, rows_q):
        x1, y1, _ = k.g1_to_limbs(rp)
        x2, y2, _ = k.g2_to_limbs(rq)
        px.append(x1), py.append(y1), qx.append(x2), qy.append(y2)
    mask = np.ones((2, 2), bool)
    got = np.asarray(jax.jit(k.pairing_check)(
        jnp.asarray(np.stack(px)), jnp.asarray(np.stack(py)),
        jnp.asarray(np.stack(qx)), jnp.asarray(np.stack(qy)),
        jnp.asarray(mask)))
    expect = [ref.pairing_check(list(zip(rp, rq)))
              for rp, rq in zip(rows_p, rows_q)]
    assert list(got) == expect == [True, False]


@slow
def test_pairing_check_infinity_mask():
    # an infinity pair contributes identity, matching the scalar skip rule
    a = 5
    p1 = ref.g1_mul(a, ref.G1_GEN)
    p2 = ref.g1_neg(ref.G1_GEN)
    q2 = ref.g2_mul(a, ref.G2_GEN)
    px, py, pok = k.g1_to_limbs([p1, None, p2])
    qx, qy, qok = k.g2_to_limbs([ref.G2_GEN, ref.G2_GEN, q2])
    mask = pok & qok
    got = np.asarray(k.pairing_check(
        jnp.asarray(px)[None], jnp.asarray(py)[None],
        jnp.asarray(qx)[None], jnp.asarray(qy)[None],
        jnp.asarray(mask)[None]))
    assert got[0] == ref.pairing_check(
        [(p1, ref.G2_GEN), (None, ref.G2_GEN), (p2, q2)]) == True  # noqa: E712


@slow
def test_bls_aggregate_batch_matches_scalar():
    header = b"collation-header-hash"
    committee = [ref.bls_keygen(bytes([i])) for i in range(4)]
    sigs = [ref.bls_sign(header, sk) for sk, _ in committee]
    agg_sig = ref.bls_aggregate_sigs(sigs)
    agg_pk = ref.bls_aggregate_pks([pk for _, pk in committee])
    h = ref.hash_to_g1(header)
    tampered = ref.g1_add(agg_sig, ref.G1_GEN)

    hx, hy, _ = k.g1_to_limbs([h, h])
    sx, sy, _ = k.g1_to_limbs([agg_sig, tampered])
    pkx, pky, _ = k.g2_to_limbs([agg_pk, agg_pk])
    got = np.asarray(jax.jit(k.bls_verify_aggregate_batch)(
        jnp.asarray(hx), jnp.asarray(hy), jnp.asarray(sx), jnp.asarray(sy),
        jnp.asarray(pkx), jnp.asarray(pky), jnp.asarray([True, True])))
    assert list(got) == [True, False]
    assert ref.bls_verify(header, agg_sig, agg_pk) is True
    assert ref.bls_verify(header, tampered, agg_pk) is False


def test_fp12_sqr_matches_mul():
    """Complex squaring must equal the generic product (fast, always on)."""
    rng = np.random.default_rng(3)
    a = _rand_fp12(rng)
    arr = jnp.asarray(_fp12_to_arr(a))
    sq = np.asarray(_arr_to_coeffs(jax.jit(k.fp12_sqr)(arr)))
    assert (sq == _fp12_coeffs(a * a)).all()


@slow
def test_committee_aggregation_matches_host():
    """Device projective tree-sum == host point addition, including the
    complete-formula corner cases: identity padding, duplicate points
    (doubling), and an inverse pair that cancels to infinity."""
    rows = []
    base = [ref.g1_mul(7 + i, ref.G1_GEN) for i in range(6)]
    rows.append(base)                     # plain sum
    rows.append([base[0], base[0]])       # doubling
    rows.append([base[1], ref.g1_neg(base[1])])  # cancels to infinity
    rows.append([base[2]])                # single point
    xs, ys, mask = k.g1_committee_to_limbs(rows, 8)
    X, Y, Z = jax.jit(k.aggregate_g1_proj)(
        jnp.asarray(xs), jnp.asarray(ys), jnp.asarray(mask))
    Xi, Yi, Zi = (k.FP.to_ints(v) for v in (X, Y, Z))
    for b, row in enumerate(rows):
        host = ref.bls_aggregate_sigs(row)
        if host is None:
            assert int(Zi[b]) % ref.P == 0
            continue
        zinv = pow(int(Zi[b]), ref.P - 2, ref.P)
        assert (int(Xi[b]) * zinv % ref.P,
                int(Yi[b]) * zinv % ref.P) == host


@slow
def test_g2_committee_aggregation_matches_host():
    """The Fp2 reduction branch (distinct b3' = 9/xi constant) against
    host G2 addition, incl. doubling, cancellation, identity padding."""
    base = [ref.g2_mul(11 + i, ref.G2_GEN) for i in range(5)]
    rows = [base,
            [base[0], base[0]],
            [base[1], ref.g2_neg(base[1])],
            [base[2]]]
    xs, ys, mask = k.g2_committee_to_limbs(rows, 8)
    X, Y, Z = jax.jit(k.aggregate_g2_proj)(
        jnp.asarray(xs), jnp.asarray(ys), jnp.asarray(mask))
    Xi, Yi, Zi = (k.FP.to_ints(np.asarray(k.FP.canon(v)))
                  for v in (X, Y, Z))
    for b, row in enumerate(rows):
        host = ref.bls_aggregate_pks(row)
        zc = ref.Fp2(int(Zi[b][0]), int(Zi[b][1]))
        if host is None:
            assert zc.is_zero()
            continue
        zinv = zc.inv()
        got = (ref.Fp2(int(Xi[b][0]), int(Xi[b][1])) * zinv,
               ref.Fp2(int(Yi[b][0]), int(Yi[b][1])) * zinv)
        assert got == host


@slow
def test_committee_verify_rejects_cancelled_aggregates():
    """Adversarial cancellation: a non-empty row whose signatures (or
    pubkeys) sum to infinity must be rejected, not vacuously accepted."""
    tag = b"cancel"
    keys = [ref.bls_keygen(tag + bytes([j])) for j in range(2)]
    sigs = [ref.bls_sign(tag, sk) for sk, _ in keys]
    pks = [pk for _, pk in keys]
    rows_sig = [[sigs[0], ref.g1_neg(sigs[0])],   # sig aggregate = inf
                sigs]                              # pk aggregate = inf
    rows_pk = [pks,
               [pks[0], ref.g2_neg(pks[0])]]
    msgs = [tag, tag]
    hx, hy, hok = k.g1_to_limbs([ref.hash_to_g1(m) for m in msgs])
    sx, sy, sm = k.g1_committee_to_limbs(rows_sig, 2)
    px, py, pm = k.g2_committee_to_limbs(rows_pk, 2)
    out = jax.jit(k.bls_aggregate_verify_committee_batch)(
        jnp.asarray(hx), jnp.asarray(hy), jnp.asarray(sx), jnp.asarray(sy),
        jnp.asarray(sm), jnp.asarray(px), jnp.asarray(py), jnp.asarray(pm),
        jnp.asarray(hok))
    assert [bool(v) for v in np.asarray(out)] == [False, False]


@slow
def test_tree_reduce_non_power_of_two_width():
    """Widths that are not powers of two reduce via binary segment
    decomposition — same sum as the host, no dropped points."""
    pts = [ref.g1_mul(3 + i, ref.G1_GEN) for i in range(6)]
    xs, ys, mask = k.g1_committee_to_limbs([pts, pts[:5]], 6)
    X, Y, Z = jax.jit(k.aggregate_g1_proj)(
        jnp.asarray(xs), jnp.asarray(ys), jnp.asarray(mask))
    Xi, Yi, Zi = (k.FP.to_ints(v) for v in (X, Y, Z))
    for b, row in enumerate([pts, pts[:5]]):
        host = ref.bls_aggregate_sigs(row)
        zinv = pow(int(Zi[b]), ref.P - 2, ref.P)
        assert (int(Xi[b]) * zinv % ref.P,
                int(Yi[b]) * zinv % ref.P) == host


# == PAIR_UNROLL differential coverage =====================================
# The unrolled drivers run the IDENTICAL op sequence as their scan/switch
# twins, so raw outputs must be bit-equal. Each driver is compared
# separately on a small input (the fully inlined end-to-end kernel takes
# >35 min to compile on XLA:CPU — too heavy for the suite; the bench's
# audit_period correctness gate covers the composed path on TPU, and
# test_pair_unroll_full_e2e below runs it on demand).


def _canon12(x):
    return np.asarray(k.FP.canon(x))


def test_pair_unroll_pow_u_matches_scan(monkeypatch):
    rng = np.random.default_rng(17)
    x = jnp.asarray(_fp12_to_arr(_rand_fp12(rng)))
    want = _canon12(k._pow_u(x))
    monkeypatch.setattr(k, "FE_UNROLL", True)
    assert (_canon12(k._pow_u(x)) == want).all()


def test_pair_unroll_pow_u_fraction_matches_scan(monkeypatch):
    rng = np.random.default_rng(19)
    x = jnp.asarray(np.stack([_fp12_to_arr(_rand_fp12(rng)),
                              _fp12_to_arr(_rand_fp12(rng))]))
    want = _canon12(k._pow_u_fraction(x))
    monkeypatch.setattr(k, "FE_UNROLL", True)
    assert (_canon12(k._pow_u_fraction(x)) == want).all()


def test_pair_unroll_hard_part_matches_scan(monkeypatch):
    """Register-machine mechanics (static indices vs dynamic slots):
    run _HARD_PROGRAM with a cheap stand-in for pow_u so the comparison
    compiles in seconds; the program executed is the real one."""
    rng = np.random.default_rng(23)
    f = jnp.asarray(_fp12_to_arr(_rand_fp12(rng)))
    want = _canon12(k._run_hard_part(f, k.fp12_sqr, k.fp12_conj))
    monkeypatch.setattr(k, "FE_UNROLL", True)
    assert (_canon12(k._run_hard_part(f, k.fp12_sqr, k.fp12_conj))
            == want).all()


def test_pair_unroll_miller_matches_scan(monkeypatch):
    """Miller drivers on a TRUNCATED static program (covers the static
    dbl/add branch selection and candidate indexing of both the affine
    and the projective walk without the 91-step inlined compile)."""
    # keep one DBL, one ADD(+Q), one ADD(πQ), one ADD(-π²Q)
    short_ops = np.asarray([0, 1, 0, 3, 4], np.int32)
    short_lines = k._GEN_LINES[:5]
    monkeypatch.setattr(k, "_OPT_OPS", short_ops)
    monkeypatch.setattr(k, "_GEN_LINES", short_lines)

    g1 = ref.g1_mul(41, ref.G1_GEN)
    g2 = ref.g2_mul(43, ref.G2_GEN)
    px, py, _ = k.g1_to_limbs([g1])
    qx, qy, _ = k.g2_to_limbs([g2])
    sig_aff = (jnp.asarray(px), jnp.asarray(py), None)
    pk_aff = (jnp.asarray(qx), jnp.asarray(qy), None)
    # projective variant: scale by z (the walk must be z-invariant up to
    # the same sequence of ops, so unrolled == scan exactly per form)
    z = 7
    sig_proj = (jnp.asarray(px), jnp.asarray(py),
                jnp.asarray(k.FP.from_ints([z])))
    qxz, qyz, _ = k.g2_to_limbs([(g2[0].scalar(z), g2[1].scalar(z * z))])
    pk_proj = (jnp.asarray(qxz), jnp.asarray(qyz),
               jnp.asarray(np.stack([k.FP.from_int(z), k.FP.from_int(0)]))[None])

    for sig, pk in ((sig_aff, pk_aff), (sig_proj, pk_proj)):
        monkeypatch.setattr(k, "PAIR_UNROLL", False)
        want = _canon12(k._bls_miller_opt(sig, jnp.asarray(px),
                                          jnp.asarray(py), pk))
        monkeypatch.setattr(k, "PAIR_UNROLL", True)
        got = _canon12(k._bls_miller_opt(sig, jnp.asarray(px),
                                         jnp.asarray(py), pk))
        assert (got == want).all()

    # the plain ate loop's unrolled twin, over a truncated bit pattern
    monkeypatch.setattr(k, "ATE_BITS", np.asarray([1, 0, 1], np.int32))
    monkeypatch.setattr(k, "PAIR_UNROLL", False)
    want = _canon12(k.miller_loop(jnp.asarray(px[0]), jnp.asarray(py[0]),
                                  jnp.asarray(qx[0]), jnp.asarray(qy[0])))
    monkeypatch.setattr(k, "PAIR_UNROLL", True)
    got = _canon12(k.miller_loop(jnp.asarray(px[0]), jnp.asarray(py[0]),
                                 jnp.asarray(qx[0]), jnp.asarray(qy[0])))
    assert (got == want).all()


@pytest.mark.skipif(os.environ.get("GETHSHARDING_RUN_XSLOW") != "1",
                    reason="fully inlined kernel compiles >35 min on "
                           "XLA:CPU; set GETHSHARDING_RUN_XSLOW=1")
def test_pair_unroll_full_e2e(monkeypatch):
    """Full-fidelity end-to-end: unrolled pairing value vs the scalar
    reference. On-demand only (see skip reason)."""
    # the production GETHSHARDING_TPU_PAIR_UNROLL=1 sets BOTH flags
    monkeypatch.setattr(k, "PAIR_UNROLL", True)
    monkeypatch.setattr(k, "FE_UNROLL", True)
    g1 = ref.g1_mul(29, ref.G1_GEN)
    g2 = ref.g2_mul(31, ref.G2_GEN)
    px, py, _ = k.g1_to_limbs([g1])
    qx, qy, _ = k.g2_to_limbs([g2])
    f = k.final_exponentiation(
        k.miller_loop(jnp.asarray(px[0]), jnp.asarray(py[0]),
                      jnp.asarray(qx[0]), jnp.asarray(qy[0])))
    got = np.asarray(_arr_to_coeffs(f))
    assert (got == _fp12_coeffs(ref.pairing(g1, g2))).all()


@slow
def test_relaxed_norm_pairing_value(monkeypatch):
    """The whole pairing stack under GETHSHARDING_TPU_NORM=relaxed (no
    exact carry anywhere in normalize) must reproduce the scalar pairing
    exactly — canon() re-canonicalizes quasi-canonical limbs at the
    comparison boundary."""
    from gethsharding_tpu.ops import limb as _limb
    if _limb.LIMB_FORM != "wide":
        pytest.skip("relaxed normalize is wide-form only")
    if _limb.CONV_IMPL == "mxu8":
        pytest.skip("mxu8 conv requires non-negative products; "
                    "incompatible with relaxed limbs")
    # the fp2/fp12 tower ops are @jax.jit with executables cached by
    # shape: earlier tests compile them under NORM_IMPL="exact" at these
    # exact shapes, which would make this test run the exact path
    # vacuously (and leak relaxed executables to later tests) without a
    # cache flush on both sides
    jax.clear_caches()
    monkeypatch.setattr(_limb, "NORM_IMPL", "relaxed")
    try:
        g1 = ref.g1_mul(57, ref.G1_GEN)
        g2 = ref.g2_mul(61, ref.G2_GEN)
        px, py, _ = k.g1_to_limbs([g1])
        qx, qy, _ = k.g2_to_limbs([g2])
        f = k.final_exponentiation(
            k.miller_loop(jnp.asarray(px[0]), jnp.asarray(py[0]),
                          jnp.asarray(qx[0]), jnp.asarray(qy[0])))
        got = np.asarray(_arr_to_coeffs(f))
        assert (got == _fp12_coeffs(ref.pairing(g1, g2))).all()
    finally:
        jax.clear_caches()
