"""Pallas fused-normalize kernel vs the XLA limb path (interpreter mode on
CPU; on TPU backends GETHSHARDING_TPU_PALLAS=1 runs it compiled)."""

import numpy as np
import jax.numpy as jnp
import pytest

from gethsharding_tpu.ops import limb
from gethsharding_tpu.ops.pallas_norm import BLOCK_ROWS, normalize_pallas

P_BN = 21888242871839275222246405745257275088696311157297823662689037894645226208583
N_SECP = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141


@pytest.mark.parametrize("modulus", [P_BN, N_SECP])
def test_normalize_pallas_matches_xla(modulus):
    rng = np.random.default_rng(11)
    arith = limb.ModArith(modulus)
    for width in (limb.NLIMBS, 2 * limb.NLIMBS - 1, 49):
        z = rng.integers(0, 1 << 28, (3 * BLOCK_ROWS, width)
                         ).astype(np.int32)
        want = np.asarray(arith.normalize(jnp.asarray(z)))
        got = np.asarray(normalize_pallas(arith, jnp.asarray(z),
                                          interpret=True))
        assert (want == got).all(), width


def test_normalize_pallas_partial_block_and_leading_dims():
    arith = limb.ModArith(P_BN)
    rng = np.random.default_rng(12)
    # non-multiple-of-block row count with extra leading axes
    z = rng.integers(0, 1 << 24, (7, 3, limb.NLIMBS)).astype(np.int32)
    want = np.asarray(arith.normalize(jnp.asarray(z)))
    got = np.asarray(normalize_pallas(arith, jnp.asarray(z), interpret=True))
    assert want.shape == got.shape == (7, 3, limb.NLIMBS)
    assert (want == got).all()


def test_mul_through_pallas_normalize_value_parity():
    """End-to-end value check: a modular product normalized by the kernel
    reconstructs to the right integer."""
    arith = limb.ModArith(P_BN)
    rng = np.random.default_rng(13)
    xs = [int(rng.integers(1, 1 << 62)) ** 4 % P_BN for _ in range(8)]
    ys = [int(rng.integers(1, 1 << 62)) ** 4 % P_BN for _ in range(8)]
    cols = arith.mul_cols(jnp.asarray(limb.ints_to_limbs(xs)),
                          jnp.asarray(limb.ints_to_limbs(ys)))
    out = normalize_pallas(arith, cols, interpret=True)
    got = arith.to_ints(out)
    for g, x, y in zip(got, xs, ys):
        assert int(g) == x * y % P_BN


# == fused pair-conv + combine kernel (ops/pallas_conv.py) =================


def _xla_pair_conv(x, y, comb):
    prod = x[..., :, :, None, :, None] * y[..., :, None, :, None, :]
    cols = limb.conv_cols(prod)
    return jnp.einsum("...iabn,iabcg->...cgn", cols, jnp.asarray(comb))


def test_pair_conv_combine_matches_xla_all_combs():
    """The fused kernel reproduces product-conv + combine bit-exactly for
    every combine tensor the pairing stack uses (fp12, sparse line, fp2
    mul and the plane-skipping fp2 square)."""
    from gethsharding_tpu.ops import bn256_jax as k
    from gethsharding_tpu.ops.pallas_conv import pair_conv_combine

    rng = np.random.default_rng(21)
    for comb in (k._COMB, k._LCOMB, k._COMB_FP2, k._COMB_FP2_SQR):
        G, A, B, _, _ = comb.shape
        x = rng.integers(0, 1 << 12, (5, G, A, limb.NLIMBS)).astype(np.int32)
        y = rng.integers(0, 1 << 12, (5, G, B, limb.NLIMBS)).astype(np.int32)
        want = np.asarray(_xla_pair_conv(jnp.asarray(x), jnp.asarray(y), comb))
        got = np.asarray(pair_conv_combine(
            jnp.asarray(x), jnp.asarray(y), comb, interpret=True))
        assert want.shape == got.shape, comb.shape
        assert (want == got).all(), comb.shape


def test_pair_conv_combine_partial_block_and_leading_dims():
    from gethsharding_tpu.ops import bn256_jax as k
    from gethsharding_tpu.ops.pallas_conv import BLOCK_COLS, pair_conv_combine

    rng = np.random.default_rng(22)
    x = rng.integers(0, 1 << 12,
                     (3, BLOCK_COLS // 2 + 1, 6, 2, limb.NLIMBS)
                     ).astype(np.int32)
    y = rng.integers(0, 1 << 12, x.shape).astype(np.int32)
    want = np.asarray(_xla_pair_conv(jnp.asarray(x), jnp.asarray(y), k._COMB))
    got = np.asarray(pair_conv_combine(
        jnp.asarray(x), jnp.asarray(y), k._COMB, interpret=True))
    assert want.shape == got.shape
    assert (want == got).all()


def test_pair_conv_combine_broadcast_operand():
    """One operand with FEWER leading dims (a constant against a batch)
    broadcasts exactly like the XLA fallback — the r4 TPU probe failure
    shape: a batched x against an unbatched Frobenius/line constant y."""
    from gethsharding_tpu.ops import bn256_jax as k
    from gethsharding_tpu.ops.pallas_conv import pair_conv_combine

    rng = np.random.default_rng(23)
    G, A, B, _, _ = k._COMB_FP2.shape
    xb = rng.integers(0, 1 << 12, (5, G, A, limb.NLIMBS)).astype(np.int32)
    yc = rng.integers(0, 1 << 12, (G, B, limb.NLIMBS)).astype(np.int32)
    for x, y in ((xb, yc), (yc, xb)):
        want = np.asarray(_xla_pair_conv(
            jnp.asarray(x), jnp.asarray(y), k._COMB_FP2))
        got = np.asarray(pair_conv_combine(
            jnp.asarray(x), jnp.asarray(y), k._COMB_FP2, interpret=True))
        assert want.shape == got.shape
        assert (want == got).all()


def test_pair_conv_combine_identity_comb_mul_many():
    """The identity combine (n independent products in one kernel call)
    matches n separate schoolbook products bit-for-bit — the G1
    aggregation tree's mul_many shape."""
    from gethsharding_tpu.ops import bn256_jax as k
    from gethsharding_tpu.ops.pallas_conv import pair_conv_combine

    rng = np.random.default_rng(31)
    n = 6
    x = rng.integers(0, 1 << 12, (7, n, 1, limb.NLIMBS)).astype(np.int32)
    y = rng.integers(0, 1 << 12, (7, n, 1, limb.NLIMBS)).astype(np.int32)
    comb = k._mul_many_comb(n)
    want = np.asarray(_xla_pair_conv(jnp.asarray(x), jnp.asarray(y), comb))
    got = np.asarray(pair_conv_combine(
        jnp.asarray(x), jnp.asarray(y), comb, interpret=True))
    assert (want == got).all()
    # and each lane equals the plain schoolbook product columns
    single = np.asarray(limb.conv_cols(
        jnp.asarray(x[:, :, 0, :, None] * y[:, :, 0, None, :])))
    assert (np.asarray(got)[..., 0, :] == single).all()
