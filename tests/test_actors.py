"""Actor services: proposer/notary/syncer/txpool/observer/simulator flows,
mirroring the reference's service tests plus the fully-wired vote loop."""

import time

import pytest

from gethsharding_tpu.actors import (
    Notary,
    Observer,
    Proposer,
    Simulator,
    Syncer,
    TXPool,
)
from gethsharding_tpu.actors.proposer import check_header_added, create_collation
from gethsharding_tpu.core.shard import Shard, ShardError
from gethsharding_tpu.core.types import Transaction
from gethsharding_tpu.crypto import secp256k1
from gethsharding_tpu.db.kv import MemoryKV
from gethsharding_tpu.mainchain.client import SMCClient
from gethsharding_tpu.p2p.service import Hub, P2PServer
from gethsharding_tpu.params import Config, ETHER
from gethsharding_tpu.smc.chain import SimulatedMainchain


def make_client(backend=None, config=None, seed=b"acct"):
    config = config or Config()
    backend = backend or SimulatedMainchain(config=config)
    client = SMCClient(backend=backend, config=config)
    client.accounts._accounts.clear()
    client._account = client.accounts.new_account(seed=seed)
    backend.fund(client.account(), 5000 * ETHER)
    client.start()
    return client


def test_txpool_emits_and_accepts():
    pool = TXPool(simulate_interval=0.01, payload_size=16)
    sub = pool.transactions_feed.subscribe()
    pool.start()
    try:
        tx = sub.get(timeout=2)
        assert isinstance(tx, Transaction)
        assert len(tx.payload) == 16
    finally:
        pool.stop()
    # direct intake works without the simulator thread
    pool2 = TXPool(simulate_interval=None)
    sub2 = pool2.transactions_feed.subscribe()
    pool2.start()
    pool2.submit(Transaction(nonce=9))
    assert sub2.get(timeout=1).nonce == 9
    pool2.stop()


def test_create_collation_signs_header():
    client = make_client()
    collation = create_collation(client, 1, 0, [Transaction(gas_limit=5)])
    header = collation.header
    assert header.proposer_address == client.account()
    assert header.chunk_root is not None
    sig = secp256k1.Signature.from_bytes65(header.proposer_signature)
    # signature covers the unsigned header hash
    from gethsharding_tpu.core.types import CollationHeader

    unsigned_header = CollationHeader(
        shard_id=1, chunk_root=header.chunk_root, period=0,
        proposer_address=client.account(),
    )
    assert secp256k1.ecrecover_address(
        bytes(unsigned_header.hash()), sig
    ) == client.account()


def test_create_collation_rejects_bad_shard():
    client = make_client()
    with pytest.raises(ValueError, match="out of range"):
        create_collation(client, 100, 0, [])


def test_proposer_saves_and_adds_header():
    config = Config()
    backend = SimulatedMainchain(config=config)
    client = make_client(backend, config)
    backend.fast_forward(1)
    pool = TXPool(simulate_interval=None)
    shard = Shard(shard_id=0, shard_db=MemoryKV())
    proposer = Proposer(client=client, txpool=pool, shard=shard,
                        config=config, poll_interval=0.01)
    pool.start()
    proposer.start()
    try:
        pool.submit(Transaction(nonce=1, payload=b"hello shard"))
        deadline = time.time() + 5
        while proposer.collations_proposed == 0 and time.time() < deadline:
            time.sleep(0.01)
        assert proposer.collations_proposed == 1
        period = client.block_number // config.period_length
        assert client.last_submitted_collation(0) == period
        record = client.collation_record(0, period)
        body = shard.body_by_chunk_root(record.chunk_root)
        assert b"hello shard" in body
        assert check_header_added(client, 0, period) is False
    finally:
        proposer.stop()
        pool.stop()


def test_notary_joins_pool_and_votes_to_canonical():
    # single notary, quorum 1: the first vote approves the collation
    config = Config(quorum_size=1)
    backend = SimulatedMainchain(config=config)
    proposer_client = make_client(backend, config, seed=b"proposer")
    notary_client = make_client(backend, config, seed=b"notary")

    hub = Hub()
    p2p = P2PServer(hub)
    shard_db = MemoryKV()
    shard = Shard(shard_id=3, shard_db=shard_db)
    notary = Notary(client=notary_client, shard=shard, p2p=p2p,
                    config=config, deposit_flag=True)
    notary.start()
    try:
        assert notary.is_account_in_notary_pool()
        backend.fast_forward(1)
        period = backend.current_period()
        # proposer adds a header; give the notary the matching body locally
        collation = create_collation(proposer_client, 3, period,
                                     [Transaction(nonce=7)])
        shard.save_collation(collation)
        proposer_client.backend.add_header(
            proposer_client.account(), 3, period,
            collation.header.chunk_root, collation.header.proposer_signature,
        )
        backend.commit()  # head triggers the vote loop synchronously
        assert notary.votes_submitted >= 1
        assert backend.last_approved_collation(3) == period
        assert notary.canonical_set == 1
        canonical = shard.canonical_collation(3, period)
        assert canonical.header.chunk_root == collation.header.chunk_root
    finally:
        notary.stop()


def test_notary_not_eligible_without_deposit():
    config = Config()
    backend = SimulatedMainchain(config=config)
    client = make_client(backend, config)
    shard = Shard(shard_id=0, shard_db=MemoryKV())
    notary = Notary(client=client, shard=shard, config=config,
                    deposit_flag=False)
    notary.start()
    try:
        backend.fast_forward(1)
        assert notary.votes_submitted == 0
        assert not notary.is_account_in_notary_pool()
    finally:
        notary.stop()


def test_syncer_roundtrip_over_hub():
    # node A (has the body) serves node B (needs it) over the hub
    config = Config()
    backend = SimulatedMainchain(config=config)
    client_a = make_client(backend, config, seed=b"a")
    client_b = make_client(backend, config, seed=b"b")
    hub = Hub()
    p2p_a, p2p_b = P2PServer(hub), P2PServer(hub)
    shard_a = Shard(shard_id=0, shard_db=MemoryKV())
    shard_b = Shard(shard_id=0, shard_db=MemoryKV())

    collation = create_collation(client_a, 0, 0, [Transaction(nonce=1)])
    shard_a.save_collation(collation)

    syncer_a = Syncer(client=client_a, shard=shard_a, p2p=p2p_a,
                      poll_interval=0.01)
    syncer_b = Syncer(client=client_b, shard=shard_b, p2p=p2p_b,
                      poll_interval=0.01)
    p2p_a.start()
    p2p_b.start()
    syncer_a.start()
    syncer_b.start()
    try:
        from gethsharding_tpu.p2p.messages import CollationBodyRequest

        p2p_b.broadcast(CollationBodyRequest(
            chunk_root=collation.header.chunk_root, shard_id=0, period=0,
            proposer=client_a.account(),
        ))
        deadline = time.time() + 5
        while syncer_b.bodies_stored == 0 and time.time() < deadline:
            time.sleep(0.01)
        assert syncer_b.bodies_stored == 1
        body = shard_b.body_by_chunk_root(collation.header.chunk_root)
        assert body == collation.body
    finally:
        syncer_b.stop()
        syncer_a.stop()
        p2p_b.stop()
        p2p_a.stop()


def test_simulator_injects_requests():
    config = Config()
    backend = SimulatedMainchain(config=config)
    client = make_client(backend, config)
    backend.fast_forward(1)
    period = backend.current_period()
    collation = create_collation(client, 2, period, [Transaction(nonce=4)])
    backend.add_header(client.account(), 2, period,
                       collation.header.chunk_root, b"")
    p2p = P2PServer()
    p2p.start()
    sub = p2p.subscribe(__import__(
        "gethsharding_tpu.p2p.messages", fromlist=["CollationBodyRequest"]
    ).CollationBodyRequest)
    simulator = Simulator(client=client, p2p=p2p, shard_id=2,
                          tick_interval=0.02)
    simulator.start()
    try:
        msg = sub.get(timeout=3)
        assert msg.data.shard_id == 2
        assert msg.data.chunk_root == collation.header.chunk_root
    finally:
        simulator.stop()
        p2p.stop()


def test_observer_sees_canonical():
    config = Config(quorum_size=1)
    backend = SimulatedMainchain(config=config)
    notary_client = make_client(backend, config, seed=b"n")
    observer_client = make_client(backend, config, seed=b"o")
    shard_db = MemoryKV()
    shard = Shard(shard_id=0, shard_db=shard_db)
    notary = Notary(client=notary_client, shard=shard, config=config,
                    deposit_flag=True)
    observer = Observer(client=observer_client, shard=shard)
    notary.start()
    observer.start()
    try:
        backend.fast_forward(1)
        period = backend.current_period()
        collation = create_collation(notary_client, 0, period,
                                     [Transaction(nonce=2)])
        shard.save_collation(collation)
        backend.add_header(notary_client.account(), 0, period,
                           collation.header.chunk_root,
                           collation.header.proposer_signature)
        backend.commit()
        assert period in observer.seen_periods
    finally:
        observer.stop()
        notary.stop()


def test_windback_blocks_vote_until_prior_body_available():
    """Enforced windback (sharding/README.md): with windback_depth set, a
    notary refuses to vote while a prior period's collation body is
    unavailable, and votes once it can be fetched over shardp2p."""
    import time as _time

    from gethsharding_tpu.actors.proposer import create_collation
    from gethsharding_tpu.params import Config

    config = Config(quorum_size=1, windback_depth=3)
    backend = SimulatedMainchain(config=config)
    client = SMCClient(backend=backend, config=config)
    backend.fund(client.account(), 2000 * ETHER)
    shard = Shard(shard_id=0, shard_db=MemoryKV())
    notary = Notary(client=client, shard=shard, config=config,
                    deposit_flag=True, all_shards=False)
    notary.start()
    try:
        # period 1: a collation whose body the notary never receives
        backend.fast_forward(1)
        old = create_collation(client, 0, 1, [Transaction(nonce=1,
                                                          payload=b"old")])
        client.add_header(0, 1, old.header.chunk_root,
                          old.header.proposer_signature)
        # period 2: a collation the notary has locally
        backend.fast_forward(1)
        fresh = create_collation(client, 0, 2, [Transaction(nonce=2,
                                                            payload=b"new")])
        shard.save_collation(fresh)
        client.add_header(0, 2, fresh.header.chunk_root,
                          fresh.header.proposer_signature)
        record = backend.collation_record(0, 2)

        assert notary.submit_vote(0, 2, record) is False
        assert any("windback" in e for e in notary.errors)
        assert notary.votes_submitted == 0

        # once the prior body is stored (synced), the vote goes through
        shard.save_collation(old)
        assert notary.submit_vote(0, 2, record) is True
        assert backend.last_approved_collation(0) == 2
    finally:
        notary.stop()


def test_observer_replays_canonical_collations():
    """The observer maintains shard state by replaying canonical
    collations (the state_processor Process analog on the live node)."""
    from gethsharding_tpu.actors.observer import Observer
    from gethsharding_tpu.core import state_processor as sp
    from gethsharding_tpu.core.shard import Shard
    from gethsharding_tpu.core.types import (
        Collation, CollationHeader, Transaction)
    from gethsharding_tpu.crypto import secp256k1
    from gethsharding_tpu.crypto.keccak import keccak256
    from gethsharding_tpu.db.kv import MemoryKV
    from gethsharding_tpu.mainchain.client import SMCClient
    from gethsharding_tpu.smc.chain import SimulatedMainchain
    from gethsharding_tpu.utils.hexbytes import Address20, Hash32

    priv = 0xD00D
    sender = secp256k1.priv_to_address(priv)
    to = secp256k1.priv_to_address(0xFEED)
    proposer = secp256k1.priv_to_address(0xF00)

    txs = [sp.sign_transaction(
        Transaction(nonce=i, gas_price=2, gas_limit=30000, to=to,
                    value=100, payload=b"pay"), priv) for i in range(3)]
    txs.append(sp.sign_transaction(  # bad nonce -> rejected, state intact
        Transaction(nonce=99, gas_price=2, gas_limit=30000, to=to,
                    value=100, payload=b"bad"), priv))

    chain = SimulatedMainchain()
    client = SMCClient(backend=chain)
    shard = Shard(shard_id=0, shard_db=MemoryKV())
    observer = Observer(client=client, shard=shard,
                        genesis={sender: sp.AccountState(balance=10**12)})

    header = CollationHeader(shard_id=0, chunk_root=Hash32(keccak256(b"x")),
                             period=1, proposer_address=proposer)
    collation = Collation(header=header, transactions=txs)
    root = observer.replay_collation(1, collation)

    assert observer.txs_replayed == 3
    assert observer.txs_rejected == 1
    assert observer.state.get(sender).nonce == 3
    assert observer.state.get(to).balance == 300
    assert observer.state_roots[1] == observer.state.root()
    assert observer.canonical_roots[1] == root  # the returned root is canonical

    # parity: an independent scalar replay reaches the same roots (flat
    # integrity check AND the canonical secure-MPT state root)
    twin = sp.ShardState({sender: sp.AccountState(balance=10**12)})
    sp.process(twin, txs, proposer)
    assert twin.root() == observer.state_roots[1]
    assert twin.trie_root() == root


def test_observer_engines_agree_when_all_txs_rejected():
    """Zero-row parity: a collation whose txs are ALL rejected must leave
    both engines at the same root (the device table materializes zero
    accounts for touched addresses; the python engine must too)."""
    from gethsharding_tpu.actors.observer import Observer
    from gethsharding_tpu.core import state_processor as sp
    from gethsharding_tpu.core.shard import Shard
    from gethsharding_tpu.core.types import (
        Collation, CollationHeader, Transaction)
    from gethsharding_tpu.crypto import secp256k1
    from gethsharding_tpu.crypto.keccak import keccak256
    from gethsharding_tpu.db.kv import MemoryKV
    from gethsharding_tpu.mainchain.client import SMCClient
    from gethsharding_tpu.smc.chain import SimulatedMainchain
    from gethsharding_tpu.utils.hexbytes import Hash32

    priv = 0xDEAD01
    sender = secp256k1.priv_to_address(priv)
    fresh = secp256k1.priv_to_address(0xF5E5)
    proposer = secp256k1.priv_to_address(0xFACADE)
    bad = [sp.sign_transaction(  # bad nonce -> rejected
        Transaction(nonce=9, gas_price=1, gas_limit=30000, to=fresh,
                    value=1, payload=b""), priv)]
    header = CollationHeader(shard_id=0, chunk_root=Hash32(keccak256(b"z")),
                             period=1, proposer_address=proposer)
    collation = Collation(header=header, transactions=bad)
    genesis = {sender: sp.AccountState(balance=10**9)}
    roots = {}
    for engine in ("python",):  # device twin covered in slow tests
        obs = Observer(client=SMCClient(backend=SimulatedMainchain()),
                       shard=Shard(0, MemoryKV()), replay_engine=engine,
                       genesis=genesis)
        roots[engine] = obs.replay_collation(1, collation)
        assert obs.txs_rejected == 1
        # zero rows exist for every touched address
        assert bytes(fresh) in {bytes(a) for a in obs.state.accounts}
    # scalar twin of the device table semantics
    twin = sp.ShardState({sender: sp.AccountState(balance=10**9)})
    for addr in sp.replay_account_table(bad, twin.accounts, proposer):
        twin.get(addr)
    sp.process(twin, bad, proposer)
    # canonical root: zero-row materialization must NOT change it (empty
    # accounts are absent from the state trie)
    assert twin.trie_root() == roots["python"]
    assert sp.ShardState({sender: sp.AccountState(balance=10**9)}
                         ).trie_root() == roots["python"]
