"""Tests for the `evm` standalone SMC runner and the `bindgen` typed
binding generator (the cmd/evm and abigen analogs, tools.py)."""

import inspect
import json
import os
import time

import pytest

from gethsharding_tpu.node.cli import build_parser, run_cli
from gethsharding_tpu.tools import generate_bindings

TESTDATA = os.path.join(os.path.dirname(__file__), "testdata")


def test_evm_runs_frozen_scenario(capsys):
    """The runner replays the conformance scenario fixture and reports
    the header record the script added."""
    rc = run_cli(["evm", os.path.join(TESTDATA, "smc.json")])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    state = out["state"]
    assert state["reverts"] == 0
    assert state["period"] == 1
    assert len(state["pool"]) == 4
    record = state["records"]["1,1"]
    assert record["chunk_root"].startswith("a48ffb9a")
    assert record["vote_count"] == 0  # the frozen script adds, not votes
    # every scripted op appears in the trace with ok status
    assert all(line["status"] == "ok" for line in out["trace"])


def test_evm_vote_eligible_and_trace(tmp_path, capsys):
    """A scenario exercising voting: eligible committee members vote and
    the approval registers once quorum is met."""
    scenario = {
        "config": {"shard_count": 3, "committee_size": 4, "quorum_size": 1},
        "account_seeds": ["conform-smc-%d" % i for i in range(4)],
        "script": [
            {"op": "register", "addr": a} for a in json.load(
                open(os.path.join(TESTDATA, "smc.json")))["addresses"]
        ] + [
            {"op": "fast_forward", "periods": 1},
            {"op": "add_header", "shard": 1, "period": 1,
             "chunk_root": "11" * 32},
            {"op": "vote_eligible", "shard": 1, "period": 1,
             "chunk_root": "11" * 32},
        ],
    }
    path = tmp_path / "scenario.json"
    path.write_text(json.dumps(scenario))
    rc = run_cli(["evm", str(path), "--trace"])
    assert rc == 0
    # --trace prints one line per op, then the indented final object
    joined = capsys.readouterr().out
    final = json.loads(joined[joined.index('{\n "trace"'):])
    state = final["state"]
    assert state["last_approved"].get("1") == 1
    assert state["records"]["1,1"]["vote_count"] >= 1


def test_evm_revert_is_reported_not_fatal(tmp_path, capsys):
    scenario = {
        "config": {"shard_count": 2, "committee_size": 2, "quorum_size": 2},
        "account_seeds": ["rev-0"],
        "script": [
            {"op": "add_header", "shard": 5, "period": 0,
             "chunk_root": "22" * 32},  # shard out of range -> revert
        ],
    }
    path = tmp_path / "bad.json"
    path.write_text(json.dumps(scenario))
    rc = run_cli(["evm", str(path)])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["state"]["reverts"] == 1
    assert out["trace"][0]["status"] == "revert"


def test_evm_bad_ops_report_reverts_not_tracebacks(tmp_path, capsys):
    """Unregistered voter, checksummed addresses and missing accounts
    all land in the trace as reverts/oks — never an uncaught crash."""
    fx = json.load(open(os.path.join(TESTDATA, "smc.json")))
    checksummed = "0x" + fx["addresses"][0].upper()
    scenario = {
        "config": {"shard_count": 2, "committee_size": 2, "quorum_size": 2},
        "account_seeds": fx["account_seeds"][:1],
        "script": [
            {"op": "register", "addr": checksummed},  # case-insensitive
            {"op": "submit_vote", "addr": fx["addresses"][1],
             "shard": 0, "chunk_root": "33" * 32},  # unknown account
        ],
    }
    path = tmp_path / "edge.json"
    path.write_text(json.dumps(scenario))
    assert run_cli(["evm", str(path)]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["trace"][0]["status"] == "ok"
    assert out["trace"][1]["status"] == "revert"

    empty = {"script": [{"op": "add_header", "shard": 0,
                         "chunk_root": "44" * 32}]}
    path2 = tmp_path / "empty.json"
    path2.write_text(json.dumps(empty))
    assert run_cli(["evm", str(path2)]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["trace"][0]["status"] == "revert"
    assert "account_seeds" in out["trace"][0]["reason"]


def test_bindgen_matches_server_surface(tmp_path):
    """The generated class has one method per rpc_* server method, each
    forwarding to the shard_-namespaced wire name with the same
    signature."""
    from gethsharding_tpu.rpc.server import RPCServer

    code = generate_bindings()
    namespace = {}
    exec(compile(code, "<bindgen>", "exec"), namespace)
    binding_cls = namespace["ChainBinding"]

    server_methods = {n[len("rpc_"):] for n in dir(RPCServer)
                      if n.startswith("rpc_")}
    bound_methods = {n for n in vars(binding_cls)
                     if not n.startswith("_")}
    assert bound_methods == server_methods

    class RecordingConn:
        def __init__(self):
            self.calls = []

        def call(self, method, *params):
            self.calls.append((method, params))
            return {"ok": True}

    conn = RecordingConn()
    binding = binding_cls(conn)
    assert binding.blockNumber() == {"ok": True}
    binding.collationRecord(3, 7)
    assert conn.calls == [("shard_blockNumber", ()),
                          ("shard_collationRecord", (3, 7))]

    # defaults are preserved (blockByNumber's number=None)
    sig = inspect.signature(binding_cls.blockByNumber)
    assert sig.parameters["number"].default is None


def test_bindgen_cli_writes_file(tmp_path, capsys):
    out = tmp_path / "binding.py"
    rc = run_cli(["bindgen", "-o", str(out)])
    assert rc == 0
    assert "class ChainBinding" in out.read_text()


def test_bindgen_binding_works_against_live_server():
    """End-to-end: generated bindings drive a real chain server over the
    real RPC client."""
    from gethsharding_tpu.params import Config
    from gethsharding_tpu.rpc.client import RPCClient
    from gethsharding_tpu.rpc.server import RPCServer
    from gethsharding_tpu.smc.chain import SimulatedMainchain

    backend = SimulatedMainchain(config=Config(shard_count=3))
    server = RPCServer(backend)
    server.start()
    try:
        client = RPCClient(*server.address)
        try:
            namespace = {}
            exec(compile(generate_bindings(), "<bindgen>", "exec"), namespace)
            binding = namespace["ChainBinding"](client)
            assert binding.blockNumber() == 0
            backend.commit()
            assert binding.blockNumber() == 1
            assert binding.shardCount() == 3
        finally:
            client.close()
    finally:
        server.stop()


# == swarm CLI (cmd/swarm up/get/serve role) ================================


def test_swarm_up_get_local_roundtrip(tmp_path, capsys):
    from gethsharding_tpu.node.cli import run_cli

    blob = os.urandom(9000)
    src = tmp_path / "content.bin"
    src.write_bytes(blob)
    datadir = str(tmp_path / "store")
    os.makedirs(datadir)
    assert run_cli(["swarm", "up", str(src), "--datadir", datadir]) == 0
    root = capsys.readouterr().out.strip()
    assert len(root) == 64

    out = tmp_path / "restored.bin"
    assert run_cli(["swarm", "get", root, "--datadir", datadir,
                    "-o", str(out)]) == 0
    assert out.read_bytes() == blob

    # unknown root: loud failure, no partial output
    missing = "ab" * 32
    assert run_cli(["swarm", "get", missing, "--datadir", datadir,
                    "-o", str(tmp_path / "nope")]) == 1


@pytest.mark.slow  # ~9 s three-node socket e2e; the local up/get roundtrip stays fast
def test_swarm_networked_get_via_relay(tmp_path, capsys):
    """Content uploaded on node A retrieves on node B over the shardp2p
    netstore tier (chunks ride the direct plane; the relay introduces)."""
    import threading

    from gethsharding_tpu.node.cli import run_cli
    from gethsharding_tpu.params import Config
    from gethsharding_tpu.rpc.server import RPCServer
    from gethsharding_tpu.smc.chain import SimulatedMainchain

    backend = SimulatedMainchain(config=Config(network_id=31))
    relay = RPCServer(backend, port=0)
    relay.start()
    try:
        host, port = relay.address
        a_dir = str(tmp_path / "a")
        b_dir = str(tmp_path / "b")
        os.makedirs(a_dir)
        os.makedirs(b_dir)
        blob = os.urandom(6000)
        src = tmp_path / "payload.bin"
        src.write_bytes(blob)
        assert run_cli(["swarm", "up", str(src), "--datadir", a_dir]) == 0
        root = capsys.readouterr().out.strip()

        server_thread = threading.Thread(
            target=run_cli,
            args=(["swarm", "serve", "--datadir", a_dir,
                   "--endpoint", f"{host}:{port}", "--runtime", "8"],),
            daemon=True)
        server_thread.start()
        deadline = time.time() + 10
        rc = None
        out = tmp_path / "fetched.bin"
        while time.time() < deadline:
            rc = run_cli(["swarm", "get", root, "--datadir", b_dir,
                          "--endpoint", f"{host}:{port}",
                          "-o", str(out), "--timeout", "3"])
            if rc == 0:
                break
            time.sleep(0.3)
        assert rc == 0
        assert out.read_bytes() == blob
        server_thread.join(timeout=12)  # serve exits at --runtime; no
        # background node outliving the test holding sockets/DBs
        assert not server_thread.is_alive()
    finally:
        relay.stop()
