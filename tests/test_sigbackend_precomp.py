"""Fixed-base pairing precomputation (ISSUE 19).

Differential coverage of the `GETHSHARDING_PRECOMP` path: Miller-loop
line tables resident in the device LRU, keyed by `pk_row_key`, consumed
by the precomp committee kernel instead of re-running the
fixed-argument point arithmetic every dispatch.

- precompute-vs-recompute BIT-IDENTITY over randomized committees ×
  empty rows × infinity slots × cancelled (infinity-aggregate) pk rows
  × the u16 wire × sync/async — every verdict pinned to
  `PythonSigBackend`;
- LRU eviction churn of line tables under a starvation budget (tables
  evict, verdicts hold, accounting stays bounded);
- the small-fix regression: line tables charged at TRUE dtype-width
  bytes, so the cache's claimed accounting equals the byte-for-byte
  buffer census exactly (devscope's 5%+64KiB drift gate stays quiet);
- non-vacuity via compiled-HLO op census (`count_ops`, the PR-18
  collective-count idiom): the precomp executable must carry far fewer
  `multiply` ops than its recompute twin;
- tri-layout (1/2/8-device mesh) bit-identity with per-shard line
  tables, one collective per step, and disjoint shard ownership.

Host-only policy tests stay in the fast tier; everything compiling a
pairing kernel at a NEW shape is marked `slow` (the fast-tier dispatch
tests reuse the resident suite's bucket-4 shapes, warm in the
persistent compile cache).
"""

import functools
import random

import pytest

from gethsharding_tpu import metrics
from gethsharding_tpu.crypto import bn256 as bls
from gethsharding_tpu.sigbackend import JaxSigBackend, get_backend
from gethsharding_tpu.sigbackend.layout import count_ops

# one shared key pool: rows drawn from it recur across rounds, so the
# line-table LRU sees hits, misses AND churn under a tiny byte budget
KEYPOOL = [bls.bls_keygen(b"pre-pool-%d" % i) for i in range(8)]


def _rand_round(rng, n_rows=4, max_k=3):
    """One randomized batch: (msgs, sig_rows, pk_rows, row_keys).

    Rows cover empty committees, infinity (None) signature/pubkey
    slots, tampered signatures, pk rows CANCELLED to the infinity
    aggregate (pk + (-pk) — the table must be the infinity-marked
    rejection, never a stale accept), and honest rows. Shapes stay
    inside one compile bucket (n_rows=4, width<=4). Row keys derive
    from the pk row CONTENT (member set + transform marker) — the
    caller contract that keys uniquely determine the row's points."""
    msgs, sig_rows, pk_rows, keys = [], [], [], []
    for _ in range(n_rows):
        kind = rng.random()
        tag = b"pre-msg-%d" % rng.randrange(6)
        if kind < 0.12:
            msgs.append(tag)
            sig_rows.append([])
            pk_rows.append([])
            keys.append(None)
            continue
        k = rng.randrange(1, max_k + 1)
        members = rng.sample(range(len(KEYPOOL)), k)
        sigs = [bls.bls_sign(tag, KEYPOOL[i][0]) for i in members]
        pks = [KEYPOOL[i][1] for i in members]
        mark = "plain"
        if kind < 0.26 and k >= 2:
            sigs[0] = None  # infinity signature slot (skipped, both paths)
            mark = "isig"
        elif kind < 0.40 and k >= 2:
            pks[1] = None  # infinity pubkey slot
            mark = "ipk"
        elif kind < 0.54:
            sigs[-1] = bls.bls_sign(b"tampered", KEYPOOL[members[-1]][0])
            mark = "forged"  # pk row unchanged; marker only aids debug
        elif kind < 0.68 and k >= 2:
            pks = [pks[0], bls.g2_neg(pks[0])] + pks[2:]
            mark = "cancel"  # pk aggregate = infinity -> reject
        msgs.append(tag)
        sig_rows.append(sigs)
        pk_rows.append(pks)
        keys.append((tuple(members), mark,
                     tuple(i for i, p in enumerate(pks) if p is None)))
    return msgs, sig_rows, pk_rows, keys


# -- flag + policy (host-only, fast tier) ----------------------------------


def test_precomp_flag_validation(monkeypatch):
    monkeypatch.setenv("GETHSHARDING_PRECOMP", "yes")
    with pytest.raises(ValueError):
        JaxSigBackend()
    monkeypatch.setenv("GETHSHARDING_PRECOMP", "0")
    off = JaxSigBackend()
    assert off._precomp is False
    # flag off: no generator table is shipped at construction
    assert off._gen_lines_dev is None and off._gen_lines_mesh is None
    monkeypatch.setenv("GETHSHARDING_PRECOMP", "1")
    monkeypatch.setenv("GETHSHARDING_PRECOMP_BLOCKS", "0")
    with pytest.raises(ValueError):
        JaxSigBackend()
    monkeypatch.setenv("GETHSHARDING_PRECOMP_BLOCKS", "two")
    with pytest.raises(ValueError):
        JaxSigBackend()
    monkeypatch.delenv("GETHSHARDING_PRECOMP_BLOCKS")
    on = JaxSigBackend()
    assert on._precomp is True and on._precomp_blocks == 2  # the default
    assert on._gen_lines_dev is not None


def test_precomp_nblocks_policy(monkeypatch):
    """Pipeline blocks: largest divisor of the bucket not above the
    flag, never splitting below the finalexp mega-kernel lane block."""
    backend = JaxSigBackend()
    monkeypatch.setattr(backend._bn, "FINALEXP", "jax", raising=False)
    backend._precomp_blocks = 4
    assert backend._precomp_nblocks(8) == 4
    assert backend._precomp_nblocks(6) == 3  # largest divisor <= 4
    assert backend._precomp_nblocks(7) == 1  # prime bucket: fused
    assert backend._precomp_nblocks(1) == 1
    monkeypatch.setattr(backend._bn, "FINALEXP", "mega", raising=False)
    from gethsharding_tpu.ops.pallas_finalexp import block_lanes

    lanes = block_lanes()
    assert backend._precomp_nblocks(lanes) == 1  # one lane block: fused
    assert backend._precomp_nblocks(4 * lanes) == 4  # lane-aligned split


def test_count_ops_on_hlo_text():
    hlo = """\
ENTRY main {
  %m = f32[8]{0} multiply(%a, %b)
  %s = f32[8]{0} add(%a, %b)
  %m2 = f32[8]{0} multiply(%m, %s)
}
"""
    assert count_ops(hlo, "multiply") == 2
    assert count_ops(hlo, "add") == 1
    assert count_ops("", "multiply") == 0


# -- single-device dispatch differentials (resident-suite shapes) ----------


@pytest.mark.parametrize("wire", ["i32", "u16"])
def test_randomized_precomp_parity_sync_async(monkeypatch, wire):
    """Randomized rounds: sync and async precomp verdicts match the
    scalar backend bit-for-bit, across the wire dtypes, with the
    precomp path engaged (line tables, not pk planes)."""
    if wire == "u16":
        monkeypatch.setenv("GETHSHARDING_TPU_WIRE", "u16")
    else:
        monkeypatch.delenv("GETHSHARDING_TPU_WIRE", raising=False)
    monkeypatch.setenv("GETHSHARDING_PRECOMP", "1")
    backend = JaxSigBackend()
    assert backend._precomp
    py = get_backend("python")
    rng = random.Random(777 if wire == "i32" else 778)
    for _ in range(3):
        msgs, sig_rows, pk_rows, keys = _rand_round(rng)
        want = py.bls_verify_committees(msgs, sig_rows, pk_rows)
        sync = backend.bls_verify_committees(
            msgs, sig_rows, pk_rows, pk_row_keys=keys)
        future = backend.bls_verify_committees_async(
            msgs, sig_rows, pk_rows, pk_row_keys=keys)
        assert sync == future.result() == want
        assert backend.last_wire["precomp"] is True


def test_warm_line_tables_ship_zero_g2_bytes():
    """The steady-state precomp shape: cold pays ONE precompute
    dispatch and ships the miss rows' pk planes; warm ships ZERO G2
    bytes — the table hit replaces even the pk-plane transfer the
    recompute-resident path would take."""
    backend = JaxSigBackend()  # defaults: resident on, precomp on
    assert backend._precomp
    rng = random.Random(42)
    msgs, sig_rows, pk_rows, keys = _rand_round(rng)
    while not any(pk_rows):  # need at least one pointful row
        msgs, sig_rows, pk_rows, keys = _rand_round(rng)
    want = get_backend("python").bls_verify_committees(
        msgs, sig_rows, pk_rows)
    cold = backend.bls_verify_committees(
        msgs, sig_rows, pk_rows, pk_row_keys=keys)
    assert cold == want
    assert backend.last_wire["precomp"] is True
    assert backend.last_wire["g2_wire_bytes"] > 0
    warm = backend.bls_verify_committees(
        msgs, sig_rows, pk_rows, pk_row_keys=keys)
    assert warm == want
    assert backend.last_wire["g2_wire_bytes"] == 0
    assert (backend.last_wire["pk_hit_rows"]
            == backend.last_wire["pk_rows"]
            == sum(1 for r in pk_rows if r))
    # a SHORT key list marks trailing rows uncached, not dropped: the
    # unkeyed pointful rows precompute per dispatch, verdict unchanged
    assert backend.bls_verify_committees(
        msgs, sig_rows, pk_rows, pk_row_keys=keys[:1]) == want
    assert backend.last_wire["precomp"] is True
    # keyless dispatch: residency (and so precomp) disengages — the
    # recompute path answers, bit-identical
    assert backend.bls_verify_committees(msgs, sig_rows, pk_rows) == want
    assert backend.last_wire["precomp"] is False


def test_line_table_eviction_churn(monkeypatch):
    """Fresh keys every round under a ~2 KB budget: every line-table
    insert immediately evicts (a table alone is ~50 KB), verdicts stay
    bit-identical, the byte accounting respects the budget."""
    monkeypatch.setenv("GETHSHARDING_TPU_RESIDENT_MB", "0.002")
    backend = JaxSigBackend()
    assert backend._precomp
    py = get_backend("python")
    evictions = metrics.counter("jax/pk_device_cache/evictions")
    before = evictions.value
    rng = random.Random(1357)
    for rnd in range(3):
        msgs, sig_rows, pk_rows, keys = _rand_round(rng)
        keys = [None if k is None else (rnd,) + k for k in keys]
        want = py.bls_verify_committees(msgs, sig_rows, pk_rows)
        got = backend.bls_verify_committees(
            msgs, sig_rows, pk_rows, pk_row_keys=keys)
        assert got == want, f"round {rnd} verdicts diverge under churn"
    assert evictions.value > before
    assert backend._pk_dev_bytes <= backend._resident_budget


def test_line_table_bytes_are_true_dtype_width(monkeypatch):
    """The ISSUE-19 small fix: line tables are charged at their TRUE
    int32 byte width, not a pk-plane-shape estimate — the cache's own
    accounting must equal the byte-for-byte census of every buffer it
    owns EXACTLY (u16 wire especially: pk planes narrow to u16 while
    tables stay i32), so devscope's claimed-vs-census drift gate
    (5%+64KiB) stays quiet on precomp-heavy workloads."""
    monkeypatch.setenv("GETHSHARDING_TPU_WIRE", "u16")
    backend = JaxSigBackend()
    assert backend._precomp
    rng = random.Random(99)
    msgs, sig_rows, pk_rows, keys = _rand_round(rng)
    while not any(pk_rows):
        msgs, sig_rows, pk_rows, keys = _rand_round(rng)
    want = get_backend("python").bls_verify_committees(
        msgs, sig_rows, pk_rows)
    for _ in range(2):  # cold (insert) + warm (memo) both censused
        assert backend.bls_verify_committees(
            msgs, sig_rows, pk_rows, pk_row_keys=keys) == want
    claimed = backend._resident_claimed_bytes()
    actual = sum(int(b.nbytes) for b in backend._resident_buffers())
    assert claimed == actual > 0, (
        f"resident accounting drifted from the buffer census: "
        f"claimed={claimed} actual={actual}")
    # and the devscope census agrees: the registered owner shows no
    # drift (this instance is the latest registrant of pk_plane_lru;
    # a throwaway poller walks the real live buffers — no boot() needed)
    from gethsharding_tpu.devscope.memory import MemoryPoller

    entry = MemoryPoller(interval_s=60).census()["owners"].get(
        "pk_plane_lru")
    assert entry is not None
    assert not entry.get("drifted"), entry


# -- non-vacuity: the compiled-HLO op census (slow: new AOT shape) ---------


@pytest.mark.slow
def test_precomp_hlo_census_drops_point_arithmetic():
    """The warm path really skips the dbl/madd point arithmetic: the
    AOT-compiled precomp executable carries far fewer `multiply` ops
    than the recompute twin at the same shape (same idiom as the mesh
    suite's collective count — optimized HLO text, no hand-claims)."""
    import jax
    import jax.numpy as jnp

    from gethsharding_tpu.ops import bn256_jax as k

    nl = k.NLIMBS
    steps = k.LINE_TABLE_SHAPE[0]
    b, w = 1, 2
    z32 = functools.partial(jnp.zeros, dtype=jnp.int32)
    pre_args = (z32((b, nl)), z32((b, nl)),
                z32((b, w, nl)), z32((b, w, nl)), jnp.zeros((b, w), bool),
                z32((b, steps, 3, 2, nl)),
                jnp.zeros((b,), bool), jnp.zeros((b,), bool))
    rec_args = (z32((b, nl)), z32((b, nl)),
                z32((b, w, nl)), z32((b, w, nl)), jnp.zeros((b, w), bool),
                z32((b, w, 2, nl)), z32((b, w, 2, nl)),
                jnp.zeros((b, w), bool), jnp.zeros((b,), bool))
    pre_mul = count_ops(jax.jit(k.bls_verify_committee_precomp_batch)
                        .lower(*pre_args).compile().as_text(), "multiply")
    rec_mul = count_ops(jax.jit(k.bls_aggregate_verify_committee_batch)
                        .lower(*rec_args).compile().as_text(), "multiply")
    assert 0 < pre_mul < 0.7 * rec_mul, (
        f"precomp executable must drop the fixed-argument point "
        f"arithmetic: {pre_mul} multiplies vs recompute {rec_mul}")


# -- tri-layout mesh differentials (slow: mesh pairing compiles) -----------


@pytest.fixture(scope="module")
def mesh_backends():
    import jax

    if jax.device_count() < 8:
        pytest.skip("needs the 8-device virtual mesh (tests/conftest.py)")
    from gethsharding_tpu.sigbackend.dispatch import JaxSigBackend as B

    return {n: B(mesh_devices=n) for n in (1, 2, 8)}


@functools.lru_cache(maxsize=1)
def _mesh_cols():
    """8 committees (one per 8-device mesh slot): honest rows plus an
    empty committee, an absent voter (infinity slots), a forged vote,
    and a pk aggregate cancelled to infinity."""
    rows, width = 8, 3
    messages, sig_rows, pk_rows, keys = [], [], [], []
    for i in range(rows):
        msg = bytes([23, i]) * 16
        sigs, pks = [], []
        for j in range(width):
            sk, pk = bls.bls_keygen(bytes([i + 1, j + 1, 41]) * 8)
            sigs.append(bls.bls_sign(msg, sk))
            pks.append(pk)
        messages.append(msg)
        sig_rows.append(sigs)
        pk_rows.append(pks)
        keys.append(f"pre-mesh:{i}")
    sig_rows[1], pk_rows[1] = [], []  # empty committee -> False
    sig_rows[2][1] = None  # absent voter: infinity in BOTH halves
    pk_rows[2][1] = None   # -> the other two signers still verify
    forged_sk, _ = bls.bls_keygen(bytes([6, 2, 41]) * 8)
    sig_rows[4][0] = bls.bls_sign(b"some other collation header!!!!!",
                                  forged_sk)
    pk_rows[6] = [pk_rows[6][0], bls.g2_neg(pk_rows[6][0])]  # cancelled
    sig_rows[6] = sig_rows[6][:2]
    return messages, sig_rows, pk_rows, keys


@functools.lru_cache(maxsize=1)
def _mesh_want():
    messages, sig_rows, pk_rows, _ = _mesh_cols()
    want = get_backend("python").bls_verify_committees(
        messages, sig_rows, pk_rows)
    assert want == [True, False, True, True, False, True, False, True]
    return want


@pytest.mark.slow
def test_precomp_tri_layout_bit_identity(mesh_backends):
    messages, sig_rows, pk_rows, keys = _mesh_cols()
    want = _mesh_want()
    for n, backend in sorted(mesh_backends.items()):
        assert backend._precomp, f"{n}-device backend must default on"
        got = backend.bls_verify_committees(messages, sig_rows, pk_rows,
                                            pk_row_keys=keys)
        assert got == want, f"{n}-device sync verdicts diverge"
        fut = backend.bls_verify_committees_async(
            messages, sig_rows, pk_rows, pk_row_keys=keys)
        assert fut.result() == want, f"{n}-device async verdicts diverge"
        assert backend.last_wire["precomp"] is True
        if n > 1:
            info = backend.last_mesh
            assert info["precomp"] is True
            assert info["collectives"] == 1, (
                f"{n}-device precomp step must psum ONCE: {info}")
            assert info["verdict_devices"] == n
            assert info["vote_total"] == sum(want)


@pytest.mark.slow
def test_precomp_mesh_warm_zero_g2_and_disjoint_shards(mesh_backends):
    """Warm mesh dispatch: line tables hit in every per-device shard
    (zero G2 bytes), and shard buffer ownership — tables included —
    stays pairwise DISJOINT under the per-shard census owners."""
    backend = mesh_backends[8]
    messages, sig_rows, pk_rows, keys = _mesh_cols()
    want = _mesh_want()
    for _ in range(2):
        assert backend.bls_verify_committees(
            messages, sig_rows, pk_rows, pk_row_keys=keys) == want
    assert backend.last_wire["precomp"] is True
    assert backend.last_wire["g2_wire_bytes"] == 0
    buf_ids = [set(map(id, backend._mesh_shard_buffers(i)))
               for i in range(8)]
    for i in range(8):
        assert buf_ids[i], f"shard{i} owns no buffers after a dispatch"
        for j in range(i + 1, 8):
            assert not (buf_ids[i] & buf_ids[j]), (
                f"shards {i} and {j} both claim a buffer")
    assert sum(backend._mesh_claimed_bytes(i) for i in range(8)) > 0
