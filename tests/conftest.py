"""Test configuration: hermetic CPU-only JAX with an 8-device virtual mesh.

Multi-chip sharding paths (`gethsharding_tpu.parallel`) are exercised on a
virtual 8-device CPU mesh (XLA host-platform device count), mirroring how the
driver dry-runs `__graft_entry__.dryrun_multichip`. The forcing logic lives
in `gethsharding_tpu.parallel.virtual` (shared with the dryrun entry) and
must run before any backend init, hence at conftest import time.
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from gethsharding_tpu.parallel.virtual import force_virtual_cpu_devices

force_virtual_cpu_devices(8)
