"""Test configuration: hermetic CPU-only JAX with an 8-device virtual mesh.

Multi-chip sharding paths (`gethsharding_tpu.parallel`) are exercised on a
virtual 8-device CPU mesh (XLA host-platform device count), mirroring how the
driver dry-runs `__graft_entry__.dryrun_multichip`. The forcing logic lives
in `gethsharding_tpu.parallel.virtual` (shared with the dryrun entry) and
must run before any backend init, hence at conftest import time.
"""

import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from gethsharding_tpu.parallel.virtual import force_virtual_cpu_devices

force_virtual_cpu_devices(8)

# The persistent compile cache is DISABLED (stickily) for MULTI-file
# pytest runs: XLA:CPU deterministically segfaults DESERIALIZING a large
# cached executable once the process holds many compiled programs.
# Pinpointed r3 (faulthandler): the crash is inside
# jax/_src/compilation_cache.py:get_executable_and_time — a cache READ
# of an entry this same host wrote and that loads fine in a short-lived
# process (run_suite.sh runs the exact same file green) — i.e. an
# XLA-side deserializer bug triggered by executable-count pressure, not
# by our programs. The off-state must be STICKY because tests that call
# force_virtual_cpu_devices (the dryrun) would otherwise re-enable the
# cache mid-suite — exactly how the r3 repro crashed at test_replay.
# Single-file invocations keep the cache automatically (decided at
# collection time below), GETHSHARDING_CACHE_WRITES=1 forces it on, and
# `scripts/run_suite.sh` runs the complete suite one process per file —
# full cache speedup, identical coverage, no crash.
import os as _os

from gethsharding_tpu.parallel.virtual import configure_compile_cache

if _os.environ.get("GETHSHARDING_CACHE_WRITES") != "1":
    configure_compile_cache(enabled=False)

# Test tiers: everything in these modules compiles the heavyweight batched
# kernels (pairing Miller loops, 256-step recovery ladders) — minutes of
# XLA:CPU compile when the persistent cache is cold. They are auto-marked
# `slow`; the fast tier (`pytest -m "not slow"`) stays green in <60s cold.
_SLOW_MODULES = {
    "test_bn256_jax",
    "test_secp256k1_jax",
    "test_sigbackend",
    "test_graft_entry",
    "test_period_pipeline",
    "test_end_to_end",
    "test_limb",  # the Fermat-inversion pow chains dominate its compiles
    "test_replay",
    "test_stress",
    "test_pallas",  # interpreter-mode kernels are slow per element
}


def pytest_collection_modifyitems(config, items):
    modules = set()
    for item in items:
        modules.add(item.module.__name__)
        if item.module.__name__ in _SLOW_MODULES:
            item.add_marker(pytest.mark.slow)
    if len(modules) == 1:
        # a single-module run is a short-lived process — the safe case;
        # re-enable the cache (nothing has compiled yet at collection
        # time, so the config change takes full effect). force=True
        # overrides the sticky off-state set at import above.
        configure_compile_cache(force=True)
