"""Test configuration: hermetic CPU-only JAX with an 8-device virtual mesh.

Multi-chip sharding paths (`gethsharding_tpu.parallel`) are exercised on a
virtual 8-device CPU mesh (XLA host-platform device count), mirroring how the
driver dry-runs `__graft_entry__.dryrun_multichip`. Must run before any jax
import, hence environment mutation at conftest import time.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# If a TPU-tunnel PJRT plugin (e.g. the axon sitecustomize hook) registered
# itself at interpreter start, drop it from the backend factories: tests are
# CPU-only by design, and a flaky tunnel must not hang backend init.
try:  # pragma: no cover - environment-dependent
    import jax
    import jax._src.xla_bridge as _xb

    # pytest plugins may import jax before this conftest runs, freezing
    # jax_platforms from the pre-mutation environment — override it too.
    jax.config.update("jax_platforms", "cpu")
    for _name in list(getattr(_xb, "_backend_factories", {})):
        if _name not in ("cpu",):
            _xb._backend_factories.pop(_name, None)
    # Persistent compilation cache: the pairing kernels take minutes to
    # compile on XLA:CPU; cache hits make repeat test runs near-instant.
    from pathlib import Path

    jax.config.update("jax_compilation_cache_dir",
                      str(Path(__file__).resolve().parents[1] / ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
except Exception:
    pass
