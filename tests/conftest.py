"""Test configuration: hermetic CPU-only JAX with an 8-device virtual mesh.

Multi-chip sharding paths (`gethsharding_tpu.parallel`) are exercised on a
virtual 8-device CPU mesh (XLA host-platform device count), mirroring how the
driver dry-runs `__graft_entry__.dryrun_multichip`. Must run before any jax
import, hence environment mutation at conftest import time.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
