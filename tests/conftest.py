"""Test configuration: hermetic CPU-only JAX with an 8-device virtual mesh.

Multi-chip sharding paths (`gethsharding_tpu.parallel`) are exercised on a
virtual 8-device CPU mesh (XLA host-platform device count), mirroring how the
driver dry-runs `__graft_entry__.dryrun_multichip`. The forcing logic lives
in `gethsharding_tpu.parallel.virtual` (shared with the dryrun entry) and
must run before any backend init, hence at conftest import time.
"""

import os as _os
import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

# The lock recorder must patch threading BEFORE any package module is
# imported: module-level singletons (metrics.DEFAULT_REGISTRY, the
# tracer) allocate their locks at import time, and a lock created
# before the patch is real, unlabeled and invisible — every write it
# guards would look lockless to the race sanitizer and the session
# gate would report false violations against the static model.
# analysis/lockcheck imports nothing from the runtime packages, so
# this is safe ahead of the virtual-device forcing below.
if _os.environ.get("GETHSHARDING_LOCKCHECK") == "1" or \
        _os.environ.get("GETHSHARDING_RACECHECK") == "1":
    from gethsharding_tpu.analysis import lockcheck as _lockcheck_early

    _lockcheck_early.install()

from gethsharding_tpu.parallel.virtual import force_virtual_cpu_devices

force_virtual_cpu_devices(8)

# perfwatch hermeticity: the flight recorder dumps post-mortem bundles
# on every breaker trip / watchdog fire / soundness violation — events
# the resilience suites trigger ON PURPOSE, hundreds of times. Point
# the bundle directory and the benchmark ledger at a session temp dir
# (unless the caller pinned them) so a test run never litters the repo
# with black-box bundles or appends test noise to the committed
# measurement history.
if "GETHSHARDING_PERFWATCH_DIR" not in _os.environ:
    import tempfile as _tempfile

    _os.environ["GETHSHARDING_PERFWATCH_DIR"] = _tempfile.mkdtemp(
        prefix="perfwatch_blackbox_")
if "GETHSHARDING_PERFWATCH_LEDGER" not in _os.environ:
    import tempfile as _tempfile

    _os.environ["GETHSHARDING_PERFWATCH_LEDGER"] = _os.path.join(
        _tempfile.mkdtemp(prefix="perfwatch_ledger_"), "ledger.jsonl")

# XLA:CPU deterministically segfaults once a process holds too many
# compiled programs (~150): r3 faulthandler runs place the crash at the
# SAME test/program both inside the persistent-cache deserializer
# (compilation_cache.get_executable_and_time) AND, with the cache off,
# inside plain backend_compile_and_load — i.e. executable-COUNT pressure
# in XLA's loader, not the cache and not our programs (the same file
# runs green in a short-lived process). The fix is to keep the live
# executable count low: `jax.clear_caches()` after every test module
# (autouse fixture below). With pressure bounded, the persistent cache
# is safe again and stays ENABLED — one-process `pytest tests/` runs
# green AND takes cache hits. GETHSHARDING_CACHE_OFF=1 disables the
# cache for debugging; `scripts/run_suite.sh` (one process per file)
# remains an equivalent, maximally isolated entry.
import gc as _gc

from gethsharding_tpu.parallel.virtual import configure_compile_cache

if _os.environ.get("GETHSHARDING_CACHE_OFF") == "1":
    configure_compile_cache(enabled=False)

# GETHSHARDING_LOCKCHECK=1: wrap threading.Lock/RLock with the runtime
# lock-order recorder (analysis/lockcheck.py) for the whole session and
# assert, at session end, that the OBSERVED acquisition orders are
# inversion-free and consistent with the static lock graph the
# lock-order lint derives — the race-detector-lite that keeps the
# static model honest. Install happens at conftest import so every
# lock a test creates is wrapped.
if _os.environ.get("GETHSHARDING_LOCKCHECK") == "1":
    from gethsharding_tpu.analysis import lockcheck as _lockcheck

    _lockcheck.install()  # idempotent: the early install above won

# GETHSHARDING_RACECHECK=1: instrument attribute writes on the
# registered component classes (analysis/racecheck.py) with the runtime
# access sanitizer — per-(instance, attr) Eraser lockset tracking over
# real threads. The session gate below cross-validates the observed
# write locksets against the static race-guard model: a shared write
# the static map calls guarded running with no lock is a violation;
# statically-flagged attrs the tests never drove shared are printed as
# honest coverage gaps. Installing implies the lock recorder (the
# sanitizer reads per-thread held locks from it).
if _os.environ.get("GETHSHARDING_RACECHECK") == "1":
    from gethsharding_tpu.analysis import racecheck as _racecheck

    _racecheck.install()


@pytest.fixture(scope="session", autouse=True)
def _lockcheck_gate():
    yield
    from gethsharding_tpu.analysis import lockcheck

    if not lockcheck.active():
        return
    verdict = lockcheck.verify_against_static()
    observed = len(lockcheck.report()["edges"])
    print(f"\nlockcheck: {observed} lock-order edge(s) observed, "
          f"{len(verdict.inversions)} inversion(s), "
          f"{len(verdict.static_violations)} static violation(s), "
          f"{len(verdict.coverage_gaps)} coverage gap(s)")
    assert not verdict.inversions, (
        "lockcheck: AB/BA lock-order inversion observed:\n" + "\n".join(
            f"  {inv.second[0]} -> {inv.second[1]} reverses "
            f"{inv.first[0]} -> {inv.first[1]} (first seen at "
            f"{inv.first_site})" for inv in verdict.inversions))
    assert not verdict.static_violations, (
        "lockcheck: observed order contradicts the static lock graph:\n"
        + "\n".join(f"  {v}" for v in verdict.static_violations))
    if verdict.coverage_gaps:  # informational: model under-approximates
        print("\nlockcheck coverage gaps (observed, not in static graph):")
        for gap in verdict.coverage_gaps:
            print(f"  {gap}")


@pytest.fixture(scope="session", autouse=True)
def _racecheck_gate():
    yield
    import json as _json

    from gethsharding_tpu.analysis import racecheck

    if not racecheck.active():
        return
    baseline_path = (Path(__file__).resolve().parents[1]
                     / "gethsharding_tpu/analysis/baseline.json")
    baselined = set()
    if baseline_path.is_file():
        data = _json.loads(baseline_path.read_text())
        baselined = {key.split("::", 1)[1]
                     for key in data.get("findings", {})
                     if key.startswith("race-guard::")}
    verdict = racecheck.verify_against_static(baseline_keys=baselined)
    stats = racecheck.stats()
    print(f"\nracecheck: {stats['writes_seen']} write(s) on "
          f"{stats['attrs_written']} attr(s) across "
          f"{stats['classes_instrumented']} instrumented class(es); "
          f"{stats['shared_attrs']} shared, "
          f"{stats['unguarded_shared']} unguarded-shared, "
          f"{len(verdict.violations)} violation(s), "
          f"{len(verdict.confirmations)} confirmation(s), "
          f"{len(verdict.coverage_gaps)} coverage gap(s)")
    assert not verdict.violations, (
        "racecheck: runtime write locksets contradict the static "
        "race-guard model:\n" + "\n".join(f"  {v}"
                                          for v in verdict.violations))
    if verdict.confirmations:
        print("racecheck confirmations (statically flagged AND observed "
              "racing — fix or baseline):")
        for line in verdict.confirmations:
            print(f"  {line}")
    if verdict.coverage_gaps:  # informational: tests never drove these
        print("racecheck coverage gaps (statically racy, never observed "
              "shared this run):")
        for gap in verdict.coverage_gaps:
            print(f"  {gap}")


@pytest.fixture(autouse=True, scope="module")
def _bound_xla_executable_pressure():
    """Drop compiled executables after each module (see header)."""
    yield
    import jax

    jax.clear_caches()
    _gc.collect()

# Test tiers: everything in these modules compiles the heavyweight batched
# kernels (pairing Miller loops, 256-step recovery ladders) — minutes of
# XLA:CPU compile when the persistent cache is cold. They are auto-marked
# `slow`; the fast tier (`pytest -m "not slow"`) holds ~105 s warm
# (the README promise is ≤120 s on this host class).
_SLOW_MODULES = {
    "test_bn256_jax",
    "test_secp256k1_jax",
    "test_sigbackend",
    "test_graft_entry",
    "test_period_pipeline",
    "test_end_to_end",
    "test_limb",  # the Fermat-inversion pow chains dominate its compiles
    "test_replay",
    "test_stress",
    "test_pallas",  # interpreter-mode kernels are slow per element
    "test_knob_combos",  # one cold kernel compile per subprocess
}
# test_pallas_finalexp stays in the FAST tier on purpose: its three
# cheap helper parity tests (normalize/conv/mul_xi) are the fast guard
# on the mega-kernel module (arity/import regressions); the heavier
# parity/oracle/interpret/miller differentials carry `@slow` marks.


def pytest_collection_modifyitems(config, items):
    for item in items:
        if item.module.__name__ in _SLOW_MODULES:
            item.add_marker(pytest.mark.slow)
