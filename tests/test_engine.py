"""Consensus-engine tests (`consensus/consensus.go` seam): fake-engine
byte compatibility, dev PoW seal/verify, clique authorization rules +
signer voting, and the chain integration (sealed commits, verified
imports, engine state through rollbacks)."""

import pytest

from gethsharding_tpu.crypto import secp256k1
from gethsharding_tpu.crypto.keccak import keccak256
from gethsharding_tpu.mainchain.accounts import AccountManager
from gethsharding_tpu.params import Config
from gethsharding_tpu.smc.chain import Block, SimulatedMainchain
from gethsharding_tpu.smc.engine import (
    CliqueEngine, DevPoWEngine, FakeEngine, InvalidHeader)
from gethsharding_tpu.utils.hexbytes import Hash32
from gethsharding_tpu.utils.rlp import int_to_big_endian, rlp_encode


def _accounts(n, seed=b"engine"):
    manager = AccountManager()
    return manager, [manager.new_account(seed=seed + b"-%d" % i)
                     for i in range(n)]


def test_fake_engine_matches_pre_engine_hashes():
    """The default engine must keep every historical block hash: the
    empty-extra hash is keccak(rlp([number, parent])) exactly as
    SimulatedMainchain._block_hash computed it."""
    engine = FakeEngine()
    parent = Hash32(keccak256(b"parent"))
    block_hash, extra = engine.seal(7, parent)
    assert extra == b""
    legacy = keccak256(rlp_encode([int_to_big_endian(7), bytes(parent)]))
    assert bytes(block_hash) == legacy
    assert bytes(SimulatedMainchain._block_hash(7, parent)) == legacy
    engine.verify_header(7, parent, b"", block_hash)
    with pytest.raises(InvalidHeader):
        engine.verify_header(7, parent, b"", Hash32(b"\x01" * 32))


def test_devpow_seal_and_verify():
    engine = DevPoWEngine(difficulty_bits=6)
    parent = Hash32(keccak256(b"pow-parent"))
    block_hash, extra = engine.seal(1, parent)
    assert len(extra) == 8
    engine.verify_header(1, parent, extra, block_hash)
    # a nonce that doesn't clear the target is rejected even with a
    # consistent hash
    bad_nonce = (int.from_bytes(extra, "big") + 1).to_bytes(8, "big")
    bad_hash = engine.hash_header(1, parent, bad_nonce)
    if engine._meets_target(bytes(bad_hash)):  # pragma: no cover - rare
        bad_nonce = (int.from_bytes(extra, "big") + 2).to_bytes(8, "big")
        bad_hash = engine.hash_header(1, parent, bad_nonce)
    with pytest.raises(InvalidHeader, match="work|hash"):
        engine.verify_header(1, parent, bad_nonce, bad_hash)
    with pytest.raises(InvalidHeader, match="8 bytes"):
        engine.verify_header(1, parent, b"\x00" * 4, block_hash)


def test_clique_seal_requires_authorized_in_turn_signer():
    manager, (a, b) = _accounts(2)
    engine = CliqueEngine([a.address, b.address])
    order = [bytes(s) for s in engine.signers()]
    parent = Hash32(keccak256(b"clique-parent"))

    in_turn = engine.in_turn_signer(1)
    sealer = a if bytes(a.address) == bytes(in_turn) else b
    other = b if sealer is a else a

    block_hash, extra = engine.seal_as(
        1, parent, sign_fn=lambda d: manager.sign_hash(sealer.address, d),
        signer=sealer.address)
    assert len(extra) == 65
    engine.verify_header(1, parent, extra, block_hash)
    assert bytes(engine.recover_signer(1, parent, extra)) \
        == bytes(sealer.address)

    # out of turn: refused at seal time AND at verify time
    with pytest.raises(InvalidHeader, match="turn"):
        engine.seal_as(1, parent,
                       sign_fn=lambda d: manager.sign_hash(other.address, d),
                       signer=other.address)
    # a seal by a key outside the signer set is unauthorized
    _, (outsider,) = _accounts(1, seed=b"outsider")
    forged_sig = secp256k1.sign(
        bytes(engine.seal_hash(1, parent, b"")), outsider.priv).to_bytes65()
    forged_hash = engine.hash_header(1, parent, forged_sig)
    with pytest.raises(InvalidHeader, match="unauthorized"):
        engine.verify_header(1, parent, forged_sig, forged_hash)
    assert order == [bytes(s) for s in engine.signers()]  # set unchanged


def engine_signer_account(engine, number, accounts):
    turn = bytes(engine.in_turn_signer(number))
    return next(acct for acct in accounts if bytes(acct.address) == turn)


def test_clique_voting_majority_adds_and_drops_signers():
    manager, accts = _accounts(3, seed=b"vote")
    engine = CliqueEngine([a.address for a in accts], epoch=1000)
    candidate = manager.new_account(seed=b"candidate")
    parent = Hash32(keccak256(b"genesis"))

    def seal_with_vote(number, parent_hash, proposal):
        acct = engine_signer_account(engine, number, accts)
        return engine.seal_as(
            number, parent_hash,
            sign_fn=lambda d: manager.sign_hash(acct.address, d),
            signer=acct.address, proposal=proposal)

    # two of three distinct signers voting "add" reaches majority
    number, votes_applied = 1, 0
    seen_signers = set()
    while votes_applied < 2:
        acct = engine_signer_account(engine, number, accts)
        proposal = ((candidate.address, True)
                    if bytes(acct.address) not in seen_signers else None)
        block_hash, extra = seal_with_vote(number, parent, proposal)
        engine.verify_header(number, parent, extra, block_hash)
        engine.finalize(number, parent, extra)
        if proposal is not None:
            seen_signers.add(bytes(acct.address))
            votes_applied += 1
        parent = block_hash
        number += 1
    assert bytes(candidate.address) in [bytes(s) for s in engine.signers()]
    assert len(engine.signers()) == 4

    # now drop the candidate: 3 votes needed for majority of 4
    voted = set()
    while bytes(candidate.address) in [bytes(s) for s in engine.signers()]:
        turn = bytes(engine.in_turn_signer(number))
        all_accts = accts + [candidate]
        acct = next(x for x in all_accts if bytes(x.address) == turn)
        proposal = None
        if acct is not candidate and bytes(acct.address) not in voted:
            proposal = (candidate.address, False)
        block_hash, extra = engine.seal_as(
            number, parent,
            sign_fn=lambda d: manager.sign_hash(acct.address, d),
            signer=acct.address, proposal=proposal)
        engine.finalize(number, parent, extra)
        if proposal is not None:
            voted.add(bytes(acct.address))
        parent = block_hash
        number += 1
    assert len(engine.signers()) == 3


def test_clique_refuses_dropping_last_signer():
    """A majority drop of the final signer would wedge the chain; the
    tally is discarded instead (the set can never become empty)."""
    manager, (a,) = _accounts(1, seed=b"lastdrop")
    engine = CliqueEngine([a.address], epoch=1000)
    parent = Hash32(keccak256(b"lastdrop-parent"))
    block_hash, extra = engine.seal_as(
        1, parent, sign_fn=lambda d: manager.sign_hash(a.address, d),
        signer=a.address, proposal=(a.address, False))
    engine.finalize(1, parent, extra)
    assert [bytes(s) for s in engine.signers()] == [bytes(a.address)]
    # the chain still seals: no ZeroDivisionError, no empty rotation
    engine.in_turn_signer(2)
    _, votes = engine.snapshot()
    assert votes == []  # discarded tally leaves no dangling votes


def test_clique_epoch_clears_pending_votes():
    manager, accts = _accounts(3, seed=b"epoch")
    engine = CliqueEngine([a.address for a in accts], epoch=2)
    _, (candidate,) = _accounts(1, seed=b"cand2")
    parent = Hash32(keccak256(b"genesis"))

    acct = engine_signer_account(engine, 1, accts)
    block_hash, extra = engine.seal_as(
        1, parent, sign_fn=lambda d: manager.sign_hash(acct.address, d),
        signer=acct.address, proposal=(candidate.address, True))
    engine.finalize(1, parent, extra)
    assert engine.snapshot()[1]  # one pending vote
    # block 2 is an epoch boundary: the tally resets before its vote
    acct2 = engine_signer_account(engine, 2, accts)
    h2, e2 = engine.seal_as(
        2, block_hash, sign_fn=lambda d: manager.sign_hash(acct2.address, d),
        signer=acct2.address)
    engine.finalize(2, block_hash, e2)
    assert not engine.snapshot()[1]
    assert len(engine.signers()) == 3


def test_chain_with_clique_engine_end_to_end():
    """The dev chain seals through a bound clique sealer (single-signer
    clique = the `geth --dev` deployment); imports verify seals;
    rollback carries engine state."""
    manager, (a,) = _accounts(1, seed=b"chain")
    engine = CliqueEngine([a.address])
    engine.bind_sealer(lambda d: manager.sign_hash(a.address, d), a.address)

    chain = SimulatedMainchain(config=Config(shard_count=2), engine=engine)

    for _ in range(4):
        chain.commit()
    assert chain.block_number == 4
    for number in range(1, 5):
        block = chain.block_by_number(number)
        engine.verify_header(block.number, block.parent_hash, block.extra,
                             block.hash)

    # imports with forged seals are refused
    _, (outsider,) = _accounts(1, seed=b"forger")
    parent = chain.block_by_number(4)
    digest = bytes(engine.seal_hash(5, parent.hash, b""))
    forged_extra = secp256k1.sign(digest, outsider.priv).to_bytes65()
    forged = Block(number=5,
                   hash=engine.hash_header(5, parent.hash, forged_extra),
                   parent_hash=parent.hash, extra=forged_extra)
    with pytest.raises(InvalidHeader, match="unauthorized"):
        chain.import_chain([forged])

    # engine state rides the snapshot ring through set_head
    snap_before = engine.snapshot()
    chain.set_head(2)
    assert engine.snapshot() == snap_before  # no votes: set unchanged
    assert chain.block_number == 2


def test_import_verifies_against_attach_point_signer_set():
    """A competing branch sealed under the signer set AS OF the fork
    point must verify even after the incumbent chain changed the set —
    and mid-branch authorization votes must rotate the expected signer
    during verification (geth recomputes clique snapshots per block)."""
    manager, (a,) = _accounts(1, seed=b"attach")
    b_acct = manager.new_account(seed=b"attach-b")
    engine = CliqueEngine([a.address], epoch=1000)
    engine.bind_sealer(lambda d: manager.sign_hash(a.address, d), a.address)
    chain = SimulatedMainchain(config=Config(shard_count=2), engine=engine)

    chain.commit()  # block 1 under {a}
    fork_parent = chain.block_by_number(1)

    # incumbent: blocks 2-3, block 2 votes b in => signer set becomes {a,b}
    engine.propose(b_acct.address, True)
    chain.commit()
    assert len(engine.signers()) == 2
    turn = engine.in_turn_signer(3)
    in_turn_acct = a if bytes(a.address) == bytes(turn) else b_acct
    engine.bind_sealer(
        lambda d: manager.sign_hash(in_turn_acct.address, d),
        in_turn_acct.address)
    chain.commit()

    # foreign branch from block 1, length 3, sealed under {a} ONLY:
    # every seal is a's (in turn in a single-signer set), which is OUT
    # of turn at some height under the incumbent's {a,b} rotation
    branch_engine = CliqueEngine([a.address], epoch=1000)
    branch = []
    parent = fork_parent
    for _ in range(3):
        h, extra = branch_engine.seal_as(
            parent.number + 1, parent.hash,
            sign_fn=lambda d: manager.sign_hash(a.address, d),
            signer=a.address)
        branch_engine.finalize(parent.number + 1, parent.hash, extra)
        block = Block(number=parent.number + 1, hash=h,
                      parent_hash=parent.hash, extra=extra)
        branch.append(block)
        parent = block

    assert chain.import_chain(branch) == 3
    assert chain.block_number == 4
    # adoption replayed the branch's (vote-free) history: set is {a}
    assert [bytes(s) for s in engine.signers()] == [bytes(a.address)]


def test_failed_seal_keeps_pending_proposal():
    manager, (a, b) = _accounts(2, seed=b"keepvote")
    engine = CliqueEngine([a.address, b.address], epoch=1000)
    engine.bind_sealer(lambda d: manager.sign_hash(a.address, d), a.address)
    candidate = manager.new_account(seed=b"keepvote-c")
    engine.propose(candidate.address, True)

    # find a height where the bound signer is OUT of turn: seal fails
    # and the proposal must survive for the next attempt
    parent = Hash32(keccak256(b"keepvote-parent"))
    out_of_turn = next(n for n in range(1, 4)
                       if bytes(engine.in_turn_signer(n)) != bytes(a.address))
    in_turn = next(n for n in range(1, 4)
                   if bytes(engine.in_turn_signer(n)) == bytes(a.address))
    with pytest.raises(InvalidHeader, match="turn"):
        engine.seal(out_of_turn, parent)
    _, extra = engine.seal(in_turn, parent)
    assert len(extra) == 21 + 65  # the preserved proposal rode along
