"""Polynomial-multiproof DAS: PCS properties, batched op, soundness.

The acceptance contracts under test:

- commit/open/verify round-trips for random polynomials; a tampered
  proof, eval, or commitment each verifies False; empty and
  single-index sets and out-of-domain indices behave per contract;
- the multiproof is CONSTANT-SIZE in the sampled index count — one
  64-byte G1 point — and ≥5× smaller than the merkle paths it
  replaces at the default sampling shape;
- batched `das_verify_multiproofs` agrees bit-for-bit with the scalar
  PCS reference across randomized periods including malformed and
  tampered rows — and through the serving and failover backends;
- `SpotCheckSigBackend` catches a silently corrupted multiproof
  verdict and raises `SoundnessViolation` into the breaker path.
"""

import functools
import random

import pytest

from gethsharding_tpu.das import pcs
from gethsharding_tpu.das.pcs import (G1_BYTES, N, commit, dev_srs,
                                      g1_from_bytes, g1_to_bytes,
                                      open_multi, verify_multi)
from gethsharding_tpu.das.poly_proofs import verify_multiproof
from gethsharding_tpu.sigbackend import get_backend


def _values(seed: int, n: int):
    rng = random.Random(seed)
    return [rng.randrange(N) for _ in range(n)]


# -- commit / open / verify properties -------------------------------------


def test_commit_open_verify_roundtrip():
    values = _values(7, 8)
    commitment = commit(values)
    for indices in ((0, 2, 5), (3,)):  # multi-index and single-index
        proof, evals = open_multi(values, indices)
        assert evals == [values[i] for i in indices]
        assert verify_multi(commitment, indices, evals, proof,
                            len(values))
    # empty set: opens to nothing and proves nothing
    proof, evals = open_multi(values, ())
    assert proof is None and evals == []
    assert not verify_multi(commitment, [], [], proof, len(values))


def test_multiproof_is_constant_size_in_m():
    values = _values(11, 32)
    sizes = set()
    for m in (1, 4, 16, 32):
        proof, _ = open_multi(values, range(m))
        sizes.add(len(g1_to_bytes(proof)))
    assert sizes == {G1_BYTES} == {64}


def test_tampered_eval_proof_or_commitment_fails():
    values = _values(13, 6)
    commitment = commit(values)
    indices = (1, 4)
    proof, evals = open_multi(values, indices)
    bad_evals = [evals[0], (evals[1] + 1) % N]
    assert not verify_multi(commitment, indices, bad_evals, proof,
                            len(values))
    bad_proof = pcs.g1_add(proof, pcs.G1_GEN)
    assert not verify_multi(commitment, indices, evals, bad_proof,
                            len(values))
    bad_commitment = pcs.g1_add(commitment, pcs.G1_GEN)
    assert not verify_multi(bad_commitment, indices, evals, proof,
                            len(values))


def test_domain_rejection_is_cheap_and_total():
    """Shape rejection happens before any pairing: out-of-domain,
    duplicate, oversized and ragged sets are False, and the prover
    refuses to open them at all."""
    values = _values(17, 5)
    commitment = commit(values)
    proof, evals = open_multi(values, (2,))
    srs = dev_srs()
    assert not verify_multi(commitment, (5,), evals, proof, 5)  # >= n
    assert not verify_multi(commitment, (-1,), evals, proof, 5)
    assert not verify_multi(commitment, (2, 2), evals * 2, proof, 5)
    assert not verify_multi(commitment, (2,), evals * 2, proof, 5)
    assert not verify_multi(commitment, (2,), [N], proof, 5)  # e >= N
    assert not verify_multi(commitment, (2,), evals, proof, 0)
    assert not verify_multi(commitment, range(srs.max_set + 1),
                            [0] * (srs.max_set + 1), proof, 200)
    with pytest.raises(ValueError):
        open_multi(values, (0, 0))
    with pytest.raises(ValueError):
        open_multi(values, (99,))


def test_g1_wire_roundtrip_and_rejection():
    values = _values(19, 4)
    point = commit(values)
    assert g1_from_bytes(g1_to_bytes(point)) == point
    assert g1_from_bytes(b"\x00" * 64) is None  # infinity
    assert g1_to_bytes(None) == b"\x00" * 64
    with pytest.raises(ValueError):
        g1_from_bytes(b"\x01" * 63)  # wrong length
    with pytest.raises(ValueError):
        g1_from_bytes(b"\x01" * 64)  # off-curve
    # the bytes-face verifier turns decode failures into verdicts
    assert not verify_multiproof(b"\x01" * 63, [0], [values[0]],
                                 b"\x00" * 64, 4)
    assert not verify_multiproof(g1_to_bytes(point), [0], [values[0]],
                                 b"garbage", 4)


# -- the batched op, bit-for-bit and through the backend layers ------------


@functools.lru_cache(maxsize=1)
def _poly_rows():
    """(commitments, index_rows, eval_rows, proofs, ns) rows: honest
    openings from randomized periods plus every malformed-row class,
    all in wire (bytes) form. Cached — scalar pairing checks are the
    expensive part of this file."""
    rows = []
    for seed, n, indices in ((101, 7, (0, 3, 6)), (102, 5, (1,)),
                             (103, 9, (2, 4, 7, 8))):
        values = _values(seed, n)
        commitment = g1_to_bytes(commit(values))
        proof, evals = open_multi(values, indices)
        rows.append((commitment, list(indices), evals,
                     g1_to_bytes(proof), n))
    values = _values(104, 6)
    commitment = g1_to_bytes(commit(values))
    proof, evals = open_multi(values, (1, 3))
    good = (commitment, [1, 3], evals, g1_to_bytes(proof), 6)
    rows += [
        # tampered eval / tampered proof bytes / tampered commitment
        (good[0], good[1], [evals[0], (evals[1] + 1) % N], good[3], 6),
        (good[0], good[1], evals,
         g1_to_bytes(pcs.g1_add(proof, pcs.G1_GEN)), 6),
        (g1_to_bytes(pcs.g1_add(commit(values), pcs.G1_GEN)),
         good[1], evals, good[3], 6),
        (b"\x07" * 64, good[1], evals, good[3], 6),   # off-curve C
        (good[0], good[1], evals, good[3][:32], 6),   # short proof
        (good[0], [1, 1], evals, good[3], 6),         # dup indices
        (good[0], [], [], good[3], 6),                # empty set
        (good[0], [1, 9], evals, good[3], 6),         # out of domain
    ]
    # the degenerate-pairing row: a constant polynomial's quotient is
    # zero, so π is the G1 infinity — must still verify True
    const = [42] * 4
    c_proof, c_evals = open_multi(const, (0, 2))
    rows.append((g1_to_bytes(commit(const)), [0, 2], c_evals,
                 g1_to_bytes(c_proof), 4))
    return tuple(map(tuple, zip(*rows)))


@functools.lru_cache(maxsize=1)
def _poly_want():
    return tuple(get_backend("python").das_verify_multiproofs(
        *[list(col) for col in _poly_rows()]))


def test_das_verify_multiproofs_scalar_vs_jax_bit_for_bit():
    cols = [list(col) for col in _poly_rows()]
    want = list(_poly_want())
    assert want == [True] * 3 + [False] * 8 + [True]
    jax_backend = get_backend("jax")
    got = jax_backend.das_verify_multiproofs(*cols)
    assert got == want
    ledger = jax_backend.last_wire
    assert ledger["op"] == "das_verify_multiproofs"
    assert ledger["rows"] == len(cols[0])
    assert ledger["wire_bytes"] > 0
    # empty batch: no dispatch, clean ledger
    assert jax_backend.das_verify_multiproofs([], [], [], [], []) == []
    assert jax_backend.last_wire is None


def test_das_verify_multiproofs_through_serving_and_failover():
    from gethsharding_tpu.resilience.breaker import FailoverSigBackend
    from gethsharding_tpu.serving import ServingSigBackend
    from gethsharding_tpu.serving.batcher import SERVING_OPS

    assert "das_verify_multiproofs" in SERVING_OPS
    cols = [list(col) for col in _poly_rows()]
    want = list(_poly_want())
    serving = ServingSigBackend(get_backend("jax"))
    try:
        assert serving.das_verify_multiproofs(*cols) == want
        counts = serving.batcher.dispatch_counts
        assert counts["das_verify_multiproofs"] == 1
    finally:
        serving.close()
    failover = FailoverSigBackend(get_backend("jax"),
                                  get_backend("python"))
    assert failover.das_verify_multiproofs(*cols) == want


def test_spotcheck_catches_corrupted_multiproof_verdict():
    """A backend that silently flips a multiproof verdict is caught by
    the soundness spot-checker, and the violation trips the failover
    breaker so the scalar fallback serves correct verdicts."""
    from gethsharding_tpu.metrics import Registry
    from gethsharding_tpu.resilience.breaker import (CircuitBreaker,
                                                     FailoverSigBackend)
    from gethsharding_tpu.resilience.chaos import (ChaosSigBackend,
                                                   parse_spec)
    from gethsharding_tpu.resilience.errors import SoundnessViolation
    from gethsharding_tpu.resilience.soundness import SpotCheckSigBackend

    values = _values(211, 6)
    commitment = g1_to_bytes(commit(values))
    proof, evals = open_multi(values, (0, 2, 5))
    cols = ([commitment], [[0, 2, 5]], [evals], [g1_to_bytes(proof)],
            [6])
    schedule = parse_spec(
        "seed=3,backend.das_verify_multiproofs:mode=corrupt")
    corrupt = ChaosSigBackend(get_backend("python"), schedule)
    audited = SpotCheckSigBackend(corrupt, rate=1.0, rows=1,
                                  registry=Registry())
    with pytest.raises(SoundnessViolation):
        audited.das_verify_multiproofs(*[list(c) for c in cols])
    # the production shape: the violation is a primary fault
    registry = Registry()
    backend = FailoverSigBackend(
        SpotCheckSigBackend(
            ChaosSigBackend(
                get_backend("python"),
                parse_spec(
                    "seed=3,backend.das_verify_multiproofs:mode=corrupt")),
            rate=1.0, rows=1, registry=registry),
        get_backend("python"),
        breaker=CircuitBreaker(name="das-poly-test", fault_threshold=1,
                               reset_s=60.0, registry=registry),
        registry=registry)
    got = backend.das_verify_multiproofs(*[list(c) for c in cols])
    assert got == [True]
    assert backend.breaker.state_name == "open"


# -- the proof-byte economics ----------------------------------------------


def test_poly_proof_bytes_are_constant_and_5x_smaller():
    from gethsharding_tpu.das.sampler import proof_bytes, soundness_table

    assert proof_bytes(16, "poly") == proof_bytes(64, "poly") == 64
    assert proof_bytes(0, "poly") == 0
    assert proof_bytes(16, "merkle") == 16 * 8 * 32
    # the ISSUE acceptance floor at the default sampling shape
    assert proof_bytes(16, "merkle") >= 5 * proof_bytes(16, "poly")
    with pytest.raises(ValueError):
        proof_bytes(16, "zk-starks")
    rows = soundness_table(n=255, k_data=170, ks=(4, 16))
    for row in rows:
        assert row["merkle_proof_bytes"] == row["k"] * 8 * 32
        assert row["poly_proof_bytes"] == 64
        assert 0.0 < row["p_detect"] <= 1.0
