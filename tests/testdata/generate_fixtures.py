"""Regenerate the JSON conformance fixtures in this directory.

Mirrors the reference's cross-client JSON-suite pattern (`tests/` wiring
BlockchainTests/GeneralStateTests/... at `tests/init_test.go:36-40`): the
protocol's wire/hash/state behaviors are pinned as frozen JSON vectors so
any reimplementation — the batched JAX kernels, the native C runtime, or
a future port — can be validated against the same fixtures, and silent
behavior drift in the scalar implementation breaks `test_conformance.py`.

Run from the repo root:  python tests/testdata/generate_fixtures.py
The output files are committed; regeneration is only needed when the
protocol itself (not an implementation) changes.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

HERE = os.path.dirname(os.path.abspath(__file__))


def _hex(b: bytes) -> str:
    return b.hex()


def gen_keccak():
    from gethsharding_tpu.crypto.keccak import keccak256

    cases = [
        b"",
        b"abc",
        b"The quick brown fox jumps over the lazy dog",
        bytes(32),
        bytes(range(256)),
        b"\xfe" * 135,   # one byte short of the rate
        b"\xfe" * 136,   # exactly one block
        b"\xfe" * 137,   # rate + 1
        b"gethsharding-tpu" * 100,
    ]
    return [{"in": _hex(m), "out": _hex(keccak256(m))} for m in cases]


def gen_rlp():
    from gethsharding_tpu.utils.rlp import rlp_encode

    def case(item):
        return {"decoded": _tree_hex(item), "encoded": _hex(rlp_encode(item))}

    def _tree_hex(item):
        if isinstance(item, bytes):
            return _hex(item)
        return [_tree_hex(x) for x in item]

    return [
        case(b""),
        case(b"\x00"),
        case(b"\x7f"),
        case(b"\x80"),
        case(b"dog"),
        case(b"x" * 55),
        case(b"x" * 56),
        case(b"y" * 300),
        case([]),
        case([b"cat", b"dog"]),
        case([[], [[]], [[], [[]]]]),   # the set-theoretic nesting classic
        case([b"a" * 60, [b"b", [b"c" * 70]], b""]),
    ]


def gen_trie():
    from gethsharding_tpu.core.trie import SecureTrie, Trie

    suites = []

    def run(ops):
        trie = Trie()
        for op in ops:
            if op[0] == "put":
                trie.update(bytes.fromhex(op[1]), bytes.fromhex(op[2]))
            else:
                trie.delete(bytes.fromhex(op[1]))
        return _hex(trie.root_hash())

    scripts = [
        [],
        [["put", b"do".hex(), b"verb".hex()],
         ["put", b"dog".hex(), b"puppy".hex()],
         ["put", b"doge".hex(), b"coin".hex()],
         ["put", b"horse".hex(), b"stallion".hex()]],
        [["put", b"A".hex(), (b"aaaa" * 20).hex()]],
        [["put", b"k1".hex(), b"v1".hex()],
         ["put", b"k2".hex(), b"v2".hex()],
         ["del", b"k2".hex()]],
        [["put", bytes(1).hex(), b"zero".hex()],
         ["put", bytes(2).hex(), b"zz".hex()],
         ["put", b"\x00\x01".hex(), b"mid".hex()],
         ["del", bytes(1).hex()]],
    ]
    for ops in scripts:
        suites.append({"ops": ops, "root": run(ops)})

    secure = SecureTrie()
    secure.update(b"key", b"value")
    secure.update(b"other", b"thing")
    suites.append({"secure": True,
                   "ops": [["put", b"key".hex(), b"value".hex()],
                           ["put", b"other".hex(), b"thing".hex()]],
                   "root": _hex(secure.root_hash())})
    return suites


def gen_collation():
    from gethsharding_tpu.core.derive_sha import chunk_root, poc_root
    from gethsharding_tpu.core.types import (
        CollationHeader, Transaction, serialize_txs_to_blob)
    from gethsharding_tpu.utils.hexbytes import Address20, Hash32

    out = []
    txs = [
        Transaction(nonce=i, gas_price=10 + i, gas_limit=21000,
                    to=Address20(bytes([i + 1]) * 20), value=1000 * i,
                    payload=b"payload-%d" % i)
        for i in range(3)
    ]
    blob = serialize_txs_to_blob(txs)
    header = CollationHeader(
        shard_id=7, chunk_root=Hash32(chunk_root(blob)), period=42,
        proposer_address=Address20(b"\xaa" * 20))
    unsigned_hash = header.hash()
    header.add_sig(b"\x01" * 65)
    out.append({
        "txs": [
            {"nonce": t.nonce, "gas_price": t.gas_price,
             "gas_limit": t.gas_limit, "to": _hex(bytes(t.to)),
             "value": t.value, "payload": _hex(t.payload),
             "tx_hash": _hex(bytes(t.hash())),
             "sig_hash_homestead": _hex(bytes(t.sig_hash())),
             "sig_hash_eip155_1": _hex(bytes(t.sig_hash(chain_id=1)))}
            for t in txs
        ],
        "blob": _hex(blob),
        "chunk_root": _hex(chunk_root(blob)),
        "poc_root_salt00": _hex(poc_root(blob, b"\x00" * 32)),
        "header_rlp": _hex(header.encode_rlp()),
        "header_hash_unsigned": _hex(bytes(unsigned_hash)),
        "header_hash_signed": _hex(bytes(header.hash())),
    })
    # edge blobs: empty, exactly 31·k, trailing partial chunk
    from gethsharding_tpu.utils.blob import RawBlob, serialize_blobs

    for body in (b"", b"z" * 31, b"z" * 62, b"z" * 40):
        wire = serialize_blobs([RawBlob(data=body)]) if body else b""
        out.append({"raw_blob_body": _hex(body),
                    "serialized": _hex(wire),
                    "chunk_root": _hex(chunk_root(wire))})
    return out


def gen_ecdsa():
    from gethsharding_tpu.crypto import secp256k1 as ecdsa
    from gethsharding_tpu.crypto.keccak import keccak256

    out = []
    for i in range(4):
        priv = int.from_bytes(keccak256(b"conform-ecdsa-%d" % i), "big") % ecdsa.N
        digest = keccak256(b"digest-%d" % i)
        sig = ecdsa.sign(digest, priv)
        out.append({
            "digest": _hex(digest),
            "priv": hex(priv),
            "address": _hex(bytes(ecdsa.priv_to_address(priv))),
            "sig65": _hex(sig.to_bytes65()),
        })
    return out


def gen_bls():
    from gethsharding_tpu.crypto import bn256 as bls

    out = []
    msgs = [b"conform-bls-0", b"conform-bls-1"]
    for msg in msgs:
        keys = [bls.bls_keygen(msg + bytes([j])) for j in range(3)]
        sigs = [bls.bls_sign(msg, sk) for sk, _ in keys]
        agg_sig = bls.bls_aggregate_sigs(sigs)
        agg_pk = bls.bls_aggregate_pks([pk for _, pk in keys])
        h = bls.hash_to_g1(msg)
        out.append({
            "msg": _hex(msg),
            "hash_to_g1": [hex(h[0]), hex(h[1])],
            "secret_keys": [hex(sk) for sk, _ in keys],
            "pubkeys": [[hex(pk[0].a), hex(pk[0].b), hex(pk[1].a),
                         hex(pk[1].b)] for _, pk in keys],
            "sigs": [[hex(s[0]), hex(s[1])] for s in sigs],
            "agg_sig": [hex(agg_sig[0]), hex(agg_sig[1])],
            "agg_pk": [hex(agg_pk[0].a), hex(agg_pk[0].b),
                       hex(agg_pk[1].a), hex(agg_pk[1].b)],
            "verifies": True,
        })
    return out


def gen_smc():
    """Deterministic SMC scenario scripts with expected outcomes,
    including the reference contract's quirks (vote-count low byte,
    period gating, double-vote rejection)."""
    from gethsharding_tpu.crypto.keccak import keccak256
    from gethsharding_tpu.mainchain.accounts import AccountManager
    from gethsharding_tpu.params import Config, ETHER
    from gethsharding_tpu.smc.chain import SimulatedMainchain
    from gethsharding_tpu.utils.hexbytes import Hash32

    config = Config(shard_count=3, committee_size=4, quorum_size=2)
    chain = SimulatedMainchain(config=config)
    manager = AccountManager()
    accounts = [manager.new_account(seed=b"conform-smc-%d" % i)
                for i in range(4)]
    script = []
    for acct in accounts:
        chain.fund(acct.address, 2000 * ETHER)
        chain.register_notary(
            acct.address, bls_pubkey=acct.bls_pubkey,
            bls_pop=manager.bls_proof_of_possession(acct.address))
        script.append({"op": "register", "addr": _hex(bytes(acct.address))})
    chain.fast_forward(1)
    period = chain.current_period()
    script.append({"op": "fast_forward", "periods": 1})
    root = Hash32(keccak256(b"conform-root"))
    proposer = accounts[0]
    chain.add_header(proposer.address, 1, period, root)
    script.append({"op": "add_header", "shard": 1, "period": period,
                   "chunk_root": _hex(bytes(root))})
    votes = []
    from gethsharding_tpu.smc.state_machine import vote_digest

    digest = bytes(vote_digest(1, period, root))
    for acct in accounts:
        member = chain.get_notary_in_committee(acct.address, 1)
        if member != acct.address:
            continue
        entry = chain.smc.notary_registry[acct.address]
        chain.submit_vote(acct.address, 1, period, entry.pool_index, root,
                          bls_sig=manager.bls_sign(acct.address, digest))
        votes.append(_hex(bytes(acct.address)))
    record = chain.smc.collation_records[(1, period)]
    return {
        "config": {"shard_count": 3, "committee_size": 4, "quorum_size": 2},
        "script": script,
        "account_seeds": ["conform-smc-%d" % i for i in range(4)],
        "addresses": [_hex(bytes(a.address)) for a in accounts],
        "sampled_voters": votes,
        "expected": {
            "period": period,
            "vote_count": record.vote_count,
            "is_elected": record.is_elected,
            "last_approved": chain.last_approved_collation(1),
            "vote_digest": _hex(digest),
        },
    }


def gen_storage():
    """BMT roots + chunk-store addresses (storage/): deterministic
    content addresses must never drift — a changed root orphans every
    stored blob."""
    from gethsharding_tpu.storage import ChunkStore, bmt_hash
    from gethsharding_tpu.storage.chunker import CHUNK_SIZE, chunk_key

    def pattern(n: int) -> bytes:
        return bytes(i % 251 for i in range(n))

    bmt_cases = [
        {"size": size, "root": _hex(bmt_hash(pattern(size)))}
        for size in (0, 1, 31, 32, 33, 64, 96, 1000, 4096)
    ]
    chunk_cases = []
    for size in (0, 5, CHUNK_SIZE, CHUNK_SIZE + 1, 3 * CHUNK_SIZE + 7):
        store = ChunkStore()
        root = store.store(pattern(size))
        chunk_cases.append({"size": size, "root": _hex(root)})
    return {
        "pattern": "bytes(i % 251 for i in range(n))",
        "bmt_roots": bmt_cases,
        "chunk_key_example": _hex(chunk_key(5, pattern(5))),
        "store_roots": chunk_cases,
    }


def gen_whisper():
    """Envelope identity + PoW values (p2p/whisper.py): the flood
    dedup/spam economics hang off these exact numbers."""
    from gethsharding_tpu.p2p.whisper import Envelope

    cases = []
    for expiry, ttl, topic, ct, nonce in (
            (1_700_000_000, 60, b"shrd", b"\x00" * 16, 0),
            (1_700_000_000, 60, b"shrd", b"\x00" * 16, 12345),
            (2_000_000_000, 7, b"abcd", bytes(range(64)), 7),
    ):
        env = Envelope(expiry=expiry, ttl=ttl, topic=topic,
                       ciphertext=ct, nonce=nonce)
        cases.append({
            "expiry": expiry, "ttl": ttl, "topic": _hex(topic),
            "ciphertext": _hex(ct), "nonce": nonce,
            "hash": _hex(env.hash()),
            "pow": env.pow(),
        })
    return {"envelopes": cases}


def main():
    suites = {
        "keccak.json": gen_keccak(),
        "rlp.json": gen_rlp(),
        "trie.json": gen_trie(),
        "collation.json": gen_collation(),
        "ecdsa.json": gen_ecdsa(),
        "bls.json": gen_bls(),
        "smc.json": gen_smc(),
        "storage.json": gen_storage(),
        "whisper.json": gen_whisper(),
    }
    for name, data in suites.items():
        path = os.path.join(HERE, name)
        with open(path, "w") as fh:
            json.dump(data, fh, indent=1, sort_keys=True)
            fh.write("\n")
        print(f"wrote {name}")


if __name__ == "__main__":
    main()
