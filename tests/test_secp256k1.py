"""secp256k1 ECDSA: curve sanity, sign/verify/recover, Ethereum addresses."""

import pytest

from gethsharding_tpu.crypto.keccak import keccak256
from gethsharding_tpu.crypto.secp256k1 import (
    G,
    N,
    Signature,
    ecrecover_address,
    is_on_curve,
    point_add,
    point_mul,
    priv_to_address,
    pubkey_from_priv,
    recover,
    sign,
    verify,
)


def test_generator_on_curve_and_order():
    from gethsharding_tpu.crypto.secp256k1 import point_mul_raw

    assert is_on_curve(G)
    assert point_mul_raw(N, G) is None  # n·G = infinity (unreduced scalar)
    assert point_mul(N - 1, G) == (G[0], -G[1] % (2**256 - 2**32 - 977))


def test_point_arithmetic_consistency():
    a = point_mul(12345, G)
    b = point_mul(54321, G)
    assert point_add(a, b) == point_mul(12345 + 54321, G)


def test_known_address_vector():
    # well-known test vector: priv key 1's address derives from G itself
    addr = priv_to_address(1)
    expected = keccak256(
        G[0].to_bytes(32, "big") + G[1].to_bytes(32, "big")
    )[12:]
    assert bytes(addr) == expected
    # and the canonical hex everyone knows for key=1
    assert addr.hex() == "7e5f4552091a69125d5dfcb7b8c2659029395bdf"


def test_sign_verify_roundtrip():
    priv = 0xDEADBEEF
    pub = pubkey_from_priv(priv)
    digest = keccak256(b"collation header")
    sig = sign(digest, priv)
    assert verify(digest, sig, pub)
    assert not verify(keccak256(b"other"), sig, pub)


def test_sign_is_deterministic_low_s():
    digest = keccak256(b"deterministic")
    s1 = sign(digest, 7)
    s2 = sign(digest, 7)
    assert s1 == s2  # RFC 6979
    assert s1.s <= N // 2  # low-S


def test_recover_matches_signer():
    priv = 0x12345678ABCDEF
    digest = keccak256(b"vote")
    sig = sign(digest, priv)
    assert recover(digest, sig) == pubkey_from_priv(priv)
    assert ecrecover_address(digest, sig) == priv_to_address(priv)


def test_recover_wrong_v_gives_different_key():
    priv = 99
    digest = keccak256(b"msg")
    sig = sign(digest, priv)
    flipped = Signature(r=sig.r, s=sig.s, v=sig.v ^ 1)
    assert recover(digest, flipped) != pubkey_from_priv(priv)


def test_high_s_rejected_by_verify():
    priv = 42
    digest = keccak256(b"malleable")
    sig = sign(digest, priv)
    high = Signature(r=sig.r, s=N - sig.s, v=sig.v ^ 1)
    # high-S is a valid classic ECDSA signature but must be rejected
    # (parity with crypto.VerifySignature's malleability rule)
    assert not verify(digest, high, pubkey_from_priv(priv))
    # yet recovery with its recid still yields the signer (ecrecover accepts)
    assert recover(digest, high) == pubkey_from_priv(priv)


def test_signature_wire_format_roundtrip():
    sig = sign(keccak256(b"wire"), 1234)
    encoded = sig.to_bytes65()
    assert len(encoded) == 65
    assert Signature.from_bytes65(encoded) == sig


def test_invalid_signatures_rejected():
    digest = keccak256(b"x")
    with pytest.raises(ValueError):
        recover(digest, Signature(r=0, s=1, v=0))
    with pytest.raises(ValueError):
        recover(digest, Signature(r=1, s=0, v=0))
    assert not verify(digest, Signature(r=0, s=1, v=0), pubkey_from_priv(5))
