"""Mesh dispatch: tri-layout bit-identity + per-device cache shards.

The acceptance contracts of the `sigbackend/` package split's mesh
path, exercised on the conftest-forced 8-device virtual CPU mesh:

- `bls_verify_committees{,_async}` and `das_verify_multiproofs` return
  BIT-IDENTICAL verdicts across the 1-, 2- and 8-device layouts and
  the scalar reference — including empty committees, infinity-point
  slots, forged rows, malformed multiproof rows and the degenerate
  infinity-proof row;
- the mesh committee step is non-vacuous: `last_mesh` shows the
  verdict plane really sharded over every device, exactly ONE
  cross-device collective (the vote-total psum) per compiled step, and
  a psum'd vote total agreeing with the verdict plane;
- the per-device cache shards churn correctly under a starvation
  byte budget (evictions tick, verdicts stay bit-identical, shards end
  empty — churn, not growth) and own pairwise-DISJOINT buffer sets
  under their per-shard devscope census owners.

The host-only geometry/marshal tests at the top stay in the fast tier;
everything that compiles a pairing kernel is marked `slow`
(run_suite.sh runs this file in its own process like the other kernel
suites).
"""

import functools
import random

import pytest

from gethsharding_tpu.crypto import bn256 as bls
from gethsharding_tpu.sigbackend import PythonSigBackend, get_backend
from gethsharding_tpu.sigbackend import marshal
from gethsharding_tpu.sigbackend.layout import (DeviceLayout,
                                                count_collectives)

# -- marshal: padding policy and the u16 wire (host-only, fast tier) -------


def test_bucket_size_quarter_pow2_policy():
    assert [marshal.bucket_size(n) for n in (0, 1, 2, 3, 5, 8)] == \
        [1, 1, 2, 4, 8, 8]
    assert marshal.bucket_size(9) == 10    # quarter steps above 8
    assert marshal.bucket_size(65) == 80   # the docstring's worst case
    assert marshal.bucket_size(100) == 112  # the 100-shard audit shape
    # idempotent: a bucket is its own bucket (serving sizes flush
    # quanta with the same function)
    for n in (1, 2, 4, 8, 10, 80, 112):
        assert marshal.bucket_size(n) == n


def test_committee_width_policy():
    assert marshal.committee_width([[1, 2, 3]], [[1, 2]]) == 4
    assert marshal.committee_width([[]], [[]]) == 1  # empty -> min width
    # above 32: next multiple of 16, driven by the WIDEST row anywhere
    assert marshal.committee_width([[0] * 135], [[0] * 7]) == 144


def test_wire_dtype_and_narrowing():
    import numpy as np

    assert marshal.wire_dtype(False, False) is np.int32
    assert marshal.wire_dtype(True, False) is np.uint16
    # GETHSHARDING_CHECK keeps planes wide so the narrowing site checks
    assert marshal.wire_dtype(True, True) is np.int32
    canonical = np.array([[0, 7, marshal.U16_LIMB_BOUND - 1]], np.int32)
    out = marshal.narrow_u16(canonical, check=True)
    assert out.dtype == np.uint16 and (out == canonical).all()
    # a wide-form limb survives the cast but violates kernel headroom:
    # only the checked mode may see it
    wide = np.array([marshal.U16_LIMB_BOUND], np.int32)
    with pytest.raises(AssertionError):
        marshal.narrow_u16(wide, check=True)
    with pytest.raises(AssertionError):
        marshal.assert_canonical_limbs(canonical, wide)
    conv = marshal.wire_converter(True, False)
    assert conv(canonical).dtype == np.uint16
    assert marshal.wire_converter(False, False)(canonical).dtype == np.int32


def test_normalize_row_keys():
    assert marshal.normalize_row_keys(None, 4) is None
    # short caller list -> trailing rows uncached; surplus dropped
    assert marshal.normalize_row_keys(["a", "b"], 4) == \
        ["a", "b", None, None]
    assert marshal.normalize_row_keys(["a", "b", "c"], 2) == ["a", "b"]


# -- layout: geometry and the collective ledger (fast tier) ----------------


def test_count_collectives_on_hlo_text():
    hlo = """\
ENTRY main {
  %p0 = f32[8]{0} parameter(0)
  %ar = f32[8]{0} all-reduce(%p0), replica_groups={}
  %ag = f32[16]{0} all-gather-start(%p0), dimensions={0}
  %agd = f32[16]{0} all-gather-done(%ag)
  %sum = f32[8]{0} add(%p0, %p0)
}
"""
    # async pairs count ONCE (on the start half); local ops never
    assert count_collectives(hlo) == 2
    assert count_collectives("add(%a, %b)") == 0


def test_single_device_layout_is_the_default():
    lay = DeviceLayout(1)
    assert not lay.is_mesh and lay.mesh is None
    # no mesh -> the bucket policy is untouched
    for n in (1, 5, 9, 100):
        assert lay.mesh_bucket(n) == marshal.bucket_size(n)


def test_mesh_layout_geometry():
    import jax
    import numpy as np

    if jax.device_count() < 4:
        pytest.skip("needs the virtual multi-device mesh (conftest)")
    lay = DeviceLayout(4)
    assert lay.is_mesh and len(lay.devices) == 4
    # buckets round UP to a device multiple so the split is even
    assert lay.mesh_bucket(9) == 12  # bucket_size(9)=10 -> 12
    assert lay.mesh_bucket(8) == 8
    assert lay.rows_per_device(12) == 3
    assert [lay.device_of_row(r, 12) for r in (0, 2, 3, 11)] == \
        [0, 0, 1, 3]
    # place: one host plane -> contiguous per-device slabs
    host = np.arange(24, dtype=np.int32).reshape(12, 2)
    placed = lay.place(host)
    assert len(placed.sharding.device_set) == 4
    assert (np.asarray(placed) == host).all()
    # assemble: per-device slabs already resident -> one global array,
    # zero bytes moved
    slabs = [jax.device_put(host[i * 3:(i + 1) * 3], dev)
             for i, dev in enumerate(lay.devices)]
    whole = lay.assemble(slabs)
    assert whole.shape == (12, 2)
    assert (np.asarray(whole) == host).all()


# -- the tri-layout dispatch workloads (slow tier: pairing compiles) -------


@pytest.fixture(scope="module")
def backends():
    import jax

    if jax.device_count() < 8:
        pytest.skip("needs the 8-device virtual mesh (tests/conftest.py)")
    from gethsharding_tpu.sigbackend.dispatch import JaxSigBackend

    return {n: JaxSigBackend(mesh_devices=n) for n in (1, 2, 8)}


@functools.lru_cache(maxsize=1)
def _committee_cols():
    """6 committees of width 3 with every interesting row class: valid,
    EMPTY (a rejection: an empty committee proves nothing), an absent
    voter encoded as INFINITY slots in both the sig and pk rows (still
    verifies via the remaining signers), and a forged row."""
    rows, width = 6, 3
    messages, sig_rows, pk_rows, keys = [], [], [], []
    for i in range(rows):
        msg = bytes([11, i]) * 16
        sigs, pks = [], []
        for j in range(width):
            sk, pk = bls.bls_keygen(bytes([i + 1, j + 1, 29]) * 8)
            sigs.append(bls.bls_sign(msg, sk))
            pks.append(pk)
        messages.append(msg)
        sig_rows.append(sigs)
        pk_rows.append(pks)
        keys.append(f"mesh-row:{i}")
    sig_rows[1], pk_rows[1] = [], []  # empty committee -> False
    sig_rows[2][1] = None  # absent voter: infinity in BOTH halves
    pk_rows[2][1] = None   # -> the other two signers still verify
    forged_sk, _ = bls.bls_keygen(bytes([5, 1, 29]) * 8)  # row 4 voter 0
    sig_rows[4][0] = bls.bls_sign(b"some other collation header!!!!!",
                                  forged_sk)
    return messages, sig_rows, pk_rows, keys


@functools.lru_cache(maxsize=1)
def _committee_want():
    messages, sig_rows, pk_rows, _ = _committee_cols()
    want = PythonSigBackend().bls_verify_committees(messages, sig_rows,
                                                    pk_rows)
    assert want == [True, False, True, True, False, True]
    return want


@functools.lru_cache(maxsize=1)
def _poly_cols():
    """Multiproof rows in wire form: honest multi- and single-index
    openings, a tampered eval, the EMPTY index set, truncated proof
    bytes, and the degenerate constant-polynomial row whose proof is
    the G1 INFINITY (must still verify True)."""
    from gethsharding_tpu.das import pcs

    rows = []
    for seed, n, indices in ((21, 6, (0, 2, 5)), (22, 5, (1,))):
        values = [random.Random(seed).randrange(pcs.N) for _ in range(n)]
        proof, evals = pcs.open_multi(values, indices)
        rows.append((pcs.g1_to_bytes(pcs.commit(values)), list(indices),
                     evals, pcs.g1_to_bytes(proof), n))
    good = rows[0]
    evals = good[2]
    rows.append((good[0], good[1],
                 [evals[0], (evals[1] + 1) % pcs.N, evals[2]],
                 good[3], good[4]))                      # tampered eval
    rows.append((good[0], [], [], good[3], good[4]))     # empty index set
    rows.append((good[0], good[1], evals, good[3][:32],
                 good[4]))                               # short proof
    const = [42] * 4
    c_proof, c_evals = pcs.open_multi(const, (0, 2))
    rows.append((pcs.g1_to_bytes(pcs.commit(const)), [0, 2], c_evals,
                 pcs.g1_to_bytes(c_proof), 4))           # infinity proof
    return tuple(tuple(col) for col in zip(*rows))


@pytest.mark.slow
def test_committee_tri_layout_bit_identity(backends):
    messages, sig_rows, pk_rows, keys = _committee_cols()
    want = _committee_want()
    for n, backend in sorted(backends.items()):
        got = backend.bls_verify_committees(messages, sig_rows, pk_rows,
                                            pk_row_keys=keys)
        assert got == want, f"{n}-device sync verdicts diverge"
        fut = backend.bls_verify_committees_async(
            messages, sig_rows, pk_rows, pk_row_keys=keys)
        assert not fut.done()  # staged, not pulled
        assert fut.result() == want, f"{n}-device async verdicts diverge"
    # the single-device layout never reports mesh evidence
    assert backends[1].last_mesh is None


@pytest.mark.slow
def test_committee_mesh_non_vacuity(backends):
    """The pjit path really sharded: verdict plane on every device,
    exactly ONE collective (the vote-total psum) in the compiled step,
    vote total agreeing with the verdict plane it reduced."""
    messages, sig_rows, pk_rows, keys = _committee_cols()
    want = _committee_want()
    for n in (2, 8):
        backend = backends[n]
        fut = backend.bls_verify_committees_async(
            messages, sig_rows, pk_rows, pk_row_keys=keys)
        info = backend.last_mesh
        assert info["op"] == "bls_verify_committees"
        assert info["n_devices"] == n
        assert info["collectives"] == 1, (
            f"{n}-device step must psum ONCE, counted from the AOT HLO")
        assert info["vote_total"] is None  # not finalized yet
        assert fut.result() == want
        assert info["verdict_devices"] == n
        assert info["vote_total"] == sum(want)
        # the memoized planes are themselves mesh-sharded arrays: the
        # line table under precomp (the default), the pk planes on the
        # recompute path
        memo = (backend._mesh_line_memo if backend._precomp
                else backend._mesh_memo)
        assert len(memo[1][0].sharding.device_set) == n


@pytest.mark.slow
def test_multiproofs_tri_layout_bit_identity(backends):
    cols = _poly_cols()
    want = get_backend("python").das_verify_multiproofs(
        *[list(col) for col in cols])
    assert want == [True, True, False, False, False, True]
    for n, backend in sorted(backends.items()):
        got = backend.das_verify_multiproofs(*[list(col) for col in cols])
        assert got == want, f"{n}-device multiproof verdicts diverge"
        if n == 1:
            continue
        info = backend.last_mesh
        assert info["op"] == "das_verify_multiproofs"
        assert info["collectives"] == 0  # per-row work: nothing crosses
        assert info["verdict_devices"] == n


@pytest.mark.slow
def test_mesh_empty_batches(backends):
    backend = backends[2]
    assert backend.das_verify_multiproofs([], [], [], [], []) == []
    assert backend.last_wire is None
    assert backend.bls_verify_committees([], [], []) == []
    assert backend.last_wire is None and backend.last_mesh is None


@pytest.mark.slow
def test_mesh_cache_shard_eviction_churn(backends):
    """Starve the per-device shards (1-byte budgets): every keyed
    insert immediately evicts, verdicts stay bit-identical, and the
    shards end EMPTY — churn must never corrupt or grow."""
    backend = backends[2]
    messages, sig_rows, pk_rows, _ = _committee_cols()
    want = _committee_want()
    shards = backend._mesh_shards
    budgets = [s.budget for s in shards]
    evict0 = [s.m_evict.value for s in shards]
    miss0 = [s.m_miss.value for s in shards]
    try:
        for s in shards:
            s.budget = 1
        for rnd in range(3):
            # fresh keys each round: misses the batch memo AND the
            # starved LRUs, so every round re-inserts and re-evicts
            keys = [f"churn{rnd}:{i}" for i in range(len(messages))]
            got = backend.bls_verify_committees(
                messages, sig_rows, pk_rows, pk_row_keys=keys)
            assert got == want, f"round {rnd} verdicts diverge under churn"
    finally:
        for s, budget in zip(shards, budgets):
            s.budget = budget
        with backend._mesh_lock:
            backend._mesh_memo = None
    for i, s in enumerate(shards):
        assert s.m_evict.value > evict0[i], f"shard{i} never evicted"
        assert s.m_miss.value > miss0[i], f"shard{i} never missed"
        assert not s.cache and s.bytes == 0, (
            f"shard{i} retained entries past a 1-byte budget")


@pytest.mark.slow
def test_mesh_shard_owners_disjoint(backends):
    """Every mesh slot registers its own devscope census owner, and
    ownership is DISJOINT: no device buffer is attributed twice."""
    from gethsharding_tpu import devscope

    backend = backends[8]
    messages, sig_rows, pk_rows, keys = _committee_cols()
    backend.bls_verify_committees(messages, sig_rows, pk_rows,
                                  pk_row_keys=keys)
    registered = set(devscope.owners())
    for i in range(8):
        assert f"pk_plane_lru_shard{i}" in registered
    buf_ids = [set(map(id, backend._mesh_shard_buffers(i)))
               for i in range(8)]
    for i in range(8):
        assert buf_ids[i], f"shard{i} owns no buffers after a dispatch"
        for j in range(i + 1, 8):
            assert not (buf_ids[i] & buf_ids[j]), (
                f"shards {i} and {j} both claim a buffer")
    assert sum(backend._mesh_claimed_bytes(i) for i in range(8)) > 0
