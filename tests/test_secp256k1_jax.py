"""Differential tests: batched ECDSA recovery (ops/secp256k1_jax) vs the
scalar reference (crypto/secp256k1.py, RFC6979 round-trip tested)."""

import numpy as np
import jax
import jax.numpy as jnp

from gethsharding_tpu.crypto import secp256k1 as ref
from gethsharding_tpu.crypto.keccak import keccak256
from gethsharding_tpu.ops import secp256k1_jax as k
from gethsharding_tpu.ops.limb import ints_to_limbs


def _case(i: int):
    priv = int.from_bytes(keccak256(b"priv" + bytes([i])), "big") % ref.N
    if priv == 0:
        priv = 1
    msg = keccak256(b"msg" + bytes([i]))
    sig = ref.sign(msg, priv)
    return priv, msg, sig


def test_batch_recovery_matches_scalar():
    cases = [_case(i) for i in range(6)]
    msgs = [m for _, m, _ in cases]
    sigs = [s for _, _, s in cases]
    e = jnp.asarray(k.hashes_to_limbs(msgs))
    r, s, v = k.sigs_to_limbs(sigs)
    qx, qy, ok = jax.jit(k.ecrecover_batch)(
        e, jnp.asarray(r), jnp.asarray(s), jnp.asarray(v),
        jnp.ones(len(cases), bool))
    got = k.limbs_to_pubkeys(qx, qy, ok)
    for i, (priv, msg, sig) in enumerate(cases):
        expect = ref.recover(msg, sig)
        assert got[i] == expect, i
        assert got[i] == ref.pubkey_from_priv(priv)


def test_invalid_rows_rejected():
    priv, msg, sig = _case(0)
    zero = ints_to_limbs([0])[0]
    big = ints_to_limbs([ref.N])[0]  # r = n: out of range
    e = jnp.asarray(k.hashes_to_limbs([msg] * 5))
    r, s, v = k.sigs_to_limbs([sig] * 5)
    r = np.stack([r[0], zero, big, r[0], r[0]])
    v2 = np.array([sig.v, sig.v, sig.v, 2, -1], np.int32)  # recid 2, -1
    qx, qy, ok = jax.jit(k.ecrecover_batch)(
        e, jnp.asarray(r), jnp.asarray(s), jnp.asarray(v2),
        jnp.ones(5, bool))
    assert list(np.asarray(ok)) == [True, False, False, False, False]


def test_tampered_hash_recovers_different_key():
    priv, msg, sig = _case(1)
    other = keccak256(b"other")
    e = jnp.asarray(k.hashes_to_limbs([msg, other]))
    r, s, v = k.sigs_to_limbs([sig, sig])
    qx, qy, ok = jax.jit(k.ecrecover_batch)(
        e, jnp.asarray(r), jnp.asarray(s), jnp.asarray(v), jnp.ones(2, bool))
    got = k.limbs_to_pubkeys(qx, qy, ok)
    assert got[0] == ref.pubkey_from_priv(priv)
    assert got[1] is not None and got[1] != got[0]
