"""Swarm-role storage tests: BMT hashing/proofs and the content-
addressed tree chunker (split/join, integrity, persistence)."""

import os

import pytest

from gethsharding_tpu.crypto.keccak import keccak256
from gethsharding_tpu.db.kv import SqliteKV
from gethsharding_tpu.storage import (
    CHUNK_SIZE, ChunkStore, SEGMENT_SIZE, bmt_hash, bmt_proof, bmt_verify)
from gethsharding_tpu.storage.bmt import BMTError, MAX_CHUNK
from gethsharding_tpu.storage.chunker import ChunkStoreError


def test_bmt_structure_matches_the_recursion_rule():
    # one segment: the raw keccak (no tree)
    assert bmt_hash(b"abc") == keccak256(b"abc")
    assert bmt_hash(b"") == keccak256(b"")
    # two segments: keccak(H(left) || H(right))
    data = os.urandom(64)
    expect = keccak256(keccak256(data[:32]) + keccak256(data[32:]))
    assert bmt_hash(data) == expect
    # 33 bytes: split at 32, one-byte raw tail hashed as a leaf
    data = os.urandom(33)
    assert bmt_hash(data) == keccak256(
        keccak256(data[:32]) + keccak256(data[32:]))
    # three segments: split at 64 (largest pow2 < 96)
    data = os.urandom(96)
    left = keccak256(keccak256(data[:32]) + keccak256(data[32:64]))
    assert bmt_hash(data) == keccak256(left + keccak256(data[64:]))
    with pytest.raises(BMTError):
        bmt_hash(b"\x00" * (MAX_CHUNK + 1))


@pytest.mark.parametrize("size", [32, 33, 64, 96, 1000, MAX_CHUNK])
def test_bmt_inclusion_proofs(size):
    data = os.urandom(size)
    root = bmt_hash(data)
    n_segments = (size + SEGMENT_SIZE - 1) // SEGMENT_SIZE
    for index in {0, n_segments // 2, n_segments - 1}:
        segment, path = bmt_proof(data, index)
        assert segment == data[index * 32:(index + 1) * 32]
        assert bmt_verify(root, segment, path)
        # forged segment fails
        assert not bmt_verify(root, b"\xee" * len(segment), path) \
            or segment == b"\xee" * len(segment)
    with pytest.raises(BMTError):
        bmt_proof(data, n_segments + 1)


@pytest.mark.parametrize("size", [
    0, 1, CHUNK_SIZE - 1, CHUNK_SIZE, CHUNK_SIZE + 1,
    3 * CHUNK_SIZE + 7, 130 * CHUNK_SIZE + 5,
    # trailing-lone-subtree sizes: a level whose last group has exactly
    # one child (the 1-ary interior regression class)
    128 * CHUNK_SIZE + 32, 128 * CHUNK_SIZE + 100, 129 * CHUNK_SIZE])
def test_chunker_roundtrip(size):
    store = ChunkStore()
    data = os.urandom(size)
    root = store.store(data)
    assert len(root) == 32
    assert store.retrieve(root) == data
    assert store.size(root) == size
    assert store.has(root)
    # storing the same content is idempotent: same address
    assert store.store(data) == root
    # different content, different address
    if size:
        assert store.store(data[:-1] + b"\x00") != root or data[-1:] == b"\x00"


@pytest.mark.parametrize("size", [0, 1, 31, 32])
def test_bmt_proof_single_segment_chunks(size):
    """A chunk of at most one segment: the proof is the empty path and
    the segment IS the (possibly partial, possibly empty) data."""
    data = os.urandom(size)
    root = bmt_hash(data)
    segment, path = bmt_proof(data, 0)
    assert segment == data
    assert path == []
    assert bmt_verify(root, segment, path)
    # the first out-of-range index must raise, not return a bogus proof
    with pytest.raises(BMTError):
        bmt_proof(data, 1)
    # a forged single-segment value fails (empty data has no forgery
    # with the same length-0 segment)
    if size:
        forged = bytes([segment[0] ^ 1]) + segment[1:]
        assert not bmt_verify(root, forged, path)


@pytest.mark.parametrize("size", [
    33, 63, 65, 95, 97, 129, 4064, 4065, 4095,
])
def test_bmt_proof_final_partial_segment(size):
    """EVERY segment of a partial-tail chunk proves — especially the
    final partial one — and the first index past the tail raises. The
    proof boundary is the exact segment count, no off-by-one in either
    direction."""
    data = os.urandom(size)
    root = bmt_hash(data)
    n_segments = (size + SEGMENT_SIZE - 1) // SEGMENT_SIZE
    for index in range(n_segments):
        segment, path = bmt_proof(data, index)
        assert segment == data[index * SEGMENT_SIZE:
                               (index + 1) * SEGMENT_SIZE]
        assert bmt_verify(root, segment, path)
    # the final segment is partial by construction for these sizes
    tail, tail_path = bmt_proof(data, n_segments - 1)
    assert 0 < len(tail) < SEGMENT_SIZE or size % SEGMENT_SIZE == 0
    # a partial tail padded to a full segment must NOT verify (the raw
    # short leaf is the hashed domain, zero-padding changes the hash)
    if len(tail) < SEGMENT_SIZE:
        padded = tail + b"\x00" * (SEGMENT_SIZE - len(tail))
        assert not bmt_verify(root, padded, tail_path)
    with pytest.raises(BMTError):
        bmt_proof(data, n_segments)
    with pytest.raises(BMTError):
        bmt_proof(data, -1)


def test_bmt_interior_preimage_forgery_is_rejected():
    """Leaf/interior domain separation: an interior node's 64-byte
    preimage presented as a 'segment' with a truncated path must NOT
    verify (it hashes to the root by construction)."""
    data = os.urandom(64)
    root = bmt_hash(data)
    forged_segment = keccak256(data[:32]) + keccak256(data[32:])
    assert not bmt_verify(root, forged_segment, [])
    # deeper variant: present a subtree's preimage one level up
    data = os.urandom(128)
    root = bmt_hash(data)
    left = keccak256(keccak256(data[:32]) + keccak256(data[32:64]))
    right = keccak256(keccak256(data[64:96]) + keccak256(data[96:]))
    assert not bmt_verify(root, left + right, [])


def test_chunker_truncated_record_is_a_chunk_error():
    store = ChunkStore()
    root = store.store(b"hello")
    store.kv.put(b"chunk:" + root, b"\x01\x02")  # shorter than the span
    with pytest.raises(ChunkStoreError, match="truncated|corrupt"):
        store.retrieve(root)


def test_chunker_detects_corruption_and_missing_chunks():
    store = ChunkStore()
    data = os.urandom(2 * CHUNK_SIZE + 100)
    root = store.store(data)

    # corrupt one stored leaf: retrieval must fail loudly
    victim = next(k for k, v in store.kv.items()
                  if k.startswith(b"chunk:") and len(v) == 8 + CHUNK_SIZE)
    store.kv.put(victim, b"\x00" * len(store.kv.get(victim)))
    with pytest.raises(ChunkStoreError, match="corrupt|missing"):
        store.retrieve(root)

    store2 = ChunkStore()
    with pytest.raises(ChunkStoreError, match="missing"):
        store2.retrieve(root)


def test_chunker_persists_over_sqlite(tmp_path):
    path = str(tmp_path / "chunks.db")
    data = os.urandom(CHUNK_SIZE * 2 + 17)
    store = ChunkStore(kv=SqliteKV(path))
    root = store.store(data)
    store.kv.close()

    reopened = ChunkStore(kv=SqliteKV(path))
    assert reopened.retrieve(root) == data
    reopened.kv.close()


# == networked store/retrieve (storage/netstore.py — netstore.go role) =====


def _net_pair():
    from gethsharding_tpu.p2p.service import Hub, P2PServer
    from gethsharding_tpu.storage.netstore import NetStore

    hub = Hub()
    a = NetStore(p2p=P2PServer(hub=hub))
    b = NetStore(p2p=P2PServer(hub=hub))
    a.start()
    b.start()
    return a, b


def test_netstore_retrieves_remote_content():
    """Content published on one node reassembles on another from just
    the root key: requests broadcast, chunks delivered peer-to-peer,
    every chunk re-verified content-addressed before it lands."""
    a, b = _net_pair()
    try:
        data = os.urandom(3 * CHUNK_SIZE + 123)
        root = a.store_content(data)
        assert not b.store.has(root)
        assert b.retrieve(root) == data
        # fetched chunks persisted locally: the second read is offline
        assert b.store.retrieve(root) == data
        assert a.chunks_served >= 4
        assert b.chunks_fetched >= 4
    finally:
        a.stop()
        b.stop()


def test_netstore_rejects_forged_deliveries_and_times_out():
    from gethsharding_tpu.p2p.service import Hub, P2PServer
    from gethsharding_tpu.storage.netstore import ChunkDelivery, NetStore
    from gethsharding_tpu.storage.chunker import ChunkStoreError

    hub = Hub()
    honest = NetStore(p2p=P2PServer(hub=hub), fetch_timeout=0.4)
    evil_p2p = P2PServer(hub=hub)
    evil_p2p.start()
    honest.start()
    try:
        missing = keccak256(b"nobody has this")
        # a forged delivery for the key we want must be discarded
        evil_p2p.broadcast(ChunkDelivery(key=missing, span=5,
                                         payload=b"evil!"))
        with pytest.raises(ChunkStoreError, match="unavailable"):
            honest.get_chunk(missing)
        assert honest.deliveries_rejected >= 1
        assert not honest.store.has(missing)
    finally:
        honest.stop()
        evil_p2p.stop()


def test_netstore_offline_is_a_plain_chunkstore():
    from gethsharding_tpu.storage.netstore import NetStore
    from gethsharding_tpu.storage.chunker import ChunkStoreError

    ns = NetStore()  # no p2p
    ns.start()
    try:
        data = os.urandom(CHUNK_SIZE + 1)
        root = ns.store_content(data)
        assert ns.retrieve(root) == data
        with pytest.raises(ChunkStoreError, match="offline"):
            ns.get_chunk(keccak256(b"absent"))
    finally:
        ns.stop()


def test_netstore_over_remote_hub_direct_plane():
    """Cross-process shape: chunk request/delivery ride the typed wire
    codec and the authenticated direct sockets between two RemoteHubs —
    content fetched from a peer process without transiting the relay."""
    from gethsharding_tpu.mainchain.accounts import AccountManager
    from gethsharding_tpu.p2p.remote import RemoteHub
    from gethsharding_tpu.p2p.service import P2PServer
    from gethsharding_tpu.params import Config
    from gethsharding_tpu.rpc.server import RPCServer
    from gethsharding_tpu.smc.chain import SimulatedMainchain
    from gethsharding_tpu.storage.netstore import NetStore

    backend = SimulatedMainchain(config=Config(network_id=13))
    server = RPCServer(backend, port=0)
    server.start()
    stores, hubs = [], []
    try:
        host, port = server.address
        for seed in (b"na", b"nb"):
            mgr = AccountManager()
            addr = mgr.new_account(seed=seed).address
            hub = RemoteHub.dial(host, port, accounts=mgr, account=addr)
            ns = NetStore(p2p=P2PServer(hub=hub), fetch_timeout=5.0)
            ns.start()
            hubs.append(hub)
            stores.append(ns)
        a, b = stores
        data = os.urandom(2 * CHUNK_SIZE + 55)
        root = a.store_content(data)
        sends_before = server.p2p_relayed_sends
        assert b.retrieve(root) == data
        # deliveries crossed the direct sockets, not the relay
        assert server.p2p_relayed_sends == sends_before
    finally:
        for ns in stores:
            ns.stop()
        server.stop()
