"""Driver-contract tests: __graft_entry__.entry / dryrun_multichip.

Mirrors what the driver does: compile-check `entry()` on one device and
run `dryrun_multichip(8)` on the virtual 8-device CPU mesh (conftest).
"""

import sys
from pathlib import Path

import jax

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import __graft_entry__ as graft  # noqa: E402


def test_entry_compiles_and_runs():
    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    out.block_until_ready()
    assert out.shape == (4,)
    assert bool(out.all()), "flagship BLS verification must accept"


def test_dryrun_multichip_8_devices():
    graft.dryrun_multichip(8)
