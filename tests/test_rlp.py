"""RLP codec conformance: canonical vectors from the Ethereum RLP spec."""

import pytest

from gethsharding_tpu.utils.rlp import (
    DecodingError,
    int_to_big_endian,
    rlp_decode,
    rlp_encode,
)

# (python object, expected encoding hex) — spec vectors
VECTORS = [
    (b"", "80"),
    (b"\x00", "00"),
    (b"\x0f", "0f"),
    (b"\x7f", "7f"),
    (b"\x80", "8180"),
    (b"dog", "83646f67"),
    ([], "c0"),
    ([b"cat", b"dog"], "c88363617483646f67"),
    (b"Lorem ipsum dolor sit amet, consectetur adipisicing elit",
     "b8384c6f72656d20697073756d20646f6c6f722073697420616d65742c20636f6e73656374657475722061646970697369636"
     "96e6720656c6974"),
    ([[], [[]], [[], [[]]]], "c7c0c1c0c3c0c1c0"),
    (0, "80"),
    (1, "01"),
    (15, "0f"),
    (1024, "820400"),
]


@pytest.mark.parametrize("obj,expected", VECTORS)
def test_encode_vectors(obj, expected):
    assert rlp_encode(obj).hex() == expected


def test_roundtrip_nested():
    obj = [b"abc", [b"", b"\x01", [b"xyz" * 40]], b"\x80" * 60]
    assert rlp_decode(rlp_encode(obj)) == obj


def test_decode_rejects_trailing():
    with pytest.raises(DecodingError):
        rlp_decode(bytes.fromhex("8180ff"))


def test_decode_rejects_noncanonical_single_byte():
    # 0x7f must encode as itself, not 0x817f
    with pytest.raises(DecodingError):
        rlp_decode(bytes.fromhex("817f"))


def test_decode_rejects_noncanonical_long_length():
    # length 3 must use short form, not long form 0xb803...
    with pytest.raises(DecodingError):
        rlp_decode(bytes.fromhex("b803646f67"))


def test_int_to_big_endian():
    assert int_to_big_endian(0) == b""
    assert int_to_big_endian(127) == b"\x7f"
    assert int_to_big_endian(256) == b"\x01\x00"
