"""Replay engine differential tests (BASELINE config 4): the vmapped
device replay (`ops/replay_jax`) against the scalar twin
(`core/state_processor`), status-for-status and root-for-root."""

import numpy as np
import pytest

from gethsharding_tpu.core import state_processor as sp
from gethsharding_tpu.core.types import Transaction
from gethsharding_tpu.crypto import secp256k1
from gethsharding_tpu.ops import replay_jax
from gethsharding_tpu.utils.hexbytes import Address20

ETH = 10 ** 18


def mkkey(seed: int):
    priv = (seed * 7919 + 13) % secp256k1.N or 1
    return priv, secp256k1.priv_to_address(priv)


def tx(priv, nonce, to, value=0, price=1, limit=25000, payload=b""):
    return sp.sign_transaction(
        Transaction(nonce=nonce, gas_price=price, gas_limit=limit, to=to,
                    value=value, payload=payload), priv)


@pytest.fixture(scope="module")
def scenario():
    """3 shards with success + every rejection class."""
    keys = [mkkey(i) for i in range(1, 7)]
    (pa, a), (pb, b), (pc, c), (pd, d), (pe, e), (_, coin) = keys

    shard0 = [
        tx(pa, 0, b, value=5 * ETH, payload=b"\x00\x01hello"),  # ok
        tx(pb, 0, c, value=1 * ETH),                            # ok
        tx(pa, 5, b, value=1),            # wrong nonce -> reject
        tx(pc, 0, a, value=100 * ETH),    # insufficient balance
        tx(pd, 0, a, value=0, limit=100),  # intrinsic > gas limit
        tx(pa, 1, a, value=2 * ETH),      # self-transfer, ok
    ]
    # a bad signature: sign then corrupt s
    bad = tx(pe, 0, a, value=1)
    bad = Transaction(nonce=bad.nonce, gas_price=bad.gas_price,
                      gas_limit=bad.gas_limit, to=bad.to, value=bad.value,
                      payload=bad.payload, v=bad.v, r=bad.r,
                      s=(bad.s + 1) % secp256k1.N)
    shard1 = [
        bad,                                                    # reject
        tx(pe, 0, coin, value=3 * ETH),   # pays the coinbase directly, ok
        tx(pe, 1, b, value=1 * ETH, price=2, payload=b"\x00" * 10),  # ok
    ]
    shard2 = []  # empty shard: pure padding path

    genesis = [
        {a: sp.AccountState(balance=10 * ETH),
         b: sp.AccountState(balance=2 * ETH),
         c: sp.AccountState(balance=1 * ETH),
         d: sp.AccountState(balance=1 * ETH)},
        {e: sp.AccountState(balance=8 * ETH)},
        {a: sp.AccountState(balance=1 * ETH)},
    ]
    return ([shard0, shard1, shard2], genesis, [coin, coin, coin])


def test_device_replay_matches_scalar(scenario):
    shard_txs, genesis, coinbases = scenario
    inp = replay_jax.build_replay_inputs(shard_txs, genesis, coinbases)
    out = replay_jax.replay_batch(inp)

    a_total = inp.addrs.shape[1]
    for i, (txs, gen, coin) in enumerate(zip(shard_txs, genesis, coinbases)):
        state = sp.ShardState({k: sp.AccountState(v.nonce, v.balance)
                               for k, v in gen.items()})
        # pre-create every table row so the commitment covers equal sets
        for a in sp.touched_addresses(txs, coin):
            state.get(a)
        receipts = sp.process(state, txs, coin)

        got_status = [bool(s) for s in np.asarray(out.statuses[i])[:len(txs)]]
        assert got_status == [r.status == 1 for r in receipts], f"shard {i}"
        got_gas = [int(g) for g in np.asarray(out.gas_used[i])[:len(txs)]]
        assert got_gas == [r.gas_used for r in receipts], f"shard {i}"

        expect_root = replay_jax.scalar_root_with_padding(state, a_total)
        got_root = bytes(np.asarray(out.roots[i]))
        assert got_root == bytes(expect_root), f"shard {i} root"


def test_replay_applies_expected_balances(scenario):
    shard_txs, genesis, coinbases = scenario
    inp = replay_jax.build_replay_inputs(shard_txs, genesis, coinbases)
    out = replay_jax.replay_batch(inp)
    # pick shard 0's sender `a`: 10 ETH - 5 ETH - fees - self-transfer nets
    state = sp.ShardState({k: sp.AccountState(v.nonce, v.balance)
                           for k, v in genesis[0].items()})
    for addr in sp.touched_addresses(shard_txs[0], coinbases[0]):
        state.get(addr)
    sp.process(state, shard_txs[0], coinbases[0])
    table = sorted(state.accounts, key=bytes)
    row = table.index(sorted(
        state.accounts, key=bytes)[0])  # deterministic row order
    nonces = np.asarray(out.nonces[0])
    balances = np.asarray(out.balances[0])
    for row, addr in enumerate(table):
        acct = state.accounts[addr]
        assert int(nonces[row]) == acct.nonce
        got_bal = sum(int(b) << (8 * k)
                      for k, b in enumerate(balances[row]))
        assert got_bal == acct.balance, f"row {row}"


def test_proposer_path_collation_replay(scenario):
    """The proposer-path flow: txs -> blob -> collation body -> decoded
    txs -> device replay (the config-4 pipeline over a real collation)."""
    from gethsharding_tpu.core.types import (
        deserialize_blob_to_txs,
        serialize_txs_to_blob,
    )

    shard_txs, genesis, coinbases = scenario
    blob = serialize_txs_to_blob(shard_txs[0])
    decoded = deserialize_blob_to_txs(blob)
    assert [t.hash() for t in decoded] == [t.hash() for t in shard_txs[0]]

    inp = replay_jax.build_replay_inputs([decoded], [genesis[0]],
                                         [coinbases[0]])
    out = replay_jax.replay_batch(inp)
    state = sp.ShardState({k: sp.AccountState(v.nonce, v.balance)
                           for k, v in genesis[0].items()})
    for a in sp.touched_addresses(decoded, coinbases[0]):
        state.get(a)
    receipts = sp.process(state, decoded, coinbases[0])
    assert [bool(s) for s in np.asarray(out.statuses[0])[:len(decoded)]] \
        == [r.status == 1 for r in receipts]
    assert bytes(np.asarray(out.roots[0])) == bytes(
        replay_jax.scalar_root_with_padding(state, inp.addrs.shape[1]))


def test_observer_device_replay_matches_python_engine():
    """The live observer's jax path (batched recovery + transition, folded
    back into the host table) ends at the same state root as the python
    engine replaying the same collations."""
    from gethsharding_tpu.actors.observer import Observer
    from gethsharding_tpu.core import state_processor as sp
    from gethsharding_tpu.core.shard import Shard
    from gethsharding_tpu.core.types import (
        Collation, CollationHeader, Transaction)
    from gethsharding_tpu.crypto import secp256k1
    from gethsharding_tpu.crypto.keccak import keccak256
    from gethsharding_tpu.db.kv import MemoryKV
    from gethsharding_tpu.mainchain.client import SMCClient
    from gethsharding_tpu.smc.chain import SimulatedMainchain
    from gethsharding_tpu.utils.hexbytes import Hash32

    priv_a, priv_b = 0xAAA1, 0xBBB2
    a = secp256k1.priv_to_address(priv_a)
    b = secp256k1.priv_to_address(priv_b)
    proposer = secp256k1.priv_to_address(0xCCC3)
    genesis = {a: sp.AccountState(balance=10**12),
               b: sp.AccountState(balance=10**9)}

    def collation(period, txs):
        header = CollationHeader(
            shard_id=0, chunk_root=Hash32(keccak256(b"r%d" % period)),
            period=period, proposer_address=proposer)
        return Collation(header=header, transactions=txs)

    col1 = collation(1, [
        sp.sign_transaction(Transaction(nonce=0, gas_price=3,
                                        gas_limit=25000, to=b, value=500,
                                        payload=b"one"), priv_a),
        sp.sign_transaction(Transaction(nonce=0, gas_price=1,
                                        gas_limit=25000, to=a, value=9,
                                        payload=b""), priv_b),
        sp.sign_transaction(Transaction(nonce=7, gas_price=1,  # bad nonce
                                        gas_limit=25000, to=a, value=9,
                                        payload=b""), priv_b),
    ])
    col2 = collation(2, [
        sp.sign_transaction(Transaction(nonce=1, gas_price=2,
                                        gas_limit=30000, to=b, value=1,
                                        payload=b"x" * 40), priv_a),
    ])
    fresh = secp256k1.priv_to_address(0xFFF7)
    col3 = collation(3, [  # ALL rejected: zero-row materialization parity
        sp.sign_transaction(Transaction(nonce=42, gas_price=1,
                                        gas_limit=25000, to=fresh, value=1,
                                        payload=b""), priv_b),
    ])

    roots = {}
    for engine in ("python", "jax"):
        observer = Observer(
            client=SMCClient(backend=SimulatedMainchain()),
            shard=Shard(shard_id=0, shard_db=MemoryKV()),
            replay_engine=engine, genesis=genesis)
        observer.replay_collation(1, col1)
        roots[engine, 1] = observer.state_roots[1]
        roots[engine, 2] = observer.replay_collation(2, col2)
        roots[engine, 3] = observer.replay_collation(3, col3)
        assert observer.txs_replayed == 3
        assert observer.txs_rejected == 2
    for period in (1, 2, 3):
        assert roots["python", period] == roots["jax", period], period


def test_canonical_state_roots_match_scalar_trie(scenario):
    """The host-side canonical secure-MPT roots of the device replay
    equal the scalar twin's trie_root per shard — and differ from the
    flat integrity commitment (they hash different structures)."""
    shard_txs, genesis, coinbases = scenario
    inp = replay_jax.build_replay_inputs(shard_txs, genesis, coinbases)
    out = replay_jax.replay_batch(inp)
    got = replay_jax.canonical_state_roots(inp, out)

    for s, (txs, gen, coin) in enumerate(zip(shard_txs, genesis, coinbases)):
        twin = sp.ShardState({a: sp.AccountState(acct.nonce, acct.balance)
                              for a, acct in gen.items()})
        sp.process(twin, txs, coin)
        assert bytes(got[s]) == bytes(twin.trie_root()), s
        assert bytes(got[s]) != bytes(
            replay_jax.scalar_root_with_padding(twin, inp.addrs.shape[1])), s


def test_state_trie_root_native_matches_python_trie():
    """The bulk native MPT builder and the Python SecureTrie agree on the
    account-state trie (32-byte keccak keys, account-RLP values up to the
    maximal 110-byte encoding)."""
    from gethsharding_tpu import native
    from gethsharding_tpu.core.trie import SecureTrie
    from gethsharding_tpu.crypto.keccak import keccak256

    rng = np.random.default_rng(5)
    accounts = {}
    for i in range(50):
        addr = Address20(bytes(rng.integers(0, 256, 20, dtype=np.uint8)))
        accounts[addr] = sp.AccountState(
            nonce=int(rng.integers(0, 2 ** 31)),
            balance=int(rng.integers(1, 2 ** 62)) << int(rng.integers(0, 190)))
    want = SecureTrie()
    for addr, acct in accounts.items():
        want.update(bytes(addr), sp.account_rlp(acct.nonce, acct.balance))
    got = sp.state_trie_root(accounts)
    assert bytes(got) == want.root_hash()
    if native.available():  # both paths must agree with the pure trie
        items = sorted((keccak256(bytes(a)),
                        sp.account_rlp(acct.nonce, acct.balance))
                       for a, acct in accounts.items())
        nat = native.mpt_root([k for k, _ in items], [v for _, v in items])
        assert nat == want.root_hash()


def test_empty_and_emptied_accounts_absent_from_canonical_root():
    """EIP-158 delete-empty parity: zero accounts never shape the trie."""
    from gethsharding_tpu.core.trie import EMPTY_ROOT

    assert bytes(sp.ShardState().trie_root()) == EMPTY_ROOT
    a = secp256k1.priv_to_address(0x111)
    b = secp256k1.priv_to_address(0x222)
    one = sp.ShardState({a: sp.AccountState(balance=7)})
    padded = sp.ShardState({a: sp.AccountState(balance=7),
                            b: sp.AccountState()})
    assert one.trie_root() == padded.trie_root()
    assert one.root() != padded.root()  # the flat check DOES see the row


def test_contract_creation_rejected_by_both_engines():
    """to=None (contract creation) is out of phase-1 scope: both engines
    reject it with no state change and identical roots."""
    priv, sender = mkkey(9)
    creation = sp.sign_transaction(
        Transaction(nonce=0, gas_price=1, gas_limit=60000, to=None,
                    value=0, payload=b"\x60\x00"), priv)
    genesis = {sender: sp.AccountState(balance=1 * ETH)}

    twin = sp.ShardState({a: sp.AccountState(acct.nonce, acct.balance)
                          for a, acct in genesis.items()})
    receipts = sp.process(twin, [creation], sender)
    assert [r.status for r in receipts] == [0]
    assert twin.get(sender).nonce == 0

    inp = replay_jax.build_replay_inputs([[creation]], [genesis], [sender])
    assert not bool(np.asarray(inp.tx_valid)[0, 0])  # rejected at marshal
    out = replay_jax.replay_batch(inp)
    assert not bool(np.asarray(out.statuses)[0, 0])
    got = replay_jax.canonical_state_roots(inp, out)
    assert bytes(got[0]) == bytes(twin.trie_root())
