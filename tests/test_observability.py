"""HTTP status endpoint + console REPL (dashboard/console analogs)."""

import io
import json
import subprocess
import sys
import urllib.request

from gethsharding_tpu.node.backend import ShardNode
from gethsharding_tpu.smc.chain import SimulatedMainchain


def _get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=5) as resp:
        return resp.status, json.loads(resp.read())


def test_status_endpoint_serves_health_metrics_status():
    node = ShardNode(actor="observer", backend=SimulatedMainchain(),
                     txpool_interval=None, http_port=0)
    node.start()
    try:
        from gethsharding_tpu.node.http_status import StatusServer

        port = node.service(StatusServer).port
        code, health = _get(port, "/healthz")
        assert code == 200
        assert health["status"] == "ok"
        assert health["services"]["syncer"] == "running"

        code, status = _get(port, "/status")
        assert code == 200
        assert status["actor"] == "observer"
        assert status["period"] == 0
        assert status["account"].startswith("0x")

        code, metrics = _get(port, "/metrics")
        assert code == 200
        assert isinstance(metrics, dict)

        # unknown path -> 404
        try:
            urllib.request.urlopen(f"http://127.0.0.1:{port}/nope", timeout=5)
            raise AssertionError("expected 404")
        except urllib.error.HTTPError as exc:
            assert exc.code == 404
    finally:
        node.stop()


def test_status_endpoint_reports_degraded_on_crash():
    from gethsharding_tpu.actors.syncer import Syncer
    from gethsharding_tpu.node.http_status import StatusServer

    node = ShardNode(actor="observer", backend=SimulatedMainchain(),
                     txpool_interval=None, http_port=0)
    node.start()
    try:
        victim = node.service(Syncer)
        victim.spawn(lambda: (_ for _ in ()).throw(RuntimeError("boom")),
                     name="crash")
        import time

        deadline = time.time() + 3.0
        while time.time() < deadline and not victim.crashed:
            time.sleep(0.02)
        port = node.service(StatusServer).port
        _, health = _get(port, "/healthz")
        assert health["status"] == "degraded"
        assert health["services"]["syncer"] == "crashed"
    finally:
        node.stop()


def test_console_drives_a_chain_over_rpc():
    """Console commands against a real chain process over a socket."""
    from gethsharding_tpu.console import ShardingConsole
    from gethsharding_tpu.crypto.keccak import keccak256
    from gethsharding_tpu.mainchain.accounts import AccountManager
    from gethsharding_tpu.params import ETHER
    from gethsharding_tpu.rpc.client import RemoteMainchain
    from gethsharding_tpu.rpc.server import RPCServer
    from gethsharding_tpu.utils.hexbytes import Hash32

    backend = SimulatedMainchain()
    server = RPCServer(backend, port=0)
    server.start()
    try:
        manager = AccountManager()
        acct = manager.new_account(seed=b"console")
        backend.fund(acct.address, 2000 * ETHER)
        backend.register_notary(
            acct.address, bls_pubkey=acct.bls_pubkey,
            bls_pop=manager.bls_proof_of_possession(acct.address))
        backend.fast_forward(1)
        root = Hash32(keccak256(b"console-root"))
        period = backend.current_period()
        backend.add_header(acct.address, 3, period, root)
        # one signed vote so the audit command has an auditable shard
        from gethsharding_tpu.smc.state_machine import vote_digest

        backend.submit_vote(
            acct.address, 3, period, 0, root,
            bls_sig=manager.bls_sign(acct.address,
                                     bytes(vote_digest(3, period, root))))

        chain = RemoteMainchain.dial(*server.address)
        addr_hex = "0x" + bytes(acct.address).hex()
        script = "\n".join([
            "block", "period", "shards",
            f"balance {addr_hex}",
            f"registry {addr_hex}",
            "record 3",
            "record 99",
            "votes 3",
            "submitted 3",
            "audit 1",
            "commit",
            "fastforward 2",
            "bogus-command",
            "record not-a-number",
            "quit",
        ]) + "\n"
        out = io.StringIO()
        console = ShardingConsole(chain, stdin=io.StringIO(script),
                                  stdout=out)
        console.cmdloop()
        chain.close()
        text = out.getvalue()
        assert f"{backend.config.shard_count}" in text
        assert "pool_index=0" in text
        assert "chunk_root=0x" + bytes(root).hex() in text
        assert "no record" in text
        # the tally audit over the bulk auditData pull
        assert "period 1 shard 3: votes=1 signed=1 elected=False" in text
        assert "1 shards audited, consistent" in text
        assert "block 6" in text      # commit mined block 6 (period 1 + 1)
        assert "error:" in text       # bad args answered, session survived
        # the two dev commands really advanced the remote chain
        assert backend.current_period() == 3
    finally:
        server.stop()


def test_cli_attach_subcommand_end_to_end():
    """`tpu-sharding attach` as a real subprocess against a chain-server
    subprocess — the full operator flow across two OS processes."""
    chain_proc = subprocess.Popen(
        [sys.executable, "-m", "gethsharding_tpu.rpc.chain_server",
         "--port", "0", "--runtime", "30"],
        stdout=subprocess.PIPE, text=True)
    try:
        info = json.loads(chain_proc.stdout.readline())
        out = subprocess.run(
            [sys.executable, "-m", "gethsharding_tpu.node.cli", "attach",
             "--port", str(info["port"])],
            input="period\ncommit\nquit\n", text=True,
            capture_output=True, timeout=30)
        assert out.returncode == 0
        assert "block 1" in out.stdout
    finally:
        chain_proc.terminate()
        chain_proc.wait(timeout=10)


def test_key_tool_roundtrip(tmp_path):
    """ethkey analog: new -> list -> inspect over the CLI."""
    from gethsharding_tpu.node.cli import run_cli

    ks = str(tmp_path / "keystore")
    pw = tmp_path / "pw"
    pw.write_text("secret\n")
    assert run_cli(["key", "new", "--keystore", ks,
                    "--password", str(pw)]) == 0
    from gethsharding_tpu.mainchain.keystore import Keystore

    accounts = Keystore(ks).accounts()
    assert len(accounts) == 1
    assert run_cli(["key", "list", "--keystore", ks]) == 0
    assert run_cli(["key", "inspect", "--keystore", ks,
                    "--address", accounts[0].address.hex_str,
                    "--password", str(pw)]) == 0
    # wrong password -> clean failure
    bad = tmp_path / "bad"
    bad.write_text("wrong")
    assert run_cli(["key", "inspect", "--keystore", ks,
                    "--address", accounts[0].address.hex_str,
                    "--password", str(bad)]) == 1


def test_rlpdump_tool(capsys):
    from gethsharding_tpu.node.cli import run_cli
    from gethsharding_tpu.utils.rlp import rlp_encode

    blob = rlp_encode([b"cat", [b"dog", b""], b"\x01\x02"])
    assert run_cli(["rlpdump", blob.hex()]) == 0
    out = capsys.readouterr().out
    assert '"cat"' in out and '"dog"' in out and "0x0102" in out
    assert run_cli(["rlpdump", "zz-not-hex"]) == 1
    assert run_cli(["rlpdump", "c1"]) == 1  # truncated list payload


def test_dashboard_page_served_at_root():
    """The dashboard role (dashboard/dashboard.go): GET / returns the
    self-contained live page wired to the three JSON endpoints."""
    from gethsharding_tpu.node.http_status import StatusServer

    node = ShardNode(actor="observer", backend=SimulatedMainchain(),
                     txpool_interval=None, http_port=0)
    node.start()
    try:
        port = node.service(StatusServer).port
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/", timeout=5) as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"].startswith("text/html")
            page = resp.read().decode()
        for needle in ("/healthz", "/status", "/metrics", "<script>"):
            assert needle in page
    finally:
        node.stop()


def test_faucet_tool_drips_funds():
    """cmd/faucet analog: the CLI faucet funds an address on a running
    chain process over RPC."""
    from gethsharding_tpu.node.cli import run_cli
    from gethsharding_tpu.rpc.server import RPCServer

    backend = SimulatedMainchain()
    server = RPCServer(backend, port=0)
    server.start()
    try:
        addr = "0x" + "ab" * 20
        rc = run_cli(["faucet", "--port", str(server.address[1]),
                      "--address", addr, "--amount", "7"])
        assert rc == 0
        from gethsharding_tpu.params import ETHER
        from gethsharding_tpu.utils.hexbytes import Address20

        assert backend.balance_of(Address20(bytes.fromhex("ab" * 20))) \
            == 7 * ETHER
        assert run_cli(["faucet", "--port", str(server.address[1]),
                        "--address", "nonsense"]) == 1
    finally:
        server.stop()


def test_console_trace_and_python_mode():
    """The trace command prints a tx's event-level execution trace, and
    `py` drops into a scriptable Python REPL with the chain bound (the
    JS-REPL scripting role) — across two real OS processes."""
    chain_proc = subprocess.Popen(
        [sys.executable, "-m", "gethsharding_tpu.rpc.chain_server",
         "--port", "0", "--runtime", "60"],
        stdout=subprocess.PIPE, text=True)
    try:
        info = json.loads(chain_proc.stdout.readline())

        # produce a traceable tx through the remote surface
        from gethsharding_tpu.mainchain.accounts import AccountManager
        from gethsharding_tpu.params import ETHER
        from gethsharding_tpu.rpc.client import RemoteMainchain

        manager = AccountManager()
        acct = manager.new_account(seed=b"trace-console")
        remote = RemoteMainchain.dial("127.0.0.1", info["port"])
        remote.fund(acct.address, 2000 * ETHER)
        receipt = remote.register_notary(acct.address)
        tx_hex = "0x" + bytes(receipt.tx_hash).hex()
        trace = remote.trace_transaction(receipt.tx_hash)
        assert trace["status"] == 1
        assert trace["trace"][0]["event"] == "NotaryRegistered"
        assert trace["trace"][0]["args"]["notary"] == \
            "0x" + bytes(acct.address).hex()
        remote.close()

        script = "\n".join([
            f"trace {tx_hex}",
            "trace 0x" + "ee" * 32,
            "py",
            "print('PYMODE', chain.block_number, binding.shardCount())",
            "exit()",
            "period",  # proves exit() RETURNED to the sharding prompt
            "quit",
        ]) + "\n"
        out = subprocess.run(
            [sys.executable, "-m", "gethsharding_tpu.node.cli", "attach",
             "--port", str(info["port"])],
            input=script, text=True, capture_output=True, timeout=30)
        assert out.returncode == 0
        assert "NotaryRegistered" in out.stdout
        assert "unknown transaction" in out.stdout
        assert "PYMODE 0 100" in out.stdout
        # the console survived exit(): the period command ran after it
        # and printed its value (0) back at the sharding prompt
        assert "> 0\n" in out.stdout[out.stdout.index("PYMODE"):]
    finally:
        chain_proc.terminate()
        chain_proc.wait(timeout=10)
