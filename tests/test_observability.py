"""HTTP status endpoint + console REPL (dashboard/console analogs),
span tracing (gethsharding_tpu/tracing), and the Prometheus exposition
surface."""

import io
import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

from gethsharding_tpu import tracing
from gethsharding_tpu.node.backend import ShardNode
from gethsharding_tpu.smc.chain import SimulatedMainchain


def _get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=5) as resp:
        return resp.status, json.loads(resp.read())


@pytest.fixture
def tracer():
    """Enabled process tracer, reset afterwards (module-global state)."""
    tracing.enable(ring_spans=65536)
    tracing.TRACER.clear()
    yield tracing.TRACER
    tracing.disable()
    tracing.TRACER.clear()


def test_status_endpoint_serves_health_metrics_status():
    node = ShardNode(actor="observer", backend=SimulatedMainchain(),
                     txpool_interval=None, http_port=0)
    node.start()
    try:
        from gethsharding_tpu.node.http_status import StatusServer

        port = node.service(StatusServer).port
        code, health = _get(port, "/healthz")
        assert code == 200
        assert health["status"] == "ok"
        assert health["services"]["syncer"] == "running"

        code, status = _get(port, "/status")
        assert code == 200
        assert status["actor"] == "observer"
        assert status["period"] == 0
        assert status["account"].startswith("0x")

        code, metrics = _get(port, "/metrics")
        assert code == 200
        assert isinstance(metrics, dict)

        # unknown path -> 404
        try:
            urllib.request.urlopen(f"http://127.0.0.1:{port}/nope", timeout=5)
            raise AssertionError("expected 404")
        except urllib.error.HTTPError as exc:
            assert exc.code == 404
    finally:
        node.stop()


def test_status_endpoint_reports_degraded_on_crash():
    from gethsharding_tpu.actors.syncer import Syncer
    from gethsharding_tpu.node.http_status import StatusServer

    node = ShardNode(actor="observer", backend=SimulatedMainchain(),
                     txpool_interval=None, http_port=0)
    node.start()
    try:
        victim = node.service(Syncer)
        victim.spawn(lambda: (_ for _ in ()).throw(RuntimeError("boom")),
                     name="crash")
        import time

        deadline = time.time() + 3.0
        while time.time() < deadline and not victim.crashed:
            time.sleep(0.02)
        port = node.service(StatusServer).port
        _, health = _get(port, "/healthz")
        assert health["status"] == "degraded"
        assert health["services"]["syncer"] == "crashed"
    finally:
        node.stop()


def test_console_drives_a_chain_over_rpc():
    """Console commands against a real chain process over a socket."""
    from gethsharding_tpu.console import ShardingConsole
    from gethsharding_tpu.crypto.keccak import keccak256
    from gethsharding_tpu.mainchain.accounts import AccountManager
    from gethsharding_tpu.params import ETHER
    from gethsharding_tpu.rpc.client import RemoteMainchain
    from gethsharding_tpu.rpc.server import RPCServer
    from gethsharding_tpu.utils.hexbytes import Hash32

    backend = SimulatedMainchain()
    server = RPCServer(backend, port=0)
    server.start()
    try:
        manager = AccountManager()
        acct = manager.new_account(seed=b"console")
        backend.fund(acct.address, 2000 * ETHER)
        backend.register_notary(
            acct.address, bls_pubkey=acct.bls_pubkey,
            bls_pop=manager.bls_proof_of_possession(acct.address))
        backend.fast_forward(1)
        root = Hash32(keccak256(b"console-root"))
        period = backend.current_period()
        backend.add_header(acct.address, 3, period, root)
        # one signed vote so the audit command has an auditable shard
        from gethsharding_tpu.smc.state_machine import vote_digest

        backend.submit_vote(
            acct.address, 3, period, 0, root,
            bls_sig=manager.bls_sign(acct.address,
                                     bytes(vote_digest(3, period, root))))

        chain = RemoteMainchain.dial(*server.address)
        addr_hex = "0x" + bytes(acct.address).hex()
        script = "\n".join([
            "block", "period", "shards",
            f"balance {addr_hex}",
            f"registry {addr_hex}",
            "record 3",
            "record 99",
            "votes 3",
            "submitted 3",
            "audit 1",
            "commit",
            "fastforward 2",
            "bogus-command",
            "record not-a-number",
            "quit",
        ]) + "\n"
        out = io.StringIO()
        console = ShardingConsole(chain, stdin=io.StringIO(script),
                                  stdout=out)
        console.cmdloop()
        chain.close()
        text = out.getvalue()
        assert f"{backend.config.shard_count}" in text
        assert "pool_index=0" in text
        assert "chunk_root=0x" + bytes(root).hex() in text
        assert "no record" in text
        # the tally audit over the bulk auditData pull
        assert "period 1 shard 3: votes=1 signed=1 elected=False" in text
        assert "1 shards audited, consistent" in text
        assert "block 6" in text      # commit mined block 6 (period 1 + 1)
        assert "error:" in text       # bad args answered, session survived
        # the two dev commands really advanced the remote chain
        assert backend.current_period() == 3
    finally:
        server.stop()


def test_cli_attach_subcommand_end_to_end():
    """`tpu-sharding attach` as a real subprocess against a chain-server
    subprocess — the full operator flow across two OS processes."""
    chain_proc = subprocess.Popen(
        [sys.executable, "-m", "gethsharding_tpu.rpc.chain_server",
         "--port", "0", "--runtime", "30"],
        stdout=subprocess.PIPE, text=True)
    try:
        info = json.loads(chain_proc.stdout.readline())
        out = subprocess.run(
            [sys.executable, "-m", "gethsharding_tpu.node.cli", "attach",
             "--port", str(info["port"])],
            input="period\ncommit\nquit\n", text=True,
            capture_output=True, timeout=30)
        assert out.returncode == 0
        assert "block 1" in out.stdout
    finally:
        chain_proc.terminate()
        chain_proc.wait(timeout=10)


def test_key_tool_roundtrip(tmp_path):
    """ethkey analog: new -> list -> inspect over the CLI."""
    from gethsharding_tpu.node.cli import run_cli

    ks = str(tmp_path / "keystore")
    pw = tmp_path / "pw"
    pw.write_text("secret\n")
    assert run_cli(["key", "new", "--keystore", ks,
                    "--password", str(pw)]) == 0
    from gethsharding_tpu.mainchain.keystore import Keystore

    accounts = Keystore(ks).accounts()
    assert len(accounts) == 1
    assert run_cli(["key", "list", "--keystore", ks]) == 0
    assert run_cli(["key", "inspect", "--keystore", ks,
                    "--address", accounts[0].address.hex_str,
                    "--password", str(pw)]) == 0
    # wrong password -> clean failure
    bad = tmp_path / "bad"
    bad.write_text("wrong")
    assert run_cli(["key", "inspect", "--keystore", ks,
                    "--address", accounts[0].address.hex_str,
                    "--password", str(bad)]) == 1


def test_rlpdump_tool(capsys):
    from gethsharding_tpu.node.cli import run_cli
    from gethsharding_tpu.utils.rlp import rlp_encode

    blob = rlp_encode([b"cat", [b"dog", b""], b"\x01\x02"])
    assert run_cli(["rlpdump", blob.hex()]) == 0
    out = capsys.readouterr().out
    assert '"cat"' in out and '"dog"' in out and "0x0102" in out
    assert run_cli(["rlpdump", "zz-not-hex"]) == 1
    assert run_cli(["rlpdump", "c1"]) == 1  # truncated list payload


def test_dashboard_page_served_at_root():
    """The dashboard role (dashboard/dashboard.go): GET / returns the
    self-contained live page wired to the three JSON endpoints."""
    from gethsharding_tpu.node.http_status import StatusServer

    node = ShardNode(actor="observer", backend=SimulatedMainchain(),
                     txpool_interval=None, http_port=0)
    node.start()
    try:
        port = node.service(StatusServer).port
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/", timeout=5) as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"].startswith("text/html")
            page = resp.read().decode()
        for needle in ("/healthz", "/status", "/metrics", "<script>"):
            assert needle in page
    finally:
        node.stop()


def test_faucet_tool_drips_funds():
    """cmd/faucet analog: the CLI faucet funds an address on a running
    chain process over RPC."""
    from gethsharding_tpu.node.cli import run_cli
    from gethsharding_tpu.rpc.server import RPCServer

    backend = SimulatedMainchain()
    server = RPCServer(backend, port=0)
    server.start()
    try:
        addr = "0x" + "ab" * 20
        rc = run_cli(["faucet", "--port", str(server.address[1]),
                      "--address", addr, "--amount", "7"])
        assert rc == 0
        from gethsharding_tpu.params import ETHER
        from gethsharding_tpu.utils.hexbytes import Address20

        assert backend.balance_of(Address20(bytes.fromhex("ab" * 20))) \
            == 7 * ETHER
        assert run_cli(["faucet", "--port", str(server.address[1]),
                        "--address", "nonsense"]) == 1
    finally:
        server.stop()


def test_console_trace_and_python_mode():
    """The trace command prints a tx's event-level execution trace, and
    `py` drops into a scriptable Python REPL with the chain bound (the
    JS-REPL scripting role) — across two real OS processes."""
    chain_proc = subprocess.Popen(
        [sys.executable, "-m", "gethsharding_tpu.rpc.chain_server",
         "--port", "0", "--runtime", "60"],
        stdout=subprocess.PIPE, text=True)
    try:
        info = json.loads(chain_proc.stdout.readline())

        # produce a traceable tx through the remote surface
        from gethsharding_tpu.mainchain.accounts import AccountManager
        from gethsharding_tpu.params import ETHER
        from gethsharding_tpu.rpc.client import RemoteMainchain

        manager = AccountManager()
        acct = manager.new_account(seed=b"trace-console")
        remote = RemoteMainchain.dial("127.0.0.1", info["port"])
        remote.fund(acct.address, 2000 * ETHER)
        receipt = remote.register_notary(acct.address)
        tx_hex = "0x" + bytes(receipt.tx_hash).hex()
        trace = remote.trace_transaction(receipt.tx_hash)
        assert trace["status"] == 1
        assert trace["trace"][0]["event"] == "NotaryRegistered"
        assert trace["trace"][0]["args"]["notary"] == \
            "0x" + bytes(acct.address).hex()
        remote.close()

        script = "\n".join([
            f"trace {tx_hex}",
            "trace 0x" + "ee" * 32,
            "py",
            "print('PYMODE', chain.block_number, binding.shardCount())",
            "exit()",
            "period",  # proves exit() RETURNED to the sharding prompt
            "quit",
        ]) + "\n"
        out = subprocess.run(
            [sys.executable, "-m", "gethsharding_tpu.node.cli", "attach",
             "--port", str(info["port"])],
            input=script, text=True, capture_output=True, timeout=30)
        assert out.returncode == 0
        assert "NotaryRegistered" in out.stdout
        assert "unknown transaction" in out.stdout
        assert "PYMODE 0 100" in out.stdout
        # the console survived exit(): the period command ran after it
        # and printed its value (0) back at the sharding prompt
        assert "> 0\n" in out.stdout[out.stdout.index("PYMODE"):]
    finally:
        chain_proc.terminate()
        chain_proc.wait(timeout=10)


# == span tracing (gethsharding_tpu/tracing) ===============================


def _garbage_rows(i):
    """One cheap serving row (invalid sig recovers to None instantly)."""
    return [bytes([i]) * 32], [bytes([i]) * 65]


def _serving_backend(flush_us=2000.0):
    from gethsharding_tpu.serving import ServingConfig, ServingSigBackend
    from gethsharding_tpu.sigbackend import get_backend

    return ServingSigBackend(get_backend("python"),
                             ServingConfig(flush_us=flush_us))


def test_serving_request_spans_decompose_to_parent(tracer, tmp_path):
    """THE attribution contract: every coalesced request's parent span
    decomposes into queue_wait / batch_assembly / device_dispatch child
    spans summing (±5%) to the parent — in the tracer AND in the
    exported Chrome trace-event JSON."""
    serving = _serving_backend()
    clients = 4
    try:
        def client(c):
            with tracing.span("client/request", client=c):
                serving.ecrecover_addresses(*_garbage_rows(c))

        threads = [threading.Thread(target=client, args=(c,))
                   for c in range(clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        serving.close()

    spans = tracer.recent_spans()
    requests = [s for s in spans
                if s["name"] == "serving/ecrecover/request"]
    assert len(requests) == clients
    by_parent = {}
    for s in spans:
        by_parent.setdefault(s["parent"], []).append(s)
    phase_names = {"serving/ecrecover/queue_wait",
                   "serving/ecrecover/batch_assembly",
                   "serving/ecrecover/device_dispatch"}
    for req in requests:
        kids = [s for s in by_parent.get(req["span"], [])
                if s["name"] in phase_names]
        assert {k["name"] for k in kids} == phase_names
        parent_dur = req["end"] - req["start"]
        kids_dur = sum(k["end"] - k["start"] for k in kids)
        assert abs(kids_dur - parent_dur) <= 0.05 * parent_dur
        # the caller's span parents the request (trace propagation
        # through submit() across three threads)
        client_spans = [s for s in spans if s["name"] == "client/request"
                        and s["trace"] == req["trace"]]
        assert len(client_spans) == 1
        assert req["parent"] == client_spans[0]["span"]
        # the caller-side wake phase rides the same trace
        wakes = [s for s in by_parent.get(req["span"], [])
                 if s["name"] == "serving/ecrecover/future_wake"]
        assert len(wakes) == 1

    # the same contract must hold in the exported Chrome trace
    path = str(tmp_path / "trace.json")
    assert tracing.write_chrome_trace(path) == len(spans)
    payload = json.load(open(path))
    # the merge anchor rides every export (scripts/trace_merge.py)
    assert "clock_offset_us" in payload["otherData"]
    events = [e for e in payload["traceEvents"] if e["ph"] != "M"]
    assert all(e["ph"] == "X" for e in events)
    for req in (e for e in events
                if e["name"] == "serving/ecrecover/request"):
        kids = [e for e in events
                if e["args"]["parent_id"] == req["args"]["span_id"]
                and e["name"] in phase_names]
        assert len(kids) == 3
        assert abs(sum(k["dur"] for k in kids) - req["dur"]) \
            <= 0.05 * req["dur"]

    # span durations fed the metrics registry (timers the influx
    # exporter and dashboard pick up for free)
    from gethsharding_tpu.metrics import DEFAULT_REGISTRY

    timer = DEFAULT_REGISTRY.get("trace/serving/ecrecover/request")
    assert timer is not None and timer.count >= clients


def test_failed_dispatch_still_emits_error_tagged_spans(tracer):
    """Errored requests are the ones most worth attributing: a batch
    whose device call raises still emits its request span tree, tagged
    with the error, before the futures fail."""
    from gethsharding_tpu.serving import ServingConfig, ServingSigBackend

    class BoomBackend:
        name = "boom"

        def ecrecover_addresses(self, digests, sigs65):
            raise RuntimeError("device on fire")

    serving = ServingSigBackend(BoomBackend(), ServingConfig(flush_us=500))
    try:
        with pytest.raises(RuntimeError, match="device on fire"):
            serving.ecrecover_addresses(*_garbage_rows(1))
    finally:
        serving.close()
    requests = [s for s in tracer.recent_spans()
                if s["name"] == "serving/ecrecover/request"]
    assert len(requests) == 1
    assert "device on fire" in requests[0]["tags"]["error"]


def test_tracer_off_overhead_on_serving_hot_path():
    """Tracer-off overhead budget: the guards the serving hot path
    evaluates per request when tracing is disabled must cost <2% of a
    request's serving latency."""
    assert not tracing.TRACER.enabled
    serving = _serving_backend(flush_us=500.0)
    try:
        serving.ecrecover_addresses(*_garbage_rows(0))  # warm the threads
        n = 100
        t0 = time.perf_counter()
        for i in range(n):
            serving.ecrecover_addresses(*_garbage_rows(i % 251))
        per_request_s = (time.perf_counter() - t0) / n
    finally:
        serving.close()

    # the disabled-path work per request: request_context() at submit
    # plus TRACER.enabled reads on the flusher/dispatch/await sides —
    # charge 6 guard evaluations per request (3x the real count)
    m = 100_000
    t0 = perf = time.perf_counter()
    for _ in range(m):
        tracing.request_context()
    guard_s = (time.perf_counter() - perf) / m
    overhead = 6 * guard_s
    assert overhead < 0.02 * per_request_s, (
        f"tracer-off overhead {overhead * 1e6:.3f}us vs request "
        f"{per_request_s * 1e6:.1f}us")


def test_trace_endpoint_and_prometheus_exposition(tracer):
    """/trace serves recent traces; /metrics?format=prom serves the
    Prometheus text exposition; both on the node status server."""
    serving = _serving_backend()
    try:
        serving.ecrecover_addresses(*_garbage_rows(7))
    finally:
        serving.close()
    node = ShardNode(actor="observer", backend=SimulatedMainchain(),
                     txpool_interval=None, http_port=0)
    node.start()
    try:
        from gethsharding_tpu.node.http_status import StatusServer

        port = node.service(StatusServer).port
        code, payload = _get(port, "/trace")
        assert code == 200 and payload["enabled"] is True
        names = {span["name"] for trace in payload["traces"]
                 for span in trace["spans"]}
        assert "serving/ecrecover/request" in names
        assert "serving/ecrecover/device_dispatch" in names

        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics?format=prom",
                timeout=5) as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"].startswith("text/plain")
            text = resp.read().decode()
        assert "# TYPE" in text
        assert "gethsharding_serving_ecrecover_requests_total" in text
        # span-duration timers folded into the registry ride the scrape
        assert "gethsharding_trace_serving_ecrecover_request" in text

        # plain /metrics stays JSON
        code, snapshot = _get(port, "/metrics")
        assert code == 200 and isinstance(snapshot, dict)
    finally:
        node.stop()


def test_rpc_response_carries_trace_id(tracer):
    """The RPC server parents serving spans under a handler span and
    returns the trace id on the response envelope."""
    import socket

    from gethsharding_tpu.rpc.server import RPCServer

    server = RPCServer(SimulatedMainchain())
    server.start()
    try:
        sock = socket.create_connection(server.address, timeout=5)
        fh = sock.makefile("rw")
        digest, sig = _garbage_rows(9)
        fh.write(json.dumps({
            "jsonrpc": "2.0", "id": 1, "method": "shard_ecrecover",
            "params": [["0x" + digest[0].hex()], ["0x" + sig[0].hex()]],
        }) + "\n")
        fh.flush()
        response = json.loads(fh.readline())
        assert response["result"] == [None]
        assert isinstance(response["trace"], int)
        sock.close()
        # the handler span and the serving request share one trace
        spans = tracer.recent_spans()
        rpc_spans = [s for s in spans if s["name"] == "rpc/shard_ecrecover"]
        assert len(rpc_spans) == 1
        assert rpc_spans[0]["trace"] == response["trace"]
        request = [s for s in spans
                   if s["name"] == "serving/ecrecover/request"][0]
        assert request["trace"] == response["trace"]
        wake = [s for s in spans
                if s["name"] == "serving/ecrecover/future_wake"]
        assert wake, "RPC handler must record the future_wake phase"
    finally:
        server.stop()


def test_jax_compile_cache_shape_tracking(tracer):
    """Per-bucket-shape compile-cache hit/miss counters: the first
    dispatch of a shape is a miss (an XLA compile), repeats are hits —
    the recompile-storm signal."""
    from gethsharding_tpu.metrics import DEFAULT_REGISTRY
    from gethsharding_tpu.sigbackend import JaxSigBackend

    backend = JaxSigBackend.__new__(JaxSigBackend)  # tracking state only:
    # full __init__ imports + jits the kernels, which the slow tier owns
    backend._shape_seen = set()
    backend._shape_lock = threading.Lock()
    from gethsharding_tpu import metrics as m

    backend._m_shape_hit = m.counter("jax/compile_cache/hits")
    backend._m_shape_miss = m.counter("jax/compile_cache/misses")
    hits0 = backend._m_shape_hit.value
    misses0 = backend._m_shape_miss.value
    assert backend._note_shape("ecrecover", 16) is True     # fresh shape
    assert backend._note_shape("ecrecover", 16) is False    # compiled
    assert backend._note_shape("ecrecover", 32) is True     # new bucket
    assert backend._note_shape("bls_committee", 16, 144) is True
    assert backend._m_shape_miss.value - misses0 == 3
    assert backend._m_shape_hit.value - hits0 == 1
    assert DEFAULT_REGISTRY.get("jax/compile_cache/misses") is not None


def test_bench_trace_mode_emits_perfetto_profile(tmp_path):
    """ACCEPTANCE: `bench.py --trace` produces a Chrome trace-event
    JSON whose serving-request spans decompose into queue_wait /
    batch_assembly / device_dispatch children summing (±5%) to the
    parent span."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    trace_path = str(tmp_path / "bench_trace.json")
    env = {**os.environ,
           "JAX_PLATFORMS": "cpu",
           "GETHSHARDING_BENCH_SERVING_CLIENTS": "4",
           "GETHSHARDING_BENCH_SERVING_REQS": "2"}
    out = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py"), "--trace",
         "--trace-out", trace_path],
        capture_output=True, text=True, timeout=180, env=env, cwd=repo)
    assert out.returncode == 0, out.stderr
    line = json.loads(out.stdout.strip().splitlines()[-1])
    assert line["metric"] == "serving_trace_profile"
    assert line["extra"]["trace_out"] == trace_path
    assert line["extra"]["traced_requests"] == 8

    events = [e for e in json.load(open(trace_path))["traceEvents"]
              if e["ph"] != "M"]  # skip the process_name merge metadata
    assert line["extra"]["trace_events"] == len(events)
    requests = [e for e in events
                if e["name"] == "serving/ecrecover/request"]
    assert len(requests) == 8
    phases = {"serving/ecrecover/queue_wait",
              "serving/ecrecover/batch_assembly",
              "serving/ecrecover/device_dispatch"}
    for req in requests:
        kids = [e for e in events
                if e["args"]["parent_id"] == req["args"]["span_id"]
                and e["name"] in phases]
        assert {k["name"] for k in kids} == phases
        assert abs(sum(k["dur"] for k in kids) - req["dur"]) \
            <= 0.05 * req["dur"]
