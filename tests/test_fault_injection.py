"""Fault injection at the mainchain interface seams + log/error
assertions — the reference's faultyReader/faultyCaller pattern
(`sharding/syncer/service_test.go:66`, `simulator/service_test.go:115`)
with `LogHandler.VerifyLogMsg`-style assertions
(`sharding/internal/log_helper.go:12,41`) mapped onto the Service error
funnel and the logging records.

Since the resilience layer, the doubles ride the REUSABLE injection
surface (`gethsharding_tpu/resilience/chaos.py`) instead of ad-hoc
`SMCClient` subclasses: `faulty_client` fronts a client with a seeded
`ChaosSchedule` at the ``client.<op>`` seam, and the retry/breaker
tests inject at the ``mainchain.<op>`` / ``backend.<op>`` seams to
exercise retry-then-succeed, retry-exhausted, and breaker-open paths.
"""

import logging
import time

import pytest

from gethsharding_tpu import metrics
from gethsharding_tpu.actors import Notary, Proposer, Simulator, Syncer, TXPool
from gethsharding_tpu.core.shard import Shard
from gethsharding_tpu.core.types import Transaction
from gethsharding_tpu.db.kv import MemoryKV
from gethsharding_tpu.mainchain.client import SMCClient
from gethsharding_tpu.p2p.messages import (
    CollationBodyRequest,
    CollationBodyResponse,
)
from gethsharding_tpu.p2p.service import Hub, P2PServer
from gethsharding_tpu.params import Config, ETHER
from gethsharding_tpu.resilience.breaker import (
    OPEN, CircuitBreaker, FailoverSigBackend)
from gethsharding_tpu.resilience.chaos import ChaosSchedule, InjectedFault, wrap
from gethsharding_tpu.resilience.policy import RetryPolicy
from gethsharding_tpu.sigbackend import PythonSigBackend
from gethsharding_tpu.smc.chain import SimulatedMainchain
from gethsharding_tpu.utils.hexbytes import Address20, Hash32


def wait_until(predicate, timeout=5.0, step=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(step)
    return predicate()


def faulty_client(backend=None, fail=(), overrides=None, **kwargs):
    """The faultyReader/faultyCaller/faultySigner double, rebuilt on the
    chaos injection surface: every op named in `fail` raises
    `InjectedFault` on EVERY call (rule True); `overrides` swaps whole
    methods for degraded-backend doubles."""
    client = SMCClient(backend=backend, **kwargs)
    schedule = ChaosSchedule(
        rules={f"client.{op}": True for op in fail})
    return wrap(client, schedule, "client", overrides=overrides)


def shard_fixture():
    return Shard(shard_id=0, shard_db=MemoryKV())


def test_syncer_faulty_signer_records_and_logs(caplog):
    """A failing keystore Sign on the response path must surface as a
    recorded service error AND a log line (not a crash, not silence)."""
    backend = SimulatedMainchain()
    client = faulty_client(backend=backend, fail={"sign"})
    hub = Hub()
    p2p = P2PServer(hub=hub)
    p2p.start()
    requester = P2PServer(hub=hub)
    requester.start()
    syncer = Syncer(client=client, shard=shard_fixture(), p2p=p2p)
    with caplog.at_level(logging.ERROR):
        syncer.start()
        try:
            requester.broadcast(CollationBodyRequest(
                chunk_root=Hash32(b"\x01" * 32), shard_id=0, period=1,
                proposer=Address20(b"\x02" * 20)))
            assert wait_until(lambda: len(syncer.errors) >= 1), syncer.errors
        finally:
            syncer.stop()
            p2p.stop()
    assert any("could not construct response" in e for e in syncer.errors)
    assert any("could not construct response" in rec.message
               for rec in caplog.records)
    assert syncer.responses_sent == 0


def test_syncer_empty_response_body_records_error():
    """An empty synced body is rejected by the shard store (ShardError)
    and funnelled to the error channel — the faultyCollationFetcher-class
    failure on the response side."""
    backend = SimulatedMainchain()
    client = SMCClient(backend=backend)
    hub = Hub()
    p2p = P2PServer(hub=hub)
    p2p.start()
    requester = P2PServer(hub=hub)
    requester.start()
    syncer = Syncer(client=client, shard=shard_fixture(), p2p=p2p)
    syncer.start()
    try:
        requester.broadcast(CollationBodyResponse(
            header_hash=Hash32(b"\x03" * 32), body=b""))
        assert wait_until(lambda: len(syncer.errors) >= 1)
    finally:
        syncer.stop()
        p2p.stop()
    assert any("could not store synced body" in e for e in syncer.errors)
    assert syncer.bodies_stored == 0


def test_notary_faulty_committee_caller_records_head_error():
    """checkSMCForNotary failures funnel into the error channel, and the
    head loop keeps running (log-and-continue, HandleServiceErrors
    parity)."""
    config = Config(quorum_size=1)
    backend = SimulatedMainchain(config=config)
    # fail the batched sampling view AND the per-shard fallback
    client = faulty_client(backend=backend, config=config,
                           fail={"committee_context",
                                 "get_notary_in_committee"})
    backend.fund(client.account(), 2000 * ETHER)
    notary = Notary(client=client, shard=shard_fixture(), config=config,
                    deposit_flag=True)
    notary.start()
    try:
        backend.fast_forward(1)
        assert wait_until(lambda: len(notary.errors) >= 1)
        first_errors = len(notary.errors)
        backend.commit()  # the loop survives and keeps reporting
        assert wait_until(lambda: len(notary.errors) > first_errors)
    finally:
        notary.stop()
    assert any("notarize failed at head" in e for e in notary.errors)


def test_proposer_faulty_signer_records_error():
    config = Config(quorum_size=1)
    backend = SimulatedMainchain(config=config)
    client = faulty_client(backend=backend, config=config,
                           fail={"sign"})
    txpool = TXPool(simulate_interval=None)
    proposer = Proposer(client=client, txpool=txpool, shard=shard_fixture(),
                        config=config)
    txpool.start()
    proposer.start()
    try:
        backend.fast_forward(1)
        txpool.submit(Transaction(nonce=1, payload=b"x"))
        assert wait_until(lambda: len(proposer.errors) >= 1)
    finally:
        proposer.stop()
        txpool.stop()
    assert any("create collation failed" in e for e in proposer.errors)
    assert proposer.collations_proposed == 0


def test_simulator_faulty_record_fetcher_records_error():
    config = Config(quorum_size=1)
    backend = SimulatedMainchain(config=config)
    client = faulty_client(backend=backend, config=config,
                           fail={"collation_record"})
    backend.fast_forward(1)
    hub = Hub()
    p2p = P2PServer(hub=hub)
    p2p.start()
    simulator = Simulator(client=client, p2p=p2p, shard_id=0,
                          tick_interval=0.05)
    simulator.start()
    try:
        assert wait_until(lambda: len(simulator.errors) >= 1)
    finally:
        simulator.stop()
        p2p.stop()
    assert any("simulator tick failed" in e for e in simulator.errors)


def test_notary_falls_back_to_per_shard_view_without_context():
    """A backend without the batched sampling view degrades to the
    reference's per-shard calls, and votes still land."""
    config = Config(quorum_size=1)
    backend = SimulatedMainchain(config=config)
    client = faulty_client(backend=backend, config=config,
                           overrides={"committee_context": lambda: None})
    backend.fund(client.account(), 2000 * ETHER)
    notary = Notary(client=client, shard=shard_fixture(), config=config,
                    deposit_flag=True, all_shards=False)
    notary.start()
    try:
        backend.fast_forward(1)
        from gethsharding_tpu.actors.proposer import create_collation

        period = backend.current_period()
        collation = create_collation(client, 0, period, [Transaction(
            nonce=1, payload=b"fallback")])
        notary.shard.save_collation(collation)
        client.add_header(0, period, collation.header.chunk_root,
                          collation.header.proposer_signature)
        approved = False
        for _ in range(config.period_length - 1):
            backend.commit()  # heads drive the notary loop
            if wait_until(lambda: backend.last_approved_collation(0) == period,
                          timeout=2.0):
                approved = True
                break
        assert approved, notary.errors
    finally:
        notary.stop()


# -- the retry and breaker paths over the same injection surface -------------


def test_notary_retry_then_succeed_under_transient_chaos(caplog):
    """A transient mainchain fault UNDER the client's retry executor is
    absorbed: the head loop completes with zero recorded errors, the
    retry counter shows the weather happened."""
    retries = metrics.DEFAULT_REGISTRY.counter(
        "resilience/retry/mainchain/retries")
    retries_before = retries.value
    config = Config(quorum_size=1)
    backend = SimulatedMainchain(config=config)
    # the first 2 notary_registry reads fail, then heal — inject at the
    # mainchain seam so the retry executor actually sees the fault
    schedule = ChaosSchedule(seed=1, rules={"mainchain.notary_registry": 2})
    client = SMCClient(
        backend=wrap(backend, schedule, "mainchain"), config=config,
        retry_policy=RetryPolicy(attempts=4, base_s=0.001, jitter=0.0))
    backend.fund(client.account(), 2000 * ETHER)
    notary = Notary(client=client, shard=shard_fixture(), config=config,
                    deposit_flag=True, all_shards=False)
    with caplog.at_level(logging.ERROR):
        notary.start()
        try:
            backend.fast_forward(1)
        finally:
            notary.stop()
    assert schedule.injected.get("mainchain.notary_registry") == 2
    assert retries.value >= retries_before + 2
    assert not notary.errors, notary.errors  # the faults never surfaced
    assert not any("notarize failed" in rec.message
                   for rec in caplog.records)


def test_notary_retry_exhausted_surfaces_and_logs(caplog):
    """A PERSISTENT mainchain fault exhausts the retry ladder: the last
    InjectedFault surfaces through the head-loop error funnel with a
    log line, and the giveup counter ticks."""
    giveups = metrics.DEFAULT_REGISTRY.counter(
        "resilience/retry/mainchain/giveups")
    giveups_before = giveups.value
    config = Config(quorum_size=1)
    backend = SimulatedMainchain(config=config)
    schedule = ChaosSchedule(rules={"mainchain.notary_registry": True})
    client = SMCClient(
        backend=wrap(backend, schedule, "mainchain"), config=config,
        retry_policy=RetryPolicy(attempts=3, base_s=0.001, jitter=0.0))
    backend.fund(client.account(), 2000 * ETHER)
    notary = Notary(client=client, shard=shard_fixture(), config=config,
                    deposit_flag=False)
    with caplog.at_level(logging.ERROR):
        notary.start()
        try:
            backend.fast_forward(1)
            assert wait_until(lambda: len(notary.errors) >= 1)
        finally:
            notary.stop()
    assert giveups.value > giveups_before
    assert any("notarize failed at head" in e and "injected fault" in e
               for e in notary.errors)
    assert any("injected fault" in rec.message for rec in caplog.records)
    # each schedule-hit call was tried `attempts` times before giving up
    assert schedule.injected["mainchain.notary_registry"] >= 3


def test_breaker_open_path_under_chaos_backend_logs_and_serves(caplog):
    """Persistent backend-seam faults trip the failover breaker open
    (logged), and calls keep answering from the scalar fallback."""
    from gethsharding_tpu.resilience.chaos import ChaosSigBackend

    registry = metrics.Registry()
    schedule = ChaosSchedule(rules={"backend.ecrecover_addresses": True})
    breaker = CircuitBreaker(name="fi", fault_threshold=2, reset_s=60,
                             registry=registry)
    backend = FailoverSigBackend(
        ChaosSigBackend(PythonSigBackend(), schedule),
        PythonSigBackend(), breaker=breaker, registry=registry)
    rows = ([b"\x11" * 32] * 2, [b"\x22" * 65] * 2)
    want = PythonSigBackend().ecrecover_addresses(*rows)
    with caplog.at_level(logging.WARNING, logger="resilience.breaker"):
        for _ in range(4):
            assert backend.ecrecover_addresses(*rows) == want
    assert breaker.state == OPEN
    assert registry.counter("resilience/breaker/fi/trips").value == 1
    assert any("breaker fi open" in rec.message for rec in caplog.records)
    # open = the primary (and its chaos) is no longer consulted
    calls_at_trip = schedule.calls("backend.ecrecover_addresses")
    backend.ecrecover_addresses(*rows)
    assert schedule.calls("backend.ecrecover_addresses") == calls_at_trip


def test_injected_fault_is_retryable_by_contract():
    """The chaos layer's faults must stay inside the retry policies'
    transient set — the whole surface composes through this."""
    assert issubclass(InjectedFault, ConnectionError)
    policy = RetryPolicy()
    assert any(issubclass(InjectedFault, cls) for cls in policy.retryable)
