"""Fault-injection doubles at the mainchain interface seams + log/error
assertions — the reference's faultyReader/faultyCaller pattern
(`sharding/syncer/service_test.go:66`, `simulator/service_test.go:115`)
with `LogHandler.VerifyLogMsg`-style assertions
(`sharding/internal/log_helper.go:12,41`) mapped onto the Service error
funnel and the logging records."""

import logging
import time

import pytest

from gethsharding_tpu.actors import Notary, Proposer, Simulator, Syncer, TXPool
from gethsharding_tpu.core.shard import Shard
from gethsharding_tpu.core.types import Transaction
from gethsharding_tpu.db.kv import MemoryKV
from gethsharding_tpu.mainchain.client import SMCClient
from gethsharding_tpu.p2p.messages import (
    CollationBodyRequest,
    CollationBodyResponse,
)
from gethsharding_tpu.p2p.service import Hub, P2PServer
from gethsharding_tpu.params import Config, ETHER
from gethsharding_tpu.smc.chain import SimulatedMainchain
from gethsharding_tpu.utils.hexbytes import Address20, Hash32


def wait_until(predicate, timeout=5.0, step=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(step)
    return predicate()


class FaultyClient(SMCClient):
    """Role-interface double that fails selected operations — the
    faultyReader/faultyCaller/faultySigner seams."""

    def __init__(self, *args, fail=(), **kwargs):
        super().__init__(*args, **kwargs)
        self.fail = set(fail)

    def _maybe(self, op):
        if op in self.fail:
            raise RuntimeError(f"injected {op} fault")

    def sign(self, digest):
        self._maybe("sign")
        return super().sign(digest)

    def collation_record(self, shard_id, period):
        self._maybe("collation_record")
        return super().collation_record(shard_id, period)

    def block_by_number(self, number=None):
        self._maybe("block_by_number")
        return super().block_by_number(number)

    def get_notary_in_committee(self, shard_id, sender=None):
        self._maybe("get_notary_in_committee")
        return super().get_notary_in_committee(shard_id, sender)

    def committee_context(self):
        self._maybe("committee_context")
        if "no_committee_context" in self.fail:
            return None  # backend without the batched view
        return super().committee_context()


def shard_fixture():
    return Shard(shard_id=0, shard_db=MemoryKV())


def test_syncer_faulty_signer_records_and_logs(caplog):
    """A failing keystore Sign on the response path must surface as a
    recorded service error AND a log line (not a crash, not silence)."""
    backend = SimulatedMainchain()
    client = FaultyClient(backend=backend, fail={"sign"})
    hub = Hub()
    p2p = P2PServer(hub=hub)
    p2p.start()
    requester = P2PServer(hub=hub)
    requester.start()
    syncer = Syncer(client=client, shard=shard_fixture(), p2p=p2p)
    with caplog.at_level(logging.ERROR):
        syncer.start()
        try:
            requester.broadcast(CollationBodyRequest(
                chunk_root=Hash32(b"\x01" * 32), shard_id=0, period=1,
                proposer=Address20(b"\x02" * 20)))
            assert wait_until(lambda: len(syncer.errors) >= 1), syncer.errors
        finally:
            syncer.stop()
            p2p.stop()
    assert any("could not construct response" in e for e in syncer.errors)
    assert any("could not construct response" in rec.message
               for rec in caplog.records)
    assert syncer.responses_sent == 0


def test_syncer_empty_response_body_records_error():
    """An empty synced body is rejected by the shard store (ShardError)
    and funnelled to the error channel — the faultyCollationFetcher-class
    failure on the response side."""
    backend = SimulatedMainchain()
    client = SMCClient(backend=backend)
    hub = Hub()
    p2p = P2PServer(hub=hub)
    p2p.start()
    requester = P2PServer(hub=hub)
    requester.start()
    syncer = Syncer(client=client, shard=shard_fixture(), p2p=p2p)
    syncer.start()
    try:
        requester.broadcast(CollationBodyResponse(
            header_hash=Hash32(b"\x03" * 32), body=b""))
        assert wait_until(lambda: len(syncer.errors) >= 1)
    finally:
        syncer.stop()
        p2p.stop()
    assert any("could not store synced body" in e for e in syncer.errors)
    assert syncer.bodies_stored == 0


def test_notary_faulty_committee_caller_records_head_error():
    """checkSMCForNotary failures funnel into the error channel, and the
    head loop keeps running (log-and-continue, HandleServiceErrors
    parity)."""
    config = Config(quorum_size=1)
    backend = SimulatedMainchain(config=config)
    # fail the batched sampling view AND the per-shard fallback
    client = FaultyClient(backend=backend, config=config,
                          fail={"committee_context",
                                "get_notary_in_committee"})
    backend.fund(client.account(), 2000 * ETHER)
    notary = Notary(client=client, shard=shard_fixture(), config=config,
                    deposit_flag=True)
    notary.start()
    try:
        backend.fast_forward(1)
        assert wait_until(lambda: len(notary.errors) >= 1)
        first_errors = len(notary.errors)
        backend.commit()  # the loop survives and keeps reporting
        assert wait_until(lambda: len(notary.errors) > first_errors)
    finally:
        notary.stop()
    assert any("notarize failed at head" in e for e in notary.errors)


def test_proposer_faulty_signer_records_error():
    config = Config(quorum_size=1)
    backend = SimulatedMainchain(config=config)
    client = FaultyClient(backend=backend, config=config,
                          fail={"sign"})
    txpool = TXPool(simulate_interval=None)
    proposer = Proposer(client=client, txpool=txpool, shard=shard_fixture(),
                        config=config)
    txpool.start()
    proposer.start()
    try:
        backend.fast_forward(1)
        txpool.submit(Transaction(nonce=1, payload=b"x"))
        assert wait_until(lambda: len(proposer.errors) >= 1)
    finally:
        proposer.stop()
        txpool.stop()
    assert any("create collation failed" in e for e in proposer.errors)
    assert proposer.collations_proposed == 0


def test_simulator_faulty_record_fetcher_records_error():
    config = Config(quorum_size=1)
    backend = SimulatedMainchain(config=config)
    client = FaultyClient(backend=backend, config=config,
                          fail={"collation_record"})
    backend.fast_forward(1)
    hub = Hub()
    p2p = P2PServer(hub=hub)
    p2p.start()
    simulator = Simulator(client=client, p2p=p2p, shard_id=0,
                          tick_interval=0.05)
    simulator.start()
    try:
        assert wait_until(lambda: len(simulator.errors) >= 1)
    finally:
        simulator.stop()
        p2p.stop()
    assert any("simulator tick failed" in e for e in simulator.errors)


def test_notary_falls_back_to_per_shard_view_without_context():
    """A backend without the batched sampling view degrades to the
    reference's per-shard calls, and votes still land."""
    config = Config(quorum_size=1)
    backend = SimulatedMainchain(config=config)
    client = FaultyClient(backend=backend, config=config,
                          fail={"no_committee_context"})
    backend.fund(client.account(), 2000 * ETHER)
    notary = Notary(client=client, shard=shard_fixture(), config=config,
                    deposit_flag=True, all_shards=False)
    notary.start()
    try:
        backend.fast_forward(1)
        from gethsharding_tpu.actors.proposer import create_collation

        period = backend.current_period()
        collation = create_collation(client, 0, period, [Transaction(
            nonce=1, payload=b"fallback")])
        notary.shard.save_collation(collation)
        client.add_header(0, period, collation.header.chunk_root,
                          collation.header.proposer_signature)
        approved = False
        for _ in range(config.period_length - 1):
            backend.commit()  # heads drive the notary loop
            if wait_until(lambda: backend.last_approved_collation(0) == period,
                          timeout=2.0):
                approved = True
                break
        assert approved, notary.errors
    finally:
        notary.stop()
