"""fleettrace: cross-process trace assembly, tail-based sampling, and
critical-path attribution (gethsharding_tpu/fleettrace/).

Contracts:

- EXPORT PLANE: the tracer's bounded export buffer stages every
  finished span, evicts oldest-first under pressure with an HONEST
  cumulative drop count, and the span codec roundtrips records
  positionally (exotic tag values coerced, never poisoning a batch).
- ASSEMBLY: the collector rebases each batch onto its own wall clock
  via the ``clock_offset_us`` + handshake ``skew_us`` anchors, groups
  by trace id across producer pids, applies pending marks, flags
  traces fed by lossy sources incomplete, and evicts oldest over the
  cap.
- TAIL SAMPLING: retention reasons are deterministic — marked traces
  always kept, the hash sample makes the same per-trace decision on
  every collector, the top latency quantile is kept once history
  accumulates, everything else is attributed THEN dropped.
- CRITICAL PATH: self-times over a span tree telescope to the root's
  duration; hedge-wasted duplicate work is reported beside the table,
  outside the identity.
- WIRE: the RPC response envelope carries the handler's exact span id
  (``traceCtx``), so a caller's client span links to the remote
  handler span unambiguously.
- BOOT: `boot_collector` assembles this process's own spans end to
  end (in-proc exporter -> collector -> attribution/exemplars) and
  `shutdown` unwinds every hook.
"""

import pytest

from gethsharding_tpu import metrics, tracing
from gethsharding_tpu.fleettrace.collector import TraceCollector
from gethsharding_tpu.fleettrace.critical_path import (
    HEDGE_WASTED,
    SEGMENTS,
    attribute,
    segment_for,
)
from gethsharding_tpu.rpc import codec


def _registry() -> metrics.Registry:
    return metrics.Registry()


def _tracer(ring: int = 64) -> tracing.Tracer:
    tracer = tracing.Tracer(ring_spans=ring, registry=_registry())
    tracer.enabled = True
    return tracer


def _row(name: str, trace: int, span: int, parent, start: float,
         end: float, tags=None) -> list:
    """One wire-format span row (what `codec.enc_spans` emits)."""
    return [name, trace, span, parent, start, end, 1, tags]


def _payload(rows, pid=100, label="r0", offset_us=0.0, skew_us=0.0,
             dropped=0) -> dict:
    return {"pid": pid, "label": label, "clock_offset_us": offset_us,
            "skew_us": skew_us, "dropped": dropped, "spans": rows}


# == the export plane =======================================================


def test_export_buffer_drains_and_counts_evictions():
    """The staging buffer is bounded: under exporter lag the OLDEST
    staged spans are evicted and counted cumulatively, the drop count
    rides every drain, and the ring-pressure gauge tracks fill."""
    tracer = _tracer(ring=256)
    tracer.enable_export(buffer_spans=4)
    for i in range(10):
        tracer.record(f"s{i}", 0.0, 0.001, trace_id=1)
    batch, dropped = tracer.drain_export(max_spans=512)
    assert [r["name"] for r in batch] == ["s6", "s7", "s8", "s9"]
    assert dropped == 6 and tracer.export_dropped == 6
    assert tracer.registry.counter("trace/export_dropped").value == 6
    # pressure gauge: 10 spans in a 256 ring
    assert tracer.registry.gauge(
        "trace/ring_pressure").value == pytest.approx(10 / 256)
    # cumulative: a later eviction round adds, never resets
    for i in range(5):
        tracer.record(f"t{i}", 0.0, 0.001, trace_id=1)
    batch, dropped = tracer.drain_export(max_spans=2)
    assert len(batch) == 2 and dropped == 7
    # disable tears the buffer down; drains report the final count
    tracer.disable_export()
    assert tracer.drain_export() == ([], 7)


def test_ring_eviction_is_counted():
    """Ring overflow (a finished span nobody exported is overwritten)
    is an alert, not silence: ``trace/dropped`` counts it."""
    tracer = _tracer(ring=8)
    for i in range(12):
        tracer.record(f"s{i}", 0.0, 0.001)
    assert tracer.spans_dropped == 4
    assert tracer.registry.counter("trace/dropped").value == 4
    assert tracer.registry.gauge("trace/ring_pressure").value == 1.0


def test_span_codec_roundtrips_and_coerces_exotic_tags():
    tracer = _tracer()
    tracer.enable_export()
    tracer.record("rpc/shard_x", 1.5, 2.25, trace_id=7, parent_id=3,
                  tags={"klass": "interactive", "rows": 4,
                        "exotic": b"\x00bytes"})
    tracer.record("fleet/route", 0.0, 1.0)
    batch, _ = tracer.drain_export()
    rows = codec.enc_spans(batch)
    back = codec.dec_spans(rows)
    assert back[0]["name"] == "rpc/shard_x"
    assert back[0]["trace"] == 7 and back[0]["parent"] == 3
    assert back[0]["start"] == 1.5 and back[0]["end"] == 2.25
    assert back[0]["tags"]["klass"] == "interactive"
    assert back[0]["tags"]["rows"] == 4
    # non-JSON tag values ship as repr, not a serialization error
    assert back[0]["tags"]["exotic"] == repr(b"\x00bytes")
    assert back[1]["parent"] is None and back[1]["tags"] == {}
    assert codec.enc_span_tags(None) is None


# == assembly + rebasing ====================================================


def test_collector_rebases_and_assembles_across_processes():
    """Two producers with different clock anchors feed ONE trace: the
    collector lands both on its wall clock (offset + handshake skew),
    the tree attributes across both pids, and a marked trace is
    retained with its mark."""
    collector = TraceCollector(_registry(), max_traces=64, linger_s=0.0,
                               sample=0.0)
    collector.mark_trace(11, "hedged")  # mark BEFORE the spans arrive
    # frontend (pid 100): anchor 1 s — client span [10.0, 10.1]
    collector.ingest_payload(_payload(
        [_row("rpc/client/shard_x", 11, 1, None, 10.0, 10.1,
              {"klass": "interactive"})],
        pid=100, label="fe", offset_us=1e6))
    # replica (pid 200): anchor 2 s + 0 skew — handler [9.05, 9.09]
    collector.ingest_payload(_payload(
        [_row("rpc/shard_x", 11, 2, 1, 9.05, 9.09)],
        pid=200, label="replica", offset_us=2e6))
    assert collector.sweep(force=True) == 1
    (exemplar,) = collector.exemplars()
    assert exemplar["trace_id"] == 11
    assert exemplar["reasons"] == ["hedged"]
    assert not exemplar["incomplete"]
    spans = exemplar["spans"]  # sorted by rebased start
    assert [s["name"] for s in spans] == ["rpc/client/shard_x",
                                          "rpc/shard_x"]
    assert spans[0]["start"] == pytest.approx(11.0)
    assert spans[1]["start"] == pytest.approx(11.05)  # nests inside
    assert {s["pid"] for s in spans} == {100, 200}
    attr = exemplar["attribution"]
    assert attr["processes"] == 2 and attr["klass"] == "interactive"
    # handler covers 40 of the client's 100 ms: wire self-time is 60
    assert attr["segments"]["wire"] == pytest.approx(0.06, abs=1e-6)
    assert attr["segments"]["rpc_handler"] == pytest.approx(0.04,
                                                            abs=1e-6)


def test_collector_skew_folds_into_the_rebase():
    collector = TraceCollector(_registry(), linger_s=0.0, sample=1.0)
    collector.ingest_payload(_payload(
        [_row("rpc/shard_x", 5, 1, None, 1.0, 2.0)],
        offset_us=1e6, skew_us=-5e5))
    collector.sweep(force=True)
    (exemplar,) = collector.exemplars()
    assert exemplar["spans"][0]["start"] == pytest.approx(1.5)


def test_lossy_source_marks_its_traces_incomplete():
    """A batch whose cumulative ``dropped`` grew means the source lost
    spans since last time: traces it feeds from then on are surfaced
    incomplete, not presented as whole trees."""
    registry = _registry()
    collector = TraceCollector(registry, linger_s=0.0, sample=1.0)
    collector.ingest_payload(_payload(
        [_row("a", 1, 1, None, 0.0, 1.0)], dropped=0))
    collector.sweep(force=True)
    collector.ingest_payload(_payload(
        [_row("a", 2, 2, None, 0.0, 1.0)], dropped=3))
    collector.sweep(force=True)
    second, first = collector.exemplars()  # newest first
    assert not first["incomplete"]
    assert second["incomplete"]
    assert registry.counter("fleettrace/ingest/lossy_batches").value == 1
    assert registry.counter("fleettrace/traces/incomplete").value == 1
    # same cumulative count again = no NEW loss
    collector.ingest_payload(_payload(
        [_row("a", 3, 3, None, 0.0, 1.0)], dropped=3))
    collector.sweep(force=True)
    assert collector.exemplars(1)[0]["incomplete"] is False


def test_live_traces_evict_oldest_over_the_cap():
    registry = _registry()
    collector = TraceCollector(registry, max_traces=4, linger_s=3600.0,
                               sample=1.0)
    for tid in range(1, 7):
        collector.ingest_payload(_payload(
            [_row("a", tid, tid * 10, None, 0.0, 1.0)]))
    assert registry.gauge("fleettrace/traces/live").value == 4
    assert registry.counter("fleettrace/traces/evicted").value == 2
    collector.sweep(force=True)
    kept = {e["trace_id"] for e in collector.exemplars(limit=16)}
    assert kept == {3, 4, 5, 6}  # 1 and 2 were the oldest


# == tail-based retention ===================================================


def test_unmarked_traces_are_attributed_then_sampled_out():
    """sample=0: an unmarked trace contributes to the per-class tables
    (attribution is unbiased) but keeps no spans."""
    registry = _registry()
    collector = TraceCollector(registry, linger_s=0.0, sample=0.0)
    collector.ingest_payload(_payload(
        [_row("rpc/shard_x", 9, 1, None, 0.0, 0.5,
              {"klass": "bulk_audit"})]))
    collector.sweep(force=True)
    assert collector.exemplars() == []
    assert registry.counter("fleettrace/traces/sampled_out").value == 1
    tables = collector.attribution()
    assert tables["traces"]["assembled"] == 1
    row = tables["classes"]["bulk_audit"]["total"]
    assert row["count"] == 1 and row["mean_ms"] == pytest.approx(500.0)
    assert tables["segments"][-2:] == [HEDGE_WASTED, "total"]


def test_hash_sample_is_deterministic_per_trace_id():
    """sample=1.0 keeps everything; the hash decision is a pure
    function of the trace id — two collectors agree."""
    decisions = []
    for _ in range(2):
        collector = TraceCollector(_registry(), linger_s=0.0, sample=0.5)
        for tid in range(1, 33):
            # strictly decreasing durations: nothing ever ranks into
            # the top quantile, so retention is the hash sample alone
            collector.ingest_payload(_payload(
                [_row("a", tid, tid, None, 0.0, (33 - tid) * 1e-3)]))
        collector.sweep(force=True)
        decisions.append(sorted(e["trace_id"]
                                for e in collector.exemplars(limit=64)))
    assert decisions[0] == decisions[1]
    assert 0 < len(decisions[0]) < 32  # a sample, not all-or-nothing
    for exemplar in collector.exemplars(limit=64):
        assert exemplar["reasons"] == ["sampled"]


def test_top_quantile_traces_are_retained_once_history_accumulates():
    collector = TraceCollector(_registry(), linger_s=0.0, sample=0.0,
                               quantile=0.99)
    for tid in range(1, 17):  # build ranking history: 1..16 ms
        collector.ingest_payload(_payload(
            [_row("a", tid, tid, None, 0.0, tid * 1e-3)]))
        collector.sweep(force=True)
    assert collector.exemplars() == []  # not enough history yet
    collector.ingest_payload(_payload(
        [_row("a", 99, 990, None, 0.0, 0.1)]))  # 100 ms outlier
    collector.sweep(force=True)
    (exemplar,) = collector.exemplars()
    assert exemplar["trace_id"] == 99
    assert exemplar["reasons"] == ["tail_quantile"]


def test_breach_hook_retains_the_breached_class():
    """An SLO breach onset keeps every LIVE trace of the breached
    class and opens a window that catches the ones still in flight."""
    collector = TraceCollector(_registry(), linger_s=3600.0, sample=0.0,
                               breach_window_s=60.0)
    collector.ingest_payload(_payload(
        [_row("a", 1, 1, None, 0.0, 1.0, {"klass": "interactive"})]))
    collector.ingest_payload(_payload(
        [_row("a", 2, 2, None, 0.0, 1.0, {"klass": "bulk_audit"})]))
    collector.on_breach("interactive", 20.0, 8.0)
    collector.sweep(force=True)
    kept = {e["trace_id"]: e for e in collector.exemplars(limit=16)}
    assert set(kept) == {1}
    assert kept[1]["reasons"] == ["slo_breach", "slo_breach_window"]
    # the window keeps catching interactive traces finalized later
    collector.ingest_payload(_payload(
        [_row("a", 3, 3, None, 0.0, 1.0, {"klass": "interactive"})]))
    collector.sweep(force=True)
    assert collector.exemplars(1)[0]["reasons"] == ["slo_breach_window"]


def test_recorder_event_opens_a_global_retention_window():
    collector = TraceCollector(_registry(), linger_s=0.0, sample=0.0,
                               breach_window_s=60.0)
    collector.on_recorder_event("heartbeat")  # not a fatal kind
    collector.ingest_payload(_payload(
        [_row("a", 1, 1, None, 0.0, 1.0)]))
    collector.sweep(force=True)
    assert collector.exemplars() == []
    collector.on_recorder_event("breaker_trip")
    collector.ingest_payload(_payload(
        [_row("a", 2, 2, None, 0.0, 1.0)]))
    collector.sweep(force=True)
    assert collector.exemplars(1)[0]["reasons"] == ["event_window"]


# == critical-path attribution ==============================================


def test_segment_vocabulary_covers_the_instrumented_span_names():
    assert segment_for("serving/ecrecover/queue_wait") == "queue_wait"
    assert segment_for("serving/ecrecover/batch_assembly") == \
        "batch_assembly"
    assert segment_for("serving/ecrecover/device_dispatch") == \
        "device_dispatch"
    assert segment_for("serving/ecrecover/future_wake") == "future_wake"
    assert segment_for("rpc/client/shard_ecrecover") == "wire"
    assert segment_for("rpc/shard_ecrecover") == "rpc_handler"
    assert segment_for("fleet/route") == "frontend_route"
    assert segment_for("fleet/attempt") == "frontend_route"
    assert segment_for("fleet/hedge_wasted") == HEDGE_WASTED
    assert segment_for("notary/audit") == "actor_queue"
    assert segment_for("bench/fleettrace_request") == "other"
    assert all(segment_for(f"x/{s}") in SEGMENTS for s in ("y",))


def test_self_times_telescope_to_the_root_duration():
    """The sum identity on a synthetic 3-process fleet tree: every
    segment's self-time, summed, equals the root span's duration —
    with the hedge-wasted duplicate reported OUTSIDE the identity."""
    spans = [
        # bench client span: the whole request, 100 ms
        {"name": "rpc/client/shard_x", "trace": 1, "span": 1,
         "parent": None, "start": 0.0, "end": 0.100, "tags": {},
         "pid": 1},
        # frontend handler covers 90 of it
        {"name": "rpc/shard_x", "trace": 1, "span": 2, "parent": 1,
         "start": 0.005, "end": 0.095, "tags": {}, "pid": 2},
        {"name": "fleet/route", "trace": 1, "span": 3, "parent": 2,
         "start": 0.010, "end": 0.090,
         "tags": {"klass": "interactive"}, "pid": 2},
        {"name": "fleet/attempt", "trace": 1, "span": 4, "parent": 3,
         "start": 0.012, "end": 0.088, "tags": {}, "pid": 2},
        # frontend -> replica wire
        {"name": "rpc/client/shard_x", "trace": 1, "span": 5,
         "parent": 4, "start": 0.014, "end": 0.086, "tags": {},
         "pid": 2},
        # replica handler + serving pipeline
        {"name": "rpc/shard_x", "trace": 1, "span": 6, "parent": 5,
         "start": 0.020, "end": 0.080, "tags": {}, "pid": 3},
        {"name": "serving/ecrecover/request", "trace": 1, "span": 7,
         "parent": 6, "start": 0.022, "end": 0.078, "tags": {},
         "pid": 3},
        {"name": "serving/ecrecover/queue_wait", "trace": 1, "span": 8,
         "parent": 7, "start": 0.022, "end": 0.030, "tags": {},
         "pid": 3},
        {"name": "serving/ecrecover/batch_assembly", "trace": 1,
         "span": 9, "parent": 7, "start": 0.030, "end": 0.040,
         "tags": {}, "pid": 3},
        {"name": "serving/ecrecover/device_dispatch", "trace": 1,
         "span": 10, "parent": 7, "start": 0.040, "end": 0.070,
         "tags": {}, "pid": 3},
        # concurrent duplicate the hedge threw away: NOT wall time
        {"name": "fleet/hedge_wasted", "trace": 1, "span": 11,
         "parent": 3, "start": 0.012, "end": 0.085,
         "tags": {"replica": "r0", "winner": "r1"}, "pid": 2},
    ]
    attr = attribute(spans)
    assert attr["root"] == "rpc/client/shard_x"
    assert attr["klass"] == "interactive"
    assert attr["processes"] == 3
    assert attr["spans"] == 11 and attr["orphan_spans"] == 0
    assert attr["total_s"] == pytest.approx(0.100)
    assert sum(attr["segments"].values()) == pytest.approx(0.100)
    assert attr["hedge_wasted_s"] == pytest.approx(0.073)
    segments = attr["segments"]
    assert segments["wire"] == pytest.approx(0.010 + 0.012)
    assert segments["queue_wait"] == pytest.approx(0.008)
    assert segments["batch_assembly"] == pytest.approx(0.010)
    assert segments["device_dispatch"] == pytest.approx(0.030)
    assert segments["frontend_route"] == pytest.approx(0.008)


def test_orphan_subtrees_are_surfaced_not_grafted():
    """A span whose parent never arrived (lossy source) must not be
    silently attached to the widest root — it is counted orphaned."""
    spans = [
        {"name": "rpc/client/shard_x", "trace": 1, "span": 1,
         "parent": None, "start": 0.0, "end": 0.1, "tags": {}},
        {"name": "serving/x/device_dispatch", "trace": 1, "span": 9,
         "parent": 777, "start": 0.02, "end": 0.04, "tags": {}},
    ]
    attr = attribute(spans)
    assert attr["root"] == "rpc/client/shard_x"
    assert attr["orphan_spans"] == 1
    assert attr["segments"]["device_dispatch"] == 0.0
    assert attribute([]) is None


def test_skewed_child_cannot_drive_negative_self_time():
    spans = [
        {"name": "rpc/client/x", "trace": 1, "span": 1, "parent": None,
         "start": 0.0, "end": 0.010, "tags": {}},
        # cross-clock skew: the child overhangs its parent both ways
        {"name": "rpc/x", "trace": 1, "span": 2, "parent": 1,
         "start": -0.005, "end": 0.020, "tags": {}},
    ]
    attr = attribute(spans)
    assert attr["segments"]["wire"] == 0.0  # clipped, not negative
    assert all(v >= 0.0 for v in attr["segments"].values())


# == the wire envelope ======================================================


def test_rpc_response_envelope_links_client_span_to_handler_span():
    """`traceCtx` on the response names the handler's exact span: the
    caller's client span joins one trace with the remote handler and
    tags the remote span id (unambiguous under retries/hedges)."""
    from gethsharding_tpu.rpc.client import RPCClient
    from gethsharding_tpu.rpc.server import RPCServer
    from gethsharding_tpu.smc.chain import SimulatedMainchain

    tracing.enable(ring_spans=4096)
    tracing.TRACER.clear()
    server = RPCServer(SimulatedMainchain())
    server.start()
    client = RPCClient(*server.address)
    try:
        client.call("shard_blockNumber")
        spans = tracing.TRACER.recent_spans()
        handler = next(s for s in spans
                       if s["name"] == "rpc/shard_blockNumber")
        client_span = next(s for s in spans
                           if s["name"] == "rpc/client/shard_blockNumber")
        # the server adopted the caller's trace and parented under it
        assert handler["trace"] == client_span["trace"]
        assert handler["parent"] == client_span["span"]
        # ... and the response envelope told the caller which span
        assert client_span["tags"]["remote_trace"] == handler["trace"]
        assert client_span["tags"]["remote_span"] == handler["span"]
    finally:
        client.close()
        server.stop()
        tracing.TRACER.clear()
        tracing.disable()


def test_trace_export_rpc_requires_a_collector():
    from gethsharding_tpu.rpc.client import RPCClient
    from gethsharding_tpu.rpc.server import RPCServer
    from gethsharding_tpu.smc.chain import SimulatedMainchain

    server = RPCServer(SimulatedMainchain())
    server.start()
    client = RPCClient(*server.address)
    try:
        ack = client.call("shard_traceExport",
                          _payload([_row("a", 1, 1, None, 0.0, 1.0)]))
        assert ack == {"accepted": False, "spans": 0}
        assert client.call("shard_traceAttribution") is None
        assert client.call("shard_traceExemplars", 4) == []
        handshake = client.call("shard_traceHandshake")
        assert handshake["pid"] > 0 and handshake["wall_us"] > 0
    finally:
        client.close()
        server.stop()


# == boot shapes ============================================================


def test_boot_collector_assembles_own_spans_end_to_end(monkeypatch):
    """Single-process shape: boot_collector's in-proc exporter feeds
    the collector from this process's tracer; a finished span tree
    shows up in attribution + exemplars + status; shutdown unwinds."""
    from gethsharding_tpu import fleettrace

    monkeypatch.setenv("GETHSHARDING_FLEETTRACE_SAMPLE", "1.0")
    registry = _registry()
    collector = fleettrace.boot_collector(registry, start_sweep=False)
    try:
        assert fleettrace.active() is collector
        assert fleettrace.boot_collector(registry) is collector  # idem
        with tracing.span("rpc/shard_demo", klass="interactive"):
            with tracing.span("serving/demo/device_dispatch"):
                pass
        fleettrace.EXPORTER.flush()
        collector.sweep(force=True)
        status = fleettrace.fleettrace_status()
        assert status["active"] and status["assembled"] >= 1
        assert status["export"]["spans"] >= 2
        tables = collector.attribution()
        assert "interactive" in tables["classes"]
        exemplar = collector.exemplars(1)[0]
        assert {s["name"] for s in exemplar["spans"]} == {
            "rpc/shard_demo", "serving/demo/device_dispatch"}
        assert exemplar["spans"][0]["pid"] is not None
    finally:
        fleettrace.shutdown()
        tracing.TRACER.clear()
        tracing.disable()
    assert fleettrace.active() is None
    assert fleettrace.EXPORTER is None
    assert fleettrace.fleettrace_status() == {"active": False}
