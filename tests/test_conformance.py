"""JSON-fixture conformance suite.

The analog of the reference's cross-client JSON test wiring
(`tests/init_test.go:36-40` running BlockchainTests/GeneralStateTests/
TransactionTests/VMTests from frozen fixture files): every protocol
surface — hashing, RLP, trie roots, collation wire format, signatures,
SMC vote outcomes — is pinned by committed vectors in
`tests/testdata/*.json`, independently of the implementation under test.
`tests/testdata/generate_fixtures.py` regenerates them (only when the
PROTOCOL changes); any implementation drift fails here first.
"""

import json
import os

import pytest


TESTDATA = os.path.join(os.path.dirname(__file__), "testdata")


def _load(name):
    with open(os.path.join(TESTDATA, name)) as fh:
        return json.load(fh)


def test_keccak_vectors():
    from gethsharding_tpu.crypto.keccak import keccak256

    for case in _load("keccak.json"):
        assert keccak256(bytes.fromhex(case["in"])).hex() == case["out"]


def test_rlp_vectors_encode_and_decode():
    from gethsharding_tpu.utils.rlp import rlp_decode, rlp_encode

    def from_tree(tree):
        if isinstance(tree, str):
            return bytes.fromhex(tree)
        return [from_tree(x) for x in tree]

    for case in _load("rlp.json"):
        decoded = from_tree(case["decoded"])
        encoded = bytes.fromhex(case["encoded"])
        assert rlp_encode(decoded) == encoded
        assert rlp_decode(encoded) == decoded


def test_trie_vectors():
    from gethsharding_tpu.core.trie import SecureTrie, Trie

    for case in _load("trie.json"):
        trie = SecureTrie() if case.get("secure") else Trie()
        for op in case["ops"]:
            if op[0] == "put":
                trie.update(bytes.fromhex(op[1]), bytes.fromhex(op[2]))
            else:
                trie.delete(bytes.fromhex(op[1]))
        assert trie.root_hash().hex() == case["root"]


def test_collation_vectors():
    from gethsharding_tpu.core.derive_sha import chunk_root, poc_root
    from gethsharding_tpu.core.types import (
        CollationHeader, Transaction, serialize_txs_to_blob)
    from gethsharding_tpu.utils.blob import RawBlob, serialize_blobs
    from gethsharding_tpu.utils.hexbytes import Address20, Hash32

    for case in _load("collation.json"):
        if "raw_blob_body" in case:
            body = bytes.fromhex(case["raw_blob_body"])
            serialized = serialize_blobs([RawBlob(data=body)]) if body else b""
            assert serialized.hex() == case["serialized"]
            assert chunk_root(serialized).hex() == case["chunk_root"]
            continue
        txs = [
            Transaction(nonce=t["nonce"], gas_price=t["gas_price"],
                        gas_limit=t["gas_limit"],
                        to=Address20(bytes.fromhex(t["to"])),
                        value=t["value"],
                        payload=bytes.fromhex(t["payload"]))
            for t in case["txs"]
        ]
        for tx, t in zip(txs, case["txs"]):
            assert bytes(tx.hash()).hex() == t["tx_hash"]
            assert bytes(tx.sig_hash()).hex() == t["sig_hash_homestead"]
            assert bytes(tx.sig_hash(chain_id=1)).hex() == t["sig_hash_eip155_1"]
        blob = serialize_txs_to_blob(txs)
        assert blob.hex() == case["blob"]
        assert chunk_root(blob).hex() == case["chunk_root"]
        assert poc_root(blob, b"\x00" * 32).hex() == case["poc_root_salt00"]
        header = CollationHeader(
            shard_id=7, chunk_root=Hash32(bytes.fromhex(case["chunk_root"])),
            period=42, proposer_address=Address20(b"\xaa" * 20))
        assert bytes(header.hash()).hex() == case["header_hash_unsigned"]
        header.add_sig(b"\x01" * 65)
        assert header.encode_rlp().hex() == case["header_rlp"]
        assert bytes(header.hash()).hex() == case["header_hash_signed"]
        # round-trip through the wire format
        decoded = CollationHeader.decode_rlp(bytes.fromhex(case["header_rlp"]))
        assert bytes(decoded.hash()).hex() == case["header_hash_signed"]


def test_ecdsa_vectors():
    from gethsharding_tpu.crypto import secp256k1 as ecdsa

    for case in _load("ecdsa.json"):
        digest = bytes.fromhex(case["digest"])
        priv = int(case["priv"], 16)
        sig = ecdsa.sign(digest, priv)
        assert sig.to_bytes65().hex() == case["sig65"]
        recovered = ecdsa.ecrecover_address(
            digest, ecdsa.Signature.from_bytes65(bytes.fromhex(case["sig65"])))
        assert bytes(recovered).hex() == case["address"]


def test_bls_vectors():
    from gethsharding_tpu.crypto import bn256 as bls

    for case in _load("bls.json"):
        msg = bytes.fromhex(case["msg"])
        h = bls.hash_to_g1(msg)
        assert [hex(h[0]), hex(h[1])] == case["hash_to_g1"]
        agg_sig = (int(case["agg_sig"][0], 16), int(case["agg_sig"][1], 16))
        coords = [int(c, 16) for c in case["agg_pk"]]
        agg_pk = (bls.Fp2(coords[0], coords[1]), bls.Fp2(coords[2], coords[3]))
        for sk_hex, sig_hex in zip(case["secret_keys"], case["sigs"]):
            sig = bls.bls_sign(msg, int(sk_hex, 16))
            assert [hex(sig[0]), hex(sig[1])] == sig_hex
        assert bls.bls_verify_aggregate(msg, agg_sig,
                                        [agg_pk]) == case["verifies"]


def test_smc_scenario_vector():
    """Replay the frozen scenario script through a fresh chain and require
    byte-identical outcomes (committee sampling, vote tally, election)."""
    from gethsharding_tpu.mainchain.accounts import AccountManager
    from gethsharding_tpu.params import Config, ETHER
    from gethsharding_tpu.smc.chain import SimulatedMainchain
    from gethsharding_tpu.smc.state_machine import vote_digest
    from gethsharding_tpu.utils.hexbytes import Hash32

    fx = _load("smc.json")
    cfg = fx["config"]
    config = Config(shard_count=cfg["shard_count"],
                    committee_size=cfg["committee_size"],
                    quorum_size=cfg["quorum_size"])
    chain = SimulatedMainchain(config=config)
    manager = AccountManager()
    accounts = [manager.new_account(seed=seed.encode())
                for seed in fx["account_seeds"]]
    assert [bytes(a.address).hex() for a in accounts] == fx["addresses"]
    for acct in accounts:
        chain.fund(acct.address, 2000 * ETHER)
        chain.register_notary(
            acct.address, bls_pubkey=acct.bls_pubkey,
            bls_pop=manager.bls_proof_of_possession(acct.address))
    chain.fast_forward(1)
    period = chain.current_period()
    assert period == fx["expected"]["period"]
    root = None
    for step in fx["script"]:
        if step["op"] == "add_header":
            root = Hash32(bytes.fromhex(step["chunk_root"]))
            chain.add_header(accounts[0].address, step["shard"],
                             step["period"], root)
    digest = bytes(vote_digest(1, period, root))
    assert digest.hex() == fx["expected"]["vote_digest"]
    voted = []
    for acct in accounts:
        if chain.get_notary_in_committee(acct.address, 1) != acct.address:
            continue
        entry = chain.smc.notary_registry[acct.address]
        chain.submit_vote(acct.address, 1, period, entry.pool_index, root,
                          bls_sig=manager.bls_sign(acct.address, digest))
        voted.append(bytes(acct.address).hex())
    assert voted == fx["sampled_voters"]
    record = chain.smc.collation_records[(1, period)]
    assert record.vote_count == fx["expected"]["vote_count"]
    assert record.is_elected == fx["expected"]["is_elected"]
    assert chain.last_approved_collation(1) == fx["expected"]["last_approved"]


# == external vectors (NOT produced by this repo) ==========================
# tests/testdata/external_vectors.json: the classic ethereum/tests RLP
# cases, published Keccak-256 known answers, the canonical trie roots,
# well-known private-key address correspondences, and the EIP-155
# specification's worked example — cross-implementation evidence, the
# same role as the reference's public JSON suites (init_test.go:36-40).


def _ext():
    return _load("external_vectors.json")


def _rlp_item(spec):
    if "str" in spec:
        return spec["str"].encode()
    if "hex" in spec:
        return bytes.fromhex(spec["hex"])
    if "int" in spec:
        return spec["int"]
    if "int_str" in spec:
        return int(spec["int_str"])
    if "list" in spec:
        return [_rlp_item(s) for s in spec["list"]]
    raise ValueError(spec)


def test_external_rlp_vectors():
    from gethsharding_tpu.utils.rlp import rlp_decode, rlp_encode

    for case in _ext()["rlp"]:
        item = _rlp_item(case["in"])
        encoded = rlp_encode(item)
        assert encoded.hex() == case["out"], case["name"]
        # decode round trip (ints decode as canonical byte strings)
        rlp_decode(encoded)


def test_external_keccak_vectors():
    from gethsharding_tpu.crypto.keccak import keccak256

    for case in _ext()["keccak"]:
        assert keccak256(case["in_str"].encode()).hex() == case["out"]


def test_external_trie_vectors():
    from gethsharding_tpu.core.trie import Trie

    for case in _ext()["trie"]:
        trie = Trie()
        for key, value in case["pairs"]:
            trie.update(key.encode(), value.encode())
        assert trie.root_hash().hex() == case["root"], case["name"]


def test_external_known_key_addresses():
    from gethsharding_tpu.crypto import secp256k1

    for case in _ext()["addresses"]:
        priv = int(case["priv"], 16)
        assert bytes(secp256k1.priv_to_address(priv)).hex() == \
            case["address"]


def test_external_eip155_example():
    """The EIP-155 spec's worked example exercises RLP + keccak +
    signing + recovery together against published constants."""
    from gethsharding_tpu.crypto import secp256k1
    from gethsharding_tpu.crypto.keccak import keccak256
    from gethsharding_tpu.utils.rlp import rlp_encode

    ex = _ext()["eip155"]
    signing_data = rlp_encode([
        ex["nonce"], ex["gas_price"], ex["gas_limit"],
        bytes.fromhex(ex["to"]), ex["value"],
        bytes.fromhex(ex["data"]), ex["chain_id"], 0, 0])
    assert signing_data.hex() == ex["signing_data"]
    sighash = keccak256(signing_data)
    assert sighash.hex() == ex["signing_hash"]

    priv = int(ex["priv"], 16)
    assert bytes(secp256k1.priv_to_address(priv)).hex() == ex["sender"]

    # the published signature recovers to the published sender
    parity = (ex["v"] - 35 - 2 * ex["chain_id"])
    sig = secp256k1.Signature(r=int(ex["r"]), s=int(ex["s"]), v=parity)
    recovered = secp256k1.ecrecover_address(sighash, sig)
    assert bytes(recovered).hex() == ex["sender"]

    # our deterministic (RFC 6979) signer reproduces the exact published
    # r/s — the same nonce construction geth's libsecp256k1 uses
    ours = secp256k1.sign(sighash, priv)
    assert (ours.r, ours.s) == (int(ex["r"]), int(ex["s"]))


def test_storage_address_vectors():
    """BMT roots and chunk-store addresses are frozen: drift orphans
    every previously stored blob."""
    from gethsharding_tpu.storage import ChunkStore, bmt_hash
    from gethsharding_tpu.storage.chunker import chunk_key

    fx = _load("storage.json")

    def pattern(n):
        return bytes(i % 251 for i in range(n))

    for case in fx["bmt_roots"]:
        assert bmt_hash(pattern(case["size"])).hex() == case["root"], case
    assert chunk_key(5, pattern(5)).hex() == fx["chunk_key_example"]
    for case in fx["store_roots"]:
        store = ChunkStore()
        assert store.store(pattern(case["size"])).hex() == case["root"], case


def test_whisper_envelope_vectors():
    """Envelope identity hashes and PoW values are frozen: the flood
    dedup and spam economics hang off these exact numbers."""
    from gethsharding_tpu.p2p.whisper import Envelope

    fx = _load("whisper.json")
    for case in fx["envelopes"]:
        env = Envelope(expiry=case["expiry"], ttl=case["ttl"],
                       topic=bytes.fromhex(case["topic"]),
                       ciphertext=bytes.fromhex(case["ciphertext"]),
                       nonce=case["nonce"])
        assert env.hash().hex() == case["hash"], case
        assert env.pow() == case["pow"], case


# == bulk external suites (r4) =============================================
# tests/testdata/keccak_kats_sha3.json: the official Keccak team
# known-answer tests (FIPS 202), as vendored by go-ethereum 1.8.9
# (crypto/sha3/testdata/keccakKats.json.deflate) — 1024 byte-aligned
# cases across SHA3-224/256/384/512, all through the SAME keccak_f1600
# permutation + sponge as consensus keccak256.
# tests/testdata/keystore_v3_vectors.json: the Web3 Secret Storage v3
# specification vectors (Ethereum wiki; accounts/keystore/testdata/
# v3_test_vector.json in the reference).


def test_external_sha3_kats_pin_the_permutation():
    from gethsharding_tpu.crypto.keccak import sha3_digest

    kats = _load("keccak_kats_sha3.json")
    total = 0
    for variant in ("SHA3-224", "SHA3-256", "SHA3-384", "SHA3-512"):
        bits = int(variant.split("-")[1])
        for case in kats[variant]:
            msg = bytes.fromhex(case["message"])[: case["len"]]
            assert sha3_digest(msg, bits).hex() == case["digest"], (
                variant, case["len"])
            total += 1
    assert total == 1024


def test_external_keystore_v3_light_vectors():
    """The spec's 30/31-byte-key scrypt vectors (cheap KDF params)."""
    from gethsharding_tpu.mainchain.keystore import decrypt_key

    vectors = _load("keystore_v3_vectors.json")
    for name in ("31_byte_key", "30_byte_key"):
        case = vectors[name]
        priv = decrypt_key(case["json"], case["password"])
        assert priv == int(case["priv"], 16), name


@pytest.mark.skipif(os.environ.get("GETHSHARDING_SKIP_SLOW") == "1",
                    reason="GETHSHARDING_SKIP_SLOW=1")
def test_external_keystore_v3_wiki_vectors():
    """The canonical wikipage scrypt + pbkdf2 vectors (n=c=262144)."""
    from gethsharding_tpu.mainchain.keystore import decrypt_key

    vectors = _load("keystore_v3_vectors.json")
    for name in ("wikipage_test_vector_scrypt", "wikipage_test_vector_pbkdf2"):
        case = vectors[name]
        priv = decrypt_key(case["json"], case["password"])
        assert priv == int(case["priv"], 16), name
    # wrong password -> rejected via MAC, never a wrong key
    from gethsharding_tpu.mainchain.keystore import KeystoreError

    with pytest.raises(KeystoreError):
        decrypt_key(vectors["31_byte_key"]["json"], "not-the-password")


# invalid-RLP rejection cases (the ethereum/tests invalidRLPTest.json
# class: the EXPECTATION is the spec's — a canonical decoder must refuse
# each stream; there is no output to publish)
_INVALID_RLP = [
    ("emptyEncoding", ""),
    ("singleByteWrapped00", "8100"),
    ("singleByteWrapped7f", "817f"),
    ("truncatedShortString", "83646f"),
    ("truncatedLongString", "b83c0102"),
    ("truncatedLengthByte", "b8"),
    ("truncatedLongLength", "b90102"),
    ("longFormShortString", "b801ff"),
    ("longLengthLeadingZero", "b900000102"),
    ("longStringNoContent", "b800"),
    ("truncatedList", "c3010203ff"[:6] + ""),  # c30102: 3-len, 2 present
    ("listExtendsPastEnd", "c40102"),
    ("longFormShortList", "f803aabbcc"),
    ("listLengthLeadingZero", "f90000"),
    ("truncatedLongList", "f83b0102"),
    ("elementPastListEnd", "c382ffff"[:6]),  # c382ff: elem needs 2, has 1
    ("trailingBytesTop", "c0c0"),
    ("trailingByteAfterString", "83646f6700"),
    ("hugeLengthOverflow", "bbffffffff"),
    ("hugeListLengthOverflow", "fbffffffff"),
    ("lengthBytesPastEnd", "ba0102"),
]


def test_invalid_rlp_streams_are_rejected():
    from gethsharding_tpu.utils.rlp import DecodingError, rlp_decode

    for name, stream in _INVALID_RLP:
        with pytest.raises(DecodingError):
            rlp_decode(bytes.fromhex(stream))
        assert True, name


def test_external_trie_vectors_any_insertion_order():
    """trieanyorder semantics: the published roots must be reached from
    EVERY insertion order (the trie is a pure function of the map)."""
    import itertools

    from gethsharding_tpu.core.trie import Trie

    for case in _ext()["trie"]:
        pairs = case["pairs"]
        orders = list(itertools.permutations(range(len(pairs)))) \
            if len(pairs) <= 4 else [
                tuple(range(len(pairs))),
                tuple(reversed(range(len(pairs)))),
                tuple(sorted(range(len(pairs)), key=lambda i: pairs[i][1]))]
        for order in orders:
            trie = Trie()
            for i in order:
                key, value = pairs[i]
                trie.update(key.encode(), value.encode())
            assert trie.root_hash().hex() == case["root"], (
                case["name"], order)


# == reference-authored sharding-domain vectors ============================
# tests/testdata/go_sharding_vectors.json holds expected values transcribed
# VERBATIM from the reference's own Go test assertions (every vector cites
# its /root/reference file:line) — the "byte-identical vs the pure-Go path"
# claim witnessed by reference-produced ground truth without needing a Go
# toolchain. scripts/go_vector_gen can extend the file with generated
# byte-exact header/POC sections on a Go-equipped host.

def _go_vectors() -> dict:
    """The transcribed vector file, or {} when absent (a partial
    checkout must skip these tests, not fail the whole module's
    collection)."""
    path = os.path.join(os.path.dirname(__file__), "testdata",
                        "go_sharding_vectors.json")
    try:
        with open(path) as fh:
            return json.load(fh)
    except OSError:
        return {}


def _go_vec_accounts(n: int):
    """Deterministic stand-ins for the reference helper's random keys
    (sharding_manager_test.go:46-48): the SMC never checks signatures at
    registration (registration is scalar-crypto-free by design), so any
    distinct Address20s reproduce the pinned outcomes."""
    from gethsharding_tpu.crypto.keccak import keccak256
    from gethsharding_tpu.utils.hexbytes import Address20

    return [Address20(keccak256(b"go-vector-account-%d" % i)[:20])
            for i in range(n)]


def _run_smc_scenario(scenario: dict) -> None:
    """Interpret one transcribed SMC scenario against this repo's chain.

    Mirrors the Go test helpers: one backend.Commit() after every
    mutating call (sharding_manager_test.go:84,121,156,192), accounts
    funded 2000 ETH like the genesis alloc (:32,51), chunk roots are
    [32]byte{b} (:151)."""
    from gethsharding_tpu.params import Config, ETHER
    from gethsharding_tpu.smc.chain import SimulatedMainchain
    from gethsharding_tpu.smc.state_machine import SMCRevert
    from gethsharding_tpu.utils.hexbytes import Hash32

    overrides = scenario.get("config", {})
    config = Config(**overrides) if overrides else Config()
    chain = SimulatedMainchain(config=config)
    accounts = _go_vec_accounts(1001)
    funded = set()

    def fund(i):
        if i not in funded:
            chain.fund(accounts[i], 2000 * ETHER)
            funded.add(i)

    def root32(b):
        return Hash32(bytes([b]) + b"\x00" * 31)

    def attempt(fn):  # tx + Commit, reverts reported not raised
        try:
            fn()
            outcome = "ok"
        except SMCRevert:
            outcome = "revert"
        chain.commit()
        return outcome

    def sample(shard):
        return chain.get_notary_in_committee(accounts[0], shard)

    for step in scenario["steps"]:
        op = step["op"]
        ctx = (scenario["name"], step)
        if op == "register":
            i = step["account"]
            fund(i)
            got = attempt(lambda: chain.register_notary(
                accounts[i], value=step["deposit_eth"] * ETHER))
            assert got == step["expect"], ctx
            if got == "ok":
                entry = chain.notary_registry(accounts[i])
                assert entry is not None and entry.deposited, ctx
                if "check_pool_index" in step:
                    assert entry.pool_index == step["check_pool_index"], ctx
                if "check_deregistered_period" in step:
                    assert (entry.deregistered_period
                            == step["check_deregistered_period"]), ctx
        elif op == "register_many":
            # registerNotaries helper (:77-113): incremental pool indices,
            # zero deregistered period, one commit per registration
            for i in range(step["count"]):
                fund(i)
                chain.register_notary(
                    accounts[i], value=step["deposit_eth"] * ETHER)
                chain.commit()
                entry = chain.notary_registry(accounts[i])
                assert (entry.pool_index == i
                        and entry.deregistered_period == 0), (ctx, i)
        elif op == "deregister":
            i = step["account"]
            got = attempt(lambda: chain.deregister_notary(accounts[i]))
            assert got == step.get("expect", "ok"), ctx
            if step.get("check_deregistered_period_nonzero"):
                # deregisterNotaries helper pin (:122-126)
                entry = chain.notary_registry(accounts[i])
                assert entry.deregistered_period != 0, ctx
        elif op == "release":
            got = attempt(
                lambda: chain.release_notary(accounts[step["account"]]))
            assert got == step["expect"], ctx
        elif op == "fast_forward":
            chain.fast_forward(step["periods"])
        elif op == "pool_length":
            # checkNotaryPoolLength (:219-230)
            assert chain.smc.notary_pool_length == step["expect"], ctx
        elif op == "registry_check":
            entry = chain.notary_registry(accounts[step["account"]])
            assert bool(entry and entry.deposited) == step["deposited"], ctx
        elif op == "balance_vs_deposit":
            # balance.Cmp(notaryDeposit) pins (:389-398 released >= deposit,
            # :434-437 unreleased <= deposit)
            bal = chain.balance_of(accounts[step["account"]])
            if step["cmp"] == "at_least":
                assert bal >= config.notary_deposit, ctx
            else:
                assert bal <= config.notary_deposit, ctx
        elif op == "add_header":
            shard, period = step["shard"], step["period"]
            root = root32(step["root_byte"])
            got = attempt(lambda: chain.add_header(
                accounts[step["account"]], shard, period, root,
                b"SIGNATURE"))
            assert got == step["expect"], ctx
            if got == "ok":
                # the addHeader helper's own pins (:156-170)
                assert chain.last_submitted_collation(shard) == period, ctx
                record = chain.collation_record(shard, period)
                assert (record is not None
                        and bytes(record.chunk_root) == bytes(root)), ctx
        elif op == "submit_vote":
            shard, index = step["shard"], step["index"]
            got = attempt(lambda: chain.submit_vote(
                accounts[step["account"]], shard, step["period"], index,
                root32(step["root_byte"])))
            assert got == step["expect"], ctx
            if got == "ok":
                # submitVote helper pin (:196-201)
                assert chain.has_voted(shard, index), ctx
        elif op == "vote_count":
            assert chain.get_vote_count(step["shard"]) == step["expect"], ctx
        elif op == "last_approved":
            assert (chain.last_approved_collation(step["shard"])
                    == step["expect"]), ctx
        elif op == "sample_equals":
            assert sample(step["shard"]) == accounts[step["account"]], ctx
        elif op == "sample_not":
            # the Go originals loop the SAME deterministic view call
            # (e.g. :481-486) — the repeat is transcription fidelity,
            # not extra coverage
            for _ in range(step["times"]):
                assert sample(step["shard"]) != accounts[step["account"]], ctx
        elif op == "samples_differ":
            for _ in range(step["times"]):
                assert sample(step["shard_a"]) != sample(step["shard_b"]), ctx
        else:
            raise AssertionError(f"unknown op {op!r}")


def _smc_scenario_params():
    scenarios = _go_vectors().get("smc_scenarios")
    if not scenarios:
        return [pytest.param({}, id="vectors-missing",
                             marks=pytest.mark.skip(
                                 reason="go_sharding_vectors.json absent"))]
    out = []
    for scenario in scenarios:
        marks = [pytest.mark.slow] if scenario.get("slow") else []
        out.append(pytest.param(scenario, id=scenario["name"], marks=marks))
    return out


@pytest.mark.parametrize("scenario", _smc_scenario_params())
def test_go_sharding_vectors_smc(scenario):
    _run_smc_scenario(scenario)


def test_go_sharding_vectors_params():
    """The reference's own config_test.go constant pins, applied to this
    framework's Config (the constants ARE the consensus)."""
    from gethsharding_tpu.params import Config

    cases = _go_vectors().get("params")
    if not cases:
        pytest.skip("go_sharding_vectors.json absent")
    config = Config()
    field_of = {
        "notary_deposit_wei": "notary_deposit",
        "period_length": "period_length",
        "notary_lockup_length": "notary_lockup_length",
        "proposer_lockup_length": "proposer_lockup_length",
        "committee_size": "committee_size",
        "quorum_size": "quorum_size",
        "challenge_period": "challenge_period",
    }
    for case in cases:
        got = getattr(config, field_of[case["name"]])
        assert got == int(case["value"]), (case["name"], got)


def test_go_sharding_vectors_blob_codec():
    """The marshal_test.go byte pins: indicator bytes, terminal lengths,
    skip-EVM flags, and data placement of the reference's own serialize/
    deserialize assertions."""
    from gethsharding_tpu.utils.blob import (RawBlob, deserialize_blobs,
                                             serialize_blobs)

    cases = _go_vectors().get("blob_vectors")
    if not cases:
        pytest.skip("go_sharding_vectors.json absent")
    for case in cases:
        expect = case["expect"]
        if case["op"] == "deserialize":
            blobs = deserialize_blobs(bytes.fromhex(case["input_hex"]))
            assert len(blobs) == expect["num_blobs"], case["name"]
            for blob, want in zip(blobs, expect["blobs"]):
                assert blob.skip_evm == want["skip_evm"], case["name"]
                assert len(blob.data) == want["data_len"], case["name"]
        else:
            blobs = [RawBlob(data=bytes.fromhex(b["data_hex"]),
                             skip_evm=b["skip_evm"])
                     for b in case["blobs"]]
            out = serialize_blobs(blobs)
            assert len(out) == expect["total_len"], case["name"]
            for pos, want in expect.get("byte_checks", {}).items():
                assert out[int(pos)] == int(want, 16), (case["name"], pos)
            for start, end, value_start in expect.get("ranges", []):
                for i in range(start, end):
                    assert out[i] == (value_start + i - start) & 0xFF, (
                        case["name"], i)


def test_go_sharding_vectors_generated_sections():
    """Byte-exact header/POC vectors from scripts/go_vector_gen — only
    present once someone runs the generator on a Go-equipped host; the
    transcribed sections above carry the reference-authored coverage
    either way."""
    vectors = _go_vectors()
    if not vectors or "collation_headers" not in vectors:
        pytest.skip("generated sections absent (scripts/go_vector_gen "
                    "needs a Go toolchain; transcribed sections cover "
                    "the reference-authored pins)")
    from gethsharding_tpu.core.types import Collation, CollationHeader
    from gethsharding_tpu.utils.blob import RawBlob, serialize_blobs
    from gethsharding_tpu.utils.hexbytes import Address20, Hash32
    from gethsharding_tpu.utils.rlp import rlp_encode

    for case in vectors["collation_headers"]:
        header = CollationHeader(
            shard_id=int(case["shardID"]),
            period=int(case["period"]),
            chunk_root=Hash32(bytes.fromhex(case["chunkRoot"])),
            proposer_address=Address20(bytes.fromhex(case["proposer"])),
            proposer_signature=bytes.fromhex(case["sig"]),
        )
        assert bytes(header.hash()).hex() == case["hash"], case
    for case in vectors.get("blob_codec", []):
        blobs = [RawBlob(data=rlp_encode(bytes.fromhex(b["payload"])),
                         skip_evm=bool(b["skip_evm"]))
                 for b in case["blobs"]]
        assert serialize_blobs(blobs).hex() == case["serialized"]
    for case in vectors.get("poc", []):
        coll = Collation(header=CollationHeader(shard_id=0, period=1),
                         body=bytes.fromhex(case["body"]))
        poc = coll.calculate_poc(bytes.fromhex(case["salt"]))
        assert bytes(poc).hex() == case["poc"], case
