"""Devnet orchestrator tests (the puppeth / ExecAdapter role): a whole
network of OS processes comes up, makes protocol progress, respawns
crashed actors within the rate limit, and tears down cleanly."""

import os
import time

import pytest

from gethsharding_tpu.devnet import MAX_RESTARTS_PER_WINDOW, Devnet
from gethsharding_tpu.rpc.client import RemoteMainchain


def _wait(cond, timeout=30.0, step=0.2):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(step)
    return False


@pytest.mark.slow
def test_devnet_progress_and_respawn(tmp_path):
    net = Devnet(notaries=1, proposers=1, observers=1, lights=1,
                 base_dir=str(tmp_path), blocktime=0.2, quorum=1)
    try:
        host, port = net.start()
        chain = RemoteMainchain.dial(host, port)
        try:
            # the network makes real protocol progress: blocks advance
            # and the proposer lands a collation header on the SMC
            assert _wait(lambda: chain.block_number > 10)
            assert _wait(
                lambda: chain.last_submitted_collation(0) > 0, timeout=45)

            # crash an actor: the next poll respawns it as a fresh
            # process with the same identity flags
            # actors are spread over the shard space and keep their
            # identity directory across respawns
            assert "--shardid" in net.actors["proposer-0"].argv
            victim = net.actors["proposer-0"]
            victim.proc.kill()
            victim.proc.wait(timeout=10)
            status = net.poll()
            assert "restarted" in status["actors"]["proposer-0"]
            fresh = net.actors["proposer-0"]
            assert fresh.proc.pid != victim.proc.pid
            assert _wait(lambda: fresh.proc.poll() is None, timeout=5)

            # the restart rate limit gives up on a crash-looping actor
            child = net.actors["proposer-0"]
            child.restarts = [time.monotonic()] * MAX_RESTARTS_PER_WINDOW
            child.proc.kill()
            child.proc.wait(timeout=10)
            status = net.poll()
            assert "gave up" in status["actors"]["proposer-0"]
            assert net.actors["proposer-0"].given_up
            # ...and stays down on later polls
            assert "down" in net.poll()["actors"]["proposer-0"]

            # the notary, observer and light node kept running through it
            for name in ("notary-0", "observer-0", "light-0"):
                assert net.actors[name].proc.poll() is None, name
        finally:
            chain.close()
    finally:
        net.stop()
    # teardown is complete: no child outlives stop()
    for child in list(net.actors.values()) + [net.chain]:
        assert child.proc.poll() is not None
    # per-actor datadirs + logs landed under the base dir
    assert os.path.isdir(tmp_path / "notary-0" / "keystore")
    assert (tmp_path / "logs" / "chain.log").exists()
