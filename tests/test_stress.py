"""Config-5 stress pipeline tests: the fused addHeader + vote + BLS
verify + replay step, mesh-sharded over the virtual 8-device CPU mesh
with distinct per-shard data and UNEVEN shard counts (padding rows),
bit-identical with the single-device run."""

import numpy as np
import pytest

from gethsharding_tpu.parallel.mesh import make_mesh
from gethsharding_tpu.parallel.stress import (
    StressPipeline,
    build_stress_inputs,
)
from gethsharding_tpu.params import Config

COMMITTEE = 7  # small pool keeps host-side workload generation fast


@pytest.fixture(scope="module")
def workload():
    # 19 shards over 8 devices: uneven (pads to 24)
    return build_stress_inputs(19, votes_per_shard=3, txs_per_shard=2,
                               committee_size=COMMITTEE)


def _run(mesh, workload):
    inputs, pool_addr, blockhash, sample_size, _ = workload
    config = Config(committee_size=COMMITTEE, quorum_size=2)
    pipeline = StressPipeline(config=config, mesh=mesh)
    return pipeline.run(inputs, pool_addr, blockhash, period=1,
                        sample_size=sample_size)


def test_single_device_stress_step(workload):
    out = _run(None, workload)
    accepted = np.asarray(out.accepted)
    # the builder constructs attempts the committee sampling must accept
    assert accepted.all(), accepted
    assert np.asarray(out.agg_ok).all()
    assert np.asarray(out.tx_status).all()
    assert int(out.total_votes) == accepted.size
    # votes_per_shard (3) >= quorum (2): every shard elects
    assert np.asarray(out.is_elected).all()
    assert int(out.total_elected) == accepted.shape[0]


def test_mesh_matches_single_device_with_padding(workload):
    single = _run(None, workload)
    mesh = make_mesh(8)
    sharded = _run(mesh, workload)
    for name in ("accepted", "vote_count", "is_elected", "agg_ok",
                 "tx_status", "roots"):
        a = np.asarray(getattr(single, name))
        b = np.asarray(getattr(sharded, name))
        assert a.shape == b.shape, name
        assert (a == b).all(), name
    assert int(single.total_votes) == int(sharded.total_votes)
    assert int(single.total_elected) == int(sharded.total_elected)
    assert int(single.total_txs) == int(sharded.total_txs)


def test_hierarchical_mesh_matches_single_device(workload):
    """The DCN tier: the same stress step over the 2-D ("dcn", "ici")
    multi-host layout (4 virtual hosts x 2 devices) is bit-identical
    with the single-device run — sampling sees global shard ids, tallies
    reduce ICI-first then DCN."""
    from gethsharding_tpu.parallel.mesh import make_multihost_mesh

    single = _run(None, workload)
    mesh2 = make_multihost_mesh(n_hosts=4, devices_per_host=2)
    sharded = _run(mesh2, workload)
    for name in ("accepted", "vote_count", "is_elected", "agg_ok",
                 "tx_status", "roots"):
        a = np.asarray(getattr(single, name))
        b = np.asarray(getattr(sharded, name))
        assert a.shape == b.shape, name
        assert (a == b).all(), name
    assert int(single.total_votes) == int(sharded.total_votes)
    assert int(single.total_elected) == int(sharded.total_elected)
    assert int(single.total_txs) == int(sharded.total_txs)
