"""Collation/Transaction types: RLP round-trips, hashing, blob pipeline."""

import pytest

from gethsharding_tpu.core.types import (
    COLLATION_SIZE_LIMIT,
    Collation,
    CollationHeader,
    Transaction,
    deserialize_blob_to_txs,
    serialize_txs_to_blob,
)
from gethsharding_tpu.utils.hexbytes import Address20, Hash32


def make_tx(gas_limit: int) -> Transaction:
    # mirrors the reference's makeTxWithGasLimit test helper: all other
    # fields zero/nil
    return Transaction(gas_limit=gas_limit)


def test_tx_rlp_roundtrip():
    tx = Transaction(
        nonce=3,
        gas_price=10**9,
        gas_limit=21000,
        to=Address20(b"\x01" * 20),
        value=10**18,
        payload=b"hello",
        v=27,
        r=12345,
        s=67890,
    )
    assert Transaction.decode_rlp(tx.encode_rlp()) == tx


def test_tx_nil_recipient_roundtrip():
    tx = Transaction(nonce=1, payload=b"init code")
    decoded = Transaction.decode_rlp(tx.encode_rlp())
    assert decoded.to is None
    assert decoded == tx


def test_tx_hash_stable():
    assert make_tx(0).hash() == make_tx(0).hash()
    assert make_tx(0).hash() != make_tx(1).hash()


def test_header_hash_and_rlp_roundtrip():
    header = CollationHeader(
        shard_id=1,
        chunk_root=Hash32(b"\x02" * 32),
        period=5,
        proposer_address=Address20(b"\x03" * 20),
        proposer_signature=b"\x04" * 65,
    )
    decoded = CollationHeader.decode_rlp(header.encode_rlp())
    assert decoded == header
    assert decoded.hash() == header.hash()


def test_header_nil_fields_like_reference():
    # NewCollationHeader(big.NewInt(1), nil, big.NewInt(1), nil, []byte{})
    header = CollationHeader(shard_id=1, period=1, proposer_signature=b"")
    encoded = header.encode_rlp()
    # [0x01, empty, 0x01, empty, empty] -> c5 01 80 01 80 80
    assert encoded.hex() == "c50180018080"
    assert CollationHeader.decode_rlp(encoded) == header


def test_sig_change_changes_hash():
    h = CollationHeader(shard_id=1, period=1)
    before = h.hash()
    h.add_sig(b"\x01" * 65)
    assert h.hash() != before


def test_serialize_deserialize_txs():
    txs = [make_tx(0), make_tx(5), make_tx(20), make_tx(100)]
    body = serialize_txs_to_blob(txs)
    assert len(body) % 32 == 0
    back = deserialize_blob_to_txs(body)
    assert back == txs


def test_collation_size_limit_enforced():
    big_tx = Transaction(payload=b"\xff" * (COLLATION_SIZE_LIMIT + 100))
    with pytest.raises(ValueError, match="size limit"):
        serialize_txs_to_blob([big_tx])


def test_collation_chunk_root_pipeline():
    txs = [make_tx(i) for i in range(4)]
    body = serialize_txs_to_blob(txs)
    collation = Collation(
        header=CollationHeader(shard_id=0, period=1), body=body, transactions=txs
    )
    root = collation.calculate_chunk_root()
    assert collation.header.chunk_root == root
    # same body -> same root
    c2 = Collation(header=CollationHeader(shard_id=0, period=1), body=body)
    assert c2.calculate_chunk_root() == root


def test_strict_decode_rejects_nested_list_fields():
    from gethsharding_tpu.utils.rlp import DecodingError, rlp_encode

    bad = rlp_encode([[b"\x01"], b"", b"", b"", b"", b"", b"", b"", b""])
    with pytest.raises(DecodingError, match="expected RLP string"):
        Transaction.decode_rlp(bad)


def test_strict_decode_rejects_wrong_length_hash():
    from gethsharding_tpu.utils.rlp import DecodingError, rlp_encode

    # 5-byte chunk root must be rejected, not zero-padded
    bad = rlp_encode([b"\x01", b"\x01\x02\x03\x04\x05", b"\x01", b"", b""])
    with pytest.raises(DecodingError, match="chunk_root"):
        CollationHeader.decode_rlp(bad)
