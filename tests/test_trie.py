"""Merkle-Patricia trie: golden roots from the canonical Ethereum trie tests."""

from gethsharding_tpu.core.derive_sha import chunk_root, derive_sha, poc_root
from gethsharding_tpu.core.trie import EMPTY_ROOT, Trie
from gethsharding_tpu.utils.rlp import rlp_encode


def test_empty_root():
    assert Trie().root_hash() == EMPTY_ROOT
    assert EMPTY_ROOT.hex() == (
        "56e81f171bcc55a6ff8345e692c0f86e5b48e01b996cadc001622fb5e363b421"
    )


def test_geth_insert_golden():
    # go-ethereum trie/trie_test.go TestInsert golden root
    t = Trie()
    t.update(b"doe", b"reindeer")
    t.update(b"dog", b"puppy")
    t.update(b"dogglesworth", b"cat")
    assert t.root_hash().hex() == (
        "8aad789dff2f538bca5d8ea56e8abe10f4c7ba3a5dea95fea4cd6e7c3a1168d3"
    )


def test_ethereum_anyorder_golden():
    # canonical trietest vector; insertion order must not matter
    pairs = [
        (b"do", b"verb"),
        (b"dog", b"puppy"),
        (b"doge", b"coin"),
        (b"horse", b"stallion"),
    ]
    expected = "5991bb8c6514148a29db676a14ac506cd2cd5775ace63c30a4fe457715e9ac84"
    for order in ([0, 1, 2, 3], [3, 2, 1, 0], [2, 0, 3, 1]):
        t = Trie()
        for i in order:
            k, v = pairs[i]
            t.update(k, v)
        assert t.root_hash().hex() == expected


def test_update_overwrites():
    t = Trie()
    t.update(b"k", b"v1")
    r1 = t.root_hash()
    t.update(b"k", b"v2")
    assert t.root_hash() != r1
    assert t.get(b"k") == b"v2"


def test_get_semantics():
    t = Trie()
    t.update(b"abc", b"1")
    t.update(b"abd", b"2")
    t.update(b"ab", b"3")
    assert t.get(b"abc") == b"1"
    assert t.get(b"abd") == b"2"
    assert t.get(b"ab") == b"3"
    assert t.get(b"a") is None
    assert t.get(b"abcd") is None


def test_derive_sha_empty():
    assert derive_sha([]) == EMPTY_ROOT


def test_derive_sha_order_sensitivity():
    items = [rlp_encode(b"a"), rlp_encode(b"b")]
    assert derive_sha(items) != derive_sha(list(reversed(items)))


def test_chunk_root_determinism():
    body = bytes(range(64))
    assert chunk_root(body) == chunk_root(bytes(range(64)))
    assert chunk_root(body) != chunk_root(body[:-1])


def test_poc_root_empty_body_uses_salt():
    assert poc_root(b"", b"salt") == chunk_root(b"salt")
    assert poc_root(b"ab", b"s") == chunk_root(b"s" + b"a" + b"s" + b"b")


def test_chunk_root_encodes_bytes_as_uint():
    # Go's Chunks.GetRlp encodes each byte as a uint: 0x00 -> 0x80 (not 0x00).
    # Regression for the consensus divergence caught in review.
    assert chunk_root(b"\x00") == derive_sha([rlp_encode(0)])
    assert chunk_root(b"\x01") == derive_sha([rlp_encode(1)])
    assert chunk_root(b"\x80") == derive_sha([bytes.fromhex("8180")])


def test_delete_matches_fresh_build():
    """Insert/delete sequences must land on the same root as building a
    fresh trie with the surviving pairs (structure fully canonicalized)."""
    import random

    from gethsharding_tpu.core.trie import EMPTY_ROOT, Trie

    rng = random.Random(99)
    for trial in range(6):
        pairs = {}
        trie = Trie()
        for _ in range(rng.randrange(5, 80)):
            k = bytes(rng.randrange(256) for _ in range(rng.randrange(1, 6)))
            v = bytes([rng.randrange(1, 256)])
            pairs[k] = v
            trie.update(k, v)
        doomed = rng.sample(sorted(pairs), k=len(pairs) // 2)
        for k in doomed:
            trie.delete(k)
            del pairs[k]
        trie.delete(b"\xde\xad\xbe\xef")  # absent key: no-op
        fresh = Trie()
        for k, v in pairs.items():
            fresh.update(k, v)
        assert trie.root_hash() == fresh.root_hash(), trial
        for k, v in pairs.items():
            assert trie.get(k) == v
        # empty-value update deletes (geth semantics)
        if pairs:
            k = next(iter(pairs))
            trie.update(k, b"")
            assert trie.get(k) is None
    empty = Trie()
    empty.update(b"x", b"1")
    empty.delete(b"x")
    assert empty.root_hash() == EMPTY_ROOT


def test_merkle_proofs_round_trip_and_tamper():
    import random

    from gethsharding_tpu.core.trie import Trie, verify_proof

    rng = random.Random(7)
    trie = Trie()
    pairs = {}
    for _ in range(120):
        k = bytes(rng.randrange(256) for _ in range(rng.randrange(1, 5)))
        v = bytes(rng.randrange(1, 256) for _ in range(rng.randrange(1, 40)))
        pairs[k] = v
        trie.update(k, v)
    root = trie.root_hash()
    for k, v in list(pairs.items())[:30]:
        proof = trie.prove(k)
        assert verify_proof(root, k, proof) == v
    # absence proof: a key that is not present verifies to None
    absent = b"\xff\xff\xff\xff\xff\xff"
    assert verify_proof(root, absent, trie.prove(absent)) is None
    # a tampered proof must be rejected
    k = next(iter(pairs))
    proof = trie.prove(k)
    bad = [bytes(proof[0][:-1]) + bytes([proof[0][-1] ^ 1])] + proof[1:]
    import pytest as _pytest

    with _pytest.raises(ValueError):
        verify_proof(root, k, bad)


def test_secure_trie_keys_are_hashed():
    from gethsharding_tpu.core.trie import SecureTrie, Trie, verify_proof
    from gethsharding_tpu.crypto.keccak import keccak256

    st = SecureTrie()
    st.update(b"account-1", b"\x01")
    st.update(b"account-2", b"\x02")
    plain = Trie()
    plain.update(keccak256(b"account-1"), b"\x01")
    plain.update(keccak256(b"account-2"), b"\x02")
    assert st.root_hash() == plain.root_hash()
    assert st.get(b"account-1") == b"\x01"
    proof = st.prove(b"account-2")
    assert verify_proof(st.root_hash(), keccak256(b"account-2"),
                        proof) == b"\x02"
    st.delete(b"account-1")
    assert st.get(b"account-1") is None
