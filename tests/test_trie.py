"""Merkle-Patricia trie: golden roots from the canonical Ethereum trie tests."""

from gethsharding_tpu.core.derive_sha import chunk_root, derive_sha, poc_root
from gethsharding_tpu.core.trie import EMPTY_ROOT, Trie
from gethsharding_tpu.utils.rlp import rlp_encode


def test_empty_root():
    assert Trie().root_hash() == EMPTY_ROOT
    assert EMPTY_ROOT.hex() == (
        "56e81f171bcc55a6ff8345e692c0f86e5b48e01b996cadc001622fb5e363b421"
    )


def test_geth_insert_golden():
    # go-ethereum trie/trie_test.go TestInsert golden root
    t = Trie()
    t.update(b"doe", b"reindeer")
    t.update(b"dog", b"puppy")
    t.update(b"dogglesworth", b"cat")
    assert t.root_hash().hex() == (
        "8aad789dff2f538bca5d8ea56e8abe10f4c7ba3a5dea95fea4cd6e7c3a1168d3"
    )


def test_ethereum_anyorder_golden():
    # canonical trietest vector; insertion order must not matter
    pairs = [
        (b"do", b"verb"),
        (b"dog", b"puppy"),
        (b"doge", b"coin"),
        (b"horse", b"stallion"),
    ]
    expected = "5991bb8c6514148a29db676a14ac506cd2cd5775ace63c30a4fe457715e9ac84"
    for order in ([0, 1, 2, 3], [3, 2, 1, 0], [2, 0, 3, 1]):
        t = Trie()
        for i in order:
            k, v = pairs[i]
            t.update(k, v)
        assert t.root_hash().hex() == expected


def test_update_overwrites():
    t = Trie()
    t.update(b"k", b"v1")
    r1 = t.root_hash()
    t.update(b"k", b"v2")
    assert t.root_hash() != r1
    assert t.get(b"k") == b"v2"


def test_get_semantics():
    t = Trie()
    t.update(b"abc", b"1")
    t.update(b"abd", b"2")
    t.update(b"ab", b"3")
    assert t.get(b"abc") == b"1"
    assert t.get(b"abd") == b"2"
    assert t.get(b"ab") == b"3"
    assert t.get(b"a") is None
    assert t.get(b"abcd") is None


def test_derive_sha_empty():
    assert derive_sha([]) == EMPTY_ROOT


def test_derive_sha_order_sensitivity():
    items = [rlp_encode(b"a"), rlp_encode(b"b")]
    assert derive_sha(items) != derive_sha(list(reversed(items)))


def test_chunk_root_determinism():
    body = bytes(range(64))
    assert chunk_root(body) == chunk_root(bytes(range(64)))
    assert chunk_root(body) != chunk_root(body[:-1])


def test_poc_root_empty_body_uses_salt():
    assert poc_root(b"", b"salt") == chunk_root(b"salt")
    assert poc_root(b"ab", b"s") == chunk_root(b"s" + b"a" + b"s" + b"b")


def test_chunk_root_encodes_bytes_as_uint():
    # Go's Chunks.GetRlp encodes each byte as a uint: 0x00 -> 0x80 (not 0x00).
    # Regression for the consensus divergence caught in review.
    assert chunk_root(b"\x00") == derive_sha([rlp_encode(0)])
    assert chunk_root(b"\x01") == derive_sha([rlp_encode(1)])
    assert chunk_root(b"\x80") == derive_sha([bytes.fromhex("8180")])
