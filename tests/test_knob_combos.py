"""The autotune knob matrix must be sound COMBINED, not just per knob.

Each case runs the committee-verify kernel end to end (good + tampered
rows) in a subprocess with the knob env set — the knobs are read at
import, so a fresh interpreter is the only honest way to exercise a
configuration exactly as the bench probes deploy it
(scripts/tpu_experiments/*_cfg_*.sh)."""

import os
import subprocess
import sys

import pytest

slow = pytest.mark.skipif(
    os.environ.get("GETHSHARDING_SKIP_SLOW") == "1",
    reason="GETHSHARDING_SKIP_SLOW=1",
)

_DRIVER = """
from gethsharding_tpu.parallel.virtual import force_virtual_cpu_devices
force_virtual_cpu_devices(1)
import numpy as np, jax.numpy as jnp, jax
from gethsharding_tpu.crypto import bn256 as ref
from gethsharding_tpu.ops import bn256_jax as k

tag = b"combo-drive"
keys = [ref.bls_keygen(tag + bytes([j])) for j in range(3)]
sigs = [ref.bls_sign(tag, sk) for sk, _ in keys]
pks = [pk for _, pk in keys]
bad = [sigs[0], sigs[1], ref.g1_add(sigs[2], ref.G1_GEN)]
hx, hy, hok = k.g1_to_limbs([ref.hash_to_g1(tag)] * 2)
sx, sy, sm = k.g1_committee_to_limbs([sigs, bad], 3)
gx, gy, gm = k.g2_committee_to_limbs([pks, pks], 3)
out = jax.jit(k.bls_aggregate_verify_committee_batch)(
    jnp.asarray(hx), jnp.asarray(hy), jnp.asarray(sx), jnp.asarray(sy),
    jnp.asarray(sm), jnp.asarray(gx), jnp.asarray(gy), jnp.asarray(gm),
    jnp.asarray(hok))
assert [bool(v) for v in np.asarray(out)] == [True, False], out
print("combo-ok")
"""

COMBOS = [
    # the round's prime probe candidates (scripts/tpu_experiments/)
    {"GETHSHARDING_TPU_LIMB_FORM": "wide", "GETHSHARDING_TPU_NORM": "relaxed",
     "GETHSHARDING_TPU_PAIR_UNROLL": "finalexp"},
    # mega finalexp on CPU exercises the knob wiring + XLA fallback (the
    # kernel itself is interpret-tested in test_pallas_finalexp)
    {"GETHSHARDING_TPU_LIMB_FORM": "exact", "GETHSHARDING_TPU_CARRY": "scan",
     "GETHSHARDING_TPU_FINALEXP": "mega"},
    {"GETHSHARDING_TPU_LIMB_FORM": "exact", "GETHSHARDING_TPU_CARRY": "unroll",
     "GETHSHARDING_TPU_SCAN_UNROLL": "4"},
    {"GETHSHARDING_TPU_LIMB_FORM": "wide", "GETHSHARDING_TPU_NORM": "relaxed",
     "GETHSHARDING_TPU_SCAN_UNROLL": "4"},
]


_RELAXED_CANON_DRIVER = """
from gethsharding_tpu.parallel.virtual import force_virtual_cpu_devices
force_virtual_cpu_devices(1)
import numpy as np
from gethsharding_tpu.ops import limb
from gethsharding_tpu.ops.bn256_jax import FP

# a value < p in a QUASI-canonical representation (one -1 limb, value
# unchanged): canon must still emit the unique canonical limb vector,
# or eq/is_zero would report two equal field values unequal
v = FP.p - 12345
base = limb.int_to_limbs(v)
k = int(np.argmin(base[1:])) + 1  # a zero-ish limb to drive to -1
quasi = base.copy()
quasi[k] -= 1
quasi[k - 1] += 1 << limb.LIMB_BITS
got = np.asarray(FP.canon(quasi[None]))[0]
assert (got == base).all(), (got, base)
assert bool(FP.eq(quasi[None], base[None])[0])
print("canon-ok")
"""


def test_relaxed_canon_handles_quasi_canonical_limbs():
    env = {key: val for key, val in os.environ.items()
           if not key.startswith("GETHSHARDING_TPU_")}
    env.update({"GETHSHARDING_TPU_LIMB_FORM": "wide",
                "GETHSHARDING_TPU_NORM": "relaxed"})
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run([sys.executable, "-c", _RELAXED_CANON_DRIVER],
                          env=env, capture_output=True, text=True,
                          timeout=600, cwd=repo_root)
    assert proc.returncode == 0 and "canon-ok" in proc.stdout, (
        proc.stdout[-500:], proc.stderr[-1500:])


def test_finalexp_mega_conflicts_with_pair_unroll():
    env = {key: val for key, val in os.environ.items()
           if not key.startswith("GETHSHARDING_TPU_")}
    env.update({"GETHSHARDING_TPU_FINALEXP": "mega",
                "GETHSHARDING_TPU_PAIR_UNROLL": "finalexp"})
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, "-c",
         "from gethsharding_tpu.parallel.virtual import "
         "force_virtual_cpu_devices\n"
         "force_virtual_cpu_devices(1)\n"
         "import gethsharding_tpu.ops.bn256_jax\n"],
        env=env, capture_output=True, text=True, timeout=600, cwd=repo_root)
    assert proc.returncode != 0 and "FINALEXP" in proc.stderr


@slow
@pytest.mark.parametrize("combo", COMBOS,
                         ids=["relaxed+feunroll", "mega", "unroll+su4",
                              "relaxed+su4"])
def test_knob_combo_committee_verify(combo):
    # a clean knob slate: ambient GETHSHARDING_TPU_* exports must not
    # leak into the configuration under test
    env = {key: val for key, val in os.environ.items()
           if not key.startswith("GETHSHARDING_TPU_")}
    env.update(combo)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run([sys.executable, "-c", _DRIVER], env=env,
                          capture_output=True, text=True, timeout=1500,
                          cwd=repo_root)
    assert proc.returncode == 0 and "combo-ok" in proc.stdout, (
        combo, proc.stdout[-500:], proc.stderr[-1500:])
