"""Differential tests: batched limb arithmetic vs. Python big ints.

Covers both consensus moduli (bn256 base/scalar fields, secp256k1 base/
scalar fields) — the same ModArith machinery backs the pairing kernel and
the ECDSA kernel, mirroring how the reference's gfP asm and libsecp256k1
field code each serve one curve (SURVEY.md §2.3).
"""

import random

import numpy as np
import pytest

import jax.numpy as jnp

from gethsharding_tpu.crypto import bn256 as bn_ref
from gethsharding_tpu.crypto import secp256k1 as secp_ref
from gethsharding_tpu.ops import limb

MODULI = {
    "bn256_p": bn_ref.P,
    "bn256_n": bn_ref.N,
    "secp_p": secp_ref.P,
    "secp_n": secp_ref.N,
}


def rand_lazy(rng, n):
    """Random *lazy* elements: any value in [0, 2^264)."""
    return [rng.randrange(limb.RADIX) for _ in range(n)]


@pytest.fixture(scope="module")
def rng():
    return random.Random(0xC0FFEE)


@pytest.mark.parametrize("name", sorted(MODULI))
def test_roundtrip_and_canon(name, rng):
    p = MODULI[name]
    fp = limb.ModArith(p)
    vals = rand_lazy(rng, 8) + [0, 1, p - 1, p, p + 1, limb.RADIX - 1]
    x = jnp.asarray(limb.ints_to_limbs(vals))
    got = fp.to_ints(x)
    for v, g in zip(vals, got):
        assert int(g) == v % p


@pytest.mark.parametrize("name", sorted(MODULI))
def test_add_sub_mul_batch(name, rng):
    p = MODULI[name]
    fp = limb.ModArith(p)
    n = 16
    xs, ys = rand_lazy(rng, n), rand_lazy(rng, n)
    # adversarial corners: max lazy values, zero, p-1 pairs
    xs[:3] = [limb.RADIX - 1, 0, p - 1]
    ys[:3] = [limb.RADIX - 1, limb.RADIX - 1, p - 1]
    x = jnp.asarray(limb.ints_to_limbs(xs))
    y = jnp.asarray(limb.ints_to_limbs(ys))

    for op, ref in [
        (fp.add, lambda a, b: (a + b) % p),
        (fp.sub, lambda a, b: (a - b) % p),
        (fp.mul, lambda a, b: (a * b) % p),
    ]:
        out = fp.to_ints(op(x, y))
        for a, b, g in zip(xs, ys, out):
            assert int(g) == ref(a, b), op.__name__

    # chained ops stay lazily-correct: (x*y + x - y)^2
    z = fp.sqr(fp.sub(fp.add(fp.mul(x, y), x), y))
    out = fp.to_ints(z)
    for a, b, g in zip(xs, ys, out):
        assert int(g) == pow(a * b + a - b, 2, p)


@pytest.mark.parametrize("name", ["bn256_p", "secp_p"])
def test_neg_small_pow_inv(name, rng):
    p = MODULI[name]
    fp = limb.ModArith(p)
    xs = rand_lazy(rng, 4) + [0, 1]
    x = jnp.asarray(limb.ints_to_limbs(xs))

    neg = fp.to_ints(fp.neg(x))
    for a, g in zip(xs, neg):
        assert int(g) == (-a) % p

    sm = fp.to_ints(fp.mul_small(x, 9))
    for a, g in zip(xs, sm):
        assert int(g) == (9 * a) % p

    e = 0x1234567890ABCDEF
    pw = fp.to_ints(fp.pow_static(x, e))
    for a, g in zip(xs, pw):
        assert int(g) == pow(a, e, p)

    inv = fp.to_ints(fp.inv(x))
    for a, g in zip(xs, inv):
        assert int(g) == (pow(a % p, p - 2, p) if a % p else 0)


def test_predicates_and_select():
    p = MODULI["bn256_p"]
    fp = limb.ModArith(p)
    vals = [0, p, 1, p + 1, 2 * p]
    x = jnp.asarray(limb.ints_to_limbs(vals))
    assert list(np.asarray(fp.is_zero(x))) == [True, True, False, False, True]

    y = jnp.asarray(limb.ints_to_limbs([p, 0, p + 1, 1, 5]))
    assert list(np.asarray(fp.eq(x, y))) == [True, True, True, True, False]

    cond = jnp.asarray([True, False, True, False, True])
    sel = fp.to_ints(fp.select(cond, x, y))
    assert [int(v) for v in sel] == [0, 0, 1, 1, 0]


def test_batch_shapes_nd():
    """Ops must be batch-first over arbitrary leading axes (vmap-free)."""
    p = MODULI["bn256_p"]
    fp = limb.ModArith(p)
    rng = random.Random(7)
    vals = [[rng.randrange(p) for _ in range(3)] for _ in range(2)]
    x = jnp.asarray(np.stack([limb.ints_to_limbs(row) for row in vals]))
    out = fp.to_ints(fp.mul(x, x))
    assert out.shape == (2, 3)
    for i in range(2):
        for j in range(3):
            assert int(out[i][j]) == pow(vals[i][j], 2, p)


def test_assoc_carry_impl_matches_scan(monkeypatch):
    """All carry implementations (scan / assoc / unroll) must agree
    exactly; the non-default paths are env-selected and would otherwise
    go untested."""
    p = MODULI["bn256_p"]
    rng = random.Random(11)
    vals_a = [rng.randrange(p) for _ in range(8)]
    vals_b = [rng.randrange(p) for _ in range(8)]
    x = jnp.asarray(limb.ints_to_limbs(vals_a))
    y = jnp.asarray(limb.ints_to_limbs(vals_b))

    fp = limb.ModArith(p)
    expect = [a * b % p for a, b in zip(vals_a, vals_b)]
    got_scan = fp.to_ints(fp.mul(x, y))
    monkeypatch.setattr(limb, "CARRY_IMPL", "assoc")
    got_assoc = fp.to_ints(fp.sub(fp.mul(x, y), y))
    monkeypatch.setattr(limb, "CARRY_IMPL", "unroll")
    got_unroll = fp.to_ints(fp.sub(fp.mul(x, y), y))
    monkeypatch.setattr(limb, "CARRY_IMPL", "scan")
    assert [int(v) for v in got_scan] == expect
    expect_sub = [(a * b - b) % p for a, b in zip(vals_a, vals_b)]
    assert [int(v) for v in got_assoc] == expect_sub
    assert [int(v) for v in got_unroll] == expect_sub


def test_conv_impls_agree():
    """Every conv_cols implementation computes the same anti-diagonal
    sums (the autotune sweep may deploy any of them)."""
    import numpy as np
    import jax.numpy as jnp

    from gethsharding_tpu.ops import limb

    rng = np.random.default_rng(7)
    for L, M in [(22, 22), (25, 49), (3, 7), (1, 5), (22, 43)]:
        prod = rng.integers(-2**20, 2**20, size=(2, 3, L, M),
                            dtype=np.int64).astype(np.int32)
        want = limb.conv_cols(jnp.asarray(prod), impl="onehot")
        for impl in ("shift", "slices", "gather"):
            got = limb.conv_cols(jnp.asarray(prod), impl=impl)
            assert np.array_equal(np.asarray(got), np.asarray(want)), (
                L, M, impl)
        # mxu8's int8-plane split assumes non-negative entries (the
        # limb-product contract: products of canonical <2^12 limbs)
        pos = np.abs(prod)
        want_pos = limb.conv_cols(jnp.asarray(pos), impl="shift")
        got_pos = limb.conv_cols(jnp.asarray(pos), impl="mxu8")
        assert np.array_equal(np.asarray(got_pos), np.asarray(want_pos)), (
            L, M, "mxu8")


def test_relaxed_norm_matches_exact(monkeypatch):
    """GETHSHARDING_TPU_NORM=relaxed (wide form): same residues as the
    exact ripple on mul/add/sub chains, and the quasi-canonical limb
    contract holds (limbs in [-1, 2^12 + 64]) — the range every fused
    accumulator's int32 proof budgets for."""
    if limb.LIMB_FORM != "wide":
        pytest.skip("relaxed normalize is wide-form only")
    if limb.CONV_IMPL == "mxu8":
        pytest.skip("mxu8 conv requires non-negative products; "
                    "incompatible with relaxed limbs")
    for name in ("bn256_p", "secp_p", "secp_n", "bn256_n"):
        _relaxed_norm_case(monkeypatch, MODULI[name])


def _relaxed_norm_case(monkeypatch, p):
    fp = limb.ModArith(p)
    rng = random.Random(99)
    vals_a = [rng.randrange(p) for _ in range(16)]
    vals_b = [rng.randrange(p) for _ in range(16)]
    x = jnp.asarray(limb.ints_to_limbs(vals_a))
    y = jnp.asarray(limb.ints_to_limbs(vals_b))

    def chain():
        z = fp.mul(fp.sub(fp.mul(x, y), y), fp.sub(x, fp.mul(y, y)))
        return fp.sub(z, fp.mul(z, x))

    monkeypatch.setattr(limb, "NORM_IMPL", "relaxed")
    # sub-heavy chain: borrows exercise the negative-limb transients the
    # top-carry re-fuse exists for
    z = chain()
    got = [int(v) for v in fp.to_ints(z)]
    arr = np.asarray(z)
    assert arr.min() >= -1 and arr.max() <= (1 << limb.LIMB_BITS) + 64, (
        arr.min(), arr.max())
    monkeypatch.setattr(limb, "NORM_IMPL", "exact")
    want = [int(v) for v in fp.to_ints(chain())]
    expect = [(((a * b - b) % p) * ((a - b * b) % p) % p) for a, b
              in zip(vals_a, vals_b)]
    expect = [(e - e * a) % p for e, a in zip(expect, vals_a)]
    assert got == want == expect
